"""Async serve engine: request coalescing, double-buffered dispatch,
plan prewarming, admission control, and the serve-path resilience layer.

The plan/session layer (`conflux_tpu.serve`) makes a *single* session
fast — compile once per traffic shape, factor once per matrix,
substitution-only solves — but every entry point is synchronous and
per-request: a fleet of sessions under open-loop traffic still dispatches
one device program per request, leaves the device idle between host
round-trips, and pays a compile stall on the first request of every new
bucket. The same trade that drives the 2.5D algorithms (a little extra
buffering/replication for far fewer, larger device operations) applies at
the request level, and :class:`ServeEngine` makes it:

- **Coalescing** — requests arriving within a ``max_batch_delay`` window
  are grouped and merged along the axes the compiled programs already
  bucket. Requests against the SAME session concatenate their RHS columns
  into one wider substitution: columns are independent through every
  substitution/GEMM/IR step, so single-system answers are bitwise the
  per-request ones (the bucket-padding argument of `SolveSession.solve`,
  asserted in tests/test_engine.py); batched plans' vmapped GEMM kernel
  changes shape with the coalesced width, so their coalesced answers are
  allclose, bitwise only within a bucket. With ``stack_sessions=True``,
  requests against DIFFERENT sessions of one single-system plan
  additionally ride one vmapped dispatch off a device-resident GANG
  (`conflux_tpu.gang.SessionGang`, DESIGN §26): member sessions hold
  slots in a shared stacked factor pytree living on their lane device,
  so the stacked solve indexes resident state directly — zero
  per-dispatch restacking, zero per-dispatch factor movement. Drifted
  sessions ride a stacked rank-bucketed Woodbury correction and checked
  engines a fused per-slot verdict, so neither falls off the stacked
  path; answers are allclose to, but not bitwise, the per-session
  programs (bitwise within a stack bucket for plain sessions), so it is
  opt-in — and the AdaptiveController can steer it from live telemetry.

- **Double-buffered async dispatch** — a dispatcher thread stages and
  dispatches batch i+1 while a drain thread waits on batch i: the
  dispatched-batch queue is bounded at two entries, so host staging
  overlaps device compute without unbounded in-flight growth, and the hot
  path never calls ``block_until_ready`` (JAX async dispatch carries the
  results; only the drain thread blocks).

- **A factor lane (coalesced cold-start)** — session churn (millions of
  users means sessions open constantly) used to pay one narrow O(N^3)
  dispatch per matrix through the synchronous ``plan.factor``.
  :meth:`ServeEngine.submit_factor` enqueues factorizations instead: the
  dispatcher coalesces same-plan requests inside the same
  ``max_batch_delay`` window into ONE vmapped batched factor dispatch at
  power-of-two batch buckets (host-staged A stacking mirroring the RHS
  staging — one transfer, one prewarmed program; pad slots carry
  identity matrices), and the drain thread slices the stacked factor
  pytree device-side into independent resident
  :class:`~conflux_tpu.serve.SolveSession`s (``batched.unstack_tree``) —
  downstream solve/update/refresh/health behavior is exactly a
  ``plan.factor`` session's, and the answers are BITWISE identical
  (``plan.factor`` rides bucket 1 of the same program family, and the
  vmapped factor body is bucket- and pad-invariant). With a health
  policy, the staged A stack is finite-guarded (a poisoned matrix fails
  its OWN future) and every coalesced factorization carries a fused
  per-slot post-factor verdict (probe-row residual through a probe
  solve, computed in the same dispatch); sick slots re-dispatch solo
  and fail with structured evidence, healthy neighbours are untouched.

- **Prewarming + admission control** — :meth:`ServeEngine.prewarm`
  compiles the declared traffic buckets (widths, stack sizes) before
  traffic lands, so p99 never eats a compile (the persistent XLA cache is
  switched on, so even cold processes deserialize); a bounded pending
  count sheds (``on_full='reject'``, the default, raising
  :class:`EngineSaturated` with an exponential-backoff ``retry_after``
  hint) or backpressures (``on_full='block'``) instead of collapsing
  into unbounded latency.

- **Resilience** (`conflux_tpu.resilience`, DESIGN.md §20) — with
  ``health=HealthPolicy()``: every request's RHS is finite-guarded at
  ``submit()`` and again at staging, so a poisoned request fails its OWN
  future instead of corrupting the coalesced batch; every dispatched
  solve carries a fused finite/spot-residual verdict, and an unhealthy
  batch re-dispatches the innocent survivors individually while the sick
  request climbs the escalation ladder (forced refactor through the
  cached factor program, one iterative-refinement sweep, then a
  structured `SolveUnhealthy`); a session failing the whole ladder
  `quarantine_after` times in a row is quarantined by a circuit breaker
  (fast `SessionQuarantined`, half-open probe after the cooldown).
  Independent of the policy: per-request ``deadline=`` with lazy
  eviction (`DeadlineExceeded` frees the pending slot), a watchdog that
  fails pending work when a worker thread dies instead of queueing
  forever, and ``close(timeout)`` that reports wedged workers and fails
  still-pending futures. `fault_plan=` injects deterministic faults at
  the named sites (tests, `scripts/soak.py --serve`).

Sessions mutate under ``update``/refactor; the engine only ever calls
``session.solve``/``solve_checked`` (under the session's lock, so the
escalation ladder's factor swaps are atomic against the dispatcher). Do
not call ``session.update`` while requests against that session are in
flight — drain first (``engine.close()`` or wait on the futures).

    engine = ServeEngine(max_batch_delay=0.002, health=HealthPolicy())
    engine.prewarm(session, widths=(1, 2, 4))
    futs = [engine.submit(session, b) for b in rhs]     # non-blocking
    xs = [f.result() for f in futs]                     # coalesced device work
    print(engine.stats())                               # p50/p95/p99, batches
    engine.close()                                      # drains in flight
"""

from __future__ import annotations

import dataclasses
import threading
import time
import weakref
import zlib
from collections import deque
from concurrent.futures import Future
from queue import Empty, Full, Queue
from typing import Any

import numpy as np

import jax
import jax.numpy as jnp

from conflux_tpu import profiler, resilience
from conflux_tpu import qos as qos_mod
from conflux_tpu.batched import _shard_batch, put_tree, stack_trees, \
    unstack_tree
from conflux_tpu.gang import SessionGang
from conflux_tpu.resilience import (
    DeadlineExceeded,
    HealthPolicy,
    MeshPlanUnsupported,
    RhsNonFinite,
    SessionQuarantined,
    SolveUnhealthy,
)
from conflux_tpu import serve
from conflux_tpu.serve import FactorPlan, SolveSession
from conflux_tpu.update import rank_bucket


def _devkey(device):
    """Hashable identity of a jax device (None = the default device).
    Keys the per-device program-warmth registry
    (`FactorPlan._warm_devices`) and the engine's device→lane map."""
    return None if device is None else (device.platform, device.id)


def rendezvous(sid, nodes, key=None):
    """Rendezvous (highest-random-weight) hashing: pick the node whose
    (sid, node-identity) hash is highest. `key(node)` supplies the
    stable identity each node is weighed by (default: the node itself);
    identities must be distinct and survive restarts for placement to.

    The property mod-N hashing lacks, and the reason the serve fabric
    (DESIGN §28) and the lane placer both use this: when the node SET
    changes, only the sids whose winning node vanished move — every
    other sid's per-node weights are untouched, so its argmax is
    untouched. Removing one of N nodes remaps ~1/N of the sids (the
    dead node's own) instead of the ~(N-1)/N a `hash % N` reshuffle
    moves; adding a node steals only the sids it now wins. Ties (a
    ~2^-32 CRC collision) break toward the lexically-largest identity
    so the choice stays a pure function of (sid, node set)."""
    sb = str(sid).encode()
    best = best_ident = None
    best_w = -1
    for n in nodes:
        ident = str(n if key is None else key(n))
        w = zlib.crc32(sb + b"@" + ident.encode())
        if w > best_w or (w == best_w and (best_ident is None
                                           or ident > best_ident)):
            best, best_ident, best_w = n, ident, w
    return best


def rendezvous_ranked(sid, nodes, k=None, key=None):
    """Rendezvous hashing, ranked: the full preference ORDER of `nodes`
    for `sid`, highest weight first (same weights and tie-break as
    :func:`rendezvous`, so `rendezvous_ranked(sid, ns)[0] ==
    rendezvous(sid, ns)`). `k` truncates to the top-k.

    The serve fabric's K-replica placement (DESIGN §34) is built on
    this: rank 0 is the primary, ranks 1..K-1 hold replica records, and
    the no-reshuffle property extends down the list — removing a node
    promotes each sid's next-ranked survivor without disturbing the
    relative order of any other pair, so fail-over re-points to the
    same standby every front would compute independently."""
    sb = str(sid).encode()
    ranked = sorted(
        nodes,
        key=lambda n: (
            zlib.crc32(sb + b"@" + str(n if key is None else key(n)).encode()),
            str(n if key is None else key(n)),
        ),
        reverse=True,
    )
    return ranked if k is None else ranked[:k]


def place_session(sid, devices):
    """Deterministic consistent placement: map a stable session id onto
    one of `devices` by rendezvous hashing over the device identities.
    Equal sids land on equal devices for any fixed device list — across
    engines, and across process restarts (the warm-restart path re-pins
    a restored fleet identically) — and a device-list CHANGE remaps
    only the sids whose device vanished (see :func:`rendezvous`; the
    pre-§28 CRC32 mod-N placer reshuffled ~(N-1)/N of the fleet when a
    lane died). The mesh-sharded serve fleet's placement function
    (DESIGN §25)."""
    if len(devices) == 1:
        return devices[0]
    return rendezvous(sid, devices, key=_devkey)


class EngineSaturated(RuntimeError):
    """submit() refused: the bounded pending set is full (shed policy).
    `retry_after` is an exponential-backoff hint in seconds — it doubles
    with every consecutive shed and resets on the next admission, so a
    retrying client herd spreads out instead of hammering the bound.
    `tenant`/`qos_class` carry the shed attribution when the request
    was QoS-classified (DESIGN §30; None on unclassified traffic), so
    a global-bound shed is auditable per class next to the fair-share
    `TenantThrottled` sheds."""

    def __init__(self, msg: str, retry_after: float = 0.0,
                 tenant: str | None = None,
                 qos_class: str | None = None):
        super().__init__(msg)
        self.retry_after = retry_after
        self.tenant = tenant
        self.qos_class = qos_class


class EngineClosed(RuntimeError):
    """submit() after close(), or pending work failed because the engine
    shut down (wedged close, dead worker thread)."""


@dataclasses.dataclass
class _Request:
    session: Any          # the SolveSession the answer comes from
    b2: Any               # HOST RHS normalized to a trailing width axis
    width: int            # pre-coalescing column count
    squeeze: bool         # drop the width axis in the result
    future: Future        # resolved by the drain thread
    t_submit: float       # perf_counter at admission (latency clock)
    expiry: float | None = None  # perf_counter deadline (lazy eviction)
    carried: bool = False  # deferred once already — never defer again
    lane: Any = None      # the DeviceLane that owns this request
    lane_slot: bool = False  # counted against the lane's pending slice
    qos: Any = None       # QosClass (DESIGN §30) or None
    cost: float = 1.0     # ledger admission weight (qos.request_cost)
    precision: Any = None  # per-request served tier / 'auto' (DESIGN §33)

    __hash__ = object.__hash__


@dataclasses.dataclass
class _FactorRequest:
    """One cold-start request in the factor lane. Shares the generic
    request surface (`future`/`expiry`/`carried`/`t_submit`) with
    :class:`_Request` so pruning, deadline capping, carry-over and
    resolution ownership treat both lanes uniformly."""

    plan: Any             # the FactorPlan whose program factors A
    A: Any                # HOST matrix (numpy), plan-shaped, plan dtype
    policy: Any           # DriftPolicy for the opened session (or None)
    future: Future        # resolves to a device-resident SolveSession
    t_submit: float       # perf_counter at admission (latency clock)
    expiry: float | None = None  # perf_counter deadline (lazy eviction)
    carried: bool = False  # deferred once already — never defer again
    lane: Any = None      # owning lane (None while in the shared pool)
    lane_slot: bool = False  # counted against the lane's pending slice
    pool: bool = False    # admitted into the work-stealing factor pool
    sid: Any = None       # stable session id for the opened session
    device: Any = None    # explicit device pin for the opened session
    qos: Any = None       # QosClass (DESIGN §30) or None
    cost: float = 1.0     # ledger admission weight (qos.request_cost)
    precision: Any = None  # served tier the session opens at (DESIGN §33)

    __hash__ = object.__hash__


@dataclasses.dataclass
class _FactorBatch:
    """A dispatched coalesced factor batch in flight to the drain
    thread: the stacked factor pytree (and, when checked, the stacked
    probe rows + the (2, bucket) per-slot verdict) plus the staged
    device A stack the sessions slice their bases from."""

    plan: Any
    reqs: list            # live requests, packed into slots 0..n-1
    factors: Any          # stacked factor pytree, leading axis = bucket
    wA: Any               # stacked probe rows (checked) or None
    verdict: Any          # (2, bucket) device verdict (checked) or None
    A: Any                # the staged (bucket,)+shape device A stack
    solo: bool = False    # a solo re-dispatch: no second retry
    mesh: bool = False    # a mesh-lane factor: ONE request, no stacking
                          # (factors/A are the sharded batch itself)
    tier: Any = None      # served tier the batch factored at (§33)


@dataclasses.dataclass
class _StackBatch:
    """A dispatched CHECKED gang-stacked batch in flight to the drain
    thread: the stacked answer plus the fused (2, cap) per-slot verdict
    (`update.health_spot_check_slots`) and the slot -> session map the
    drain needs to attribute a sick slot without re-dispatching its
    gang-mates. Unchecked gang dispatches ride the plain drain tuple
    (their verdict is None, like any other batch)."""

    plan: Any
    spec: list            # (request, slot, column-offset) scatter plan
    x: Any                # (cap, N, wb) stacked device answer
    verdict: Any          # (2, cap) device verdict block
    sessions: dict        # slot -> session, live-request slots only


def _normalize_rhs(session, b):
    """Mirror `SolveSession._rhs` on the HOST: returns (b2, squeeze) with
    b2 a numpy array carrying an explicit trailing width axis. Staying in
    numpy keeps admission free of device work — the dispatcher memcpys
    requests into one bucket-width staging buffer per batch, so the
    device sees ONE transfer and ONE prewarmed program regardless of how
    many requests coalesced (a per-batch `concatenate` of varying widths
    would be a fresh XLA compile per width combination)."""
    plan = session.plan
    b = np.asarray(b)
    if plan.batched:
        want = (plan.B, plan.N)
        if b.ndim == 2:
            if b.shape != want:
                raise ValueError(f"rhs {b.shape}, session needs {want}")
            return b[:, :, None], True
        if b.ndim != 3 or b.shape[:2] != want:
            raise ValueError(
                f"rhs {b.shape}, session needs {want} (+ rhs axis)")
        return b, False
    rows = plan.M  # == N for square kinds; QR solves take an M-row rhs
    if b.ndim == 1:
        if b.shape[0] != rows:
            raise ValueError(f"rhs {b.shape}, session needs ({rows},)")
        return b[:, None], True
    if b.ndim != 2 or b.shape[0] != rows:
        raise ValueError(f"rhs {b.shape}, session needs ({rows}, k)")
    return b, False


_STOP = object()
# a lane nudge: "run a dispatch window, there may be pooled factor work"
# — carries no request itself (multi-lane cold-start load balancing)
_WAKE = object()


def _percentile(sorted_vals, pct: float) -> float:
    """Nearest-rank percentile of an ascending list (0.0 when empty)."""
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1,
              max(0, int(round(pct / 100.0 * len(sorted_vals) + 0.5)) - 1))
    return sorted_vals[idx]


class DeviceLane:
    """One device's worth of the serve engine: a dispatcher/drain pair,
    their staging buffers and bucket carry-over, and per-lane telemetry
    — the unit the mesh-sharded fleet scales by (DESIGN §25).

    A :class:`ServeEngine` owns one lane per serving device, all behind
    ONE admission front: the engine keeps the bounded pending set,
    deadlines, health guards, knobs, and the resolution-ownership
    `_live` set; each lane owns its input queue, its 2-deep dispatched-
    batch handoff queue, its two worker threads, and its small-remainder
    carry-over. Requests route to the lane that owns their session
    (pinned at open — consistent hash of the session id over the
    engine's devices, `device=` overrides); cold-start factorizations
    load-balance through the engine's shared pool, which any lane with
    a free dispatch round drains (work-stealing). A single-lane engine
    (`lanes=1`, the default, or a one-device host) is EXACTLY the
    pre-fleet engine: `device=None`, no placement, no pool — the same
    code on the same default device, byte-identical behavior.

    Fault domain: a lane. A poisoned request, a crashed dispatch, or a
    dead worker thread fails only work routed to its lane; the per-lane
    watchdog respawns dead lane workers (`lane_revives` budget) while
    the other lanes keep serving. Shared engine state (counters,
    admission) is touched only under the ENGINE's admission lock —
    lane-local counters ride the same lock; `busy_*_s` gauges are
    single-writer by construction (each written only by its own worker
    thread) and read racily by design."""

    def __init__(self, eng: "ServeEngine", index: int, device):
        self.eng = eng
        self.index = index
        self.device = device  # jax.Device, or None = default device
        # per-lane coalescing window override (the adaptive controller's
        # per-lane knob; None = the engine-wide max_batch_delay)
        self.delay_override: float | None = None
        self._inq: Queue = Queue()
        # bounded at 2: the double buffer (see ServeEngine.__init__)
        self._outq: Queue = Queue(maxsize=2)
        # per-lane telemetry — written under the ENGINE lock next to the
        # engine-wide counters (cross-object, so annotated in prose):
        self.batches = 0
        self.coalesced = 0
        self.bucket_hits: dict = {}
        self.factor_batches = 0
        self.factor_coalesced = 0
        self.gang_batches = 0
        self.gang_coalesced = 0
        # per-lane pending slice (max_lane_pending): requests admitted
        # against this lane, and sheds its slice caused — one lane's
        # backlog must not starve admission fleet-wide
        self.pending = 0
        self.sheds = 0
        # the lane's device-resident gangs, one per plan (DESIGN §26);
        # mutation of the DICT is engine-lock guarded, the gangs
        # themselves carry their own RLock
        self._gangs: dict = {}
        # queue high-water: monotone max, racy update by design
        self.queue_hw = 0
        # single-writer busy gauges (dispatcher / drainer respectively)
        self.busy_dispatch_s = 0.0
        self.busy_drain_s = 0.0
        self.t_start = time.perf_counter()
        # per-lane fault-domain state: watchdog revival budget spent,
        # permanently-dead flag (admission routes around a dead lane),
        # (thread name, exc) post-mortem — write-once by the dying
        # worker, racy reads tolerate staleness by design
        self.revives = 0
        self.dead = False
        self._dead: tuple | None = None
        # serializes concurrent trips (dying thread + watchdog poll)
        self._trip_lock = threading.Lock()
        self._dispatcher: threading.Thread | None = None
        self._drainer: threading.Thread | None = None

    @property
    def delay(self) -> float:
        """This lane's coalescing window: its own override when the
        controller set one (`ServeEngine.set_knobs(lane=...)`), else
        the engine-wide `max_batch_delay`."""
        d = self.delay_override
        return self.eng.max_batch_delay if d is None else d

    # hot-path
    def _collect_delay(self, r) -> float:
        """The request's collect delay inside this lane's window:
        exactly `self.delay` for unclassified requests (the qos=None
        path resolves in one attribute check), else the class's tier
        delay (DESIGN §30 — latency rides ~0, batch pads the window
        out). Priority-aware coalescing happens HERE, inside the one
        existing window, not in per-class queues: the window's
        effective deadline is the MIN over its members' class delays."""
        if r.qos is None:
            return self.delay
        st = self.eng._qos
        # racy read of the tier-override dict (a knob, like max_batch_
        # delay itself): a concurrent set_knobs lands on the next window
        return qos_mod.collect_delay(
            r.qos, self.delay, st.tier_delay if st is not None else {})

    # hot-path
    def _carry_delay(self, reqs) -> float:
        """The window to give a carried batch: the MIN of its members'
        collect delays (== `self.delay` when none are classified)."""
        d = self.delay
        for r in reqs:
            if r.qos is not None:
                d = min(d, self._collect_delay(r))
        return d

    def _tname(self, role: str) -> str:
        """Worker thread name: the pre-fleet names on a single-lane
        engine (ops tooling and tests key on them), lane-suffixed on a
        fleet."""
        if len(self.eng._lanes) == 1:
            return f"serve-engine-{role}"
        return f"serve-engine-{role}-L{self.index}"

    def start(self) -> None:
        self._dispatcher = threading.Thread(
            target=self._dispatch_loop,
            name=self._tname("dispatch"), daemon=True)
        self._drainer = threading.Thread(
            target=self._drain_loop,
            name=self._tname("drain"), daemon=True)
        self._dispatcher.start()
        self._drainer.start()

    def revive(self, exclude=None) -> None:
        """Respawn this lane's dead worker threads — the per-lane
        watchdog's recovery action. The queues and carry state survive;
        requests the trip already failed are no longer in the engine's
        `_live` set, so a late re-dispatch of one resolves nothing
        (resolution ownership). `exclude` is the currently-dying thread
        (alive while it runs its own post-mortem, but done the moment
        it returns — replace it too)."""
        self._dead = None
        if self._dispatcher is None or not self._dispatcher.is_alive() \
                or self._dispatcher is exclude:
            self._dispatcher = threading.Thread(
                target=self._dispatch_loop,
                name=self._tname("dispatch"), daemon=True)
            self._dispatcher.start()
        if self._drainer is None or not self._drainer.is_alive() \
                or self._drainer is exclude:
            self._drainer = threading.Thread(
                target=self._drain_loop,
                name=self._tname("drain"), daemon=True)
            self._drainer.start()
        self.revives += 1

    def _to_device(self, host_buf):
        """Commit a host-staged buffer to this lane's device — the ONE
        h2d per coalesced batch. The default-device lane keeps the
        pre-fleet `jnp.asarray` byte-for-byte."""
        if self.device is None:
            return jnp.asarray(host_buf)
        return jax.device_put(host_buf, self.device)

    # ------------------------------------------------------------------ #
    # dispatcher: collect a window, coalesce, dispatch async
    # ------------------------------------------------------------------ #

    # futures-owner (post-mortem wrapper: escapes reach _thread_died)
    def _dispatch_loop(self) -> None:
        try:
            self._dispatch_inner()
        except BaseException as e:  # noqa: BLE001 — post-mortem + watchdog
            self._thread_died(threading.current_thread(), e)

    def _thread_died(self, thread, exc: BaseException) -> None:
        """Post-mortem hook run ON the dying worker thread: record the
        cause and trip the watchdog path immediately (the polling
        watchdog is the backstop for silent deaths). Single-lane
        engines trip the whole engine — exactly the pre-fleet
        behavior; multi-lane engines trip only this lane."""
        self.eng._lane_died(self, thread, exc)

    def _wait_bound(self, reqs, remaining: float) -> float:
        """Cap a collect wait at the soonest request deadline, so lazy
        eviction runs when a deadline passes mid-window instead of after
        the whole `max_batch_delay` (or a blocked slot's whole wait)."""
        exps = [r.expiry for r in reqs if r.expiry is not None]
        if not exps:
            return remaining
        return min(remaining,
                   max(0.0, min(exps) - time.perf_counter()) + 1e-4)

    def _prune_expired(self, reqs) -> list:
        """Lazy deadline eviction: fail expired requests with
        :class:`DeadlineExceeded` (releasing their pending slots — this
        is what un-wedges an `on_full='block'` submitter whose queue is
        full of abandoned work) and return the survivors."""
        now = time.perf_counter()
        live = []
        for r in reqs:
            if r.expiry is not None and now > r.expiry:
                resilience.bump("evictions")
                self.eng._fail([r], DeadlineExceeded(
                    f"deadline passed {now - r.expiry:.3f}s before "
                    "dispatch (lazily evicted; pending slot released)"))
            else:
                live.append(r)
        return live

    # hot-path, futures-owner (the dispatcher loop)
    def _dispatch_inner(self) -> None:
        eng = self.eng
        stop = False
        carry: list = []  # small remainder chunks deferred to this round
        while not stop:
            if carry:
                try:
                    first = self._inq.get(
                        timeout=self._wait_bound(
                            carry, self._carry_delay(carry)))
                except Empty:
                    first = None  # window spent waiting on the carry
            else:
                first = self._inq.get()
            batch = list(carry)
            carry = []
            collect = True
            if first is _STOP:
                stop = True
                collect = False
            elif first is None:
                collect = False
            elif first is not _WAKE:
                batch.append(first)
            if collect:
                # the window's effective deadline is the MIN over its
                # members' class collect delays (== self.delay when
                # nothing is classified — _carry_delay is one attribute
                # check per member on the qos=None path)
                deadline = time.perf_counter() + self._carry_delay(batch)
                while True:
                    batch = self._prune_expired(batch)
                    remaining = deadline - time.perf_counter()
                    if remaining <= 0:
                        # the window is over, but anything ALREADY queued
                        # still coalesces (the burst shape: a backlog
                        # should never dispatch one request at a time)
                        try:
                            r = self._inq.get_nowait()
                        except Empty:
                            break
                    else:
                        try:
                            r = self._inq.get(
                                timeout=self._wait_bound(batch, remaining))
                        except Empty:
                            # the wait may have been truncated by a batch
                            # member's deadline — loop: prune, recompute,
                            # and let the remaining<=0 path end the window
                            continue
                    if r is _STOP:
                        stop = True
                        break
                    if r is _WAKE:
                        continue  # pooled work is drawn at dispatch time
                    batch.append(r)
                    if r.qos is not None:
                        # a latency-class arrival pulls the whole
                        # window in; batch-class arrivals never push an
                        # already-set deadline out
                        deadline = min(deadline, time.perf_counter()
                                       + self._collect_delay(r))
                    if len(batch) >= eng.max_pending:
                        break
            if batch:
                batch = self._prune_expired(batch)
            if batch or eng._pool_pending():
                try:
                    resilience.maybe_fault(eng._faults, "dispatch")
                    t0 = time.perf_counter()
                    carry = self._dispatch(
                        batch,
                        may_defer=not stop and not self._inq.empty())
                    self.busy_dispatch_s += time.perf_counter() - t0
                except Exception as e:  # noqa: BLE001 — engine survives
                    eng._fail(batch, e)
        # close-time drain: the carry AND any still-pooled cold starts
        # are answered, not dropped (every lane races to empty the pool;
        # each pooled request is popped exactly once)
        tail = self._prune_expired(carry) + eng._pool_draw(None)
        if tail:
            self._dispatch(tail, may_defer=False)
        self._outq.put(_STOP)

    # hot-path, futures-owner
    def _dispatch(self, batch, may_defer: bool = False) -> list:
        """Group a window's requests and dispatch each group as one
        device program (async — nothing here blocks on device work).
        With `may_defer` (more traffic already queued), each session's
        small remainder chunk is handed back once to ride the next
        window instead of wasting a whole dispatch on a sliver. Factor
        requests ride the same window: lane-pinned ones arrive in the
        batch, and on multi-lane engines each dispatch round also DRAWS
        from the engine's shared cold-start pool (up to one batch
        bucket per round, so a fast lane cannot vacuum the backlog
        while another idles — this is the work-stealing half of the
        factor lane's load balancing). They group per PLAN and coalesce
        into stacked factor dispatches."""
        eng = self.eng
        freqs = [r for r in batch if isinstance(r, _FactorRequest)]
        if len(eng._lanes) > 1:
            freqs += eng._pool_draw(eng.max_factor_batch)
        deferred: list = []
        if freqs:
            deferred += self._dispatch_factors(freqs, may_defer)
            batch = [r for r in batch if not isinstance(r, _FactorRequest)]
        groups: dict[tuple, list[_Request]] = {}
        order = []
        for r in batch:
            # a coalesced chunk shares ONE session.solve call, so the
            # group key carries the request's precision route (§33):
            # same-session requests at different tiers dispatch apart
            key = (id(r.session), r.precision)
            if key not in groups:
                groups[key] = []
                order.append((r.session, r.precision))
            groups[key].append(r)
        stackable: dict[int, list] = {}
        plan_order = []
        opportunity: dict[int, int] = {}
        for session, precision in order:
            reqs = groups[(id(session), precision)]
            plan = session.plan
            # racy read by design (like _revive_for): the served tier
            # is written once at construction
            tiered = (precision is not None
                      or session._served_tier is not None)
            if eng.stack_sessions and not plan.batched \
                    and plan.key.kind != "qr" and not tiered:
                # gang eligibility (DESIGN §26): single-system plans
                # only — a non-batched plan is never mesh-sharded, and
                # drifted (`_upd`) / checked sessions now STACK (the
                # stacked Woodbury + per-slot-verdict programs closed
                # the old exclusion holes). kind='qr' plans and
                # tier-routed requests are COUNTED exclusions (§33):
                # the gang stacks carry neither the (M, N) factor
                # shapes nor per-tier program families.
                pk = id(plan)
                if pk not in stackable:
                    stackable[pk] = []
                    plan_order.append(plan)
                stackable[pk].append((session, reqs))
                continue
            if eng.stack_sessions:
                eng._note_exclusion(
                    "kind" if plan.key.kind == "qr"
                    else "precision" if tiered
                    else "mesh" if plan.mesh is not None else "batched")
            elif not plan.batched:
                # stacking disabled: count the opportunity the window
                # left on the table (the controller's enable signal)
                opportunity[id(plan)] = opportunity.get(id(plan), 0) + 1
            deferred += self._dispatch_session(session, reqs,
                                               may_defer)
        missed = sum(c for c in opportunity.values() if c >= 2)
        if missed:
            with eng._lock:
                eng._gang_opportunity += missed
        for plan in plan_order:
            entries = stackable[id(plan)]
            if len(entries) == 1:
                eng._note_exclusion("singleton")
                deferred += self._dispatch_session(*entries[0], may_defer)
            else:
                self._dispatch_gang(plan, entries)
        if len(eng._lanes) > 1 and eng._pool_pending() \
                and not self.dead:
            # backlog left after this round's draw: keep draining it
            # through THIS lane (burst locality — each device's stream
            # executes serially, so consecutive buckets on one device
            # run back-to-back instead of N devices crunching O(N^3)
            # batches concurrently and thrashing a small core count;
            # measured 20% of churn throughput on the 1-core runner).
            # Lanes serving their own traffic still steal: every
            # dispatch round draws the pool.
            with eng._lock:
                eng._pool_waked = True
            self._inq.put(_WAKE)
        return deferred

    # hot-path
    def _dispatch_session(self, session, reqs,
                          may_defer: bool = False) -> list:
        """Per-session coalescing: concatenate RHS columns up to the
        width cap and run each chunk through `session.solve` (which
        already buckets, pads, shards, and counts). Returns the deferred
        remainder (at most one small chunk, each request deferred at most
        once — the latency cost is bounded by one extra window)."""
        eng = self.eng
        chunks: list[list[_Request]] = []
        chunk: list[_Request] = []
        width = 0
        for r in reqs:
            if chunk and width + r.width > eng.max_coalesce_width:
                chunks.append(chunk)
                chunk, width = [], 0
                with eng._lock:
                    # the width cap split a window's chunk: the
                    # controller's grow-the-bucket-set pressure signal
                    eng._width_capped += 1
            chunk.append(r)
            width += r.width
        deferred: list = []
        if chunk:
            if (may_defer and width <= eng.max_coalesce_width // 2
                    and not any(r.carried for r in chunk)):
                for r in chunk:
                    r.carried = True
                deferred = chunk
            else:
                chunks.append(chunk)
        for c in chunks:
            self._run_chunk(session, c)
        return deferred

    # hot-path
    def _admit_stage(self, reqs) -> list:
        """Pre-staging admission on the dispatch path: lazy deadline
        eviction and the 'staging' fault site (poisons the request's OWN
        host copy, upstream of the guard — exactly what a corrupted
        staging write looks like)."""
        eng = self.eng
        reqs = self._prune_expired(reqs)
        if eng._faults is not None or resilience.active_faults():
            for r in reqs:
                if resilience.data_fault(eng._faults, "staging",
                                         "nan") is not None:
                    # conflint: disable=CFX-HOSTSYNC fault-injection copy of host-staged numpy
                    poisoned = np.array(r.b2, copy=True)
                    poisoned[..., 0] = np.nan
                    r.b2 = poisoned
        return reqs

    # hot-path, futures-owner
    def _isolate_poisoned(self, reqs) -> list:
        """The SECOND finite guard (staging): one summation over the
        coalesced buffer answers 'is anything poisoned?' per BATCH; only
        on suspicion does the per-request scan run to fail the culprits
        alone. Requests poisoned after submit-time admission (or by an
        injected fault) therefore never reach the device, and the
        co-batched answers stay exactly what they would have been."""
        eng = self.eng
        live = []
        for r in reqs:
            if resilience.rhs_finite(r.b2):
                live.append(r)
                continue
            resilience.bump("staging_isolations")
            eng._restore_guards()
            eng._fail([r], RhsNonFinite(
                "rhs went non-finite after admission — isolated at "
                "staging (co-batched requests unaffected)"))
        return live

    # hot-path (numpy staging IS the point: one h2d per batch)
    def _stage(self, reqs):
        """Host-stage a session chunk: memcpy every request's columns
        into ONE bucket-width buffer (zero-padded — exactly the padding
        `SolveSession.solve` would add, so answers stay bitwise). A numpy
        buffer keeps staging off the device and, crucially, off the
        compiler: the device sees one transfer of one already-bucketed
        shape, never a fresh concatenate signature. Returns (buf, spec)
        with spec the (request, stack-slot, column-offset) scatter plan
        for the drain thread."""
        W = sum(r.width for r in reqs)
        wb = rank_bucket(W)
        lead = reqs[0].b2.shape[:-1]
        buf = np.zeros(lead + (wb,), reqs[0].b2.dtype)
        spec = []
        lo = 0
        for r in reqs:
            buf[..., lo:lo + r.width] = r.b2
            spec.append((r, None, lo))
            lo += r.width
        return buf, spec

    # hot-path
    def _revive_for(self, session, reqs) -> None:
        """Deadline-aware fault-in ahead of a dispatch to a spilled
        session (DESIGN §23): the revive-lane wait is capped at the
        requests' soonest deadline (else `revive_wait`), so a request
        expiring mid-revival fails with `DeadlineExceeded`/
        `SessionSpilled` through the usual survivor machinery — its
        admission slot released, the session left FULLY spilled with
        its record intact — instead of wedging the dispatcher. The
        resident fast path costs two attribute reads."""
        rs = getattr(session, "_residency", None)
        # racy fast-path read by design: fault_in re-checks under the
        # session lock, and a session cannot spill mid-dispatch (the
        # manager needs the session lock we are about to take)
        if rs is None or session._spill is None:
            return
        timeout = self.eng.revive_wait
        exps = [r.expiry for r in reqs if r.expiry is not None]
        if exps:
            timeout = max(0.0, min(exps) - time.perf_counter())
        rs.fault_in(session, timeout=timeout)

    # hot-path
    def _solve_session(self, session, buf, precision=None):
        """One dispatch through the session, checked when the policy
        says so. Holds the session lock so a drain-thread escalation
        (factor swap) is atomic against this dispatcher. 'auto'
        precision requests ALWAYS dispatch checked — the fused §20
        verdict is the ladder's escalation signal, with or without an
        engine HealthPolicy (§33)."""
        eng = self.eng
        with session._lock:
            if (precision == "auto"
                    or (eng.health is not None
                        and eng.health.check_output)):
                return session.solve_checked(buf, precision=precision)
            return session.solve(buf, precision=precision), None

    # hot-path, futures-owner
    def _run_chunk(self, session, reqs, solo: bool = False) -> None:
        eng = self.eng
        reqs = self._admit_stage(reqs)
        if not reqs:
            return
        try:
            buf, spec = self._stage(reqs)
            if (eng.health is not None and eng.health.check_rhs
                    and not eng.health.check_output
                    and eng._tick_staging()
                    and not resilience.rhs_finite(buf)):
                # no fused output verdict to backstop the staging guard:
                # one per-BATCH summation here; the per-request scan
                # runs only on suspicion. (With check_output on, the
                # device-side finite verdict detects staged poison for
                # FREE — NaN stays in its own answer column — and the
                # drain isolates the culprit with the same exact scan,
                # so the clean path stages without re-reading a byte.)
                reqs = self._isolate_poisoned(reqs)
                if not reqs:
                    return
                buf, spec = self._stage(reqs)
            self._revive_for(session, reqs)
            x, verdict = self._solve_session(session, buf,
                                             reqs[0].precision)
        except Exception as e:  # noqa: BLE001 — engine must survive
            self._redispatch_survivors(reqs, e, solo)
            return
        wb = buf.shape[-1]
        with eng._lock:
            eng._batches += 1
            eng._coalesced_requests += len(reqs)
            eng._bucket_hits[wb] = eng._bucket_hits.get(wb, 0) + 1
            eng._active_sessions[id(session)] = weakref.ref(session)
            self.batches += 1
            self.coalesced += len(reqs)
            self.bucket_hits[wb] = self.bucket_hits.get(wb, 0) + 1
        self._outq.put((spec, x, verdict, buf))

    # futures-owner
    def _redispatch_survivors(self, reqs, exc, solo: bool = False) -> None:
        """A batch-attributable failure (dispatch exception, failed d2h
        copy, unhealthy verdict on a multi-request batch) re-dispatches
        each member INDIVIDUALLY instead of failing all of them with the
        same exception: the innocent co-batched requests get their
        answers; only the actually-sick request fails (possibly after
        its own escalation ladder). One level deep — a solo request that
        fails again fails for real."""
        if solo or len(reqs) == 1:
            self.eng._fail(reqs, exc)
            return
        resilience.bump("survivor_redispatches", len(reqs))
        for r in reqs:
            self._run_chunk(r.session, [r], solo=True)

    # ------------------------------------------------------------------ #
    # the factor lane: coalesced cold-start dispatch
    # ------------------------------------------------------------------ #

    # hot-path
    def _dispatch_factors(self, reqs, may_defer: bool = False) -> list:
        """Per-plan coalescing of factor requests: same-plan requests
        stack into chunks of up to `max_factor_batch` matrices, each
        chunk one vmapped batched factor dispatch. Returns the deferred
        remainder (with `may_defer`, a small trailing chunk rides the
        next window once instead of wasting a whole bucket on a
        sliver — the solve lane's carry-over discipline)."""
        eng = self.eng
        # per-(plan, tier) coalescing: a served tier selects a distinct
        # compiled factor family, so mixed-tier requests cannot share a
        # stacked dispatch (§33)
        groups: dict[tuple, list] = {}
        order = []
        for r in reqs:
            key = (id(r.plan), r.precision)
            if key not in groups:
                groups[key] = []
                order.append((r.plan, key))
            groups[key].append(r)
        deferred: list = []
        for plan, key in order:
            greqs = groups[key]
            # mesh plans never slot-stack (the genuine gang/stacking
            # residue — their batch axis IS the parallelism): each
            # request dispatches as its own sharded (B, N, N) factor
            cap = 1 if plan.mesh is not None else eng.max_factor_batch
            chunks = [greqs[i:i + cap]
                      for i in range(0, len(greqs), cap)]
            last = chunks[-1]
            if (may_defer and len(last) <= cap // 2
                    and not any(r.carried for r in last)):
                for r in last:
                    r.carried = True
                deferred += last
                chunks = chunks[:-1]
            for c in chunks:
                self._run_factor_chunk(plan, c)
        return deferred

    # hot-path
    def _admit_stage_factor(self, reqs) -> list:
        """Pre-staging admission for the factor lane: lazy deadline
        eviction plus the 'factor' nan fault site (poisons the request's
        OWN host matrix, upstream of the staging guard — a corrupted
        staging write)."""
        eng = self.eng
        reqs = self._prune_expired(reqs)
        if eng._faults is not None or resilience.active_faults():
            for r in reqs:
                if resilience.data_fault(eng._faults, "factor",
                                         "nan") is not None:
                    # conflint: disable=CFX-HOSTSYNC fault-injection copy of host-staged numpy
                    poisoned = np.array(r.A, copy=True)
                    poisoned[..., 0, 0] = np.nan
                    r.A = poisoned
        return reqs

    # hot-path, futures-owner
    def _isolate_poisoned_A(self, reqs) -> list:
        """Factor-lane staging guard: a matrix gone non-finite after
        admission fails its OWN future and is dropped from the staged
        stack; co-batched factorizations are untouched (the vmapped
        factor body never mixes slots). One per-batch summation answers
        'anything poisoned?'; the per-request scan runs only on
        suspicion."""
        eng = self.eng
        live = []
        for r in reqs:
            if resilience.rhs_finite(r.A):
                live.append(r)
                continue
            resilience.bump("factor_isolations")
            eng._restore_guards()
            eng._fail([r], RhsNonFinite(
                "matrix went non-finite after admission — isolated at "
                "staging (co-batched factorizations unaffected)"))
        return live

    # hot-path (numpy staging: one h2d per factor batch)
    def _stage_factor(self, plan, reqs):
        """Host-stage a factor chunk: memcpy every request's matrix into
        ONE (bucket,)+shape staging buffer — the factor-lane mirror of
        `_stage`, with `_pad_batch`'s fill='eye' discipline in numpy:
        pad slots carry identity matrices (well-conditioned by
        construction, never a copy of a request that might itself be
        poisoned). The device sees one transfer and one prewarmed
        program per batch regardless of how many requests coalesced."""
        bb = rank_bucket(len(reqs))
        buf = np.empty((bb,) + plan.key.shape, np.dtype(plan.key.dtype))
        for i, r in enumerate(reqs):
            buf[i] = r.A
        if bb != len(reqs):
            # eye(M, N) for rectangular (QR) plans: full column rank by
            # construction, so pad slots stay factorable
            buf[len(reqs):] = np.eye(*plan.key.shape[-2:],
                                     dtype=buf.dtype)
        return buf

    # hot-path
    def _run_factor_chunk(self, plan, reqs, solo: bool = False) -> None:
        fb = self._build_factor_batch(plan, reqs, solo)
        if fb is not None:
            self._outq.put(fb)

    # hot-path, futures-owner
    def _build_factor_batch(self, plan, reqs, solo: bool = False):
        """Stage and dispatch one coalesced factor chunk (async —
        nothing blocks on device work here); returns the
        :class:`_FactorBatch` for the drain thread, or None when every
        request was already failed/evicted. A batch-attributable
        exception re-dispatches the members solo (`_redispatch_factor_
        survivors`), mirroring `_run_chunk`. The staged stack commits to
        THIS lane's device, so the factor program compiles and runs
        there and the opened sessions are lane-resident."""
        eng = self.eng
        reqs = self._admit_stage_factor(reqs)
        if not reqs:
            return None
        mesh = plan.mesh is not None

        def stage(rs):
            if mesh:
                # the mesh lane: ONE request IS the whole (B, N, N)
                # batch — no slot stacking (the batch axis is the
                # parallelism), so the 'stack' is the request's own
                # matrix batch, dispatched batch-sharded below
                # conflint: disable=CFX-HOSTSYNC A is the caller's host array (submit_factor stages host-side); no device value reaches here
                return np.asarray(rs[0].A)
            return self._stage_factor(plan, rs)

        try:
            buf = stage(reqs)
            if (eng.health is not None and eng.health.check_rhs
                    and eng._tick_staging()
                    and not resilience.rhs_finite(buf)):
                # exact per-batch guard (one summation of the staged
                # stack — noise next to the O(N^3) factor): poisoned
                # matrices fail alone BEFORE burning a factor dispatch,
                # and always as RhsNonFinite (exact attribution), even
                # when the fused verdict would also have caught them
                reqs = self._isolate_poisoned_A(reqs)
                if not reqs:
                    return None
                buf = stage(reqs)
            tier = reqs[0].precision
            checked = (tier is None and eng.health is not None
                       and eng.health.check_output)
            if mesh:
                (Ad,) = _shard_batch((jnp.asarray(buf),), plan.mesh)
            else:
                Ad = self._to_device(buf)
            with profiler.region("serve.factor"):
                if mesh and checked:
                    F, wA, verdict = plan._mesh_factor_health_fn()(Ad)
                elif mesh:
                    F = plan._factor_fn(Ad)
                    wA = verdict = None
                elif tier is not None:
                    # tier cold starts ride the unchecked tier factor
                    # family: the opened session's first checked solve
                    # carries the ladder's verdict (§33), so a fused
                    # post-factor probe here would be a second compile
                    # per tier for no added coverage
                    F = plan._tier_stacked_factor_fn(
                        tier, buf.shape[0])(Ad)
                    wA = verdict = None
                elif checked:
                    F, wA, verdict = plan._factor_health_fn(
                        buf.shape[0])(Ad)
                else:
                    F = plan._stacked_factor_fn(buf.shape[0])(Ad)
                    wA = verdict = None
        except Exception as e:  # noqa: BLE001 — engine must survive
            self._redispatch_factor_survivors(reqs, e, solo)
            return None
        bb = 1 if mesh else buf.shape[0]
        with eng._lock:
            eng._factor_batches += 1
            eng._factor_coalesced += len(reqs)
            eng._factor_slots += bb
            eng._factor_pad += bb - len(reqs)
            eng._factor_bucket_hits[bb] = \
                eng._factor_bucket_hits.get(bb, 0) + 1
            eng._active_plans[id(plan)] = weakref.ref(plan)
            self.factor_batches += 1
            self.factor_coalesced += len(reqs)
        return _FactorBatch(plan, reqs, F, wA, verdict, Ad, solo,
                            mesh=mesh, tier=tier)

    # futures-owner
    def _redispatch_factor_survivors(self, reqs, exc,
                                     solo: bool = False) -> None:
        """Batch-attributable factor-dispatch failure: re-dispatch each
        member individually (one level deep) so innocents still get
        their sessions; a solo retry that fails again fails for real."""
        if solo or len(reqs) == 1:
            self.eng._fail(reqs, exc)
            return
        resilience.bump("survivor_redispatches", len(reqs))
        for r in reqs:
            self._run_factor_chunk(r.plan, [r], solo=True)

    def _gang_for(self, plan) -> SessionGang:
        """This lane's device-resident gang for `plan`, created on
        first stacked contact (DESIGN §26). Dict mutation rides the
        engine lock; the gang carries its own RLock."""
        g = self._gangs.get(id(plan))
        if g is None:
            with self.eng._lock:
                g = self._gangs.get(id(plan))
                if g is None:
                    g = SessionGang(plan, self.device)
                    self._gangs[id(plan)] = g
        return g

    # hot-path
    def _dispatch_gang(self, plan, entries) -> None:
        """Cross-session coalescing through the plan's device-resident
        gang (DESIGN §26): per-session RHS concat first (width-capped;
        overflow falls back to per-session dispatch), then every
        request-carrying session dispatches from its resident gang slot
        in ONE vmapped program. Drifted sessions ride the stacked
        rank-bucketed Woodbury correction and checked engines the fused
        per-slot verdict, so neither excludes a session from stacking
        any more; what still falls back solo is counted per reason
        (`stack_exclusions`). All sessions here are pinned to THIS lane
        (requests route by session placement), so the gang's stacks
        share one device."""
        eng = self.eng
        ready = []
        for session, reqs in entries:
            reqs = self._admit_stage(reqs)
            chunk: list[_Request] = []
            width = 0
            rest: list[_Request] = []
            for r in reqs:
                if not rest and (not chunk or width + r.width
                                 <= eng.max_coalesce_width):
                    chunk.append(r)
                    width += r.width
                else:
                    rest.append(r)
            if chunk:
                ready.append((session, chunk, width))
            if rest:
                self._dispatch_session(session, rest)
        if len(ready) < 2:
            for session, chunk, _w in ready:
                eng._note_exclusion("singleton")
                self._run_chunk(session, chunk)
            return
        gang = self._gang_for(plan)
        checked = eng.health is not None and eng.health.check_output
        try:
            admitted, excluded = gang.ensure(
                [s for s, _c, _w in ready], eng.max_stack, checked)
        except Exception:  # noqa: BLE001 — adoption is best-effort
            admitted = {}
            excluded = {id(s): "error" for s, _c, _w in ready}
        part = []
        for session, chunk, w in ready:
            if id(session) in admitted:
                part.append((session, chunk, w))
            else:
                eng._note_exclusion(excluded.get(id(session), "error"))
                self._run_chunk(session, chunk)
        if len(part) == 1:
            eng._note_exclusion("singleton")
            self._run_chunk(part[0][0], part[0][1])
            return
        if part:
            self._run_gang(plan, gang, part, checked)

    # hot-path, futures-owner
    def _run_gang(self, plan, gang, part, checked: bool) -> None:
        """One dispatch for the whole gang window: stage the RHS into a
        (cap, N, wb) host buffer (one h2d — idle slots keep zero
        columns; the paper's trade, pay flops on idle slots to move no
        factor bytes) and solve straight off the RESIDENT stacks. Zero
        per-dispatch stack_trees, zero per-dispatch factor movement —
        the whole point of gang residency. The gang RLock is held
        across the dispatch (legal — the session-RLock precedent) so a
        concurrent adopt's donating slot write can never invalidate the
        snapshot mid-enqueue."""
        eng = self.eng
        reqs_all = [r for _s, chunk, _w in part for r in chunk]
        verdict = None
        poisoned = False
        try:
            wb = rank_bucket(max(w for _s, _c, w in part))
            with gang._lock:
                snap = gang.prepare([s for s, _c, _w in part])
                cap = snap["cap"]
                buf = np.zeros((cap, plan.N, wb),
                               part[0][1][0].b2.dtype)
                spec = []
                slot_sessions = {}
                for session, chunk, _w in part:
                    si = snap["slots"][id(session)]
                    slot_sessions[si] = session
                    lo = 0
                    for r in chunk:
                        buf[si, :, lo:lo + r.width] = r.b2
                        spec.append((r, si, lo))
                        lo += r.width
                if (eng.health is not None and eng.health.check_rhs
                        and not checked and eng._tick_staging()
                        and not resilience.rhs_finite(buf)):
                    # no fused verdict to backstop (check_output off):
                    # the per-batch staging guard runs here; culprits
                    # isolate per session chunk below, outside the lock
                    poisoned = True
                else:
                    if checked and snap["wA"] is None:
                        # a checked upgrade did not complete (snapshot
                        # failures mid-rebuild) — solo-dispatch this
                        # window; the next ensure() retries the upgrade
                        raise RuntimeError(
                            "gang probe stack unavailable for checked "
                            "dispatch")
                    with profiler.region("serve.solve"):
                        if snap["kb"]:
                            A0u = snap["A0"] if snap["sweeps"] else None
                            fn = (plan._stacked_update_solve_health_fn
                                  if checked
                                  else plan._stacked_update_solve_fn)(
                                cap, snap["kb"], wb, snap["sweeps"])
                            if checked:
                                X, verdict = fn(
                                    snap["F"], A0u, snap["Up"],
                                    snap["Vp"], snap["Y"],
                                    snap["Cinv"], snap["wA"], buf)
                            else:
                                X = fn(snap["F"], A0u, snap["Up"],
                                       snap["Vp"], snap["Y"],
                                       snap["Cinv"], buf)
                        elif checked:
                            X, verdict = plan._stacked_solve_health_fn(
                                cap, wb)(snap["F"],
                                         snap["A0"] if plan.key.refine
                                         else None, snap["wA"], buf)
                        else:
                            X = plan._stacked_solve_fn(cap, wb)(
                                snap["F"],
                                snap["A0"] if plan.key.refine else None,
                                buf)
        except Exception as e:  # noqa: BLE001
            self._redispatch_survivors(reqs_all, e)
            return
        if poisoned:
            for session, chunk, _w in part:
                live = self._isolate_poisoned(chunk)
                if live:
                    self._run_chunk(session, live)
            return
        for session, _c, _w in part:
            with session._lock:  # solves is guarded-by the session lock
                session.solves += 1
        with eng._lock:
            eng._batches += 1
            eng._coalesced_requests += len(reqs_all)
            eng._gang_batches += 1
            eng._gang_coalesced += len(reqs_all)
            eng._bucket_hits[wb] = eng._bucket_hits.get(wb, 0) + 1
            for session, _c, _w in part:
                eng._active_sessions[id(session)] = weakref.ref(session)
            self.batches += 1
            self.coalesced += len(reqs_all)
            self.gang_batches += 1
            self.gang_coalesced += len(reqs_all)
        if verdict is None:
            self._outq.put((spec, X, None, None))
        else:
            self._outq.put(_StackBatch(plan, spec, X, verdict,
                                       slot_sessions))

    # ------------------------------------------------------------------ #
    # drain: the only lane thread that blocks on device work
    # ------------------------------------------------------------------ #

    # futures-owner (post-mortem wrapper: escapes reach _thread_died)
    def _drain_loop(self) -> None:
        try:
            self._drain_inner()
        except BaseException as e:  # noqa: BLE001 — post-mortem + watchdog
            self._thread_died(threading.current_thread(), e)

    # futures-owner (the drain loop — the one thread that MAY block)
    def _drain_inner(self) -> None:
        eng = self.eng
        while True:
            item = self._outq.get()
            if item is _STOP:
                break
            t0 = time.perf_counter()
            try:
                if isinstance(item, _FactorBatch):
                    self._drain_factor(item)
                    continue
                if isinstance(item, _StackBatch):
                    self._drain_stack(item)
                    continue
                spec, block_on, verdict, buf = item
                reqs = [r for r, _si, _lo in spec]
                try:
                    resilience.maybe_fault(eng._faults, "drain")
                    resilience.maybe_fault(eng._faults, "d2h")
                    # ONE blocking device->host copy per coalesced
                    # batch; the per-request scatter is numpy views of
                    # it, so answering N requests costs zero extra
                    # device dispatches
                    xh = np.asarray(block_on)
                except Exception as e:  # noqa: BLE001
                    # batch-attributable drain failure routes through
                    # survivor re-dispatch, not batch-wide _fail
                    self._drain_redispatch(reqs, e)
                    continue
                if verdict is not None:
                    session = reqs[0].session
                    limit = eng._limit(session)
                    healthy, finite, res = resilience.evaluate(verdict,
                                                               limit)
                    if resilience.data_fault(eng._faults, "solve",
                                             "unhealthy") is not None:
                        healthy = False
                    if not healthy:
                        resilience.bump("output_failures")
                        eng._restore_guards()
                        self._drain_unhealthy(session, spec, buf,
                                              finite, res)
                        continue
                    if session._breaker is not None:
                        session._breaker.record_success()
                self.eng._settle(spec, xh)
            finally:
                self.busy_drain_s += time.perf_counter() - t0

    # futures-owner
    def _drain_stack(self, sb: _StackBatch) -> None:
        """Drain one CHECKED gang-stacked batch: ONE blocking d2h for
        the stacked answer, then per-slot verdict evaluation
        (`resilience.evaluate_slots` — slot verdicts are independent by
        construction). Healthy slots settle in place and their
        sessions' breakers record the success; each sick slot's
        requests re-dispatch SOLO through the escalation machinery
        (`_solo_drain`, the factor lane's solo-survivor shape), so a
        sick session never costs its gang-mates a re-dispatch."""
        eng = self.eng
        reqs = [r for r, _si, _lo in sb.spec]
        try:
            resilience.maybe_fault(eng._faults, "drain")
            resilience.maybe_fault(eng._faults, "d2h")
            xh = np.asarray(sb.x)
            limit = eng._plan_limit(sb.plan)
            verdicts = resilience.evaluate_slots(sb.verdict, limit)
            if resilience.data_fault(eng._faults, "solve",
                                     "unhealthy") is not None:
                verdicts = [(False, fin, res)
                            for _h, fin, res in verdicts]
        except Exception as e:  # noqa: BLE001
            self._drain_redispatch(reqs, e)
            return
        healthy_spec, sick = [], []
        for r, si, lo in sb.spec:
            if verdicts[si][0]:
                healthy_spec.append((r, si, lo))
            else:
                sick.append(r)
        for slot, session in sb.sessions.items():
            if verdicts[slot][0] and session._breaker is not None:
                session._breaker.record_success()
        if sick:
            nslots = len({si for _r, si, _lo in sb.spec
                          if not verdicts[si][0]})
            resilience.bump("output_failures", nslots)
            resilience.bump("gang_unhealthy_slots", nslots)
            eng._restore_guards()
            resilience.bump("survivor_redispatches", len(sick))
            for r in sick:
                self._solo_drain(r)
        if healthy_spec:
            eng._settle(healthy_spec, xh)

    # ------------------------------------------------------------------ #
    # the factor lane: drain, per-slot health, slice-out
    # ------------------------------------------------------------------ #

    # futures-owner
    def _drain_factor(self, fb: _FactorBatch) -> None:
        """Drain one coalesced factor batch: ONE block on the dispatched
        program (the factors never cross to the host — only the tiny
        verdict does, when checked), per-slot health evaluation, then
        device-side slice-out into independent resident sessions. Slot
        verdicts are independent, so — unlike the solve lane, which must
        re-dispatch to ATTRIBUTE a batch verdict — healthy neighbours of
        a sick slot settle in place; only the sick slot re-runs solo
        (distinguishing transient staged corruption from a genuinely
        unfactorable matrix) and fails alone with evidence."""
        eng = self.eng
        reqs = fb.reqs
        try:
            resilience.maybe_fault(eng._faults, "drain")
            verdicts = None
            if fb.verdict is not None:
                limit = eng._plan_limit(fb.plan)
                verdicts = resilience.evaluate_slots(fb.verdict, limit)
                if resilience.data_fault(eng._faults, "factor",
                                         "unhealthy") is not None:
                    verdicts = [(False, fin, res)
                                for _h, fin, res in verdicts]
            else:
                jax.block_until_ready(fb.factors)
        except Exception as e:  # noqa: BLE001
            self._drain_factor_redispatch(reqs, e)
            return
        entries = list(enumerate(reqs))
        if verdicts is not None:
            sick = [(i, r) for i, r in entries if not verdicts[i][0]]
            entries = [(i, r) for i, r in entries if verdicts[i][0]]
            for i, r in sick:
                resilience.bump("factor_unhealthy")
                eng._restore_guards()
                _h, finite, res = verdicts[i]
                if fb.solo:
                    limit = eng._plan_limit(fb.plan)
                    eng._fail([r], SolveUnhealthy(
                        f"coalesced factorization unhealthy after solo "
                        f"re-dispatch: finite={finite} res={res:.3e} "
                        f"(limit {limit:.3e})",
                        {"rungs": [{"rung": "factor", "finite": finite,
                                    "residual": res}],
                         "residual_limit": limit}))
                else:
                    self._solo_factor_drain(fb.plan, r)
        if entries:
            self._settle_factor(fb, entries)

    # futures-owner
    def _drain_factor_redispatch(self, reqs, exc) -> None:
        """Drain-side batch-attributable factor failure: re-run each
        request solo, inline (the rare path — the drain thread may
        block)."""
        if len(reqs) == 1:
            self.eng._fail(reqs, exc)
            return
        resilience.bump("survivor_redispatches", len(reqs))
        for r in reqs:
            self._solo_factor_drain(r.plan, r)

    # futures-owner
    def _solo_factor_drain(self, plan, r) -> None:
        """One factor request, re-dispatched and drained inline on the
        drain thread with its own per-slot verdict (solo, so a second
        failure is final)."""
        fb = self._build_factor_batch(plan, [r], solo=True)
        if fb is not None:
            self._drain_factor(fb)

    # futures-owner
    def _settle_factor(self, fb: _FactorBatch, entries) -> None:
        """Resolve a drained factor batch: slice each live slot's factor
        pytree, base matrix, and (when checked) probe row out of the
        stacked device arrays — `batched.unstack_tree`, lazy device
        indexing, zero host copies — and open one independent resident
        :class:`~conflux_tpu.serve.SolveSession` per request. The
        session is constructed exactly as `plan.factor` constructs it
        (same keep-A rule, same policy plumbing), so every downstream
        path — solve, update, drift refactor, the §20 health ladder —
        behaves identically. Sessions open PINNED to this lane's device
        (sid from the request, so re-submits route straight back
        here)."""
        eng = self.eng
        now = time.perf_counter()
        owned = eng._take([r for _i, r in entries])
        with eng._lock:
            for _i, r in entries:
                if r in owned:
                    eng._factor_latencies.append(now - r.t_submit)
            eng._flat_seq += len(owned)
            eng._completed += len(owned)
            st = eng._qos
            if st is not None:
                # classified cold starts settle against the same
                # per-class rings/ledger as solves (DESIGN §30)
                for r in owned:
                    if r.qos is not None:
                        st.record_settle(r.qos, now - r.t_submit,
                                         r.cost)
        plan = fb.plan
        if fb.mesh:
            # the mesh lane: the dispatched pytree IS the session state
            # (no slot axis to slice), and the session stays UNPINNED —
            # its state is batch-sharded across the plan's mesh, not
            # resident on this lane's device (DESIGN §32)
            trees = [fb.factors]
        else:
            trees = unstack_tree(fb.factors, len(fb.reqs))
        for i, r in entries:
            if r not in owned:
                continue
            A_i = fb.A if fb.mesh else fb.A[i]
            # tier-opened sessions keep A resident even without refine:
            # tier solves always consume A0 (the ladder refines against
            # the full-precision base, §33)
            keep_A = A_i if (plan.key.refine or fb.tier is not None) \
                else None
            session = SolveSession(plan, trees[i], keep_A,
                                   A_i, r.policy,
                                   device=None if fb.mesh
                                   else self.device, sid=r.sid,
                                   served_tier=fb.tier)
            if fb.wA is not None:
                # the probe row wA = w^T A0 came out of the checked
                # factor dispatch — the session opens with its half of
                # the Freivalds check already resident (a tuple of
                # stacks for QR plans: slice each part)
                session._probe = fb.wA if fb.mesh else (
                    tuple(p[i] for p in fb.wA)
                    if isinstance(fb.wA, tuple) else fb.wA[i])
            r.future.set_result(session)

    # futures-owner
    def _drain_redispatch(self, reqs, exc) -> None:
        """Survivor re-dispatch from the drain side: re-solve each
        request solo, synchronously (this is the rare failure path — the
        drain thread may block)."""
        if len(reqs) == 1:
            self.eng._fail(reqs, exc)
            return
        resilience.bump("survivor_redispatches", len(reqs))
        for r in reqs:
            self._solo_drain(r)

    # futures-owner
    def _solo_drain(self, r) -> None:
        """One request, re-dispatched and drained inline, with its own
        health verdict and (if needed) escalation ladder."""
        eng = self.eng
        session = r.session
        if not self._admit_stage([r]):
            return
        try:
            buf, spec = self._stage([r])
            if (eng.health is not None and eng.health.check_rhs
                    and not self._isolate_poisoned([r])):
                return
            self._revive_for(session, [r])
            x, verdict = self._solve_session(session, buf, r.precision)
            if verdict is not None:
                limit = eng._limit(session)
                healthy, finite, res = resilience.evaluate(verdict, limit)
                if resilience.data_fault(eng._faults, "solve",
                                         "unhealthy") is not None:
                    healthy = False
                if not healthy:
                    resilience.bump("output_failures")
                    eng._restore_guards()
                    self._escalate_settle(session, spec, buf, finite, res)
                    return
                if session._breaker is not None:
                    session._breaker.record_success()
            eng._settle(spec, np.asarray(x))
        except Exception as e:  # noqa: BLE001
            eng._fail([r], e)

    # futures-owner
    def _drain_unhealthy(self, session, spec, buf, finite, res) -> None:
        """An unhealthy verdict on a drained batch: multi-request
        batches isolate first (solo re-dispatch finds the sick request —
        a poisoned column fails alone, the survivors answer); a solo
        batch climbs the escalation ladder directly."""
        reqs = [r for r, _si, _lo in spec]
        if len(reqs) > 1:
            resilience.bump("survivor_redispatches", len(reqs))
            for r in reqs:
                self._solo_drain(r)
            return
        self._escalate_settle(session, spec, buf, finite, res)

    # futures-owner
    def _escalate_settle(self, session, spec, buf, finite, res) -> None:
        """Run the ladder for one request's staged buffer; settle on
        recovery, fail with the structured evidence (and count toward
        quarantine) otherwise. Tier-routed requests climb the precision
        ladder FIRST (`resilience.escalate_precision` — cheap higher-
        tier re-solves before any refactor), then fall through to the
        native rungs."""
        eng = self.eng
        reqs = [r for r, _si, _lo in spec]
        br = session._breaker
        evidence0 = {"rung": "dispatch", "finite": finite,
                     "residual": res}
        try:
            if reqs[0].precision is not None:
                xh = resilience.escalate_precision(
                    session, buf, reqs[0].precision, eng.health,
                    eng._limit(session), evidence0=evidence0,
                    faults=eng._faults)
            else:
                xh = resilience.escalate(
                    session, buf, eng.health, eng._limit(session),
                    evidence0=evidence0,
                    faults=eng._faults)
        except Exception as e:  # noqa: BLE001 — SolveUnhealthy et al.
            if br is not None:
                br.record_failure()
            eng._fail(reqs, e)
            return
        if br is not None:
            br.record_success()
        eng._settle(spec, xh)


class ServeEngine:
    """A thread-safe request queue in front of a fleet of SolveSessions.

    Knobs (the latency/throughput dial, DESIGN.md §19; resilience §20):

    max_batch_delay: how long the dispatcher holds the first request of a
        batch while more arrive to coalesce with it. 0 disables the wait
        (requests still coalesce when they are already queued — the burst
        shape); larger trades p50 latency for wider device dispatches.
    max_pending: admission bound on un-answered requests (queued plus in
        flight). `on_full` picks the policy at the bound: 'reject' (shed:
        submit raises :class:`EngineSaturated` with a backoff hint) or
        'block' (backpressure the submitter).
    max_coalesce_width: cap on coalesced RHS columns per dispatch — also
        the widest bucket `prewarm` needs to cover for a compile-free
        steady state.
    max_factor_batch: cap on coalesced factorizations per factor-lane
        dispatch (rounded up to a power of two — the batch buckets) and
        the widest `factor_batches` bucket `prewarm` needs to cover.
    stack_sessions / max_stack: opt-in gang-resident cross-session
        stacking for single-system plans (see module docstring;
        `max_stack` caps a gang's membership). Both are live knobs
        (`set_knobs`), which is how the adaptive controller steers
        them.
    max_lane_pending: optional per-lane slice of the pending bound on
        multi-lane engines — one lane's backlog sheds its own overflow
        (per-lane `sheds` counted in the lane stats rows) instead of
        filling `max_pending` and starving every other lane's
        admission. None (default) keeps the single global bound.
    latency_window: how many completed-request latencies the percentile
        window keeps.
    health: a :class:`~conflux_tpu.resilience.HealthPolicy` switches on
        the numerical guards (RHS finite checks, fused output verdicts,
        escalation, quarantine). None (default) keeps the dispatch path
        byte-identical to the unguarded engine — the checked programs
        are *different* compiled programs, so guarded answers are
        allclose, not bitwise, the unguarded ones.
    fault_plan: a :class:`~conflux_tpu.resilience.FaultPlan` consulted at
        the instrumented sites (staging, dispatch, drain, d2h, solve) —
        deterministic chaos for tests/soak; None costs one comparison.
    watchdog_interval: poll period of the worker-liveness watchdog
        (0 disables; a worker dying by exception still trips the same
        path directly).
    residency: a :class:`~conflux_tpu.tier.ResidentSet` managing the
        served fleet's tiers (DESIGN §23). The engine then (a) faults
        spilled sessions back in BEFORE dispatching to them —
        deadline-aware, so a request whose deadline expires mid-revival
        releases its admission slot and the session stays fully spilled
        — and (b) lends its coalesced factor lane to the manager's
        stale-drift revivals. `engine.stats()` gains the manager's tier
        gauges, and `checkpoint()`/`restore()` default to this fleet.
    revive_wait: worker-thread cap (seconds) on waiting for a revive
        admission slot when the faulting requests carry no deadline —
        bounds how long a saturated revive lane can stall the
        dispatcher before the requests fail with structured
        `SessionSpilled`.
    controller: a :class:`~conflux_tpu.control.AdaptiveController`
        (DESIGN §24) — opt-in closed-loop autotuning of the knobs above
        against a latency SLO, from windowed telemetry, on its own
        daemon thread. The controller writes exclusively through
        :meth:`set_knobs` (thread-safe, validated, never holding a lock
        across a dispatch) and only ever routes traffic onto
        already-prewarmed bucket programs. None (default) leaves every
        knob exactly as constructed — the default dispatch path is
        byte-identical to the controller-free engine.
    """

    def __init__(self, *, max_batch_delay: float = 0.002,
                 max_pending: int = 1024, on_full: str = "reject",
                 max_coalesce_width: int = 32,
                 max_factor_batch: int = 32,
                 stack_sessions: bool = False, max_stack: int = 8,
                 max_lane_pending: int | None = None,
                 latency_window: int = 8192,
                 persistent_cache: bool = True,
                 health: HealthPolicy | None = None,
                 fault_plan=None,
                 watchdog_interval: float = 0.2,
                 residency=None, revive_wait: float = 30.0,
                 controller=None,
                 lanes: int | str = 1, devices=None,
                 max_lane_revives: int = 8):
        if on_full not in ("reject", "block"):
            raise ValueError(f"unknown on_full {on_full!r} (reject|block)")
        if max_pending < 1 or max_coalesce_width < 1 or max_stack < 1 \
                or max_factor_batch < 1:
            raise ValueError("max_pending, max_coalesce_width, max_stack "
                             "and max_factor_batch must be >= 1")
        # ---- the lane fleet (DESIGN §25) -------------------------------
        # lanes=1 (default) or a one-device host: ONE lane on the default
        # device — the pre-fleet engine, byte-identical. lanes='auto':
        # one lane per jax device. devices=: an explicit device list.
        if devices is not None:
            devs = list(devices)
            if not devs:
                raise ValueError("devices must name at least one device")
        else:
            n = jax.device_count() if lanes == "auto" else int(lanes)
            if n < 1:
                raise ValueError("lanes must be >= 1 or 'auto'")
            if n == 1:
                devs = [None]
            else:
                avail = jax.devices()
                if n > len(avail):
                    raise ValueError(
                        f"lanes={n} exceeds jax.device_count()="
                        f"{len(avail)}")
                devs = list(avail[:n])
        if len(devs) == 1 and devices is None:
            devs = [None]  # single lane rides the default device
        if max_lane_revives < 0:
            raise ValueError("max_lane_revives must be >= 0")
        self.max_lane_revives = int(max_lane_revives)
        if persistent_cache:
            from conflux_tpu import cache

            cache.enable_persistent_cache()
        self.max_batch_delay = float(max_batch_delay)
        self.max_pending = int(max_pending)
        self.on_full = on_full
        self.max_coalesce_width = int(max_coalesce_width)
        self.max_factor_batch = rank_bucket(int(max_factor_batch))
        self.stack_sessions = bool(stack_sessions)
        self.max_stack = int(max_stack)
        # per-lane pending slice (DESIGN §25 follow-on): with a value
        # set, a multi-lane engine bounds each lane's share of the
        # pending set so one lane's backlog cannot starve admission
        # fleet-wide. None (default) keeps the single global bound —
        # byte-identical to the PR 9 engine.
        if max_lane_pending is not None and max_lane_pending < 1:
            raise ValueError("max_lane_pending must be >= 1 (or None)")
        self.max_lane_pending = (None if max_lane_pending is None
                                 else int(max_lane_pending))
        self.health = health
        self._faults = fault_plan
        self.watchdog_interval = float(watchdog_interval)
        self.residency = residency
        self.revive_wait = float(revive_wait)
        if residency is not None and residency.engine is None:
            # lend the factor lane to the tier manager's stale-drift
            # revivals (tier.ResidentSet._revive_refactor)
            residency.engine = self

        # per-device lanes: each owns its input queue, its 2-deep
        # double-buffer handoff queue (the dispatcher stages/dispatches
        # batch i+1 while the drain thread waits on batch i; a third
        # batch blocks the dispatcher instead of growing in-flight
        # device work), its dispatcher/drain threads, and its carry
        self._lanes: tuple = tuple(
            DeviceLane(self, i, d) for i, d in enumerate(devs))
        self._lane_by_dev: dict = {_devkey(ln.device): ln
                                   for ln in self._lanes}
        # the shared cold-start pool (multi-lane only): factor requests
        # with no explicit placement queue here and any lane with a free
        # dispatch round draws them — work-stealing load balance.
        # Guarded by _lock for mutation; emptiness fast-checks are racy
        # by design (a missed draw is picked up by the next wake).
        self._factor_pool: deque = deque()
        # one wake in flight at a time: a submission burst must not fan
        # WAKEs across every lane (each waked lane would draw a sliver
        # and the burst would factor in fragments instead of full
        # buckets) — the flag clears at the next pool draw, and the
        # drawing lane re-wakes if backlog remains
        self._pool_waked = False        # guarded-by: _lock
        self._sid_seq = 0               # guarded-by: _lock
        # the admission lock: every counter and the live set below are
        # `# guarded-by: _lock` (conflint CFX-LOCK enforces it). This
        # lock must NEVER be held across a device dispatch — the
        # lockcheck harness forbids it at runtime.
        self._lock = threading.Lock()
        self._not_full = threading.Condition(self._lock)
        self._closed = False            # guarded-by: _lock
        # checkpoint drain barrier: admission holds while True so the
        # snapshot observes a quiesced fleet (pending == 0)
        self._draining = False          # guarded-by: _lock
        # serializes whole checkpoint() calls: two overlapping drains
        # would share the single _draining flag, and whichever snapshot
        # finished first would reopen admission under the other —
        # silently voiding its pending==0 consistent cut
        self._ckpt_lock = threading.Lock()
        self._pending = 0               # guarded-by: _lock
        self._queue_peak = 0            # guarded-by: _lock
        self._requests = 0              # guarded-by: _lock
        self._completed = 0             # guarded-by: _lock
        self._failed = 0                # guarded-by: _lock
        self._sheds = 0                 # guarded-by: _lock
        self._consec_sheds = 0          # guarded-by: _lock
        self._batches = 0               # guarded-by: _lock
        self._coalesced_requests = 0    # guarded-by: _lock
        self._latencies: deque = deque(  # guarded-by: _lock
            maxlen=int(latency_window))
        # factor-lane (cold-start) counters: batches dispatched, requests
        # coalesced into them, total bucket slots vs pad slots (the
        # pad-waste ratio), and the session-open latency window
        self._factor_requests = 0       # guarded-by: _lock
        self._factor_batches = 0        # guarded-by: _lock
        self._factor_coalesced = 0      # guarded-by: _lock
        self._factor_slots = 0          # guarded-by: _lock
        self._factor_pad = 0            # guarded-by: _lock
        self._factor_latencies: deque = deque(  # guarded-by: _lock
            maxlen=int(latency_window))
        # window-delta telemetry for the adaptive controller (and any
        # profiler.StatsWindow): total samples ever appended to each
        # rolling latency window (sequence tokens for latency_window()),
        # per-bucket dispatch hit counters, and the count of chunks the
        # coalescing width cap split (the width-growth pressure signal)
        self._lat_seq = 0               # guarded-by: _lock
        self._flat_seq = 0              # guarded-by: _lock
        self._bucket_hits: dict = {}    # guarded-by: _lock
        self._factor_bucket_hits: dict = {}  # guarded-by: _lock
        self._width_capped = 0          # guarded-by: _lock
        # gang-stacked serving telemetry (DESIGN §26): stacked batches
        # dispatched and the requests they carried; per-reason counts
        # of sessions that fell back to a solo dispatch instead of
        # stacking (the exclusion trace); and, with stacking DISABLED,
        # the per-window count of same-plan sessions that would have
        # stacked — the controller's enable signal
        self._gang_batches = 0          # guarded-by: _lock
        self._gang_coalesced = 0        # guarded-by: _lock
        self._gang_opportunity = 0      # guarded-by: _lock
        # pre-seeded so the closed holes are PROVABLY closed: a bench
        # or ops read sees upd_pending/checked at literal zero, not
        # merely absent (they only move if a regression reopens them)
        self._stack_exclusions: dict = {  # guarded-by: _lock
            k: 0 for k in ("upd_pending", "checked", "mesh", "batched",
                           "singleton", "stack_cap", "error",
                           "kind", "precision")}
        # recently-served sessions/plans, weakly held — the adaptive
        # controller's prewarm targets (active_targets())
        self._active_sessions: dict = {}  # guarded-by: _lock
        self._active_plans: dict = {}     # guarded-by: _lock
        # measured drain rate (completions/s, EMA) installed by the
        # controller; sizes EngineSaturated.retry_after when present
        self._drain_rate: float | None = None  # guarded-by: _lock
        # guard-relaxation state: the controller may thin the staging
        # guard to 1-in-stride batches and swap in a relaxed policy
        # after a long clean streak; ANY trip restores both instantly
        # (engine-side, `_restore_guards` — never waiting for a
        # controller tick). Benign racy reads by design: both old and
        # new values are valid, a stale read only moves one batch's
        # sampling point.
        self._staging_stride = 1
        self._staging_tick = 0          # guarded-by: _lock
        self._health_strict = health
        # every admitted, unanswered request. Resolution OWNERSHIP: a
        # request's future is only ever resolved by the path that removed
        # it from this set under the lock (`_take`), so a wedged worker
        # finishing late after close()/watchdog failed its request can
        # never double-resolve the Future.
        self._live: set = set()         # guarded-by: _lock
        # (thread name, exc) post-mortem: write-once by the dying worker,
        # racy reads tolerate staleness by design — not lock-guarded
        self._dead: tuple | None = None
        # multi-tenant QoS state (DESIGN §30): stays None until the
        # first CLASSIFIED submission, so the qos=None engine carries no
        # new state and every hot-path branch is one attribute check
        self._qos = None                # guarded-by: _lock
        self._qos_latency_window = int(latency_window)

        profiler.register_engine(self)
        for lane in self._lanes:
            lane.start()
        self._watchdog = None
        if self.watchdog_interval > 0:
            self._watchdog = threading.Thread(
                target=self._watchdog_loop, name="serve-engine-watchdog",
                daemon=True)
            self._watchdog.start()
        # the adaptive controller (conflux_tpu.control) attaches LAST so
        # its first window observes a fully-constructed engine; close()
        # stops it first, and its loop exits on its own when a watchdog
        # trip closes the engine under it (the knobs simply freeze at
        # their last values — the controller is advisory, never
        # load-bearing)
        self._controller = None
        if controller is not None:
            controller.attach(self)
            controller.start()
            self._controller = controller

    # ------------------------------------------------------------------ #
    # client surface
    # ------------------------------------------------------------------ #

    # hot-path (admission: host work only, no device syncs)
    def submit(self, session, b, *, deadline: float | None = None,
               qos=None, precision=None) -> Future:
        """Enqueue one solve against `session`; returns a Future whose
        result is a HOST (numpy) array with the shape and values
        `session.solve(b)` would have returned. A served answer crosses
        the host boundary anyway, so the engine pays it once per
        coalesced batch (one contiguous device->host copy on the drain
        thread) instead of per request — the per-request scatter is then
        numpy views, zero extra device dispatches.

        `deadline` (seconds from now) bounds how long the request may
        wait: a request still queued past its deadline is lazily evicted
        — its pending slot is released and its future fails with
        :class:`DeadlineExceeded` (an abandoned `result(timeout)` alone
        would leak the slot). Raises :class:`EngineSaturated` at the
        pending bound under the 'reject' policy (with a `retry_after`
        backoff hint); blocks under 'block'. With a
        :class:`HealthPolicy`, a non-finite RHS raises
        :class:`RhsNonFinite` here and a quarantined session
        :class:`SessionQuarantined`.

        `qos=` classifies the request (DESIGN §30,
        :class:`conflux_tpu.qos.QosClass`): the tenant joins the
        weighted fair-share ledger — an over-share tenant on a
        contended engine is shed with a structured
        :class:`~conflux_tpu.resilience.TenantThrottled` — and the
        tier picks the request's collect delay inside the lane's
        coalescing window (latency ~0, throughput the engine window,
        batch a stretched window). `qos=None` (the default) keeps
        every path byte-identical to the unclassified engine.

        `precision=` routes THIS request through a served tier's
        program family (DESIGN §33): a tier name
        (`serve.PRECISION_TIERS`) dispatches that tier, 'auto' starts
        on the session's sticky rung and ALWAYS carries the fused §20
        verdict (the ladder's escalation signal — even on an unguarded
        engine), None keeps the session's own serving config (bitwise
        pre-§33 for native sessions). Tier requests are a counted gang
        exclusion, never an error; mesh-sharded plans refuse them at
        admission."""
        return self._admit(self._prepare(session, b, deadline, qos,
                                         precision))

    # hot-path (admission prelude: validation + request construction —
    # no locks, no device syncs)
    def _prepare(self, session, b, deadline=None, qos=None,
                 precision=None):
        """submit()'s lock-free prelude — fast-fail checks, RHS
        normalization/guarding, request construction, lane resolution.
        Shared with :meth:`submit_many` so a batched wire frame runs
        the identical validation per item."""
        # conflint: disable=CFX-LOCK benign racy fast-fail; _admit re-checks locked
        if self._closed:
            raise EngineClosed("submit() on a closed ServeEngine")
        if self._dead is not None:
            name, exc = self._dead
            raise EngineClosed(f"engine worker {name} died: {exc!r}")
        if self.health is not None:
            br = resilience.breaker_for(session, self.health)
            ok, retry = br.allow()
            if not ok:
                raise SessionQuarantined(
                    f"session quarantined after repeated escalation "
                    f"failures (breaker open; probe in ~{retry:.2f}s)",
                    retry_after=retry)
        b2, squeeze = _normalize_rhs(session, b)
        if (self.health is not None and self.health.check_rhs
                and not resilience.rhs_finite(
                    b2, sample=self.health.submit_guard_sample)):
            resilience.bump("rhs_rejects")
            self._restore_guards()
            raise RhsNonFinite(
                "rhs contains NaN/Inf — rejected at admission (a poisoned "
                "request would corrupt every co-batched answer)")
        if qos is not None and not isinstance(qos, qos_mod.QosClass):
            raise TypeError(f"qos must be a conflux_tpu.qos.QosClass "
                            f"(or None), got {type(qos).__name__}")
        precision = serve.check_precision_request(precision)
        if precision is not None and session.plan.mesh is not None:
            raise MeshPlanUnsupported(
                "mesh-sharded plans serve their native precision only — "
                "per-request precision= does not compose with the mesh "
                "lane (DESIGN §33)", surface="submit")
        now = time.perf_counter()
        req = _Request(session, b2, int(b2.shape[-1]), squeeze, Future(),
                       now, None if deadline is None else now + deadline,
                       qos=qos, precision=precision)
        if qos is not None:
            # byte/flop-aware ledger weight (DESIGN §32): a large-N
            # mesh solve occupies the slots it actually displaces
            req.cost = qos_mod.request_cost(session.plan.key.shape,
                                            width=req.width)
        # resolve the owning lane BEFORE admission (placement may move a
        # not-yet-pinned session's state — device work, so never under
        # the admission lock), so every live request is lane-attributed
        # for the per-lane watchdog
        req.lane = self._lane_for(session)
        return req

    # hot-path (batched admission: ONE lock round-trip per wire frame)
    def submit_many(self, items) -> list:
        """Batched :meth:`submit` for the zero-copy wire (DESIGN §31):
        `items` is ``[(session, b, qos)]``; returns len(items) futures,
        aligned. Items that can be admitted WITHOUT waiting are
        admitted under a single acquisition of the admission lock — a
        coalesced control frame pays one lock round-trip instead of
        one per request — and routed (queue pushes) outside it, like
        submit(). An item that would have to WAIT (the checkpoint
        drain barrier, or the 'block' policy at the global/per-lane
        pending bound) first flushes its already-admitted frame-mates
        to their lanes, then waits alone through the ordinary
        :meth:`_admit` path: an admitted-but-unrouted request can
        never complete, so a condition wait that needs ITS pending
        slot to free would deadlock the frame (and wedge the wire
        recv thread behind it).

        Per-item failures (validation, quarantine, saturation, tenant
        throttle) are set ON that item's future instead of raised, so
        one bad request never takes down its frame-mates; the wire
        encodes each future's exception back to its own caller."""
        reqs: deque = deque()
        futs: list = []
        for session, b, qos in items:
            try:
                req = self._prepare(session, b, qos=qos)
            except Exception as e:
                fut = Future()
                fut.set_exception(e)
                futs.append(fut)
            else:
                reqs.append(req)
                futs.append(req.future)
        while reqs:
            admitted = []
            with self._lock:
                while reqs:
                    req = reqs[0]
                    try:
                        if not self._admit_locked(req, wait=False):
                            break  # would wait: flush admitted first
                    except Exception as e:
                        reqs.popleft()
                        req.future.set_exception(e)
                        continue
                    reqs.popleft()
                    admitted.append(req)
            for req in admitted:
                self._route(req)
            if reqs:
                # the head of the remainder must wait; every admitted
                # frame-mate is routed by now (free to complete and
                # release its slot), so the plain blocking path holds
                # no frame state — then resume batching the tail
                req = reqs.popleft()
                try:
                    self._admit(req)
                except Exception as e:
                    req.future.set_exception(e)
        return futs

    def _admit(self, req) -> Future:
        """Shared admission control for both lanes: the bounded pending
        set (shed with a backoff hint, or block), registration in the
        `_live` resolution-ownership set, and the queue push."""
        with self._lock:
            self._admit_locked(req)
        self._route(req)
        return req.future

    # requires-lock: _lock
    def _admit_locked(self, req, wait: bool = True) -> bool:
        """The locked body of admission (also the per-item step of
        :meth:`submit_many`'s batch). May WAIT on `_not_full` (drain
        barrier / 'block' policy); with ``wait=False`` every
        would-wait site instead returns False with NOTHING committed,
        so a batched caller can route its already-admitted work before
        blocking — a wait taken while admitted-but-unrouted
        frame-mates hold pending slots could never be satisfied by
        them. Returns True when the request was admitted."""
        if self._closed:
            raise EngineClosed("submit() on a closed ServeEngine")
        while self._draining and not self._closed:
            if isinstance(req, _FactorRequest):
                # A factor submission must SHED at the drain
                # barrier, never wait: a client-thread stale-drift
                # revival (tier._revive_refactor) legitimately
                # holds its session RLock while submitting here,
                # and checkpoint()'s save_fleet needs that same
                # lock — and _draining only clears after save_fleet
                # returns, so waiting would close the cycle and
                # wedge the engine forever. EngineSaturated routes
                # the revival onto its direct plan._factor_once
                # fallback (same program family, same bits).
                raise EngineSaturated(
                    "factor lane paused at the checkpoint drain "
                    "barrier (snapshot serializing) — retry "
                    "shortly, or fall back to plan.factor",
                    retry_after=0.05)
            if not wait:
                return False
            # checkpoint drain barrier: hold admission (both
            # policies) until the snapshot completes — brief by
            # construction, the snapshot is host-side serialization
            self._not_full.wait()
        if self._closed:
            raise EngineClosed("engine closed while checkpointing")
        if self._pending >= self.max_pending:
            if self.on_full == "reject":
                self._sheds += 1
                self._consec_sheds += 1
                hint, why = self._shed_hint_locked()
                raise EngineSaturated(
                    f"{self._pending} pending requests >= max_pending="
                    f"{self.max_pending} (shed policy 'reject'; "
                    f"{why})", retry_after=hint,
                    **self._qos_shed_attr(req))
            if not wait:
                return False
            while self._pending >= self.max_pending \
                    and not self._closed:
                self._not_full.wait()
            if self._closed:
                raise EngineClosed("engine closed while blocked")
        lane = getattr(req, "lane", None)
        slice_cap = self.max_lane_pending
        take_slot = (slice_cap is not None and lane is not None
                     and len(self._lanes) > 1)
        if take_slot:
            # the per-lane pending slice: one hot lane's backlog
            # sheds ITS OWN overflow instead of filling the global
            # bound and starving every other lane's admission
            if lane.pending >= slice_cap:
                if self.on_full == "reject":
                    self._sheds += 1
                    self._consec_sheds += 1
                    lane.sheds += 1
                    hint, why = self._shed_hint_locked()
                    raise EngineSaturated(
                        f"lane {lane.index} holds {lane.pending} "
                        f"pending >= max_lane_pending={slice_cap} "
                        f"(per-lane slice; other lanes keep "
                        f"admitting — {why})", retry_after=hint,
                        **self._qos_shed_attr(req))
                if not wait:
                    return False
                while lane.pending >= slice_cap \
                        and not self._closed:
                    self._not_full.wait()
                if self._closed:
                    raise EngineClosed("engine closed while blocked")
        # weighted fair-share admission (DESIGN §30): runs LAST so
        # a throttle has committed nothing to roll back; the
        # qos=None path is one attribute check
        if req.qos is not None:
            self._qos_admit_locked(req)
        if take_slot:
            req.lane_slot = True
            lane.pending += 1
        self._consec_sheds = 0
        self._pending += 1
        self._requests += 1
        if isinstance(req, _FactorRequest):
            self._factor_requests += 1
        self._live.add(req)
        if self._pending > self._queue_peak:
            self._queue_peak = self._pending
        return True

    # requires-lock: _lock
    def _shed_hint_locked(self) -> tuple:
        """(retry_after, reason) for an EngineSaturated shed — sized
        from the measured drain rate when the controller installed one
        (the k-th consecutive shed backs off k drain intervals, so a
        retrying herd lands as slots actually free up), else the
        original exponential-backoff guess."""
        rate = self._drain_rate
        if rate is not None and rate > 0.0:
            hint = min(1.0, max(1e-4, self._consec_sheds / rate))
            why = (f"retry in ~{1e3 * hint:.0f}ms, sized "
                   f"from the measured drain rate "
                   f"{rate:.0f}/s")
        else:
            hint = min(1.0, 1e-3 * (1 << min(
                self._consec_sheds - 1, 10)))
            why = (f"retry in ~{1e3 * hint:.0f}ms, backoff "
                   "hint doubles per consecutive shed")
        return hint, why

    def _qos_shed_attr(self, req) -> dict:
        """Saturation-shed attribution (DESIGN §30): {} on the
        qos=None path (EngineSaturated raises exactly as before);
        tenant/qos_class kwargs — plus the lazy per-class health count
        `engine_saturated[tenant/tier]` — for a classified request, so
        a global-bound shed is auditable next to the fair-share
        TenantThrottled sheds."""
        if req.qos is None:
            return {}
        key = req.qos.key
        resilience.bump(f"engine_saturated[{key}]")
        return {"tenant": req.qos.tenant, "qos_class": key}

    # requires-lock: _lock
    def _qos_admit_locked(self, req) -> None:
        """Weighted fair-share admission for a CLASSIFIED request (the
        qos=None path never calls this). Lazily creates the engine's
        QoS state, interns the class (latest declaration of a
        tenant/tier key wins), and consults the ledger: a throttle
        raises :class:`~conflux_tpu.resilience.TenantThrottled` with a
        `retry_after` sized from the tenant's weighted fraction of the
        measured drain rate — by then roughly one of the tenant's OWN
        slots should have freed. Throttling is a policy outcome, so it
        applies under both on_full policies ('block' waits out global
        saturation but never a fair-share violation — blocking would
        let the over-quota tenant queue in front of everyone else)."""
        st = self._qos
        if st is None:
            st = self._qos = qos_mod.EngineQosState(
                self._qos_latency_window)
        cls = st.intern(req.qos)
        req.qos = cls
        over = st.ledger.try_admit(cls, self._pending, self.max_pending,
                                   req.cost)
        if over is None:
            st.record_admit(cls)
            return
        st.record_throttle(cls)
        rate = self._drain_rate
        frac = st.ledger.frac(cls.tenant)
        if rate is not None and rate * frac > 0.0:
            hint = min(1.0, max(1e-4, over / (rate * frac)))
            why = (f"retry in ~{1e3 * hint:.0f}ms, sized from the "
                   f"tenant's {100 * frac:.0f}% share of the measured "
                   f"drain rate {rate:.0f}/s")
        else:
            hint = min(1.0, 2e-3 * max(1.0, over))
            why = (f"retry in ~{1e3 * hint:.0f}ms, scaled by the "
                   "tenant's over-share backlog")
        raise resilience.TenantThrottled(
            f"tenant {cls.tenant!r} is at/over its fair share "
            f"({st.ledger.share(cls.tenant, self.max_pending):.0f} of "
            f"max_pending={self.max_pending}) while the engine is "
            f"contended ({self._pending} pending; {why})",
            retry_after=hint, tenant=cls.tenant, qos_class=cls.key)

    def _note_exclusion(self, reason: str) -> None:
        """Count one stacking exclusion: a session the gang path COULD
        have stacked fell back to a solo dispatch. Before PR 10 a
        disqualified session left no trace of why — these per-reason
        counters ('upd_pending', 'checked', 'mesh', 'batched',
        'singleton', 'stack_cap', 'error') are the trace, surfaced in
        `stats()`/`counters()` and merged into
        `profiler.serve_stats()['engine']`."""
        with self._lock:
            self._stack_exclusions[reason] = \
                self._stack_exclusions.get(reason, 0) + 1

    def _route(self, req) -> None:
        """Hand an admitted request to its lane's queue — or, for an
        unpinned cold-start on a multi-lane engine, to the shared
        work-stealing pool (waking the least-loaded lane). The dead-lane
        re-sweep closes the race between a lane dying and a request
        landing in its queue: either the trip's sweep of `_live` sees
        the request, or this sees `dead` — resolution ownership makes a
        double sweep harmless."""
        if isinstance(req, _FactorRequest) and req.pool:
            with self._lock:
                self._factor_pool.append(req)
            self._wake_lane()
            return
        lane = req.lane
        d = lane._inq.qsize() + 1
        if d > lane.queue_hw:  # monotone high-water; racy max by design
            lane.queue_hw = d
        lane._inq.put(req)
        if lane.dead:
            with self._lock:
                leftover = [r for r in self._live
                            if getattr(r, "lane", None) is lane]
            self._fail(leftover, EngineClosed(
                f"lane {lane.index} is dead (worker threads exhausted "
                f"their revival budget) — request failed by the "
                "admission front"))

    # hot-path (placement: at most one state move per session, ever)
    def _lane_for(self, session):
        """The lane that owns `session`, pinning it on first contact.

        Placement is deterministic (DESIGN §25): an explicit
        `session.device` wins; otherwise the consistent hash of the
        session id over the engine's devices (`place_session`) — a
        session with no sid gets one assigned (stable for its lifetime;
        give sessions stable sids for restart-deterministic placement).
        Mesh-sharded sessions are never pinned — their state spans the
        whole mesh — and ride the first live lane (the DESIGN §25
        placeholder made real: the lane contributes its dispatcher/
        drain threads, admission and coalescing; the mesh contributes
        the devices). Sessions on a device no lane
        serves (or a dead lane) are served by the first live lane:
        dispatch follows the committed factors, so answers are
        unaffected, only the thread that runs them."""
        lanes = self._lanes
        if len(lanes) == 1:
            return lanes[0]
        if session.plan.mesh is not None:
            for ln in lanes:
                if not ln.dead:
                    return ln
            return lanes[0]
        dev = session.device
        if dev is None:
            with session._lock:
                dev = session.device
                if dev is None:
                    if session.sid is None:
                        session.sid = self._auto_sid()
                    alive = [ln.device for ln in lanes if not ln.dead]
                    dev = place_session(
                        session.sid,
                        alive or [ln.device for ln in lanes])
                    session.to_device(dev)
        lane = self._lane_by_dev.get(_devkey(dev))
        if lane is None or lane.dead:
            for ln in lanes:
                if not ln.dead:
                    return ln
            return lanes[0]
        return lane

    def _auto_sid(self) -> str:
        with self._lock:
            self._sid_seq += 1
            return f"auto-{self._sid_seq}"

    @property
    def lanes(self) -> tuple:
        """The engine's :class:`DeviceLane`s, in device order (length 1
        on a single-lane engine)."""
        return self._lanes

    @property
    def devices(self) -> tuple:
        """The lane devices (a single None = the default device)."""
        return tuple(ln.device for ln in self._lanes)

    def placement(self, sid):
        """The device `place_session` pins `sid` to on THIS engine's
        device list — the ops-facing "where would this session land"
        query."""
        return place_session(sid, [ln.device for ln in self._lanes])

    def _pool_pending(self) -> bool:
        # racy emptiness fast-check by design (see __init__)
        return bool(self._factor_pool)

    def _pool_draw(self, n) -> list:
        """Pop up to `n` queued cold-start requests from the shared
        factor pool (None = all) — lane dispatchers call this every
        round, so any lane with a free round takes work. Drawing clears
        the wake-in-flight flag: the next submission burst gets a fresh
        wake."""
        if not self._factor_pool:
            return []
        out: list = []
        with self._lock:
            while self._factor_pool and (n is None or len(out) < n):
                out.append(self._factor_pool.popleft())
            self._pool_waked = False
        return out

    def _wake_lane(self, force: bool = False) -> None:
        """Nudge the least-loaded live lane (queue depth, ties to the
        lowest index) — the admission half of cold-start load
        balancing; the dispatch-round pool draw is the stealing half.
        At most one wake rides between draws (see `_pool_waked`) so a
        burst coalesces into full buckets; `force` bypasses that (lane
        death re-arms the pool)."""
        with self._lock:
            if self._pool_waked and not force:
                return
            self._pool_waked = True
        best = None
        best_load = None
        for ln in self._lanes:
            if ln.dead:
                continue
            load = ln._inq.qsize()
            if best is None or load < best_load:
                best, best_load = ln, load
        if best is not None:
            best._inq.put(_WAKE)

    # hot-path (admission: host work only, no device syncs)
    def submit_factor(self, plan, A, *, policy=None,
                      deadline: float | None = None,
                      sid=None, device=None, qos=None,
                      precision=None) -> Future:
        """Enqueue one factorization against `plan`; returns a Future
        whose result is a device-resident
        :class:`~conflux_tpu.serve.SolveSession` — exactly what
        ``plan.factor(A, policy=policy)`` would have opened, down to the
        bits (both ride the same stacked factor program family; see
        `FactorPlan._stacked_factor_fn`). Same-plan requests landing in
        one ``max_batch_delay`` window coalesce into ONE vmapped batched
        factor dispatch at a power-of-two batch bucket, so session churn
        pays the per-dispatch overhead once per batch instead of once
        per matrix.

        `A` is host-staged (numpy memcpy into the stacked buffer — one
        transfer per batch); pad slots carry identity matrices. Shares
        the solve lane's admission control (:class:`EngineSaturated` /
        'block', `deadline=` lazy eviction, close semantics). With a
        :class:`HealthPolicy`, a non-finite `A` raises
        :class:`RhsNonFinite` here (sampled guard; the staging guard
        re-checks exactly), and every coalesced factorization carries a
        fused per-slot post-factor finite/probe-residual verdict —
        a sick slot re-dispatches solo and fails alone with structured
        evidence (:class:`SolveUnhealthy`), its co-batched neighbours
        untouched. Mesh-sharded plans ride the MESH LANE (DESIGN §32):
        the request dispatches as its own batch-sharded (B, N, N)
        factor — no slot stacking, the batch axis is the parallelism —
        through the first live lane's dispatcher, with the same
        admission, deadline, staging-guard and per-batch health
        machinery; the opened session is unpinned (its state spans the
        mesh). Only `device=` naming a device outside the plan's mesh
        still raises :class:`~conflux_tpu.resilience.MeshPlanUnsupported`
        (sharded state cannot migrate off its mesh).

        On a multi-lane engine the cold start LOAD-BALANCES: with no
        `sid`/`device` the request joins the shared pool and whichever
        lane has a free dispatch round takes it (work-stealing);
        `sid=` pins the opened session by consistent hash
        (`place_session` — deterministic across restarts), `device=`
        pins it explicitly.

        `qos=` classifies the cold start exactly as on :meth:`submit`
        (DESIGN §30): the tenant's factor churn counts against the
        same fair-share ledger as its solves, so a bulk tenant cannot
        starve the latency class by flooding session opens instead."""
        # conflint: disable=CFX-LOCK benign racy fast-fail; _admit re-checks locked
        if self._closed:
            raise EngineClosed("submit_factor() on a closed ServeEngine")
        if self._dead is not None:
            name, exc = self._dead
            raise EngineClosed(f"engine worker {name} died: {exc!r}")
        if not isinstance(plan, FactorPlan):
            raise TypeError(f"submit_factor takes a FactorPlan, got "
                            f"{type(plan).__name__} (submit() serves "
                            "sessions)")
        if plan.mesh is not None and device is not None \
                and not any(device == d for d in plan.mesh.devices.flat):
            raise MeshPlanUnsupported(
                "device= names a device outside this plan's mesh — a "
                "mesh-sharded session's state cannot migrate off its "
                "mesh", surface="factor_lane")
        # conflint: disable=CFX-HOSTSYNC host request ingestion, not a device readback
        A2 = np.asarray(A)
        if tuple(A2.shape) != plan.key.shape:
            raise ValueError(f"A shape {A2.shape} does not match the "
                             f"plan's {plan.key.shape}")
        want = np.dtype(plan.key.dtype)
        if A2.dtype != want:
            A2 = A2.astype(want)  # mirror jnp.asarray's implicit cast
        if (self.health is not None and self.health.check_rhs
                and not resilience.rhs_finite(
                    A2, sample=self.health.submit_guard_sample)):
            resilience.bump("factor_rejects")
            self._restore_guards()
            raise RhsNonFinite(
                "matrix contains NaN/Inf — rejected at admission (a "
                "poisoned system would waste a coalesced factor dispatch)")
        if qos is not None and not isinstance(qos, qos_mod.QosClass):
            raise TypeError(f"qos must be a conflux_tpu.qos.QosClass "
                            f"(or None), got {type(qos).__name__}")
        precision = serve.check_precision_request(precision)
        if precision == "auto":
            # a cold start has no verdict history yet: "auto" opens on
            # the ladder's cheapest rung (§33) and the session's first
            # checked solve drives any escalation
            precision = serve.PRECISION_TIERS[0]
        if precision is not None and plan.mesh is not None:
            raise MeshPlanUnsupported(
                "precision= tiers are not served for mesh-sharded "
                "plans (the ladder's program families are per-device)",
                surface="factor_lane")
        now = time.perf_counter()
        req = _FactorRequest(plan, A2, policy, Future(), now,
                             None if deadline is None else now + deadline,
                             sid=sid, device=device, qos=qos,
                             precision=precision)
        if qos is not None:
            # byte/flop-aware ledger weight: the O(N^3) cold start
            # counts for the slots it displaces (qos.request_cost)
            req.cost = qos_mod.request_cost(plan.key.shape, factor=True)
        if plan.mesh is not None:
            # the mesh lane: the opened session stays UNPINNED (its
            # state spans the mesh — an in-mesh device= was a placement
            # no-op) and the request rides the first live lane's
            # dispatcher, like _lane_for routes mesh solves
            req.device = None
            for ln in self._lanes:
                if not ln.dead:
                    req.lane = ln
                    break
            else:
                req.lane = self._lanes[0]
            return self._admit(req)
        # lane resolution (multi-lane): an explicit device pins the lane,
        # a sid pins it by consistent hash, otherwise the request joins
        # the shared pool and the lanes load-balance it between them
        if len(self._lanes) == 1:
            req.lane = self._lanes[0]
        elif device is not None:
            lane = self._lane_by_dev.get(_devkey(device))
            if lane is None:
                raise ValueError(
                    f"device {device} is not one of this engine's lane "
                    "devices — open the session with plan.factor, or "
                    "build the engine with devices= including it")
            req.lane = lane
        elif sid is not None:
            req.lane = self._lane_by_dev[_devkey(self.placement(sid))]
            req.device = req.lane.device
        else:
            req.pool = True
        return self._admit(req)

    def factor(self, plan, A, timeout: float | None = None, *,
               policy=None, deadline: float | None = None,
               sid=None, device=None, qos=None):
        """Blocking convenience (the mirror of :meth:`solve`):
        ``submit_factor(plan, A).result(timeout)`` — returns the opened
        :class:`~conflux_tpu.serve.SolveSession`."""
        return self.submit_factor(plan, A, policy=policy,
                                  deadline=deadline, sid=sid,
                                  device=device, qos=qos).result(timeout)

    def solve(self, session, b, timeout: float | None = None,
              deadline: float | None = None, qos=None, precision=None):
        """Blocking convenience: ``submit(session, b).result(timeout)``."""
        return self.submit(session, b, deadline=deadline,
                           qos=qos, precision=precision).result(timeout)

    # futures-owner
    def close(self, timeout: float | None = None) -> list:
        """Stop admission, drain every in-flight request, join the
        workers. Queued requests are answered, not dropped; idempotent.
        Returns the names of wedged worker threads ([] normally): when a
        join times out, still-pending futures are failed with
        :class:`EngineClosed` naming the wedged thread instead of being
        left hanging forever."""
        with self._lock:
            already = self._closed
            self._closed = True
            self._not_full.notify_all()
        if self._controller is not None:
            # stop the knob writer before tearing down what it tunes
            self._controller.close()
        if not already:
            for lane in self._lanes:
                lane._inq.put(_STOP)
        wedged = []
        for lane in self._lanes:
            lane._dispatcher.join(timeout)
            lane._drainer.join(timeout)
            wedged += [t.name for t in (lane._dispatcher, lane._drainer)
                       if t.is_alive() and not lane.dead]
        if wedged:
            with self._lock:
                leftover = list(self._live)
            self._fail(leftover, EngineClosed(
                f"close(timeout={timeout}) gave up: worker thread(s) "
                f"{wedged} wedged; {len(leftover)} pending request(s) "
                "failed instead of hanging"))
        return wedged

    def __enter__(self) -> "ServeEngine":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # ------------------------------------------------------------------ #
    # knob actuation (the adaptive controller's write surface, DESIGN §24)
    # ------------------------------------------------------------------ #

    def set_knobs(self, *, max_batch_delay: float | None = None,
                  max_pending: int | None = None,
                  max_coalesce_width: int | None = None,
                  max_factor_batch: int | None = None,
                  stack_sessions: bool | None = None,
                  max_stack: int | None = None,
                  max_lane_pending: int | None = None,
                  health: HealthPolicy | None = None,
                  staging_stride: int | None = None,
                  drain_rate: float | None = None,
                  qos_contention: float | None = None,
                  qos_tier_delay: dict | None = None,
                  lane: int | None = None) -> dict:
        """Thread-safe knob actuation: the write half of the adaptive
        control loop (`conflux_tpu.control.AdaptiveController`), also a
        public ops surface. Writes land under the admission lock; the
        hot paths read each knob once per decision point, so a move
        applies at the NEXT batch window / admission — never mid-batch,
        and never with a lock held across a dispatch. Validation mirrors
        the constructor (`max_factor_batch` rounds up to its power-of-two
        bucket); raising `max_pending` wakes blocked submitters.

        `health` swaps the active policy object (the first swap records
        the original as the strict restore point — see
        `_restore_guards`); `staging_stride` thins the exact staging
        guard to 1-in-stride batches (any guard trip resets it to 1
        instantly, engine-side). `drain_rate` installs the measured
        completions/s estimate that sizes `EngineSaturated.retry_after`
        (None leaves the current estimate in place). Returns the full
        knob dict after the move.

        `qos_contention` moves the fair-share ledger's contention
        fraction (the pending fraction of `max_pending` above which
        over-share tenants throttle, DESIGN §30); `qos_tier_delay`
        merges per-tier collect-delay overrides in seconds (keys from
        `conflux_tpu.qos.TIERS`; a None value clears that tier's
        override). Either knob lazily creates the engine's QoS state;
        neither appears in the knob dict of an engine that has none.

        `lane=` scopes the move to ONE lane: only `max_batch_delay` may
        ride it (the per-lane coalescing window the adaptive controller
        tunes independently per device, DESIGN §25) — the write lands as
        that lane's `delay_override`, leaving the engine-wide default
        and every other lane untouched."""
        if max_batch_delay is not None and max_batch_delay < 0:
            raise ValueError("max_batch_delay must be >= 0")
        if lane is not None:
            if not 0 <= int(lane) < len(self._lanes):
                raise ValueError(f"lane {lane} out of range "
                                 f"(engine has {len(self._lanes)})")
            if max_batch_delay is None or any(
                    v is not None for v in (max_pending,
                                            max_coalesce_width,
                                            max_factor_batch,
                                            stack_sessions, max_stack,
                                            max_lane_pending, health,
                                            staging_stride, drain_rate,
                                            qos_contention,
                                            qos_tier_delay)):
                raise ValueError("lane= scopes exactly one knob: "
                                 "max_batch_delay")
            with self._lock:
                self._lanes[int(lane)].delay_override = \
                    float(max_batch_delay)
                return self._knobs_locked()
        if (max_pending is not None and max_pending < 1) \
                or (max_coalesce_width is not None
                    and max_coalesce_width < 1) \
                or (max_factor_batch is not None and max_factor_batch < 1):
            raise ValueError("max_pending, max_coalesce_width and "
                             "max_factor_batch must be >= 1")
        if staging_stride is not None and staging_stride < 1:
            raise ValueError("staging_stride must be >= 1")
        if max_stack is not None and max_stack < 1:
            raise ValueError("max_stack must be >= 1")
        if max_lane_pending is not None and max_lane_pending < 1:
            raise ValueError("max_lane_pending must be >= 1")
        if qos_contention is not None \
                and not 0 < qos_contention <= 1:
            raise ValueError("qos_contention must be in (0, 1]")
        if qos_tier_delay is not None:
            for tier, v in qos_tier_delay.items():
                if tier not in qos_mod.TIERS:
                    raise ValueError(
                        f"qos_tier_delay key {tier!r} is not one of "
                        f"{qos_mod.TIERS}")
                if v is not None and v < 0:
                    raise ValueError("qos_tier_delay values must be "
                                     ">= 0 seconds (or None to clear)")
        with self._lock:
            if max_batch_delay is not None:
                self.max_batch_delay = float(max_batch_delay)
            if max_pending is not None:
                self.max_pending = int(max_pending)
                self._not_full.notify_all()  # blocked submitters re-check
            if max_coalesce_width is not None:
                self.max_coalesce_width = int(max_coalesce_width)
            if max_factor_batch is not None:
                self.max_factor_batch = rank_bucket(int(max_factor_batch))
            if stack_sessions is not None:
                # flipping stacking is always safe mid-flight: the
                # dispatcher reads the flag once per window, gangs keep
                # their resident state across an off/on cycle, and the
                # controller only flips ON after prewarming the stacked
                # bucket (`FactorPlan.bucket_ready(stack=...)`)
                self.stack_sessions = bool(stack_sessions)
            if max_stack is not None:
                self.max_stack = int(max_stack)
            if max_lane_pending is not None:
                self.max_lane_pending = int(max_lane_pending)
                self._not_full.notify_all()  # blocked submitters re-check
            if health is not None:
                if self._health_strict is None:
                    self._health_strict = self.health
                self.health = health
            if staging_stride is not None:
                self._staging_stride = int(staging_stride)
            if drain_rate is not None:
                self._drain_rate = float(drain_rate)
            if qos_contention is not None or qos_tier_delay is not None:
                st = self._qos
                if st is None:
                    st = self._qos = qos_mod.EngineQosState(
                        self._qos_latency_window)
                if qos_contention is not None:
                    st.ledger.contention = float(qos_contention)
                if qos_tier_delay is not None:
                    for tier, v in qos_tier_delay.items():
                        if v is None:
                            st.tier_delay.pop(tier, None)
                        else:
                            st.tier_delay[tier] = float(v)
            return self._knobs_locked()

    # requires-lock: _lock
    def _knobs_locked(self) -> dict:
        out = {"max_batch_delay": self.max_batch_delay,
                "max_pending": self.max_pending,
                "max_coalesce_width": self.max_coalesce_width,
                "max_factor_batch": self.max_factor_batch,
                "stack_sessions": self.stack_sessions,
                "max_stack": self.max_stack,
                "max_lane_pending": self.max_lane_pending,
                "staging_stride": self._staging_stride,
                "drain_rate": self._drain_rate,
                "health_relaxed": (self._health_strict is not None
                                   and self.health
                                   is not self._health_strict),
                "lanes": len(self._lanes),
                "lane_delays": {ln.index: ln.delay_override
                                for ln in self._lanes
                                if ln.delay_override is not None}}
        if self._qos is not None:
            # present only with live QoS state, so a qos=None engine's
            # knob dict is unchanged (knobs() equality in old tests)
            out["qos_contention"] = self._qos.ledger.contention
            out["qos_tier_delay"] = dict(self._qos.tier_delay)
        return out

    def knobs(self) -> dict:
        """The current knob values (a consistent snapshot)."""
        with self._lock:
            return self._knobs_locked()

    def _restore_guards(self) -> None:
        """Any guard trip restores full-strength guarding INSTANTLY,
        on the tripping thread: the controller only ever relaxes the
        sampling knobs on sustained-silence evidence, and the restore
        path cannot wait for its next tick (a poison burst would ride
        the relaxed window). Plain attribute stores — benign against
        concurrent readers, both old and new values are valid."""
        self._staging_stride = 1
        strict = self._health_strict
        if strict is not None and self.health is not strict:
            self.health = strict

    def _tick_staging(self) -> bool:
        """True when this batch should run the exact staging guard
        (1-in-stride sampling while the controller has the guard
        relaxed; stride 1 = every batch, the default)."""
        s = self._staging_stride
        if s <= 1:
            return True
        with self._lock:
            self._staging_tick += 1
            return self._staging_tick % s == 0

    def active_targets(self) -> tuple:
        """(sessions, plans) recently served by this engine, live refs
        only — the controller's prewarm targets when it grows a bucket
        set. Dead weakrefs are pruned as a side effect."""
        with self._lock:
            srefs = list(self._active_sessions.items())
            prefs = list(self._active_plans.items())
        sessions, plans, dead_s, dead_p = [], [], [], []
        for k, ref in srefs:
            obj = ref()
            (sessions.append(obj) if obj is not None
             else dead_s.append(k))
        for k, ref in prefs:
            obj = ref()
            (plans.append(obj) if obj is not None else dead_p.append(k))
        if dead_s or dead_p:
            with self._lock:
                for k in dead_s:
                    self._active_sessions.pop(k, None)
                for k in dead_p:
                    self._active_plans.pop(k, None)
        return sessions, plans

    # (not a futures-owner: readoption never touches request futures)
    def _gang_readopt(self, sessions) -> None:
        """Adopt revived sessions straight back into their lane gangs —
        the tier layer's grouped-revival hook (`tier.ResidentSet.
        revive_many`): by the time traffic touches a revived fleet its
        slots are already written, so a revival storm rejoins the
        stacked path without a first-window solo straggle. Advisory
        and best-effort — any failure leaves dispatch-time adoption to
        pick the sessions up; called WITHOUT any session lock held."""
        if not self.stack_sessions:
            return
        groups: dict = {}
        for s in sessions:
            if s.plan.batched or s.plan.mesh is not None:
                continue
            lane = self._lane_for(s)
            key = (id(s.plan), lane.index)
            if key not in groups:
                groups[key] = (lane, s.plan, [])
            groups[key][2].append(s)
        checked = self.health is not None and self.health.check_output
        for lane, plan, group in groups.values():
            try:
                lane._gang_for(plan).ensure(group, self.max_stack,
                                            checked)
            except Exception:  # noqa: BLE001 — adoption is advisory
                pass

    # ------------------------------------------------------------------ #
    # durable checkpoint / warm restart (DESIGN §23)
    # ------------------------------------------------------------------ #

    def checkpoint(self, path: str, sessions=None, names=None, *,
                   base=None, gen=None, full=True) -> dict:
        """Snapshot the served fleet to `path` at a drain barrier.

        Admission holds (both `on_full` policies block briefly) while
        the engine waits for `pending == 0`, so the snapshot observes
        no in-flight mutation — a consistent cut of every session's
        factors, base, Woodbury drift state, probe row and counters,
        across ALL tiers without moving anything (resident state d2hs,
        spilled records serialize in place; `conflux_tpu.tier.
        save_fleet`). `sessions` defaults to the attached residency's
        fleet. Restored sessions (`restore`) solve BITWISE identically
        to their pre-checkpoint selves. Returns {name: record dir}.

        `base`/`gen`/`full` pass through to `tier.save_fleet`'s
        incremental mode (DESIGN §35): against a previous generation
        dir, clean sessions (dirty clock unchanged) carry as
        references (``full=False``) or byte-identical local copies
        (``full=True`` compaction) instead of re-serializing."""
        if sessions is None and self.residency is None:
            raise ValueError(
                "checkpoint() needs sessions= when the engine has "
                "no residency-managed fleet")
        from conflux_tpu import tier

        # one checkpoint at a time: concurrent calls each queue behind
        # the mutex and take their own complete drain barrier, so
        # _draining never clears while another snapshot is serializing
        with self._ckpt_lock:
            with self._lock:
                self._draining = True
                while self._pending and not self._closed:
                    self._not_full.wait()
            try:
                if sessions is None:
                    # resolve the fleet AT the barrier, so sessions
                    # adopted while we queued behind an earlier
                    # checkpoint still make this snapshot
                    sessions = self.residency.sessions()
                return tier.save_fleet(path, sessions, names,
                                       base=base, gen=gen, full=full)
            finally:
                with self._lock:
                    self._draining = False
                    self._not_full.notify_all()

    def restore(self, path: str) -> list:
        """Rebuild a `checkpoint()` fleet: plans from their exact keys,
        sessions with their full state and counters. With a residency
        attached the sessions come back HOST-tier and fault in lazily
        as traffic touches them (the scalable warm restart — restore
        cost is file reads, capacity stays bounded); without one they
        restore eagerly device-resident. Returns the sessions in
        checkpoint order."""
        from conflux_tpu import tier

        return tier.load_fleet(path, residency=self.residency)

    # ------------------------------------------------------------------ #
    # prewarming
    # ------------------------------------------------------------------ #

    def prewarm(self, target, widths=(1,), stacks=(), factor_batches=(),
                update_ranks=(), precisions=(), wait: bool = True):
        """Compile the declared traffic's programs before it lands.

        `target` is a SolveSession (solve-lane warming) or a FactorPlan
        (factor-lane warming only — no session exists yet at cold
        start). `widths` are RHS widths (rounded up to power-of-two
        buckets — include the coalesced widths you expect;
        `max_coalesce_width` covers the worst case), `stacks` are
        cross-session stack sizes (single-system plans only), and
        `factor_batches` are coalesced cold-start batch sizes (rounded
        up likewise; `(1, 2, ..., max_factor_batch)` covers every bucket
        churn traffic can produce, INCLUDING the bucket-1 program that
        `plan.factor` itself rides). Warms the CHECKED programs instead
        when the engine's health policy checks outputs — whatever
        program steady-state traffic will actually ride observes zero
        compiles (asserted via `plan.trace_counts` in tests and
        bench_engine). `wait=False` compiles on a background thread (the
        engine-start pattern) and returns the Thread.

        `precisions` warms the §33 per-request tier program families
        next to the native ones: each named tier's solve programs (per
        width bucket, against a session target) and its coalesced
        factor programs (per factor bucket). `"auto"` warms the WHOLE
        ladder — an auto request may escalate to any rung, and every
        rung's checked program must be resident for the steady state to
        stay compile-free."""
        plan = target if isinstance(target, FactorPlan) else target.plan
        session = None if isinstance(target, FactorPlan) else target
        tiers: list = []
        auto = False
        for p in precisions:
            p2 = serve.check_precision_request(p)
            if p2 == "auto":
                auto = True
                tiers += [t for t in serve.PRECISION_TIERS
                          if t not in tiers]
            elif p2 is not None and p2 not in tiers:
                tiers.append(p2)

        def run():
            with profiler.region("engine.prewarm"):
                if session is not None:
                    with session._lock:  # a spilled target faults in
                        session._ensure_resident()
                    for wb in sorted({rank_bucket(w) for w in widths}):
                        self._prewarm_width(session, wb)
                        for t in tiers:
                            self._prewarm_tier_width(session, t, wb,
                                                     auto)
                        for s in stacks:
                            self._prewarm_stack(session, rank_bucket(s),
                                                wb, update_ranks)
                for fbk in sorted({rank_bucket(n) for n in factor_batches}):
                    self._prewarm_factor(plan, fbk)
                    for t in tiers:
                        self._prewarm_tier_factor(plan, t, fbk)

        if wait:
            run()
            return None
        t = threading.Thread(target=run, name="serve-engine-prewarm",
                             daemon=True)
        t.start()
        return t

    def _prewarm_width(self, session, wb: int) -> None:
        """Warm one RHS bucket on EVERY lane device. A jitted program
        traces once per shape but compiles one executable per device,
        so each lane must eat its own first-dispatch compile here —
        dedupe rides the plan's (kind, bucket, device) warm registry,
        so warming two sessions of one plan (or calling prewarm twice)
        repeats nothing."""
        plan = session.plan
        checked = self.health is not None and self.health.check_output
        kind = "solve_health" if checked else "solve"
        shape = ((plan.B, plan.N, wb) if plan.batched else (plan.M, wb))
        if plan.mesh is not None:
            # mesh lane: the sharded executable is keyed on the plan's
            # device SET, not one lane device (dispatch rides the first
            # live lane, see `_lane_for`) — one warm covers every lane,
            # and a per-lane `put_tree` would gather the sharded factors
            # onto a single device. devkey None = the mesh itself.
            if plan.device_warm(kind, wb, None):
                return
            b2 = jnp.zeros(shape, jnp.dtype(plan.key.dtype))
            (b2,) = _shard_batch((b2,), plan.mesh)
            with session._lock:
                session._ensure_resident()
                F, A, A0 = session._factors, session._A, session._A0
                probe = session._probe_row() if checked else None
            if checked:
                x, _ = plan._solve_health_fn(wb)(F, A0, probe, b2)
                x.block_until_ready()
            else:
                plan._solve_fn(wb)(F, A, b2).block_until_ready()
            plan.mark_device_warm(kind, wb, None)
            return
        for lane in self._lanes:
            dk = _devkey(lane.device)
            if plan.device_warm(kind, wb, dk):
                continue
            b2 = jnp.zeros(shape, jnp.dtype(plan.key.dtype))
            with session._lock:
                session._ensure_resident()
                F, A, A0 = session._factors, session._A, session._A0
                probe = session._probe_row() if checked else None
            if lane.device is not None:
                # temporary per-device copies: compile-time only, freed
                # with this loop iteration. The RHS stays UNCOMMITTED —
                # traffic dispatches host-staged RHS buffers the same
                # way, and the executable cache keys on the commitment
                # signature, so a committed prewarm RHS would warm a
                # program traffic never runs
                F = put_tree(F, lane.device)
                A = put_tree(A, lane.device)
                A0 = put_tree(A0, lane.device)
                probe = put_tree(probe, lane.device)
            if checked:
                x, _ = plan._solve_health_fn(wb)(F, A0, probe, b2)
                x.block_until_ready()
            else:
                plan._solve_fn(wb)(F, A, b2).block_until_ready()
            plan.mark_device_warm(kind, wb, dk)

    def _prewarm_stack(self, session, sb: int, wb: int,
                       update_ranks=()) -> None:
        """Warm the gang-stacked programs for one (stack, width)
        bucket on every lane device: the plain stacked solve (or the
        checked per-slot-verdict variant when this engine's policy
        checks outputs), plus — for each rank bucket in
        `update_ranks` — the stacked Woodbury programs a drifting gang
        will dispatch, fed zero drift state (the clean-slot shape, the
        exact signature a mixed clean/drifted gang uses). Also warms
        the gang's slot-write programs (`batched.write_slot_tree`), so
        adoption itself stays compile-free after prewarm."""
        plan = session.plan
        if plan.batched:
            raise ValueError(
                "stacks= prewarming applies to single-system plans only")
        checked = self.health is not None and self.health.check_output
        kind = "stacked_health" if checked else "stacked"
        for lane in self._lanes:
            dk = _devkey(lane.device)
            ranks = sorted({rank_bucket(k) for k in update_ranks
                            if not plan.device_warm(
                                "stacked_usolve",
                                (sb, rank_bucket(k), wb), dk)})
            if plan.device_warm(kind, (sb, wb), dk) and not ranks:
                continue
            with session._lock:
                session._ensure_resident()
                F0, A0, A0full = (session._factors, session._A,
                                  session._A0)
                probe = session._probe_row() if checked else None
            if lane.device is not None:
                F0 = put_tree(F0, lane.device)
                A0 = put_tree(A0, lane.device)
                A0full = put_tree(A0full, lane.device)
                probe = put_tree(probe, lane.device)
            F = stack_trees([F0] * sb)
            A = None if A0 is None else jnp.stack([A0] * sb)
            wA = None if probe is None else jnp.stack([probe] * sb)
            # the RHS stays uncommitted, matching traffic (see
            # _prewarm_width)
            b = jnp.zeros((sb, plan.N, wb), jnp.dtype(plan.key.dtype))
            if not plan.device_warm(kind, (sb, wb), dk):
                if checked:
                    x, _v = plan._stacked_solve_health_fn(sb, wb)(
                        F, A, wA, b)
                    x.block_until_ready()
                else:
                    plan._stacked_solve_fn(sb, wb)(
                        F, A, b).block_until_ready()
                # warm the gang's adopt/write-back row writes too (one
                # program per stacked leaf shape)
                from conflux_tpu.batched import write_slot_tree

                jax.block_until_ready(
                    write_slot_tree(stack_trees([F0] * sb), F0, 0))
                plan.mark_device_warm(kind, (sb, wb), dk)
            if not ranks:
                continue
            from conflux_tpu.update import zero_update_state

            sweeps = plan.key.refine + session.policy.refine
            A0s = jnp.stack([A0full] * sb) if sweeps else None
            for kb in ranks:
                z = zero_update_state(plan.N, kb, plan.key.dtype,
                                      plan.key.factor_dtype)
                Up = jnp.stack([z[0]] * sb)
                Vp = jnp.stack([z[1]] * sb)
                Y = jnp.stack([z[2]] * sb)
                Ci = jnp.stack([z[3]] * sb)
                if checked:
                    x, _v = plan._stacked_update_solve_health_fn(
                        sb, kb, wb, sweeps)(F, A0s, Up, Vp, Y, Ci,
                                            wA, b)
                    x.block_until_ready()
                else:
                    plan._stacked_update_solve_fn(
                        sb, kb, wb, sweeps)(
                        F, A0s, Up, Vp, Y, Ci, b).block_until_ready()
                plan.mark_device_warm("stacked_usolve", (sb, kb, wb),
                                      dk)

    def _prewarm_factor(self, plan, bb: int) -> None:
        checked = self.health is not None and self.health.check_output
        if plan.mesh is not None:
            # mesh lane: the (B, N, N) batch IS the dispatch (no slot
            # stacking, `_dispatch_factors` caps mesh chunks at 1), so
            # every requested bucket warms the same bucket-1 sharded
            # program — `_factor_fn` plain, `_mesh_factor_health_fn`
            # checked. One warm per mesh (devkey None), identity batch
            # filler as below.
            kind = "factor_health" if checked else "factor"
            if plan.device_warm(kind, 1, None):
                return
            buf = np.empty(plan.key.shape, np.dtype(plan.key.dtype))
            buf[:] = np.eye(*plan.key.shape[-2:], dtype=buf.dtype)
            (Ad,) = _shard_batch((jnp.asarray(buf),), plan.mesh)
            if checked:
                F, _wA, v = plan._mesh_factor_health_fn()(Ad)
                v.block_until_ready()
            else:
                F = plan._factor_fn(Ad)
            jax.block_until_ready(F)
            plan.mark_device_warm(kind, 1, None)
            return
        kind = "factor_health" if checked else "factor"
        # identity stacks: well-conditioned in every mode (LU, Cholesky,
        # trsm/blocked/inv substitution — an identity's diagonal-block
        # inverses are identities too) — the same filler the pad slots
        # use
        buf = np.empty((bb,) + plan.key.shape, np.dtype(plan.key.dtype))
        buf[:] = np.eye(*plan.key.shape[-2:], dtype=buf.dtype)
        for lane in self._lanes:
            dk = _devkey(lane.device)
            if plan.device_warm(kind, bb, dk):
                continue
            Ad = lane._to_device(buf)
            if checked:
                F, wA, v = plan._factor_health_fn(bb)(Ad)
                v.block_until_ready()
            else:
                F = plan._stacked_factor_fn(bb)(Ad)
                wA = None
            # warm the drain-side slice-out too: `_settle_factor` slices
            # each slot out of the stacked device arrays with eager
            # indexing, and each (slot, shape, device) slice is its own
            # tiny compiled program — cold ones would put first-batch
            # compile stalls on every NEW lane even with the factor
            # program warm
            slots = unstack_tree(F, bb)
            jax.block_until_ready(slots)
            jax.block_until_ready([Ad[i] for i in range(bb)])
            if wA is not None:
                jax.block_until_ready([wA[i] for i in range(bb)])
            plan.mark_device_warm(kind, bb, dk)

    def _prewarm_tier_width(self, session, tier: str, wb: int,
                            auto: bool = False) -> None:
        """Warm one served tier's solve program for one RHS bucket on
        every lane device (§33). 'auto' traffic always dispatches the
        CHECKED tier variant — the fused verdict is the ladder's
        escalation signal — so auto warming compiles `tier_health`
        even on an unguarded engine. Warming a cross-tier bucket also
        populates the session's derived `_tier_factors` cache (and the
        bucket-1 `tier_factor` program it rides)."""
        plan = session.plan
        if plan.mesh is not None:
            return  # tiers are validated away at submit for mesh plans
        checked = auto or (self.health is not None
                           and self.health.check_output)
        kind = "tier_health" if checked else "tier"
        shape = ((plan.B, plan.N, wb) if plan.batched
                 else (plan.M, wb))
        for lane in self._lanes:
            dk = _devkey(lane.device)
            if plan.device_warm(kind, (tier, wb), dk):
                continue
            b2 = jnp.zeros(shape, jnp.dtype(plan.key.dtype))
            with session._lock:
                session._ensure_resident()
                F = (session._factors
                     if tier == session._served_tier
                     else session._tier_factor(tier))
                A0 = session._A0
                probe = session._probe_row() if checked else None
            if lane.device is not None:
                F = put_tree(F, lane.device)
                A0 = put_tree(A0, lane.device)
                probe = put_tree(probe, lane.device)
            if checked:
                x, _ = plan._tier_solve_health_fn(tier, wb)(
                    F, A0, probe, b2)
                x.block_until_ready()
            else:
                plan._tier_solve_fn(tier, wb)(
                    F, A0, b2).block_until_ready()
            plan.mark_device_warm(kind, (tier, wb), dk)

    def _prewarm_tier_factor(self, plan, tier: str, bb: int) -> None:
        """Warm one served tier's coalesced factor bucket on every lane
        device — plus the drain-side slot slice-outs, mirroring the
        native `_prewarm_factor`. Tier factor batches dispatch
        UNCHECKED (§33: the opened session's first checked solve
        carries the ladder's verdict), so there is no health variant to
        warm here."""
        if plan.mesh is not None:
            return
        buf = np.empty((bb,) + plan.key.shape, np.dtype(plan.key.dtype))
        buf[:] = np.eye(*plan.key.shape[-2:], dtype=buf.dtype)
        for lane in self._lanes:
            dk = _devkey(lane.device)
            if plan.device_warm("tier_factor", (tier, bb), dk):
                continue
            Ad = lane._to_device(buf)
            F = plan._tier_stacked_factor_fn(tier, bb)(Ad)
            slots = unstack_tree(F, bb)
            jax.block_until_ready(slots)
            jax.block_until_ready([Ad[i] for i in range(bb)])
            plan.mark_device_warm("tier_factor", (tier, bb), dk)

    # ------------------------------------------------------------------ #
    # resolution ownership + failure bookkeeping
    # ------------------------------------------------------------------ #

    def _take(self, reqs) -> set:
        """Atomically claim resolution ownership: only requests still in
        `_live` are returned, and their pending slots are released. The
        claimer — and nobody else — resolves their futures."""
        with self._lock:
            owned = {r for r in reqs if r in self._live}
            self._live.difference_update(owned)
            self._pending -= len(owned)
            for r in owned:
                if r.lane_slot and r.lane is not None:
                    r.lane.pending -= 1
                    r.lane_slot = False
            self._not_full.notify_all()
        return owned

    def _fail(self, reqs, exc: Exception) -> None:
        owned = self._take(reqs)
        with self._lock:
            self._failed += len(owned)
            st = self._qos
            if st is not None:
                # per-class failure accounting + the ledger slot release
                # (the DRR refill) — classified requests only
                for r in owned:
                    if r.qos is not None:
                        st.record_fail(r.qos, r.cost)
        for r in owned:
            r.future.set_exception(exc)

    def _settle(self, spec, xh) -> None:
        """Resolve a drained batch: per-request scatter as numpy views
        of the one host copy (zero extra device dispatches)."""
        now = time.perf_counter()
        owned = self._take([r for r, _si, _lo in spec])
        with self._lock:
            for r in owned:
                self._latencies.append(now - r.t_submit)
            self._lat_seq += len(owned)
            self._completed += len(owned)
            st = self._qos
            if st is not None:
                # per-class latency rings + completion counts + the
                # ledger slot release (classified requests only; the
                # qos=None path pays one attribute read)
                for r in owned:
                    if r.qos is not None:
                        st.record_settle(r.qos, now - r.t_submit,
                                         r.cost)
        for r, si, lo in spec:
            if r not in owned:
                continue
            xs = (xh[..., lo:lo + r.width] if si is None
                  else xh[si, :, lo:lo + r.width])
            if r.squeeze:
                xs = xs[..., 0]
            r.future.set_result(xs)

    def _limit(self, session) -> float:
        return self._plan_limit(session.plan)

    def _plan_limit(self, plan) -> float:
        # 'auto' precision requests carry a verdict even on an
        # unguarded engine (the ladder's escalation signal, §33) — the
        # default HealthPolicy supplies the residual limit then
        policy = self.health if self.health is not None \
            else resilience.HealthPolicy()
        return policy.resolved_residual_limit(
            np.dtype(plan.key.dtype), plan.N)

    # ------------------------------------------------------------------ #
    # watchdog: a dead worker fails pending work instead of queueing
    # (multi-lane: a dead LANE fails only its own work, then revives)
    # ------------------------------------------------------------------ #

    def _is_worker_thread(self) -> bool:
        """True on any lane's dispatcher/drain thread — the tier
        manager's refactor-revival must not block on the factor lane
        from one (a worker waiting on its own queue would deadlock)."""
        t = threading.current_thread()
        for ln in self._lanes:
            if t is ln._dispatcher or t is ln._drainer:
                return True
        return False

    def _lane_died(self, lane, thread, exc: BaseException) -> None:
        """Post-mortem hook run ON a dying lane worker thread: record
        the cause and trip the watchdog path immediately (the polling
        watchdog is the backstop for silent deaths). A single-lane
        engine trips whole — exactly the pre-fleet behavior; a
        multi-lane engine trips ONLY the dead lane (its fault domain)
        and leaves the rest of the fleet serving."""
        lane._dead = (thread.name, exc)
        if len(self._lanes) == 1:
            self._dead = (thread.name, exc)
            self._watchdog_trip([thread.name], exc)
        else:
            self._lane_trip(lane, [thread.name], exc, dying=thread)

    # futures-owner
    def _lane_trip(self, lane, names, exc, dying=None) -> None:
        """Per-lane watchdog action (multi-lane engines): the blast
        radius of a dead lane worker is ITS lane. Fail the live
        requests routed to that lane (queued or in flight — resolution
        ownership makes failing an about-to-settle one harmless), then
        respawn the dead threads, bounded by `max_lane_revives`; past
        the budget the lane is marked dead and the admission front
        routes around it (all lanes dead = the global trip). Other
        lanes' work never notices."""
        if not lane._trip_lock.acquire(blocking=False):
            return  # a concurrent trip (dying thread + poll) owns it
        try:
            resilience.bump("watchdog_trips")
            with self._lock:
                revive = (lane.revives < self.max_lane_revives
                          and not self._closed)
                if not revive:
                    lane.dead = True
                leftover = [r for r in self._live
                            if getattr(r, "lane", None) is lane]
            self._fail(leftover, EngineClosed(
                f"lane {lane.index} worker thread(s) {names} died"
                + (f" ({exc!r})" if exc is not None else "")
                + f" — {len(leftover)} pending request(s) on this lane "
                "failed by the watchdog; other lanes unaffected"))
            if revive:
                lane.revive(exclude=dying)
                resilience.bump("lane_revives")
            if self._pool_pending():
                # the dead lane may have held the one in-flight wake:
                # re-arm the pool so queued cold starts aren't stranded
                self._wake_lane(force=True)
        finally:
            lane._trip_lock.release()

    # futures-owner
    def _watchdog_trip(self, names, exc) -> None:
        resilience.bump("watchdog_trips")
        with self._lock:
            self._closed = True
            self._not_full.notify_all()
            leftover = list(self._live)
        self._fail(leftover, EngineClosed(
            f"engine worker thread(s) {names} died"
            + (f" ({exc!r})" if exc is not None else "")
            + f" — {len(leftover)} pending request(s) failed by the "
            "watchdog instead of queueing forever"))
        # unwedge whichever workers survived
        for lane in self._lanes:
            lane._inq.put(_STOP)
            try:
                lane._outq.put_nowait(_STOP)
            # conflint: disable=CFX-FUTURE a full outq already wakes the drain; nothing owned here
            except Full:
                pass

    def _watchdog_loop(self) -> None:
        while True:
            time.sleep(self.watchdog_interval)
            # conflint: disable=CFX-LOCK benign racy poll; a stale read only delays one tick
            if self._closed:
                return
            if len(self._lanes) == 1:
                lane = self._lanes[0]
                dead = [t.name for t in (lane._dispatcher, lane._drainer)
                        if not t.is_alive()]
                if dead:
                    exc = (lane._dead[1] if lane._dead is not None
                           else None)
                    self._watchdog_trip(dead, exc)
                    return
                continue
            for lane in self._lanes:
                if lane.dead:
                    continue
                dead = [t.name for t in (lane._dispatcher, lane._drainer)
                        if not t.is_alive()]
                if dead:
                    exc = (lane._dead[1] if lane._dead is not None
                           else None)
                    self._lane_trip(lane, dead, exc)
            if all(ln.dead for ln in self._lanes):
                # nothing left to serve: the global trip fails whatever
                # is still pending and closes the engine
                self._watchdog_trip(["all lanes"], None)
                return

    # ------------------------------------------------------------------ #
    # observability (merged into profiler.serve_stats()['engine'])
    # ------------------------------------------------------------------ #

    def counters(self) -> dict:
        """The raw counter/gauge snapshot WITHOUT the percentile
        computation — the cheap read the windowed-telemetry path
        (`profiler.StatsWindow` → the controller tick) takes every
        interval. `stats()` sorts the full latency rings for its
        percentiles, which is fine for humans and benches but not for
        a 4-times-a-second control loop sharing one core with the
        dispatch path."""
        with self._lock:
            out = {
                "pending": self._pending,
                "queue_peak": self._queue_peak,
                "requests": self._requests,
                "completed": self._completed,
                "failed": self._failed,
                "shed": self._sheds,
                "batches": self._batches,
                "coalesced_requests": self._coalesced_requests,
                "factor_requests": self._factor_requests,
                "factor_batches": self._factor_batches,
                "factor_coalesced_requests": self._factor_coalesced,
                "factor_slots": self._factor_slots,
                "factor_pad_slots": self._factor_pad,
                "width_capped": self._width_capped,
                "gang_batches": self._gang_batches,
                "gang_coalesced_requests": self._gang_coalesced,
                "gang_opportunity": self._gang_opportunity,
                "stack_exclusions": dict(self._stack_exclusions),
                "gang": self._gang_locked(),
                "bucket_hits": dict(self._bucket_hits),
                "factor_bucket_hits": dict(self._factor_bucket_hits),
                "lanes": self._lane_rows_locked(),
            }
            if self._qos is not None:
                # present only once classified traffic (or a qos knob
                # write) created the state — a qos=None engine's
                # counter dict is unchanged
                out["qos"] = self._qos.counters(self.max_pending)
            return out

    # requires-lock: _lock
    def _gang_locked(self) -> dict:
        """Aggregate gang-residency gauges across every lane's gangs —
        SORT-FREE and lock-free on the gang side (racy reads of
        monotone counters by design; this rides the 10 Hz counters()
        path)."""
        gangs = members = slots = 0
        adopts = releases = refreshes = rebuilds = 0
        for ln in self._lanes:
            for g in ln._gangs.values():
                gangs += 1
                members += len(g._by_id)
                slots += g.cap
                adopts += g.adopts
                releases += g.releases
                refreshes += g.refreshes
                rebuilds += g.rebuilds
        return {"gangs": gangs, "sessions": members,
                "capacity_slots": slots, "adopts": adopts,
                "releases": releases, "refreshes": refreshes,
                "rebuilds": rebuilds}

    # requires-lock: _lock
    def _lane_rows_locked(self) -> list:
        """Per-lane telemetry rows — SORT-FREE (counters() ships these
        to the 10 Hz controller tick): per-device batches and coalesced
        means, cold-start batches, queue depth/high-water, busy-time
        occupancy, the resolved coalescing window, and the fault-domain
        state (revivals spent, dead flag)."""
        now = time.perf_counter()
        rows = []
        for ln in self._lanes:
            wall = max(1e-9, now - ln.t_start)
            busy = max(ln.busy_dispatch_s, ln.busy_drain_s)
            rows.append({
                "lane": ln.index,
                "device": (None if ln.device is None else str(ln.device)),
                "delay": ln.delay,
                "batches": ln.batches,
                "coalesced_requests": ln.coalesced,
                "coalesced_mean": (ln.coalesced / ln.batches
                                   if ln.batches else 0.0),
                "factor_batches": ln.factor_batches,
                "factor_coalesced_requests": ln.factor_coalesced,
                "gang_batches": ln.gang_batches,
                "gang_coalesced_requests": ln.gang_coalesced,
                "bucket_hits": dict(ln.bucket_hits),
                "pending": ln.pending,
                "sheds": ln.sheds,
                "queue_depth": ln._inq.qsize(),
                "queue_peak": ln.queue_hw,
                "occupancy": min(1.0, busy / wall),
                "revives": ln.revives,
                "dead": ln.dead,
            })
        return rows

    def stats(self) -> dict:
        """Engine counters: queue depth high-water mark, batches
        dispatched, mean coalesced batch size, shed count, and
        p50/p95/p99 request latency over the rolling window, plus the
        factor lane's cold-start counters — factor batches dispatched,
        mean coalesced factor-batch size, pad-waste ratio (identity pad
        slots / total bucket slots dispatched), and session-open
        latency percentiles. (Health outcomes — guard trips,
        escalations, evictions, quarantines — are global counters:
        `profiler.serve_stats()['health']`.)"""
        with self._lock:
            lats = sorted(self._latencies)
            flats = sorted(self._factor_latencies)
            batches = self._batches
            fbatches = self._factor_batches
            out = {
                "pending": self._pending,
                "queue_peak": self._queue_peak,
                "requests": self._requests,
                "completed": self._completed,
                "failed": self._failed,
                "shed": self._sheds,
                "batches": batches,
                "coalesced_requests": self._coalesced_requests,
                "coalesced_mean": (self._coalesced_requests / batches
                                   if batches else 0.0),
                "latency_p50_ms": 1e3 * _percentile(lats, 50),
                "latency_p95_ms": 1e3 * _percentile(lats, 95),
                "latency_p99_ms": 1e3 * _percentile(lats, 99),
                "factor_requests": self._factor_requests,
                "factor_batches": fbatches,
                "factor_coalesced_requests": self._factor_coalesced,
                "factor_coalesced_mean": (self._factor_coalesced / fbatches
                                          if fbatches else 0.0),
                "factor_slots": self._factor_slots,
                "factor_pad_slots": self._factor_pad,
                "factor_pad_waste": (self._factor_pad / self._factor_slots
                                     if self._factor_slots else 0.0),
                "factor_latency_p50_ms": 1e3 * _percentile(flats, 50),
                "factor_latency_p95_ms": 1e3 * _percentile(flats, 95),
                "factor_latency_p99_ms": 1e3 * _percentile(flats, 99),
                "width_capped": self._width_capped,
                "gang_batches": self._gang_batches,
                "gang_coalesced_requests": self._gang_coalesced,
                "gang_coalesced_mean": (self._gang_coalesced
                                        / self._gang_batches
                                        if self._gang_batches else 0.0),
                "gang_opportunity": self._gang_opportunity,
                "stack_exclusions": dict(self._stack_exclusions),
                "gang": self._gang_locked(),
                "bucket_hits": dict(self._bucket_hits),
                "factor_bucket_hits": dict(self._factor_bucket_hits),
                "lanes": self._lane_rows_locked(),
                "knobs": self._knobs_locked(),
            }
            psc = pfb = 0
            for ref in self._active_sessions.values():
                s = ref()
                if s is not None:
                    # conflint: disable=CFX-LOCK benign racy reads of monotonic ints (ops counter roll-up)
                    psc += s.precision_escalations
                    pfb += s.precision_fallbacks
            # ladder traffic roll-up over the engine's recently-served
            # sessions (§33): rung climbs + drifted-session tier
            # fallbacks. Global twins live in serve_stats()['health'].
            out["precision_escalations"] = psc
            out["precision_fallbacks"] = pfb
            if self._qos is not None:
                # per-class counters + latency percentiles + SLO
                # attainment (absent on a qos=None engine)
                out["qos"] = self._qos.stats(self.max_pending)
        if self.residency is not None:
            # outside the engine lock: the manager takes its own
            # (engine-lock -> manager-lock never nests)
            out["tier"] = self.residency.stats()
        if self._controller is not None:
            # likewise outside: the controller's stats take its own lock
            out["controller"] = self._controller.stats()
        return out

    def latency_samples(self) -> list:
        """The rolling latency window in seconds (profiler merges these
        across engines for fleet-wide percentiles)."""
        with self._lock:
            return list(self._latencies)

    def factor_latency_samples(self) -> list:
        """The factor lane's rolling session-open latency window in
        seconds (submit_factor admission -> session resolved)."""
        with self._lock:
            return list(self._factor_latencies)

    def latency_window(self, token: int | None = None) -> tuple:
        """(new_token, samples): the latencies recorded SINCE `token`
        (a sequence number returned by a previous call; None = the
        whole rolling window). The windowed read under the ring buffer:
        if more samples landed than the ring holds, the overflow is
        gone and the ring's full contents are returned. This is what
        `profiler.StatsWindow` (and through it the adaptive controller)
        percentiles over — tail latency of THIS window, not of the
        cumulative ring."""
        with self._lock:
            seq = self._lat_seq
            lats = list(self._latencies)
            if token is None:
                return seq, lats
            n = min(len(lats), max(0, seq - token))
            return seq, lats[len(lats) - n:] if n else []

    def factor_latency_window(self, token: int | None = None) -> tuple:
        """`latency_window` for the factor lane's session-open window."""
        with self._lock:
            seq = self._flat_seq
            lats = list(self._factor_latencies)
            if token is None:
                return seq, lats
            n = min(len(lats), max(0, seq - token))
            return seq, lats[len(lats) - n:] if n else []

    def qos_latency_samples(self) -> dict:
        """Per-class rolling latency windows in seconds, keyed
        'tenant/tier' ({} on a qos=None engine). The per-class twin of
        :meth:`latency_samples`."""
        with self._lock:
            st = self._qos
            if st is None:
                return {}
            return {k: list(d) for k, d in st.latencies.items()}

    def qos_latency_window(self, key: str,
                           token: int | None = None) -> tuple:
        """:meth:`latency_window` for ONE QoS class's ring (`key` is
        the 'tenant/tier' class key). A class the engine has not seen
        (including every key on a qos=None engine) reads as (0, []),
        so a per-class `profiler.StatsWindow` may open ahead of the
        class's first request."""
        with self._lock:
            st = self._qos
            if st is None or key not in st.latencies:
                return 0, []
            seq = st.lat_seq[key]
            lats = list(st.latencies[key])
            if token is None:
                return seq, lats
            n = min(len(lats), max(0, seq - token))
            return seq, lats[len(lats) - n:] if n else []
