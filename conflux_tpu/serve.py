"""Throughput serving: plan cache + device-resident solve sessions.

The one-shot entry points (`solvers.solve`, `lu_distributed_host`) pay a
host scatter, a jit trace, and a host gather per call, and repeated solves
against the same matrix re-run the whole O(N^3) pipeline. A serving
workload ("many users, many right-hand sides") wants the opposite cost
profile: compile once per *shape/config*, factor once per *matrix*, and
answer each request with only the O(N^2) substitution against factors that
never leave the device.

Two objects deliver that split:

- :class:`FactorPlan` — the compiled-program cache. ``FactorPlan.create``
  is keyed the way the internal ``_build*`` lru_caches already key
  (shape, dtype, tile size, knobs, mesh identity) but covers the WHOLE
  pipeline — factor program and solve program together — so a process
  serving one traffic shape compiles exactly two XLA programs, total.
  Plans also switch on the persistent compilation cache
  (`conflux_tpu.cache`), so even the first trace of a known config
  deserializes instead of compiling.

- :class:`SolveSession` — device-resident factors. ``plan.factor(A)``
  runs the factor program once and pins its outputs on device;
  ``session.solve(b)`` then runs only the substitution (+ the plan's
  refinement sweeps). N new RHS batches cost N substitutions — never a
  refactorization, never a host round-trip of the factors.

Batched plans (shape ``(B, N, N)``) vmap the blocked single-device
factor/solve over the batch and shard it across a `batch_mesh` as data
parallelism (see `conflux_tpu.batched`); 2D plans serve a single system
per call on one device. Every traced program bumps a plan-level trace
counter at trace time, so tests (and monitoring) can assert the
"zero recompiles after the first call" contract instead of trusting it.

    plan = FactorPlan.create((32, 256, 256), jnp.float32, v=128, mesh=mesh)
    session = plan.factor(A)          # O(N^3), once
    x1 = session.solve(b1)            # O(N^2) substitution only
    x2 = session.solve(b2)            # same compiled program, same factors
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Any

import jax
import jax.numpy as jnp
from jax import lax

from conflux_tpu.ops import blas
from conflux_tpu.batched import _batch_spec, _shard_batch
from conflux_tpu.parallel.mesh import lookup_mesh, mesh_cache_key


@dataclasses.dataclass(frozen=True)
class PlanKey:
    """Identity of a compiled serving pipeline — the cache key.

    Mirrors the keying of the internal ``_build*`` caches (geometry +
    mesh identity + trace-time knobs), lifted to the serving surface:
    two calls that agree on every field share one compiled factor program
    and one compiled solve program.
    """

    shape: tuple          # (B, N, N) batched or (N, N) single
    dtype: str            # storage dtype of A
    factor_dtype: str     # dtype the factorization runs in (HPL-MxP knob)
    v: int                # tile size
    refine: int           # classic-IR sweeps fused into the solve program
    spd: bool             # Cholesky instead of LU
    substitution: str     # 'trsm' | 'inv' (resolved from 'auto' at create)
    precision: Any        # trailing-GEMM precision
    backend: str          # gemm backend
    panel_algo: str       # LU panel election algo
    mesh_key: Any         # batch-mesh identity (None = default device)


_PLANS: dict[PlanKey, "FactorPlan"] = {}
_PLANS_LOCK = threading.Lock()


def clear_plans() -> None:
    """Drop every cached plan (tests; frees the jitted closures)."""
    with _PLANS_LOCK:
        _PLANS.clear()


class FactorPlan:
    """A reusable, cached scatter→factor→solve pipeline for one config.

    Construct through :meth:`create` (the cache); the constructor itself
    builds the jitted programs but does not trace them — tracing happens
    on first use and is counted in :attr:`trace_counts`.
    """

    def __init__(self, key: PlanKey):
        self.key = key
        shape = key.shape
        self.batched = len(shape) == 3
        self.B = shape[0] if self.batched else None
        self.N = shape[-1]
        if shape[-1] != shape[-2]:
            raise ValueError(f"plan needs square systems, got {shape}")
        if self.N % key.v:
            raise ValueError(
                f"N={self.N} not a multiple of v={key.v}; pre-pad with an "
                "identity extension (cf. solvers.solve)")
        self.mesh = (lookup_mesh(key.mesh_key)
                     if key.mesh_key is not None else None)
        if self.mesh is not None and not self.batched:
            raise ValueError(
                "a mesh only applies to batched (B, N, N) plans — a single "
                "system has no batch axis to shard")
        if self.batched and self.mesh is not None \
                and self.B % self.mesh.devices.size:
            raise ValueError(
                f"plan batch {self.B} must be a multiple of the mesh size "
                f"{self.mesh.devices.size} (pad the batch, or create the "
                "plan at the padded size and slice results)")
        # trace-time side effects let tests assert "second call compiles
        # nothing" without reaching into jax internals
        self.trace_counts = {"factor": 0, "solve": 0}
        self._factor_fn = self._build_factor()
        self._solve_cache: dict[tuple, Any] = {}

    # ------------------------------------------------------------------ #
    # cache
    # ------------------------------------------------------------------ #

    @classmethod
    def create(cls, shape, dtype, *, v: int = 256, factor_dtype=None,
               refine: int = 0, spd: bool = False, mesh=None,
               substitution: str = "auto", precision=None,
               backend: str | None = None,
               persistent_cache: bool = True) -> "FactorPlan":
        """Get-or-build the plan for a traffic shape.

        shape is (B, N, N) for a batched plan or (N, N) for a
        single-system plan; `dtype` is the request dtype. `factor_dtype`,
        `refine`, `spd` follow `solvers.solve`; `mesh` (a `batch_mesh`)
        shards batched plans across devices. `persistent_cache=True`
        also switches on the on-disk XLA cache so cold processes reuse
        warm compiles.

        `substitution` picks the per-request engine: 'trsm' runs the
        classic triangular substitutions; 'inv' additionally inverts the
        triangular factors AT FACTOR TIME (O(N^3), amortized into the
        session open) so every solve is two batched GEMVs — the
        MXU/BLAS3-friendly layout. XLA's *batched* small-rhs
        triangular_solve is serial per row (measured 70x slower than the
        GEMV form at B=32, N=256 on CPU), so 'auto' resolves to 'inv'
        for batched plans and 'trsm' for single-system ones. Explicit
        triangular inverses trade a bounded accuracy term (growth ~
        cond(L) cond(U) instead of cond(A)); the serve tests hold the
        result to the one-shot oracle's residual bars, and the plan's
        `refine` sweeps restore working accuracy when the traffic is
        harder.
        """
        if persistent_cache:
            from conflux_tpu import cache

            cache.enable_persistent_cache()
        dtype = jnp.dtype(dtype)
        fdtype = dtype if factor_dtype is None else jnp.dtype(factor_dtype)
        precision = (blas.matmul_precision() if precision is None
                     else precision)
        backend = blas.get_backend() if backend is None else backend
        if substitution == "auto":
            substitution = "inv" if len(shape) == 3 else "trsm"
        if substitution not in ("trsm", "inv"):
            raise ValueError(
                f"unknown substitution {substitution!r} (auto|trsm|inv)")
        key = PlanKey(
            shape=tuple(int(s) for s in shape), dtype=dtype.name,
            factor_dtype=fdtype.name, v=int(v), refine=int(refine),
            spd=bool(spd), substitution=substitution,
            precision=precision, backend=backend,
            panel_algo=blas.get_panel_algo(),
            mesh_key=None if mesh is None else mesh_cache_key(mesh))
        with _PLANS_LOCK:
            plan = _PLANS.get(key)
            if plan is None:
                plan = cls(key)
                _PLANS[key] = plan
        return plan

    # ------------------------------------------------------------------ #
    # program builders
    # ------------------------------------------------------------------ #

    def _one_factor(self, A):
        """Per-system factorization in the factor dtype. Returns the
        device-resident factor pytree the solve program consumes: packed
        factors for 'trsm' substitution, explicit triangular inverses
        (computed here, once, in the compute dtype) for 'inv'."""
        from conflux_tpu.cholesky.single import _cholesky_blocked
        from conflux_tpu.lu.single import _lu_factor_blocked

        self.trace_counts["factor"] += 1  # trace-time, not per call
        k = self.key
        Af = A.astype(jnp.dtype(k.factor_dtype))
        if k.spd:
            L = _cholesky_blocked(Af, k.v, k.precision, k.backend)
            if k.substitution != "inv":
                return (L,)
            cdtype = blas.compute_dtype(jnp.dtype(k.factor_dtype))
            eye = jnp.eye(self.N, dtype=cdtype)
            Li = lax.linalg.triangular_solve(
                L.astype(cdtype), eye, left_side=True, lower=True)
            return (Li,)
        LU, perm = _lu_factor_blocked(Af, k.v, k.precision, k.backend,
                                      k.panel_algo)
        if k.substitution != "inv":
            return (LU, perm)
        cdtype = blas.compute_dtype(jnp.dtype(k.factor_dtype))
        LUc = LU.astype(cdtype)
        eye = jnp.eye(self.N, dtype=cdtype)
        Li = lax.linalg.triangular_solve(
            LUc, eye, left_side=True, lower=True, unit_diagonal=True)
        Ui = lax.linalg.triangular_solve(
            LUc, eye, left_side=True, lower=False)
        return (Li, Ui, perm)

    def _one_solve(self, factors, A, b2):
        """Per-system substitution + the plan's IR sweeps. `A` is only
        consumed when refine > 0 (the residual matvec)."""
        from conflux_tpu.solvers import cholesky_solve, lu_solve

        self.trace_counts["solve"] += 1  # trace-time, not per call
        k = self.key
        if k.substitution == "inv":
            hi = lax.Precision.HIGHEST
            if k.spd:
                Li = factors[0]

                def corr(r):
                    y = jnp.matmul(Li, r.astype(Li.dtype), precision=hi)
                    return jnp.matmul(Li.conj().T, y, precision=hi)
            else:
                Li, Ui, perm = factors

                def corr(r):
                    y = jnp.matmul(Li, r.astype(Li.dtype)[perm],
                                   precision=hi)
                    return jnp.matmul(Ui, y, precision=hi)
        elif k.spd:
            corr = lambda r: cholesky_solve(factors[0], r)
        else:
            corr = lambda r: lu_solve(factors[0], factors[1], r)
        cdtype = blas.compute_dtype(jnp.dtype(k.dtype))
        x = corr(b2).astype(cdtype)
        for _ in range(k.refine):
            r = (b2.astype(cdtype)
                 - jnp.matmul(A.astype(cdtype), x,
                              precision=lax.Precision.HIGHEST))
            x = x + corr(r).astype(cdtype)
        return x

    def _build_factor(self):
        fn = self._one_factor
        if self.batched:
            fn = jax.vmap(fn)
        if self.mesh is None:
            return jax.jit(fn)
        # the factor pytree per mode — (L,) / (Li,) spd, (LU, perm) trsm,
        # (Li, Ui, perm) inv — every leaf batch-axis-first, batch-sharded
        k = self.key
        spec3, spec2 = _batch_spec(self.mesh, 3), _batch_spec(self.mesh, 2)
        if k.spd:
            out_shardings = (spec3,)
        elif k.substitution == "inv":
            out_shardings = (spec3, spec3, spec2)
        else:
            out_shardings = (spec3, spec2)
        return jax.jit(fn, out_shardings=out_shardings)

    def _solve_fn(self, nrhs: int):
        """The jitted substitution program for a given RHS width (cached
        per width; serving traffic with one width compiles once)."""
        fn = self._solve_cache.get(nrhs)
        if fn is None:
            one = self._one_solve
            f = jax.vmap(one) if self.batched else one
            if self.mesh is None:
                fn = jax.jit(f)
            else:
                fn = jax.jit(
                    f, out_shardings=_batch_spec(self.mesh, 3))
            self._solve_cache[nrhs] = fn
        return fn

    # ------------------------------------------------------------------ #
    # serving surface
    # ------------------------------------------------------------------ #

    def _check_A(self, A):
        want = self.key.shape
        if tuple(A.shape) != want:
            raise ValueError(f"A shape {A.shape} does not match the plan's "
                             f"{want}")
        if A.dtype != jnp.dtype(self.key.dtype):
            raise ValueError(f"A dtype {A.dtype} does not match the plan's "
                             f"{self.key.dtype}")

    def factor(self, A) -> "SolveSession":
        """Run the factor program on A and open a device-resident session.

        The returned session holds the factors (and, when the plan
        refines, A itself) on device; every `session.solve` afterwards is
        substitution-only.
        """
        A = jnp.asarray(A)
        self._check_A(A)
        if self.mesh is not None:
            (A,) = _shard_batch((A,), self.mesh)
        factors = self._factor_fn(A)
        keep_A = A if self.key.refine else None
        return SolveSession(self, factors, keep_A)

    def solve(self, A, b):
        """One-shot convenience: factor + solve in one call (a fresh
        session per call — serving code should hold the session)."""
        return self.factor(A).solve(b)


class SolveSession:
    """Device-resident factors + the compiled substitution program.

    Sessions are cheap handles: the heavy state lives on device. `solves`
    and `factorizations` count what this session actually ran — the
    serving invariant (`factorizations == 1` forever, `solves` growing)
    is asserted by tests/test_serve.py.
    """

    def __init__(self, plan: FactorPlan, factors, A):
        self.plan = plan
        self._factors = factors
        self._A = A
        self.factorizations = 1
        self.solves = 0

    @property
    def factors(self):
        """The device-resident factor pytree: (LU, perm) / (L,) for
        'trsm' plans, (Li, Ui, perm) / (Li,) triangular inverses for
        'inv' plans."""
        return self._factors

    def _rhs(self, b):
        plan = self.plan
        b = jnp.asarray(b)
        if plan.batched:
            if b.ndim == 2:
                want = (plan.B, plan.N)
                if b.shape != want:
                    raise ValueError(f"rhs {b.shape}, session needs {want}")
                return b[:, :, None], True
            want = (plan.B, plan.N)
            if b.ndim != 3 or b.shape[:2] != want:
                raise ValueError(
                    f"rhs {b.shape}, session needs {want} (+ rhs axis)")
            return b, False
        if b.ndim == 1:
            if b.shape[0] != plan.N:
                raise ValueError(f"rhs {b.shape}, session needs ({plan.N},)")
            return b[:, None], True
        if b.ndim != 2 or b.shape[0] != plan.N:
            raise ValueError(f"rhs {b.shape}, session needs ({plan.N}, k)")
        return b, False

    def solve(self, b):
        """Solve against the resident factors: O(N^2) substitution plus
        the plan's `refine` sweeps. b is (N,)/(N, k) for single plans,
        (B, N)/(B, N, k) for batched ones; x comes back in b's shape."""
        plan = self.plan
        b2, squeeze = self._rhs(b)
        if plan.mesh is not None:
            (b2,) = _shard_batch((b2,), plan.mesh)
        fn = plan._solve_fn(b2.shape[-1])
        x = fn(self._factors, self._A, b2)
        self.solves += 1
        if squeeze:
            return x[..., 0]
        return x
