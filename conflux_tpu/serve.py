"""Throughput serving: plan cache + device-resident solve sessions.

The one-shot entry points (`solvers.solve`, `lu_distributed_host`) pay a
host scatter, a jit trace, and a host gather per call, and repeated solves
against the same matrix re-run the whole O(N^3) pipeline. A serving
workload ("many users, many right-hand sides") wants the opposite cost
profile: compile once per *shape/config*, factor once per *matrix*, and
answer each request with only the O(N^2) substitution against factors that
never leave the device.

Two objects deliver that split:

- :class:`FactorPlan` — the compiled-program cache. ``FactorPlan.create``
  is keyed the way the internal ``_build*`` lru_caches already key
  (shape, dtype, tile size, knobs, mesh identity) but covers the WHOLE
  pipeline — factor program and solve program together — so a process
  serving one traffic shape compiles exactly two XLA programs, total.
  Plans also switch on the persistent compilation cache
  (`conflux_tpu.cache`), so even the first trace of a known config
  deserializes instead of compiling.

- :class:`SolveSession` — device-resident factors. ``plan.factor(A)``
  runs the factor program once and pins its outputs on device;
  ``session.solve(b)`` then runs only the substitution (+ the plan's
  refinement sweeps). N new RHS batches cost N substitutions — never a
  refactorization, never a host round-trip of the factors.

Batched plans (shape ``(B, N, N)``) vmap the blocked single-device
factor/solve over the batch and shard it across a `batch_mesh` as data
parallelism (see `conflux_tpu.batched`); 2D plans serve a single system
per call on one device. Every traced program bumps a plan-level trace
counter at trace time, so tests (and monitoring) can assert the
"zero recompiles after the first call" contract instead of trusting it.

Sessions also absorb *drift*: ``session.update(U, V)`` applies a rank-k
change A <- A + U V^H through a Sherman-Morrison-Woodbury correction
(`conflux_tpu.update`) instead of a refactorization — O(N^2 k) refresh,
O(N^2 + N k) per later solve, all device-resident, compiled once per
(rank bucket, RHS bucket) — and a :class:`~conflux_tpu.update.DriftPolicy`
triggers one true refactor through the plan's cached factor program when
accumulated rank or capacitance conditioning stops paying.

    plan = FactorPlan.create((32, 256, 256), jnp.float32, v=128, mesh=mesh)
    session = plan.factor(A)          # O(N^3), once
    x1 = session.solve(b1)            # O(N^2) substitution only
    x2 = session.solve(b2)            # same compiled program, same factors
    session.update(U, V)              # rank-k drift, NO refactor
    x3 = session.solve(b3)            # base factors + k x k correction
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Any

import jax
import jax.numpy as jnp
from jax import lax

from conflux_tpu.ops import blas
from conflux_tpu import profiler
from conflux_tpu.batched import (
    _batch_spec,
    _shard_batch,
    put_tree,
    unstack_tree,
)
from conflux_tpu.parallel.mesh import lookup_mesh, mesh_cache_key
from conflux_tpu.update import (
    DriftPolicy,
    capacitance,
    health_spot_check,
    health_spot_check_slots,
    probe_row,
    probe_vector,
    rank_bucket,
    updated_matvec,
    woodbury_apply,
)


@dataclasses.dataclass(frozen=True)
class PlanKey:
    """Identity of a compiled serving pipeline — the cache key.

    Mirrors the keying of the internal ``_build*`` caches (geometry +
    mesh identity + trace-time knobs), lifted to the serving surface:
    two calls that agree on every field share one compiled factor program
    and one compiled solve program.
    """

    shape: tuple          # (B, N, N) batched, (N, N) single, or (M, N)
                          # tall-skinny (kind='qr' least squares, M >= N)
    dtype: str            # storage dtype of A
    factor_dtype: str     # dtype the factorization runs in (HPL-MxP knob)
    v: int                # tile size
    refine: int           # classic-IR sweeps fused into the solve program
    kind: str             # factorization family: 'lu' | 'chol' | 'qr'
                          # (DESIGN §33 — replaces the old spd boolean;
                          # 'qr' serves min||Ax-b|| least-squares)
    substitution: str     # 'trsm' | 'inv' | 'blocked' ('auto' resolves
                          # at create — DESIGN §27)
    precision: Any        # trailing-GEMM precision
    backend: str          # gemm backend
    panel_algo: str       # LU panel election algo
    mesh_key: Any         # batch-mesh identity (None = default device)

    @property
    def spd(self) -> bool:
        """Back-compat read of the pre-§33 boolean: True iff the plan
        factors by Cholesky. Writers must use `kind` — the codec and
        the cache key speak `kind` only."""
        return self.kind == "chol"


PLAN_KINDS = ("lu", "chol", "qr")

# the per-request precision ladder (DESIGN §33): each served tier names
# a factor dtype + the IR sweeps its solve programs fuse. 'bf16_ir'
# factors in bfloat16 (half the resident factor bytes of f32) and ALWAYS
# refines at least once; 'f64' degrades to f32 storage when x64 is off
# (jax canonicalizes the dtype — same programs, documented in TUNING).
# Requests say precision='auto' to start on the cheapest rung and let
# the §20 Freivalds verdict drive escalation up this tuple.
PRECISION_TIERS = ("bf16_ir", "f32", "f64")


def check_precision_request(precision):
    """Validate a per-request ``precision=`` value (submit/solve
    surface): None (the plan's native path, bitwise pre-§33 behavior),
    a served tier name, or 'auto'. Returns the value; raises
    ValueError naming the offending value otherwise."""
    if precision is None or precision == "auto" \
            or precision in PRECISION_TIERS:
        return precision
    raise ValueError(
        f"unknown precision {precision!r} — expected None, 'auto', or "
        f"one of {PRECISION_TIERS}")


def next_precision_tier(tier: str):
    """The next rung up the ladder, or None at the top (escalation
    then falls through to the native `resilience.escalate` rungs)."""
    i = PRECISION_TIERS.index(tier)
    return PRECISION_TIERS[i + 1] if i + 1 < len(PRECISION_TIERS) \
        else None


_PLANS: dict[PlanKey, "FactorPlan"] = {}
_PLANS_LOCK = threading.Lock()


def _encode_precision(p):
    """JSON-encode a PlanKey's trailing-GEMM precision. Only the enum
    (tagged), None, and plain strings are representable — anything else
    (a tuple of precisions, a config object, a jnp dtype) would pass
    through json.dump into the fleet codec and poison every later
    restore, so it is refused HERE with the offending value named,
    while the checkpoint is still writable."""
    if isinstance(p, lax.Precision):
        return ["precision", p.name]
    if p is None or isinstance(p, str):
        return p
    raise ValueError(
        f"plan precision {p!r} (type {type(p).__name__}) is not "
        "codec-representable — use None, a string, or lax.Precision")


def _decode_precision(p):
    """Inverse of :func:`_encode_precision`. Malformed payloads (a
    mistagged list, a number, a dict — anything no encoder produced)
    raise ValueError with the offending value instead of flowing into
    a PlanKey that would never match its originating plan."""
    if isinstance(p, list):
        if len(p) == 2 and p[0] == "precision" \
                and isinstance(p[1], str) \
                and p[1] in lax.Precision.__members__:
            return lax.Precision[p[1]]
        raise ValueError(
            f"malformed precision payload {p!r} — expected "
            "['precision', <enum name>]")
    if p is None or isinstance(p, str):
        return p
    raise ValueError(
        f"malformed precision payload {p!r} (type {type(p).__name__}) "
        "— expected None, a string, or a tagged enum pair")


def plan_spec(plan: "FactorPlan") -> dict:
    """JSON-serializable identity of a plan — the persistence/wire
    codec shared by the checkpoint fleet.json (`tier.save_fleet`), the
    serve fabric's cross-process session open (`conflux_tpu.fabric`
    worker 'open' op, DESIGN §28) and anything else that must rebuild
    the EXACT plan in another process. Mesh-sharded plans carry their
    mesh identity (device ids + axis names + device-grid shape) in a
    ``"mesh"`` sub-dict; :func:`plan_from_spec` rebuilds the mesh on a
    process holding the SAME local devices (cross-host restore of
    sharded state stays unsupported — DESIGN §32)."""
    k = plan.key
    d = {"shape": list(k.shape), "dtype": k.dtype,
         "factor_dtype": k.factor_dtype, "v": k.v,
         "refine": k.refine, "kind": k.kind,
         "substitution": k.substitution,
         "precision": _encode_precision(k.precision),
         "backend": k.backend, "panel_algo": k.panel_algo}
    if k.mesh_key is not None:
        mesh = plan.mesh
        d["mesh"] = {
            "device_ids": [int(dev.id) for dev in mesh.devices.flat],
            "axis_names": [str(a) for a in mesh.axis_names],
            "device_shape": [int(s) for s in mesh.devices.shape]}
    return d


def mesh_from_spec(m: dict):
    """Rebuild a batch mesh from its :func:`plan_spec` wire identity —
    the mesh half of the checkpoint/fabric codec. The rebuilt mesh
    registers under the SAME `mesh_cache_key` as the original (the key
    is (device ids, axis names)), so a restored plan lands on the
    identical PlanKey and compiled-program family. A process that does
    not hold every named device id cannot host the sharded state —
    that is the genuine cross-host-migration residue, surfaced as
    :class:`~conflux_tpu.resilience.MeshPlanUnsupported`."""
    import numpy as np

    ids = [int(i) for i in m["device_ids"]]
    local = {dev.id: dev for dev in jax.devices()}
    missing = [i for i in ids if i not in local]
    if missing:
        from conflux_tpu.resilience import MeshPlanUnsupported

        raise MeshPlanUnsupported(
            f"mesh plan names device ids {missing} this process does "
            "not hold — sharded session state cannot migrate across "
            "hosts (restore on a host with the same device topology)",
            surface="plan_codec")
    devs = np.array([local[i] for i in ids], dtype=object)
    devs = devs.reshape(tuple(int(s) for s in m["device_shape"]))
    return jax.sharding.Mesh(devs, tuple(str(a) for a in m["axis_names"]))


def plan_from_spec(d: dict) -> "FactorPlan":
    """Reconstruct the EXACT PlanKey from a :func:`plan_spec` dict
    (trace-time knobs included, not re-derived from process globals)
    and get-or-build its plan — the restore/adopt path's half of the
    bitwise contract: same key, same compiled program family, same
    bits. Mesh plans rebuild their mesh from the spec's ``"mesh"``
    sub-dict (:func:`mesh_from_spec`) — same devices, same axis names,
    same out_shardings."""
    mesh_key = None
    m = d.get("mesh")
    if m is not None:
        mesh_key = mesh_cache_key(mesh_from_spec(m))
    # migration shim (§33): pre-kind checkpoints spelled the
    # factorization family as a bare 'spd' boolean — decode it so every
    # PR-16-era durable fleet.json stays restorable bitwise
    if "kind" in d:
        kind = str(d["kind"])
        if kind not in PLAN_KINDS:
            raise ValueError(
                f"plan spec names unknown kind {kind!r} — expected one "
                f"of {PLAN_KINDS}")
    else:
        kind = "chol" if bool(d["spd"]) else "lu"
    key = PlanKey(
        shape=tuple(int(s) for s in d["shape"]), dtype=d["dtype"],
        factor_dtype=d["factor_dtype"], v=int(d["v"]),
        refine=int(d["refine"]), kind=kind,
        substitution=d["substitution"],
        precision=_decode_precision(d["precision"]),
        backend=d["backend"], panel_algo=d["panel_algo"],
        mesh_key=mesh_key)
    return FactorPlan.from_key(key)


class _CompileOnce:
    """Serialize the FIRST call of a jitted program; later calls bypass.

    jax.jit wrappers are cheap to build but trace on first call, and two
    engine workers hitting a cold wrapper concurrently can both pay the
    trace (double-compiling the bucket and double-bumping the plan's
    trace counters). Memoizing the wrapper under the plan lock is not
    enough — the trace happens at call time — so the first execution
    holds a per-program lock; once it completes, the hot path is
    lock-free.
    """

    __slots__ = ("fn", "_lock", "_warm")

    def __init__(self, fn):
        self.fn = fn
        self._lock = threading.Lock()
        self._warm = False

    def __call__(self, *args):
        if self._warm:
            return self.fn(*args)
        with self._lock:
            out = self.fn(*args)
            self._warm = True
        return out

    @property
    def warm(self) -> bool:
        """True once the first call completed — the program is traced
        and cached, so later calls are dispatch-only. The adaptive
        controller's prewarm gate (`FactorPlan.bucket_ready`) reads
        this: a knob move may only route traffic onto warm buckets."""
        return self._warm


def clear_plans() -> None:
    """Drop every cached plan (tests; frees the jitted closures)."""
    with _PLANS_LOCK:
        _PLANS.clear()


class FactorPlan:
    """A reusable, cached scatter→factor→solve pipeline for one config.

    Construct through :meth:`create` (the cache); the constructor itself
    builds the jitted programs but does not trace them — tracing happens
    on first use and is counted in :attr:`trace_counts`.
    """

    def __init__(self, key: PlanKey):
        self.key = key
        shape = key.shape
        self.batched = len(shape) == 3
        self.B = shape[0] if self.batched else None
        self.N = shape[-1]
        # M is the RHS row count (== N for the square kinds; > N for a
        # tall-skinny 'qr' least-squares plan) — _rhs/_stage size by it
        self.M = shape[-2]
        if key.kind not in PLAN_KINDS:
            raise ValueError(
                f"unknown plan kind {key.kind!r} — expected one of "
                f"{PLAN_KINDS}")
        if key.kind == "qr":
            if self.batched:
                raise ValueError(
                    "kind='qr' serves single tall-skinny systems — a "
                    f"batched plan shape {shape} has no least-squares "
                    "semantics here (open one session per system; the "
                    "engine's factor lane coalesces them)")
            if self.M < self.N:
                raise ValueError(
                    f"kind='qr' needs M >= N (min||Ax-b|| over a "
                    f"tall-skinny A), got {shape}")
            if key.substitution != "trsm":
                raise ValueError(
                    "kind='qr' substitutes through R only "
                    "(substitution='trsm'); 'blocked'/'inv' are the "
                    "square kinds' engines")
        else:
            if shape[-1] != shape[-2]:
                raise ValueError(
                    f"plan needs square systems, got {shape}")
            if self.N % key.v:
                raise ValueError(
                    f"N={self.N} not a multiple of v={key.v}; pre-pad "
                    "with an identity extension (cf. solvers.solve)")
        self.mesh = (lookup_mesh(key.mesh_key)
                     if key.mesh_key is not None else None)
        if key.kind == "qr" and key.mesh_key is not None:
            raise ValueError(
                "kind='qr' plans are unsharded (a single tall system "
                "has no batch axis to mesh-shard)")
        if self.mesh is not None and not self.batched:
            raise ValueError(
                "a mesh only applies to batched (B, N, N) plans — a single "
                "system has no batch axis to shard")
        if self.batched and self.mesh is not None \
                and self.B % self.mesh.devices.size:
            raise ValueError(
                f"plan batch {self.B} must be a multiple of the mesh size "
                f"{self.mesh.devices.size} (pad the batch, or create the "
                "plan at the padded size and slice results)")
        # trace-time side effects let tests assert "second call compiles
        # nothing" without reaching into jax internals
        self.trace_counts = {"factor": 0, "solve": 0}
        # concurrent engine workers fill the memoized program caches
        # double-checked under this lock (one built wrapper per bucket)
        # and serialize each wrapper's first call through _CompileOnce
        # (one TRACE per bucket) — see tests/test_serve.py's thread hammer
        self._compile_lock = threading.Lock()
        self._factor_fn = _CompileOnce(self._build_factor())
        self._solve_cache: dict[Any, Any] = {}
        self._update_cache: dict[tuple, Any] = {}
        # the factor lane's stacked cold-start programs, keyed by batch
        # bucket (kept apart from _solve_cache, whose keys tests assert)
        self._factor_cache: dict[tuple, Any] = {}
        # the blocked-trsm engine's fused-probe checked programs
        # (DESIGN §27) — their OWN memo dict, again because
        # tests/test_serve.py asserts set(_solve_cache) == width
        # buckets exactly; release_buckets/bucket_ready cover it like
        # the others so the adaptive controller's grow/retire cycle
        # can neither strand nor re-compile the family
        self._trsm_cache: dict[tuple, Any] = {}
        # per-DEVICE warm registry (kept apart from the program caches,
        # whose key sets tests assert): one jitted program traces once
        # per shape but compiles one executable per device it runs on,
        # so a mesh-sharded serve fleet must warm each (kind, bucket)
        # once per LANE device. Engine prewarm records completions here
        # and dedupes identical (plan, bucket, device) work across
        # sessions/lanes; devkey None is the default device.
        self._warm_devices: set = set()  # guarded-by: _compile_lock

    def _memo(self, cache: dict, key, build):
        """Double-checked get-or-build of a compiled-program cache entry;
        the built wrapper is a :class:`_CompileOnce` so the bucket is
        traced exactly once even under concurrent first callers."""
        fn = cache.get(key)
        if fn is None:
            with self._compile_lock:
                fn = cache.get(key)
                if fn is None:
                    fn = _CompileOnce(build())
                    cache[key] = fn
        return fn

    # ------------------------------------------------------------------ #
    # cache
    # ------------------------------------------------------------------ #

    @classmethod
    def create(cls, shape, dtype, *, v: int = 256, factor_dtype=None,
               refine: int = 0, kind: str | None = None,
               spd: bool = False, mesh=None,
               substitution: str = "auto", precision=None,
               backend: str | None = None,
               persistent_cache: bool = True) -> "FactorPlan":
        """Get-or-build the plan for a traffic shape.

        shape is (B, N, N) for a batched plan, (N, N) for a
        single-system plan, or (M, N) with M > N for a tall-skinny
        least-squares plan (`kind='qr'`); `dtype` is the request dtype.
        `kind` picks the factorization family: 'lu' (default), 'chol'
        (SPD A), or 'qr' (min||Ax-b|| via the blocked CholeskyQR2
        recipe, `conflux_tpu.qr` — sessions answer the least-squares
        solution x of each rhs). `spd=True` is the pre-§33 spelling of
        kind='chol' and stays accepted. `factor_dtype`, `refine` follow
        `solvers.solve`; `mesh` (a `batch_mesh`) shards batched plans
        across devices. `persistent_cache=True` also switches on the
        on-disk XLA cache so cold processes reuse warm compiles.

        `substitution` picks the per-request engine: 'trsm' runs the
        classic triangular substitutions; 'blocked' runs them BLOCKED
        (diagonal-block inverses computed at factor time, O(N/bs)
        GEMM steps per solve — `conflux_tpu.ops.batched_trsm`, DESIGN
        §27); 'inv' inverts the FULL triangular factors at factor time
        so every solve is two batched GEMVs. XLA's *batched* small-rhs
        triangular_solve is serial per row (measured 70x slower than
        the GEMV form at B=32, N=256 on CPU), and every servable plan
        may be dispatched VMAPPED — batched plans over their own batch
        axis, single-system plans through the factor lane's stacked
        programs (§21) and the gang-resident stacks (§26) — so 'auto'
        resolves to 'blocked' everywhere: triangular accuracy (error
        growth ~ max cond of a bs-wide diagonal block) at GEMM speed.
        'trsm' and 'inv' stay explicit opt-ins; 'inv' trades the
        larger cond(L) cond(U) growth term for the two-GEMV solve
        shape. The serve tests hold every engine to the one-shot
        oracle's residual bars, and the plan's `refine` sweeps restore
        working accuracy when the traffic is harder.
        """
        if persistent_cache:
            from conflux_tpu import cache

            cache.enable_persistent_cache()
        dtype = jnp.dtype(dtype)
        fdtype = dtype if factor_dtype is None else jnp.dtype(factor_dtype)
        precision = (blas.matmul_precision() if precision is None
                     else precision)
        backend = blas.get_backend() if backend is None else backend
        if kind is None:
            kind = "chol" if spd else "lu"
        elif spd and kind != "chol":
            raise ValueError(
                f"kind={kind!r} contradicts spd=True (the legacy "
                "spelling of kind='chol') — pass one or the other")
        if kind == "qr" and substitution == "auto":
            # QR substitutes through R alone (one triangular solve on
            # the Q^H-projected rhs) — the blocked/inv engines are the
            # square kinds' machinery
            substitution = "trsm"
        if substitution == "auto":
            # branch on how the plan will be SERVED, not on its shape
            # alone: batched plans vmap their solve body over the batch
            # axis, and single-system plans are served vmapped too —
            # the factor lane's stacked programs and the gang-resident
            # stacks (§21/§26) — so every auto plan takes the blocked
            # engine (the vmapped-safe fast path). Callers wanting the
            # classic serial substitutions or the full-inverse GEMV
            # form opt in explicitly.
            served_vmapped = len(shape) == 3 or mesh is None
            substitution = "blocked" if served_vmapped else "trsm"
        if substitution not in ("trsm", "inv", "blocked"):
            raise ValueError(
                f"unknown substitution {substitution!r} "
                "(auto|trsm|inv|blocked)")
        key = PlanKey(
            shape=tuple(int(s) for s in shape), dtype=dtype.name,
            factor_dtype=fdtype.name, v=int(v), refine=int(refine),
            kind=kind, substitution=substitution,
            precision=precision, backend=backend,
            panel_algo=blas.get_panel_algo(),
            mesh_key=None if mesh is None else mesh_cache_key(mesh))
        with _PLANS_LOCK:
            plan = _PLANS.get(key)
            if plan is None:
                plan = cls(key)
                _PLANS[key] = plan
        return plan

    @classmethod
    def from_key(cls, key: PlanKey) -> "FactorPlan":
        """Get-or-build the plan for an EXACT :class:`PlanKey` — the
        checkpoint-restore path (`conflux_tpu.tier.load_fleet`), which
        must reconstruct the key verbatim (trace-time knobs included)
        rather than re-derive them from process globals: same key, same
        compiled program family, bitwise the same restored solves."""
        if not isinstance(key, PlanKey):
            raise TypeError(f"from_key takes a PlanKey, got "
                            f"{type(key).__name__}")
        with _PLANS_LOCK:
            plan = _PLANS.get(key)
            if plan is None:
                plan = cls(key)
                _PLANS[key] = plan
        return plan

    def spec(self) -> dict:
        """This plan's :func:`plan_spec` dict (JSON/wire codec)."""
        return plan_spec(self)

    @classmethod
    def from_spec(cls, d: dict) -> "FactorPlan":
        """Get-or-build the plan a :func:`plan_spec` dict names."""
        return plan_from_spec(d)

    # ------------------------------------------------------------------ #
    # bucket lifecycle (the adaptive controller's actuation surface)
    # ------------------------------------------------------------------ #

    def bucket_ready(self, *, width: int | None = None,
                     factor_batch: int | None = None,
                     stack=None,
                     checked: bool = False,
                     precision: str | None = None) -> bool:
        """True when the named bucket's program is built AND warm (first
        call completed — traced, cached, dispatch-only from here on).

        The prewarm-before-switch gate: `conflux_tpu.control.
        AdaptiveController` grows an engine's active bucket set by
        prewarming the target bucket on a background thread and only
        actuating the knob once this reports True, so a knob move can
        never put a compile stall on the serving path. `checked` asks
        about the health-guarded program variant (what an engine with
        ``check_output`` dispatches). `precision` asks about a served
        tier's program family instead of the native one: with `width`,
        the per-tier solve program (`("tier", tier, wb)` /
        `("tier_health", tier, wb)`); with `factor_batch`, the per-tier
        stacked factor program (`("tier_factor", tier, bb)`)."""
        # checked programs of a fused-probe (blocked) plan live in
        # their own memo dict — look there, or a controller knob move
        # would see a warm bucket as forever-cold (or vice versa)
        checked_cache = (self._trsm_cache if self._fused_probe
                         else self._solve_cache)
        if precision is not None:
            tier = check_precision_request(precision)
            if tier is None or tier == "auto":
                raise ValueError(
                    "bucket_ready(precision=) names a concrete tier "
                    f"from {PRECISION_TIERS}, not {precision!r}")
            if width is not None:
                key = (("tier_health", tier, int(width)) if checked
                       else ("tier", tier, int(width)))
                fn = self._solve_cache.get(key)
                if fn is None or not fn.warm:
                    return False
            if factor_batch is not None:
                fn = self._factor_cache.get(
                    ("tier_factor", tier, int(factor_batch)))
                if fn is None or not fn.warm:
                    return False
            if stack is not None:
                raise ValueError(
                    "gang-stacked buckets have no per-tier program "
                    "family (tier requests are a counted gang "
                    "exclusion, DESIGN §33)")
            return width is not None or factor_batch is not None
        if width is not None:
            key = ("health", int(width)) if checked else int(width)
            fn = (checked_cache if checked else self._solve_cache).get(key)
            if fn is None or not fn.warm:
                return False
        if factor_batch is not None:
            key = (("factor_health", int(factor_batch)) if checked
                   else ("factor", int(factor_batch)))
            fn = self._factor_cache.get(key)
            if fn is None or not fn.warm:
                return False
        if stack is not None:
            # stack = (sessions, width): the gang-stacked bucket the
            # adaptive controller prewarm-gates before flipping
            # `stack_sessions` on (DESIGN §26)
            sb, wb = int(stack[0]), int(stack[1])
            key = (("gstack_health", sb, wb) if checked
                   else ("stacked", sb, wb))
            fn = (checked_cache if checked else self._solve_cache).get(key)
            if fn is None or not fn.warm:
                return False
        return (width is not None or factor_batch is not None
                or stack is not None)

    def release_buckets(self, widths=(), factor_batches=()) -> int:
        """Drop retired bucket programs from the plan's caches — the
        reverse of prewarming, so a bucket set that grew under a traffic
        peak does not pin dead compiled programs (and their jitted
        closures) forever. `widths` drops each RHS bucket's plain,
        checked, refine, and stacked solve programs from `_solve_cache`;
        `factor_batches` drops the stacked cold-start programs (plain +
        checked) from `_factor_cache`. Non-bucket entries — the probe
        program, the Woodbury update programs — are never touched, and
        factor bucket 1 is refused outright: ``plan.factor`` itself
        rides it. Returns the number of cache entries dropped.

        A released bucket is not forbidden, just cold: traffic touching
        it again rebuilds and re-TRACES the program (`trace_counts`
        grow), which is exactly why the adaptive controller retires only
        buckets with a long zero-hit history and the zero-compile
        steady-state contract is stated over the ACTIVE bucket set. A
        dispatcher holding a wrapper it fetched before the release keeps
        using it safely — release only unlinks the cache entry."""
        dropped = 0
        with self._compile_lock:
            wbs = {int(w) for w in widths}
            fbs = set()
            for w in widths:
                wb = int(w)
                keys = [wb, ("health", wb), ("refine", wb)]
                keys += [k for k in self._solve_cache
                         if isinstance(k, tuple) and len(k) == 3
                         and k[0] in ("stacked", "gstack_health",
                                      "tier", "tier_health")
                         and k[2] == wb]
                for key in keys:
                    dropped += self._solve_cache.pop(key, None) is not None
                # the blocked engine's fused-probe checked programs
                # retire with their width bucket too — a retired bucket
                # must not pin the family's jitted closures, and a
                # regrow must re-trace (never find a stale wrapper)
                tkeys = [("health", wb)]
                tkeys += [k for k in self._trsm_cache
                          if len(k) == 3 and k[0] == "gstack_health"
                          and k[2] == wb]
                for key in tkeys:
                    dropped += self._trsm_cache.pop(key, None) is not None
            for bb in factor_batches:
                bb = int(bb)
                if bb == 1:
                    raise ValueError(
                        "factor bucket 1 is the plan.factor/refactor "
                        "path itself (FactorPlan._factor_once) — it is "
                        "not a retirable coalescing bucket")
                fbs.add(bb)
                keys = [("factor", bb), ("factor_health", bb)]
                keys += [k for k in self._factor_cache
                         if isinstance(k, tuple) and len(k) == 3
                         and k[0] == "tier_factor" and k[2] == bb]
                for key in keys:
                    dropped += (self._factor_cache.pop(key, None)
                                is not None)
            # a released bucket is COLD again on every device: drop its
            # per-device warm records too, or a later regrow would skip
            # the re-warm and put the first-dispatch compile back on
            # the serving path
            self._warm_devices = {
                k for k in self._warm_devices
                if not (
                    (k[0] in ("solve", "solve_health") and k[1] in wbs)
                    or (k[0] in ("stacked", "stacked_health")
                        and isinstance(k[1], tuple) and k[1][1] in wbs)
                    or (k[0] == "stacked_usolve"
                        and isinstance(k[1], tuple) and k[1][2] in wbs)
                    or (k[0] in ("tier", "tier_health")
                        and isinstance(k[1], tuple) and k[1][1] in wbs)
                    or (k[0] == "tier_factor"
                        and isinstance(k[1], tuple) and k[1][1] in fbs)
                    or (k[0] in ("factor", "factor_health")
                        and k[1] in fbs))}
        return dropped

    @staticmethod
    def _warm_key(kind: str, bucket, devkey) -> tuple:
        # composite buckets ((stack, width), (stack, rank, width),
        # (tier, width)) pass through as tuples; int() on them was a
        # latent crash, and tier names are strings — pass those through
        b = (tuple((x if isinstance(x, str) else int(x)) for x in bucket)
             if isinstance(bucket, tuple) else int(bucket))
        return (kind, b, devkey)

    def device_warm(self, kind: str, bucket, devkey) -> bool:
        """True when (kind, bucket) has completed a warm-up dispatch on
        the device identified by `devkey` (see `engine._devkey`; None =
        the default device). The per-lane prewarm dedupe read. `bucket`
        is an int for the width/factor families and a tuple for the
        stacked ones."""
        with self._compile_lock:
            return self._warm_key(kind, bucket, devkey) \
                in self._warm_devices

    def mark_device_warm(self, kind: str, bucket, devkey) -> None:
        """Record a completed (kind, bucket, device) warm-up. Called by
        the engine AFTER the warming dispatch finished, so a crashed
        prewarm never poisons the registry."""
        with self._compile_lock:
            self._warm_devices.add(self._warm_key(kind, bucket, devkey))

    # ------------------------------------------------------------------ #
    # program builders
    # ------------------------------------------------------------------ #

    def _one_factor(self, A, fdtype=None):
        """Per-system factorization in the factor dtype (`fdtype`
        overrides the key's — the per-request precision ladder's served
        tiers factor the SAME base at their own dtype, §33). Returns the
        device-resident factor pytree the solve program consumes: packed
        factors for 'trsm' substitution, packed factors + diagonal-block
        inverses for 'blocked' (the bs-wide blocks only — O(N bs^2)
        inversion work, `ops.batched_trsm.diag_block_inverses`), explicit
        FULL triangular inverses (computed here, once, in the compute
        dtype) for 'inv', and the thin (Q, R) pair for kind='qr'
        (blocked CholeskyQR2, `qr.single.qr_factor_blocked`)."""
        from conflux_tpu.cholesky.single import _cholesky_blocked
        from conflux_tpu.lu.single import _lu_factor_blocked
        from conflux_tpu.ops.batched_trsm import diag_block_inverses

        self.trace_counts["factor"] += 1  # trace-time, not per call
        k = self.key
        fd = jnp.dtype(k.factor_dtype if fdtype is None else fdtype)
        Af = A.astype(fd)
        cdtype = blas.compute_dtype(fd)
        if k.kind == "qr":
            from conflux_tpu.qr.single import qr_factor_blocked

            Q, R = qr_factor_blocked(Af, v=min(k.v, self.N))
            return (Q, R)
        if k.spd:
            L = _cholesky_blocked(Af, k.v, k.precision, k.backend)
            if k.substitution == "blocked":
                Dl = diag_block_inverses(L.astype(cdtype), lower=True)
                return (L, Dl)
            if k.substitution != "inv":
                return (L,)
            eye = jnp.eye(self.N, dtype=cdtype)
            Li = lax.linalg.triangular_solve(
                L.astype(cdtype), eye, left_side=True, lower=True)
            return (Li,)
        LU, perm = _lu_factor_blocked(Af, k.v, k.precision, k.backend,
                                      k.panel_algo)
        if k.substitution == "blocked":
            LUc = LU.astype(cdtype)
            Dl = diag_block_inverses(LUc, lower=True, unit_diagonal=True)
            Du = diag_block_inverses(LUc, lower=False)
            return (LU, Dl, Du, perm)
        if k.substitution != "inv":
            return (LU, perm)
        LUc = LU.astype(cdtype)
        eye = jnp.eye(self.N, dtype=cdtype)
        Li = lax.linalg.triangular_solve(
            LUc, eye, left_side=True, lower=True, unit_diagonal=True)
        Ui = lax.linalg.triangular_solve(
            LUc, eye, left_side=True, lower=False)
        return (Li, Ui, perm)

    def _base_corr(self, factors):
        """The per-system base substitution r -> A0^{-1} r through the
        resident factor pytree — shared by the solve program and the
        Woodbury update programs (which wrap it in the capacitance
        correction). Traceable; factors carry no batch axis here (vmap
        adds it outside)."""
        from conflux_tpu.solvers import cholesky_solve, lu_solve

        k = self.key
        if k.kind == "qr":
            # least-squares normal-equations-free substitution: project
            # the (M, k) residual/rhs onto range(A) through Q^H, then
            # one triangular solve through R — (M, k) -> (N, k). The IR
            # sweep in _one_solve reuses this corr verbatim (the corr of
            # the LS residual IS the LS correction).
            Q, R = factors
            hi = lax.Precision.HIGHEST

            def qr_corr(r):
                y = jnp.matmul(Q.conj().T, r.astype(Q.dtype),
                               precision=hi)
                return lax.linalg.triangular_solve(
                    R, y, left_side=True, lower=False)

            return qr_corr
        if k.substitution == "blocked":
            from conflux_tpu.ops.batched_trsm import blocked_solve

            # the blocked engine (DESIGN §27): forward + back
            # substitution through the factor-resident diagonal-block
            # inverses — every step a GEMM, so the vmapped stacked
            # programs never touch XLA's serial batched trsm
            if k.spd:
                L, Dl = factors

                def corr(r):
                    Lc = L.astype(Dl.dtype)
                    y = blocked_solve(Lc, Dl, r.astype(Dl.dtype),
                                      lower=True)
                    Du = jnp.swapaxes(Dl.conj(), -1, -2)
                    return blocked_solve(Lc.conj().T, Du, y,
                                         lower=False)
            else:
                LU, Dl, Du, perm = factors

                def corr(r):
                    LUc = LU.astype(Dl.dtype)
                    y = blocked_solve(LUc, Dl,
                                      r.astype(Dl.dtype)[perm],
                                      lower=True)
                    return blocked_solve(LUc, Du, y, lower=False)
            return corr
        if k.substitution == "inv":
            hi = lax.Precision.HIGHEST
            if k.spd:
                Li = factors[0]

                def corr(r):
                    y = jnp.matmul(Li, r.astype(Li.dtype), precision=hi)
                    return jnp.matmul(Li.conj().T, y, precision=hi)
            else:
                Li, Ui, perm = factors

                def corr(r):
                    y = jnp.matmul(Li, r.astype(Li.dtype)[perm],
                                   precision=hi)
                    return jnp.matmul(Ui, y, precision=hi)
            return corr
        if k.spd:
            return lambda r: cholesky_solve(factors[0], r)
        return lambda r: lu_solve(factors[0], factors[1], r)

    def _one_solve(self, factors, A, b2, sweeps=None):
        """Per-system substitution + the plan's IR sweeps (`sweeps`
        overrides the key's — the served tiers fuse their own count,
        §33). `A` is only consumed when the sweep count > 0 (the
        residual matvec — for kind='qr' the (M, k) residual's corr IS
        the least-squares correction, so the same loop refines
        min||Ax-b||)."""
        self.trace_counts["solve"] += 1  # trace-time, not per call
        k = self.key
        corr = self._base_corr(factors)
        cdtype = blas.compute_dtype(jnp.dtype(k.dtype))
        x = corr(b2).astype(cdtype)
        for _ in range(k.refine if sweeps is None else sweeps):
            r = (b2.astype(cdtype)
                 - jnp.matmul(A.astype(cdtype), x,
                              precision=lax.Precision.HIGHEST))
            x = x + corr(r).astype(cdtype)
        return x

    def _build_factor(self):
        fn = self._one_factor
        if self.batched:
            fn = jax.vmap(fn)
        if self.mesh is None:
            return jax.jit(fn)
        # the factor pytree per mode — (L,) / (Li,) spd, (LU, perm) trsm,
        # (Li, Ui, perm) inv — every leaf batch-axis-first, batch-sharded
        k = self.key
        spec3, spec2 = _batch_spec(self.mesh, 3), _batch_spec(self.mesh, 2)
        spec4 = _batch_spec(self.mesh, 4)  # (B, nb, bs, bs) dinv stacks
        if k.spd:
            out_shardings = ((spec3, spec4)
                             if k.substitution == "blocked" else (spec3,))
        elif k.substitution == "blocked":
            out_shardings = (spec3, spec4, spec4, spec2)
        elif k.substitution == "inv":
            out_shardings = (spec3, spec3, spec2)
        else:
            out_shardings = (spec3, spec2)
        return jax.jit(fn, out_shardings=out_shardings)

    def _solve_fn(self, nrhs: int):
        """The jitted substitution program for a given RHS-width BUCKET.

        `SolveSession.solve` rounds the request width up to the next
        power of two (pad + slice — columns are independent through every
        substitution/GEMM/IR step, so padded answers are bitwise those of
        the unpadded width), so a traffic mix of widths compiles O(log)
        programs. The bucket contract is asserted here and in
        tests/test_serve.py."""
        if nrhs & (nrhs - 1) or nrhs < 1:
            raise AssertionError(
                f"_solve_fn takes power-of-two RHS buckets, got {nrhs} — "
                "route request widths through SolveSession.solve")

        def build():
            one = self._one_solve
            f = jax.vmap(one) if self.batched else one
            if self.mesh is None:
                return jax.jit(f)
            return jax.jit(f, out_shardings=_batch_spec(self.mesh, 3))

        return self._memo(self._solve_cache, nrhs, build)

    def _check_stack_bucket(self, what: str, ns: int, nrhs: int) -> None:
        if self.batched:
            raise AssertionError(
                "stacked dispatch is for single-system plans — batched "
                "plans already amortize over their own batch axis")
        if ns & (ns - 1) or ns < 1 or nrhs & (nrhs - 1) or nrhs < 1:
            raise AssertionError(
                f"{what} takes power-of-two buckets, got "
                f"({ns}, {nrhs}) — route requests through ServeEngine")

    def _stacked_solve_fn(self, ns: int, nrhs: int):
        """The engine's cross-session program: `ns` sessions of this
        (single-system) plan stack their factor pytrees on a new leading
        axis and ride ONE vmapped substitution dispatch (`ServeEngine`
        with ``stack_sessions=True`` — the gang-resident stacks of
        `conflux_tpu.gang` index their device-resident state straight
        into this program). Bucketed like everything else — power-of-two
        session count and RHS width; the engine pads by repeating a
        session slot / zero columns and slices back. The stacked result
        is allclose to, but not bitwise, the per-session dispatch (XLA
        batches the GEMMs differently under vmap); it IS bitwise
        invariant to the stack bucket size and the pad-slot contents
        (slots never interact), which is the gang's within-a-bucket
        contract for plain sessions."""
        self._check_stack_bucket("_stacked_solve_fn", ns, nrhs)
        return self._memo(self._solve_cache, ("stacked", ns, nrhs),
                          lambda: jax.jit(jax.vmap(self._one_solve)))

    def _stacked_solve_health_fn(self, ns: int, nrhs: int):
        """Checked stacked program — what closes the gang's `checked`
        exclusion hole: (F, A0, wA, b) -> (x, (2, ns) verdict) with the
        §20 Freivalds verdict fused PER SLOT
        (`update.health_spot_check_slots`), so health-guarded sessions
        ride the same one-dispatch stacked path as plain ones and a
        sick slot is attributed without re-dispatching its gang-mates
        (the factor lane's per-slot-flags machinery,
        `resilience.evaluate_slots`). A0 is None for refine-free plans
        (the body never consumes it); wA is the stacked probe rows the
        gang keeps resident."""
        self._check_stack_bucket("_stacked_solve_health_fn", ns, nrhs)
        if self._fused_probe:
            from conflux_tpu.update import health_verdict_from_stats_slots

            def build_fused():
                w = self.probe_w
                body = jax.vmap(self._blocked_probe_body)

                def f(factors, A0, wA, b2):
                    self._bump("health")  # trace-time, not per call
                    x, xsum, wAx = body(factors, wA, b2)
                    return x, health_verdict_from_stats_slots(
                        w, xsum, wAx, b2)

                return jax.jit(f)

            return self._memo(self._trsm_cache,
                              ("gstack_health", ns, nrhs), build_fused)

        def build():
            w = self.probe_w
            body = jax.vmap(self._one_solve)

            def f(factors, A0, wA, b2):
                self._bump("health")  # trace-time, not per call
                x = body(factors, A0, b2)
                return x, health_spot_check_slots(w, wA, x, b2)

            return jax.jit(f)

        return self._memo(self._solve_cache, ("gstack_health", ns, nrhs),
                          build)

    def _stacked_update_solve_fn(self, ns: int, kb: int, nrhs: int,
                                 sweeps: int):
        """Stacked rank-bucketed Woodbury program — what closes the
        gang's `upd_pending` exclusion hole: every slot rides the base
        substitution plus a kb-bucketed capacitance correction
        (`update.woodbury_apply` via `_one_update_solve`), with clean
        slots carrying zero U/V (exactly-zero correction) and drifted
        slots their `pad_update_state`-padded state. A0 is None when
        sweeps == 0. Signature: (F, A0, Up, Vp, Y, Cinv, b) -> x."""
        self._check_stack_bucket("_stacked_update_solve_fn", ns, nrhs)

        def build():
            import functools

            one = functools.partial(self._one_update_solve, sweeps)
            return jax.jit(jax.vmap(one))

        return self._memo(self._update_cache,
                          ("gusolve", ns, kb, nrhs, sweeps), build)

    def _stacked_update_solve_health_fn(self, ns: int, kb: int, nrhs: int,
                                        sweeps: int):
        """Checked stacked Woodbury program: drifted AND health-guarded
        sessions in one dispatch. The per-slot projected residual
        routes through each slot's DRIFTED matrix
        (w^T A1 = wA + (w^T Up) Vp^H; zero-padded columns inert), so
        SMW garbage trips its own slot's verdict only."""
        self._check_stack_bucket("_stacked_update_solve_health_fn",
                                 ns, nrhs)

        def build():
            import functools

            one = functools.partial(self._one_update_solve, sweeps)
            w = self.probe_w
            body = jax.vmap(one)

            def f(factors, A0, Up, Vp, Y, Cinv, wA, b2):
                self._bump("health")  # trace-time, not per call
                x = body(factors, A0, Up, Vp, Y, Cinv, b2)
                return x, health_spot_check_slots(w, wA, x, b2, Up, Vp)

            return jax.jit(f)

        return self._memo(self._update_cache,
                          ("guhealth", ns, kb, nrhs, sweeps), build)

    # ------------------------------------------------------------------ #
    # stacked (cold-start) factor programs — the engine's factor lane
    # ------------------------------------------------------------------ #

    @property
    def _pallas_factor(self) -> bool:
        """True when this plan's stacked factor programs run the factor
        itself through the batch-blocked Pallas kernels
        (`ops.pallas_factor`, DESIGN §29) instead of vmapping
        `_one_factor`: opt-in via `backend='pallas'`, non-mesh plans
        only (the kernel grid owns the batch axis), and f32/f64 with
        `dtype == factor_dtype` (the kernel's verified dtypes; equality
        keeps the in-kernel probe row `wA = w^T A` on the same operand
        `probe_row` would read). Everything about the bucket lifecycle
        and the bitwise bucket/pad-invariance contract is unchanged —
        only the traced factor body differs."""
        k = self.key
        return (k.backend == "pallas" and self.mesh is None
                and k.kind != "qr"  # no batch-grid QR kernel (§29)
                and jnp.dtype(k.dtype) == jnp.dtype(k.factor_dtype)
                and jnp.dtype(k.factor_dtype) in (jnp.float32,
                                                  jnp.float64))

    def _stacked_factor_body(self, Ast, probe: bool = False):
        """The stacked factor computation of XLA-backend plans, shared
        by :meth:`_stacked_factor_fn` and :meth:`_factor_health_fn`:
        (bb,) + key.shape -> stacked factor pytree (plus the (bb, N)
        probe rows wA when `probe`), by vmapping `_one_factor` verbatim
        — bit continuity with every pre-§29 program. `_pallas_factor`
        plans use the core/epilogue pair below instead. Traceable;
        callers jit."""
        w = self.probe_w if probe else None
        one = self._one_factor
        f = jax.vmap(jax.vmap(one)) if self.batched else jax.vmap(one)
        F = f(Ast)
        if not probe:
            return F
        if self.key.kind == "qr":
            # the least-squares probe pair (u, uA) per slot — vmap of a
            # tuple-returning body yields a tuple of stacks (§33)
            from conflux_tpu.update import probe_lstsq

            probe_one = lambda A0: probe_lstsq(w, A0)  # noqa: E731
        else:
            probe_one = lambda A0: probe_row(w, A0)  # noqa: E731
        inner_probe = (jax.vmap(jax.vmap(probe_one))
                       if self.batched else jax.vmap(probe_one))
        return F, inner_probe(Ast)

    def _pallas_factor_core(self, Ast, probe: bool = False):
        """EAGER half of a `_pallas_factor` plan's stacked factor:
        flatten the stack (batched plans fold (bb, B) into one kernel
        batch — pure metadata, `dtype == factor_dtype` is part of the
        eligibility gate so no cast happens here) and dispatch the
        batch-grid kernel (`blas.batched_lu_factor` /
        `batched_cholesky_factor`) as its OWN compiled program. Returns
        (LU, perm[, wA]) / (L[, wA]) stacks. Off-TPU the kernel runs in
        interpret mode — a large inlined XLA graph whose per-slot bits
        are invariant to the kernel batch only when the program boundary
        sits exactly at the kernel wrapper: under a caller's outer jit
        the graph fuses with its consumers differently per bucket size
        and the factor lane's bitwise bucket-invariance contract breaks
        (measured: low-bit LU drift between bucket 1 and 4). So this
        half must NEVER run under a trace — the bucket programs are
        Python closures chaining this dispatch with the jitted
        :meth:`_pallas_factor_epilogue`."""
        k = self.key
        shp = Ast.shape
        A2 = (Ast.reshape((shp[0] * shp[1],) + shp[2:])
              if self.batched else Ast)
        w = self.probe_w if probe else None
        if k.spd:
            out = blas.batched_cholesky_factor(A2, probe_w=w,
                                               backend="pallas")
            return out if probe else (out,)
        return blas.batched_lu_factor(A2, probe_w=w, backend="pallas")

    def _pallas_factor_epilogue(self, core, probe: bool = False):
        """Traceable second half of a `_pallas_factor` plan's stacked
        factor: the substitution epilogue on the kernel's stacked output
        — per-slot diagonal-block inverses for 'blocked' (the §27
        factor-time pass), full triangular inverses for 'inv' — plus
        the (bb, B) unflatten for batched plans. Every epilogue op is
        per-slot exact (vmapped triangular-solve custom calls, triangle
        masking, reshapes), so the kernel's per-slot bitwise invariance
        survives to the session pytrees. Callers jit (one program per
        bucket; `trace_counts['factor']` counts its traces)."""
        from conflux_tpu.ops.batched_trsm import diag_block_inverses

        self.trace_counts["factor"] += 1  # trace-time, not per call
        k = self.key
        cdtype = blas.compute_dtype(jnp.dtype(k.factor_dtype))
        if k.spd:
            L = core[0]
            wA = core[1] if probe else None
            if k.substitution == "blocked":
                dbi = jax.vmap(lambda t: diag_block_inverses(t, lower=True))
                F = (L, dbi(L.astype(cdtype)))
            elif k.substitution == "inv":
                Lc = L.astype(cdtype)
                eye = jnp.broadcast_to(jnp.eye(self.N, dtype=cdtype),
                                       Lc.shape)
                F = (lax.linalg.triangular_solve(
                    Lc, eye, left_side=True, lower=True),)
            else:
                F = (L,)
        else:
            LU, perm = core[0], core[1]
            wA = core[2] if probe else None
            if k.substitution == "blocked":
                LUc = LU.astype(cdtype)
                dbi_l = jax.vmap(lambda t: diag_block_inverses(
                    t, lower=True, unit_diagonal=True))
                dbi_u = jax.vmap(lambda t: diag_block_inverses(
                    t, lower=False))
                F = (LU, dbi_l(LUc), dbi_u(LUc), perm)
            elif k.substitution == "inv":
                LUc = LU.astype(cdtype)
                eye = jnp.broadcast_to(jnp.eye(self.N, dtype=cdtype),
                                       LUc.shape)
                Li = lax.linalg.triangular_solve(
                    LUc, eye, left_side=True, lower=True,
                    unit_diagonal=True)
                Ui = lax.linalg.triangular_solve(
                    LUc, eye, left_side=True, lower=False)
                F = (Li, Ui, perm)
            else:
                F = (LU, perm)

        def unflat(x):
            if not self.batched:
                return x
            B = self.key.shape[0]
            return x.reshape((x.shape[0] // B, B) + x.shape[1:])

        F = tuple(unflat(x) for x in F)
        if not probe:
            return F
        return F, unflat(wA)

    def _stacked_factor_fn(self, bb: int):
        """The factor lane's coalesced cold-start program: `bb` systems
        of this plan stack on a new leading axis — (bb,) + key.shape —
        and factor in ONE dispatch (vmapped `_one_factor`, or the
        batch-grid Pallas kernel for `_pallas_factor` plans — see
        :meth:`_stacked_factor_body`), at power-of-two batch
        buckets so a traffic mix of coalesced sizes compiles O(log)
        programs (pad slots carry identity matrices, well-conditioned by
        construction). Per-slot factors are BITWISE invariant to the
        bucket size and to the pad contents (slots never interact —
        asserted in tests/test_factor_lane.py), which is why
        :meth:`factor` itself rides this program at bucket 1: a session
        opened by `plan.factor` and one opened by a coalesced engine
        dispatch are the same bits. (The UNvmapped factor body differs
        from its vmapped form at rounding level, so routing both paths
        through one program family is what makes the contract hold; the
        Pallas kernel keeps it by flooring its grid batch at 2 slots —
        `ops.pallas_factor._pad_batch_floor`.)"""
        if self.mesh is not None:
            raise AssertionError(
                "the stacked factor program is unsharded — mesh plans "
                "factor through the batch-sharded _factor_fn")
        if bb & (bb - 1) or bb < 1:
            raise AssertionError(
                f"_stacked_factor_fn takes power-of-two batch buckets, "
                f"got {bb} — route requests through ServeEngine")

        def build():
            if not self._pallas_factor:
                return jax.jit(self._stacked_factor_body)
            epi = jax.jit(self._pallas_factor_epilogue)

            def run(Ast):
                return epi(self._pallas_factor_core(Ast))

            return run

        return self._memo(self._factor_cache, ("factor", bb), build)

    def _factor_health_fn(self, bb: int):
        """Checked cold-start program: factor the stack AND produce the
        post-factor health evidence in the SAME dispatch —
        (bb,)+shape A -> (factors, wA, verdict (2, bb)).

        wA[i] = w^T A_i is each session's Freivalds probe row
        (`update.probe_row`), computed here so coalesced sessions open
        with their probe already device-resident (no later lazy probe
        dispatch). The verdict solves A_i x = w through the fresh
        factors — one O(N^2) substitution per system next to the O(N^3)
        factor — and projects the residual through wA; slot i's verdict
        depends only on slot i's matrix, so one sick system can never
        contaminate its co-batched slots' evidence (blast-radius
        isolation at the verdict level). Per-slot reductions run OUTSIDE
        the vmaps as a handful of batched ops (the XLA-CPU fixed-op-cost
        rule, §20).

        `_fused_probe` plans run the probe solve through
        `_blocked_probe_body` — finite/projection accumulators ride the
        back substitution's own block loop (§27), so the verdict costs
        two O(N) dots; `_pallas_factor` plans additionally compute the
        factor AND wA inside the batch-grid kernel
        (`_stacked_factor_body`), making the checked coalesced factor
        one dispatch end to end (§29). All three producers emit the
        same (2, bb) verdict block `resilience.evaluate_slots` reads."""
        if self.mesh is not None:
            raise AssertionError(
                "the checked stacked factor program is unsharded — mesh "
                "plans factor through the batch-sharded _factor_fn")
        if bb & (bb - 1) or bb < 1:
            raise AssertionError(
                f"_factor_health_fn takes power-of-two batch buckets, "
                f"got {bb} — route requests through ServeEngine")

        def build():
            w = self.probe_w
            fused = self._fused_probe
            if self.key.kind == "qr":
                # least-squares verdict: u_i ∈ range(A_i) by
                # construction (`update.probe_lstsq`), so the minimizer
                # of ||A_i x − u_i|| reproduces u_i exactly and the
                # per-slot projected residual |u·u − uA·x| vanishes at
                # the solution — same tripwire scale as the square
                # lane's |w·w − wA·x| (u is normalized to ||u|| = √M).
                # qr plans are single-system, unfused, XLA-backend.
                solve_u = jax.vmap(self._one_solve)

                def check_qr(F, wA, Ast):
                    u_st, uA_st = wA
                    x = solve_u(F, Ast, u_st[..., None])
                    cdtype = x[..., 0].dtype
                    finite = jnp.isfinite(
                        jnp.sum(x, axis=tuple(range(1, x.ndim))))
                    x0 = x[..., 0].astype(cdtype)
                    uc = u_st.astype(cdtype)
                    ax = jnp.sum(uA_st.astype(cdtype) * x0, axis=-1)
                    num = jnp.abs(jnp.sum(uc * uc, axis=-1) - ax)
                    den = (jnp.sqrt(jnp.sum(jnp.abs(uc) ** 2, axis=-1))
                           + jnp.finfo(cdtype).tiny)
                    return jnp.stack([finite.astype(jnp.float32),
                                      (num / den).astype(jnp.float32)])

                def f_qr(Ast):
                    self._bump("factor_health")  # trace-time
                    F, wA = self._stacked_factor_body(Ast, probe=True)
                    return F, wA, check_qr(F, wA, Ast)

                return jax.jit(f_qr)
            if fused:
                # the §27 fused probe epilogue: the probe solve's back
                # substitution accumulates the finite/projection stats
                # in its own block loop, so the verdict costs two O(N)
                # dots instead of a pass over x
                probe_body = jax.vmap(self._blocked_probe_body,
                                      in_axes=(0, 0, None))
                if self.batched:
                    probe_body = jax.vmap(probe_body, in_axes=(0, 0, None))
            else:
                solve_one = jax.vmap(self._one_solve, in_axes=(0, 0, None))
                if self.batched:
                    solve_one = jax.vmap(solve_one, in_axes=(0, 0, None))

            def check(F, wA, Ast):
                # per-slot verdict, batched reductions outside the vmaps:
                # finite flag rides one summation per slot (factor NaNs
                # propagate into x), residual is the probe projection
                # |w.w - wA.x0| / ||w|| per system, max-reduced over the
                # plan's own batch axis for batched plans
                w2 = w[:, None].astype(jnp.dtype(self.key.dtype))
                if fused:
                    _x, xsum, wAx = probe_body(F, wA, w2)
                    cdtype = wAx.dtype
                    fin_acc = (jnp.sum(xsum, axis=-1) if self.batched
                               else xsum)
                    ax = wAx
                else:
                    x = solve_one(F, Ast, w2)
                    cdtype = x[..., 0].dtype
                    fin_acc = jnp.sum(x, axis=tuple(range(1, x.ndim)))
                    x0 = x[..., 0].astype(cdtype)
                    ax = jnp.sum(wA.astype(cdtype) * x0, axis=-1)
                finite = jnp.isfinite(fin_acc)
                wc = w.astype(cdtype)
                num = jnp.abs(jnp.sum(wc * wc) - ax)
                den = (jnp.sqrt(jnp.sum(jnp.abs(wc) ** 2))
                       + jnp.finfo(cdtype).tiny)
                res = num / den
                if self.batched:
                    res = jnp.max(res, axis=-1)
                return jnp.stack([finite.astype(jnp.float32),
                                  res.astype(jnp.float32)])

            if self._pallas_factor:
                # same core/epilogue split as _stacked_factor_fn: the
                # kernel (which already computed wA in-grid) dispatches
                # standalone, and ONE jitted epilogue program builds the
                # substitution pytree + probe solve + verdict
                def epi(Ast, core):
                    self._bump("factor_health")  # trace-time
                    F, wA = self._pallas_factor_epilogue(core, probe=True)
                    return F, wA, check(F, wA, Ast)

                jepi = jax.jit(epi)

                def run(Ast):
                    return jepi(Ast,
                                self._pallas_factor_core(Ast, probe=True))

                return run

            def f(Ast):
                self._bump("factor_health")  # trace-time, not per call
                F, wA = self._stacked_factor_body(Ast, probe=True)
                return F, wA, check(F, wA, Ast)

            return jax.jit(f)

        return self._memo(self._factor_cache, ("factor_health", bb), build)

    def _mesh_factor_health_fn(self):
        """The mesh lane's checked cold-start program: factor ONE
        (B, N, N) batch through the batch-sharded factor body AND
        produce the session's health evidence in the SAME sharded
        dispatch — A -> (factors, wA, verdict (2, 1)).

        The factor body is the same vmapped `_one_factor` that
        `_factor_fn` jits (a mesh `plan.factor` rides `_factor_fn`
        through `_factor_once`), so the engine's checked mesh factor
        and the bare one carry the same bits. wA[i] = w^T A_i is the
        per-system Freivalds probe row ((B, N), batch-sharded) — the
        session opens with its probe device-resident, like the stacked
        lane. The verdict reduces over the plan's OWN batch axis (one
        mesh session is one tenant: max residual, any non-finite slot
        poisons it) into the (2, 1) block `resilience.evaluate_slots`
        reads, so the engine's drain path treats a mesh factor as a
        one-slot batch."""
        if self.mesh is None:
            raise AssertionError(
                "_mesh_factor_health_fn is the mesh lane's checked "
                "factor program — unsharded plans ride "
                "_factor_health_fn")

        def build():
            w = self.probe_w
            fused = self._fused_probe
            if fused:
                probe_body = jax.vmap(self._blocked_probe_body,
                                      in_axes=(0, 0, None))
            else:
                solve_one = jax.vmap(self._one_solve, in_axes=(0, 0, None))
            k = self.key
            spec3 = _batch_spec(self.mesh, 3)
            spec2 = _batch_spec(self.mesh, 2)
            spec4 = _batch_spec(self.mesh, 4)
            if k.spd:
                fac_shard = ((spec3, spec4) if k.substitution == "blocked"
                             else (spec3,))
            elif k.substitution == "blocked":
                fac_shard = (spec3, spec4, spec4, spec2)
            elif k.substitution == "inv":
                fac_shard = (spec3, spec3, spec2)
            else:
                fac_shard = (spec3, spec2)

            def check(F, wA, A):
                w2 = w[:, None].astype(jnp.dtype(k.dtype))
                if fused:
                    _x, xsum, wAx = probe_body(F, wA, w2)
                    cdtype = wAx.dtype
                    fin_acc = jnp.sum(xsum)
                    ax = wAx
                else:
                    x = solve_one(F, A, w2)
                    cdtype = x[..., 0].dtype
                    fin_acc = jnp.sum(x)
                    x0 = x[..., 0].astype(cdtype)
                    ax = jnp.sum(wA.astype(cdtype) * x0, axis=-1)
                finite = jnp.isfinite(fin_acc)
                wc = w.astype(cdtype)
                num = jnp.abs(jnp.sum(wc * wc) - ax)
                den = (jnp.sqrt(jnp.sum(jnp.abs(wc) ** 2))
                       + jnp.finfo(cdtype).tiny)
                res = jnp.max(num / den)
                return jnp.stack([finite.astype(jnp.float32),
                                  res.astype(jnp.float32)])[:, None]

            body = jax.vmap(self._one_factor)
            probe = jax.vmap(lambda A0: probe_row(w, A0))

            def f(A):
                self._bump("factor_health")  # trace-time, not per call
                F = body(A)
                wA = probe(A)
                return F, wA, check(F, wA, A)

            return jax.jit(f, out_shardings=(fac_shard, spec2, None))

        return self._memo(self._factor_cache, ("factor_health_mesh",),
                          build)

    def _factor_once(self, A):
        """Factor ONE system (or one (B, N, N) batch for batched plans)
        through the bucket-1 slot of the stacked factor program —
        `factor()`, `refactor()` and the drift-policy `_refactor` all
        route here, so every session of a non-mesh plan carries factors
        from the SAME program family as the engine's coalesced factor
        lane (bitwise, see :meth:`_stacked_factor_fn`). Mesh plans keep
        the batch-sharded unvmapped program."""
        if self.mesh is not None:
            return self._factor_fn(A)
        F = self._stacked_factor_fn(1)(A[None])
        return unstack_tree(F, 1)[0]

    # ------------------------------------------------------------------ #
    # checked (health-guarded) solve programs — the resilience layer
    # ------------------------------------------------------------------ #

    @property
    def probe_w(self):
        """The plan's fixed Rademacher probe w (`update.probe_vector`):
        one vector per plan size keeps every checked program and every
        session's cached probe row wA = w^T A0 consistent."""
        w = getattr(self, "_probe_w_cache", None)
        if w is None:
            w = jnp.asarray(probe_vector(self.N))
            self._probe_w_cache = w
        return w

    def _probe_fn(self):
        """Jitted wA = w^T A0 program — the once-per-base half of the
        Freivalds-style residual check (`update.probe_row`); sessions
        cache its output next to the factors and invalidate on
        refactor. kind='qr' plans cache the LEAST-SQUARES probe pair
        (u, uA) = `update.probe_lstsq` instead (u in range(A0), so the
        LS residual's orthogonality makes the same projected check
        work — §33)."""
        w = self.probe_w

        def build():
            if self.key.kind == "qr":
                from conflux_tpu.update import probe_lstsq

                one = lambda A0: probe_lstsq(w, A0)  # noqa: E731
            else:
                one = lambda A0: probe_row(w, A0)  # noqa: E731
            f = jax.vmap(one) if self.batched else one
            if self.mesh is None:
                return jax.jit(f)
            return jax.jit(f, out_shardings=_batch_spec(self.mesh, 2))

        return self._memo(self._solve_cache, ("probe",), build)

    def _checked(self, inner):
        """Wrap a per-system (factors, A0, b2) solve body into the
        checked-program shape (factors, A0, wA, b2) -> (x, (2,) verdict).
        The body is vmapped for batched plans; the verdict
        (`update.health_spot_check`) is computed OUTSIDE the vmap on the
        whole batched block — XLA CPU charges fixed per-op overhead next
        to these small dispatches, so the check stays a handful of
        batched reductions, and the clean path pays no extra dispatch
        (the verdict rides the same program as the answer)."""
        w = self.probe_w
        qr = self.key.kind == "qr"
        body = jax.vmap(inner) if self.batched else inner

        def f(factors, A0, wA, b2):
            self._bump("health")  # trace-time, not per call
            x = body(factors, A0, b2)
            if qr:
                # the session's probe is the (u, uA) pair: u ∈ range(A0)
                # is orthogonal to the least-squares residual, so the
                # SAME projected check u·b − uA·x vanishes at min||Ax−b||
                # (§33) — health_spot_check consumes it verbatim
                u, uA = wA
                return x, health_spot_check(u, uA, x, b2)
            return x, health_spot_check(w, wA, x, b2)

        return f

    def _jit_checked(self, f):
        if self.mesh is None:
            return jax.jit(f)
        return jax.jit(f, out_shardings=(_batch_spec(self.mesh, 3),
                                         None))

    @property
    def _fused_probe(self) -> bool:
        """True when this plan's checked programs fuse the Freivalds
        probe epilogue into the blocked back-substitution's final block
        steps (`ops.batched_trsm.blocked_solve_probe`, DESIGN §27) —
        blocked plans without IR sweeps (`refine` re-reads x per sweep,
        so only the refine-free shape has a 'final' block step to fuse
        into). Fused programs live in `_trsm_cache`; everything about
        the bucket lifecycle (`bucket_ready`, `release_buckets`,
        `_warm_devices`) treats the two families uniformly."""
        return self.key.substitution == "blocked" and not self.key.refine

    def _blocked_probe_body(self, factors, wA, b2):
        """Per-system blocked solve with the probe epilogue fused into
        the final (back-substitution) block loop: returns (x, xsum,
        wAx) where the finite accumulator and the probe projection
        accumulate as each x block is produced — no separate verdict
        pass over x (`update.health_verdict_from_stats` assembles the
        (2,) verdict from these plus two O(N) b-side dots). Traceable;
        vmapped for batched plans and the gang's stacked programs."""
        from conflux_tpu.ops.batched_trsm import (
            blocked_solve,
            blocked_solve_probe,
        )

        k = self.key
        cdtype = blas.compute_dtype(jnp.dtype(k.dtype))
        if k.spd:
            L, Dl = factors
            Lc = L.astype(Dl.dtype)
            y = blocked_solve(Lc, Dl, b2.astype(Dl.dtype), lower=True)
            Du = jnp.swapaxes(Dl.conj(), -1, -2)
            x, xsum, wAx = blocked_solve_probe(
                Lc.conj().T, Du, y, wA, lower=False, stats_dtype=cdtype)
        else:
            LU, Dl, Du, perm = factors
            LUc = LU.astype(Dl.dtype)
            y = blocked_solve(LUc, Dl, b2.astype(Dl.dtype)[perm],
                              lower=True)
            x, xsum, wAx = blocked_solve_probe(
                LUc, Du, y, wA, lower=False, stats_dtype=cdtype)
        return x.astype(cdtype), xsum, wAx

    def _solve_health_fn(self, nrhs: int):
        """The checked substitution program per RHS bucket — what
        `SolveSession.solve_checked` (and the engine with output guards
        on) dispatches instead of `_solve_fn`. Signature:
        (factors, A0, wA, b2) -> (x, verdict); A0 feeds the plan's
        `refine` sweeps exactly like the plain program's `A`, wA is the
        session's cached probe row."""
        if nrhs & (nrhs - 1) or nrhs < 1:
            raise AssertionError(
                f"_solve_health_fn takes power-of-two RHS buckets, got "
                f"{nrhs} — route request widths through solve_checked")
        if self._fused_probe:
            from conflux_tpu.update import health_verdict_from_stats

            def build():
                w = self.probe_w
                body = (jax.vmap(self._blocked_probe_body)
                        if self.batched else self._blocked_probe_body)

                def f(factors, A0, wA, b2):
                    self._bump("health")  # trace-time, not per call
                    x, xsum, wAx = body(factors, wA, b2)
                    return x, health_verdict_from_stats(w, xsum, wAx, b2)

                return self._jit_checked(f)

            return self._memo(self._trsm_cache, ("health", nrhs), build)
        return self._memo(
            self._solve_cache, ("health", nrhs),
            lambda: self._jit_checked(self._checked(self._one_solve)))

    def _update_solve_health_fn(self, kb: int, nrhs: int, sweeps: int):
        """Checked Woodbury solve program: the projected residual routes
        through the DRIFTED matrix (w^T A1 = wA + (w^T Up) Vp^H, padded
        columns inert), so SMW garbage from an ill-conditioned
        capacitance trips the verdict."""
        def build():
            import functools

            one = functools.partial(self._one_update_solve, sweeps)
            w = self.probe_w
            body = jax.vmap(one) if self.batched else one

            def f(factors, A0, Up, Vp, Y, Cinv, wA, b2):
                self._bump("health")  # trace-time, not per call
                x = body(factors, A0, Up, Vp, Y, Cinv, b2)
                return x, health_spot_check(w, wA, x, b2, Up, Vp)

            return self._jit_checked(f)

        return self._memo(self._update_cache,
                          ("uhealth", kb, nrhs, sweeps), build)

    def _one_refine(self, factors, A0, x, b2):
        """One iterative-refinement sweep against the CURRENT base
        factors — escalation rung 2's body (the forced refactor of rung
        1 already absorbed any drift, so the TRUE residual matvec runs
        against A0; only the re-check verdict rides the probe)."""
        self._bump("refine")
        corr = self._base_corr(factors)
        cdtype = blas.compute_dtype(jnp.dtype(self.key.dtype))
        xc = x.astype(cdtype)
        r = (b2.astype(cdtype)
             - jnp.matmul(A0.astype(cdtype), xc,
                          precision=lax.Precision.HIGHEST))
        return xc + corr(r).astype(cdtype)

    def _refine_fn(self, nrhs: int):
        def build():
            w = self.probe_w
            qr = self.key.kind == "qr"
            one = self._one_refine
            body = jax.vmap(one) if self.batched else one

            def f(factors, A0, wA, x, b2):
                x2 = body(factors, A0, x, b2)
                if qr:
                    u, uA = wA
                    return x2, health_spot_check(u, uA, x2, b2)
                return x2, health_spot_check(w, wA, x2, b2)

            return self._jit_checked(f)

        return self._memo(self._solve_cache, ("refine", nrhs), build)

    # ------------------------------------------------------------------ #
    # served precision tiers — the per-request ladder (DESIGN §33)
    # ------------------------------------------------------------------ #

    def _tier_spec(self, tier: str):
        """(factor dtype, fused IR sweep count) for a served tier.
        'bf16_ir' factors in bfloat16 — half the resident factor bytes —
        and always fuses at least one refinement sweep (the IR half of
        the name; the residual matvec runs against the f32 base, so one
        sweep recovers working-precision accuracy for well-conditioned
        systems, §15). 'f32'/'f64' factor at that storage dtype with the
        plan's own sweep count; 'f64' canonicalizes to f32 when x64 is
        off (same programs, documented in TUNING)."""
        if tier not in PRECISION_TIERS:
            raise ValueError(
                f"unknown served tier {tier!r} — one of {PRECISION_TIERS}")
        if tier == "bf16_ir":
            return jnp.dtype(jnp.bfloat16), max(int(self.key.refine), 1)
        if tier == "f32":
            return jnp.dtype(jnp.float32), int(self.key.refine)
        return (jnp.dtype(jax.dtypes.canonicalize_dtype(jnp.float64)),
                int(self.key.refine))

    def _check_tier(self, what: str, tier: str) -> None:
        if tier not in PRECISION_TIERS:
            raise ValueError(
                f"{what} takes a served tier from {PRECISION_TIERS}, "
                f"got {tier!r}")
        if self.mesh is not None:
            raise AssertionError(
                "mesh-sharded plans serve their native precision only — "
                "per-request tiers are validated away at submit "
                "(engine._prepare)")

    def _tier_stacked_factor_fn(self, tier: str, bb: int):
        """The served tiers' coalesced factor program: `bb` systems
        factor at the TIER's dtype in one dispatch — the
        `("tier_factor", tier, bb)` family next to the native
        `("factor", bb)` one, same power-of-two buckets, same per-slot
        bitwise bucket/pad-invariance (vmapped `_one_factor` with the
        dtype override; always the XLA body — the §29 Pallas kernels
        carry no bf16 grid, and tier traffic is routed, not default)."""
        self._check_tier("_tier_stacked_factor_fn", tier)
        if bb & (bb - 1) or bb < 1:
            raise AssertionError(
                f"_tier_stacked_factor_fn takes power-of-two batch "
                f"buckets, got {bb} — route requests through ServeEngine")

        def build():
            fd, _ = self._tier_spec(tier)
            one = lambda A: self._one_factor(A, fdtype=fd)  # noqa: E731
            f = jax.vmap(jax.vmap(one)) if self.batched else jax.vmap(one)
            return jax.jit(f)

        return self._memo(self._factor_cache, ("tier_factor", tier, bb),
                          build)

    def _tier_factor_once(self, tier: str, A):
        """Factor ONE system at a served tier through the bucket-1 slot
        of the tier's stacked program — `factor(precision=...)`, the
        cross-tier derived cache (`SolveSession._tier_factor`), and the
        tier-aware revive path all route here, mirroring
        :meth:`_factor_once`'s one-program-family contract."""
        F = self._tier_stacked_factor_fn(tier, 1)(A[None])
        return unstack_tree(F, 1)[0]

    def _tier_solve_fn(self, tier: str, nrhs: int):
        """The served tiers' substitution program per RHS bucket: the
        tier's factors + the tier's fused sweep count against the f32
        base — the `("tier", tier, nrhs)` family in `_solve_cache`,
        warmed/retired through the same `bucket_ready`/`release_buckets`
        lifecycle as the native width buckets. Signature
        (factors, A0, b2) -> x; A0 is always consumed (bf16_ir fuses at
        least one residual sweep)."""
        self._check_tier("_tier_solve_fn", tier)
        if nrhs & (nrhs - 1) or nrhs < 1:
            raise AssertionError(
                f"_tier_solve_fn takes power-of-two RHS buckets, got "
                f"{nrhs} — route request widths through SolveSession.solve")
        _, sweeps = self._tier_spec(tier)

        def build():
            def one(factors, A0, b2):
                return self._one_solve(factors, A0, b2, sweeps=sweeps)

            f = jax.vmap(one) if self.batched else one
            return jax.jit(f)

        return self._memo(self._solve_cache, ("tier", tier, nrhs), build)

    def _tier_solve_health_fn(self, tier: str, nrhs: int):
        """Checked tier substitution per RHS bucket — what 'auto'
        requests dispatch (the verdict IS the ladder's escalation
        signal) and what explicit-tier requests ride under engine
        output guards. Always the unfused `_checked` shape — the §27
        fused-probe epilogue belongs to the native blocked family; the
        tiers keep one program shape across substitution modes."""
        self._check_tier("_tier_solve_health_fn", tier)
        if nrhs & (nrhs - 1) or nrhs < 1:
            raise AssertionError(
                f"_tier_solve_health_fn takes power-of-two RHS buckets, "
                f"got {nrhs} — route widths through solve_checked")
        _, sweeps = self._tier_spec(tier)

        def build():
            def one(factors, A0, b2):
                return self._one_solve(factors, A0, b2, sweeps=sweeps)

            return jax.jit(self._checked(one))

        return self._memo(self._solve_cache, ("tier_health", tier, nrhs),
                          build)

    # ------------------------------------------------------------------ #
    # incremental (Woodbury) update programs — compiled once per bucket
    # ------------------------------------------------------------------ #

    def _bump(self, name: str) -> None:
        """Trace-time counter for the update-path programs: keys appear
        lazily so plans that never update keep the original
        {'factor', 'solve'} counter shape."""
        self.trace_counts[name] = self.trace_counts.get(name, 0) + 1

    def _one_update(self, factors, Up, Vp):
        self._bump("update")
        Y, Cinv, cond1 = capacitance(self._base_corr(factors), Up, Vp)
        return Y, Cinv, cond1

    def _one_update_solve(self, sweeps, factors, A0, Up, Vp, Y, Cinv, b2):
        """Woodbury-corrected substitution + `sweeps` IR backstop sweeps
        against the DRIFTED matrix (A0 x + U (V^H x) residual matvec,
        the serve layer's refinement-loop discipline)."""
        self._bump("update_solve")
        corr = self._base_corr(factors)
        cdtype = blas.compute_dtype(jnp.dtype(self.key.dtype))
        x = woodbury_apply(corr, Y, Cinv, Vp, b2).astype(cdtype)
        bc = b2.astype(cdtype)
        for _ in range(sweeps):
            r = bc - updated_matvec(A0, Up, Vp, x)
            x = x + woodbury_apply(corr, Y, Cinv, Vp, r).astype(cdtype)
        return x

    def _update_fn(self, kb: int):
        """Jitted capacitance-assembly program per rank bucket kb:
        (factors, Up, Vp) -> (Y, Cinv, cond1)."""
        def build():
            f = jax.vmap(self._one_update) if self.batched \
                else self._one_update
            if self.mesh is None:
                return jax.jit(f)
            return jax.jit(f, out_shardings=(
                _batch_spec(self.mesh, 3), _batch_spec(self.mesh, 3),
                _batch_spec(self.mesh, 1)))

        return self._memo(self._update_cache, ("update", kb), build)

    def _update_solve_fn(self, kb: int, nrhs: int, sweeps: int):
        """Jitted Woodbury solve program per (rank bucket, RHS bucket,
        backstop sweeps)."""
        def build():
            import functools

            one = functools.partial(self._one_update_solve, sweeps)
            f = jax.vmap(one) if self.batched else one
            if self.mesh is None:
                return jax.jit(f)
            return jax.jit(f, out_shardings=_batch_spec(self.mesh, 3))

        return self._memo(self._update_cache, ("usolve", kb, nrhs, sweeps),
                          build)

    def _refresh_fn(self, kb: int, donate: bool = False):
        """Jitted A0 + U V^H materialization per rank bucket — the
        refactor trigger's input, feeding the existing factor program.

        `donate=True` hands the superseded A0 buffer to XLA (the output
        replaces it), so a long-lived drifting session holds ONE resident
        base matrix at the refactor peak instead of two. Only safe when
        the session owns A0 — i.e. it came from a previous refactor, not
        from the caller, who may still hold the array — so the session
        tracks ownership and the donating and non-donating programs cache
        separately."""
        from conflux_tpu.update import apply_update

        def build():
            f = jax.vmap(apply_update) if self.batched else apply_update
            donate_argnums = (0,) if donate else ()
            if self.mesh is None:
                return jax.jit(f, donate_argnums=donate_argnums)
            return jax.jit(f, out_shardings=_batch_spec(self.mesh, 3),
                           donate_argnums=donate_argnums)

        return self._memo(self._update_cache, ("refresh", kb, donate),
                          build)

    # ------------------------------------------------------------------ #
    # serving surface
    # ------------------------------------------------------------------ #

    def _check_A(self, A):
        want = self.key.shape
        if tuple(A.shape) != want:
            raise ValueError(f"A shape {A.shape} does not match the plan's "
                             f"{want}")
        if A.dtype != jnp.dtype(self.key.dtype):
            raise ValueError(f"A dtype {A.dtype} does not match the plan's "
                             f"{self.key.dtype}")

    def factor(self, A, *, policy: DriftPolicy | None = None,
               device=None, sid=None,
               precision: str | None = None) -> "SolveSession":
        """Run the factor program on A and open a device-resident session.

        The returned session holds the factors (and A itself — the
        refinement residual matvec and the incremental-update/refactor
        path both consume it) on device; every `session.solve` afterwards
        is substitution-only. `policy` governs when `session.update`
        drifts trigger a true refactorization (default
        :class:`DriftPolicy`).

        `device` pins the session to one device of the serve fleet: A is
        committed there before factoring, so the factors (and every
        later substitution) live and run on that device — the mesh-
        sharded engine's per-lane placement (DESIGN §25). None keeps the
        default device (byte-identical to the pre-fleet behavior).
        `sid` is an optional STABLE session id; the engine's consistent-
        hash placement (`engine.place_session`) maps equal sids to equal
        devices across engine restarts. For mesh plans a `device` INSIDE
        the plan's mesh is a placement no-op (the state is batch-sharded
        across the whole mesh already — the session stays unpinned);
        a device outside the mesh is refused, since sharded state
        cannot migrate off its mesh.

        `precision` opens the session AT a served tier (DESIGN §33):
        the factors are built at that tier's dtype directly — a
        bf16-tier session never pays the f32 factorization — and
        subsequent solves default to the tier's program family.
        'auto' opens on the cheapest rung (bf16+IR) with the session's
        sticky escalation rung at 0. None (the default) is the native
        path, bitwise-identical to pre-§33 behavior. Mesh-sharded
        plans serve native precision only.
        """
        tier0 = check_precision_request(precision)
        if tier0 is not None and self.mesh is not None:
            raise ValueError(
                "mesh-sharded plans serve their native precision only — "
                "precision= does not compose with mesh plans (§33)")
        if tier0 == "auto":
            tier0 = PRECISION_TIERS[0]
        if device is not None and self.mesh is not None:
            if not any(device == d for d in self.mesh.devices.flat):
                from conflux_tpu.resilience import MeshPlanUnsupported

                raise MeshPlanUnsupported(
                    "device= names a device outside this plan's mesh — "
                    "a mesh-sharded session's state cannot migrate off "
                    "its mesh", surface="factor")
            device = None  # in-mesh pin: state already spans the mesh
        A = jnp.asarray(A)
        self._check_A(A)
        if self.mesh is not None:
            (A,) = _shard_batch((A,), self.mesh)
        elif device is not None:
            A = jax.device_put(A, device)
        with profiler.region("serve.factor"):
            factors = (self._factor_once(A) if tier0 is None
                       else self._tier_factor_once(tier0, A))
        # tier sessions always retain the base — their solve programs
        # fuse residual sweeps against A0 (bf16_ir at minimum one)
        keep_A = A if (self.key.refine or tier0 is not None) else None
        return SolveSession(self, factors, keep_A, A, policy,
                            device=device, sid=sid, served_tier=tier0)

    def solve(self, A, b):
        """One-shot convenience: factor + solve in one call (a fresh
        session per call — serving code should hold the session)."""
        return self.factor(A).solve(b)


class SolveSession:
    """Device-resident factors + the compiled substitution program.

    Sessions are cheap handles: the heavy state lives on device. `solves`
    and `factorizations` count what this session actually ran — the
    serving invariant (`factorizations == 1` under solve-only traffic,
    `solves` growing) is asserted by tests/test_serve.py.

    `update(U, V)` applies a rank-k drift A <- A + U V^H WITHOUT
    refactoring: subsequent solves ride the base factors plus a k x k
    capacitance correction (Sherman-Morrison-Woodbury, see
    `conflux_tpu.update`), all device-resident and compiled once per
    (rank bucket, RHS bucket). The session's :class:`DriftPolicy` decides
    when accumulated rank/conditioning stops paying and triggers ONE true
    refactorization through the plan's existing factor program
    (`factorizations`/`refactors` record it).
    """

    def __init__(self, plan: FactorPlan, factors, A, A_base=None,
                 policy: DriftPolicy | None = None, *,
                 device=None, sid=None, served_tier=None,
                 auto_rung: int = 0):
        self.plan = plan
        # fleet placement (DESIGN §25): the device this session's state
        # lives on (None = default device — the pre-fleet behavior,
        # byte-identical) and an optional STABLE id the engine's
        # consistent-hash placement keys on. Both write-once-ish: the
        # engine pins an unplaced session at first submit (under the
        # session lock) and never re-pins a placed one.
        self.device = device
        self.sid = sid
        # resilience + concurrency state: every factor/drift mutation
        # and every read of the resident state happens under this
        # re-entrant lock (conflint CFX-LOCK enforces the guarded-by
        # annotations below) — a drain-thread escalation's factor swap
        # (`self._factors = None` then the fresh dispatch) is atomic
        # against any dispatcher or direct-caller solve. The RLock
        # makes the engine's outer hold (`_solve_session`) and the
        # escalation ladder's (`resilience.escalate`) re-enter cleanly.
        self._lock = threading.RLock()
        self._factors = factors    # guarded-by: _lock
        self._A = A                # guarded-by: _lock
        self._A0 = A if A_base is None else A_base  # guarded-by: _lock
        self.policy = DriftPolicy() if policy is None else policy
        self._upd = None  # guarded-by: _lock — dict(k, kb, Up, Vp, Y, Cinv)
        # the base matrix is the CALLER's array until the first refactor
        # replaces it with an engine-built one; only owned bases may be
        # donated to the refresh program (see FactorPlan._refresh_fn)
        self._owns_base = False    # guarded-by: _lock
        # the breaker is attached lazily by resilience.breaker_for
        # (write-once under its own attach lock); last_cond is the
        # latest capacitance condition estimate — SolveUnhealthy
        # evidence
        self._breaker = None
        self.last_cond = None      # guarded-by: _lock
        # wA = w^T A0, the once-per-base half of the projected-residual
        # check — computed lazily on the first checked solve, dropped
        # whenever a refactor replaces the base
        self._probe = None         # guarded-by: _lock
        # served precision tier (DESIGN §33): `_served_tier` names the
        # tier the resident `_factors` were built at (None = the plan's
        # native factor dtype — bitwise the pre-ladder behavior);
        # `_auto_rung` is the sticky 'auto' ladder position (escalations
        # ratchet it up, so a session that needed f32 once starts there
        # next time); `_tier_factors` is the DERIVED per-tier factor
        # cache for cross-tier requests — rebuildable from `_A0`, so it
        # is excluded from nbytes, spill records and checkpoints, and
        # cleared on every base swap / device move / spill
        self._served_tier = served_tier  # guarded-by: _lock
        self._auto_rung = int(auto_rung)  # guarded-by: _lock
        self._tier_factors: dict = {}  # guarded-by: _lock
        self.precision_escalations = 0  # guarded-by: _lock
        self.precision_fallbacks = 0  # guarded-by: _lock
        self.factorizations = 1    # guarded-by: _lock
        self.solves = 0            # guarded-by: _lock
        self.updates = 0           # guarded-by: _lock
        self.refactors = 0         # guarded-by: _lock
        # tiered residency (conflux_tpu.tier): `_residency` is the
        # managing ResidentSet (write-once at adopt; None = untiered,
        # zero behavioral change), `_spill` holds the spill record
        # while the session's state lives off-device — every
        # state-touching method faults it back in first
        # (`_ensure_resident`, under this same lock). `_tier_stamp` is
        # the LRU clock: a single int write per touch, read only by the
        # manager's eviction scan — benign staleness by design
        self._residency = None
        self._spill = None         # guarded-by: _lock
        self._tier_stamp = 0
        # gang residency (conflux_tpu.gang, DESIGN §26): `_gang` is the
        # SessionGang holding this session's stacked slot (None =
        # unganged, zero behavioral change), `_gang_slot` its slot
        # index. Both are written by the gang under ITS protocol (the
        # gang lock orders after this session lock, so they are plain
        # attribute writes here — racy reads tolerated by design).
        # `_gang_ver` is the write-back sync: every state mutation
        # below bumps it under this lock, and the engine's dispatcher
        # re-syncs a stale slot before the next stacked dispatch —
        # write-back is LAZY, so no mutation path ever needs the gang
        # lock while holding this one.
        self._gang = None
        self._gang_slot = None
        self._gang_ver = 0         # guarded-by: _lock
        # checkpoint dirty clock (DESIGN §35): bumped by every mutation
        # that changes what `tier.save_fleet` would persist (update /
        # refactor / device move / precision escalation / adopt).
        # Solve-only traffic leaves it untouched, so the incremental
        # checkpointer can skip clean sessions. Counters that only
        # solves advance (solve/residual tallies) lag in carried
        # records by design — they are observability, not state.
        self._ckpt_ver = 0         # guarded-by: _lock

    @property
    def factors(self):
        """The device-resident factor pytree: (LU, perm) / (L,) for
        'trsm' plans, (Li, Ui, perm) / (Li,) triangular inverses for
        'inv' plans."""
        with self._lock:
            return self._factors

    @property
    def served_tier(self):
        """The served precision tier the resident factors carry (None =
        the plan's native factor dtype)."""
        with self._lock:
            return self._served_tier

    @property
    def auto_rung(self) -> int:
        """The sticky 'auto' ladder position (index into
        `PRECISION_TIERS`) — escalations ratchet it up."""
        with self._lock:
            return self._auto_rung

    @property
    def update_rank(self) -> int:
        """Accumulated drift rank since the last (re)factorization."""
        with self._lock:
            if self._spill is not None and self._spill.meta:
                # spilled: report the record's drift rank without the
                # cost of faulting the session back in
                u = self._spill.meta.get("upd")
                return 0 if u is None else u["k"]
            return 0 if self._upd is None else self._upd["k"]

    # ------------------------------------------------------------------ #
    # tiered residency (conflux_tpu.tier)
    # ------------------------------------------------------------------ #

    # requires-lock: _lock
    def _ensure_resident(self) -> None:
        """Fault a spilled session back in and stamp the LRU clock —
        the transparent-revival hook every state-touching method runs
        first, under the session RLock (so a request never observes a
        half-restored factor pytree). Untiered sessions pay two
        attribute reads."""
        if self._spill is not None:
            if self._residency is None:
                from conflux_tpu.resilience import SessionSpilled

                raise SessionSpilled(
                    "session is spilled but no ResidentSet manages it "
                    "(the manager detached or the record was grafted) — "
                    "revive through ResidentSet.fault_in")
            self._residency.fault_in(self)
        rs = self._residency
        if rs is not None:
            self._tier_stamp = rs._tick()

    @property
    def tier(self) -> str:
        """'device' (resident), 'host' or 'disk' (spilled), or
        'corrupt' (a spill record that failed its integrity check —
        permanently failed, see `resilience.RestoreCorrupt`)."""
        with self._lock:
            return "device" if self._spill is None else self._spill.tier

    @property
    def nbytes(self) -> int:
        """Device-resident footprint in bytes: factors + base matrix +
        Woodbury correction state + the cached probe row, deduplicated
        by buffer identity (`_A` aliases `_A0` whenever the plan keeps
        it). 0 while spilled — the spill record accounts its own
        host/disk bytes. The byte-bounded tier policy
        (`tier.ResidentSet(max_bytes=...)`) and `engine.stats()` read
        this."""
        with self._lock:
            seen: dict[int, int] = {}
            # tree_leaves: the probe is a (u, uA) TUPLE for kind='qr'
            # plans; `_tier_factors` is derived state (rebuildable from
            # _A0) and deliberately unaccounted
            leaves = jax.tree_util.tree_leaves(
                (self._factors, self._A, self._A0, self._probe))
            if self._upd is not None:
                leaves += [self._upd[k] for k in
                           ("Up", "Vp", "Y", "Cinv")]
            for leaf in leaves:
                if leaf is not None:
                    seen[id(leaf)] = int(leaf.nbytes)
            return sum(seen.values())

    def to_device(self, device) -> "SolveSession":
        """Move the session's resident state to `device` and pin it
        there — the engine's placement hook (a not-yet-placed session
        submitted to a mesh-sharded fleet lands on its consistent-hash
        lane through this). One `jax.device_put` per UNIQUE buffer
        (`batched.put_tree` preserves the `_A is _A0` alias, so the
        byte accounting stays deduplicated); `device=None` or an
        already-there session is a no-op. Runs under the session RLock
        — a concurrent solve never observes half-moved state. For mesh
        plans a device INSIDE the mesh is a no-op (the state already
        spans the mesh — the session stays unpinned); a device outside
        the mesh is refused, the genuine cross-device-migration
        residue (DESIGN §32)."""
        if device is None:
            return self
        if self.plan.mesh is not None:
            if any(device == d for d in self.plan.mesh.devices.flat):
                return self
            from conflux_tpu.resilience import MeshPlanUnsupported

            raise MeshPlanUnsupported(
                "a mesh-sharded session's state is batch-sharded "
                "across the whole mesh — it cannot move off its mesh "
                "to one device", surface="to_device")
        with self._lock:
            self._ensure_resident()
            moved = put_tree(
                {"f": self._factors, "A": self._A, "A0": self._A0,
                 "probe": self._probe,
                 "upd": (None if self._upd is None else
                         {k: self._upd[k]
                          for k in ("Up", "Vp", "Y", "Cinv")})},
                device)
            self._factors = moved["f"]
            self._A = moved["A"]
            self._A0 = moved["A0"]
            self._probe = moved["probe"]
            self._tier_factors = {}  # derived state stays device-local
            if self._upd is not None:
                self._upd = {**self._upd, **moved["upd"]}
            self.device = device
            self._gang_ver += 1
            self._ckpt_ver += 1
            if self._gang is not None:
                # the gang's stack lives on the OLD device — leave it
                # (release requires this held session lock; the session
                # re-adopts into its new lane's gang at next dispatch)
                self._gang.release(self)
        return self

    def _rhs(self, b):
        plan = self.plan
        b = jnp.asarray(b)
        if plan.batched:
            if b.ndim == 2:
                want = (plan.B, plan.N)
                if b.shape != want:
                    raise ValueError(f"rhs {b.shape}, session needs {want}")
                return b[:, :, None], True
            want = (plan.B, plan.N)
            if b.ndim != 3 or b.shape[:2] != want:
                raise ValueError(
                    f"rhs {b.shape}, session needs {want} (+ rhs axis)")
            return b, False
        if b.ndim == 1:
            if b.shape[0] != plan.M:
                raise ValueError(f"rhs {b.shape}, session needs ({plan.M},)")
            return b[:, None], True
        if b.ndim != 2 or b.shape[0] != plan.M:
            raise ValueError(f"rhs {b.shape}, session needs ({plan.M}, k)")
        return b, False

    # requires-lock: _lock
    def _resolve_tier(self, precision):
        """Resolve a per-request ``precision=`` to a served tier (or
        None = the native program family). None defers to the tier the
        session was OPENED at (`_served_tier` — so a bf16-tier session's
        plain solves ride its own factors); 'auto' reads the sticky
        ladder rung. Drifted sessions (`_upd` set) fall back to their
        resident (Woodbury-corrected) path for CROSS-tier requests —
        a derived-tier factor set carries no drift state, so routing
        there would answer against the un-drifted base; the fallback is
        counted (`precision_fallbacks`), never an error."""
        tier = check_precision_request(precision)
        if tier is None:
            return self._served_tier
        if tier == "auto":
            tier = PRECISION_TIERS[
                min(self._auto_rung, len(PRECISION_TIERS) - 1)]
        if self._upd is not None and tier != self._served_tier:
            self.precision_fallbacks += 1
            return self._served_tier
        return tier

    # requires-lock: _lock
    def _tier_factor(self, tier):
        """The derived per-tier factor cache: factors of `_A0` at a
        tier OTHER than the session's served one, built lazily through
        the plan's tier factor family and dropped on any base swap."""
        F = self._tier_factors.get(tier)
        if F is None:
            F = self.plan._tier_factor_once(tier, self._A0)
            self._tier_factors[tier] = F
        return F

    # requires-lock: _lock
    def _factor_base(self, A):
        """(Re)build the session's RESIDENT factors from base `A` at
        the session's serving configuration — the native program family
        for untier'd sessions, the served tier's for tier'd ones. Every
        refactor path routes here so a bf16-tier session never silently
        reverts to f32 factors."""
        if self._served_tier is None:
            return self.plan._factor_once(A)
        return self.plan._tier_factor_once(self._served_tier, A)

    def solve(self, b, *, precision=None):  # hot-path
        """Solve against the resident factors: O(N^2) substitution plus
        the plan's `refine` sweeps (plus the Woodbury correction when the
        session carries an un-refactored drift). b is (N,)/(N, k) for
        single plans, (B, N)/(B, N, k) for batched ones ((M,)/(M, k)
        for kind='qr' least-squares plans — x comes back with N rows);
        otherwise x comes back in b's shape. RHS widths are padded up to
        power-of-two buckets and sliced back, so a width mix compiles
        O(log) programs. The dispatch rides the session lock
        (uncontended RLock, ~100ns) so a concurrent drift update or
        escalation refactor can never show this solve half-swapped
        factors.

        `precision` routes THIS request through a served tier's program
        family (§33): None keeps the session's own serving config
        (bitwise pre-§33 for native sessions), a tier name dispatches
        that tier (factors derived lazily when it isn't the session's
        own), 'auto' starts at the session's sticky rung."""
        plan = self.plan
        b2, squeeze = self._rhs(b)
        nrhs = b2.shape[-1]
        nb = rank_bucket(nrhs)
        if nb != nrhs:
            pad = [(0, 0)] * (b2.ndim - 1) + [(0, nb - nrhs)]
            b2 = jnp.pad(b2, pad)
        if plan.mesh is not None:
            (b2,) = _shard_batch((b2,), plan.mesh)
        with self._lock:
            self._ensure_resident()
            tier = self._resolve_tier(precision)
            with profiler.region("serve.solve"):
                if self._upd is not None:
                    u = self._upd
                    sweeps = plan.key.refine + self.policy.refine
                    x = plan._update_solve_fn(u["kb"], nb, sweeps)(
                        self._factors, self._A0, u["Up"], u["Vp"],
                        u["Y"], u["Cinv"], b2)
                elif tier is None:
                    x = plan._solve_fn(nb)(self._factors, self._A, b2)
                else:
                    F = (self._factors if tier == self._served_tier
                         else self._tier_factor(tier))
                    x = plan._tier_solve_fn(tier, nb)(F, self._A0, b2)
            self.solves += 1
        if nb != nrhs:
            x = x[..., :nrhs]
        if squeeze:
            return x[..., 0]
        return x

    # ------------------------------------------------------------------ #
    # checked solves + escalation rungs (the resilience layer's surface)
    # ------------------------------------------------------------------ #

    def _rhs_bucketed(self, b):
        plan = self.plan
        b2, squeeze = self._rhs(b)
        nrhs = b2.shape[-1]
        nb = rank_bucket(nrhs)
        if nb != nrhs:
            pad = [(0, 0)] * (b2.ndim - 1) + [(0, nb - nrhs)]
            b2 = jnp.pad(b2, pad)
        if plan.mesh is not None:
            (b2,) = _shard_batch((b2,), plan.mesh)
        return b2, nb, nrhs, squeeze

    def _probe_row(self):
        """The session's cached probe row wA = w^T A0 (device-resident,
        like the factors; O(N^2) once per base, invalidated by
        refactors)."""
        with self._lock:
            self._ensure_resident()
            if self._probe is None:
                self._probe = self.plan._probe_fn()(self._A0)
            return self._probe

    def solve_checked(self, b, *, precision=None):  # hot-path
        """`solve` plus the fused finite/projected-residual health
        verdict, in the SAME dispatched program. Returns (x, verdict)
        with verdict a (2,) float32 device array
        [finite_flag, residual] — nothing here blocks; the engine's
        drain thread (or `resilience.evaluate`) reads the verdict with
        the answer. The answer keeps `solve`'s shape contract (bucket
        pad + slice, squeeze)."""
        plan = self.plan
        b2, nb, nrhs, squeeze = self._rhs_bucketed(b)
        with self._lock:
            self._ensure_resident()
            tier = self._resolve_tier(precision)
            wA = self._probe_row()
            with profiler.region("serve.solve"):
                if self._upd is not None:
                    u = self._upd
                    sweeps = plan.key.refine + self.policy.refine
                    x, verdict = plan._update_solve_health_fn(
                        u["kb"], nb, sweeps)(
                        self._factors, self._A0, u["Up"], u["Vp"],
                        u["Y"], u["Cinv"], wA, b2)
                elif tier is None:
                    x, verdict = plan._solve_health_fn(nb)(
                        self._factors, self._A0, wA, b2)
                else:
                    F = (self._factors if tier == self._served_tier
                         else self._tier_factor(tier))
                    x, verdict = plan._tier_solve_health_fn(tier, nb)(
                        F, self._A0, wA, b2)
            self.solves += 1
        if nb != nrhs:
            x = x[..., :nrhs]
        if squeeze:
            x = x[..., 0]
        return x, verdict

    def refine_checked(self, b, x):
        """One iterative-refinement sweep of a previous answer `x`
        against the CURRENT base factors, re-checked — escalation rung 2
        (`resilience.escalate`). `b` and `x` carry the same (bucketed)
        solve shapes; sessions with un-refactored drift must refactor
        first (rung 1 always precedes this one)."""
        plan = self.plan
        b2, nb, nrhs, squeeze = self._rhs_bucketed(b)
        x2 = jnp.asarray(x)
        if squeeze:
            x2 = x2[..., None]
        if nb != nrhs:
            pad = [(0, 0)] * (x2.ndim - 1) + [(0, nb - nrhs)]
            x2 = jnp.pad(x2, pad)
        if plan.mesh is not None:
            (x2,) = _shard_batch((x2,), plan.mesh)
        with self._lock:
            self._ensure_resident()
            if self._upd is not None:
                raise AssertionError(
                    "refine_checked rides the base factors — refactor() "
                    "the drifted session first (escalation rung order)")
            with profiler.region("serve.solve"):
                x2, verdict = plan._refine_fn(nb)(
                    self._factors, self._A0, self._probe_row(), x2, b2)
        if nb != nrhs:
            x2 = x2[..., :nrhs]
        if squeeze:
            x2 = x2[..., 0]
        return x2, verdict

    def refactor(self):
        """Force one true refactorization through the plan's CACHED
        factor program — escalation rung 1. Absorbs any accumulated
        drift into a fresh base (the `_refactor` path, donation and
        all); an un-drifted session re-runs the factor program on its
        resident base, replacing possibly-corrupt factors. Chainable."""
        with self._lock:
            self._ensure_resident()
            if self._upd is not None:
                u = self._upd
                k = u["k"]
                self._refactor(u["Up"][..., :k], u["Vp"][..., :k])
                return self
            with profiler.region("serve.refactor"):
                from conflux_tpu import resilience

                resilience.maybe_fault(None, "refresh")
                self._factors = None  # release before the factor dispatch
                self._factors = self._factor_base(self._A0)
                # possibly-corrupt derived factors die with the rung-1
                # rebuild — they'd be rebuilt from the same A0, but a
                # transient-corruption escalation must not trust them
                self._tier_factors = {}
            self.factorizations += 1
            self.refactors += 1
            self._gang_ver += 1  # the gang slot is stale; lazy re-sync
            self._ckpt_ver += 1
            return self

    # ------------------------------------------------------------------ #
    # incremental drift
    # ------------------------------------------------------------------ #

    def _check_uv(self, U, V):
        plan = self.plan
        if U.shape != V.shape:
            raise ValueError(f"U {U.shape} and V {V.shape} must agree")
        lead = (plan.B, plan.N) if plan.batched else (plan.N,)
        want_nd = len(lead) + 1
        if U.ndim != want_nd or U.shape[:-1] != lead:
            raise ValueError(
                f"update factors {U.shape}, session needs {lead} (+ rank "
                "axis)")
        if U.shape[-1] < 1:
            raise ValueError("update rank must be >= 1")

    def update(self, U, V, *, replace: bool = False):
        """Apply the rank-k drift A <- A + U V^H without refactoring.

        U, V are (N, k) for single plans, (B, N, k) for batched ones
        (k << N). Updates ACCUMULATE (rank adds) unless `replace=True`,
        which measures the drift from the current base factors instead —
        the steady-state "rank-k drift per request" traffic shape.
        Subsequent `solve` calls apply the base factors plus the k x k
        capacitance correction; the drift policy refactors through the
        plan's cached factor program once accumulated rank exceeds
        `policy.max_rank` or the capacitance conditioning exceeds
        `policy.cond_limit`. Returns self (chainable:
        `session.update(U, V).solve(b)`).
        """
        plan = self.plan
        if plan.key.kind == "qr":
            raise ValueError(
                "incremental (Woodbury) drift updates apply to square "
                "plans — a kind='qr' least-squares session re-factors "
                "on base change (the SMW identity corrects A^-1, not "
                "the pseudoinverse; DESIGN §33)")
        dtype = jnp.dtype(plan.key.dtype)
        U = jnp.asarray(U, dtype)
        V = jnp.asarray(V, dtype)
        self._check_uv(U, V)
        with self._lock, profiler.region("serve.update"):
            self._ensure_resident()
            if self._upd is not None:
                if replace:
                    # the superseded Woodbury state (Up/Vp/Y/Cinv) is dead
                    # the moment the drift is re-measured — drop it before
                    # the new dispatch so it never doubles peak memory
                    self._upd = None
                else:
                    k0 = self._upd["k"]
                    U = jnp.concatenate([self._upd["Up"][..., :k0], U],
                                        axis=-1)
                    V = jnp.concatenate([self._upd["Vp"][..., :k0], V],
                                        axis=-1)
                    # the concatenated copies carry the history now
                    self._upd = None
            k = U.shape[-1]
            if k > self.policy.resolved_max_rank(plan.N):
                self._refactor(U, V)
                return self
            kb = rank_bucket(k)
            if kb != k:
                pad = [(0, 0)] * (U.ndim - 1) + [(0, kb - k)]
                U, V = jnp.pad(U, pad), jnp.pad(V, pad)
            if plan.mesh is not None:
                U, V = _shard_batch((U, V), plan.mesh)
            Y, Cinv, cond1 = plan._update_fn(kb)(self._factors, U, V)
            # the scalar readback is deliberate (and why update() is
            # not a hot-path function): the drift policy's refactor
            # decision is host control flow
            cond = float(jnp.max(cond1))
            self.last_cond = cond
            if not (cond <= self.policy.cond_limit):  # catches NaN/inf too
                from conflux_tpu import resilience

                resilience.bump("cond_refactors")
                self._refactor(U, V)
                return self
            self._upd = {"k": k, "kb": kb, "Up": U, "Vp": V,
                         "Y": Y, "Cinv": Cinv}
            self.updates += 1
            self._gang_ver += 1  # the gang slot is stale; lazy re-sync
            self._ckpt_ver += 1
            if self._residency is not None:
                # footprint grew by the Woodbury state: refresh the
                # manager's byte gauge (nbytes under this held lock,
                # the gauge store under the manager's — the tier
                # layer's session->manager lock order)
                self._residency._note_bytes(self)
        return self

    def _refactor(self, Up, Vp):
        """Drift-policy trigger: materialize A0 + U V^H and pay one true
        refactorization through the plan's cached factor program; the
        session's base then absorbs the drift and the correction resets.
        Callers (`update`, `refactor`) already hold the session lock;
        the re-entrant acquire here keeps the swap atomic regardless."""
        plan = self.plan
        with self._lock, profiler.region("serve.refactor"):
            from conflux_tpu import resilience

            resilience.maybe_fault(None, "refresh")
            k = Up.shape[-1]
            kb = rank_bucket(k)
            if kb != k:  # zero columns leave A0 + U V^H unchanged
                pad = [(0, 0)] * (Up.ndim - 1) + [(0, kb - k)]
                Up, Vp = jnp.pad(Up, pad), jnp.pad(Vp, pad)
            if plan.mesh is not None:
                Up, Vp = _shard_batch((Up, Vp), plan.mesh)
            # the superseded drift state is dead the moment the new base
            # exists — drop it before dispatching, and donate the old base
            # once the session owns it, so the refactor peak holds one
            # resident base + one factor set, not two of each
            self._upd = None
            A_new = plan._refresh_fn(kb, donate=self._owns_base)(
                self._A0, Up, Vp)
            self._A0 = A_new
            self._probe = None  # wA was against the superseded base
            self._tier_factors = {}  # derived from the superseded base
            self._owns_base = True
            if self._A is not None:
                self._A = A_new
            self._factors = None  # release before the factor dispatch
            self._factors = self._factor_base(A_new)
            self.factorizations += 1
            self.refactors += 1
            self._gang_ver += 1  # the gang slot is stale; lazy re-sync
            self._ckpt_ver += 1
            if self._residency is not None:
                self._residency._note_bytes(self)
