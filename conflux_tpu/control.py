"""Closed-loop autotuning of the serve engine from live telemetry.

The serving stack's throughput comes from the same trade the 2.5D
algorithms make — spend a little buffering/latency/redundancy to buy far
fewer, larger device operations — but until now the knobs that price
that trade were static: `max_batch_delay` (how long a request waits for
company), the prewarmed width/stack/factor bucket sets (which coalesced
shapes are compile-free), `max_pending` (how much backlog admission
tolerates) and the health guards' sampling rates. `profiler.
serve_stats()` already measures everything a controller needs — queue
depth, coalesced means, pad waste, p50/p95/p99 — and real open-loop
traffic shifts (diurnal ramps, bursts, width-mix drift), so a knob set
that is right at 9am is wrong at noon.

:class:`AdaptiveController` closes the loop. It runs on its own daemon
thread inside a :class:`~conflux_tpu.engine.ServeEngine`
(``ServeEngine(controller=...)``), consumes WINDOWED deltas of the
engine/health/tier telemetry (`profiler.StatsWindow` — each tick sees
what changed, not lifetime averages that stop responding after the first
million requests), and retunes a declared knob set against a latency
SLO:

- **max_batch_delay** — hill-climbed: widen the window when the
  coalesced mean is low while the backlog is building (wider dispatches
  raise effective capacity), shrink it when the window p99 approaches
  the SLO or traffic is light (the window is then pure added latency).
- **max_pending / EngineSaturated.retry_after** — sized from the
  MEASURED drain rate: admission holds roughly what can drain inside
  the SLO, so under hard overload the completed requests' tail stays
  near the SLO instead of inheriting a mis-sized queue, and shed
  clients get a retry hint spaced at the actual completion rate
  (`ServeEngine._admit`; the static exponential guess remains the
  no-estimate fallback).
- **active bucket sets** — grown only through BACKGROUND prewarm: when
  the width cap keeps splitting chunks (`width_capped` pressure) the
  controller prewarms the next power-of-two bucket on the engine's
  recently-served sessions/plans and moves the cap only once
  `FactorPlan.bucket_ready` reports the program warm, so the steady
  state stays zero-compile by construction. Cold buckets (no hits for
  `retire_after` windows) are retired: their compiled programs are
  dropped through `FactorPlan.release_buckets` and the cap shrinks back
  to what traffic actually uses. The factor lane's batch buckets get
  the same treatment.
- **health guard sampling** — after `relax_health_after` consecutive
  windows with ZERO guard trips, the submit-time finite guard's sample
  shrinks and the exact staging guard thins to 1-in-`staging_stride`
  batches (detection is never lost — the device-side finite verdict
  and per-request isolation still backstop exactly; only the reporting
  point moves, see resilience.rhs_finite). ANY trip restores full
  guarding INSTANTLY, engine-side, on the tripping thread
  (`ServeEngine._restore_guards`) — the controller then just re-syncs
  its bookkeeping.

The controller is strictly advisory and strictly opt-in: every write
goes through the engine's validated, thread-safe :meth:`~conflux_tpu.
engine.ServeEngine.set_knobs`; a controller tick that throws is counted
and skipped (the serve path never depends on it); a dead or detached
controller simply freezes the knobs at their last values; and
``controller=None`` engines carry ZERO behavioral change — the
acceptance bar test_engine's bitwise assertions hold untouched.

    ctl = AdaptiveController(slo_p99_ms=25.0, interval=0.25)
    eng = ServeEngine(max_batch_delay=0.002, controller=ctl)
    ...traffic...
    eng.stats()["controller"]   # ticks, decisions, window, knobs

Decisions are recorded in a bounded log (`stats()['decisions_log']`),
each entry (t, knob, old, new, reason) — the ops-facing answer to "why
did p50 just change".
"""

from __future__ import annotations

import dataclasses
import json
import os
import threading
import time

from conflux_tpu import profiler
from conflux_tpu import qos as qos_mod
from conflux_tpu.resilience import bump
from conflux_tpu.update import rank_bucket

# the health counters whose window deltas count as "guard trips" — any
# nonzero sum vetoes (and reverts) guard relaxation
_TRIP_KEYS = (
    "rhs_rejects", "staging_isolations", "factor_rejects",
    "factor_isolations", "output_failures", "factor_unhealthy",
)


def _pow2_at_most(n: int) -> int:
    """Largest power of two <= n (n >= 1)."""
    p = 1
    while p * 2 <= n:
        p *= 2
    return p


# --------------------------------------------------------------------------- #
# persistent operating point (autotune.py-style rule rows on disk)
# --------------------------------------------------------------------------- #
#
# A restarted engine used to start at the cold constructor defaults and
# spend the controller's first dozen windows re-climbing to wherever
# yesterday's traffic had already settled. With
# `AdaptiveController(persist=True)` the controller dumps its current
# knob vector per REGIME to a small JSON beside the XLA cache dir and
# re-seeds it at `attach` — the same discipline as `autotune.py`'s rule
# table: strict row validation, most-recent-wins per regime, an env-var
# override for tests, and unreadable/invalid files degrade to the cold
# defaults (the store is advisory, never load-bearing).

_OP_VERSION = 1

# the knob subset a restart may safely re-seed: window/admission/QoS
# knobs apply instantly and can never put a compile on the serving
# path. Bucket caps (max_coalesce_width, max_factor_batch, max_stack)
# are deliberately EXCLUDED — growing them is only ever allowed behind
# the controller's prewarm gate, and a re-seeded cap would point at
# programs the restarted process has not compiled yet.
_SEED_KNOBS = ("max_batch_delay", "max_pending", "qos_contention")


def operating_point_path() -> str:
    """Where the operating-point rows live: beside the XLA cache dir
    (`~/.cache/conflux_tpu/operating_point.json` by default), or
    wherever `$CONFLUX_TPU_OPERATING_POINT` points (the test hook)."""
    p = os.environ.get("CONFLUX_TPU_OPERATING_POINT")
    if p:
        return p
    from conflux_tpu import cache

    return os.path.join(os.path.dirname(cache.default_cache_dir()),
                        "operating_point.json")


def _validate_op_row(row) -> bool:
    """One rule row: {'regime': str, 'knobs': dict, 'updated': str}.
    Unknown fields reject the row (the autotune.py strictness: a
    half-understood row is worse than a cold start)."""
    if not isinstance(row, dict) or set(row) != {"regime", "knobs",
                                                "updated"}:
        return False
    if not isinstance(row["regime"], str) or not row["regime"]:
        return False
    if not isinstance(row["updated"], str):
        return False
    k = row["knobs"]
    if not isinstance(k, dict):
        return False
    for key, v in k.items():
        if key == "qos_tier_delay":
            if not (isinstance(v, dict)
                    and all(t in qos_mod.TIERS for t in v)
                    and all(isinstance(x, (int, float)) and x >= 0
                            for x in v.values())):
                return False
        elif key not in _SEED_KNOBS \
                or not isinstance(v, (int, float)) \
                or isinstance(v, bool):
            return False
    return True


def load_operating_point(regime: str, path: str | None = None) -> dict:
    """The saved knob vector for `regime` ({} when absent/invalid —
    callers fall back to the cold defaults)."""
    path = operating_point_path() if path is None else path
    try:
        with open(path, encoding="utf-8") as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return {}
    if not isinstance(doc, dict) or doc.get("version") != _OP_VERSION \
            or not isinstance(doc.get("rows"), list):
        return {}
    for row in doc["rows"]:
        if _validate_op_row(row) and row["regime"] == regime:
            return dict(row["knobs"])
    return {}


def save_operating_point(regime: str, knobs: dict,
                         path: str | None = None) -> str:
    """Upsert `regime`'s row (read-modify-write, atomic tmp+rename so
    a crashed writer never leaves a torn table) and return the path."""
    path = operating_point_path() if path is None else path
    row = {"regime": regime,
           "knobs": {k: v for k, v in knobs.items()
                     if k in _SEED_KNOBS + ("qos_tier_delay",)
                     and v is not None},
           "updated": time.strftime("%Y-%m-%dT%H:%M:%S")}
    if not _validate_op_row(row):
        raise ValueError(f"unsaveable knob vector {knobs!r}")
    rows = []
    try:
        with open(path, encoding="utf-8") as f:
            doc = json.load(f)
        if isinstance(doc, dict) and doc.get("version") == _OP_VERSION:
            rows = [r for r in doc.get("rows", ())
                    if _validate_op_row(r) and r["regime"] != regime]
    except (OSError, ValueError):
        pass
    rows.append(row)
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump({"version": _OP_VERSION, "rows": rows}, f, indent=1)
    os.replace(tmp, path)
    return path


@dataclasses.dataclass(frozen=True)
class ControlLimits:
    """Hard bounds every controller move respects — the declared
    actuation envelope. The controller hill-climbs INSIDE this box; it
    never widens it, so an operator reading the limits knows the worst
    case of every knob regardless of what traffic does.

    min/max_batch_delay: the coalescing-window range (seconds).
    min/max_pending: the admission-bound range.
    max_coalesce_width / max_factor_batch: the widest buckets the
        controller may grow to (and therefore prewarm); growth past the
        engine's construction values happens only through the
        prewarm-gated path.
    relaxed_guard_sample: the submit-guard sample size while guards are
        relaxed (elements scanned per request; the strict policy's own
        value is the restore point).
    staging_stride: staging-guard thinning while relaxed (exact check
        runs on 1-in-stride batches).
    """

    min_batch_delay: float = 0.0
    max_batch_delay: float = 0.032
    min_pending: int = 32
    max_pending: int = 8192
    max_coalesce_width: int = 64
    max_factor_batch: int = 64
    max_stack: int = 16
    relaxed_guard_sample: int = 256
    staging_stride: int = 8


class AdaptiveController:
    """The feedback controller: windowed telemetry in, validated knob
    moves out (DESIGN §24 has the full telemetry→decision→actuation
    table).

    slo_p99_ms: the latency objective. The controller treats it as a
        ceiling to stay under, not a target to fill: knobs that buy
        throughput (wider windows, deeper admission) grow only while
        the window p99 keeps `headroom` of slack.
    interval: seconds between control ticks (each tick one
        `StatsWindow.delta()`).
    limits: a :class:`ControlLimits` actuation envelope.
    headroom: fraction of the SLO at which p99 is "approaching" —
        shrink-the-window territory.
    coalesce_target: mean requests/batch below which the window is
        considered under-coalescing (the widen signal, gated on a
        building backlog).
    delay_grow / delay_shrink: multiplicative hill-climb steps for
        `max_batch_delay`; `delay_floor_step` seeds the climb out of a
        zero window.
    pending_slack: admission sizes to `drain_rate * slo * slack` —
        >1 keeps the pipe full, large values re-grow the mis-sized
        queues the sizing exists to prevent.
    pending_deadband: relative change below which max_pending is left
        alone (actuation hysteresis).
    ema: weight of the newest window in the drain-rate estimate.
    grow_after: consecutive windows of width-cap pressure before a
        bucket grows (debounce — one burst must not inflate the
        compiled-program set).
    retire_after: consecutive hit-less windows before a bucket is
        retired. Retirement drops compiled programs; a later touch
        re-traces, so this defaults LONG.
    relax_health_after: consecutive trip-free windows before guard
        sampling relaxes.
    min_window_samples: latency samples a window needs before its p99
        is trusted to steer the delay knob.
    persist: opt into the on-disk operating point (see
        :func:`operating_point_path`): `attach` re-seeds the safe knob
        subset from the saved row for `regime`, and every
        `persist_every`-th tick (and `close`) dumps the current vector
        back. Default off — a `persist=False` controller touches no
        files, exactly the pre-§30 behavior.
    regime: the operating-point row key (defaults to a key derived
        from the SLO and the engine's lane count at attach — restarts
        of the same deployment shape share a row; distinct shapes
        never cross-seed).
    """

    def __init__(self, *, slo_p99_ms: float = 25.0,
                 interval: float = 0.25,
                 limits: ControlLimits | None = None,
                 headroom: float = 0.8,
                 coalesce_target: float = 2.0,
                 delay_grow: float = 1.6,
                 delay_shrink: float = 0.5,
                 delay_floor_step: float = 5e-4,
                 pending_slack: float = 1.5,
                 pending_deadband: float = 0.25,
                 ema: float = 0.5,
                 grow_after: int = 2,
                 retire_after: int = 120,
                 relax_health_after: int = 20,
                 stack_after: int = 2,
                 unstack_after: int = 30,
                 min_window_samples: int = 8,
                 decision_log: int = 256,
                 persist: bool = False,
                 regime: str | None = None,
                 persist_every: int = 40):
        if slo_p99_ms <= 0 or interval <= 0:
            raise ValueError("slo_p99_ms and interval must be > 0")
        if not 0 < headroom <= 1:
            raise ValueError("headroom must be in (0, 1]")
        if delay_grow <= 1 or not 0 < delay_shrink < 1:
            raise ValueError("need delay_grow > 1 and 0 < delay_shrink < 1")
        self.slo_p99_ms = float(slo_p99_ms)
        self.interval = float(interval)
        self.limits = ControlLimits() if limits is None else limits
        self.headroom = float(headroom)
        self.coalesce_target = float(coalesce_target)
        self.delay_grow = float(delay_grow)
        self.delay_shrink = float(delay_shrink)
        self.delay_floor_step = float(delay_floor_step)
        self.pending_slack = float(pending_slack)
        self.pending_deadband = float(pending_deadband)
        self.ema = float(ema)
        self.grow_after = int(grow_after)
        self.retire_after = int(retire_after)
        self.relax_health_after = int(relax_health_after)
        self.stack_after = int(stack_after)
        self.unstack_after = int(unstack_after)
        self.min_window_samples = int(min_window_samples)

        # cross-thread state: step() runs on the controller thread,
        # stats() on any caller's — everything below is guarded
        self._lock = threading.Lock()
        self._engine_ref = None         # guarded-by: _lock (weakref)
        self._window = None             # guarded-by: _lock
        self._ticks = 0                 # guarded-by: _lock
        self._errors = 0                # guarded-by: _lock
        self._decisions = 0             # guarded-by: _lock
        self._log: list = []            # guarded-by: _lock (bounded)
        self._log_cap = int(decision_log)
        self._last_window: dict = {}    # guarded-by: _lock
        self._drain_rate: float | None = None  # guarded-by: _lock
        # decision state machines (controller-thread only, but kept
        # under the lock so stats() reads a consistent picture)
        self._widen_pressure = 0        # guarded-by: _lock
        self._cap_pressure = 0          # guarded-by: _lock
        self._fcap_pressure = 0         # guarded-by: _lock
        self._calm_windows = 0          # guarded-by: _lock
        self._relaxed = False           # guarded-by: _lock
        self._strict_health = None      # guarded-by: _lock
        # bucket -> consecutive hit-less windows (solve / factor lanes)
        self._cold: dict = {}           # guarded-by: _lock
        self._fcold: dict = {}          # guarded-by: _lock
        # in-flight background prewarm: (target_bucket, Thread) or None
        self._width_prewarm = None      # guarded-by: _lock
        self._fbatch_prewarm = None     # guarded-by: _lock
        # gang-stacking steering state (DESIGN §26): consecutive
        # windows of missed stacking opportunity / of an idle enabled
        # gang path, and the in-flight stacked-bucket prewarm
        # ((max_stack target, width, Thread) or None)
        self._stack_pressure = 0        # guarded-by: _lock
        self._stack_idle = 0            # guarded-by: _lock
        self._stack_prewarm = None      # guarded-by: _lock
        # per-lane delay tuning state (multi-lane engines, DESIGN §25):
        # the previous tick's per-lane counter rows and each lane's
        # debounced widen-pressure count
        self._lane_prev: dict = {}      # guarded-by: _lock
        self._lane_widen: dict = {}     # guarded-by: _lock
        # multi-tenant QoS steering state (DESIGN §30): one per-class
        # StatsWindow (key -> window) opened lazily once the engine
        # reports QoS traffic, plus the debounce counters for the
        # contention / batch-stretch knobs
        self._qos_windows: dict = {}    # guarded-by: _lock
        self._qos_hot = 0               # guarded-by: _lock
        self._qos_calm = 0              # guarded-by: _lock
        self._qos_batch_pressure = 0    # guarded-by: _lock
        self._qos_batch_idle = 0        # guarded-by: _lock
        # persistent operating point (DESIGN §30): the regime row this
        # controller seeds from / dumps to, or None when persist=False
        self.persist = bool(persist)
        self._regime = regime           # resolved at attach when None
        self._persist_every = max(1, int(persist_every))
        self._reseeded: dict = {}       # guarded-by: _lock (last seed)

        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # ------------------------------------------------------------------ #
    # lifecycle (engine start/close own these; tests drive step() bare)
    # ------------------------------------------------------------------ #

    def attach(self, engine) -> "AdaptiveController":
        """Bind to an engine (weakly — the controller must never keep a
        dead engine alive) and prime the telemetry window. Called by
        ``ServeEngine(controller=...)``; tests may attach manually and
        drive :meth:`step` without ever starting the thread."""
        import weakref

        with self._lock:
            if self._engine_ref is not None and self._engine_ref() is not None:
                raise RuntimeError("controller is already attached — one "
                                   "controller steers one engine")
            self._engine_ref = weakref.ref(engine)
            self._window = profiler.StatsWindow(engine)
            self._strict_health = engine.health
            if self._regime is None:
                # same deployment shape -> same row; distinct shapes
                # (different SLO or lane fan-out) never cross-seed
                self._regime = (f"slo{self.slo_p99_ms:g}"
                                f"-l{max(1, len(engine._lanes))}")
        if self.persist:
            self._reseed(engine)
        return self

    def _reseed(self, engine) -> None:
        """Apply the saved operating point for this regime (if any),
        clamped to the limits envelope so a stale or hand-edited row
        can never steer outside what the live controller would."""
        row = load_operating_point(self._regime)
        if not row:
            return
        lim = self.limits
        seed: dict = {}
        if "max_batch_delay" in row:
            seed["max_batch_delay"] = min(
                lim.max_batch_delay,
                max(lim.min_batch_delay, float(row["max_batch_delay"])))
        if "max_pending" in row:
            seed["max_pending"] = min(
                lim.max_pending,
                max(lim.min_pending, int(row["max_pending"])))
        if "qos_contention" in row:
            seed["qos_contention"] = min(
                1.0, max(0.05, float(row["qos_contention"])))
        if "qos_tier_delay" in row:
            seed["qos_tier_delay"] = {
                t: min(lim.max_batch_delay, float(v))
                for t, v in row["qos_tier_delay"].items()}
        if not seed:
            return
        try:
            engine.set_knobs(**seed)
        except Exception:  # noqa: BLE001 — a bad row must not kill attach
            with self._lock:
                self._errors += 1
            return
        with self._lock:
            self._reseeded = seed
        self._record("operating_point", None, seed,
                     f"re-seeded regime {self._regime!r} from "
                     f"{operating_point_path()}")

    def _persist_tick(self, eng, final: bool = False) -> None:
        """Dump the current knob vector for this regime — every
        `persist_every`-th tick and once at close."""
        if not self.persist or self._regime is None:
            return
        with self._lock:
            due = final or (self._ticks % self._persist_every == 0)
        if not due:
            return
        try:
            save_operating_point(self._regime, eng.knobs())
        except Exception:  # noqa: BLE001 — persistence is best-effort
            with self._lock:
                self._errors += 1

    def start(self) -> None:
        """Spawn the control-loop daemon thread (idempotent)."""
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="serve-engine-controller", daemon=True)
        self._thread.start()

    def close(self, timeout: float | None = 5.0) -> None:
        """Stop the control loop and join it (idempotent). The engine's
        close() calls this before tearing down the workers; the knobs
        stay wherever the last tick left them."""
        self._stop.set()
        t = self._thread
        if t is not None and t.is_alive():
            t.join(timeout)
        if self.persist:
            with self._lock:
                ref = self._engine_ref
            eng = None if ref is None else ref()
            if eng is not None:
                self._persist_tick(eng, final=True)

    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            with self._lock:
                ref = self._engine_ref
            eng = None if ref is None else ref()
            if eng is None or eng._closed:
                return  # the watchdog tie-in: a closed engine ends us
            try:
                self.step()
            except Exception:  # noqa: BLE001 — the controller is advisory
                with self._lock:
                    self._errors += 1

    # ------------------------------------------------------------------ #
    # the control tick
    # ------------------------------------------------------------------ #

    def step(self) -> dict | None:
        """One control tick: take the telemetry window, run every
        decision block, actuate through `engine.set_knobs`. Public so
        tests and benches can drive the loop deterministically (no
        thread, no timing). Returns the window it acted on (None when
        the engine is gone)."""
        with self._lock:
            ref = self._engine_ref
            window = self._window
        eng = None if ref is None else ref()
        if eng is None or window is None:
            return None
        d = window.delta()
        with self._lock:
            self._ticks += 1
            self._last_window = d
        e = d["engine"]
        self._decide_drain_rate(eng, d, e)
        self._decide_pending(eng, d, e)
        self._decide_delay(eng, d, e)
        self._decide_lane_delays(eng, d, e)
        self._decide_widths(eng, d, e)
        self._decide_factor_batches(eng, d, e)
        self._decide_stacking(eng, d, e)
        self._decide_health(eng, d, e)
        self._decide_qos(eng, d, e)
        self._persist_tick(eng)
        return d

    def _record(self, knob: str, old, new, reason: str) -> None:
        with self._lock:
            self._decisions += 1
            self._log.append((time.perf_counter(), knob, old, new, reason))
            if len(self._log) > self._log_cap:
                del self._log[: len(self._log) - self._log_cap]

    # -- drain rate (feeds retry_after and the admission sizing) -------- #

    def _decide_drain_rate(self, eng, d, e) -> None:
        if not e["completed"] or d["seconds"] <= 0:
            return  # nothing drained: keep the last estimate
        rate = e["completed"] / d["seconds"]
        with self._lock:
            prev = self._drain_rate
            rate = (rate if prev is None
                    else self.ema * rate + (1 - self.ema) * prev)
            self._drain_rate = rate
        eng.set_knobs(drain_rate=rate)

    # -- admission bound: hold what can drain inside the SLO ------------ #

    def _decide_pending(self, eng, d, e) -> None:
        with self._lock:
            rate = self._drain_rate
        if rate is None or rate <= 0:
            return
        lim = self.limits
        want = int(rate * (self.slo_p99_ms * 1e-3) * self.pending_slack)
        want = max(lim.min_pending, min(lim.max_pending, want))
        cur = eng.max_pending
        if abs(want - cur) <= self.pending_deadband * cur:
            return  # hysteresis: don't thrash the bound over noise
        eng.set_knobs(max_pending=want)
        self._record(
            "max_pending", cur, want,
            f"drain {rate:.0f}/s x SLO {self.slo_p99_ms:.0f}ms x "
            f"slack {self.pending_slack:g} — admission holds what can "
            "drain inside the SLO")

    # -- batch-delay hill climb ----------------------------------------- #

    def _decide_delay(self, eng, d, e) -> None:
        lim = self.limits
        cur = eng.max_batch_delay
        have_p99 = e["latency_samples"] >= self.min_window_samples
        p99 = e["latency_p99_ms"]
        if have_p99 and p99 >= self.headroom * self.slo_p99_ms:
            # p99 approaching the SLO: the window is latency we can
            # refund — shrink it first (cheapest reversible lever)
            new = max(lim.min_batch_delay, cur * self.delay_shrink)
            if new < self.delay_floor_step / 4:
                new = lim.min_batch_delay  # snap out of the decay tail
            if cur > lim.min_batch_delay and new < cur:
                eng.set_knobs(max_batch_delay=new)
                self._record("max_batch_delay", cur, new,
                             f"window p99 {p99:.1f}ms >= "
                             f"{self.headroom:.0%} of SLO "
                             f"{self.slo_p99_ms:.0f}ms — shrink")
            return
        # "backlog building" must mean it, not a 2-deep transient: a
        # busy-but-stable regime leaves a few requests in flight at any
        # instant, and widening the window there trades p50/p99 for
        # nothing (the over-eager version of this test cost the bench's
        # ramp tail ~60% p99). Require either a meaningful fraction of
        # the window's arrivals left unserved, or a queue deep relative
        # to the admission bound.
        backlog_rising = (
            e["backlog_delta"] > max(2.0, 0.05 * e["requests"])
            or e["pending"] > 0.5 * eng.max_pending)
        under_coalesced = (e["batches"] > 0
                           and e["coalesced_mean"] < self.coalesce_target)
        with self._lock:
            if under_coalesced and backlog_rising:
                self._widen_pressure += 1
            else:
                self._widen_pressure = 0
            widen = self._widen_pressure >= 2
        if widen:
            # demand outpaces narrow dispatches for two consecutive
            # windows (one Poisson clump must not widen the window —
            # a transient costs every later request the full delay):
            # widen so each dispatch amortizes over more requests
            new = min(lim.max_batch_delay,
                      max(cur * self.delay_grow,
                          self.delay_floor_step))
            if new > cur:
                eng.set_knobs(max_batch_delay=new)
                self._record(
                    "max_batch_delay", cur, new,
                    f"coalesced mean {e['coalesced_mean']:.1f} < "
                    f"{self.coalesce_target:g} with backlog "
                    f"{e['backlog_delta']:+d} — widen")
            return
        if (e["requests"] and not backlog_rising
                and e["coalesced_mean"] <= 1.5
                and cur > lim.min_batch_delay):
            # light traffic arriving alone: the window buys nothing and
            # costs its full length in p50 — decay it
            new = max(lim.min_batch_delay, cur * self.delay_shrink)
            if new < self.delay_floor_step / 4:
                new = lim.min_batch_delay  # snap out of the decay tail
            if new < cur:
                eng.set_knobs(max_batch_delay=new)
                self._record("max_batch_delay", cur, new,
                             "light solo traffic — the window is pure "
                             "added latency; decay")

    # -- per-lane batch-delay trim (mesh-sharded fleets, DESIGN §25) ---- #

    def _decide_lane_delays(self, eng, d, e) -> None:
        """Tune each lane's coalescing window INDEPENDENTLY on a
        multi-lane engine: the fleet's devices see different traffic
        (hot sessions pin to one lane), so the engine-wide window that
        `_decide_delay` hill-climbs is only the default — a lane whose
        own dispatches stay narrow while ITS queue builds widens its
        override (debounced two windows, like the global climb), and a
        lane coalescing fine on solo traffic decays back toward the
        engine-wide value. Writes ride the same `set_knobs` rails
        (`lane=` scope), inside the same `ControlLimits` envelope."""
        lanes = eng.counters().get("lanes", ())
        if len(lanes) < 2:
            return
        lim = self.limits
        base = eng.max_batch_delay
        with self._lock:
            prev = self._lane_prev
            self._lane_prev = {ln["lane"]: ln for ln in lanes}
        for ln in lanes:
            i = ln["lane"]
            if ln.get("dead"):
                continue
            p = prev.get(i, {})
            batches = ln["batches"] - p.get("batches", 0)
            coalesced = (ln["coalesced_requests"]
                         - p.get("coalesced_requests", 0))
            mean = coalesced / batches if batches else 0.0
            depth = ln.get("queue_depth", 0)
            cur = ln.get("delay", base)
            under = (batches > 0 and mean < self.coalesce_target
                     and depth > 1)
            with self._lock:
                n = self._lane_widen.get(i, 0) + 1 if under else 0
                self._lane_widen[i] = n
            if n >= 2:
                new = min(lim.max_batch_delay,
                          max(cur * self.delay_grow,
                              self.delay_floor_step))
                if new > cur:
                    eng.set_knobs(lane=i, max_batch_delay=new)
                    self._record(
                        f"lane{i}.max_batch_delay", cur, new,
                        f"lane {i} coalesced mean {mean:.1f} < "
                        f"{self.coalesce_target:g} with queue depth "
                        f"{depth} — widen this lane only")
                continue
            if (batches > 0 and depth == 0 and mean <= 1.5
                    and cur > base):
                # solo traffic on an over-widened lane: decay its
                # override toward the engine-wide default
                new = max(base, cur * self.delay_shrink)
                eng.set_knobs(lane=i, max_batch_delay=new)
                self._record(
                    f"lane{i}.max_batch_delay", cur, new,
                    f"lane {i} light solo traffic — decay toward the "
                    f"engine-wide window {base * 1e3:.1f}ms")

    # -- bucket growth (prewarm-gated) + retirement --------------------- #

    def _decide_widths(self, eng, d, e) -> None:
        lim = self.limits
        cur = eng.max_coalesce_width
        with self._lock:
            pre = self._width_prewarm
        # 1. an in-flight growth completes only when every active plan's
        # target bucket is warm — the knob NEVER moves onto a cold
        # program (a failed prewarm just drops the attempt)
        if pre is not None:
            target, thread = pre
            if thread.is_alive():
                return  # still compiling in the background
            sessions, _plans = eng.active_targets()
            checked = eng.health is not None and eng.health.check_output
            ready = [s.plan.bucket_ready(width=target, checked=checked)
                     for s in sessions]
            with self._lock:
                self._width_prewarm = None
            if ready and all(ready) and target > eng.max_coalesce_width:
                eng.set_knobs(max_coalesce_width=target)
                self._record("max_coalesce_width", cur, target,
                             f"bucket {target} prewarmed on "
                             f"{len(ready)} session(s) — cap grows "
                             "onto warm programs only")
            return
        # 2. growth pressure: the cap keeps splitting chunks
        with self._lock:
            if e.get("width_capped", 0) > 0:
                self._cap_pressure += 1
            else:
                self._cap_pressure = 0
            pressure = self._cap_pressure
        have_p99 = e["latency_samples"] >= self.min_window_samples
        p99_ok = (not have_p99
                  or e["latency_p99_ms"] < self.headroom * self.slo_p99_ms)
        if pressure >= self.grow_after and p99_ok \
                and cur < lim.max_coalesce_width:
            target = min(lim.max_coalesce_width, 2 * _pow2_at_most(cur))
            if target > cur:
                self._launch_width_prewarm(eng, target)
            return
        # 3. retirement: buckets with a long zero-hit history drop
        # their compiled programs and the cap shrinks to what traffic
        # actually uses
        self._retire_widths(eng, d, e)

    def _launch_width_prewarm(self, eng, target: int) -> None:
        sessions, _plans = eng.active_targets()
        if not sessions:
            return  # nothing served yet — nothing to warm against
        # one representative session per plan (the program cache is
        # per-plan; any session of it warms the bucket)
        per_plan: dict = {}
        for s in sessions:
            per_plan.setdefault(id(s.plan), s)

        def run():
            for s in per_plan.values():
                eng.prewarm(s, widths=(target,))

        t = threading.Thread(target=run, daemon=True,
                             name="serve-engine-controller-prewarm")
        with self._lock:
            self._width_prewarm = (target, t)
        t.start()
        self._record("prewarm", None, target,
                     f"width cap pressure: background-prewarming "
                     f"bucket {target} on {len(per_plan)} plan(s) "
                     "before any cap move")

    def _retire_widths(self, eng, d, e) -> None:
        hits = d.get("bucket_hits", {})
        with self._lock:
            seen = set(self._cold) | set(hits)
            for b in seen:
                self._cold[b] = 0 if hits.get(b, 0) else \
                    self._cold.get(b, 0) + 1
            cold = sorted(b for b, n in self._cold.items()
                          if n >= self.retire_after and b > 1)
            hot = [b for b, n in self._cold.items()
                   if n < self.retire_after]
        if not cold:
            return
        sessions, plans = eng.active_targets()
        all_plans = {id(p): p for p in plans}
        for s in sessions:
            all_plans.setdefault(id(s.plan), s.plan)
        dropped = 0
        for p in all_plans.values():
            dropped += p.release_buckets(widths=cold)
        cur = eng.max_coalesce_width
        new_cap = max([1] + hot)
        if new_cap < cur:
            eng.set_knobs(max_coalesce_width=new_cap)
        with self._lock:
            for b in cold:
                self._cold.pop(b, None)
        self._record(
            "release_widths", cur,
            new_cap if new_cap < cur else cur,
            f"buckets {cold} cold for {self.retire_after} windows — "
            f"released {dropped} compiled program(s)"
            + (f", cap {cur} -> {new_cap}" if new_cap < cur else ""))

    def _decide_factor_batches(self, eng, d, e) -> None:
        lim = self.limits
        cur = eng.max_factor_batch
        with self._lock:
            pre = self._fbatch_prewarm
        if pre is not None:
            target, thread = pre
            if thread.is_alive():
                return
            _sessions, plans = eng.active_targets()
            checked = eng.health is not None and eng.health.check_output
            ready = [p.bucket_ready(factor_batch=target, checked=checked)
                     for p in plans]
            with self._lock:
                self._fbatch_prewarm = None
            if ready and all(ready) and target > eng.max_factor_batch:
                eng.set_knobs(max_factor_batch=target)
                self._record("max_factor_batch", cur, target,
                             f"factor bucket {target} prewarmed on "
                             f"{len(ready)} plan(s)")
            return
        # growth pressure: factor batches keep filling the cap while
        # cold-start work queues behind them
        full = (e["factor_batches"] > 0
                and e["factor_coalesced_mean"] >= 0.9 * cur)
        with self._lock:
            self._fcap_pressure = self._fcap_pressure + 1 if full else 0
            pressure = self._fcap_pressure
        if pressure >= self.grow_after and cur < lim.max_factor_batch:
            _sessions, plans = eng.active_targets()
            if plans:
                target = min(lim.max_factor_batch, 2 * cur)

                def run():
                    for p in plans:
                        eng.prewarm(p, widths=(),
                                    factor_batches=(target,))

                t = threading.Thread(
                    target=run, daemon=True,
                    name="serve-engine-controller-prewarm")
                with self._lock:
                    self._fbatch_prewarm = (target, t)
                t.start()
                self._record("prewarm", None, target,
                             f"factor cap pressure: background-"
                             f"prewarming batch bucket {target}")
            return
        # retirement (never bucket 1 — plan.factor's own path)
        hits = d.get("factor_bucket_hits", {})
        with self._lock:
            for b in set(self._fcold) | set(hits):
                self._fcold[b] = 0 if hits.get(b, 0) else \
                    self._fcold.get(b, 0) + 1
            cold = sorted(b for b, n in self._fcold.items()
                          if n >= self.retire_after and b > 1)
        if not cold:
            return
        _sessions, plans = eng.active_targets()
        dropped = sum(p.release_buckets(factor_batches=cold)
                      for p in plans)
        with self._lock:
            for b in cold:
                self._fcold.pop(b, None)
        if dropped:
            self._record("release_factor_batches", None, cold,
                         f"factor buckets {cold} cold for "
                         f"{self.retire_after} windows — released "
                         f"{dropped} program(s)")

    # -- gang stacking: enable on missed opportunity, prewarm-gated ----- #

    def _decide_stacking(self, eng, d, e) -> None:
        """Steer `stack_sessions` / `max_stack` (DESIGN §26): with
        stacking OFF the engine counts, per window, the same-plan
        sessions it dispatched solo that a gang would have stacked
        (`gang_opportunity`); sustained opportunity prewarms the
        stacked bucket for the traffic's dominant width on every
        active single-system plan (BACKGROUND thread) and flips the
        knob only once `FactorPlan.bucket_ready(stack=...)` reports
        every program warm — the same prewarm-gated discipline as
        every other bucket move, so the switch itself never puts a
        compile on the serving path. With stacking ON, sustained
        windows of dispatches with ZERO stacked batches mean the
        fleet stopped offering pairs — disable, refunding the (tiny)
        per-window grouping work."""
        lim = self.limits
        with self._lock:
            pre = self._stack_prewarm
        if pre is not None:
            target, wb, thread = pre
            if thread.is_alive():
                return
            sessions, _plans = eng.active_targets()
            checked = eng.health is not None and eng.health.check_output
            ready = [s.plan.bucket_ready(stack=(target, wb),
                                         checked=checked)
                     for s in sessions
                     if not s.plan.batched and s.plan.mesh is None]
            with self._lock:
                self._stack_prewarm = None
            if ready and all(ready) and not eng.stack_sessions:
                eng.set_knobs(stack_sessions=True, max_stack=target)
                self._record(
                    "stack_sessions", False, target,
                    f"stacked bucket ({target}, {wb}) prewarmed on "
                    f"{len(ready)} session(s) — gang stacking enabled "
                    "onto warm programs only")
            return
        opp = e.get("gang_opportunity", 0)
        if not eng.stack_sessions:
            with self._lock:
                self._stack_pressure = (self._stack_pressure + 1
                                        if opp >= 2 else 0)
                pressure = self._stack_pressure
            if pressure < self.stack_after:
                return
            sessions, _plans = eng.active_targets()
            targets = {}
            for s in sessions:
                if not s.plan.batched and s.plan.mesh is None:
                    targets.setdefault(id(s.plan), s)
            if not targets:
                return
            target = max(2, min(_pow2_at_most(lim.max_stack),
                                rank_bucket(max(2, opp))))
            hits = d.get("bucket_hits", {})
            wb = max(hits, key=hits.get) if hits else 1
            reps = list(targets.values())

            def run():
                for s in reps:
                    eng.prewarm(s, widths=(wb,), stacks=(target,))

            t = threading.Thread(target=run, daemon=True,
                                 name="serve-engine-controller-prewarm")
            with self._lock:
                self._stack_pressure = 0
                self._stack_prewarm = (target, wb, t)
            t.start()
            self._record(
                "prewarm", None, (target, wb),
                f"{opp} stackable session(s) dispatched solo this "
                f"window: background-prewarming the ({target}, {wb}) "
                "stacked bucket before any knob move")
            return
        # stacking is on: watch for a fleet that stopped pairing up
        idle = (e["batches"] > 0 and e.get("gang_batches", 0) == 0)
        with self._lock:
            self._stack_idle = self._stack_idle + 1 if idle else 0
            idle_n = self._stack_idle
        if idle_n >= self.unstack_after:
            eng.set_knobs(stack_sessions=False)
            with self._lock:
                self._stack_idle = 0
            self._record(
                "stack_sessions", True, False,
                f"{idle_n} consecutive windows dispatched without a "
                "single stacked batch — gang stacking disabled (gangs "
                "keep their resident state for a later re-enable)")

    # -- guard sampling: back off on silence, restore on any trip ------- #

    def _decide_health(self, eng, d, e) -> None:
        with self._lock:
            strict = self._strict_health
        if strict is None or not strict.check_rhs:
            return  # nothing to relax
        trips = sum(d["health"].get(k, 0) for k in _TRIP_KEYS)
        with self._lock:
            if trips:
                self._calm_windows = 0
                was_relaxed = self._relaxed
                self._relaxed = False
            else:
                self._calm_windows += 1
                was_relaxed = self._relaxed
        if trips:
            # the ENGINE already restored strict guarding on the
            # tripping thread (`_restore_guards`); this just re-syncs
            # the controller's bookkeeping and records the event
            if was_relaxed:
                eng.set_knobs(health=strict, staging_stride=1)
                self._record("health", "relaxed", "strict",
                             f"{trips} guard trip(s) in the window — "
                             "full guarding restored (engine-side, "
                             "instantly; this records it)")
            return
        with self._lock:
            calm = self._calm_windows
            relaxed = self._relaxed
        if relaxed or calm < self.relax_health_after:
            return
        lim = self.limits
        sample = strict.submit_guard_sample
        relaxed_sample = (lim.relaxed_guard_sample if sample is None
                          else min(sample, lim.relaxed_guard_sample))
        relaxed_policy = dataclasses.replace(
            strict, submit_guard_sample=relaxed_sample)
        eng.set_knobs(health=relaxed_policy,
                      staging_stride=lim.staging_stride)
        with self._lock:
            self._relaxed = True
        self._record(
            "health", "strict", "relaxed",
            f"{calm} trip-free windows — submit guard sample -> "
            f"{relaxed_sample}, staging guard 1-in-"
            f"{lim.staging_stride} batches (device verdict still "
            "exact; any trip restores instantly)")

    # -- per-class QoS steering (DESIGN §30) ---------------------------- #

    def _decide_qos(self, eng, d, e) -> None:
        """Steer the two QoS knobs off per-class telemetry windows:

        * SLO pressure: any latency-SLO class whose windowed p99 runs
          inside `headroom` of its SLO for two consecutive ticks means
          bulk work is crowding it out — halve `qos_contention` so the
          fair-share ledger bites earlier; relax it back (x1.5, cap
          0.5) after `relax_health_after` comfortable windows.
        * Batch stretch: batch-tier traffic that still coalesces under
          `coalesce_target` can afford to wait longer — grow the
          `batch` tier delay override; clear it after `unstack_after`
          batch-idle windows.
        """
        qc = eng.counters().get("qos")
        if qc is None:
            return  # no classified traffic yet: nothing to steer
        with self._lock:
            for key in qc.get("classes", {}):
                if key not in self._qos_windows:
                    # same lock shape as attach(): a per-class window
                    # constructed under the controller lock takes the
                    # engine lock once to snapshot
                    self._qos_windows[key] = profiler.StatsWindow(
                        eng, qos_class=key)
            windows = dict(self._qos_windows)
        hot = comfortable = False
        batch_busy = batch_under = False
        slo_by_key = {k: row.get("slo_ms")
                      for k, row in qc.get("classes", {}).items()}
        tier_by_key = {k: row.get("tier")
                       for k, row in qc.get("classes", {}).items()}
        for key, w in windows.items():
            we = w.delta()["engine"]
            slo_ms = slo_by_key.get(key)
            if (slo_ms is not None
                    and we["latency_samples"] >= self.min_window_samples):
                p99 = we["latency_p99_ms"]
                if p99 >= self.headroom * slo_ms:
                    hot = True
                elif p99 < 0.5 * self.headroom * slo_ms:
                    comfortable = True
            if tier_by_key.get(key) == "batch" and we["qos_requests"]:
                batch_busy = True
                if (e["coalesced_mean"]
                        and e["coalesced_mean"] < self.coalesce_target):
                    batch_under = True
        knobs = eng.knobs()
        contention = knobs.get("qos_contention", 0.5)
        tier_delay = knobs.get("qos_tier_delay") or {}
        with self._lock:
            self._qos_hot = self._qos_hot + 1 if hot else 0
            self._qos_calm = (0 if hot or not comfortable
                              else self._qos_calm + 1)
            self._qos_batch_pressure = (
                self._qos_batch_pressure + 1 if batch_under else 0)
            self._qos_batch_idle = (
                0 if batch_busy else self._qos_batch_idle + 1)
            hot_n, calm_n = self._qos_hot, self._qos_calm
            bp, bi = self._qos_batch_pressure, self._qos_batch_idle
        if hot_n >= 2 and contention > 0.1:
            new = max(0.1, 0.5 * contention)
            eng.set_knobs(qos_contention=new)
            self._record(
                "qos_contention", contention, new,
                f"{hot_n} windows with a latency class p99 inside "
                f"{self.headroom:g}x of its SLO — the fair-share "
                "ledger now bites earlier")
            with self._lock:
                self._qos_hot = 0
        elif calm_n >= self.relax_health_after and contention < 0.5:
            new = min(0.5, 1.5 * contention)
            eng.set_knobs(qos_contention=new)
            self._record(
                "qos_contention", contention, new,
                f"{calm_n} comfortable windows — admission pressure "
                "relaxed toward the default")
            with self._lock:
                self._qos_calm = 0
        cur_batch = tier_delay.get("batch")
        if bp >= self.grow_after:
            base = (cur_batch if cur_batch is not None else min(
                eng.max_batch_delay * qos_mod.BATCH_STRETCH,
                qos_mod.MAX_TIER_DELAY))
            new_delay = min(self.limits.max_batch_delay,
                            max(base * self.delay_grow,
                                base + self.delay_floor_step))
            if new_delay > (cur_batch or 0.0):
                eng.set_knobs(qos_tier_delay={"batch": new_delay})
                self._record(
                    "qos_tier_delay[batch]", cur_batch, new_delay,
                    f"{bp} windows of batch-tier traffic coalescing "
                    f"under target {self.coalesce_target:g} — batch "
                    "classes wait longer for fuller devices")
            with self._lock:
                self._qos_batch_pressure = 0
        elif cur_batch is not None and bi >= self.unstack_after:
            eng.set_knobs(qos_tier_delay={"batch": None})
            self._record(
                "qos_tier_delay[batch]", cur_batch, None,
                f"{bi} windows without batch-tier traffic — the "
                "stretch override is retired until it earns its way "
                "back")
            with self._lock:
                self._qos_batch_idle = 0

    # ------------------------------------------------------------------ #
    # observability
    # ------------------------------------------------------------------ #

    def stats(self) -> dict:
        """Controller counters for `engine.stats()['controller']`:
        ticks taken, decisions made, tick errors, the guard-relaxation
        state, the last telemetry window it acted on, and the tail of
        the decision log."""
        with self._lock:
            return {
                "ticks": self._ticks,
                "decisions": self._decisions,
                "errors": self._errors,
                "relaxed_guards": self._relaxed,
                "drain_rate": self._drain_rate,
                "slo_p99_ms": self.slo_p99_ms,
                "qos_windows": sorted(self._qos_windows),
                "persist": {
                    "enabled": self.persist,
                    "regime": self._regime,
                    "reseeded": dict(self._reseeded),
                } if self.persist else {"enabled": False},
                "last_window": dict(self._last_window),
                "decisions_log": [
                    {"t": t, "knob": k, "old": o, "new": n, "reason": r}
                    for t, k, o, n, r in self._log[-16:]],
            }

    @staticmethod
    def blank_delta(seconds: float = 0.25) -> dict:
        """A zeroed `StatsWindow.delta()`-shaped dict — the test/bench
        harness hook for driving `step()` with synthetic telemetry
        (stub the attached window's `delta` with edits of this)."""
        eng = {k: 0 for k in profiler._ENGINE_COUNTERS}
        eng.update(pending=0, backlog_delta=0, arrival_per_s=0.0,
                   drain_per_s=0.0, coalesced_mean=0.0,
                   factor_coalesced_mean=0.0, latency_samples=0,
                   factor_latency_samples=0)
        for prefix in ("latency", "factor_latency"):
            for pct in (50, 95, 99):
                eng[f"{prefix}_p{pct}_ms"] = 0.0
        return {
            "seconds": seconds,
            "engine": eng,
            "bucket_hits": {},
            "factor_bucket_hits": {},
            "phases": {ph: {"count": 0, "wall_s": 0.0}
                       for ph in profiler.SERVE_PHASES},
            "health": {},
            "tier": {},
            "tier_gauges": {},
        }


class HostLoadEstimator:
    """Per-host drain-rate EMAs for the serve fabric.

    The fabric's heartbeat thread feeds each host's counter deltas
    (``profiler.CounterWindow`` output over the ping payload) into
    :meth:`feed`; this keeps one smoothed solves/s estimate and one
    pending-depth gauge per host.  Two consumers:

    * :meth:`retry_after` — a measured-drain-rate retry hint for
      ``HostUnavailable``/``FleetDegraded`` (same policy as
      ``EngineSaturated.retry_after``: backlog over the smoothed
      drain rate, clamped to ``[floor, ceil]``).
    * :meth:`least_loaded` — migration/fail-over target pick among
      candidate hosts: lowest pending depth, ties broken by highest
      drain rate, then host id (deterministic).
    """

    def __init__(self, ema: float = 0.3, floor: float = 0.05,
                 ceil: float = 5.0):
        self.ema = float(ema)
        self.floor = float(floor)
        self.ceil = float(ceil)
        self._lock = threading.Lock()
        self._rate: dict[str, float] = {}     # guarded-by: _lock
        self._pending: dict[str, int] = {}    # guarded-by: _lock
        # host -> tier -> smoothed drain rate, fed from the flat
        # qos_<tier>_solves heartbeat counters (DESIGN §30); empty for
        # hosts that never report classified traffic
        self._tier_rate: dict[str, dict[str, float]] = {}  # guarded-by: _lock
        # host -> shm wire ring occupancy in [0, 1] (DESIGN §31): the
        # fuller of the host's two payload rings, a gauge straight off
        # the ping payload; absent for pickle-wire hosts
        self._wire: dict[str, float] = {}  # guarded-by: _lock

    def feed(self, host: str, delta: dict) -> None:
        """Fold one heartbeat counter-delta window for ``host``.

        ``delta`` is a ``CounterWindow.feed`` result over the host's
        engine counters: ``solves`` (window increment) and ``seconds``
        give the instantaneous rate; ``pending`` gives the depth (a
        gauge — the fabric re-injects the RAW heartbeat value after
        the window differences the payload). ``wire_used_frac`` (also
        a re-injected gauge) reports the host's shm payload-ring
        occupancy (DESIGN §31) — a near-full wire backpressures
        admission before pending depth shows it, so placement reads
        it directly.
        """
        secs = max(1e-9, float(delta.get("seconds", 0.0) or 0.0))
        rate = float(delta.get("solves", 0) or 0) / secs
        pending = int(delta.get("pending", 0) or 0)
        tiers = {k[len("qos_"):-len("_solves")]:
                 float(v or 0) / secs
                 for k, v in delta.items()
                 if k.startswith("qos_") and k.endswith("_solves")}
        with self._lock:
            prev = self._rate.get(host)
            if prev is None:
                self._rate[host] = rate
            else:
                self._rate[host] = self.ema * rate + (1 - self.ema) * prev
            self._pending[host] = pending
            wire = delta.get("wire_used_frac")
            if wire is not None:
                self._wire[host] = min(1.0, max(0.0, float(wire)))
            if tiers:
                cur = self._tier_rate.setdefault(host, {})
                for t, r in tiers.items():
                    p = cur.get(t)
                    cur[t] = r if p is None else (
                        self.ema * r + (1 - self.ema) * p)

    def forget(self, host: str) -> None:
        """Drop a dead host's state so it doesn't skew future picks."""
        with self._lock:
            self._rate.pop(host, None)
            self._pending.pop(host, None)
            self._tier_rate.pop(host, None)
            self._wire.pop(host, None)

    def retry_after(self, backlog: int = 1,
                    hosts: "list[str] | None" = None) -> float:
        """Seconds until ~``backlog`` items drain at the measured
        aggregate rate of ``hosts`` (all known hosts when None)."""
        with self._lock:
            rates = [r for h, r in self._rate.items()
                     if hosts is None or h in hosts]
        total = sum(rates)
        if total <= 0.0:
            return self.ceil
        return min(self.ceil, max(self.floor, backlog / total))

    def wire_frac(self, host: str) -> float:
        """The host's last-reported shm ring occupancy in [0, 1]
        (0.0 when unknown/pickle-wire) — the fabric's shared
        `_pick_target` refuses rebalance targets at ≥ 0.9."""
        with self._lock:
            return self._wire.get(host, 0.0)

    def sessions_capacity_util(self, host: str,
                               sessions: int,
                               bytes_per_session: float,
                               host_bytes: float) -> float:
        """Memory-model utilization for one host: owned sessions ×
        the measured bytes/session working set over the host's state
        budget. The :class:`FabricAutoscaler`'s capacity axis, seeded
        from BENCH_WORKINGSET's bytes/session."""
        del host  # symmetry with the rate axis; the model is global
        if host_bytes <= 0:
            return 0.0
        return sessions * bytes_per_session / host_bytes

    def drain_util(self, host: str, capacity_per_s: float) -> float:
        """Rate-model utilization for one host: the smoothed TOTAL
        qos drain rate (sum over tiers — the per-host
        `qos_drain_per_s` EMAs off the heartbeat's flat counters)
        against a per-host drain capacity. 0.0 when the capacity is
        unset/unknown — the memory axis then decides alone."""
        if capacity_per_s <= 0:
            return 0.0
        with self._lock:
            tiers = self._tier_rate.get(host)
            rate = (sum(tiers.values()) if tiers
                    else self._rate.get(host, 0.0))
        return rate / capacity_per_s

    def least_loaded(self, hosts: "list[str]") -> str:
        """The best adoption target among ``hosts``: hosts whose shm
        wire is congested (ring ≥ 90% full — their admission is about
        to shed RingFull regardless of queue depth) sort behind
        everyone else, then fewest pending solves, then fastest drain,
        then lexicographic host id."""
        if not hosts:
            raise ValueError("least_loaded() needs at least one host")
        with self._lock:
            return min(hosts, key=lambda h: (self._wire.get(h, 0.0) >= 0.9,
                                             self._pending.get(h, 0),
                                             -self._rate.get(h, 0.0), h))

    def stats(self) -> dict:
        """Per-host smoothed rates and pending depths (telemetry)."""
        with self._lock:
            out = {h: {"drain_per_s": self._rate[h],
                       "pending": self._pending.get(h, 0)}
                   for h in sorted(self._rate)}
            for h, tiers in self._tier_rate.items():
                if h in out:
                    out[h]["qos_drain_per_s"] = dict(sorted(tiers.items()))
            for h, frac in self._wire.items():
                if h in out:
                    out[h]["wire_used_frac"] = round(frac, 4)
            return out


# --------------------------------------------------------------------------- #
# fabric autoscaling (DESIGN §34)
# --------------------------------------------------------------------------- #


@dataclasses.dataclass
class AutoscalePolicy:
    """Knobs for :class:`FabricAutoscaler` (TUNING.md "Elastic
    fabric"). The decision table lives in DESIGN §34.

    min_hosts / max_hosts: hard bounds on the live host count.
    interval: controller tick period (seconds) for the daemon loop.
    high_water / low_water: fleet-mean utilization thresholds. Scale
        OUT above high_water; scale IN only when the fleet would
        STILL sit below high_water after losing a host (the low_water
        check alone would flap right back out).
    sustain: consecutive ticks a threshold must hold before acting —
        the hysteresis that keeps one Poisson clump of arrivals (or
        one quiet beat) from triggering a resize.
    cooldown: seconds after ANY membership action before the next;
        covers the drain/adopt transient a resize itself causes.
    bytes_per_session: capacity-model seed — the measured per-session
        working set (BENCH_WORKINGSET: ~525 KB/session for the
        default serve shapes; re-seed from your own artifact).
    host_bytes: per-host session-state budget the memory axis fills.
    drain_capacity_per_s: per-host solve-rate capacity for the
        `qos_drain_per_s` axis; 0 disables it (memory axis only).
        Seeded from the measured bench drain numbers the same way
        the memory axis rides BENCH_WORKINGSET: BENCH_QOS's bulk
        overload leg drains ~1348 solves/s and BENCH_ADAPTIVE's
        burst leg ~1253/s per host at the default serve shapes —
        the default takes the conservative burst figure, so
        sustained qos pressure (not just memory) can trigger
        growth. Re-seed from your own artifact for other shapes.
    rebalance_ratio / rebalance_floor / max_rebalance_moves: the
        hot-host skew detector forwarded to `ServeFabric.rebalance`
        every tick (bounded background correction, independent of the
        resize hysteresis).
    """

    min_hosts: int = 1
    max_hosts: int = 8
    interval: float = 0.5
    high_water: float = 0.80
    low_water: float = 0.35
    sustain: int = 3
    cooldown: float = 5.0
    bytes_per_session: float = 525e3
    host_bytes: float = 64e6
    drain_capacity_per_s: float = 1250.0
    rebalance_ratio: float = 2.0
    rebalance_floor: int = 4
    max_rebalance_moves: int = 2

    def __post_init__(self):
        if not (1 <= self.min_hosts <= self.max_hosts):
            raise ValueError("need 1 <= min_hosts <= max_hosts")
        if not (0.0 < self.low_water < self.high_water):
            raise ValueError("need 0 < low_water < high_water")
        if self.sustain < 1 or self.interval <= 0:
            raise ValueError("sustain must be >= 1 and interval > 0")
        if self.cooldown < 0 or self.bytes_per_session <= 0 \
                or self.host_bytes <= 0 \
                or self.drain_capacity_per_s < 0:
            raise ValueError("cooldown >= 0 and positive capacity "
                             "model required")


class FabricAutoscaler:
    """The elastic-fabric controller loop (DESIGN §34): grows and
    shrinks a :class:`~conflux_tpu.fabric.ServeFabric`'s host set and
    drains hot-host skew, from the same telemetry the fabric already
    collects (`HostLoadEstimator` EMAs + the owners census).

    **Utilization model.** Per alive host, utilization is the max of
    two axes: memory (owned sessions × `bytes_per_session` /
    `host_bytes` — the BENCH_WORKINGSET capacity model) and drain
    rate (the per-host `qos_drain_per_s` EMA sum against
    `drain_capacity_per_s`, when configured). Decisions use the
    fleet MEAN over alive hosts.

    **Decision table** (evaluated every `interval`; see DESIGN §34):
    scale OUT one host when mean utilization > `high_water` for
    `sustain` consecutive ticks (bounded by `max_hosts`); scale IN
    one host — the least-loaded alive host, drained through
    `remove_host(drain=True)` — when mean utilization < `low_water`
    for `sustain` ticks AND the post-removal fleet would still sit
    under `high_water` (bounded by `min_hosts`). Every action arms a
    `cooldown`; ticks inside it only rebalance. A tick that crosses
    neither threshold resets both streaks — hysteresis by
    construction, so one Poisson clump never resizes the fleet.

    **Host identity.** New hosts come from the `provider` callback
    (``provider(host_id) -> HostHandle``, unstarted — tests and
    soaks pass LocalHost factories; deployments spawn ProcessHost
    or cloud instances). Ids are fresh monotonically (`as0`, `as1`,
    ...) and never reuse a retired id — the fabric would refuse it.

    Drive it either as a daemon (`start()`/`close()`) or
    deterministically from tests/benches: `step(now=...)` takes one
    decision with an injectable clock and no thread."""

    def __init__(self, fabric, provider, *,
                 policy: AutoscalePolicy | None = None,
                 id_prefix: str = "as"):
        self.fabric = fabric
        self.provider = provider
        self.policy = policy if policy is not None else AutoscalePolicy()
        self.id_prefix = id_prefix
        self._lock = threading.Lock()
        self._hot = 0            # guarded-by: _lock — high-water streak
        self._cold = 0           # guarded-by: _lock — low-water streak
        self._seq = 0            # guarded-by: _lock — fresh-id counter
        self._cooldown_until = float("-inf")  # guarded-by: _lock
        self._ticks = 0          # guarded-by: _lock
        self._errors = 0         # guarded-by: _lock
        self._scale_out = 0      # guarded-by: _lock
        self._scale_in = 0       # guarded-by: _lock
        self._rebalanced = 0     # guarded-by: _lock
        self._log: list[tuple] = []  # guarded-by: _lock
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # -- lifecycle ------------------------------------------------------ #

    def start(self) -> "FabricAutoscaler":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, daemon=True,
                name="fabric-autoscaler")
            self._thread.start()
        return self

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None

    def __enter__(self) -> "FabricAutoscaler":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.close()

    def _loop(self) -> None:
        while not self._stop.wait(self.policy.interval):
            try:
                self.step()
            except Exception:  # noqa: BLE001 — the loop must survive
                with self._lock:
                    self._errors += 1

    # -- the decision tick ---------------------------------------------- #

    def utilization(self) -> dict[str, float]:
        """Per-alive-host utilization under the two-axis model."""
        pol = self.policy
        est = self.fabric.load
        per = self.fabric.owner_census()
        out: dict[str, float] = {}
        for h in self.fabric._alive():
            mem = est.sessions_capacity_util(
                h, per.get(h, 0), pol.bytes_per_session, pol.host_bytes)
            rate = est.drain_util(h, pol.drain_capacity_per_s)
            out[h] = max(mem, rate)
        return out

    def step(self, now: float | None = None) -> dict:
        """One decision tick. Returns {action, mean_util, hosts, ...}
        (action ∈ 'none'/'cooldown'/'scale_out'/'scale_in'/'refused')
        — the deterministic harness entry (tests/benches drive this
        with an injected clock; the daemon loop calls it on a
        timer)."""
        pol = self.policy
        t = time.monotonic() if now is None else float(now)
        util = self.utilization()
        n = len(util)
        mean = (sum(util.values()) / n) if n else 0.0
        action = "none"
        detail = ""
        with self._lock:
            self._ticks += 1
            if mean > pol.high_water:
                self._hot += 1
                self._cold = 0
            elif mean < pol.low_water and n > 0 \
                    and (mean * n) / max(1, n - 1) < pol.high_water:
                # scale-in pre-check: the surviving fleet must absorb
                # the departing host's share WITHOUT crossing the
                # high-water mark, or we'd flap straight back out
                self._cold += 1
                self._hot = 0
            else:
                self._hot = 0
                self._cold = 0
            hot, cold = self._hot, self._cold
            cooling = t < self._cooldown_until
        if cooling:
            action = "cooldown"
        elif hot >= pol.sustain and n >= pol.min_hosts:
            if n >= pol.max_hosts:
                action, detail = "refused", "at max_hosts"
            else:
                action, detail = self._grow(t)
        elif cold >= pol.sustain:
            if n <= pol.min_hosts:
                action, detail = "refused", "at min_hosts"
            else:
                action, detail = self._shrink(t, util)
        # bounded skew correction rides every tick, resize or not —
        # it moves sessions, never membership, so no cooldown gate
        try:
            moved = self.fabric.rebalance(
                max_moves=pol.max_rebalance_moves,
                ratio=pol.rebalance_ratio,
                floor=pol.rebalance_floor)
        except Exception:  # noqa: BLE001 — correction must not kill the tick
            moved = []
            with self._lock:
                self._errors += 1
        out = {"action": action, "detail": detail, "mean_util": mean,
               "hosts": n, "rebalanced": len(moved)}
        with self._lock:
            if moved:
                self._rebalanced += len(moved)
            if action not in ("none", "cooldown"):
                self._log.append((t, action, detail, round(mean, 4), n))
                del self._log[:-32]
        return out

    def _fresh_id(self) -> str:
        taken = self.fabric.taken_ids()
        with self._lock:
            while f"{self.id_prefix}{self._seq}" in taken:
                self._seq += 1
            hid = f"{self.id_prefix}{self._seq}"
            self._seq += 1
        return hid

    def _grow(self, t: float) -> tuple[str, str]:
        hid = self._fresh_id()
        try:
            self.fabric.add_host(self.provider(hid))
        except Exception as e:  # noqa: BLE001 — provider/join failure is a counted refusal
            with self._lock:
                self._errors += 1
            return "refused", f"add_host({hid}) failed: {e!r}"
        with self._lock:
            self._scale_out += 1
            self._hot = 0
            self._cooldown_until = t + self.policy.cooldown
        bump("fabric_autoscale_out")
        return "scale_out", hid

    def _shrink(self, t: float, util: dict[str, float]) -> tuple[str, str]:
        victim = min(sorted(util), key=lambda h: util[h])
        try:
            self.fabric.remove_host(victim, drain=True)
        except Exception as e:  # noqa: BLE001 — an incomplete drain is a counted refusal; retried next tick
            with self._lock:
                self._errors += 1
                self._cooldown_until = t + self.policy.cooldown
            return "refused", f"remove_host({victim}) failed: {e!r}"
        with self._lock:
            self._scale_in += 1
            self._cold = 0
            self._cooldown_until = t + self.policy.cooldown
        bump("fabric_autoscale_in")
        return "scale_in", victim

    # -- observability -------------------------------------------------- #

    def stats(self) -> dict:
        with self._lock:
            return {
                "ticks": self._ticks,
                "errors": self._errors,
                "scale_out": self._scale_out,
                "scale_in": self._scale_in,
                "rebalanced": self._rebalanced,
                "hot_streak": self._hot,
                "cold_streak": self._cold,
                "decisions_log": [
                    {"t": t, "action": a, "detail": d, "mean_util": u,
                     "hosts": n} for t, a, d, u, n in self._log[-16:]],
            }
