"""Correctness oracles: factorization residuals.

TPU-native equivalent of the reference's CONFLUX_WITH_VALIDATION path, which
assembles the factors in ScaLAPACK layout and computes ||PA - LU||_F with two
`pdgemm_` calls (`examples/conflux_miniapp.cpp:404-500`). Here the residual
is a direct JAX computation — on a single host for tests, or on the gathered
result of a distributed run.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax


def _norm_ratio(rss, ass) -> float:
    """sqrt(sum-of-squares ratio) with the zero-norm guard — shared
    epilogue of the three on-mesh residual oracles."""
    return float(np.sqrt(float(rss)) / max(np.sqrt(float(ass)), 1e-30))


def lu_residual(A, LU, perm) -> float:
    """Normalized ||A[perm] - L U||_F / ||A||_F for packed LU factors.

    Handles rectangular factorizations both ways: L is (M, K) unit-lower
    and U (K, N) upper with K = min(M, N)."""
    A = np.asarray(A)
    LU = np.asarray(LU)
    perm = np.asarray(perm)
    M, N = LU.shape
    K = min(M, N)
    L = np.tril(LU, -1)[:, :K] + np.eye(M, K, dtype=LU.dtype)
    U = np.triu(LU[:K, :])
    R = A[perm, :] - L @ U
    return float(np.linalg.norm(R) / max(np.linalg.norm(A), 1e-30))


def lu_residual_distributed(A_shards, LU_shards, perm, geom, mesh) -> float:
    """Gather-free ||A[perm] - L U||_F / ||A||_F, computed on the mesh.

    The role of the reference's ScaLAPACK validation (COSTA transforms +
    two `pdgemm_` calls, `examples/conflux_miniapp.cpp:404-500`): nothing
    (M, N)-sized ever exists on the host or on a single device. Two
    on-mesh passes, each a fori_loop of (v, Nl)/(Ml, v)-sized collectives:

      1. SUMMA product: for each column tile t, the owner column of L and
         owner row of U are broadcast (masked psums over 'y' / 'x') and
         every device accumulates its (Ml, Nl) share of L @ U.
      2. Row permutation: for each row tile t of *positions*, the original
         rows A[perm[t*v:(t+1)*v]] are assembled by a masked psum over 'x'
         and handed to the position owner — the same pattern as the
         factorization's pivot-row reduction.

    A_shards: the original matrix's block-cyclic shards (Px, Py, Ml, Nl)
    (original row order). LU_shards, perm: `lu_factor_distributed` outputs
    (factors in pivoted order). Returns the relative Frobenius residual.
    """
    from conflux_tpu.parallel.mesh import mesh_cache_key

    fn = _build_lu_residual(geom, mesh_cache_key(mesh))
    rss, ass = fn(A_shards, LU_shards, jnp.asarray(perm, jnp.int32))
    return _norm_ratio(rss, ass)


@functools.lru_cache(maxsize=16)
def _build_lu_residual(geom, mesh_key):
    from jax.sharding import PartitionSpec as P

    from conflux_tpu.parallel.mesh import (
        AXIS_X, AXIS_Y, AXIS_Z, lookup_mesh, pvary, shard_map,
    )

    mesh = lookup_mesh(mesh_key)
    v = geom.v
    Px, Py = geom.grid.Px, geom.grid.Py
    Ml, Nl = geom.Ml, geom.Nl
    Mt, Nt = geom.Mt, geom.Nt

    def device_fn(Ablk, LUblk, perm):
        x = lax.axis_index(AXIS_X)
        y = lax.axis_index(AXIS_Y)
        Aloc = Ablk[0, 0]
        dtype = jnp.float32 if Aloc.dtype == jnp.bfloat16 else Aloc.dtype
        Aloc = Aloc.astype(dtype)
        LUloc = LUblk[0, 0].astype(dtype)

        lr = jnp.arange(Ml, dtype=jnp.int32)
        gp = ((lr // v) * Px + x) * v + (lr % v)  # global position
        lc = jnp.arange(Nl, dtype=jnp.int32)
        gcol = ((lc // v) * Py + y) * v + (lc % v)
        i0 = jnp.zeros((), jnp.int32)

        # ---- pass 1: SUMMA accumulation of L @ U ---------------------- #
        def summa(t, acc):
            ly = ((t // Py) * v).astype(jnp.int32)
            Lcol = lax.dynamic_slice(LUloc, (i0, ly), (Ml, v))
            colt = t * v + jnp.arange(v, dtype=jnp.int32)
            Lcol = jnp.where(gp[:, None] > colt[None, :], Lcol, 0.0)
            Lcol = Lcol + (gp[:, None] == colt[None, :]).astype(dtype)
            Lcol = lax.psum(
                jnp.where(y == t % Py, Lcol, jnp.zeros((), dtype)), AXIS_Y)
            lx = ((t // Px) * v).astype(jnp.int32)
            Urow = lax.dynamic_slice(LUloc, (lx, i0), (v, Nl))
            Urow = jnp.where(colt[:, None] <= gcol[None, :], Urow, 0.0)
            Urow = lax.psum(
                jnp.where(x == t % Px, Urow, jnp.zeros((), dtype)), AXIS_X)
            return acc + jnp.matmul(Lcol, Urow,
                                    precision=lax.Precision.HIGHEST)

        zero0 = pvary(jnp.zeros((Ml, Nl), dtype),
                      (AXIS_X, AXIS_Y, AXIS_Z))
        prod = lax.fori_loop(0, Nt, summa, zero0)

        # ---- pass 2: assemble A[perm] rows at their positions --------- #
        def permrows(t, Ap):
            pv = lax.dynamic_slice(perm, (t * v,), (v,))  # original rows
            # my local rows holding those original rows (original order!)
            gri = gp  # A shards are in original row order: id == position
            match = gri[:, None] == pv[None, :]  # (Ml, v)
            owned = match.any(axis=0)
            li = jnp.where(owned, jnp.argmax(match, axis=0), Ml)
            part = jnp.take(Aloc, li, axis=0, mode="fill", fill_value=0)
            rows = lax.psum(part, AXIS_X)  # (v, Nl)
            dst = ((t // Px) * v).astype(jnp.int32)
            return jnp.where(
                x == t % Px,
                lax.dynamic_update_slice(Ap, rows, (dst, i0)),
                Ap,
            )

        Ap = lax.fori_loop(
            0, Mt, permrows,
            pvary(jnp.zeros((Ml, Nl), dtype),
                  (AXIS_X, AXIS_Y, AXIS_Z)))

        R = Ap - prod
        rss = lax.psum(jnp.sum((R * jnp.conj(R)).real), (AXIS_X, AXIS_Y))
        ass = lax.psum(jnp.sum((Aloc * jnp.conj(Aloc)).real),
                       (AXIS_X, AXIS_Y))
        # identical across z already; pmax satisfies replication
        return (lax.pmax(rss, AXIS_Z), lax.pmax(ass, AXIS_Z))

    fn = shard_map(
        device_fn,
        mesh=mesh,
        in_specs=(P(AXIS_X, AXIS_Y, None, None),
                  P(AXIS_X, AXIS_Y, None, None), P()),
        out_specs=(P(), P()),
    )
    return jax.jit(fn)


def cholesky_residual(A, L) -> float:
    """Normalized ||A - L L^H||_F / ||A||_F for a lower Cholesky factor
    (^H == ^T for real dtypes)."""
    A = np.asarray(A)
    L = np.tril(np.asarray(L))
    R = A - L @ L.conj().T
    return float(np.linalg.norm(R) / max(np.linalg.norm(A), 1e-30))


def cholesky_residual_distributed(A_shards, L_shards, geom, mesh) -> float:
    """Gather-free ||A - L L^H||_F / ||A||_F on the mesh — the Cholesky
    counterpart of :func:`lu_residual_distributed` (reference pdgemm
    validation role; ^H == ^T for real dtypes). One SUMMA pass: for each
    column tile t, the lower-triangular column slab of L is y-broadcast
    and its conjugate-transpose-rows are delivered to column owners by the
    same masked-psum exchange the factorization's scatterA11 uses; every
    device accumulates its share of L L^H. No (N, N) array exists
    anywhere.
    """
    from conflux_tpu.parallel.mesh import mesh_cache_key

    fn = _build_cholesky_residual(geom, mesh_cache_key(mesh))
    rss, ass = fn(A_shards, L_shards)
    return _norm_ratio(rss, ass)


@functools.lru_cache(maxsize=16)
def _build_cholesky_residual(geom, mesh_key):
    from jax.sharding import PartitionSpec as P

    from conflux_tpu.parallel.mesh import (
        AXIS_X, AXIS_Y, AXIS_Z, lookup_mesh, pvary, shard_map,
    )

    mesh = lookup_mesh(mesh_key)
    v = geom.v
    Px, Py = geom.grid.Px, geom.grid.Py
    Ml, Nl = geom.Ml, geom.Nl
    Nt = geom.Kappa  # tile columns == supersteps

    def device_fn(Ablk, Lblk):
        x = lax.axis_index(AXIS_X)
        y = lax.axis_index(AXIS_Y)
        Aloc = Ablk[0, 0]
        dtype = jnp.float32 if Aloc.dtype == jnp.bfloat16 else Aloc.dtype
        Aloc = Aloc.astype(dtype)
        Lloc = Lblk[0, 0].astype(dtype)

        lr = jnp.arange(Ml, dtype=jnp.int32)
        gp = ((lr // v) * Px + x) * v + (lr % v)  # global row index
        lc = jnp.arange(Nl, dtype=jnp.int32)
        gcol = ((lc // v) * Py + y) * v + (lc % v)
        col_owner_x = (gcol // v) % Px
        col_local_row = ((gcol // v) // Px) * v + gcol % v
        i0 = jnp.zeros((), jnp.int32)

        def summa(t, acc):
            colt = t * v + jnp.arange(v, dtype=jnp.int32)
            ly = ((t // Py) * v).astype(jnp.int32)
            Lcol = lax.dynamic_slice(Lloc, (i0, ly), (Ml, v))
            Lcol = jnp.where(gp[:, None] >= colt[None, :], Lcol, 0.0)
            Lcol = lax.psum(
                jnp.where(y == t % Py, Lcol, jnp.zeros((), dtype)), AXIS_Y)
            # rows of L^T for my columns: L[gcol, t-block], delivered from
            # each row's x-owner (the scatterA11 exchange pattern)
            from_L = jnp.where(
                (col_owner_x == x)[:, None],
                jnp.take(Lcol, col_local_row, axis=0, mode="fill",
                         fill_value=0),
                jnp.zeros((), dtype))
            # conj().T: the product is L L^H for complex dtypes
            LrowT = lax.psum(from_L, AXIS_X).conj().T  # (v, Nl)
            return acc + jnp.matmul(Lcol, LrowT,
                                    precision=lax.Precision.HIGHEST)

        zero0 = pvary(jnp.zeros((Ml, Nl), dtype),
                      (AXIS_X, AXIS_Y, AXIS_Z))
        prod = lax.fori_loop(0, Nt, summa, zero0)

        R = Aloc - prod
        rss = lax.psum(jnp.sum((R * jnp.conj(R)).real), (AXIS_X, AXIS_Y))
        ass = lax.psum(jnp.sum((Aloc * jnp.conj(Aloc)).real), (AXIS_X, AXIS_Y))
        return (lax.pmax(rss, AXIS_Z), lax.pmax(ass, AXIS_Z))

    fn = shard_map(
        device_fn,
        mesh=mesh,
        in_specs=(P(AXIS_X, AXIS_Y, None, None),
                  P(AXIS_X, AXIS_Y, None, None)),
        out_specs=(P(), P()),
    )
    return jax.jit(fn)


def residual_bound(n: int, dtype) -> float:
    """Acceptance threshold: c * sqrt(n) * eps, with headroom for pivot growth."""
    eps = float(jnp.finfo(dtype).eps)
    return 100.0 * np.sqrt(n) * eps


def make_test_matrix(M: int, N: int, seed: int = 42, dtype=np.float64) -> np.ndarray:
    """Deterministic well-conditioned random matrix (the role of the
    reference's seeded `InitMatrix`, `lu_params.hpp:141-376`, without its
    hard-coded fixtures)."""
    rng = np.random.default_rng(seed)
    A = rng.uniform(-1.0, 1.0, size=(M, N)).astype(dtype)
    # diagonal boost keeps condition number moderate without killing pivoting
    d = min(M, N)
    A[np.arange(d), np.arange(d)] += 2.0
    return A


def make_spd_matrix(N: int, seed: int = 7, dtype=np.float64) -> np.ndarray:
    """Deterministic SPD matrix (role of `CholeskyIO::generateInputMatrixDistributed`,
    `CholeskyIO.cpp:100-172`: random symmetric + diagonal dominance)."""
    rng = np.random.default_rng(seed)
    B = rng.uniform(-1.0, 1.0, size=(N, N)).astype(dtype)
    A = (B + B.T) / 2
    A[np.arange(N), np.arange(N)] += N
    return A


def make_hpd_matrix(N: int, seed: int = 7,
                    dtype=np.complex128) -> np.ndarray:
    """Deterministic Hermitian positive-definite matrix (the complex
    instantiation of :func:`make_spd_matrix`: random Hermitian + diagonal
    dominance; the diagonal is real by construction)."""
    rng = np.random.default_rng(seed)
    B = (rng.uniform(-1.0, 1.0, size=(N, N))
         + 1j * rng.uniform(-1.0, 1.0, size=(N, N))).astype(dtype)
    A = (B + B.conj().T) / 2
    A[np.arange(N), np.arange(N)] += N
    return A


def qr_residual_distributed(A_shards, Q_shards, R_shards, geom, mesh):
    """Gather-free (||A - Q R||_F/||A||_F, ||Q^T Q - I||_F/sqrt(N)) on the
    mesh — the QR counterpart of :func:`lu_residual_distributed` (pdgemm
    validation role). One SUMMA loop over column tiles: the owner's Q
    column slab is y-broadcast and R's row slab x-broadcast (masked
    psums), every device accumulates its share of Q R; the same Q column
    slab also yields an orthogonality strip Q^T Qcol - I. Both error
    checks without any (N, N) array. Complex inputs use the Hermitian
    adjoint throughout."""
    from conflux_tpu.parallel.mesh import mesh_cache_key

    fn = _build_qr_residual(geom, mesh_cache_key(mesh))
    rss, ass, oss = fn(jnp.asarray(A_shards), jnp.asarray(Q_shards),
                       jnp.asarray(R_shards))
    return _norm_ratio(rss, ass), float(np.sqrt(float(oss)) / np.sqrt(geom.N))


@functools.lru_cache(maxsize=16)
def _build_qr_residual(geom, mesh_key):
    from jax.sharding import PartitionSpec as P

    from conflux_tpu.parallel.mesh import (
        AXIS_X, AXIS_Y, AXIS_Z, lookup_mesh, pvary, shard_map,
    )

    mesh = lookup_mesh(mesh_key)
    v, Px, Py = geom.v, geom.grid.Px, geom.grid.Py
    Ml, Nl, Nt = geom.Ml, geom.Nl, geom.Nt

    def device_fn(Ablk, Qblk, Rblk):
        x = lax.axis_index(AXIS_X)
        y = lax.axis_index(AXIS_Y)
        from conflux_tpu.ops import blas as _blas

        dtype = _blas.compute_dtype(Ablk.dtype)
        Aloc = Ablk[0, 0].astype(dtype)
        Qloc = Qblk[0, 0].astype(dtype)
        Rloc = Rblk[0, 0].astype(dtype)
        lc = jnp.arange(Nl, dtype=jnp.int32)
        gcol = ((lc // v) * Py + y) * v + (lc % v)
        i0 = jnp.zeros((), jnp.int32)

        def body(t, carry):
            prod, oss = carry
            ly = ((t // Py) * v).astype(jnp.int32)
            lx = ((t // Px) * v).astype(jnp.int32)
            Qcol = lax.psum(
                jnp.where(y == t % Py,
                          lax.dynamic_slice(Qloc, (i0, ly), (Ml, v)),
                          jnp.zeros((), dtype)), AXIS_Y)  # (Ml, v)
            Rrow = lax.psum(
                jnp.where(x == t % Px,
                          lax.dynamic_slice(Rloc, (lx, i0), (v, Nl)),
                          jnp.zeros((), dtype)), AXIS_X)  # (v, Nl)
            prod = prod + jnp.matmul(Qcol, Rrow,
                                     precision=lax.Precision.HIGHEST)
            # orthogonality strip: G[my cols, tile-t cols] via psum over
            # rows (x); replicated over x afterwards, so only x == 0
            # devices contribute to the sum of squares
            strip = lax.psum(
                jnp.matmul(Qloc.conj().T, Qcol,
                           precision=lax.Precision.HIGHEST), AXIS_X)
            eye = (gcol[:, None]
                   == (t * v + jnp.arange(v, dtype=jnp.int32))[None, :])
            E = strip - eye.astype(dtype)
            oss = oss + jnp.where(
                x == 0, jnp.sum((E * jnp.conj(E)).real), 0.0)
            return prod, oss

        rdtype = jnp.zeros((), dtype).real.dtype
        zero = pvary(jnp.zeros((Ml, Nl), dtype),
                     (AXIS_X, AXIS_Y, AXIS_Z))
        zoss = pvary(jnp.zeros((), rdtype),
                     (AXIS_X, AXIS_Y, AXIS_Z))
        prod, oss = lax.fori_loop(0, Nt, body, (zero, zoss))
        E = Aloc - prod
        rss = lax.psum(jnp.sum((E * jnp.conj(E)).real), (AXIS_X, AXIS_Y))
        ass = lax.psum(jnp.sum((Aloc * jnp.conj(Aloc)).real), (AXIS_X, AXIS_Y))
        oss = lax.psum(oss, (AXIS_X, AXIS_Y))
        return (lax.pmax(rss, AXIS_Z), lax.pmax(ass, AXIS_Z),
                lax.pmax(oss, AXIS_Z))

    fn = shard_map(
        device_fn,
        mesh=mesh,
        in_specs=(P(AXIS_X, AXIS_Y, None, None),
                  P(AXIS_X, AXIS_Y, None, None),
                  P(AXIS_X, AXIS_Y, None, None)),
        out_specs=(P(), P(), P()),
    )
    return jax.jit(fn)
