"""Correctness oracles: factorization residuals.

TPU-native equivalent of the reference's CONFLUX_WITH_VALIDATION path, which
assembles the factors in ScaLAPACK layout and computes ||PA - LU||_F with two
`pdgemm_` calls (`examples/conflux_miniapp.cpp:404-500`). Here the residual
is a direct JAX computation — on a single host for tests, or on the gathered
result of a distributed run.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np


def lu_residual(A, LU, perm) -> float:
    """Normalized ||A[perm] - L U||_F / ||A||_F for packed LU factors."""
    A = np.asarray(A)
    LU = np.asarray(LU)
    perm = np.asarray(perm)
    M, N = LU.shape
    L = np.tril(LU, -1)[:, :N] + np.eye(M, N, dtype=LU.dtype)
    U = np.triu(LU[:N, :])
    R = A[perm, :] - L @ U
    return float(np.linalg.norm(R) / max(np.linalg.norm(A), 1e-30))


def cholesky_residual(A, L) -> float:
    """Normalized ||A - L L^T||_F / ||A||_F for a lower Cholesky factor."""
    A = np.asarray(A)
    L = np.tril(np.asarray(L))
    R = A - L @ L.T
    return float(np.linalg.norm(R) / max(np.linalg.norm(A), 1e-30))


def residual_bound(n: int, dtype) -> float:
    """Acceptance threshold: c * sqrt(n) * eps, with headroom for pivot growth."""
    eps = float(jnp.finfo(dtype).eps)
    return 100.0 * np.sqrt(n) * eps


def make_test_matrix(M: int, N: int, seed: int = 42, dtype=np.float64) -> np.ndarray:
    """Deterministic well-conditioned random matrix (the role of the
    reference's seeded `InitMatrix`, `lu_params.hpp:141-376`, without its
    hard-coded fixtures)."""
    rng = np.random.default_rng(seed)
    A = rng.uniform(-1.0, 1.0, size=(M, N)).astype(dtype)
    # diagonal boost keeps condition number moderate without killing pivoting
    d = min(M, N)
    A[np.arange(d), np.arange(d)] += 2.0
    return A


def make_spd_matrix(N: int, seed: int = 7, dtype=np.float64) -> np.ndarray:
    """Deterministic SPD matrix (role of `CholeskyIO::generateInputMatrixDistributed`,
    `CholeskyIO.cpp:100-172`: random symmetric + diagonal dominance)."""
    rng = np.random.default_rng(seed)
    B = rng.uniform(-1.0, 1.0, size=(N, N)).astype(dtype)
    A = (B + B.T) / 2
    A[np.arange(N), np.arange(N)] += N
    return A
