"""Serving-throughput benchmark: prints ONE JSON line with solves/s.

The serving claim (ISSUE 1 / ROADMAP north star) measured, not asserted.
Workload: B same-shape (N, N) systems, each solved against R successive
right-hand-side batches — the "many users keep querying the same models"
traffic shape the serve layer exists for. Two implementations run it:

  naive  — per request, one `solvers.solve(A_i, b_i)` call per matrix
           (the pre-serve API): every RHS round re-runs the O(N^3)
           factorization B times and pays B Python/dispatch round-trips.
           Compile is amortized by a warm-up round — this measures
           steady-state cost, not tracing.
  served — ONE batched factorization through a cached `serve.FactorPlan`
           (`conflux_tpu.batched` vmap path), then R
           `SolveSession.solve` substitution-only batches against the
           device-resident factors. Zero refactorizations, zero
           recompiles (asserted against the plan's trace counters).

Headline value is served solves/s over the whole workload (B*R solves in
factor + R substitutions); `speedup_vs_naive` is the ratio against the
naive loop on identical work. Per-element relative residuals of the
served path are checked against the naive path's residuals (the one-shot
oracle bar) — a throughput number from wrong answers is worthless.

Batch sharding (`--shard`): 'auto' shards over a `batch_mesh` when the
host actually has parallel hardware (more than one device AND more than
one core — on a single-core CPU container the mesh multiplexes one core
and only adds partition overhead); 'on'/'off' force it. The CPU-mesh
*correctness* of the sharded path is covered by tests/test_serve.py on
the simulated 8-device mesh regardless of what this bench picks.

Runs on the CPU backend by default (reproducible anywhere, the tier-1
topology); on a real fleet pass `--platform default`. GFLOP/s uses the
nominal LU flop count (2/3 N^3 per system), the bench.py convention.
"""

import argparse
import json
import os
import time


def parse_args():
    ap = argparse.ArgumentParser("bench_serve")
    ap.add_argument("--batch", type=int, default=32,
                    help="number of same-shape systems per request batch")
    ap.add_argument("-N", type=int, default=256, help="system size")
    ap.add_argument("-v", type=int, default=128, help="tile size")
    ap.add_argument("--rhs-batches", type=int, default=16,
                    help="RHS rounds per workload (the serving hot path)")
    ap.add_argument("--reps", type=int, default=3,
                    help="timed repetitions per leg (mean reported)")
    ap.add_argument("--devices", type=int, default=8,
                    help="simulated device count with --platform cpu")
    ap.add_argument("--platform", default="cpu", choices=["cpu", "default"],
                    help="cpu: simulated host devices (default, reproducible "
                    "anywhere); default: whatever the environment gives")
    ap.add_argument("--shard", default="auto", choices=["auto", "on", "off"],
                    help="shard the batch over a batch_mesh (auto: only "
                    "when parallel hardware exists)")
    ap.add_argument("--factor-dtype", default=None,
                    choices=["bfloat16", "float32"],
                    help="HPL-MxP factor dtype (refine sweeps ride along)")
    ap.add_argument("--refine", type=int, default=0,
                    help="classic-IR sweeps fused into the solve program")
    return ap.parse_args()


def main():
    args = parse_args()
    if args.platform == "cpu":
        os.environ["XLA_FLAGS"] = (
            f"--xla_force_host_platform_device_count={args.devices} "
            + os.environ.get("XLA_FLAGS", ""))
        os.environ["JAX_PLATFORMS"] = "cpu"

    import numpy as np

    import jax
    import jax.numpy as jnp

    if args.platform == "cpu":
        jax.config.update("jax_platforms", "cpu")

    from conflux_tpu import batched, cache, serve, solvers

    cache.enable_persistent_cache()

    B, N, v, R = args.batch, args.N, args.v, args.rhs_batches
    if N % v:
        raise SystemExit(f"-N must be a multiple of -v, got {N} % {v}")
    fdtype = None if args.factor_dtype is None else jnp.dtype(args.factor_dtype)

    if args.shard == "on":
        use_mesh = True
    elif args.shard == "off":
        use_mesh = False
    else:
        use_mesh = jax.device_count() > 1 and (os.cpu_count() or 1) > 1
    mesh = batched.batch_mesh() if use_mesh else None

    rng = np.random.default_rng(0)
    # well-conditioned batch (diagonally shifted), the bench.py matrix
    # class — the bf16-factor leg's classic IR needs the conditioning
    A = (rng.standard_normal((B, N, N)) / np.sqrt(N)
         + 2.0 * np.eye(N)).astype(np.float32)
    rhs = [rng.standard_normal((B, N)).astype(np.float32) for _ in range(R)]
    Ad = jnp.asarray(A)
    rhs_d = [jnp.asarray(r) for r in rhs]

    def sync(x):
        return float(jnp.sum(x))

    # ---------------- naive: per-matrix one-shot loop, refactor per round #
    def naive_round(bd):
        xs = []
        for i in range(B):
            xs.append(solvers.solve(Ad[i], bd[i], v=v, factor_dtype=fdtype,
                                    refine=args.refine))
        return jnp.stack(xs)

    x_naive = naive_round(rhs_d[0])  # compile + warm-up
    sync(x_naive)
    t0 = time.perf_counter()
    for _ in range(args.reps):
        for bd in rhs_d:
            x_naive = naive_round(bd)
        sync(x_naive)
    t_naive = (time.perf_counter() - t0) / args.reps  # per workload

    # ---------------- served: one batched factor + R session solves ----- #
    plan = serve.FactorPlan.create((B, N, N), jnp.float32, v=v,
                                   factor_dtype=fdtype, refine=args.refine,
                                   mesh=mesh)
    session = plan.factor(Ad)  # compile + warm-up
    sync(session.solve(rhs_d[0]))
    traces = dict(plan.trace_counts)
    t_factor = t_sub = 0.0
    for _ in range(args.reps):
        t0 = time.perf_counter()
        session = plan.factor(Ad)
        sync(jnp.sum(session.factors[0]))
        t_factor += time.perf_counter() - t0
        t0 = time.perf_counter()
        for bd in rhs_d:
            x_served = session.solve(bd)
        sync(x_served)
        t_sub += time.perf_counter() - t0
    t_factor /= args.reps
    t_sub /= args.reps
    t_served = t_factor + t_sub  # per workload
    assert plan.trace_counts == traces, \
        "serving recompiled mid-workload — the plan-cache contract is broken"
    assert session.factorizations == 1, "session refactored"

    # ---------------- residual oracle (last round, per element) --------- #
    def residuals(x, bref):
        xn = np.asarray(x, np.float64)
        r = np.einsum("bij,bj->bi", A.astype(np.float64), xn) \
            - bref.astype(np.float64)
        return (np.linalg.norm(r, axis=1)
                / np.linalg.norm(bref.astype(np.float64), axis=1))

    res_naive = residuals(x_naive, rhs[-1])
    res_served = residuals(x_served, rhs[-1])
    # bar: the served path may not be meaningfully worse than the one-shot
    # oracle on any element (same algorithm, same dtype discipline)
    bar = np.maximum(4.0 * res_naive, 1e-6)
    ok = bool((res_served <= bar).all())

    solves = B * R
    mode = (f"bf16+IR{args.refine}" if args.factor_dtype == "bfloat16"
            else "f32")
    out = {
        "metric": (f"serve throughput B={B} N={N} v={v} R={R} {mode} "
                   f"({jax.device_count()} {jax.devices()[0].platform} "
                   f"devices, shard={'on' if use_mesh else 'off'})"),
        "value": round(solves / t_served, 2),
        "unit": "solves/s",
        "naive_solves_per_s": round(solves / t_naive, 2),
        "speedup_vs_naive": round(t_naive / t_served, 2),
        "factor_s": round(t_factor, 4),
        "session_solves_per_s": round(solves / t_sub, 2),
        "factor_gflops": round((2 / 3) * N**3 * B / t_factor / 1e9, 2),
        "residual_naive_max": float(res_naive.max()),
        "residual_served_max": float(res_served.max()),
        "residual_oracle_ok": ok,
    }
    print(json.dumps(out))
    if not ok:
        raise SystemExit("served residuals exceed the one-shot oracle bar")


if __name__ == "__main__":
    main()
