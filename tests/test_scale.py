"""Control-plane scale contracts (ISSUE 20, DESIGN §35).

- The O(log F) lazy-invalidation LRU heaps pick EXACTLY the victim
  sets the retired materialize-and-sort baseline picked, on randomized
  touch/churn traces, global and per-device caps included — the heap
  path is a pure complexity change, never a policy change.
- The checkpoint dirty clock: solve-only sessions stay CLEAN (skipped
  by delta generations, carried as pointers into the base); update /
  refactor / adopt mark dirty; carried chains re-base every generation
  (single-hop links) and restore BITWISE, through compaction and
  through fabric fail-over off a delta chain.
- Reference-aware pruning: a kept delta generation pins the base
  generations its carried records point into; compaction releases
  them.
- The scripts/replay.py harness invariants hold at a small
  deterministic scale (victim-set equality inside the bench loop,
  schedule determinism).
"""

import importlib.util
import json
import os
import sys
import time

import numpy as np
import pytest

import jax.numpy as jnp

from conflux_tpu import fabric, serve, tier
from conflux_tpu.fabric import FabricPolicy, LocalHost, ServeFabric
from conflux_tpu.tier import ResidentSet

N, V = 24, 8


def _plan():
    return serve.FactorPlan.create((N, N), jnp.float32, v=V)


def _mk(rng, n=N):
    return (rng.standard_normal((n, n)) / np.sqrt(n)
            + 2.0 * np.eye(n)).astype(np.float32)


def _fleet(plan, count, seed=0):
    rng = np.random.default_rng(seed)
    return [plan.factor(jnp.asarray(_mk(rng))) for _ in range(count)]


# --------------------------------------------------------------------------- #
# the LRU heaps vs the sort oracle
# --------------------------------------------------------------------------- #


class _FakeDev:
    """Hashable stand-in for a jax device (platform/id are all the
    tier's devkey reads)."""

    def __init__(self, i):
        self.platform = "cpu"
        self.id = i


class _Stub:
    """Metadata-only session: the tier manages lock/stamp/bytes, and
    `_pick_victims` only MARKS victims — no device state needed."""

    __slots__ = ("_lock", "_residency", "_tier_stamp", "_spill",
                 "_ckpt_ver", "nbytes", "device")

    def __init__(self, nbytes, device=None):
        import threading

        self._lock = threading.RLock()
        self._residency = None
        self._tier_stamp = 0
        self._spill = None
        self._ckpt_ver = 0
        self.nbytes = nbytes
        self.device = device


def _pick_both(rs, incoming_bytes, incoming_count):
    """One victim pick per impl on the SAME tier state: pick, record,
    revert (stamps untouched). Returns (sort_ids, heap_ids)."""
    out = {}
    for impl in ("sort", "heap"):
        rs._lru_impl = impl
        victims = rs._pick_victims(incoming_bytes, incoming_count)
        out[impl] = frozenset(id(s) for s in victims)
        with rs._lock:
            for s in victims:
                rs._set_state(id(s), s, "resident")
    return out["sort"], out["heap"]


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_victim_sets_match_sort_oracle_randomized(seed):
    """Randomized touch traces + byte/count pressure: the heap pick and
    the full-sort oracle claim IDENTICAL victim sets, every wave."""
    rng = np.random.default_rng(seed)
    F = 160
    rs = ResidentSet(evict_batch=int(rng.integers(1, 4)))
    stubs = [_Stub(int(rng.integers(1_000, 50_000))) for _ in range(F)]
    rs.adopt(*stubs)
    for wave in range(30):
        for i in rng.choice(F, size=40):
            stubs[i]._tier_stamp = rs._tick()
        rs.max_sessions = int(rng.integers(F - 12, F + 4))
        rs.max_bytes = (None if rng.random() < 0.5 else
                        int(rng.integers(1, F) * 25_000))
        sort_ids, heap_ids = _pick_both(
            rs, int(rng.integers(0, 100_000)), int(rng.integers(0, 4)))
        assert sort_ids == heap_ids, f"wave {wave}: victim sets differ"


def test_victim_sets_match_with_per_device_caps():
    """Per-device pressure picks victims from the overfull device only,
    identically in both impls (the §25 cap path over the §35 heaps)."""
    rng = np.random.default_rng(7)
    devs = [_FakeDev(0), _FakeDev(1), _FakeDev(2)]
    rs = ResidentSet(evict_batch=1)
    stubs = [_Stub(10_000, device=devs[i % 3]) for i in range(60)]
    rs.adopt(*stubs)  # cap set AFTER adopt: stubs mark, never spill
    for wave in range(20):
        for i in rng.choice(60, size=15):
            stubs[i]._tier_stamp = rs._tick()
        rs.max_sessions_per_device = int(rng.integers(5, 22))
        sort_ids, heap_ids = _pick_both(rs, 0, 0)
        assert sort_ids == heap_ids, f"wave {wave}: victim sets differ"
        # and the pick honored device locality: census never negative
        with rs._lock:
            assert all(d[0] >= 0 for d in rs._dev_res.values())


def test_spill_lru_uses_heap_order():
    """spill_lru(n) must take the n OLDEST stamps — off the heap, no
    fleet sort."""
    plan = _plan()
    sessions = _fleet(plan, 5, seed=3)
    rs = ResidentSet()
    rs.adopt(*sessions)
    # freshen 2 and 4: the spill must take 0, 1, 3
    for i in (2, 4):
        with sessions[i]._lock:
            sessions[i]._tier_stamp = rs._tick()
    assert rs.spill_lru(3) == 3
    st = {i: rs._state[id(s)] for i, s in enumerate(sessions)}
    assert [st[i] for i in range(5)] == [
        "host", "host", "resident", "host", "resident"]


# --------------------------------------------------------------------------- #
# the checkpoint dirty clock + delta generations
# --------------------------------------------------------------------------- #


def test_solves_stay_clean_mutations_dirty():
    plan = _plan()
    (s,) = _fleet(plan, 1, seed=5)
    rng = np.random.default_rng(5)
    b = rng.standard_normal((N, 1)).astype(np.float32)
    v0 = s._ckpt_ver
    s.solve(b)
    s.solve_checked(b)
    assert s._ckpt_ver == v0  # solve-only traffic leaves it untouched
    u = (0.01 * rng.standard_normal((N, 1))).astype(np.float32)
    w = (0.01 * rng.standard_normal((N, 1))).astype(np.float32)
    s.update(u, w)
    assert s._ckpt_ver > v0  # drift is persisted state
    v1 = s._ckpt_ver
    ResidentSet().adopt(s)
    assert s._ckpt_ver > v1  # so is the manager identity


def _counters():
    st = tier.tier_stats()
    return (st.get("checkpoint_records_written", 0),
            st.get("checkpoint_records_carried", 0))


def test_delta_generation_skips_clean_sessions(tmp_path):
    plan = _plan()
    sessions = _fleet(plan, 3, seed=6)
    for i, s in enumerate(sessions):
        s.sid = f"sess{i}"  # records carry by (sid, ver) identity
    rng = np.random.default_rng(6)
    b = rng.standard_normal((N, 2)).astype(np.float32)
    for s in sessions:
        s.solve(b)
    p0, p1 = str(tmp_path / "g0"), str(tmp_path / "g1")
    tier.save_fleet(p0, sessions, gen=0)
    want = [np.asarray(s.solve(b)) for s in sessions]  # stays clean
    u = (0.01 * rng.standard_normal((N, 1))).astype(np.float32)
    w = (0.01 * rng.standard_normal((N, 1))).astype(np.float32)
    sessions[1].update(u, w)
    want[1] = np.asarray(sessions[1].solve(b))
    w0, c0 = _counters()
    tier.save_fleet(p1, sessions, base=p0, gen=1, full=False)
    w1, c1 = _counters()
    assert w1 - w0 == 1 and c1 - c0 == 2  # only the dirty one written
    with open(os.path.join(p1, "fleet.json")) as f:
        doc = json.load(f)
    assert doc["format"] == 2 and doc["carried"] == 2
    dirs = {e["sid"]: e["dir"] for e in doc["sessions"]}
    gens = {e["sid"]: e["gen"] for e in doc["sessions"]}
    assert dirs["sess0"].startswith("..")  # carried: a pointer
    assert not dirs["sess1"].startswith("..")  # dirty: fresh bytes
    assert gens["sess1"] == 1 and gens["sess0"] == 0
    serve.clear_plans()
    restored = tier.load_fleet(p1)
    for i, r in enumerate(restored):
        assert np.array_equal(want[i], np.asarray(r.solve(b)))


def test_delta_chain_rebases_and_compaction_localizes(tmp_path):
    """gen0 full -> gen1,gen2 deltas (carried links re-based to stay
    single-hop) -> gen3 compaction (no out-of-tree links at all);
    every generation restores bitwise."""
    plan = _plan()
    sessions = _fleet(plan, 3, seed=8)
    for i, s in enumerate(sessions):
        s.sid = f"sess{i}"
    rng = np.random.default_rng(8)
    b = rng.standard_normal((N, 1)).astype(np.float32)
    paths = [str(tmp_path / f"g{i}") for i in range(4)]
    tier.save_fleet(paths[0], sessions, gen=0)

    def drift(i):
        u = (0.01 * rng.standard_normal((N, 1))).astype(np.float32)
        w = (0.01 * rng.standard_normal((N, 1))).astype(np.float32)
        sessions[i].update(u, w)

    drift(0)
    tier.save_fleet(paths[1], sessions, base=paths[0], gen=1, full=False)
    drift(1)
    tier.save_fleet(paths[2], sessions, base=paths[1], gen=2, full=False)
    with open(os.path.join(paths[2], "fleet.json")) as f:
        doc2 = json.load(f)
    for e in doc2["sessions"]:
        d = os.path.normpath(e["dir"])
        if d.startswith(".."):  # re-based: one hop, never a chain
            assert d.count("..") == 1
            assert os.path.isdir(os.path.normpath(
                os.path.join(paths[2], d)))
    # session 2 was never dirtied: its record still carries gen 0
    gens = {e["sid"]: e["gen"] for e in doc2["sessions"]}
    assert gens["sess2"] == 0
    tier.save_fleet(paths[3], sessions, base=paths[2], gen=3, full=True)
    with open(os.path.join(paths[3], "fleet.json")) as f:
        doc3 = json.load(f)
    assert all(not os.path.normpath(e["dir"]).startswith("..")
               for e in doc3["sessions"])  # compaction localizes
    # compaction copies keep the ORIGINAL write generation (standbys
    # holding that push stay provably current)
    gens3 = {e["sid"]: e["gen"] for e in doc3["sessions"]}
    assert gens3["sess2"] == 0
    want = [np.asarray(s.solve(b)) for s in sessions]
    for p in (paths[2], paths[3]):
        serve.clear_plans()
        restored = tier.load_fleet(p)
        for i, r in enumerate(restored):
            assert np.array_equal(want[i], np.asarray(r.solve(b))), p


def test_missing_base_degrades_to_full_write(tmp_path):
    import shutil

    plan = _plan()
    sessions = _fleet(plan, 2, seed=9)
    p0, p1 = str(tmp_path / "g0"), str(tmp_path / "g1")
    tier.save_fleet(p0, sessions, gen=0)
    shutil.rmtree(p0)  # the base vanished (pruned / lost disk)
    w0, _ = _counters()
    tier.save_fleet(p1, sessions, base=p0, gen=1, full=False)
    w1, _ = _counters()
    assert w1 - w0 == 2  # every record freshly written, no broken link
    rng = np.random.default_rng(9)
    b = rng.standard_normal((N, 1)).astype(np.float32)
    want = [np.asarray(s.solve(b)) for s in sessions]
    serve.clear_plans()
    restored = tier.load_fleet(p1)
    for i, r in enumerate(restored):
        assert np.array_equal(want[i], np.asarray(r.solve(b)))


# --------------------------------------------------------------------------- #
# fabric: delta chains under fail-over + reference-aware pruning
# --------------------------------------------------------------------------- #


def _scale_fab(tmp_path, n=2, **pol):
    kw = dict(heartbeat_interval=0.05, heartbeat_timeout=1.0,
              suspect_after=2, dead_after=4, checkpoint_interval=0.0,
              durable_open=False)
    kw.update(pol)
    return fabric.local_fabric(
        n, str(tmp_path), policy=FabricPolicy(**kw),
        engine_kwargs={"max_batch_delay": 0.0})


def test_prune_keeps_delta_referenced_generations(tmp_path):
    """checkpoint_keep bounds the KEPT generations; a kept delta pins
    the base generations its carried records point into, so no kept
    fleet.json ever dangles."""
    with _scale_fab(tmp_path, n=1, checkpoint_keep=2,
                    checkpoint_compact_every=100) as fab:
        rng = np.random.default_rng(11)
        for i in range(3):
            fab.open(f"s{i}", _plan(), _mk(rng))
        for _ in range(5):  # gen0 full, gens1.. all deltas
            fab.checkpoint_all()
        core = fab._hosts["h0"].core
        have = {d for d in os.listdir(core.ckpt_dir)
                if d.startswith("fleet-")}
        kept = sorted(have)[-2:]
        for g in kept:
            with open(os.path.join(core.ckpt_dir, g,
                                   "fleet.json")) as f:
                doc = json.load(f)
            for e in doc["sessions"]:
                src = os.path.normpath(os.path.join(
                    core.ckpt_dir, g, e["dir"]))
                assert os.path.isdir(src), (g, e["dir"])
        # gen0 is pinned (every delta carries into it) but the
        # unreferenced middle deltas are gone
        assert "fleet-000000" in have and len(have) == 3


def test_failover_recovers_from_delta_chain(tmp_path):
    """Kill the owner AFTER a full->delta->delta chain: the survivor
    adopts every session (carried records resolved through the chain)
    and recovered solves answer bitwise vs the checkpointed state."""
    rng = np.random.default_rng(12)
    b = rng.standard_normal((N, 1)).astype(np.float32)
    with _scale_fab(tmp_path, n=2, replicas=2,
                    checkpoint_compact_every=100) as fab:
        sids = [f"user-{i}" for i in range(6)]
        As = {}
        for s in sids:
            As[s] = _mk(rng)
            fab.open(s, _plan(), As[s])
        fab.checkpoint_all()  # gen0: full
        dirty = sids[:2]
        for s in dirty:
            u = (0.01 * rng.standard_normal((N, 1))).astype(np.float32)
            w = (0.01 * rng.standard_normal((N, 1))).astype(np.float32)
            fab.update(s, u, w)
        fab.checkpoint_all()  # gen1: delta (2 written, rest carried)
        fab.checkpoint_all()  # gen2: delta (all carried)
        want = {s: np.asarray(fab.solve(s, b)) for s in sids}
        victim_hid = fab.owner_of(sids[0])
        moved = [s for s in sids if fab.owner_of(s) == victim_hid]
        assert moved
        fab._hosts[victim_hid].kill()
        t0 = time.perf_counter()
        while time.perf_counter() - t0 < 20.0:
            if fab.host_state(victim_hid) == "dead":
                break
            time.sleep(0.02)
        st = fab.stats()
        assert st["lost_sessions"] == 0
        for s in sids:
            t1 = time.perf_counter()
            while True:
                try:
                    got = np.asarray(fab.solve(s, b))
                    break
                except Exception:  # noqa: BLE001 — fail-over window
                    if time.perf_counter() - t1 > 20.0:
                        raise
                    time.sleep(0.02)
            assert np.array_equal(want[s], got), s


# --------------------------------------------------------------------------- #
# the replay harness at deterministic small scale
# --------------------------------------------------------------------------- #


def _load_replay():
    path = os.path.join(os.path.dirname(__file__), "..", "scripts",
                        "replay.py")
    spec = importlib.util.spec_from_file_location("replay_mod", path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules.setdefault("replay_mod", mod)
    spec.loader.exec_module(mod)
    return mod


def test_replay_control_plane_leg_equivalence():
    replay = _load_replay()
    out = replay.control_plane_leg(fleet=300, pairs=4,
                                   victims_per_pick=6,
                                   touches_per_round=200, seed=3)
    assert out["victim_set_mismatches"] == 0
    assert out["sort_us_per_victim_p50"] > 0
    assert out["heap_us_per_victim_p50"] > 0


def test_replay_schedule_deterministic():
    replay = _load_replay()
    a = replay.make_schedule(np.random.default_rng(5), 50, 2.0, 10.0,
                             storms=2, storm_frac=0.1)
    bb = replay.make_schedule(np.random.default_rng(5), 50, 2.0, 10.0,
                              storms=2, storm_frac=0.1)
    assert a == bb  # same seed, same scenario — replayable
    assert a == sorted(a, key=lambda e: e[0])
    kinds = {e[1] for e in a}
    assert kinds == {"solve", "update"}
    assert all(0 <= e[2] < 50 for e in a)
