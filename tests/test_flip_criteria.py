"""The pre-decided default-flip criteria applier: parsing of tpu_tune
log lines and deterministic ADOPT/KEEP decisions (docs/ROUND3.md)."""

import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "scripts"))

from apply_flip_criteria import evaluate_flip, main, parse_log  # noqa: E402

LOG = """\
=== LU flat-tree + segmentation A/B at N=32768 ===
algo=lu precision=highest chunk=8192 v=1024 segs=lib tree=pairwise \
swap=xla update=segments: 10500.0 GFLOP/s
    residual=2.900e-05
algo=lu precision=highest chunk=8192 v=1024 segs=lib tree=flat \
swap=xla update=segments: 11000.0 GFLOP/s
    residual=2.950e-05
algo=lu precision=highest chunk=8192 v=1024 segs=lib tree=pairwise \
swap=xla update=block: 10600.0 GFLOP/s
    residual=3.000e-05
algo=lu precision=highest chunk=12288 v=1024 segs=lib tree=pairwise \
swap=xla update=segments: 10550.0 GFLOP/s
    residual FAILED: wedged
"""


def test_parse_log():
    recs = parse_log(LOG)
    assert len(recs) == 4
    assert recs[0]["tree"] == "pairwise" and recs[0]["gflops"] == 10500.0
    assert recs[1]["residual"] == 2.95e-05
    assert recs[3]["residual"] is None  # FAILED line never attaches


def test_flat_tree_adopted_on_gain_and_clean_residual():
    o = evaluate_flip(parse_log(LOG), "tree", "flat", "pairwise")
    assert o["decision"] == "ADOPT"
    assert abs(o["gain"] - (11000 / 10500 - 1)) < 1e-9


def test_block_update_kept_below_gain_bar():
    o = evaluate_flip(parse_log(LOG), "update", "block", "segments")
    assert o["decision"].startswith("KEEP (gain below")


def test_chunk_kept_without_residual():
    """A record whose residual line FAILED can never be adopted — the
    at-scale residual gate is mandatory (DESIGN §14)."""
    o = evaluate_flip(parse_log(LOG), "chunk", "12288", "8192")
    assert o["decision"].startswith("KEEP (residual gate failed")


def test_no_data_criterion():
    # 'swap' survives only in historical logs (the knob was removed in
    # round 4); a flip with no matching rows must report NO-DATA
    o = evaluate_flip(parse_log(LOG), "swap", "dma", "xla")
    assert o["decision"] == "NO-DATA"


def test_residual_dirty_flip_rejected():
    dirty = LOG.replace("residual=2.950e-05", "residual=5.000e-04")
    o = evaluate_flip(parse_log(dirty), "tree", "flat", "pairwise")
    assert o["decision"].startswith("KEEP (residual gate failed")


def test_emit_rules_roundtrips_into_autotune(tmp_path, capsys):
    log = tmp_path / "rec.txt"
    log.write_text(LOG)
    rules = tmp_path / "rules.json"
    assert main([str(log), "--emit-rules", str(rules)]) == 0
    out = capsys.readouterr().out
    assert "criterion tree: ADOPT" in out
    from conflux_tpu import autotune

    autotune.reset_loaded_table()
    try:
        assert autotune.load_table(str(rules)) == 1
        r = autotune.recommended("lu", 32768, device_kind="tpu v5 lite")
        assert r.knobs["tree"] == "flat"  # best clean record wins
        assert "chip-session A/B" in r.provenance
    finally:
        autotune.reset_loaded_table()
    data = json.loads(rules.read_text())
    assert data[0]["knobs"]["panel_chunk"] == 8192


def test_emit_rules_encodes_decisions_not_best_record(tmp_path, capsys):
    """A KEEP'd flip must not become a table default just because its
    record is the global best: the emitted rule follows the printed
    decisions (and never adopts dma/12288 — those have their own
    criteria outside this script)."""
    # flat gains only +1% (below the bar) yet is the best clean record
    log = tmp_path / "rec.txt"
    log.write_text(LOG.replace("11000.0 GFLOP/s", "10605.0 GFLOP/s"))
    rules = tmp_path / "rules.json"
    assert main([str(log), "--emit-rules", str(rules)]) == 0
    out = capsys.readouterr().out
    assert "criterion tree: KEEP (gain below" in out
    data = json.loads(rules.read_text())
    assert data[0]["knobs"]["tree"] == "pairwise"
    assert "swap" not in data[0]["knobs"]  # knob removed in round 4
    assert data[0]["knobs"]["panel_chunk"] == 8192


def test_emit_rules_refuses_without_clean_record(tmp_path, capsys):
    log = tmp_path / "rec.txt"
    log.write_text(LOG.replace("residual=", "residual FAILED was "))
    rules = tmp_path / "rules.json"
    assert main([str(log), "--emit-rules", str(rules)]) == 2
    assert "NOT writing" in capsys.readouterr().out
    assert not rules.exists()


def test_dirty_flip_does_not_mask_clean_pair():
    """A FAILED-residual flip timing must not shadow a clean adoptable
    pair of the same criterion (DESIGN §14 gates adoption, not
    consideration of the clean record)."""
    log = LOG + (
        "algo=lu precision=highest chunk=8192 v=1024 segs=lib tree=flat "
        "swap=xla update=segments: 11500.0 GFLOP/s\n"
        "    residual FAILED: wedge\n")
    o = evaluate_flip(parse_log(log), "tree", "flat", "pairwise")
    assert o["decision"] == "ADOPT"       # the clean 11000 pair decides
    assert o["flip"]["gflops"] == 11000.0


def test_off_baseline_pair_cannot_decide():
    """A flip winning only under some OTHER non-default knob (here
    segs=32x16) must not flip the global default: the decisive pair is
    restricted to the all-defaults baseline config (ADVICE r4 #2)."""
    # flat gains +20% under segs=32x16 but only +1% on the baseline
    log = LOG.replace("11000.0", "10605.0") + (
        "algo=lu precision=highest chunk=8192 v=1024 segs=32x16 "
        "tree=pairwise swap=xla update=segments: 10000.0 GFLOP/s\n"
        "    residual=2.900e-05\n"
        "algo=lu precision=highest chunk=8192 v=1024 segs=32x16 "
        "tree=flat swap=xla update=segments: 12000.0 GFLOP/s\n"
        "    residual=2.900e-05\n")
    o = evaluate_flip(parse_log(log), "tree", "flat", "pairwise")
    assert o["decision"].startswith("KEEP (gain below")
    assert o["flip"]["gflops"] == 10605.0  # the baseline-config pair


def test_dirty_baseline_does_not_block_adoption():
    """BOTH pair sides prefer residual-clean records: a FAILED-residual
    baseline timing (untrustworthy — DESIGN §14 saw corrupted runs time
    fast) must not out-shout the clean baseline and mask a real
    adoptable gain."""
    log = LOG + (
        "algo=lu precision=highest chunk=8192 v=1024 segs=lib "
        "tree=pairwise swap=xla update=segments: 12000.0 GFLOP/s\n"
        "    residual FAILED: wedge\n")
    o = evaluate_flip(parse_log(log), "tree", "flat", "pairwise")
    assert o["decision"] == "ADOPT"          # judged vs the clean 10500
    assert o["base"]["gflops"] == 10500.0


def test_off_baseline_win_is_surfaced_as_context():
    """When an off-baseline flip row out-gains the decisive pair, the
    detail line says so (a re-measure hint) — without deciding."""
    log = LOG.replace("11000.0", "10605.0") + (
        "algo=lu precision=highest chunk=8192 v=1024 segs=32x16 "
        "tree=flat swap=xla update=segments: 12000.0 GFLOP/s\n"
        "    residual=2.900e-05\n")
    o = evaluate_flip(parse_log(log), "tree", "flat", "pairwise")
    assert o["decision"].startswith("KEEP (gain below")
    assert "off-baseline context" in o["detail"]
    assert "segs=32x16" in o["detail"]


def test_off_baseline_only_reports_no_data():
    """With ONLY off-baseline flip rows, the criterion is NO-DATA (and
    says the off-baseline rows exist), never an adoption."""
    log = (
        "algo=lu precision=highest chunk=8192 v=1024 segs=32x16 "
        "tree=flat swap=xla update=segments: 12000.0 GFLOP/s\n"
        "    residual=2.900e-05\n"
        "algo=lu precision=highest chunk=8192 v=1024 segs=lib "
        "tree=pairwise swap=xla update=segments: 10000.0 GFLOP/s\n"
        "    residual=2.900e-05\n")
    o = evaluate_flip(parse_log(log), "tree", "flat", "pairwise")
    assert o["decision"] == "NO-DATA"
    assert "off-baseline" in o["detail"]


def test_lookahead_token_and_criterion():
    """Round-5 lines carry a lookahead=on|off token (older logs parse as
    'off'); the lookahead criterion decides on the all-defaults pair
    like any other knob and the emitted rule encodes the decision."""
    log = LOG + (
        "algo=lu precision=highest chunk=8192 v=1024 segs=lib "
        "tree=pairwise lookahead=on update=segments: 10400.0 GFLOP/s\n"
        "    residual=2.900e-05\n")
    recs = parse_log(log)
    assert all(r["lookahead"] == "off" for r in recs[:4])  # legacy lines
    assert recs[4]["lookahead"] == "on"
    o = evaluate_flip(recs, "lookahead", "on", "off")
    assert o["decision"].startswith("KEEP (gain below")  # 10400 < 10500*1.02


def test_emit_rules_lookahead_knob(tmp_path, capsys):
    log = tmp_path / "rec.txt"
    log.write_text(LOG)
    rules = tmp_path / "rules.json"
    assert main([str(log), "--emit-rules", str(rules)]) == 0
    data = json.loads(rules.read_text())
    assert data[0]["knobs"]["lookahead"] is False  # NO-DATA -> stays off


def test_headline_check(tmp_path, capsys):
    log = tmp_path / "rec.txt"
    log.write_text(LOG + '\n{"metric": "distributed LU N=32768 v=1024 '
                   'f32 GFLOP/s (single chip)", "value": 11892.0, '
                   '"unit": "GFLOP/s", "vs_baseline": 1.1, '
                   '"residual": 2.9e-05}\n')
    main([str(log)])
    out = capsys.readouterr().out
    assert "headline: 11892 GFLOP/s" in out and "MEETS" in out
