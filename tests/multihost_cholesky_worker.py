"""Worker for the two-process multihost Cholesky test (`test_multihost.py`).

Same execution model as `multihost_worker.py` (the LU form): each
process brings up `jax.distributed`, contributes 4 virtual CPU devices
to an 8-device mesh, materializes ONLY its own block-cyclic shards from
an SPD position formula, factors with the distributed 2.5D Cholesky, and
validates gather-free on the mesh.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(__file__))
import mh_common  # noqa: F401  (must precede jax backend init)

pid, nproc, port = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
grid_arg = sys.argv[4] if len(sys.argv) > 4 else "4,2,1"

import jax  # noqa: E402
import numpy as np  # noqa: E402

from conflux_tpu.cholesky.distributed import (  # noqa: E402
    cholesky_factor_distributed,
)
from conflux_tpu.geometry import CholeskyGeometry, Grid3  # noqa: E402
from conflux_tpu.parallel.mesh import (  # noqa: E402
    distribute_shards,
    initialize_multihost,
    make_mesh,
)
from conflux_tpu.validation import cholesky_residual_distributed  # noqa: E402

initialize_multihost(f"localhost:{port}", nproc, pid)
assert len(jax.devices()) == 8, jax.devices()

grid = Grid3.parse(grid_arg)
v = 8
geom = CholeskyGeometry.create(v * 8, v, grid)
mesh = make_mesh(grid, devices=jax.devices()[: grid.P])

calls: list[tuple[int, int]] = []


def local_shard(px, py):
    calls.append((px, py))
    # the library's tile-local SPD generator (the reference's per-rank
    # InitMatrix role) — exactly one device's shard, no global matrix
    from conflux_tpu.io import generate_spd_local

    return generate_spd_local(geom, px, py, dtype=np.float32)


shards = distribute_shards(
    local_shard, mesh, shape=(grid.Px, grid.Py, geom.Ml, geom.Nl),
    dtype=np.float32)
out = cholesky_factor_distributed(shards, geom, mesh)
res = float(cholesky_residual_distributed(shards, out, geom, mesh))
n_local = len(set(calls))
mine = mh_common.my_shard_coords(mesh)
print(f"proc {pid}: local_shards={n_local} residual={res:.3e}", flush=True)
assert n_local == len(mine), (pid, sorted(set(calls)), mine)
assert res < 1e-5, res
