"""Direct solvers + mixed-precision iterative refinement (HPL-MxP mode)."""

import jax.numpy as jnp
import numpy as np
import pytest

from conflux_tpu.solvers import cholesky_solve, lu_solve, solve
from conflux_tpu.validation import make_spd_matrix, make_test_matrix


def _relerr(A, x, b):
    r = np.asarray(A) @ np.asarray(x) - np.asarray(b)
    return np.linalg.norm(r) / np.linalg.norm(np.asarray(b))


def test_lu_solve_direct():
    N = 128
    A = make_test_matrix(N, N, seed=1)
    b = np.linspace(-1, 1, N)
    from conflux_tpu.lu.single import lu_factor_blocked

    LU, perm = lu_factor_blocked(jnp.asarray(A), v=16)
    x = lu_solve(LU, perm, jnp.asarray(b))
    assert _relerr(A, x, b) < 1e-10


def test_lu_solve_multiple_rhs():
    N = 64
    A = make_test_matrix(N, N, seed=2)
    B = make_test_matrix(N, 3, seed=3)
    from conflux_tpu.lu.single import lu_factor_blocked

    LU, perm = lu_factor_blocked(jnp.asarray(A), v=16)
    X = lu_solve(LU, perm, jnp.asarray(B))
    assert X.shape == (N, 3)
    assert _relerr(A, X, B) < 1e-10


def test_cholesky_solve_direct():
    N = 128
    A = make_spd_matrix(N, seed=4)
    b = np.cos(np.arange(N))
    from conflux_tpu.cholesky.single import cholesky_blocked

    L = cholesky_blocked(jnp.asarray(A), v=32)
    x = cholesky_solve(L, jnp.asarray(b))
    assert _relerr(A, x, b) < 1e-10


@pytest.mark.parametrize("spd", [False, True])
def test_solve_wrapper(spd):
    N = 96
    A = make_spd_matrix(N, seed=5) if spd else make_test_matrix(N, N, seed=5)
    b = np.sin(np.arange(N))
    x = solve(jnp.asarray(A), jnp.asarray(b), v=32, spd=spd)
    assert _relerr(A, x, b) < 1e-10


def test_solve_bf16_factors_refined():
    """bf16 factorization + refinement reaches f32-grade accuracy; without
    refinement it stays at bf16 grade — the HPL-MxP effect. Richardson
    refinement needs cond(A) * err(factors) < 1, so the system is made
    diagonally dominant (the regime the docstring documents)."""
    N = 256
    A = make_test_matrix(N, N, dtype=np.float32, seed=6)
    A[np.arange(N), np.arange(N)] += 16.0
    b = np.linspace(-1, 1, N).astype(np.float32)
    raw = solve(jnp.asarray(A), jnp.asarray(b), v=64,
                factor_dtype=jnp.bfloat16, refine=0)
    ref = solve(jnp.asarray(A), jnp.asarray(b), v=64,
                factor_dtype=jnp.bfloat16, refine=3)
    err_raw = _relerr(A, raw, b)
    err_ref = _relerr(A, ref, b)
    assert err_raw > 1e-4  # bf16 factors alone are coarse
    assert err_ref < 1e-5, (err_raw, err_ref)
    assert err_ref < err_raw / 10


def test_solve_refined_spd():
    N = 256
    A = make_spd_matrix(N, seed=7).astype(np.float32)
    b = np.cos(np.arange(N)).astype(np.float32)
    x = solve(jnp.asarray(A), jnp.asarray(b), v=64, spd=True,
              factor_dtype=jnp.bfloat16, refine=3)
    assert _relerr(A, x, b) < 1e-5


def test_lu_solve_rejects_rectangular():
    from conflux_tpu.lu.single import lu_factor_blocked

    A = make_test_matrix(64, 32, seed=8)
    LU, perm = lu_factor_blocked(jnp.asarray(A), v=16)
    with pytest.raises(ValueError):
        lu_solve(LU, perm, jnp.zeros(32))
    with pytest.raises(ValueError):
        lu_solve(jnp.zeros((32, 32)), jnp.arange(32), jnp.zeros(16))


def test_solve_clamps_tile_size():
    # N=100 is no multiple of the default v: solve identity-pads to 256
    N = 100
    A = make_test_matrix(N, N, seed=9)
    b = np.ones(N)
    x = solve(jnp.asarray(A), jnp.asarray(b))
    assert x.shape == (N,)
    assert _relerr(A, x, b) < 1e-10


def test_solve_prime_dim_pads_not_unrolls():
    # prime N used to fall back to v=1 (N unrolled supersteps at trace
    # time); identity padding keeps the superstep count bounded
    N = 211
    A = make_test_matrix(N, N, seed=12)
    b = np.ones(N)
    x = solve(jnp.asarray(A), jnp.asarray(b), v=64)
    assert x.shape == (N,)
    assert _relerr(A, x, b) < 1e-10
    B = np.stack([b, 2 * b], axis=1)
    X = solve(jnp.asarray(A), jnp.asarray(B), v=64, spd=False)
    assert X.shape == (N, 2)
    assert _relerr(A, X[:, 1], 2 * b) < 1e-10


def test_solve_prime_dim_spd():
    from conflux_tpu.validation import make_spd_matrix

    N = 127
    A = make_spd_matrix(N, seed=3)
    b = np.ones(N)
    x = solve(jnp.asarray(A), jnp.asarray(b), v=64, spd=True)
    assert x.shape == (N,)
    assert _relerr(A, x, b) < 1e-10


def test_top_level_solve_is_callable_twice():
    # the lazy package attribute must not be shadowed by the solvers module
    import conflux_tpu

    for _ in range(2):
        fn = conflux_tpu.solve
        assert callable(fn) and not hasattr(fn, "__path__"), fn


def test_lu_solve_distributed_matches_single():
    import jax

    from conflux_tpu.geometry import Grid3, LUGeometry
    from conflux_tpu.lu.distributed import lu_factor_distributed
    from conflux_tpu.parallel.mesh import make_mesh
    from conflux_tpu.solvers import lu_solve_distributed

    N, vt = 64, 8
    grid = Grid3(2, 2, 2)
    geom = LUGeometry.create(N, N, vt, grid)
    mesh = make_mesh(grid, devices=jax.devices()[:8])
    A = make_test_matrix(N, N, seed=12)
    b = np.linspace(-1, 1, N)

    shards, perm = lu_factor_distributed(
        jnp.asarray(geom.scatter(A)), geom, mesh
    )
    x = lu_solve_distributed(shards, perm, geom, mesh, jnp.asarray(b))
    assert x.shape == (N,)
    assert _relerr(A, x, b) < 1e-10


def test_solve_distributed_refined():
    """Full at-scale solve path: distributed factor + mesh solve + IR with
    an f64 residual must reach f64-grade accuracy from f32 factors."""
    import jax

    from conflux_tpu.geometry import Grid3
    from conflux_tpu.solvers import solve_distributed

    N = 128
    A = make_test_matrix(N, N, seed=17, dtype=np.float32)
    b = np.linspace(-1, 1, N).astype(np.float32)
    x = solve_distributed(jnp.asarray(A), jnp.asarray(b), grid=Grid3(2, 2, 1),
                          v=16, mesh=None, refine=3)
    assert _relerr(A, np.asarray(x, np.float64), b) < 1e-9


def test_solve_distributed_bf16_factors():
    from conflux_tpu.geometry import Grid3
    from conflux_tpu.solvers import solve_distributed

    N = 128
    A = make_test_matrix(N, N, seed=18, dtype=np.float32)
    # IR with bf16 factors converges only while cond(A)*eps_bf16 << 1
    # (eps_bf16 ~ 8e-3): boost the diagonal well past the random part's
    # spectral norm (~13 at N=128)
    A += 32 * np.eye(N, dtype=np.float32)
    b = np.ones(N, np.float32)
    x = solve_distributed(jnp.asarray(A), jnp.asarray(b), grid=Grid3(2, 1, 1),
                          v=16, refine=6, factor_dtype=jnp.bfloat16)
    assert _relerr(A, np.asarray(x, np.float64), b) < 1e-7


def test_fgmres_exact_preconditioner_one_cycle():
    """With an exact inverse as preconditioner, FGMRES converges in the
    first Arnoldi step — the identity sanity check of the engine."""
    from conflux_tpu.solvers import fgmres

    rng = np.random.default_rng(7)
    N = 96
    A = rng.standard_normal((N, N)) + 4 * np.eye(N)
    b = rng.standard_normal(N)
    Ad = jnp.asarray(A, jnp.float64)
    Ainv = jnp.asarray(np.linalg.inv(A), jnp.float64)
    x, info = fgmres(lambda v: Ad @ v, lambda r: Ainv @ r,
                     jnp.asarray(b, jnp.float64), tol=1e-12, restart=4)
    assert info["restarts"] == 1
    assert info["residual"] < 1e-12
    np.testing.assert_allclose(np.asarray(x), np.linalg.solve(A, b),
                               rtol=1e-9)


def test_fgmres_beats_classic_ir_on_bf16_factors():
    """The GMRES-IR claim (HPL-MxP): on a matrix where classic IR with
    bf16 factors contracts at ~0.7/sweep (cond ~1e3 — measured: 10 sweeps
    still stall above 1e-2), FGMRES preconditioned by the SAME factors
    reaches 1e-6."""
    from conflux_tpu.lu.single import lu_factor_blocked
    from conflux_tpu.solvers import fgmres, lu_solve

    N = 512
    A = make_test_matrix(N, N, dtype=np.float32)  # cond ~1.4e3
    b = np.ones(N, np.float32)
    LU, perm = lu_factor_blocked(jnp.asarray(A).astype(jnp.bfloat16), v=64)
    Ad = jnp.asarray(A)

    # classic IR baseline: verify it genuinely stalls on this problem
    b_r = jnp.asarray(b, jnp.float64)
    x = lu_solve(LU, perm, jnp.asarray(b)).astype(jnp.float64)
    from conflux_tpu.solvers import _residual_strips
    for _ in range(6):
        r = _residual_strips(Ad, x, b_r, jnp.float64)
        x = x + lu_solve(LU, perm, r.astype(jnp.float32)).astype(jnp.float64)
    r = _residual_strips(Ad, x, b_r, jnp.float64)
    classic = float(jnp.linalg.norm(r) / jnp.linalg.norm(b_r))
    assert classic > 1e-4, f"classic IR unexpectedly converged: {classic}"

    xg, info = fgmres(
        lambda v: Ad.astype(jnp.float64) @ v,
        lambda rr: lu_solve(LU, perm, rr.astype(jnp.float32)),
        b_r, tol=1e-6, restart=16, max_restarts=8)
    assert info["residual"] <= 1e-6, info
    assert _relerr(A, np.asarray(xg, np.float64), b) < 1e-6


def test_solve_distributed_gmres_ir():
    """ir='gmres' end-to-end on the mesh: bf16 factors + FGMRES reach the
    1e-6 bar where refine= (classic) cannot on an ill-enough matrix."""
    from conflux_tpu.geometry import Grid3
    from conflux_tpu.solvers import solve_distributed

    N = 128
    A = make_test_matrix(N, N, seed=18, dtype=np.float32)  # no diag boost
    b = np.ones(N, np.float32)
    x = solve_distributed(jnp.asarray(A), jnp.asarray(b), grid=Grid3(2, 1, 1),
                          v=16, factor_dtype=jnp.bfloat16, ir="gmres",
                          tol=1e-8, restart=16, max_restarts=8)
    assert _relerr(A, np.asarray(x, np.float64), b) < 1e-8


def test_solve_distributed_rejects_padding():
    import pytest

    from conflux_tpu.geometry import Grid3
    from conflux_tpu.solvers import solve_distributed

    A = make_test_matrix(100, 100, dtype=np.float32)
    with pytest.raises(ValueError, match="multiple"):
        solve_distributed(jnp.asarray(A), jnp.ones(100), grid=Grid3(2, 2, 1),
                          v=16)
    # column-only padding (M fits, N doesn't) must hit the same guard
    B = make_test_matrix(64, 64, dtype=np.float32)
    with pytest.raises(ValueError, match="multiple"):
        solve_distributed(jnp.asarray(B), jnp.ones(64), grid=Grid3(1, 3, 1),
                          v=16)


def test_lu_solve_distributed_asymmetric_grid():
    import jax

    from conflux_tpu.geometry import Grid3, LUGeometry
    from conflux_tpu.lu.distributed import lu_factor_distributed
    from conflux_tpu.parallel.mesh import make_mesh
    from conflux_tpu.solvers import lu_solve_distributed

    N, vt = 64, 8
    grid = Grid3(4, 2, 1)
    geom = LUGeometry.create(N, N, vt, grid)
    mesh = make_mesh(grid, devices=jax.devices()[:8])
    A = make_test_matrix(geom.M, geom.N, seed=13)
    b = np.cos(np.arange(geom.M))

    shards, perm = lu_factor_distributed(
        jnp.asarray(geom.scatter(A)), geom, mesh
    )
    x = lu_solve_distributed(shards, perm, geom, mesh, jnp.asarray(b))
    assert _relerr(A, x, b) < 1e-10


def test_mesh_solves_multi_rhs():
    """Multi-RHS (LAPACK getrs/potrs semantics): all columns ride each
    substitution step together and match per-column solves exactly."""
    import jax

    from conflux_tpu.cholesky.distributed import cholesky_factor_distributed
    from conflux_tpu.geometry import CholeskyGeometry, Grid3, LUGeometry
    from conflux_tpu.lu.distributed import lu_factor_distributed
    from conflux_tpu.parallel.mesh import make_mesh
    from conflux_tpu.solvers import (
        cholesky_solve_distributed,
        lu_solve_distributed,
    )
    from conflux_tpu.validation import make_spd_matrix, make_test_matrix

    grid = Grid3(2, 2, 1)
    N, v, k = 64, 8, 3
    mesh = make_mesh(grid, devices=jax.devices()[: grid.P])
    rng = np.random.default_rng(4)
    B = rng.standard_normal((N, k)).astype(np.float32)

    geom = LUGeometry.create(N, N, v, grid)
    A = make_test_matrix(N, N, dtype=np.float32)
    lu_sh, perm = lu_factor_distributed(jnp.asarray(geom.scatter(A)), geom,
                                        mesh)
    X = np.asarray(lu_solve_distributed(lu_sh, perm, geom, mesh, B))
    assert X.shape == (N, k)
    for j in range(k):
        xj = np.asarray(lu_solve_distributed(lu_sh, perm, geom, mesh,
                                             B[:, j]))
        # the blocked triangular solve's rounding depends on the RHS
        # count, so agreement is f32-level, not bitwise
        np.testing.assert_allclose(X[:, j], xj, rtol=2e-4, atol=2e-5)
    assert np.linalg.norm(A @ X - B) / np.linalg.norm(B) < 1e-4

    cgeom = CholeskyGeometry.create(N, v, grid)
    S = make_spd_matrix(N, dtype=np.float32)
    L_sh = cholesky_factor_distributed(jnp.asarray(cgeom.scatter(S)), cgeom,
                                       mesh)
    Xc = np.asarray(cholesky_solve_distributed(L_sh, cgeom, mesh, B))
    assert Xc.shape == (N, k)
    assert np.linalg.norm(S @ Xc - B) / np.linalg.norm(B) < 1e-4


def test_lstsq_single():
    """QR least squares vs np.linalg.lstsq (well-conditioned, tall)."""
    import numpy as np
    from conflux_tpu.solvers import lstsq

    rng = np.random.default_rng(31)
    A = rng.standard_normal((200, 24))
    b = rng.standard_normal(200)
    x = np.asarray(lstsq(jnp.asarray(A), jnp.asarray(b)))
    x_ref = np.linalg.lstsq(A, b, rcond=None)[0]
    np.testing.assert_allclose(x, x_ref, atol=1e-9)


def test_lstsq_distributed_matches_single():
    import numpy as np
    import jax
    from conflux_tpu.geometry import Grid3
    from conflux_tpu.parallel.mesh import make_mesh
    from conflux_tpu.solvers import lstsq, lstsq_distributed

    rng = np.random.default_rng(37)
    Px, Ml, n = 4, 50, 16
    A = rng.standard_normal((Px * Ml, n))
    B = rng.standard_normal((Px * Ml, 3))  # multi-RHS
    mesh = make_mesh(Grid3(Px, 1, 1), devices=jax.devices()[:Px])
    for algo in ("tsqr", "cholesky"):
        X = np.asarray(lstsq_distributed(A.reshape(Px, Ml, n), mesh, B,
                                         algo=algo))
        X1 = np.asarray(lstsq(jnp.asarray(A), jnp.asarray(B)))
        np.testing.assert_allclose(X, X1, atol=1e-9, err_msg=algo)
        # normal-equations optimality: A^T (A X - B) ~ 0
        g = A.T @ (A @ X - B)
        assert np.abs(g).max() < 1e-9 * np.abs(A.T @ B).max() + 1e-8


def test_lstsq_bf16_factors_with_refinement():
    """HPL-MxP recipe on least squares: bf16 QR factors + refinement
    sweeps in f32 recover f32-grade accuracy on a consistent system."""
    import numpy as np
    from conflux_tpu.solvers import lstsq

    rng = np.random.default_rng(53)
    A = rng.standard_normal((256, 32)).astype(np.float32)
    x_true = rng.standard_normal(32).astype(np.float32)
    b = A @ x_true  # consistent: residual-free system
    x0 = np.asarray(lstsq(jnp.asarray(A), jnp.asarray(b),
                          factor_dtype=jnp.bfloat16))
    x3 = np.asarray(lstsq(jnp.asarray(A), jnp.asarray(b),
                          factor_dtype=jnp.bfloat16, refine=3))
    err0 = np.linalg.norm(x0 - x_true) / np.linalg.norm(x_true)
    err3 = np.linalg.norm(x3 - x_true) / np.linalg.norm(x_true)
    assert err0 > 1e-4          # bf16 factors alone are bf16-grade
    assert err3 < 50 * err0
    assert err3 < 1e-5          # refinement lands at f32 grade


def test_lu_solve_transposed():
    import numpy as np
    from conflux_tpu.lu.single import lu_factor_blocked
    from conflux_tpu.solvers import lu_solve_transposed

    rng = np.random.default_rng(73)
    N = 96
    A = (rng.standard_normal((N, N)) + 3 * np.eye(N))
    LU, perm = lu_factor_blocked(jnp.asarray(A), v=16)
    b = rng.standard_normal(N)
    x = np.asarray(lu_solve_transposed(LU, perm, jnp.asarray(b)))
    np.testing.assert_allclose(A.T @ x, b, atol=1e-9)


def test_slogdet_and_cond():
    import numpy as np
    from conflux_tpu.lu.single import lu_factor_blocked
    from conflux_tpu.solvers import cond_estimate_1, slogdet_from_lu

    rng = np.random.default_rng(79)
    N = 64
    A = rng.standard_normal((N, N)) + 3 * np.eye(N)
    LU, perm = lu_factor_blocked(jnp.asarray(A), v=16)
    sign, logabs = slogdet_from_lu(LU, perm)
    s_ref, l_ref = np.linalg.slogdet(A)
    assert sign == s_ref
    np.testing.assert_allclose(logabs, l_ref, rtol=1e-10)

    # Hager's estimate is a lower bound on ||A^{-1}||_1 within a small
    # factor in practice; check bracketing against the exact 1-norm cond
    exact = np.abs(A).sum(axis=0).max() * np.abs(np.linalg.inv(A)).sum(axis=0).max()
    est = cond_estimate_1(A, LU, perm)
    assert 0.1 * exact <= est <= 1.01 * exact, (est, exact)


def test_inv_from_lu():
    import numpy as np
    from conflux_tpu.lu.single import lu_factor_blocked
    from conflux_tpu.solvers import inv_from_lu

    rng = np.random.default_rng(83)
    N = 80
    A = rng.standard_normal((N, N)) + 3 * np.eye(N)
    LU, perm = lu_factor_blocked(jnp.asarray(A), v=16)
    Ainv = np.asarray(inv_from_lu(LU, perm))
    np.testing.assert_allclose(A @ Ainv, np.eye(N), atol=1e-9)


def test_qr_lstsq_distributed():
    """Distributed least squares through the block-cyclic QR factors:
    matches np.linalg.lstsq across grids (incl. Pz > 1) for tall and
    square systems, multi-RHS."""
    import numpy as np
    import jax
    from conflux_tpu.geometry import Grid3, LUGeometry
    from conflux_tpu.parallel.mesh import make_mesh
    from conflux_tpu.qr.distributed import qr_factor_distributed
    from conflux_tpu.solvers import qr_lstsq_distributed

    rng = np.random.default_rng(91)
    for gridspec, (M, N) in [((2, 2, 1), (64, 32)), ((2, 2, 2), (32, 32)),
                             ((4, 2, 1), (96, 48))]:
        grid = Grid3(*gridspec)
        geom = LUGeometry.create(M, N, 8, grid)
        mesh = make_mesh(grid, devices=jax.devices()[: grid.P])
        A = rng.standard_normal((geom.M, geom.N))
        B = rng.standard_normal((geom.M, 3))
        Qs, Rs = qr_factor_distributed(jnp.asarray(geom.scatter(A)), geom,
                                       mesh)
        X = np.asarray(qr_lstsq_distributed(Qs, Rs, geom, mesh, B))
        X_ref = np.linalg.lstsq(A, B, rcond=None)[0]
        np.testing.assert_allclose(X, X_ref, atol=1e-9,
                                   err_msg=str((gridspec, M, N)))

    # single-RHS squeeze semantics
    b = rng.standard_normal(geom.M)
    x = np.asarray(qr_lstsq_distributed(Qs, Rs, geom, mesh, b))
    assert x.shape == (geom.N,)
    np.testing.assert_allclose(x, np.linalg.lstsq(A, b, rcond=None)[0],
                               atol=1e-9)


def test_solver_utilities_complex():
    """Transpose solve / slogdet / inverse on complex inputs (the solver
    utilities must track the complex instantiation set like the cores)."""
    import numpy as np
    from conflux_tpu.lu.single import lu_factor_blocked
    from conflux_tpu.solvers import (
        inv_from_lu,
        lu_solve_transposed,
        slogdet_from_lu,
    )

    rng = np.random.default_rng(103)
    N = 48
    A = (rng.standard_normal((N, N))
         + 1j * rng.standard_normal((N, N))).astype(np.complex128)
    A[np.arange(N), np.arange(N)] += 3.0 + 1.0j
    LU, perm = lu_factor_blocked(jnp.asarray(A), v=16)
    b = (rng.standard_normal(N) + 1j * rng.standard_normal(N))
    x = np.asarray(lu_solve_transposed(LU, perm, jnp.asarray(b)))
    np.testing.assert_allclose(A.T @ x, b, atol=1e-10)
    sign, logabs = slogdet_from_lu(LU, perm)
    s_ref, l_ref = np.linalg.slogdet(A)
    assert np.iscomplexobj(sign)
    np.testing.assert_allclose(sign, s_ref, atol=1e-10)
    np.testing.assert_allclose(logabs, l_ref, rtol=1e-10)
    Ainv = np.asarray(inv_from_lu(LU, perm))
    np.testing.assert_allclose(A @ Ainv, np.eye(N), atol=1e-10)
