"""True multi-process execution of the distributed LU.

The reference's multi-rank path is MPI SPMD; the TPU equivalent is
`jax.distributed` — multiple host processes, each owning a slice of the
global device set, running the SAME jitted shard_map program. The CPU-mesh
tests in this suite simulate 8 devices in ONE process; this test runs the
real thing: two OS processes x 4 virtual CPU devices each, gloo
collectives between them, block-cyclic shards materialized per process
from a position formula (never the global matrix), and the gather-free
on-mesh residual check.
"""

import os
import socket
import subprocess
import sys

import pytest


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


@pytest.mark.slow
@pytest.mark.parametrize("gridspec,shards_per_proc", [
    ("4,2,1", 4),   # x axis split across the two processes
    ("2,2,2", 2),   # z-replication spans processes: 2 shards x 2 layers
])
def test_two_process_multihost_lu(gridspec, shards_per_proc):
    worker = os.path.join(os.path.dirname(__file__), "multihost_worker.py")
    port = str(_free_port())
    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    procs = [
        subprocess.Popen(
            [sys.executable, worker, str(pid), "2", port, gridspec],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env, cwd=os.path.dirname(worker),
        )
        for pid in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=240)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"proc {pid} failed:\n{out[-3000:]}"
        assert (f"proc {pid}: local_shards={shards_per_proc} residual="
                in out)
