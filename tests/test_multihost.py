"""True multi-process execution of the distributed LU.

The reference's multi-rank path is MPI SPMD; the TPU equivalent is
`jax.distributed` — multiple host processes, each owning a slice of the
global device set, running the SAME jitted shard_map program. The CPU-mesh
tests in this suite simulate 8 devices in ONE process; these tests run the
real thing: two OS processes x 4 virtual CPU devices each, gloo
collectives between them, block-cyclic shards materialized per process
from a position formula (never the global matrix), gather-free on-mesh
validation, bounded-time failure detection, and checkpoint-based recovery
with a fresh process set.
"""

import os
import socket
import subprocess
import sys
import time

import pytest


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def _run_workers(worker: str, extra_args: list[str], nproc: int = 2,
                 timeout: int = 240):
    """Spawn one worker process per pid, collect (returncode, output) for
    each, killing stragglers on the way out."""
    port = str(_free_port())
    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    path = os.path.join(os.path.dirname(__file__), worker)
    procs = [
        subprocess.Popen(
            [sys.executable, path, str(pid), str(nproc), port, *extra_args],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env, cwd=os.path.dirname(path),
        )
        for pid in range(nproc)
    ]
    results = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=timeout)
            results.append((p.returncode, out))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()
    return results


@pytest.mark.slow
@pytest.mark.parametrize("gridspec,shards_per_proc,election", [
    ("4,2,1", (4, 4), "gather"),  # x axis split across the two processes
    ("2,2,2", (2, 2), "gather"),  # z-replication spans: 2 shards x 2 layers
    # odd Px across the process boundary: the butterfly's overflow-rank
    # fold/unfold (x=2 lives on process 1) runs over real gloo
    # collectives; process 0 owns 4 shards, process 1 the x=2 row's 2
    ("3,2,1", (4, 2), "butterfly"),
])
def test_two_process_multihost_lu(gridspec, shards_per_proc, election):
    results = _run_workers("multihost_worker.py", [gridspec, election])
    for pid, (rc, out) in enumerate(results):
        assert rc == 0, f"proc {pid} failed:\n{out[-3000:]}"
        assert (f"proc {pid}: local_shards={shards_per_proc[pid]} residual="
                in out)


@pytest.mark.slow
def test_three_process_multihost_lu_butterfly():
    """THREE host processes (4 virtual devices each, 3x2x2 grid): one
    x-row of the grid per process, so the odd-Px butterfly's fold/unfold
    AND the 2.5D z-psum both cross process boundaries; beyond the
    two-process coverage, this exercises a gloo collective group larger
    than a pair."""
    results = _run_workers("multihost_worker.py", ["3,2,2", "butterfly"],
                           nproc=3, timeout=360)
    for pid, (rc, out) in enumerate(results):
        assert rc == 0, f"proc {pid} failed:\n{out[-3000:]}"
        # each process owns exactly its x-row's 2 (x, y) shard coords
        assert f"proc {pid}: local_shards=2 residual=" in out


@pytest.mark.slow
def test_two_process_multihost_cholesky():
    """Core parity: the distributed Cholesky runs the same real
    two-process model as the LU (jax.distributed, per-process shard
    materialization, gather-free on-mesh validation)."""
    results = _run_workers("multihost_cholesky_worker.py", ["2,2,2"])
    for pid, (rc, out) in enumerate(results):
        assert rc == 0, f"proc {pid} failed:\n{out[-3000:]}"
        assert f"proc {pid}: local_shards=2 residual=" in out


@pytest.mark.slow
def test_peer_failure_detected_in_bounded_time():
    """Failure detection (beyond the reference, which has none: a lost MPI
    rank hangs the job): when one process dies, the coordination service's
    heartbeat watchdog must terminate the survivor in bounded time instead
    of letting it hang on the next collective."""
    worker = os.path.join(os.path.dirname(__file__),
                          "multihost_failure_worker.py")
    port = str(_free_port())
    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}

    def spawn(pid, role):
        return subprocess.Popen(
            [sys.executable, worker, str(pid), "2", port, role],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env, cwd=os.path.dirname(worker),
        )

    t0 = time.time()
    survivor, dier = spawn(0, "survive"), spawn(1, "die")
    try:
        out_d, _ = dier.communicate(timeout=120)
        assert dier.returncode == 17, out_d[-2000:]
        # worker gives up (exit 3, "never aborted") at 120s; communicate's
        # timeout sits above that so the clear assertion below fires
        # rather than an opaque TimeoutExpired
        out_s, _ = survivor.communicate(timeout=150)
    finally:
        for p in (survivor, dier):
            if p.poll() is None:
                p.kill()
                p.wait()
    elapsed = time.time() - t0
    # aborted by the watchdog: nonzero (and not the worker's own exit 3)
    assert survivor.returncode not in (0, 3), out_s[-2000:]
    assert "survivor was never aborted" not in out_s
    assert elapsed < 110, elapsed


@pytest.mark.slow
def test_failure_recovery_new_processes_resume_from_checkpoint(tmp_path):
    """Full recovery story (beyond the reference, which loses the run):
    a process pair factors half the supersteps, checkpoints per-process
    shards, and exits; a brand-new pair resumes from the checkpoint and
    finishes with a valid factorization."""
    ckpt = str(tmp_path)
    outs1 = _run_workers("multihost_resume_worker.py", ["1", ckpt])
    for pid, (rc, out) in enumerate(outs1):
        assert rc == 0, f"phase1 proc {pid}:\n{out[-3000:]}"
        assert "phase1 checkpointed" in out
    outs2 = _run_workers("multihost_resume_worker.py", ["2", ckpt])
    for pid, (rc, out) in enumerate(outs2):
        assert rc == 0, f"phase2 proc {pid}:\n{out[-3000:]}"
        assert "phase2 residual=" in out


@pytest.mark.slow
def test_two_process_multihost_tsqr():
    """TSQR's (n, n) R all_gather crosses the process boundary; each
    worker validates reconstruction on its own shards and orthogonality
    via one psum — no global matrix anywhere."""
    results = _run_workers("multihost_qr_worker.py", [])
    for pid, (rc, out) in enumerate(results):
        assert rc == 0, f"proc {pid} failed:\n{out[-3000:]}"
        assert f"proc {pid}: qr rec=" in out
