"""True multi-process execution of the distributed LU.

The reference's multi-rank path is MPI SPMD; the TPU equivalent is
`jax.distributed` — multiple host processes, each owning a slice of the
global device set, running the SAME jitted shard_map program. The CPU-mesh
tests in this suite simulate 8 devices in ONE process; this test runs the
real thing: two OS processes x 4 virtual CPU devices each, gloo
collectives between them, block-cyclic shards materialized per process
from a position formula (never the global matrix), and the gather-free
on-mesh residual check.
"""

import os
import socket
import subprocess
import sys

import pytest


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


@pytest.mark.slow
@pytest.mark.parametrize("gridspec,shards_per_proc", [
    ("4,2,1", 4),   # x axis split across the two processes
    ("2,2,2", 2),   # z-replication spans processes: 2 shards x 2 layers
])
def test_two_process_multihost_lu(gridspec, shards_per_proc):
    worker = os.path.join(os.path.dirname(__file__), "multihost_worker.py")
    port = str(_free_port())
    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    procs = [
        subprocess.Popen(
            [sys.executable, worker, str(pid), "2", port, gridspec],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env, cwd=os.path.dirname(worker),
        )
        for pid in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=240)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"proc {pid} failed:\n{out[-3000:]}"
        assert (f"proc {pid}: local_shards={shards_per_proc} residual="
                in out)


@pytest.mark.slow
def test_peer_failure_detected_in_bounded_time():
    """Failure detection (beyond the reference, which has none: a lost MPI
    rank hangs the job): when one process dies, the coordination service's
    heartbeat watchdog must terminate the survivor in bounded time instead
    of letting it hang on the next collective."""
    import time

    worker = os.path.join(os.path.dirname(__file__),
                          "multihost_failure_worker.py")
    port = str(_free_port())
    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}

    def spawn(pid, role):
        return subprocess.Popen(
            [sys.executable, worker, str(pid), "2", port, role],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env, cwd=os.path.dirname(worker),
        )

    t0 = time.time()
    survivor, dier = spawn(0, "survive"), spawn(1, "die")
    try:
        out_d, _ = dier.communicate(timeout=120)
        assert dier.returncode == 17, out_d[-2000:]
        # worker gives up (exit 3, "never aborted") at 120s; communicate's
        # timeout sits above that so the clear assertion below fires
        # rather than an opaque TimeoutExpired
        out_s, _ = survivor.communicate(timeout=150)
    finally:
        for p in (survivor, dier):
            if p.poll() is None:
                p.kill()
                p.wait()
    elapsed = time.time() - t0
    # aborted by the watchdog: nonzero (and not the worker's own exit 3)
    assert survivor.returncode not in (0, 3), out_s[-2000:]
    assert "survivor was never aborted" not in out_s
    assert elapsed < 110, elapsed
