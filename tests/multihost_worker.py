"""Worker for the two-process multihost test (`test_multihost.py`).

Each process runs this script with (process_id, num_processes, port): it
brings up `jax.distributed` over localhost (the `MPI_Init` role,
reference `examples/conflux_miniapp.cpp:90`), contributes 4 virtual CPU
devices to an 8-device global mesh, materializes ONLY its own block-cyclic
shards — from a position formula, so no process ever holds the global
matrix (the reference's per-rank `InitMatrix` fill, `lu_params.hpp:141-376`)
— factors, and validates gather-free on the mesh.
"""

import os
import sys

pid, nproc, port = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
grid_arg = sys.argv[4] if len(sys.argv) > 4 else "4,2,1"
os.environ["XLA_FLAGS"] = ("--xla_force_host_platform_device_count=4 "
                           + os.environ.get("XLA_FLAGS", ""))
sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
import jax

jax.config.update("jax_platforms", "cpu")

from conflux_tpu.parallel.mesh import (  # noqa: E402
    distribute_shards,
    initialize_multihost,
    make_mesh,
)

initialize_multihost(f"localhost:{port}", nproc, pid)

import numpy as np  # noqa: E402

from conflux_tpu.geometry import Grid3, LUGeometry  # noqa: E402
from conflux_tpu.lu.distributed import lu_factor_distributed  # noqa: E402
from conflux_tpu.validation import lu_residual_distributed  # noqa: E402

assert len(jax.devices()) == 8, jax.devices()
grid = Grid3.parse(grid_arg)
v = 8
geom = LUGeometry.create(v * 8, v * 8, v, grid)
mesh = make_mesh(grid, devices=jax.devices()[: grid.P])

calls: list[tuple[int, int]] = []


def local_shard(px, py):
    """(Ml, Nl) shard straight from global indices — tile-local, the whole
    point of the callable `distribute_shards` form: a position-formula
    fill (diagonally dominant) evaluated only on owned coordinates."""
    calls.append((px, py))
    li = np.arange(geom.Ml)
    lj = np.arange(geom.Nl)
    gi = ((li // v) * grid.Px + px) * v + li % v  # global rows here
    gj = ((lj // v) * grid.Py + py) * v + lj % v
    G = np.sin(0.37 * gi[:, None] + 1.31 * gj[None, :]).astype(np.float32)
    return G + geom.M * (gi[:, None] == gj[None, :])


shards = distribute_shards(
    local_shard, mesh, shape=(grid.Px, grid.Py, geom.Ml, geom.Nl),
    dtype=np.float32)
out, perm = lu_factor_distributed(shards, geom, mesh)
res = float(lu_residual_distributed(shards, out, perm, geom, mesh))
n_local = len(set(calls))
# expected: the distinct (x, y) shard coordinates among THIS process's
# devices (z-replication means a shard can live on several local devices)
mine = {
    (ix, iy)
    for (ix, iy, iz), d in np.ndenumerate(mesh.devices)
    if d.process_index == jax.process_index()
}
print(f"proc {pid}: local_shards={n_local} residual={res:.3e}", flush=True)
# the callable form must touch only this process's addressable shards
assert n_local == len(mine), (pid, sorted(set(calls)), sorted(mine))
assert res < 1e-4, res
