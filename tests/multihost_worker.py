"""Worker for the two-process multihost test (`test_multihost.py`).

Each process runs this script with (process_id, num_processes, port,
grid): it brings up `jax.distributed` over localhost (the `MPI_Init`
role, reference `examples/conflux_miniapp.cpp:90`), contributes 4
virtual CPU devices to an 8-device global mesh, materializes ONLY its
own block-cyclic shards — from a position formula, so no process ever
holds the global matrix — factors, and validates gather-free on the
mesh.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(__file__))
import mh_common  # noqa: F401  (must precede jax backend init)

pid, nproc, port = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
grid_arg = sys.argv[4] if len(sys.argv) > 4 else "4,2,1"
election_arg = sys.argv[5] if len(sys.argv) > 5 else "gather"

import jax  # noqa: E402
import numpy as np  # noqa: E402

from conflux_tpu.geometry import Grid3, LUGeometry  # noqa: E402
from conflux_tpu.lu.distributed import lu_factor_distributed  # noqa: E402
from conflux_tpu.parallel.mesh import (  # noqa: E402
    distribute_shards,
    initialize_multihost,
    make_mesh,
)
from conflux_tpu.validation import lu_residual_distributed  # noqa: E402

initialize_multihost(f"localhost:{port}", nproc, pid)
assert len(jax.devices()) == 4 * nproc, jax.devices()

grid = Grid3.parse(grid_arg)
v = 8
geom = LUGeometry.create(v * 8, v * 8, v, grid)
mesh = make_mesh(grid, devices=jax.devices()[: grid.P])

calls: list[tuple[int, int]] = []


def local_shard(px, py):
    calls.append((px, py))
    return mh_common.pos_fill(geom, grid, px, py)


shards = distribute_shards(
    local_shard, mesh, shape=(grid.Px, grid.Py, geom.Ml, geom.Nl),
    dtype=np.float32)
out, perm = lu_factor_distributed(shards, geom, mesh,
                                  election=election_arg)
res = float(lu_residual_distributed(shards, out, perm, geom, mesh))
n_local = len(set(calls))
mine = mh_common.my_shard_coords(mesh)
print(f"proc {pid}: local_shards={n_local} residual={res:.3e}", flush=True)
# the callable form must touch only this process's addressable shards
assert n_local == len(mine), (pid, sorted(set(calls)), mine)
assert res < 1e-4, res
