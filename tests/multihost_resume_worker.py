"""Worker for the failure-recovery test (`test_multihost.py`).

phase=1: both processes factor supersteps [0, half), checkpoint the
state to a shared directory (each process writes only its own shards —
no global matrix anywhere), and exit: the simulated job loss.
phase=2: a NEW process pair loads the checkpoint and finishes
[half, n_steps), then validates on the mesh. The reference cannot do any
of this — a lost rank loses the whole factorization.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(__file__))
import mh_common  # noqa: F401  (must precede jax backend init)

pid, nproc, port, phase, ckpt = (int(sys.argv[1]), int(sys.argv[2]),
                                 sys.argv[3], int(sys.argv[4]), sys.argv[5])

import jax  # noqa: E402
import numpy as np  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from conflux_tpu.geometry import Grid3, LUGeometry  # noqa: E402
from conflux_tpu.io import load_matrix, save_matrix  # noqa: E402
from conflux_tpu.lu.distributed import lu_factor_steps  # noqa: E402
from conflux_tpu.parallel.mesh import (  # noqa: E402
    distribute_shards,
    initialize_multihost,
    make_mesh,
)
from conflux_tpu.validation import lu_residual_distributed  # noqa: E402

initialize_multihost(f"localhost:{port}", nproc, pid)

grid = Grid3(4, 2, 1)
v = 8
geom = LUGeometry.create(v * 8, v * 8, v, grid)
mesh = make_mesh(grid, devices=jax.devices()[: grid.P])
half = geom.n_steps // 2


def fill(px, py):
    return mh_common.pos_fill(geom, grid, px, py)


def shard_path(px, py, name):
    return os.path.join(ckpt, f"{name}_{px}_{py}.bin")


if phase == 1:
    shards = distribute_shards(
        fill, mesh, shape=(grid.Px, grid.Py, geom.Ml, geom.Nl),
        dtype=np.float32)
    s, o, _ = lu_factor_steps(shards, geom, mesh, 0, half)
    # checkpoint: every process saves ONLY its addressable shards + the
    # x-rows of the origin state it owns (int32 round-trips exactly)
    saved = set()
    for sh in s.addressable_shards:
        px, py = (sh.index[0].start or 0, sh.index[1].start or 0)
        if (px, py) not in saved:  # z-replicas carry identical data
            save_matrix(shard_path(px, py, "A"), np.asarray(sh.data)[0, 0])
            saved.add((px, py))
    for sh in o.addressable_shards:
        px = sh.index[0].start or 0
        save_matrix(os.path.join(ckpt, f"orig_{px}.bin"), np.asarray(sh.data))
    print(f"proc {pid}: phase1 checkpointed {len(saved)} shards", flush=True)
    sys.exit(0)

# phase 2: a fresh process pair resumes from the checkpoint (the test
# runs the phases strictly in sequence, so every file already exists)
shards = distribute_shards(
    lambda px, py: load_matrix(shard_path(px, py, "A")), mesh,
    shape=(grid.Px, grid.Py, geom.Ml, geom.Nl), dtype=np.float32)
orig = jnp.asarray(np.concatenate([
    load_matrix(os.path.join(ckpt, f"orig_{px}.bin"))
    for px in range(grid.Px)
], axis=0))
s, o, perm = lu_factor_steps(shards, geom, mesh, half, geom.n_steps,
                             orig=orig)
# validate against the ORIGINAL input, rebuilt from the position formula
orig_shards = distribute_shards(
    fill, mesh, shape=(grid.Px, grid.Py, geom.Ml, geom.Nl),
    dtype=np.float32)
res = float(lu_residual_distributed(orig_shards, s, perm, geom, mesh))
print(f"proc {pid}: phase2 residual={res:.3e}", flush=True)
assert res < 1e-4, res
