"""Cholesky (CONFCHOX side): residual oracle ||A - L L^T||_F across grids."""

import numpy as np
import pytest

from conflux_tpu.cholesky.single import cholesky_blocked
from conflux_tpu.cholesky.distributed import cholesky_distributed_host
from conflux_tpu.geometry import Grid3
from conflux_tpu.validation import cholesky_residual, make_spd_matrix, residual_bound

import jax.numpy as jnp


@pytest.mark.parametrize("N,v", [(32, 8), (64, 16), (64, 64)])
def test_cholesky_single(N, v):
    A = make_spd_matrix(N, seed=N + v)
    L = cholesky_blocked(jnp.asarray(A), v=v)
    assert cholesky_residual(A, np.asarray(L)) < residual_bound(N, np.float64)
    assert np.allclose(np.triu(np.asarray(L), 1), 0.0)


def test_cholesky_single_matches_numpy():
    A = make_spd_matrix(48)
    L = cholesky_blocked(jnp.asarray(A), v=16)
    np.testing.assert_allclose(np.asarray(L), np.linalg.cholesky(A), atol=1e-9)


GRIDS = [
    Grid3(1, 1, 1),
    Grid3(2, 1, 1),
    Grid3(1, 2, 1),
    Grid3(2, 2, 1),
    Grid3(1, 1, 2),
    Grid3(2, 2, 2),
    Grid3(4, 2, 1),
]


@pytest.mark.parametrize("grid", GRIDS, ids=str)
def test_cholesky_distributed_residual(grid):
    N, v = 64, 8
    A = make_spd_matrix(N, seed=grid.P * 3 + grid.Px)
    L, geom = cholesky_distributed_host(A, grid, v)
    assert geom.N == N
    res = cholesky_residual(A, L)
    assert res < residual_bound(N, np.float64), (grid, res)


def test_cholesky_distributed_matches_numpy():
    """No pivoting -> deterministic; must match the dense factor closely."""
    N, v = 32, 8
    A = make_spd_matrix(N, seed=123)
    L, _ = cholesky_distributed_host(A, Grid3(2, 2, 2), v)
    np.testing.assert_allclose(L, np.linalg.cholesky(A), atol=1e-8)


def test_cholesky_distributed_segs_invariant():
    """Segmentation (incl. the above-diagonal segment skip) partitions the
    same per-element math: any (row, col) segment counts must give a
    correct factor; the skipped strict-upper region is never read."""
    N, v = 64, 8
    A = make_spd_matrix(N, seed=7)
    for segs in [(4, 4), (1, 1), (3, 5), (8, 8)]:
        L, _ = cholesky_distributed_host(A, Grid3(2, 2, 2), v, segs=segs)
        res = cholesky_residual(A, L)
        assert res < residual_bound(N, np.float64), (segs, res)


def test_cholesky_distributed_padding():
    N, v = 50, 8
    A = make_spd_matrix(N, seed=31)
    L, geom = cholesky_distributed_host(A, Grid3(2, 2, 1), v)
    assert geom.N == 64
    assert cholesky_residual(A, L[:N, :N]) < residual_bound(N, np.float64)


def test_cholesky_distributed_f32():
    N, v = 64, 16
    A = make_spd_matrix(N, seed=8, dtype=np.float32)
    L, _ = cholesky_distributed_host(A, Grid3(2, 2, 1), v)
    assert cholesky_residual(A, L) < residual_bound(N, np.float32)


def test_cholesky_distributed_bf16():
    from conflux_tpu.cholesky.distributed import cholesky_factor_distributed
    from conflux_tpu.geometry import CholeskyGeometry
    from conflux_tpu.parallel.mesh import make_mesh
    import jax

    N, v = 64, 16
    grid = Grid3(2, 2, 1)
    A = make_spd_matrix(N, seed=4, dtype=np.float32)
    geom = CholeskyGeometry.create(N, v, grid)
    mesh = make_mesh(grid, devices=jax.devices()[: grid.P])
    shards = jnp.asarray(geom.scatter(A)).astype(jnp.bfloat16)
    out = cholesky_factor_distributed(shards, geom, mesh)
    assert out.dtype == jnp.bfloat16
    L = np.tril(geom.gather(np.asarray(out, dtype=np.float64)))
    res = cholesky_residual(A, L)
    # bf16 eps ~7.8e-3: accept c*eps*sqrt(N), reject the f32 regime below
    eps = 2.0 ** -7
    assert res < 0.5 * eps * np.sqrt(N), res
    assert res > 1e-7


def test_cholesky_solve_distributed():
    """Mesh solve from distributed Cholesky factors (the Cholesky twin of
    lu_solve_distributed)."""
    import jax

    from conflux_tpu.cholesky.distributed import cholesky_factor_distributed
    from conflux_tpu.geometry import CholeskyGeometry
    from conflux_tpu.parallel.mesh import make_mesh
    from conflux_tpu.solvers import cholesky_solve_distributed

    N, v = 64, 8
    for grid in (Grid3(2, 2, 1), Grid3(2, 2, 2), Grid3(4, 2, 1)):
        geom = CholeskyGeometry.create(N, v, grid)
        mesh = make_mesh(grid, devices=jax.devices()[: grid.P])
        A = make_spd_matrix(N, seed=grid.P)
        b = np.linspace(-1, 1, N)
        shards = jnp.asarray(geom.scatter(A))
        out = cholesky_factor_distributed(shards, geom, mesh)
        x = cholesky_solve_distributed(out, geom, mesh, jnp.asarray(b))
        relerr = np.linalg.norm(A @ np.asarray(x, np.float64) - b) / np.linalg.norm(b)
        assert relerr < 1e-10, (grid, relerr)


@pytest.mark.parametrize("gridspec", [(2, 2, 1), (2, 2, 2), (4, 2, 1)])
def test_cholesky_residual_distributed_matches_host(gridspec):
    """The on-mesh ||A - L L^T|| oracle must agree with the host oracle."""
    import jax

    from conflux_tpu.validation import (
        cholesky_residual,
        cholesky_residual_distributed,
    )

    from conflux_tpu.cholesky.distributed import cholesky_factor_distributed
    from conflux_tpu.geometry import CholeskyGeometry
    from conflux_tpu.parallel.mesh import make_mesh

    grid = Grid3(*gridspec)
    v = 8
    N = v * 8
    geom = CholeskyGeometry.create(N, v, grid)
    mesh = make_mesh(grid, devices=jax.devices()[: grid.P])
    A = make_spd_matrix(geom.N, dtype=np.float32)
    shards = jnp.asarray(geom.scatter(A))
    out = cholesky_factor_distributed(shards, geom, mesh)

    on_mesh = cholesky_residual_distributed(shards, out, geom, mesh)
    host = cholesky_residual(np.asarray(A, np.float64),
                             np.tril(geom.gather(np.asarray(out))))
    assert on_mesh < 1e-5
    np.testing.assert_allclose(on_mesh, host, rtol=0.3)


@pytest.mark.parametrize("gridspec", [(1, 1, 1), (2, 2, 2), (4, 2, 1)])
def test_cholesky_distributed_lookahead_bitwise_equal(gridspec):
    """The pipelined Cholesky loop must match the plain loop bitwise."""
    import jax

    from conflux_tpu.cholesky.distributed import cholesky_factor_distributed
    from conflux_tpu.geometry import CholeskyGeometry
    from conflux_tpu.parallel.mesh import make_mesh

    grid = Grid3(*gridspec)
    v = 8
    N = v * 8
    geom = CholeskyGeometry.create(N, v, grid)
    mesh = make_mesh(grid, devices=jax.devices()[: grid.P])
    A = make_spd_matrix(geom.N, dtype=np.float32)
    shards = jnp.asarray(geom.scatter(A))
    out_a = cholesky_factor_distributed(shards, geom, mesh)
    out_b = cholesky_factor_distributed(shards, geom, mesh, lookahead=True)
    np.testing.assert_allclose(np.asarray(out_a), np.asarray(out_b),
                               rtol=0, atol=0)


def test_cholesky_factor_distributed_odd_grid():
    """Non-power-of-two grids (3x2x1): ragged tile ownership on the x
    axis and odd-extent psums — the same grid-shape generality the LU
    core's odd-Px election now gates (round 4)."""
    import jax

    from conflux_tpu.geometry import CholeskyGeometry, Grid3
    from conflux_tpu.cholesky.distributed import cholesky_factor_distributed
    from conflux_tpu.parallel.mesh import make_mesh
    from conflux_tpu.validation import (
        cholesky_residual_distributed,
        make_spd_matrix,
    )

    grid = Grid3(3, 2, 1)
    geom = CholeskyGeometry.create(320, 32, grid)  # ragged: 10 tiles / 3
    mesh = make_mesh(grid, devices=jax.devices()[: grid.P])
    S = make_spd_matrix(geom.N, dtype=np.float32)
    shards = jnp.asarray(geom.scatter(S))
    L = cholesky_factor_distributed(shards, geom, mesh)
    res = float(cholesky_residual_distributed(shards, L, geom, mesh))
    assert res < 1e-6, res
