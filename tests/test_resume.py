"""Checkpoint/restart of partial factorizations — beyond the reference
(SURVEY §5: the reference has no checkpoint of partial factorizations; a
rank failure loses the run). The LAPACK-order state makes a superstep
boundary a clean checkpoint: factor steps [0,k), save (shards, orig),
resume [k,end) — bit-identical to the uninterrupted factorization."""

import numpy as np
import pytest
import jax.numpy as jnp

from conflux_tpu.geometry import Grid3, LUGeometry, CholeskyGeometry
from conflux_tpu.lu.distributed import (
    lu_factor_distributed,
    lu_factor_steps,
)
from conflux_tpu.cholesky.distributed import (
    cholesky_factor_distributed,
    cholesky_factor_steps,
)
from conflux_tpu.parallel.mesh import make_mesh
from conflux_tpu.validation import make_spd_matrix, make_test_matrix


@pytest.mark.parametrize("gridspec", [(1, 1, 1), (2, 2, 1), (2, 2, 2)])
def test_lu_resume_matches_uninterrupted(gridspec):
    import jax

    grid = Grid3(*gridspec)
    v, Nt = 8, 8
    N = v * Nt
    geom = LUGeometry.create(N, N, v, grid)
    mesh = make_mesh(grid, devices=jax.devices()[: grid.P])
    A = make_test_matrix(N, N, dtype=np.float32)
    shards = jnp.asarray(geom.scatter(A))

    full, perm_full = lu_factor_distributed(shards, geom, mesh)

    # three segments with a host round-trip (the checkpoint) in between
    s, o, _ = lu_factor_steps(shards, geom, mesh, 0, 3)
    s, o = jnp.asarray(np.asarray(s)), jnp.asarray(np.asarray(o))  # "save/load"
    s, o, _ = lu_factor_steps(s, geom, mesh, 3, 5, orig=o)
    s, o, perm = lu_factor_steps(s, geom, mesh, 5, geom.n_steps, orig=o)

    np.testing.assert_array_equal(np.asarray(perm), np.asarray(perm_full))
    if gridspec[2] == 1:
        # no z-partials to consolidate: exact round-trip
        np.testing.assert_allclose(np.asarray(s), np.asarray(full),
                                   rtol=0, atol=0)
    else:
        # the checkpoint re-associates 2.5D z-partial sums (documented in
        # lu_factor_steps): equivalent factorization, f32-level differences
        np.testing.assert_allclose(np.asarray(s), np.asarray(full),
                                   rtol=0, atol=5e-3)
        LUp = geom.gather(np.asarray(s))
        p = np.asarray(perm)
        L = np.tril(LUp, -1) + np.eye(N, dtype=LUp.dtype)
        U = np.triu(LUp)
        res = (np.linalg.norm(A[p] - L @ U) / np.linalg.norm(A))
        assert res < 5e-6, res


def test_lu_steps_rejects_bad_usage():
    import jax

    grid = Grid3(1, 1, 1)
    geom = LUGeometry.create(32, 32, 8, grid)
    mesh = make_mesh(grid, devices=jax.devices()[:1])
    shards = jnp.zeros((1, 1, 32, 32), jnp.float32)
    with pytest.raises(ValueError, match="step range"):
        lu_factor_steps(shards, geom, mesh, 2, 1)
    with pytest.raises(ValueError, match="orig state"):
        lu_factor_steps(shards, geom, mesh, 1, 2)


@pytest.mark.parametrize("gridspec", [(2, 2, 1), (2, 2, 2)])
def test_cholesky_resume_matches_uninterrupted(gridspec):
    import jax

    grid = Grid3(*gridspec)
    v = 8
    N = v * 8
    geom = CholeskyGeometry.create(N, v, grid)
    mesh = make_mesh(grid, devices=jax.devices()[: grid.P])
    A = make_spd_matrix(geom.N, dtype=np.float32)
    shards = jnp.asarray(geom.scatter(A))

    full = cholesky_factor_distributed(shards, geom, mesh)
    s = cholesky_factor_steps(shards, geom, mesh, 0, 4)
    s = jnp.asarray(np.asarray(s))  # checkpoint round-trip
    s = cholesky_factor_steps(s, geom, mesh, 4, geom.Kappa)
    if gridspec[2] == 1:
        np.testing.assert_allclose(np.asarray(s), np.asarray(full),
                                   rtol=0, atol=0)
    else:
        # z-partial consolidation at the checkpoint (see docstring)
        np.testing.assert_allclose(np.asarray(s), np.asarray(full),
                                   rtol=0, atol=5e-3)
        from conflux_tpu.validation import cholesky_residual

        L = np.tril(geom.gather(np.asarray(s)))
        assert cholesky_residual(np.asarray(A, np.float64), L) < 5e-6


@pytest.mark.parametrize("gridspec", [(1, 1, 1), (2, 2, 1), (2, 2, 2)])
def test_qr_resume_matches_uninterrupted(gridspec):
    import jax

    from conflux_tpu.qr.distributed import (
        qr_factor_distributed,
        qr_factor_steps,
    )

    grid = Grid3(*gridspec)
    v, Nt = 8, 8
    N = v * Nt
    geom = LUGeometry.create(N, N, v, grid)
    mesh = make_mesh(grid, devices=jax.devices()[: grid.P])
    A = make_test_matrix(N, N, dtype=np.float32)
    shards = jnp.asarray(geom.scatter(A))

    Qf, Rf = qr_factor_distributed(shards, geom, mesh)

    Qs, Rs = qr_factor_steps(shards, geom, mesh, 0, 3)
    Qs, Rs = jnp.asarray(np.asarray(Qs)), jnp.asarray(np.asarray(Rs))
    Qs, Rs = qr_factor_steps(Qs, geom, mesh, 3, 5, R=Rs)
    Qs, Rs = qr_factor_steps(Qs, geom, mesh, 5, geom.Nt, R=Rs)

    if gridspec[2] == 1:
        np.testing.assert_allclose(np.asarray(Qs), np.asarray(Qf),
                                   rtol=0, atol=0)
        np.testing.assert_allclose(np.asarray(Rs), np.asarray(Rf),
                                   rtol=0, atol=0)
    else:
        np.testing.assert_allclose(np.asarray(Qs), np.asarray(Qf),
                                   atol=1e-5)
        np.testing.assert_allclose(np.asarray(Rs), np.asarray(Rf),
                                   atol=1e-4)


def test_qr_steps_rejects_bad_usage():
    import jax

    from conflux_tpu.qr.distributed import qr_factor_steps

    grid = Grid3(1, 1, 1)
    geom = LUGeometry.create(32, 32, 8, grid)
    mesh = make_mesh(grid, devices=jax.devices()[:1])
    shards = jnp.zeros((1, 1, 32, 32), jnp.float32)
    with pytest.raises(ValueError):
        qr_factor_steps(shards, geom, mesh, 2, 1)
    with pytest.raises(ValueError):
        qr_factor_steps(shards, geom, mesh, 2, 4)  # R=None at k0 > 0


def test_factor_steps_accept_segs():
    """Resumed runs keep the tuned segmentation (ADVICE r2): segs threads
    through the *_factor_steps wrappers. Segmentation is math-invariant
    (same pivots, residual-level factors — f32 summation order differs per
    segment shape, so not bitwise; cf. test_lu_distributed_segs_invariant)."""
    import jax

    from conflux_tpu.validation import cholesky_residual, lu_residual

    grid = Grid3(1, 1, 1)
    v, Nt = 8, 8
    N = v * Nt
    geom = LUGeometry.create(N, N, v, grid)
    mesh = make_mesh(grid, devices=jax.devices()[:1])
    A = make_test_matrix(N, N, dtype=np.float32)
    shards = jnp.asarray(geom.scatter(A))

    _, perm_full = lu_factor_distributed(shards, geom, mesh)
    s, o, _ = lu_factor_steps(shards, geom, mesh, 0, 3, segs=(4, 2))
    s, o, perm = lu_factor_steps(s, geom, mesh, 3, geom.n_steps, orig=o,
                                 segs=(4, 2))
    np.testing.assert_array_equal(np.asarray(perm), np.asarray(perm_full))
    p = np.asarray(perm)
    LUp = geom.gather(np.asarray(s))
    assert lu_residual(A, LUp, p) < 5e-6

    cgeom = CholeskyGeometry.create(N, v, grid)
    Aspd = make_spd_matrix(N, dtype=np.float32)
    cshards = jnp.asarray(cgeom.scatter(Aspd))
    cs = cholesky_factor_steps(cshards, cgeom, mesh, 0, 4, segs=(4, 2))
    cs = cholesky_factor_steps(cs, cgeom, mesh, 4, cgeom.Kappa, segs=(4, 2))
    L = np.tril(cgeom.gather(np.asarray(cs)))
    assert cholesky_residual(np.asarray(Aspd, np.float64), L) < 5e-6

    # tree threads through too (flat may break ties differently from
    # pairwise, so a flat-tuned run must resume flat): same-tree resume
    # is bitwise at Pz=1
    ffull, fperm = lu_factor_distributed(shards, geom, mesh,
                                         panel_chunk=16, tree="flat")
    fs, fo, _ = lu_factor_steps(shards, geom, mesh, 0, 3, panel_chunk=16,
                                tree="flat")
    fs, fo, fp = lu_factor_steps(fs, geom, mesh, 3, geom.n_steps, orig=fo,
                                 panel_chunk=16, tree="flat")
    np.testing.assert_array_equal(np.asarray(fp), np.asarray(fperm))
    np.testing.assert_allclose(np.asarray(fs), np.asarray(ffull),
                               rtol=0, atol=0)


def test_lu_resume_butterfly_election_bitwise():
    """A butterfly-elected factorization must checkpoint/resume with the
    same pivot bracket (election passthrough): bitwise at Pz == 1."""
    import jax

    grid = Grid3(2, 2, 1)
    v, Nt = 8, 8
    N = v * Nt
    geom = LUGeometry.create(N, N, v, grid)
    mesh = make_mesh(grid, devices=jax.devices()[: grid.P])
    A = make_test_matrix(N, N, dtype=np.float32)
    shards = jnp.asarray(geom.scatter(A))

    full, perm_full = lu_factor_distributed(shards, geom, mesh,
                                            election="butterfly")
    s, o, _ = lu_factor_steps(shards, geom, mesh, 0, 4,
                              election="butterfly")
    s, o, perm = lu_factor_steps(s, geom, mesh, 4, geom.n_steps, orig=o,
                                 election="butterfly")
    np.testing.assert_array_equal(np.asarray(perm), np.asarray(perm_full))
    np.testing.assert_allclose(np.asarray(s), np.asarray(full),
                               rtol=0, atol=0)
