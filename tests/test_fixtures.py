"""The reference's deterministic hand-checkable matrices as fixtures
(SURVEY.md §4: `lu_params.hpp:157-363` hard-codes them so multi-rank runs
are reproducible and hand-verifiable — e.g. its comments call out which
rank owns the 900 at (5, 2)). Random matrices can hide grid-dependent
bugs behind residual tolerances; these cannot:

 - the elected first pivot is the hand-computable column-0 maximum,
 - the full factorization must match an independent no-pivot Doolittle
   elimination of A[perm] to fp accuracy (LU uniqueness),
 - and every grid must produce a valid factorization of the same matrix.
"""

import numpy as np
import pytest

from conflux_tpu.geometry import Grid3
from conflux_tpu.lu.distributed import lu_distributed_host
from conflux_tpu.validation import lu_residual, residual_bound

from fixtures_lu import REFERENCE_MATRICES

# (n, v, grids that divide n / v evenly on <= 8 devices)
CASES = [
    (8, 4, [Grid3(1, 1, 1), Grid3(2, 1, 1), Grid3(1, 2, 1), Grid3(2, 2, 1),
            Grid3(2, 2, 2)]),
    (9, 3, [Grid3(1, 1, 1), Grid3(3, 1, 1), Grid3(1, 3, 1), Grid3(1, 1, 2)]),
    (16, 4, [Grid3(1, 1, 1), Grid3(2, 2, 1), Grid3(4, 2, 1), Grid3(2, 2, 2)]),
    (27, 3, [Grid3(1, 1, 1), Grid3(3, 1, 1), Grid3(1, 3, 1), Grid3(1, 1, 3)]),
    (32, 4, [Grid3(1, 1, 1), Grid3(2, 2, 1), Grid3(4, 2, 1), Grid3(2, 2, 2),
             Grid3(8, 1, 1)]),
]


def _nopivot_lu(A):
    """Independent oracle: packed Doolittle elimination, no pivoting."""
    lu = A.astype(np.float64).copy()
    n = lu.shape[0]
    for j in range(n - 1):
        lu[j + 1:, j] /= lu[j, j]
        lu[j + 1:, j + 1:] -= np.outer(lu[j + 1:, j], lu[j, j + 1:])
    return lu


@pytest.mark.parametrize("n,v,grids", CASES, ids=lambda c: str(c))
def test_fixture_factorization_all_grids(n, v, grids):
    A = REFERENCE_MATRICES[n]
    first_pivot = int(np.argmax(np.abs(A[:, 0])))
    for grid in grids:
        LU, perm, geom = lu_distributed_host(A, grid, v)
        assert geom.M == n, (n, grid)
        assert sorted(perm.tolist()) == list(range(n)), grid
        # hand-checkable: the first elected pivot is the column-0 maximum
        # (the value the reference's comments point at, e.g. 300 at (2,0)
        # of the 8x8)
        assert perm[0] == first_pivot, (grid, perm[0], first_pivot)
        res = lu_residual(A, LU[perm], perm)
        assert res < residual_bound(n, np.float64), (grid, res)
        # LU uniqueness: our factors of A[perm] must equal an independent
        # no-pivot elimination of A[perm], entry for entry
        ref = _nopivot_lu(A[perm])
        np.testing.assert_allclose(LU[perm], ref, rtol=1e-9, atol=1e-9,
                                   err_msg=str(grid))


def test_fixture_20_singular_leading_part():
    """The 20x20 fixture is rank 16 (rows 16-19 duplicate rows 0-3): the
    elimination must still complete its 4 well-posed supersteps, freezing
    a correct rank-16 factorization. The degenerate trailing block's perm
    entries are unspecified (all candidates are exactly zero — the getrf
    `info > 0` situation), so the check uses the device outputs directly
    rather than the host wrapper's inverse scatter."""
    import jax
    import jax.numpy as jnp

    from conflux_tpu.geometry import LUGeometry
    from conflux_tpu.lu.distributed import lu_factor_distributed
    from conflux_tpu.parallel.mesh import make_mesh

    A = REFERENCE_MATRICES[20]
    assert np.linalg.matrix_rank(A) == 16
    # grids whose v*P sides divide 20 exactly (padding would add identity
    # rows and change the rank structure under test)
    for grid in (Grid3(1, 1, 1), Grid3(5, 1, 1), Grid3(1, 5, 1)):
        geom = LUGeometry.create(20, 20, 4, grid)
        mesh = make_mesh(grid, devices=jax.devices()[: grid.P])
        out, perm = lu_factor_distributed(
            jnp.asarray(geom.scatter(A)), geom, mesh)
        LUp = geom.gather(np.asarray(out))
        perm = np.asarray(perm)
        # the well-posed leading 16 positions are a valid partial
        # permutation and reconstruct A's pivoted rows exactly
        lead = perm[:16]
        assert len(set(lead.tolist())) == 16 and lead.max() < 20, grid
        L16 = np.tril(LUp[:, :16], -1)[:16] + np.eye(16)
        U16 = np.triu(LUp[:16, :])
        assert np.isfinite(L16).all() and np.isfinite(U16).all(), grid
        R = A[lead] - L16 @ U16
        assert np.linalg.norm(R) / np.linalg.norm(A) < 1e-10, grid
