"""Factor-lane (coalesced cold-start) tests: the ISSUE 5 contracts.

- `stack_trees` / `unstack_tree` round-trip BITWISE (the lane's
  slice-out primitive — slot i of a stack IS tree i), and `_pad_batch`'s
  fill='eye' mode pads with identity without touching live slots.
- Sessions opened by coalesced factor dispatches solve BITWISE
  identically to `plan.factor` sessions: `plan.factor` rides bucket 1 of
  the same stacked factor program family, and the vmapped factor body is
  bucket- and pad-invariant (asserted here directly).
- Blast-radius isolation: a non-finite A is rejected at admission
  (`RhsNonFinite`), a post-admission poisoned A fails its OWN future at
  staging, and an unfactorable (singular) matrix fails alone with
  structured `SolveUnhealthy` evidence while co-batched matrices get
  their sessions, bitwise.
- Prewarming `factor_batches` (and the solve widths) leaves a mixed
  solve+factor churn trace with ZERO compiles (plan trace counters).
- close()/deadline semantics cover factor futures: queued requests are
  answered at close, a wedged close fails them with `EngineClosed`, and
  expired requests are lazily evicted with `DeadlineExceeded`.
- Cold-start counters surface through `engine.stats()` and merge into
  `profiler.serve_stats()['engine']`.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from conflux_tpu import batched, profiler, resilience, serve
from conflux_tpu.batched import stack_trees, unstack_tree
from conflux_tpu.engine import EngineClosed, ServeEngine
from conflux_tpu.resilience import (
    DeadlineExceeded,
    FaultPlan,
    FaultSpec,
    HealthPolicy,
    RhsNonFinite,
    SolveUnhealthy,
)

B, N, V = 4, 32, 16


def _systems(b, n=N, seed=0):
    rng = np.random.default_rng(seed)
    return (rng.standard_normal((b, n, n)) / np.sqrt(n)
            + 2.0 * np.eye(n)).astype(np.float32)


def _delta(h0, h1):
    return {k: h1[k] - h0.get(k, 0) for k in h1}


# --------------------------------------------------------------------- #
# the slice-out primitive
# --------------------------------------------------------------------- #


def test_unstack_stack_roundtrip_bitwise():
    """stack_trees / unstack_tree are exact inverses on real factor
    pytrees (mixed float factor + int perm leaves) — no arithmetic
    happens, so the round-trip is bitwise both ways."""
    serve.clear_plans()
    A = _systems(3, seed=11)
    plan = serve.FactorPlan.create((N, N), jnp.float32, v=V)
    trees = [plan.factor(jnp.asarray(A[i]))._factors for i in range(3)]
    stacked = stack_trees(trees)
    back = unstack_tree(stacked, 3)
    for orig, got in zip(trees, back):
        for lo, lg in zip(orig, got):
            np.testing.assert_array_equal(np.asarray(lo), np.asarray(lg))
    # prefix unstack (the engine leaves pad slots untouched)
    two = unstack_tree(stacked, 2)
    assert len(two) == 2
    # and stack(unstack(stack)) is the original stack, leaf for leaf
    restacked = stack_trees(back)
    for ls, lr in zip(stacked, restacked):
        np.testing.assert_array_equal(np.asarray(ls), np.asarray(lr))


def test_pad_batch_eye_fill():
    A = jnp.asarray(_systems(3, seed=13))
    (Ap,), Bp = batched._pad_batch((A,), 3, 4, fill="eye")
    assert Bp == 4 and Ap.shape == (4, N, N)
    np.testing.assert_array_equal(np.asarray(Ap[:3]), np.asarray(A))
    np.testing.assert_array_equal(np.asarray(Ap[3]),
                                  np.eye(N, dtype=np.float32))
    with pytest.raises(ValueError, match="square"):
        batched._pad_batch((jnp.zeros((3, N)),), 3, 4, fill="eye")


# --------------------------------------------------------------------- #
# bitwise identity with plan.factor
# --------------------------------------------------------------------- #


def test_stacked_factor_bucket_and_pad_invariance():
    """The property the whole lane leans on, asserted directly: per-slot
    factors are bitwise identical across batch buckets and regardless of
    the (identity) pad contents."""
    serve.clear_plans()
    A = _systems(4, seed=17)
    plan = serve.FactorPlan.create((N, N), jnp.float32, v=V)
    F1 = plan._stacked_factor_fn(1)(jnp.asarray(A[:1]))
    F4 = plan._stacked_factor_fn(4)(jnp.asarray(A))
    for l1, l4 in zip(F1, F4):
        np.testing.assert_array_equal(np.asarray(l1[0]), np.asarray(l4[0]))
    Apad = np.stack([A[0], np.eye(N, dtype=np.float32)])
    F2 = plan._stacked_factor_fn(2)(jnp.asarray(Apad))
    for l1, l2 in zip(F1, F2):
        np.testing.assert_array_equal(np.asarray(l1[0]), np.asarray(l2[0]))
    # bucket contract: non-power-of-two buckets are a routing bug
    with pytest.raises(AssertionError, match="power-of-two"):
        # conflint: disable=CFX-RECOMPILE asserting the bucket contract rejects 3
        plan._stacked_factor_fn(3)


@pytest.mark.parametrize("health", [None, HealthPolicy()],
                         ids=["plain", "checked"])
def test_factor_lane_bitwise_vs_plan_factor(health):
    """Sessions opened by one coalesced factor dispatch (single-system
    AND batched plans, mixed in one window) solve bitwise identically to
    plan.factor sessions of the same matrices — including through the
    CHECKED factor program (the fused verdict changes the program, not
    the factor bits)."""
    serve.clear_plans()
    A = _systems(3, seed=19)
    Ab = _systems(B, seed=23)
    plan = serve.FactorPlan.create((N, N), jnp.float32, v=V)
    bplan = serve.FactorPlan.create((B, N, N), jnp.float32, v=V)
    rng = np.random.default_rng(23)
    b1 = rng.standard_normal((N, 2)).astype(np.float32)
    bb = rng.standard_normal((B, N)).astype(np.float32)
    with ServeEngine(max_batch_delay=0.05, max_factor_batch=4,
                     health=health) as eng:
        futs = [eng.submit_factor(plan, A[i]) for i in range(3)]
        bfut = eng.submit_factor(bplan, Ab)
        sessions = [f.result(timeout=120) for f in futs]
        bsession = bfut.result(timeout=120)
        for i, s in enumerate(sessions):
            ref = plan.factor(jnp.asarray(A[i]))
            np.testing.assert_array_equal(np.asarray(s.solve(b1)),
                                          np.asarray(ref.solve(b1)),
                                          err_msg=f"session {i}")
        bref = bplan.factor(jnp.asarray(Ab))
        np.testing.assert_array_equal(np.asarray(bsession.solve(bb)),
                                      np.asarray(bref.solve(bb)))
        stats = eng.stats()
    # 3 single-system requests coalesced into one 4-bucket dispatch
    # (1 pad slot), the batched request into its own 1-bucket dispatch
    assert stats["factor_requests"] == 4
    assert stats["factor_batches"] == 2
    assert stats["factor_pad_slots"] == 1
    if health is not None:
        # checked sessions open with their probe row already resident
        assert sessions[0]._probe is not None
        _x, verdict = sessions[0].solve_checked(b1)
        healthy, finite, _res = resilience.evaluate(
            verdict, health.resolved_residual_limit(np.float32, N))
        assert healthy and finite


def test_factor_lane_session_full_downstream_behavior():
    """A coalesced-factored session is a first-class SolveSession:
    update/drift, refactor, and the engine's solve lane all behave as on
    a plan.factor session (same counters, same answers)."""
    serve.clear_plans()
    A = _systems(2, seed=29)
    plan = serve.FactorPlan.create((N, N), jnp.float32, v=V)
    rng = np.random.default_rng(29)
    b = rng.standard_normal((N, 2)).astype(np.float32)
    U = (0.01 * rng.standard_normal((N, 2))).astype(np.float32)
    Vf = (0.01 * rng.standard_normal((N, 2))).astype(np.float32)
    with ServeEngine(max_batch_delay=0.02) as eng:
        s_eng = eng.factor(plan, A[0], timeout=120)
        s_ref = plan.factor(jnp.asarray(A[0]))
        for s in (s_eng, s_ref):
            s.update(jnp.asarray(U), jnp.asarray(Vf))
        np.testing.assert_array_equal(np.asarray(s_eng.solve(b)),
                                      np.asarray(s_ref.solve(b)))
        for s in (s_eng, s_ref):
            s.refactor()
        np.testing.assert_array_equal(np.asarray(s_eng.solve(b)),
                                      np.asarray(s_ref.solve(b)))
        assert s_eng.factorizations == s_ref.factorizations == 2
        # and the solve lane serves the churned-in session
        np.testing.assert_array_equal(
            np.asarray(eng.solve(s_eng, b, timeout=120)),
            np.asarray(s_ref.solve(b)))


# --------------------------------------------------------------------- #
# blast-radius isolation
# --------------------------------------------------------------------- #


def test_factor_admission_rejects_nonfinite_A():
    serve.clear_plans()
    plan = serve.FactorPlan.create((N, N), jnp.float32, v=V)
    Abad = _systems(1, seed=31)[0]
    Abad[0, 0] = np.inf
    h0 = resilience.health_stats()
    with ServeEngine(max_batch_delay=0.0, health=HealthPolicy()) as eng:
        with pytest.raises(RhsNonFinite, match="admission"):
            eng.submit_factor(plan, Abad)
        assert eng.stats()["pending"] == 0, "reject consumed a slot"
    assert _delta(h0, resilience.health_stats())["factor_rejects"] == 1


def test_factor_staging_poison_isolated_survivors_bitwise():
    """A matrix poisoned AFTER admission (injected at the 'factor' nan
    site) fails its own future at staging; its co-batched neighbours
    still get sessions whose answers are bitwise plan.factor's."""
    serve.clear_plans()
    A = _systems(3, seed=37)
    plan = serve.FactorPlan.create((N, N), jnp.float32, v=V)
    rng = np.random.default_rng(37)
    b = rng.standard_normal((N,)).astype(np.float32)
    faults = FaultPlan([FaultSpec("factor", "nan", count=1)])
    h0 = resilience.health_stats()
    with ServeEngine(max_batch_delay=0.1, max_factor_batch=4,
                     health=HealthPolicy(), fault_plan=faults) as eng:
        futs = [eng.submit_factor(plan, A[i]) for i in range(3)]
        with pytest.raises(RhsNonFinite, match="staging"):
            futs[0].result(timeout=120)
        for i in (1, 2):
            s = futs[i].result(timeout=120)
            ref = plan.factor(jnp.asarray(A[i]))
            np.testing.assert_array_equal(np.asarray(s.solve(b)),
                                          np.asarray(ref.solve(b)),
                                          err_msg=f"survivor {i}")
    dh = _delta(h0, resilience.health_stats())
    assert dh["factor_isolations"] == 1
    assert faults.injected[("factor", "nan")] == 1


def test_singular_matrix_fails_alone_with_evidence():
    """No fault injection: a genuinely unfactorable matrix trips the
    fused post-factor verdict, re-dispatches solo, and fails with
    structured evidence — the finite co-batched matrix is unaffected."""
    serve.clear_plans()
    A = _systems(2, seed=41)
    Asing = np.zeros((N, N), np.float32)  # finite, passes the A guards
    plan = serve.FactorPlan.create((N, N), jnp.float32, v=V)
    h0 = resilience.health_stats()
    with ServeEngine(max_batch_delay=0.1, max_factor_batch=4,
                     health=HealthPolicy()) as eng:
        f_good = eng.submit_factor(plan, A[0])
        f_sick = eng.submit_factor(plan, Asing)
        s = f_good.result(timeout=120)
        with pytest.raises(SolveUnhealthy) as ei:
            f_sick.result(timeout=120)
        rungs = ei.value.evidence["rungs"]
        assert rungs and rungs[-1]["rung"] == "factor"
        assert not rungs[-1]["finite"]
        b = np.ones(N, np.float32)
        ref = plan.factor(jnp.asarray(A[0]))
        np.testing.assert_array_equal(np.asarray(s.solve(b)),
                                      np.asarray(ref.solve(b)))
    dh = _delta(h0, resilience.health_stats())
    # batch verdict + failed solo retry
    assert dh["factor_unhealthy"] == 2


def test_forced_unhealthy_verdict_recovers_via_solo_redispatch():
    """A transiently-sick batch verdict (forced once at the 'factor'
    unhealthy site) re-dispatches every flagged slot solo; the solo
    re-factor comes back healthy and every request still gets its
    session."""
    serve.clear_plans()
    A = _systems(2, seed=43)
    plan = serve.FactorPlan.create((N, N), jnp.float32, v=V)
    faults = FaultPlan([FaultSpec("factor", "unhealthy", count=1)])
    h0 = resilience.health_stats()
    with ServeEngine(max_batch_delay=0.1, max_factor_batch=2,
                     health=HealthPolicy(), fault_plan=faults) as eng:
        futs = [eng.submit_factor(plan, A[i]) for i in range(2)]
        sessions = [f.result(timeout=120) for f in futs]
    assert all(s.solves == 0 and s.factorizations == 1 for s in sessions)
    assert _delta(h0, resilience.health_stats())["factor_unhealthy"] == 2


# --------------------------------------------------------------------- #
# prewarmed zero-compile churn
# --------------------------------------------------------------------- #


def test_prewarmed_churn_trace_zero_compiles():
    """A mixed solve+factor churn trace against prewarmed buckets
    compiles NOTHING: factor_batches covers every coalesced bucket
    (including plan.factor's own bucket 1), widths cover the solve
    lane."""
    serve.clear_plans()
    A = _systems(6, seed=47)
    plan = serve.FactorPlan.create((N, N), jnp.float32, v=V)
    rng = np.random.default_rng(47)
    with ServeEngine(max_batch_delay=0.02, max_factor_batch=4,
                     max_coalesce_width=4) as eng:
        seed_session = plan.factor(jnp.asarray(A[0]))
        eng.prewarm(seed_session, widths=(1, 2, 4),
                    factor_batches=(1, 2, 4))
        snapshot = dict(plan.trace_counts)
        fleet = [seed_session]
        futs = []
        for i in range(1, 6):  # churn: open sessions, solve against them
            futs.append(eng.submit_factor(plan, A[i]))
            b = rng.standard_normal((N, 1 + i % 2)).astype(np.float32)
            futs.append(eng.submit(fleet[rng.integers(len(fleet))],
                                   jnp.asarray(b)))
            if i % 2 == 0:
                fleet.append(futs[-2].result(timeout=120))
        for f in futs:
            f.result(timeout=120)
        assert plan.trace_counts == snapshot, \
            "churn traffic compiled after prewarm"
        stats = eng.stats()
    assert stats["factor_batches"] >= 1
    assert stats["factor_coalesced_mean"] >= 1.0
    # prewarming a bare plan (no session yet — true cold start) works too
    with ServeEngine(max_batch_delay=0.0) as eng2:
        eng2.prewarm(plan, factor_batches=(2,))
        snapshot = dict(plan.trace_counts)
        eng2.factor(plan, A[1], timeout=120)
        assert plan.trace_counts == snapshot


# --------------------------------------------------------------------- #
# close / deadline semantics
# --------------------------------------------------------------------- #


def test_close_answers_queued_factor_requests():
    serve.clear_plans()
    A = _systems(2, seed=53)
    plan = serve.FactorPlan.create((N, N), jnp.float32, v=V)
    eng = ServeEngine(max_batch_delay=60.0)  # everything queued at close
    futs = [eng.submit_factor(plan, A[i]) for i in range(2)]
    eng.close(timeout=120)
    b = np.ones(N, np.float32)
    for i, f in enumerate(futs):
        assert f.done(), "close() dropped a queued factor request"
        ref = plan.factor(jnp.asarray(A[i]))
        np.testing.assert_array_equal(np.asarray(f.result().solve(b)),
                                      np.asarray(ref.solve(b)))
    with pytest.raises(EngineClosed):
        eng.submit_factor(plan, A[0])


def test_wedged_close_fails_pending_factor_futures():
    """A wedged worker (injected drain delay) cannot strand factor
    futures: close(timeout) names the wedged thread and fails the
    still-pending requests with EngineClosed."""
    serve.clear_plans()
    A = _systems(1, seed=59)
    plan = serve.FactorPlan.create((N, N), jnp.float32, v=V)
    plan._stacked_factor_fn(1)(jnp.asarray(A[:1]))  # no compile stall below
    faults = FaultPlan([FaultSpec("drain", "delay", delay_s=8.0)])
    eng = ServeEngine(max_batch_delay=0.0, fault_plan=faults,
                      watchdog_interval=0)
    f = eng.submit_factor(plan, A[0])
    wedged = eng.close(timeout=0.4)
    assert wedged, "drain should still be sleeping in the injected delay"
    with pytest.raises(EngineClosed, match="wedged"):
        f.result(timeout=10)


def test_factor_deadline_lazy_eviction():
    serve.clear_plans()
    A = _systems(1, seed=61)
    plan = serve.FactorPlan.create((N, N), jnp.float32, v=V)
    eng = ServeEngine(max_batch_delay=60.0)  # parked dispatcher window
    h0 = resilience.health_stats()
    f = eng.submit_factor(plan, A[0], deadline=0.01)
    with pytest.raises(DeadlineExceeded):
        f.result(timeout=60)
    # the blocking wrapper carries the same deadline semantics
    with pytest.raises(DeadlineExceeded):
        eng.factor(plan, A[0], timeout=60, deadline=0.01)
    assert _delta(h0, resilience.health_stats())["evictions"] == 2
    assert eng.stats()["pending"] == 0, "eviction leaked a pending slot"
    eng.close(timeout=60)


def test_factor_lane_rejects_bad_inputs():
    serve.clear_plans()
    plan = serve.FactorPlan.create((N, N), jnp.float32, v=V)
    mplan = serve.FactorPlan.create((8, N, N), jnp.float32, v=V,
                                    mesh=batched.batch_mesh())
    session = plan.factor(jnp.asarray(_systems(1, seed=67)[0]))
    with ServeEngine(max_batch_delay=0.0) as eng:
        # mesh plans are ADMITTED now (DESIGN §32) — the bad-input
        # rejection left on the mesh path is a shape mismatch
        with pytest.raises(ValueError, match="shape"):
            eng.submit_factor(mplan, np.zeros((N, N), np.float32))
        with pytest.raises(ValueError, match="shape"):
            eng.submit_factor(plan, np.zeros((N, N + 1), np.float32))
        with pytest.raises(TypeError, match="FactorPlan"):
            eng.submit_factor(session, np.zeros((N, N), np.float32))


# --------------------------------------------------------------------- #
# observability
# --------------------------------------------------------------------- #


def test_factor_counters_in_serve_stats():
    serve.clear_plans()
    A = _systems(3, seed=71)
    plan = serve.FactorPlan.create((N, N), jnp.float32, v=V)
    with ServeEngine(max_batch_delay=0.05, max_factor_batch=4) as eng:
        futs = [eng.submit_factor(plan, A[i]) for i in range(3)]
        for f in futs:
            f.result(timeout=120)
        merged = profiler.serve_stats()["engine"]
        mine = eng.stats()
    assert mine["factor_requests"] == 3
    assert mine["factor_batches"] >= 1
    assert mine["factor_coalesced_mean"] >= 1.0
    assert 0.0 <= mine["factor_pad_waste"] < 1.0
    assert mine["factor_latency_p50_ms"] > 0.0
    assert mine["factor_latency_p99_ms"] >= mine["factor_latency_p50_ms"]
    assert merged["factor_requests"] >= mine["factor_requests"]
    assert merged["factor_batches"] >= mine["factor_batches"]
    assert merged["factor_latency_p99_ms"] >= \
        merged["factor_latency_p50_ms"] > 0.0
