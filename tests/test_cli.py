"""Miniapp CLIs driven in-process on the CPU test platform — covers the
reference's driver surface (`examples/conflux_miniapp.cpp`,
`examples/cholesky_miniapp.cpp`) including the `_result_` protocol."""

import re

import pytest

from conflux_tpu.cli import cholesky_miniapp, conflux_miniapp


def run_cli(main, argv, capsys):
    rc = main(argv)
    assert rc == 0
    return capsys.readouterr().out


def test_conflux_miniapp_result_line(capsys):
    out = run_cli(
        conflux_miniapp.main,
        ["-N", "64", "-b", "16", "--p_grid", "2,2,1", "-r", "2", "--validate",
         "--dtype", "float64"],
        capsys,
    )
    lines = [l for l in out.splitlines() if l.startswith("_result_")]
    assert len(lines) == 2
    m = re.match(
        r"_result_ lu,conflux_tpu,64,32,4,2x2x1,time,weak,([\d.]+),16,float64",
        lines[0]
    )
    assert m, lines[0]
    res = [l for l in out.splitlines() if l.startswith("_residual_")]
    assert len(res) == 1
    assert float(res[0].split()[1]) < 1e-10


def test_conflux_miniapp_auto_grid(capsys):
    out = run_cli(conflux_miniapp.main, ["-N", "64", "-b", "8", "-r", "1"], capsys)
    assert "_result_" in out


def test_conflux_miniapp_grid_too_large():
    with pytest.raises(SystemExit):
        conflux_miniapp.main(["-N", "64", "-b", "8", "--p_grid", "4,4,4"])


def test_cholesky_miniapp(capsys):
    out = run_cli(
        cholesky_miniapp.main,
        ["--dim", "64", "--tile", "16", "--grid", "2,2,2", "--run", "2", "--validate"],
        capsys,
    )
    assert "PROBLEM PARAMETERS" in out
    lines = [l for l in out.splitlines() if l.startswith("_result_")]
    assert len(lines) == 2
    assert lines[0].startswith("_result_ cholesky,conflux_tpu,64,32,8,2x2x2,time,weak,")
    res = [l for l in out.splitlines() if l.startswith("_residual_")]
    assert float(res[0].split()[1]) < 1e-4


def test_profiler_report(capsys):
    from conflux_tpu import profiler

    profiler.clear()
    with profiler.region("step0_reduce"):
        pass
    with profiler.region("step0_reduce"):
        pass
    t = profiler.timings()
    assert t["step0_reduce"][0] == 2
    out = profiler.report()
    assert "step0_reduce" in out
    profiler.clear()
    assert profiler.timings() == {}


def test_cholesky_helper_roundtrip(tmp_path, capsys):
    """generate -> factor -> compare pipeline (the reference's
    cholesky_helper + compare_res.py workflow)."""
    from conflux_tpu.cli import cholesky_helper

    inp = str(tmp_path / "input_64.bin")
    ref = str(tmp_path / "result_64.bin")
    mine = str(tmp_path / "mine_64.bin")
    rc = cholesky_helper.main(
        ["generate", "--dim", "64", "--out", inp, "--result", ref,
         "--dtype", "float64"]
    )
    assert rc == 0
    rc = cholesky_helper.main(
        ["factor", inp, mine, "--tile", "16", "--grid", "2,2,1",
         "--dtype", "float64"]
    )
    assert rc == 0
    rc = cholesky_helper.main(["compare", mine, ref, "--lower", "--tol", "1e-8"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "_compare_" in out


def test_cholesky_helper_compare_fails_above_tol(tmp_path, capsys):
    import numpy as np

    from conflux_tpu.cli import cholesky_helper
    from conflux_tpu.io import save_matrix

    a, b = str(tmp_path / "a.bin"), str(tmp_path / "b.bin")
    save_matrix(a, np.eye(8))
    save_matrix(b, 2 * np.eye(8))
    assert cholesky_helper.main(["compare", a, b, "--tol", "1e-3"]) == 1


def test_cholesky_helper_reads_reference_raw_format(tmp_path):
    """The reference cholesky_helper writes raw headerless dim*dim doubles;
    factor + compare must consume them directly."""
    import numpy as np

    from conflux_tpu.cli import cholesky_helper
    from conflux_tpu.io import load_matrix_auto

    dim = 32
    rng = np.random.default_rng(0)
    B = rng.standard_normal((dim, dim))
    A = B @ B.T + dim * np.eye(dim)
    raw = tmp_path / "input_32.bin"
    A.astype(np.float64).tofile(str(raw))  # reference format: no header

    np.testing.assert_array_equal(load_matrix_auto(str(raw)), A)

    out = tmp_path / "mine_32.bin"
    rc = cholesky_helper.main(
        ["factor", str(raw), str(out), "--tile", "8", "--platform", "cpu",
         "--devices", "1", "--dtype", "float64"])
    assert rc == 0
    import scipy.linalg

    ref = tmp_path / "result_32.bin"
    L = scipy.linalg.cholesky(A, lower=True)
    L.astype(np.float64).tofile(str(ref))  # raw reference result file
    rc = cholesky_helper.main(
        ["compare", str(out), str(ref), "--lower", "--tol", "1e-10"])
    assert rc == 0


def test_qr_miniapp_tall_and_full(capsys):
    from conflux_tpu.cli import qr_miniapp

    out = run_cli(
        qr_miniapp.main,
        ["-M", "128", "--cols", "16", "-r", "2", "--p_grid", "4,1,1",
         "--validate", "--dtype", "float64"],
        capsys,
    )
    lines = [l for l in out.splitlines() if l.startswith("_result_")]
    assert len(lines) == 2
    assert re.match(
        r"_result_ qr-tsqr,conflux_tpu,128,64,4,4x1x1,time,weak,[\d.]+,16,float64",
        lines[0]), lines[0]
    res = [l for l in out.splitlines() if l.startswith("_residual_")]
    assert "orth=" in res[0]
    assert float(res[0].split("orth=")[1].split()[0]) < 1e-12

    out = run_cli(
        qr_miniapp.main,
        ["-M", "64", "--cols", "64", "--full", "-b", "16", "--p_grid",
         "2,2,1", "-r", "1", "--validate", "--dtype", "float64"],
        capsys,
    )
    assert "_result_ qr,conflux_tpu,64," in out
    res = [l for l in out.splitlines() if l.startswith("_residual_")][0]
    assert float(res.split("reconstruction=")[1]) < 1e-12


def test_qr_miniapp_rejects_wide(capsys):
    from conflux_tpu.cli import qr_miniapp

    with pytest.raises(SystemExit):
        qr_miniapp.main(["-M", "16", "--cols", "32"])


def test_bench_cli_smoke():
    """The driver's bench entry runs end-to-end off-chip via the smoke
    overrides (-N, --platform cpu) in every mode — the one-shot chip
    queue must exercise no untested code path."""
    import json
    import os
    import subprocess
    import sys

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    for mode_args in (["--mode", "f32"], ["--mode", "mxp", "--ir", "gmres",
                                          "--refine", "2"]):
        out = subprocess.run(
            [sys.executable, os.path.join(root, "bench.py"),
             "--platform", "cpu", "-N", "1024", *mode_args],
            capture_output=True, text=True, timeout=600, cwd=root, env=env,
        )
        assert out.returncode == 0, out.stderr[-2000:]
        line = [l for l in out.stdout.splitlines() if l.startswith("{")][-1]
        rec = json.loads(line)
        assert rec["unit"] == "GFLOP/s" and rec["value"] > 0
        assert rec["residual"] < (1e-5 if mode_args[1] == "f32" else 1e-6)
    bad = subprocess.run(
        [sys.executable, os.path.join(root, "bench.py"),
         "--platform", "cpu", "-N", "1000"],
        capture_output=True, text=True, timeout=120, cwd=root, env=env,
    )
    assert bad.returncode != 0 and "multiple" in bad.stderr


def test_cholesky_miniapp_refine(capsys):
    from conflux_tpu.cli import cholesky_miniapp

    out = run_cli(
        cholesky_miniapp.main,
        ["--dim", "64", "--tile", "16", "--grid", "2,1,1", "--run", "1",
         "--refine", "2"],
        capsys,
    )
    line = [l for l in out.splitlines()
            if l.startswith("_solve_residual_")][0]
    assert "[PASS <=1e-6]" in line, line
    assert float(line.split("rel=")[1].split()[0]) <= 1e-6
    with pytest.raises(SystemExit):
        cholesky_miniapp.main(["--dim", "64", "--tile", "16", "--run", "1",
                               "--refine", "-1"])


def test_conflux_miniapp_refine(capsys):
    from conflux_tpu.cli import conflux_miniapp

    out = run_cli(
        conflux_miniapp.main,
        ["-N", "64", "-b", "16", "--p_grid", "2,1,1", "-r", "1",
         "--refine", "2"],
        capsys,
    )
    line = [l for l in out.splitlines()
            if l.startswith("_solve_residual_")][0]
    assert "[PASS <=1e-6]" in line, line


def test_miniapps_auto_knob_resolution(capsys):
    """--auto resolves un-passed knobs from the measured dispatch table
    (conflux_tpu.autotune) and reports the provenance; explicit flags are
    untouched."""
    out = run_cli(conflux_miniapp.main,
                  ["-N", "128", "-r", "1", "--auto", "--validate"], capsys)
    # CPU sweep rule: tile 256 (N=128 < v is tile-rounded by geometry)
    assert "_auto_ block_size=256" in out
    assert "_auto_provenance_ CPU-mesh sweep" in out
    assert "_result_" in out and "_residual_" in out
    # an explicit flag wins over the table
    out = run_cli(conflux_miniapp.main,
                  ["-N", "128", "-b", "32", "-r", "1", "--auto"], capsys)
    assert "block_size=" not in out.split("_auto_ ")[1].splitlines()[0]
    assert [l for l in out.splitlines()
            if l.startswith("_result_")][0].rsplit(",", 2)[1] == "32"


def test_auto_explicit_default_value_pins(capsys):
    """A flag explicitly passed AT the library default value still pins
    its knob — the table must not silently override it (ADVICE r4 #1:
    sentinel None parser defaults distinguish un-passed from
    passed-at-default)."""
    out = run_cli(conflux_miniapp.main,
                  ["-N", "128", "-b", "128", "-r", "1", "--auto"], capsys)
    # table says 256, but -b 128 (the library default) was explicit
    assert "block_size=" not in out.split("_auto_ ")[1].splitlines()[0]
    assert [l for l in out.splitlines()
            if l.startswith("_result_")][0].rsplit(",", 2)[1] == "128"


def test_auto_without_flag_resolves_library_defaults(capsys):
    """Without --auto, sentinel-None knobs resolve to library defaults
    (the pre-sentinel behavior must be unchanged for plain runs)."""
    out = run_cli(conflux_miniapp.main,
                  ["-N", "128", "-r", "1"], capsys)
    assert [l for l in out.splitlines()
            if l.startswith("_result_")][0].rsplit(",", 2)[1] == "128"
    assert "_auto_" not in out


def test_qr_auto_empty_mode_reports_no_knobs(capsys):
    """qr tall CholeskyQR2 mode has no auto-tunable knobs; --auto must
    say so rather than print "(all knobs pinned)" (ADVICE r4 #4)."""
    from conflux_tpu.cli import qr_miniapp

    out = run_cli(qr_miniapp.main,
                  ["-M", "256", "--cols", "64", "--algo", "cholesky",
                   "-r", "1", "--auto"], capsys)
    assert "_auto_ (no auto-tunable knobs for this mode)" in out
    assert "(all knobs pinned)" not in out
    assert "_auto_provenance_" not in out
