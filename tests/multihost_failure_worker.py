"""Worker for the failure-detection test (`test_multihost.py`).

role=die: exit hard right after joining — the simulated rank failure.
role=survive: keep running collectives; the coordination service's
heartbeat watchdog must abort this process in bounded time once the
peer dies (the reference has no failure detection at all — a lost rank
hangs the MPI job until the scheduler's walltime kills it).
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(__file__))
import mh_common  # noqa: F401  (must precede jax backend init)

pid, nproc, port, role = (int(sys.argv[1]), int(sys.argv[2]), sys.argv[3],
                          sys.argv[4])

from conflux_tpu.parallel.mesh import initialize_multihost  # noqa: E402

initialize_multihost(f"localhost:{port}", nproc, pid,
                     initialization_timeout=60,
                     heartbeat_timeout_seconds=10)
print(f"proc {pid} joined", flush=True)

if role == "die":
    os._exit(17)

import jax.numpy as jnp  # noqa: E402

x = jnp.ones((64,))
deadline = time.time() + 120
while time.time() < deadline:
    # keep the runtime active; the heartbeat watchdog terminates this
    # process once the peer is declared dead
    float(x.sum())
    time.sleep(1)
print("survivor was never aborted", flush=True)
sys.exit(3)
