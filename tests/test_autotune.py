"""Measured variant dispatch: rule lookup, overrides, honesty contract
(the role of the reference's hand-measured variant switch,
`src/conflux/cholesky/Cholesky.cpp:857-921`)."""

import json

import pytest

from conflux_tpu import autotune


@pytest.fixture(autouse=True)
def _clean_table():
    autotune.reset_loaded_table()
    yield
    autotune.reset_loaded_table()


def test_measured_v5e_lu_rule():
    r = autotune.recommended("lu", 32768, device_kind="tpu v5 lite")
    assert r.knobs["panel_chunk"] == 8192
    assert r.knobs["tree"] == "pairwise"  # flip pending hardware A/B
    assert "BENCH_r01" in r.provenance


def test_cpu_rules_disable_lookahead():
    for algo in ("lu", "cholesky", "qr"):
        r = autotune.recommended(algo, 4096, P=8, device_kind="cpu")
        assert r.knobs["lookahead"] is False
        assert "CPU-mesh sweep" in r.provenance


def test_unmeasured_configs_say_so():
    """The honesty contract: no measurement -> the provenance admits it
    instead of dressing defaults up as a tune."""
    r = autotune.recommended("cholesky", 32768, device_kind="tpu v5e")
    assert "NO hardware measurement" in r.provenance
    r2 = autotune.recommended("lu", 1024, device_kind="some future chip")
    assert "library defaults" in r2.provenance
    # unmeasured rules must not pin a tile: the un-passed default is
    # adaptive (Cholesky memory heuristic, per-miniapp defaults) and a
    # None knob never overwrites it
    assert r.knobs["v"] is None and r2.knobs["v"] is None


def test_out_of_range_n_falls_through():
    """The v5e LU rule is bounded to the measured N range; outside it the
    query falls to the catch-all rather than extrapolating."""
    r = autotune.recommended("lu", 4096, device_kind="tpu v5 lite")
    assert "library defaults" in r.provenance


def test_json_override_beats_builtin(tmp_path):
    table = tmp_path / "tune.json"
    table.write_text(json.dumps([{
        "algo": "lu", "device": "v5 lite", "P": 1,
        "n_lo": 8192, "n_hi": 32768, "dtype": "float32",
        "knobs": {"tree": "flat", "segs": [16, 16]},
        "provenance": "hypothetical chip session A/B",
    }]))
    assert autotune.load_table(str(table)) == 1
    r = autotune.recommended("lu", 32768, device_kind="tpu v5 lite")
    # same specificity as the built-in -> later-loaded (the override) wins
    assert r.knobs["tree"] == "flat"
    assert r.knobs["segs"] == (16, 16)  # JSON lists arrive as tuples
    assert "chip session" in r.provenance


def test_load_table_validates(tmp_path):
    bad = tmp_path / "bad.json"
    bad.write_text(json.dumps([{"algo": "svd", "knobs": {}}]))
    with pytest.raises(ValueError, match="unknown algo"):
        autotune.load_table(str(bad))
    bad.write_text(json.dumps([{"algo": "lu", "knobs": {}, "spee": 1}]))
    with pytest.raises(ValueError, match="unknown rule fields"):
        autotune.load_table(str(bad))
    bad.write_text(json.dumps({"algo": "lu"}))
    with pytest.raises(ValueError, match="JSON list"):
        autotune.load_table(str(bad))
    bad.write_text(json.dumps([{"knobs": {}}]))
    with pytest.raises(ValueError, match="algo"):
        autotune.load_table(str(bad))


def test_env_table(tmp_path, monkeypatch):
    table = tmp_path / "env.json"
    table.write_text(json.dumps([{
        "algo": "qr", "device": "cpu", "knobs": {"v": 64},
        "P": 4, "provenance": "env table",
    }]))
    monkeypatch.setenv("CONFLUX_TPU_TUNE_TABLE", str(table))
    autotune.reset_loaded_table()  # force the env re-read
    r = autotune.recommended("qr", 4096, P=4, device_kind="cpu")
    assert r.knobs["v"] == 64 and "env table" in r.provenance
    # other P still served by the built-in sweep rule
    r2 = autotune.recommended("qr", 4096, P=8, device_kind="cpu")
    assert r2.knobs["v"] == 128


def test_recommended_validates():
    with pytest.raises(ValueError, match="algo"):
        autotune.recommended("svd", 1024, device_kind="cpu")
    with pytest.raises(ValueError, match="positive"):
        autotune.recommended("lu", 0, device_kind="cpu")
