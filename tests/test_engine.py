"""Serve-engine tests: the ISSUE 3 acceptance contracts, asserted.

- Coalesced results are BITWISE the direct `SolveSession.solve` answers
  under a deterministic mixed-width / mixed-session / mixed-plan trace
  (RHS columns are independent through every substitution/GEMM/IR step,
  and the power-of-two bucket programs agree per column — the same
  argument `test_solve_rhs_bucketing_bounds_recompiles` established for
  padding, extended across buckets).
- Backpressure SHEDS (raises `EngineSaturated`) at the pending bound
  instead of deadlocking, and every admitted request still completes.
- Prewarming the declared buckets means steady-state traffic observes
  ZERO compiles (the plans' trace counters, the serve layer's contract
  hook).
- `close()` drains in-flight requests rather than dropping them.
- Cross-session stacking (opt-in) matches direct solves to working
  accuracy and compiles one stacked bucket program.
- Engine counters surface through `profiler.serve_stats()['engine']`.
"""

import threading

import numpy as np
import pytest

import jax.numpy as jnp

from conflux_tpu import profiler, serve
from conflux_tpu.engine import (
    EngineClosed,
    EngineSaturated,
    ServeEngine,
)

B, N, V = 4, 32, 16


def _systems(b, n=N, seed=0):
    rng = np.random.default_rng(seed)
    A = (rng.standard_normal((b, n, n)) / np.sqrt(n)
         + 2.0 * np.eye(n)).astype(np.float32)
    return A


def _trace(rng, n_req, widths=(1, 2, 3, 4)):
    """A deterministic mixed-width request trace: (width, rhs) pairs,
    width-1 requests submitted in the squeeze (vector) form."""
    out = []
    for i in range(n_req):
        w = widths[i % len(widths)]
        shape = (N, w) if w > 1 else (N,)
        out.append((w, rng.standard_normal(shape).astype(np.float32)))
    return out


def test_engine_bitwise_matches_direct_solve():
    """Mixed widths, mixed sessions, mixed plans (single + batched):
    single-system answers are BITWISE the direct session.solve ones
    (per-column kernels agree across width buckets); batched-plan
    answers ride vmapped GEMMs whose kernel shape changes with the
    coalesced width, so they are held to a tight allclose instead."""
    serve.clear_plans()
    A = _systems(3, seed=41)
    Ab = _systems(B, seed=43)
    plan = serve.FactorPlan.create((N, N), jnp.float32, v=V)
    bplan = serve.FactorPlan.create((B, N, N), jnp.float32, v=V)
    sessions = [plan.factor(jnp.asarray(A[i])) for i in range(3)]
    bsession = bplan.factor(jnp.asarray(Ab))

    rng = np.random.default_rng(47)
    reqs = []
    for i, (w, b) in enumerate(_trace(rng, 12)):
        reqs.append((sessions[i % 3], jnp.asarray(b)))
    for _ in range(3):  # batched-plan traffic rides the same queue
        reqs.append((bsession, jnp.asarray(
            rng.standard_normal((B, N)).astype(np.float32))))

    direct = [np.asarray(s.solve(b)) for s, b in reqs]
    with ServeEngine(max_batch_delay=0.05, max_coalesce_width=8) as eng:
        futs = [eng.submit(s, b) for s, b in reqs]
        results = [np.asarray(f.result(timeout=60)) for f in futs]
    for i, (d, r) in enumerate(zip(direct, results)):
        assert d.shape == r.shape, (i, d.shape, r.shape)
        if reqs[i][0] is bsession:
            np.testing.assert_allclose(r, d, rtol=1e-5, atol=1e-6,
                                       err_msg=f"request {i}")
        else:
            np.testing.assert_array_equal(d, r, err_msg=f"request {i}")
    # a batched request alone in its window runs the very same program —
    # bitwise, no caveat
    with ServeEngine(max_batch_delay=0.0) as eng:
        b1 = reqs[-1][1]
        np.testing.assert_array_equal(
            np.asarray(eng.solve(bsession, b1, timeout=60)),
            np.asarray(bsession.solve(b1)))


def test_engine_prewarm_zero_compiles_in_steady_state():
    serve.clear_plans()
    A = _systems(1, seed=53)
    plan = serve.FactorPlan.create((N, N), jnp.float32, v=V)
    session = plan.factor(jnp.asarray(A[0]))
    rng = np.random.default_rng(53)
    # cap coalescing at 4 so prewarming buckets {1, 2, 4} covers every
    # width steady-state traffic can produce
    with ServeEngine(max_batch_delay=0.02, max_coalesce_width=4) as eng:
        eng.prewarm(session, widths=(1, 2, 4))
        snapshot = dict(plan.trace_counts)
        futs = [eng.submit(session, jnp.asarray(b))
                for _, b in _trace(rng, 16, widths=(1, 2, 1, 1))]
        for f in futs:
            f.result(timeout=60)
        assert plan.trace_counts == snapshot, \
            "steady-state traffic compiled after prewarm"
        stats = eng.stats()
    assert stats["completed"] == 16
    assert stats["batches"] >= 1
    assert stats["coalesced_mean"] >= 1.0
    assert stats["queue_peak"] >= 1


def test_engine_backpressure_sheds_not_deadlocks():
    serve.clear_plans()
    A = _systems(1, seed=59)
    plan = serve.FactorPlan.create((N, N), jnp.float32, v=V)
    session = plan.factor(jnp.asarray(A[0]))
    b = jnp.asarray(np.ones(N, np.float32))
    # a huge window parks the dispatcher on its first batch, so the
    # pending bound is hit deterministically; close() releases it
    eng = ServeEngine(max_batch_delay=60.0, max_pending=2)
    f1 = eng.submit(session, b)
    f2 = eng.submit(session, b)
    with pytest.raises(EngineSaturated, match="max_pending"):
        eng.submit(session, b)
    assert eng.stats()["shed"] == 1
    eng.close(timeout=60)
    # the shed did not poison the admitted requests
    assert f1.done() and f2.done()
    np.testing.assert_array_equal(np.asarray(f1.result()),
                                  np.asarray(f2.result()))
    with pytest.raises(EngineClosed):
        eng.submit(session, b)


def test_engine_block_policy_backpressures():
    """'block' admission never deadlocks: a submitter thread pushing past
    the bound finishes once the dispatcher drains."""
    serve.clear_plans()
    A = _systems(1, seed=61)
    plan = serve.FactorPlan.create((N, N), jnp.float32, v=V)
    session = plan.factor(jnp.asarray(A[0]))
    rng = np.random.default_rng(61)
    futs = []
    with ServeEngine(max_batch_delay=0.0, max_pending=2,
                     on_full="block") as eng:
        def pump():
            for _, b in _trace(rng, 12, widths=(1,)):
                futs.append(eng.submit(session, jnp.asarray(b)))

        t = threading.Thread(target=pump)
        t.start()
        t.join(timeout=120)
        assert not t.is_alive(), "blocked submitter never released"
        for f in futs:
            f.result(timeout=60)
    assert eng.stats()["completed"] == 12
    assert eng.stats()["shed"] == 0


def test_engine_block_policy_submit_many_frame_no_deadlock():
    """A submit_many frame LARGER than max_pending under 'block' never
    self-deadlocks: an item that must wait first flushes its already-
    admitted frame-mates to their lanes — a wait taken while unrouted
    frame-mates held the pending slots could never be satisfied by
    them — and every item of the frame still completes bitwise."""
    serve.clear_plans()
    A = _systems(1, seed=63)
    plan = serve.FactorPlan.create((N, N), jnp.float32, v=V)
    session = plan.factor(jnp.asarray(A[0]))
    rng = np.random.default_rng(63)
    items = [(session, jnp.asarray(b), None)
             for _, b in _trace(rng, 8, widths=(1,))]
    futs = []
    with ServeEngine(max_batch_delay=0.0, max_pending=2,
                     on_full="block") as eng:
        t = threading.Thread(
            target=lambda: futs.extend(eng.submit_many(items)))
        t.start()
        t.join(timeout=120)
        assert not t.is_alive(), \
            "batched frame wedged at the pending bound"
        results = [np.asarray(f.result(timeout=60)) for f in futs]
        for (s, b, _q), r in zip(items, results):
            np.testing.assert_array_equal(r, np.asarray(s.solve(b)))
    assert eng.stats()["completed"] == 8
    assert eng.stats()["shed"] == 0


def test_engine_close_drains_in_flight():
    serve.clear_plans()
    A = _systems(2, seed=67)
    plan = serve.FactorPlan.create((N, N), jnp.float32, v=V)
    sessions = [plan.factor(jnp.asarray(A[i])) for i in range(2)]
    rng = np.random.default_rng(67)
    eng = ServeEngine(max_batch_delay=60.0)  # everything queued at close
    pairs = [(sessions[i % 2], jnp.asarray(b))
             for i, (_, b) in enumerate(_trace(rng, 10))]
    futs = [eng.submit(s, b) for s, b in pairs]
    eng.close(timeout=120)
    assert all(f.done() for f in futs), "close() dropped queued requests"
    for (s, b), f in zip(pairs, futs):
        np.testing.assert_array_equal(np.asarray(f.result()),
                                      np.asarray(s.solve(b)))


def test_engine_stacked_sessions_match_direct():
    """Opt-in cross-session stacking: one vmapped dispatch answers many
    single-system sessions; allclose to direct (not bitwise — XLA batches
    the GEMMs differently under vmap), one stacked bucket program."""
    serve.clear_plans()
    A = _systems(3, seed=71)
    plan = serve.FactorPlan.create((N, N), jnp.float32, v=V)
    sessions = [plan.factor(jnp.asarray(A[i])) for i in range(3)]
    rng = np.random.default_rng(71)
    bs = [jnp.asarray(rng.standard_normal((N, w)).astype(np.float32))
          for w in (1, 2, 2)]
    direct = [np.asarray(s.solve(b)) for s, b in zip(sessions, bs)]
    eng = ServeEngine(max_batch_delay=60.0, stack_sessions=True,
                      max_stack=4)
    futs = [eng.submit(s, b) for s, b in zip(sessions, bs)]
    eng.close(timeout=120)
    for i, f in enumerate(futs):
        r = np.asarray(f.result())
        assert r.shape == direct[i].shape
        np.testing.assert_allclose(r, direct[i], rtol=2e-5, atol=1e-6)
    # 3 sessions pad to the 4-stack bucket, widths (1, 2, 2) to bucket 2
    assert ("stacked", 4, 2) in plan._solve_cache
    assert eng.stats()["batches"] == 1, "stack did not coalesce"
    # a batched plan refuses the stacked builder outright
    bplan = serve.FactorPlan.create((B, N, N), jnp.float32, v=V)
    with pytest.raises(AssertionError, match="single-system"):
        bplan._stacked_solve_fn(2, 1)


def test_engine_bad_rhs_fails_that_request_only():
    serve.clear_plans()
    A = _systems(1, seed=73)
    plan = serve.FactorPlan.create((N, N), jnp.float32, v=V)
    session = plan.factor(jnp.asarray(A[0]))
    good = jnp.asarray(np.ones(N, np.float32))
    with ServeEngine(max_batch_delay=0.01) as eng:
        with pytest.raises(ValueError, match="session needs"):
            eng.submit(session, jnp.zeros((N + 1,), jnp.float32))
        f = eng.submit(session, good)
        np.testing.assert_array_equal(np.asarray(f.result(timeout=60)),
                                      np.asarray(session.solve(good)))


def test_engine_counters_in_serve_stats():
    serve.clear_plans()
    A = _systems(1, seed=79)
    plan = serve.FactorPlan.create((N, N), jnp.float32, v=V)
    session = plan.factor(jnp.asarray(A[0]))
    b = jnp.asarray(np.ones(N, np.float32))
    with ServeEngine(max_batch_delay=0.01) as eng:
        for _ in range(4):
            eng.solve(session, b, timeout=60)
        merged = profiler.serve_stats()["engine"]
        mine = eng.stats()
    assert merged["engines"] >= 1
    assert merged["requests"] >= mine["requests"] >= 4
    assert merged["batches"] >= mine["batches"] >= 1
    assert merged["queue_peak"] >= mine["queue_peak"]
    assert merged["latency_p50_ms"] > 0.0
    assert merged["latency_p99_ms"] >= merged["latency_p50_ms"]
    # profiler.clear() resets phases, not the engines' own counters
    profiler.clear()
    assert profiler.serve_stats()["engine"]["requests"] >= 4
