"""Gang-resident session stacking tests: the ISSUE 10 contracts.

- Gang-stacked answers are allclose to solo dispatch, and BITWISE
  invariant to the stack bucket / pad contents (slot i of a gang
  dispatch == slot of a hand-built stacked dispatch at another bucket).
- Drifted (pending-Woodbury) and checked (health-guarded) sessions ride
  the stacked path — the two old exclusion holes — with the per-reason
  exclusion counters at literal zero.
- The stacked state is RESIDENT: steady-state windows re-stack nothing
  and compile nothing; session mutations re-sync their slot lazily via
  the version counter.
- Slot lifecycle: spill frees the slot (reused by the next adoptee),
  revival re-adopts bitwise, `stack_cap` overflow falls back solo and
  is counted, a sick slot re-dispatches solo while its gang-mates
  settle in place.
- Per-lane `max_pending` slices shed a hot lane's overflow without
  starving the fleet; per-lane shed counts surface in the lane rows.
- The adaptive controller steers `stack_sessions`/`max_stack` from
  windowed opportunity telemetry, prewarm-gated.
- Concurrency: adopt/update/solve hammering from client threads keeps
  every future resolved and every answer correct.
"""

import threading

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from conflux_tpu import serve
from conflux_tpu.batched import (
    grow_stack_tree,
    stack_trees,
    unstack_tree,
    write_slot_tree,
)
from conflux_tpu.control import AdaptiveController
from conflux_tpu.engine import EngineSaturated, ServeEngine
from conflux_tpu.gang import SessionGang
from conflux_tpu.resilience import HealthPolicy
from conflux_tpu.tier import ResidentSet

N, V = 32, 16


def _fleet(n, seed=0, policy=None):
    rng = np.random.default_rng(seed)
    A = (rng.standard_normal((n, N, N)) / np.sqrt(N)
         + 2.0 * np.eye(N)).astype(np.float32)
    plan = serve.FactorPlan.create((N, N), jnp.float32, v=V)
    return plan, [plan.factor(jnp.asarray(A[i]), policy=policy)
                  for i in range(n)], A


def _rhs(n, seed=1, width=1):
    rng = np.random.default_rng(seed)
    return [rng.standard_normal((N, width)).astype(np.float32)
            for _ in range(n)]


def _gang_of(eng, plan):
    return eng.lanes[0]._gangs.get(id(plan))


# --------------------------------------------------------------------- #
# primitives: the slot round-trip contract
# --------------------------------------------------------------------- #


def test_write_slot_roundtrip_bitwise():
    """write_slot_tree -> unstack_tree round-trips the written bits,
    and grow_stack_tree keeps old slots bitwise while padding with
    slot 0 (or zeros)."""
    rng = np.random.default_rng(7)
    trees = [(jnp.asarray(rng.standard_normal((N, N)).astype(np.float32)),
              jnp.asarray(rng.integers(0, N, N).astype(np.int32)))
             for _ in range(3)]
    stack = stack_trees([trees[0], trees[1]])
    stack = write_slot_tree(stack, trees[2], 1)
    back = unstack_tree(stack, 2)
    for a, b in zip(back[0], trees[0]):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    for a, b in zip(back[1], trees[2]):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    grown = grow_stack_tree(stack, 4)
    gb = unstack_tree(grown, 4)
    for a, b in zip(gb[1], trees[2]):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    for a, b in zip(gb[3], back[0]):  # pad slots self-reference slot 0
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    zgrown = grow_stack_tree(stack[0], 4, fill="zero")
    assert float(jnp.abs(zgrown[2:]).sum()) == 0.0


# --------------------------------------------------------------------- #
# numerics: allclose to solo, bitwise within a bucket
# --------------------------------------------------------------------- #


def test_gang_matches_direct_and_bitwise_within_bucket():
    serve.clear_plans()
    plan, fleet, _A = _fleet(5, seed=11)
    bs = _rhs(5, seed=12, width=1)
    direct = [np.asarray(s.solve(b)) for s, b in zip(fleet, bs)]
    eng = ServeEngine(max_batch_delay=60.0, stack_sessions=True,
                      max_stack=8)
    futs = [eng.submit(s, b) for s, b in zip(fleet, bs)]
    eng.close(timeout=120)  # one window: close flushes the batch
    res = [np.asarray(f.result(60)) for f in futs]
    for r, d in zip(res, direct):
        np.testing.assert_allclose(r, d, rtol=2e-5, atol=1e-6)
    st = eng.stats()
    assert st["gang_batches"] == 1
    assert st["batches"] == 1
    assert st["gang"]["sessions"] == 5
    assert st["gang"]["capacity_slots"] == 8  # rank_bucket(5)
    # bitwise within a bucket: slot results equal a hand-built stacked
    # dispatch at a DIFFERENT bucket with different pad contents
    with fleet[0]._lock, fleet[3]._lock:
        F = stack_trees([fleet[3]._factors, fleet[0]._factors])
    buf = np.zeros((2, N, 1), np.float32)
    buf[0] = bs[3]
    ref = np.asarray(plan._stacked_solve_fn(2, 1)(F, None, buf))[0]
    np.testing.assert_array_equal(res[3], ref)


def test_gang_resident_steady_state_no_restack_no_compile():
    """Second and later windows re-sync nothing (version counters
    unchanged), rebuild nothing, and compile nothing."""
    serve.clear_plans()
    plan, fleet, _A = _fleet(4, seed=21)
    bs = _rhs(4, seed=22)
    eng = ServeEngine(max_batch_delay=0.05, stack_sessions=True,
                      max_stack=4)
    try:
        for f in [eng.submit(s, b) for s, b in zip(fleet, bs)]:
            f.result(60)
        g = _gang_of(eng, plan)
        st0 = g.stats()
        traces0 = dict(plan.trace_counts)
        for _ in range(3):
            for f in [eng.submit(s, b) for s, b in zip(fleet, bs)]:
                f.result(60)
        st1 = g.stats()
    finally:
        eng.close(timeout=120)
    assert plan.trace_counts == traces0, "steady-state window compiled"
    assert st1["adopts"] == st0["adopts"]
    assert st1["rebuilds"] == st0["rebuilds"]
    assert st1["refreshes"] == st0["refreshes"] == 0
    assert eng.stats()["gang_batches"] >= 4


# --------------------------------------------------------------------- #
# the closed exclusion holes: drifted + checked sessions stack
# --------------------------------------------------------------------- #


def test_gang_drifted_and_checked_sessions_stack():
    serve.clear_plans()
    plan, fleet, _A = _fleet(4, seed=31)
    rng = np.random.default_rng(32)
    U = (0.01 * rng.standard_normal((N, 3))).astype(np.float32)
    Vm = (0.01 * rng.standard_normal((N, 3))).astype(np.float32)
    fleet[0].update(U, Vm)
    fleet[2].update(2 * U, Vm)
    bs = _rhs(4, seed=33, width=2)
    direct = [np.asarray(s.solve(b)) for s, b in zip(fleet, bs)]
    eng = ServeEngine(max_batch_delay=60.0, stack_sessions=True,
                      max_stack=4, health=HealthPolicy())
    futs = [eng.submit(s, b) for s, b in zip(fleet, bs)]
    eng.close(timeout=120)  # one window: close flushes the batch
    res = [np.asarray(f.result(60)) for f in futs]
    for i, (r, d) in enumerate(zip(res, direct)):
        np.testing.assert_allclose(r, d, rtol=5e-5, atol=1e-6,
                                   err_msg=f"session {i}")
    st = eng.stats()
    excl = st["stack_exclusions"]
    assert excl["upd_pending"] == 0, "drifted sessions must stack now"
    assert excl["checked"] == 0, "checked sessions must stack now"
    assert st["gang_batches"] == 1, "the whole window rode one dispatch"
    g = _gang_of(eng, plan)
    assert g.stats()["rank_bucket"] == 4  # rank_bucket(3)
    assert g.stats()["checked"]


def test_gang_refresh_after_mutation():
    """update()/refactor() bump the session version; the next stacked
    window re-syncs ONLY that slot and answers track the new state."""
    serve.clear_plans()
    plan, fleet, _A = _fleet(3, seed=41)
    bs = _rhs(3, seed=42)
    eng = ServeEngine(max_batch_delay=0.05, stack_sessions=True,
                      max_stack=4)
    try:
        for f in [eng.submit(s, b) for s, b in zip(fleet, bs)]:
            f.result(60)
        g = _gang_of(eng, plan)
        r0 = g.stats()["refreshes"]
        rng = np.random.default_rng(43)
        U = (0.05 * rng.standard_normal((N, 2))).astype(np.float32)
        fleet[1].update(U, U)
        direct = [np.asarray(s.solve(b)) for s, b in zip(fleet, bs)]
        futs = [eng.submit(s, b) for s, b in zip(fleet, bs)]
        res = [np.asarray(f.result(60)) for f in futs]
        assert g.stats()["refreshes"] == r0 + 1
        for r, d in zip(res, direct):
            np.testing.assert_allclose(r, d, rtol=5e-5, atol=1e-6)
        # refactor absorbs the drift; the slot re-syncs again and the
        # gang returns to the PLAIN stacked program path
        fleet[1].refactor()
        direct = [np.asarray(s.solve(b)) for s, b in zip(fleet, bs)]
        futs = [eng.submit(s, b) for s, b in zip(fleet, bs)]
        res = [np.asarray(f.result(60)) for f in futs]
        assert g.stats()["refreshes"] == r0 + 2
        for r, d in zip(res, direct):
            np.testing.assert_allclose(r, d, rtol=5e-5, atol=1e-6)
    finally:
        eng.close(timeout=120)


# --------------------------------------------------------------------- #
# slot lifecycle: spill frees, revival re-adopts, cap excludes
# --------------------------------------------------------------------- #


def test_gang_slot_reuse_after_spill_and_revive_bitwise():
    serve.clear_plans()
    plan, fleet, _A = _fleet(4, seed=51)
    bs = _rhs(4, seed=52)
    rs = ResidentSet(max_sessions=16)
    eng = ServeEngine(max_batch_delay=0.05, stack_sessions=True,
                      max_stack=4, residency=rs)
    try:
        rs.adopt(*fleet)
        futs = [eng.submit(s, b) for s, b in zip(fleet, bs)]
        before = [np.asarray(f.result(60)) for f in futs]
        g = _gang_of(eng, plan)
        assert g.members == 4 and g.cap == 4
        slot1 = fleet[1]._gang_slot
        assert rs.spill(fleet[1]) == 1
        assert fleet[1].tier == "host"
        assert fleet[1]._gang is None, "spill must free the gang slot"
        assert g.members == 3
        # a NEW session reuses the freed slot — capacity does not grow
        extra = plan.factor(jnp.asarray(_A[0]))
        futs = [eng.submit(s, bs[0])
                for s in (fleet[0], fleet[2], extra)]
        for f in futs:
            f.result(60)
        assert g.cap == 4
        assert extra._gang_slot == slot1, "freed slot not reused"
        # free the slot again (spill the stand-in) so the revival can
        # land straight back into it at the SAME stack bucket
        rs.adopt(extra)
        assert rs.spill(extra) == 1
        assert extra._gang is None
        assert g.members == 3
        # revival re-adopts (grouped revival lands straight in a slot)
        assert rs.revive_many([fleet[1]]) == 1
        assert fleet[1].tier == "device"
        assert fleet[1]._gang is g and fleet[1]._gang_slot == slot1, \
            "grouped revival did not land straight into the gang slot"
        futs = [eng.submit(s, b) for s, b in zip(fleet, bs)]
        after = [np.asarray(f.result(60)) for f in futs]
        # revived state is bitwise (h2d restore) and the stacked
        # program is pad/bucket-invariant within the SAME bucket, so
        # the answers replay exactly
        np.testing.assert_array_equal(after[1], before[1])
    finally:
        eng.close(timeout=120)


def test_gang_stack_cap_exclusion_counted():
    serve.clear_plans()
    plan, fleet, _A = _fleet(3, seed=61)
    bs = _rhs(3, seed=62)
    direct = [np.asarray(s.solve(b)) for s, b in zip(fleet, bs)]
    eng = ServeEngine(max_batch_delay=60.0, stack_sessions=True,
                      max_stack=2)
    futs = [eng.submit(s, b) for s, b in zip(fleet, bs)]
    eng.close(timeout=120)  # one window: close flushes the batch
    res = [np.asarray(f.result(60)) for f in futs]
    for r, d in zip(res, direct):
        np.testing.assert_allclose(r, d, rtol=2e-5, atol=1e-6)
    st = eng.stats()
    assert st["stack_exclusions"]["stack_cap"] >= 1
    assert st["gang"]["sessions"] == 2


def test_gang_sick_slot_isolated_gangmates_settle():
    """A slot whose factors went bad fails its per-slot verdict; its
    request recovers through the SOLO escalation ladder (refactor from
    the clean base) while gang-mates settle from the same dispatch."""
    serve.clear_plans()
    plan, fleet, _A = _fleet(3, seed=71)
    bs = _rhs(3, seed=72)
    eng = ServeEngine(max_batch_delay=0.05, stack_sessions=True,
                      max_stack=4, health=HealthPolicy())
    try:
        for f in [eng.submit(s, b) for s, b in zip(fleet, bs)]:
            f.result(60)
        direct = [np.asarray(s.solve(b)) for s, b in zip(fleet, bs)]
        with fleet[1]._lock:  # corrupt the resident factors in place
            bad = tuple(jnp.full_like(leaf, jnp.nan)
                        if jnp.issubdtype(leaf.dtype, jnp.floating)
                        else leaf for leaf in fleet[1]._factors)
            fleet[1]._factors = bad
            fleet[1]._gang_ver += 1
        from conflux_tpu import resilience as res_mod

        h0 = res_mod.health_stats()
        futs = [eng.submit(s, b) for s, b in zip(fleet, bs)]
        res = [np.asarray(f.result(120)) for f in futs]
        h1 = res_mod.health_stats()
    finally:
        eng.close(timeout=120)
    for i, (r, d) in enumerate(zip(res, direct)):
        np.testing.assert_allclose(r, d, rtol=5e-5, atol=1e-6,
                                   err_msg=f"session {i}")
    assert h1["gang_unhealthy_slots"] > h0.get("gang_unhealthy_slots", 0)
    assert h1["refactor_escalations"] > h0.get("refactor_escalations", 0)


# --------------------------------------------------------------------- #
# per-lane pending slices
# --------------------------------------------------------------------- #


@pytest.mark.skipif(jax.device_count() < 2, reason="needs >=2 devices")
def test_lane_pending_slice_sheds_hot_lane_only():
    serve.clear_plans()
    plan, fleet, _A = _fleet(2, seed=81)
    eng = ServeEngine(max_batch_delay=60.0, lanes=2, max_pending=64,
                      max_lane_pending=2)
    b = np.ones((N, 1), np.float32)
    try:
        s0 = fleet[0]
        s0.sid = "hot"
        lane = eng._lane_for(s0)
        futs = [eng.submit(s0, b) for _ in range(2)]
        with pytest.raises(EngineSaturated, match="max_lane_pending"):
            eng.submit(s0, b)
        # the OTHER lane still admits
        other = fleet[1]
        other_dev = [ln.device for ln in eng.lanes
                     if ln is not lane][0]
        other.to_device(other_dev)
        f2 = eng.submit(other, b)
        rows = {r["lane"]: r for r in eng.stats()["lanes"]}
        assert rows[lane.index]["sheds"] == 1
        assert rows[lane.index]["pending"] == 2
        futs.append(f2)
    finally:
        eng.close(timeout=120)
    for f in futs:
        assert f.result(60) is not None
    assert eng.knobs()["max_lane_pending"] == 2


# --------------------------------------------------------------------- #
# controller steering
# --------------------------------------------------------------------- #


class _FakeWindow:
    def __init__(self, deltas):
        self.deltas = list(deltas)

    def delta(self):
        if len(self.deltas) > 1:
            return self.deltas.pop(0)
        return self.deltas[0]


def test_controller_steers_stacking_prewarm_gated():
    serve.clear_plans()
    plan, fleet, _A = _fleet(2, seed=91)
    eng = ServeEngine(max_batch_delay=0.0)
    ctl = AdaptiveController(slo_p99_ms=25.0, interval=60.0,
                             stack_after=2, unstack_after=2)
    ctl.attach(eng)
    try:
        b = np.ones((N, 1), np.float32)
        eng.solve(fleet[0], b, timeout=60)  # registers active targets
        opp = AdaptiveController.blank_delta()
        opp["engine"]["gang_opportunity"] = 4
        opp["engine"]["batches"] = 4
        opp["bucket_hits"] = {1: 4}
        ctl._window = _FakeWindow([opp])
        assert not eng.stack_sessions
        ctl.step()          # pressure 1
        ctl.step()          # pressure 2 -> background prewarm launched
        pre = ctl._stack_prewarm
        assert pre is not None
        target, wb, thread = pre
        thread.join(120)
        assert plan.bucket_ready(stack=(target, wb))
        ctl.step()          # gate passes -> knob flips
        assert eng.stack_sessions
        assert eng.max_stack == target == 4  # rank_bucket(4) capped
        # idle windows with zero stacked batches disable it again
        idle = AdaptiveController.blank_delta()
        idle["engine"]["batches"] = 3
        idle["engine"]["gang_batches"] = 0
        ctl._window = _FakeWindow([idle])
        ctl.step()
        ctl.step()
        assert not eng.stack_sessions
        log = [d["knob"] for d in ctl.stats()["decisions_log"]]
        assert "stack_sessions" in log
    finally:
        eng.close(timeout=120)


# --------------------------------------------------------------------- #
# concurrency: adopt/update/solve hammer
# --------------------------------------------------------------------- #


def test_gang_concurrent_adopt_update_solve_hammer():
    serve.clear_plans()
    plan, fleet, _A = _fleet(6, seed=101)
    eng = ServeEngine(max_batch_delay=0.001, stack_sessions=True,
                      max_stack=8, max_pending=4096)
    rng = np.random.default_rng(102)
    bs = _rhs(6, seed=103)
    errors: list = []
    stop = threading.Event()

    def submitter(idx):
        try:
            for _ in range(30):
                f = eng.submit(fleet[idx], bs[idx])
                f.result(120)
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    def mutator():
        try:
            k = 0
            while not stop.is_set() and k < 10:
                s = fleet[k % len(fleet)]
                U = (0.01 * rng.standard_normal((N, 2))
                     ).astype(np.float32)
                s.update(U, U, replace=True)
                if k % 3 == 0:
                    s.refactor()
                k += 1
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    threads = [threading.Thread(target=submitter, args=(i,))
               for i in range(len(fleet))]
    threads.append(threading.Thread(target=mutator))
    try:
        for t in threads:
            t.start()
        for t in threads:
            t.join(300)
        stop.set()
        assert not any(t.is_alive() for t in threads), "hammer wedged"
        assert not errors, errors
        # quiesced oracle: every session answers correctly afterwards
        direct = [np.asarray(s.solve(b)) for s, b in zip(fleet, bs)]
        futs = [eng.submit(s, b) for s, b in zip(fleet, bs)]
        for f, d in zip(futs, direct):
            np.testing.assert_allclose(np.asarray(f.result(120)), d,
                                       rtol=5e-5, atol=1e-6)
    finally:
        stop.set()
        eng.close(timeout=120)


def test_gang_set_knobs_validation_and_roundtrip():
    serve.clear_plans()
    with ServeEngine(max_batch_delay=0.0) as eng:
        k = eng.set_knobs(stack_sessions=True, max_stack=4,
                          max_lane_pending=16)
        assert k["stack_sessions"] and k["max_stack"] == 4
        assert k["max_lane_pending"] == 16
        assert eng.knobs() == k
        with pytest.raises(ValueError, match="max_stack"):
            eng.set_knobs(max_stack=0)
        with pytest.raises(ValueError, match="max_lane_pending"):
            eng.set_knobs(max_lane_pending=0)
        with pytest.raises(ValueError, match="lane"):
            eng.set_knobs(lane=0, max_batch_delay=0.001,
                          stack_sessions=True)


def test_unganged_session_unchanged_and_gang_detach_on_to_device():
    """stack_sessions=False engines never create gangs (the PR 9
    byte-identical contract's structural half), and `to_device` on a
    ganged session releases its slot."""
    serve.clear_plans()
    plan, fleet, _A = _fleet(2, seed=111)
    bs = _rhs(2, seed=112)
    eng = ServeEngine(max_batch_delay=60.0)
    futs = [eng.submit(s, b) for s, b in zip(fleet, bs)]
    eng.close(timeout=120)
    for f in futs:
        f.result(60)
    assert not eng.lanes[0]._gangs
    st = eng.stats()
    assert st["gang_batches"] == 0
    assert st["gang_opportunity"] >= 1  # the controller's signal
    eng2 = ServeEngine(max_batch_delay=60.0, stack_sessions=True,
                       max_stack=4)
    futs = [eng2.submit(s, b) for s, b in zip(fleet, bs)]
    eng2.close(timeout=120)
    for f in futs:
        f.result(60)
    g = _gang_of(eng2, plan)
    assert g.members == 2
    fleet[0].to_device(jax.devices()[0])
    assert fleet[0]._gang is None
    assert g.members == 1


def test_gang_module_refuses_batched_plans():
    serve.clear_plans()
    bplan = serve.FactorPlan.create((4, N, N), jnp.float32, v=V)
    with pytest.raises(AssertionError, match="single-system"):
        bplan._stacked_solve_health_fn(2, 1)
    with pytest.raises(AssertionError, match="single-system"):
        bplan._stacked_update_solve_fn(2, 2, 1, 0)
    g = SessionGang(bplan, None)  # construction is fine; dispatch never
    assert g.members == 0
