"""Adaptive-controller tests: the ISSUE 8 acceptance contracts.

- `controller=None` engines are byte-identical to the pre-controller
  engine (no thread, no stats key, bitwise answers).
- Each decision block steers its knob the right way, driven
  deterministically through `AdaptiveController.step()` with synthetic
  telemetry windows (no timing, no sleeps).
- Knob moves are prewarm-gated: the width cap grows only after the
  target bucket's program is warm on every active plan, and moves never
  compile anything.
- `EngineSaturated.retry_after` rides the measured drain rate when an
  estimate exists and falls back to the exponential guess otherwise.
- Guard relaxation backs off sampling only after a clean streak and
  restores INSTANTLY (engine-side) on any trip.
- `FactorPlan.release_buckets` drops retired bucket programs (and only
  them) — grow-then-shrink leaves no stale programs.
- The windowed profiler API: per-window deltas are consistent under
  concurrent writers, `clear()` keeps its semantics, and cumulative
  consumers are unchanged.
"""

import threading

import numpy as np
import pytest

import jax.numpy as jnp

from conflux_tpu import profiler, resilience, serve
from conflux_tpu.control import AdaptiveController, ControlLimits
from conflux_tpu.engine import EngineSaturated, ServeEngine
from conflux_tpu.resilience import HealthPolicy, RhsNonFinite

N, V = 32, 16


def _session(seed=0, v=V):
    rng = np.random.default_rng(seed)
    A = (rng.standard_normal((N, N)) / np.sqrt(N)
         + 2.0 * np.eye(N)).astype(np.float32)
    plan = serve.FactorPlan.create((N, N), jnp.float32, v=v)
    return plan, plan.factor(jnp.asarray(A))


class _FakeWindow:
    """A scripted StatsWindow: yields each delta once, then repeats the
    last — deterministic telemetry for step()-driven tests."""

    def __init__(self, deltas):
        self.deltas = list(deltas)

    def delta(self):
        if len(self.deltas) > 1:
            return self.deltas.pop(0)
        return self.deltas[0]


def _ctl(eng, **kw):
    kw.setdefault("slo_p99_ms", 25.0)
    kw.setdefault("interval", 60.0)  # never ticks on its own
    ctl = AdaptiveController(**kw)
    ctl.attach(eng)
    return ctl


# --------------------------------------------------------------------- #
# opt-in contract
# --------------------------------------------------------------------- #


def test_controller_none_default_unchanged():
    """No controller: no thread, no stats key, bitwise answers."""
    serve.clear_plans()
    _plan, s = _session(seed=3)
    b = np.ones((N, 1), np.float32)
    before = {t.name for t in threading.enumerate()}
    with ServeEngine(max_batch_delay=0.01) as eng:
        x = np.asarray(eng.solve(s, b, timeout=60))
        st = eng.stats()
    assert "controller" not in st
    assert "serve-engine-controller" not in before
    np.testing.assert_array_equal(x, np.asarray(s.solve(b)))


def test_controller_lifecycle_and_stats():
    serve.clear_plans()
    _plan, s = _session(seed=5)
    ctl = AdaptiveController(slo_p99_ms=25.0, interval=0.01)
    eng = ServeEngine(max_batch_delay=0.0, controller=ctl)
    try:
        b = np.ones((N, 1), np.float32)
        eng.solve(s, b, timeout=60)
        deadline = threading.Event()
        for _ in range(200):  # wait for a couple of real ticks
            if ctl.stats()["ticks"] >= 2:
                break
            deadline.wait(0.01)
        st = eng.stats()
        assert st["controller"]["ticks"] >= 2
        assert st["controller"]["errors"] == 0
        assert st["knobs"]["max_batch_delay"] == eng.max_batch_delay
    finally:
        eng.close(timeout=60)
    assert not ctl._thread.is_alive(), "close() left the controller running"
    eng.close()  # idempotent with the controller attached


def test_attach_twice_raises():
    serve.clear_plans()
    with ServeEngine(max_batch_delay=0.0) as e1, \
            ServeEngine(max_batch_delay=0.0) as e2:
        ctl = AdaptiveController()
        ctl.attach(e1)
        with pytest.raises(RuntimeError, match="already attached"):
            ctl.attach(e2)


# --------------------------------------------------------------------- #
# knob setters + retry_after
# --------------------------------------------------------------------- #


def test_set_knobs_validates_and_buckets():
    serve.clear_plans()
    with ServeEngine(max_batch_delay=0.002) as eng:
        with pytest.raises(ValueError, match="max_batch_delay"):
            eng.set_knobs(max_batch_delay=-1.0)
        with pytest.raises(ValueError, match=">= 1"):
            eng.set_knobs(max_pending=0)
        with pytest.raises(ValueError, match="staging_stride"):
            eng.set_knobs(staging_stride=0)
        k = eng.set_knobs(max_batch_delay=0.004, max_pending=99,
                          max_factor_batch=9)
        assert k["max_batch_delay"] == 0.004
        assert k["max_pending"] == 99
        assert k["max_factor_batch"] == 16  # rounds to its pow2 bucket
        assert eng.knobs() == k


def test_retry_after_measured_drain_rate_with_fallback():
    """The satellite: shed hints ride the measured drain rate when one
    exists; the exponential guess is the no-estimate fallback."""
    serve.clear_plans()
    _plan, s = _session(seed=7)
    b = np.ones(N, np.float32)
    # a huge window parks the dispatcher so the bound trips reliably
    eng = ServeEngine(max_batch_delay=60.0, max_pending=2)
    try:
        eng.submit(s, b)
        eng.submit(s, b)
        with pytest.raises(EngineSaturated, match="backoff") as ei:
            eng.submit(s, b)
        assert ei.value.retry_after == pytest.approx(1e-3)  # 2^0 ms
        eng.set_knobs(drain_rate=100.0)
        with pytest.raises(EngineSaturated, match="drain rate") as ei:
            eng.submit(s, b)
        # second consecutive shed at 100/s drain: 2 drain intervals
        assert ei.value.retry_after == pytest.approx(2 / 100.0)
    finally:
        eng.close(timeout=60)


# --------------------------------------------------------------------- #
# decision blocks (deterministic: scripted windows through step())
# --------------------------------------------------------------------- #


def test_delay_shrinks_when_p99_near_slo():
    serve.clear_plans()
    with ServeEngine(max_batch_delay=0.008) as eng:
        ctl = _ctl(eng)
        d = AdaptiveController.blank_delta()
        d["engine"].update(latency_samples=64, latency_p99_ms=24.0,
                           requests=64, completed=64, batches=8,
                           coalesced_requests=64, coalesced_mean=8.0)
        ctl._window = _FakeWindow([d])
        ctl.step()
        assert eng.max_batch_delay == pytest.approx(0.004)
        ctl.step()  # still near the SLO: keeps shrinking
        assert eng.max_batch_delay == pytest.approx(0.002)
        log = ctl.stats()["decisions_log"]
        assert any(e["knob"] == "max_batch_delay" and "shrink" in e["reason"]
                   for e in log)


def test_delay_widens_when_under_coalesced_and_backlogged():
    serve.clear_plans()
    with ServeEngine(max_batch_delay=0.0) as eng:
        ctl = _ctl(eng)
        d = AdaptiveController.blank_delta()
        d["engine"].update(latency_samples=64, latency_p99_ms=3.0,
                           requests=100, completed=60, batches=60,
                           coalesced_requests=60, coalesced_mean=1.0,
                           backlog_delta=40, pending=40)
        ctl._window = _FakeWindow([d])
        ctl.step()  # one window of pressure is a clump, not a regime
        assert eng.max_batch_delay == 0.0
        ctl.step()  # two consecutive: widen
        first = eng.max_batch_delay
        assert first > 0.0  # seeded out of the zero window
        ctl.step()
        assert eng.max_batch_delay > first  # multiplicative climb
        assert eng.max_batch_delay <= ctl.limits.max_batch_delay


def test_delay_decays_on_light_solo_traffic():
    serve.clear_plans()
    with ServeEngine(max_batch_delay=0.008) as eng:
        ctl = _ctl(eng)
        d = AdaptiveController.blank_delta()
        d["engine"].update(latency_samples=10, latency_p99_ms=9.0,
                           requests=10, completed=10, batches=10,
                           coalesced_requests=10, coalesced_mean=1.0,
                           backlog_delta=0, pending=0)
        ctl._window = _FakeWindow([d])
        ctl.step()
        assert eng.max_batch_delay == pytest.approx(0.004)


def test_max_pending_sized_from_drain_rate_with_deadband():
    serve.clear_plans()
    with ServeEngine(max_batch_delay=0.0, max_pending=1024) as eng:
        ctl = _ctl(eng, pending_slack=1.5)
        d = AdaptiveController.blank_delta(seconds=1.0)
        d["engine"].update(requests=1000, completed=1000, batches=100,
                           coalesced_requests=1000, coalesced_mean=10.0,
                           latency_samples=100, latency_p99_ms=5.0)
        ctl._window = _FakeWindow([d])
        ctl.step()
        # 1000/s drain x 25ms SLO x 1.5 slack = 37 (above the floor)
        assert eng.max_pending == 37
        assert eng.knobs()["drain_rate"] == pytest.approx(1000.0)
        before = eng.max_pending
        ctl.step()  # identical window: inside the deadband, no thrash
        assert eng.max_pending == before
        decisions = [e for e in ctl.stats()["decisions_log"]
                     if e["knob"] == "max_pending"]
        assert len(decisions) == 1


def test_width_growth_is_prewarm_gated_and_compile_free_at_switch():
    serve.clear_plans()
    plan, s = _session(seed=11)
    with ServeEngine(max_batch_delay=0.0, max_coalesce_width=4) as eng:
        eng.prewarm(s, widths=(1, 2, 4))
        b = np.ones((N, 1), np.float32)
        eng.solve(s, b, timeout=60)  # registers the session
        ctl = _ctl(eng, grow_after=1,
                   limits=ControlLimits(max_coalesce_width=8))
        d = AdaptiveController.blank_delta()
        d["engine"].update(requests=50, completed=50, batches=20,
                           coalesced_requests=50, coalesced_mean=2.5,
                           width_capped=10, latency_samples=50,
                           latency_p99_ms=2.0)
        ctl._window = _FakeWindow([d])
        assert not plan.bucket_ready(width=8)
        ctl.step()  # launches the background prewarm; cap must NOT move
        assert eng.max_coalesce_width == 4
        pre = ctl._width_prewarm
        assert pre is not None and pre[0] == 8
        pre[1].join(timeout=120)
        assert plan.bucket_ready(width=8), "prewarm did not warm bucket 8"
        snapshot = dict(plan.trace_counts)
        ctl.step()  # prewarm complete -> the cap moves, compiling nothing
        assert eng.max_coalesce_width == 8
        assert plan.trace_counts == snapshot, \
            "the knob move itself compiled a program"
        # and traffic at the new cap rides the warm bucket: still zero
        futs = [eng.submit(s, b) for _ in range(8)]
        for f in futs:
            f.result(timeout=60)
        assert plan.trace_counts == snapshot


def test_width_retirement_releases_cold_bucket_programs():
    serve.clear_plans()
    plan, s = _session(seed=13)
    with ServeEngine(max_batch_delay=0.0, max_coalesce_width=4) as eng:
        rng = np.random.default_rng(13)
        for w in (1, 4):
            eng.solve(s, rng.standard_normal((N, w)).astype(np.float32),
                      timeout=60)
        assert {1, 4} <= set(plan._solve_cache)
        ctl = _ctl(eng, retire_after=2)
        hot = AdaptiveController.blank_delta()
        hot["engine"].update(requests=6, completed=6, batches=6,
                             coalesced_requests=6, coalesced_mean=1.0)
        hot["bucket_hits"] = {1: 3, 4: 3}
        cold = AdaptiveController.blank_delta()
        cold["engine"].update(requests=3, completed=3, batches=3,
                              coalesced_requests=3, coalesced_mean=1.0)
        cold["bucket_hits"] = {1: 3}
        ctl._window = _FakeWindow([hot, cold])
        ctl.step()            # both buckets hot
        assert 4 in plan._solve_cache
        ctl.step()            # bucket 4 cold x1
        assert 4 in plan._solve_cache
        ctl.step()            # cold x2 == retire_after -> retired
        assert 4 not in plan._solve_cache
        assert 1 in plan._solve_cache
        assert eng.max_coalesce_width == 1  # cap follows live traffic
        # retirement is eviction, not prohibition: a late wide request
        # still answers (paying one re-trace)
        x = np.asarray(eng.solve(
            s, rng.standard_normal((N, 4)).astype(np.float32), timeout=60))
        assert x.shape == (N, 4)


def test_health_relaxes_after_calm_and_restores_instantly_on_trip():
    serve.clear_plans()
    _plan, s = _session(seed=17)
    strict = HealthPolicy(submit_guard_sample=4096)
    with ServeEngine(max_batch_delay=0.0, health=strict) as eng:
        eng.prewarm(s, widths=(1,))
        ctl = _ctl(eng, relax_health_after=3)
        ctl._window = _FakeWindow([AdaptiveController.blank_delta()])
        for _ in range(3):
            assert eng.health is strict
            ctl.step()
        assert eng.health is not strict
        assert eng.health.submit_guard_sample == \
            ctl.limits.relaxed_guard_sample
        assert eng._staging_stride == ctl.limits.staging_stride
        assert ctl.stats()["relaxed_guards"] is True
        # ANY trip restores full guarding on the tripping thread — the
        # engine does not wait for a controller tick
        bad = np.ones(N, np.float32)
        bad[0] = np.nan
        with pytest.raises(RhsNonFinite):
            eng.submit(s, bad)
        assert eng.health is strict
        assert eng._staging_stride == 1
        # the next window reports the trip; the controller re-syncs
        tripped = AdaptiveController.blank_delta()
        tripped["health"] = {"rhs_rejects": 1}
        ctl._window = _FakeWindow([tripped])
        ctl.step()
        assert ctl.stats()["relaxed_guards"] is False
        # good traffic still answers under the restored strict policy
        good = np.ones(N, np.float32)
        np.testing.assert_array_equal(
            np.asarray(eng.solve(s, good, timeout=60)),
            np.asarray(s.solve(good)))


def test_knob_moves_compile_nothing():
    serve.clear_plans()
    plan, s = _session(seed=19)
    with ServeEngine(max_batch_delay=0.002, max_coalesce_width=4) as eng:
        eng.prewarm(s, widths=(1, 2, 4))
        b = np.ones((N, 1), np.float32)
        eng.solve(s, b, timeout=60)
        snapshot = dict(plan.trace_counts)
        ctl = _ctl(eng)
        busy = AdaptiveController.blank_delta()
        busy["engine"].update(requests=100, completed=60, batches=60,
                              coalesced_requests=60, coalesced_mean=1.0,
                              backlog_delta=40, pending=40,
                              latency_samples=60, latency_p99_ms=30.0)
        ctl._window = _FakeWindow([busy])
        for _ in range(4):
            ctl.step()  # delay + pending moves under pressure
        futs = [eng.submit(s, b) for _ in range(8)]
        for f in futs:
            f.result(timeout=60)
        assert plan.trace_counts == snapshot, \
            "knob moves (or traffic after them) compiled a program"


# --------------------------------------------------------------------- #
# FactorPlan.release_buckets (the grow-then-shrink satellite)
# --------------------------------------------------------------------- #


def test_release_buckets_grow_then_shrink_leaves_no_stale_programs():
    serve.clear_plans()
    plan, s = _session(seed=23)
    rng = np.random.default_rng(23)
    for w in (1, 2, 4, 8):
        s.solve(jnp.asarray(rng.standard_normal((N, w)).astype(np.float32)))
    assert set(plan._solve_cache) == {1, 2, 4, 8}
    dropped = plan.release_buckets(widths=(4, 8))
    assert dropped == 2
    assert set(plan._solve_cache) == {1, 2}
    assert plan.release_buckets(widths=(4, 8)) == 0  # idempotent
    # checked programs and the probe: only the released bucket's
    # programs go; the probe program is not a bucket and survives.
    # Blocked (default) plans keep their fused-probe checked programs
    # in the dedicated _trsm_cache (DESIGN §27) — released with the
    # width bucket all the same
    s.solve_checked(jnp.asarray(np.ones(N, np.float32)))
    assert ("health", 1) in plan._trsm_cache
    assert ("health", 1) not in plan._solve_cache
    assert ("probe",) in plan._solve_cache
    plan.release_buckets(widths=(1,))
    assert ("health", 1) not in plan._trsm_cache
    assert 1 not in plan._solve_cache
    assert ("probe",) in plan._solve_cache
    # factor lane: stacked buckets release; bucket 1 is plan.factor's
    # own path and is refused
    plan._stacked_factor_fn(2)
    assert ("factor", 2) in plan._factor_cache
    assert plan.release_buckets(factor_batches=(2,)) == 1
    assert ("factor", 2) not in plan._factor_cache
    with pytest.raises(ValueError, match="bucket 1"):
        plan.release_buckets(factor_batches=(1,))
    # a released width still answers (re-traced, not forbidden)
    x = np.asarray(s.solve(jnp.asarray(
        rng.standard_normal((N, 8)).astype(np.float32))))
    assert x.shape == (N, 8)


def test_bucket_ready_reflects_warmth():
    serve.clear_plans()
    plan, s = _session(seed=29)
    assert not plan.bucket_ready(width=2)
    assert not plan.bucket_ready()  # nothing asked -> not ready
    s.solve(jnp.asarray(np.ones((N, 2), np.float32)))
    assert plan.bucket_ready(width=2)
    assert not plan.bucket_ready(width=2, checked=True)
    s.solve_checked(jnp.asarray(np.ones((N, 2), np.float32)))
    assert plan.bucket_ready(width=2, checked=True)
    assert not plan.bucket_ready(factor_batch=2)
    plan._stacked_factor_fn(2)  # built but never called: NOT ready
    assert not plan.bucket_ready(factor_batch=2)


# --------------------------------------------------------------------- #
# the windowed profiler API
# --------------------------------------------------------------------- #


def test_stats_window_engine_deltas_and_tokens():
    serve.clear_plans()
    _plan, s = _session(seed=31)
    b = np.ones((N, 1), np.float32)
    with ServeEngine(max_batch_delay=0.0) as eng:
        for f in [eng.submit(s, b) for _ in range(4)]:
            f.result(timeout=60)
        w = profiler.StatsWindow(eng)  # baseline AFTER the first 4
        for f in [eng.submit(s, b) for _ in range(3)]:
            f.result(timeout=60)
        d = w.delta()
        assert d["engine"]["completed"] == 3
        assert d["engine"]["latency_samples"] == 3
        assert d["engine"]["latency_p50_ms"] > 0.0
        assert d["engine"]["requests"] == 3
        d2 = w.delta()  # empty window
        assert d2["engine"]["completed"] == 0
        assert d2["engine"]["latency_samples"] == 0
        assert d2["engine"]["latency_p99_ms"] == 0.0
        # cumulative consumers are untouched by windowing
        assert eng.stats()["completed"] == 7


def test_stats_window_concurrent_writers_sum_to_cumulative():
    """Thread-hammer: windows taken WHILE workers bump the shared
    telemetry never lose or double-count — the window deltas sum to
    exactly the cumulative difference."""
    profiler.clear()
    w = profiler.StatsWindow()
    h0 = resilience.health_stats()["rhs_rejects"]
    c0 = profiler.serve_stats()["solve"]["count"]
    PER, WORKERS = 200, 4
    stop = threading.Event()
    sums = {"rhs_rejects": 0, "solve": 0}

    def hammer():
        for _ in range(PER):
            resilience.bump("rhs_rejects")
            with profiler.region("serve.solve"):
                pass

    def window_taker():
        while not stop.is_set():
            d = w.delta()
            sums["rhs_rejects"] += d["health"].get("rhs_rejects", 0)
            sums["solve"] += d["phases"]["solve"]["count"]

    ts = [threading.Thread(target=hammer) for _ in range(WORKERS)]
    taker = threading.Thread(target=window_taker)
    taker.start()
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=120)
    stop.set()
    taker.join(timeout=120)
    d = w.delta()  # the tail window
    sums["rhs_rejects"] += d["health"].get("rhs_rejects", 0)
    sums["solve"] += d["phases"]["solve"]["count"]
    total = WORKERS * PER
    assert sums["rhs_rejects"] == total
    assert sums["solve"] == total
    # cumulative consumers unchanged by any of it
    assert resilience.health_stats()["rhs_rejects"] - h0 == total
    assert profiler.serve_stats()["solve"]["count"] - c0 == total
    profiler.clear()


def test_stats_window_clear_clamps_not_negates():
    """profiler.clear() mid-window: the next delta reports the
    post-clear counts (clamped at zero), never negatives, and clear()'s
    cumulative semantics are preserved."""
    profiler.clear()
    w = profiler.StatsWindow()
    for _ in range(5):
        resilience.bump("rhs_rejects")
    assert w.delta()["health"]["rhs_rejects"] == 5
    for _ in range(3):
        resilience.bump("rhs_rejects")
    profiler.clear()
    for _ in range(2):
        resilience.bump("rhs_rejects")
    d = w.delta()
    assert d["health"]["rhs_rejects"] == 2  # post-clear counts
    assert all(v >= 0 for v in d["health"].values())
    assert resilience.health_stats()["rhs_rejects"] == 2
    profiler.clear()
