"""Seeded randomized sweep: distributed LU vs the scipy oracle across
random (M, N, v, grid) configurations — the broad-coverage net that
catches geometry/segmentation edge cases the hand-picked grids miss."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from conflux_tpu.geometry import Grid3, LUGeometry
from conflux_tpu.lu.distributed import lu_factor_distributed
from conflux_tpu.parallel.mesh import make_mesh
from conflux_tpu.validation import lu_residual, residual_bound


GRID_POOL = [(1, 1, 1), (2, 1, 1), (1, 2, 1), (2, 2, 1), (2, 2, 2),
             (4, 2, 1), (2, 4, 1), (1, 1, 2), (4, 1, 2)]


@pytest.mark.slow
def test_randomized_configs_against_oracle():
    rng = np.random.default_rng(2026)
    for trial in range(12):
        grid = Grid3(*GRID_POOL[rng.integers(len(GRID_POOL))])
        v = int(rng.choice([4, 8, 16]))
        # ragged, rectangular, and tiny extents all allowed
        M = int(rng.integers(v, 6 * v)) * max(1, grid.Px // 2)
        N = int(rng.integers(v, 6 * v)) * max(1, grid.Py // 2)
        geom = LUGeometry.create(M, N, v, grid)
        mesh = make_mesh(grid, devices=jax.devices()[: grid.P])
        A = (rng.standard_normal((geom.M, geom.N))
             .astype(np.float32))
        A[:, : min(geom.M, geom.N)] += 2 * np.eye(
            geom.M, min(geom.M, geom.N), dtype=np.float32)
        out, perm = lu_factor_distributed(
            jnp.asarray(geom.scatter(A)), geom, mesh,
            lookahead=bool(rng.integers(2)))
        LUp = geom.gather(np.asarray(out))
        res = lu_residual(A.astype(np.float64), LUp, np.asarray(perm))
        bound = residual_bound(max(geom.M, geom.N), np.float32)
        assert res < bound, (trial, grid, v, M, N, res, bound)


@pytest.mark.slow
def test_randomized_cholesky_configs():
    from conflux_tpu.cholesky.distributed import cholesky_factor_distributed
    from conflux_tpu.geometry import CholeskyGeometry
    from conflux_tpu.validation import cholesky_residual, make_spd_matrix

    rng = np.random.default_rng(777)
    padded_trials = 0
    for trial in range(8):
        grid = Grid3(*GRID_POOL[rng.integers(len(GRID_POOL))])
        v = int(rng.choice([4, 8, 16]))
        # ragged draw: S is built at the DRAWN size and identity-padded to
        # the grid multiple (same recipe as cholesky_distributed_host, which
        # this bypasses to pass lookahead), so non-divisible sizes test the
        # padded-geometry factorization instead of silently rounding the
        # trial up to geom.N
        N = int(rng.integers(2 * v, 8 * v))
        geom = CholeskyGeometry.create(N, v, grid)
        mesh = make_mesh(grid, devices=jax.devices()[: grid.P])
        S = make_spd_matrix(N, seed=int(rng.integers(2**31)),
                            dtype=np.float32)
        Sp = np.eye(geom.N, dtype=np.float32)
        Sp[:N, :N] = S
        padded_trials += geom.N != N
        out = cholesky_factor_distributed(
            jnp.asarray(geom.scatter(Sp)), geom, mesh,
            lookahead=bool(rng.integers(2)))
        L = np.tril(geom.gather(np.asarray(out)))
        res = cholesky_residual(Sp.astype(np.float64), L)
        bound = residual_bound(geom.N, np.float32)
        assert res < bound, (trial, grid, v, N, res, bound)
    assert padded_trials, "no trial exercised the padding path"


@pytest.mark.slow
def test_randomized_qr_configs():
    """Random (M, N, v, grid) draws through the full block-cyclic QR,
    checked against the positive-diagonal-unique LAPACK factorization."""
    from conflux_tpu.qr.distributed import qr_blocked_distributed_host

    rng = np.random.default_rng(555)
    for trial in range(6):
        grid = Grid3(*GRID_POOL[rng.integers(len(GRID_POOL))])
        v = int(rng.choice([4, 8]))
        # exact grid multiples (no identity-padding for QR); M >= N
        N = int(rng.integers(1, 4)) * v * grid.Py
        # M >= N by construction, rounded up to a whole x-tile multiple
        M = -(-(N + int(rng.integers(0, 3)) * v * grid.Px)
              // (v * grid.Px)) * v * grid.Px
        A = rng.standard_normal((M, N))
        Q, R, _ = qr_blocked_distributed_host(A, grid, v)
        Qr, Rr = np.linalg.qr(A)
        s = np.sign(np.diag(Rr)); s[s == 0] = 1
        np.testing.assert_allclose(
            R, Rr * s[:, None], atol=1e-9 * max(1.0, np.abs(Rr).max()),
            err_msg=str((trial, grid, v, M, N)))
        orth = np.linalg.norm(Q.T @ Q - np.eye(N))
        assert orth < 1e-12 * N + 1e-13, (trial, grid, orth)
        np.testing.assert_allclose(Q @ R, A, atol=1e-10 * max(1.0, np.abs(A).max()),
                                   err_msg=str((trial, grid, v, M, N)))
