"""Mesh-sharded serve fleet (ISSUE 9): per-device lanes behind one
admission front.

- Placement is DETERMINISTIC: `place_session` is a pure function of
  (sid, device list), so equal sids land on equal devices across engine
  restarts (and across checkpoint/restore, which persists sids).
- A mixed solve/factor/update trace through a multi-lane engine is
  BITWISE the single-lane engine's answers: every CPU host device runs
  the same executable code, and lanes never change the staged bytes.
- Fault domains are lanes: a poisoned request fails alone while
  co-temporal requests on other lanes answer; an injected lane-thread
  death fails only that lane's pending work, the watchdog respawns the
  lane, and the engine keeps serving.
- `prewarm` warms EVERY lane (per-device executables) and dedupes
  (plan, bucket, device) work; steady-state traffic then observes zero
  XLA compiles on every lane (`profiler.compile_count`).
- Per-lane telemetry surfaces in `engine.stats()['lanes']` and merges
  into `profiler.serve_stats()['engine']`; `counters()` stays
  sort-free.
- `MeshPlanUnsupported` replaces the ad-hoc ValueErrors (structured,
  counted in serve_stats()['health']).
- `ResidentSet` per-device caps bound each device separately: a hot
  device's pressure evicts ITS residents, not the fleet's.
"""

import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from conflux_tpu import profiler, resilience, serve
from conflux_tpu.engine import (
    EngineClosed,
    ServeEngine,
    place_session,
)
from conflux_tpu.resilience import (
    FaultPlan,
    FaultSpec,
    HealthPolicy,
    MeshPlanUnsupported,
    RhsNonFinite,
)

N, V = 32, 16


def _mk(seed, n=N):
    rng = np.random.default_rng(seed)
    return (rng.standard_normal((n, n)) / np.sqrt(n)
            + 2.0 * np.eye(n)).astype(np.float32)


def _rhs(seed, w=1):
    b = np.random.default_rng(seed).standard_normal(
        (N, w) if w > 1 else (N,))
    return b.astype(np.float32)


# --------------------------------------------------------------------- #
# placement
# --------------------------------------------------------------------- #


def test_place_session_deterministic_across_engines():
    devs = jax.devices()
    assert len(devs) >= 8, "conftest pins an 8-device CPU mesh"
    # pure function of (sid, device list): equal across calls and
    # across engine instances ("restarts")
    for sid in ("user-1", "user-2", 12345, "a-long-session-id"):
        assert place_session(sid, devs) is place_session(sid, devs)
    eng1 = ServeEngine(max_batch_delay=0.0, lanes="auto")
    d1 = {sid: eng1.placement(sid) for sid in map(str, range(32))}
    eng1.close(timeout=60)
    eng2 = ServeEngine(max_batch_delay=0.0, lanes="auto")
    d2 = {sid: eng2.placement(sid) for sid in map(str, range(32))}
    eng2.close(timeout=60)
    assert d1 == d2
    # and sids actually spread over more than one device
    assert len({str(d) for d in d1.values()}) > 1


def test_place_session_remap_only_removed_device():
    """The rendezvous (HRW) property the multi-host fabric rides
    (ISSUE 13): shrinking the device set remaps ONLY the sids the
    removed device owned — every other placement is bit-identical.
    Regression guard against mod-N style placement, where one removal
    reshuffles nearly every sid."""
    devs = jax.devices()
    assert len(devs) >= 8, "conftest pins an 8-device CPU mesh"
    sids = [f"user-{i}" for i in range(200)]
    before = {sid: place_session(sid, devs) for sid in sids}
    gone = devs[3]
    survivors = [d for d in devs if d is not gone]
    after = {sid: place_session(sid, survivors) for sid in sids}
    moved = [sid for sid in sids if after[sid] is not before[sid]]
    # exactly the removed device's sids moved, nothing else
    assert moved == [sid for sid in sids if before[sid] is gone]
    for sid in moved:
        assert after[sid] in survivors
    # the hash spreads: the removed device owned a nontrivial share
    assert 0 < len(moved) < len(sids)


def test_sid_pinned_factor_and_resubmit_route_to_same_lane():
    serve.clear_plans()
    plan = serve.FactorPlan.create((N, N), jnp.float32, v=V)
    with ServeEngine(max_batch_delay=0.0, lanes="auto") as eng:
        want = eng.placement("user-42")
        s = eng.factor(plan, _mk(1), sid="user-42", timeout=60)
        assert s.device is want and s.sid == "user-42"
        # solve routes by the pinned device; answer matches direct
        b = _rhs(2, 2)
        np.testing.assert_array_equal(
            np.asarray(eng.solve(s, b, timeout=60)),
            np.asarray(s.solve(b)))
    # a fresh engine with the same devices pins user-42 identically
    with ServeEngine(max_batch_delay=0.0, lanes="auto") as eng2:
        assert eng2.placement("user-42") is want


def test_explicit_device_override_wins():
    serve.clear_plans()
    plan = serve.FactorPlan.create((N, N), jnp.float32, v=V)
    dev = jax.devices()[5]
    s = plan.factor(jnp.asarray(_mk(3)), device=dev, sid="pinme")
    assert s.device is dev
    assert all(list(leaf.devices())[0] is dev for leaf in s._factors)
    with ServeEngine(max_batch_delay=0.0, lanes="auto") as eng:
        s2 = eng.factor(plan, _mk(4), device=dev, timeout=60)
        assert s2.device is dev
        b = _rhs(5)
        np.testing.assert_array_equal(
            np.asarray(eng.solve(s2, b, timeout=60)),
            np.asarray(s2.solve(b)))


# --------------------------------------------------------------------- #
# bitwise parity: fleet vs single lane
# --------------------------------------------------------------------- #


def test_fleet_bitwise_parity_mixed_trace():
    """A mixed solve/factor/update trace through an 8-lane engine gives
    BITWISE the single-lane engine's answers (same staged bytes, same
    executables — CPU host devices agree bit-for-bit)."""
    serve.clear_plans()
    plan = serve.FactorPlan.create((N, N), jnp.float32, v=V)
    rng = np.random.default_rng(11)
    mats = [_mk(100 + i) for i in range(6)]
    widths = [1, 2, 1, 4, 1, 2]
    answers = {}
    for lanes in (1, "auto"):
        eng = ServeEngine(max_batch_delay=0.01, lanes=lanes)
        # cold-start through the factor lane, sid-pinned so the fleet
        # leg spreads deterministically
        sessions = [eng.factor(plan, mats[i], sid=f"u{i}", timeout=60)
                    for i in range(6)]
        # drift two sessions, then solve a mixed-width trace
        for i in (1, 4):
            U = rng.standard_normal((N, 2)).astype(np.float32) * 0.01
            Vv = rng.standard_normal((N, 2)).astype(np.float32) * 0.01
            sessions[i].update(U, Vv)
        futs = [eng.submit(sessions[i], _rhs(200 + i, widths[i]))
                for i in range(6)]
        out = [np.asarray(f.result(timeout=60)) for f in futs]
        eng.close(timeout=60)
        answers[lanes] = out
        rng = np.random.default_rng(11)  # identical drift both legs
    for a, b in zip(answers[1], answers["auto"]):
        np.testing.assert_array_equal(a, b)


# --------------------------------------------------------------------- #
# fault domains: lanes
# --------------------------------------------------------------------- #


def test_poisoned_request_fails_alone_across_lanes():
    serve.clear_plans()
    plan = serve.FactorPlan.create((N, N), jnp.float32, v=V)
    faults = FaultPlan([FaultSpec("staging", "nan", count=1)])
    with ServeEngine(max_batch_delay=0.02, lanes="auto",
                     health=HealthPolicy(check_output=False),
                     fault_plan=faults) as eng:
        sessions = [eng.factor(plan, _mk(20 + i), sid=f"p{i}",
                               timeout=60) for i in range(4)]
        bs = [_rhs(300 + i) for i in range(8)]
        futs = [eng.submit(sessions[i % 4], bs[i]) for i in range(8)]
        failed, ok = [], []
        for i, f in enumerate(futs):
            try:
                ok.append((i, np.asarray(f.result(timeout=60))))
            except RhsNonFinite:
                failed.append(i)
        assert len(failed) == 1, "exactly the poisoned request fails"
        for i, x in ok:
            np.testing.assert_array_equal(
                x, np.asarray(sessions[i % 4].solve(bs[i])))


def test_lane_thread_death_fails_only_its_lane_then_revives():
    """An injected kill on one lane's dispatcher fails only that lane's
    pending work; the watchdog respawns the lane's workers and BOTH the
    victim lane and the rest of the fleet keep serving (the engine
    never closes)."""
    serve.clear_plans()
    plan = serve.FactorPlan.create((N, N), jnp.float32, v=V)
    faults = FaultPlan([FaultSpec("dispatch", "kill", count=1)])
    eng = ServeEngine(max_batch_delay=0.0, lanes="auto",
                      watchdog_interval=0.05, fault_plan=faults)
    try:
        # open OUTSIDE the engine (plan.factor, explicit devices): the
        # kill budget must be spent by the victim lane's solve
        # dispatch, not a cold-start round
        sa = plan.factor(jnp.asarray(_mk(31)), device=eng.devices[0])
        sb = plan.factor(jnp.asarray(_mk(32)), device=eng.devices[1])
        lane_a, lane_b = eng.lanes[0], eng.lanes[1]
        # the kill fires on lane_a's dispatcher (only it dispatches)
        f_bad = eng.submit(sa, _rhs(40))
        with pytest.raises(EngineClosed, match="lane"):
            f_bad.result(timeout=30)
        # other lanes never noticed
        b = _rhs(41)
        np.testing.assert_array_equal(
            np.asarray(eng.solve(sb, b, timeout=60)),
            np.asarray(sb.solve(b)))
        # the victim lane revives (watchdog poll) and serves again
        deadline = time.time() + 30
        while time.time() < deadline:
            if lane_a._dispatcher.is_alive() and lane_a.revives >= 1:
                break
            time.sleep(0.02)
        assert lane_a.revives >= 1 and lane_a._dispatcher.is_alive()
        b2 = _rhs(42)
        np.testing.assert_array_equal(
            np.asarray(eng.solve(sa, b2, timeout=60)),
            np.asarray(sa.solve(b2)))
        st = eng.stats()
        assert [ln for ln in st["lanes"] if ln["revives"]], \
            "stats must surface the lane revival"
        h = profiler.serve_stats()["health"]
        assert h["lane_revives"] >= 1 and h["watchdog_trips"] >= 1
    finally:
        eng.close(timeout=60)


# --------------------------------------------------------------------- #
# prewarm: every lane, deduped, zero compiles after
# --------------------------------------------------------------------- #


def test_prewarm_warms_every_lane_and_dedupes():
    serve.clear_plans()
    plan = serve.FactorPlan.create((N, N), jnp.float32, v=V)
    devs = jax.devices()[:3]
    with ServeEngine(max_batch_delay=0.01, devices=devs,
                     max_coalesce_width=4) as eng:
        sessions = [eng.factor(plan, _mk(50 + i), device=devs[i],
                               timeout=60) for i in range(3)]
        eng.prewarm(sessions[0], widths=(1, 2, 4), factor_batches=(2,))
        for wb in (1, 2, 4):
            for d in devs:
                assert plan.device_warm("solve", wb,
                                        (d.platform, d.id))
        # dedupe: a second prewarm (same plan, another session) skips
        # every (kind, bucket, device) — zero fresh compiles
        c0 = profiler.compile_count()
        eng.prewarm(sessions[1], widths=(1, 2, 4), factor_batches=(2,))
        assert profiler.compile_count() == c0
        # steady state: traffic on every lane compiles nothing
        traces0 = dict(plan.trace_counts)
        futs = [eng.submit(sessions[i % 3], _rhs(400 + i, 1 + i % 2))
                for i in range(12)]
        for f in futs:
            f.result(timeout=60)
        assert profiler.compile_count() == c0, \
            "a lane paid a compile after prewarm"
        assert plan.trace_counts == traces0
        st = eng.stats()
        active = [ln for ln in st["lanes"] if ln["batches"]]
        assert len(active) == 3, "every lane dispatched"


# --------------------------------------------------------------------- #
# telemetry
# --------------------------------------------------------------------- #


def test_lane_telemetry_in_stats_counters_and_serve_stats():
    serve.clear_plans()
    plan = serve.FactorPlan.create((N, N), jnp.float32, v=V)
    devs = jax.devices()[:2]
    with ServeEngine(max_batch_delay=0.005, devices=devs) as eng:
        ss = [eng.factor(plan, _mk(60 + i), device=devs[i], timeout=60)
              for i in range(2)]
        for i in range(6):
            eng.solve(ss[i % 2], _rhs(500 + i), timeout=60)
        cnt = eng.counters()
        rows = cnt["lanes"]
        assert [r["lane"] for r in rows] == [0, 1]
        assert all("latency_p50_ms" not in r for r in rows), \
            "counters() must stay sort/percentile-free"
        assert sum(r["batches"] for r in rows) == cnt["batches"]
        st = eng.stats()
        for r in st["lanes"]:
            assert r["batches"] >= 1 and 0.0 <= r["occupancy"] <= 1.0
            assert r["coalesced_mean"] >= 1.0
            assert r["device"] is not None
        merged = profiler.serve_stats()["engine"]
        assert merged["lanes"] >= 2
        assert merged["lane_batches_max"] >= merged["lane_batches_min"]


def test_set_knobs_lane_scope():
    with ServeEngine(max_batch_delay=0.002,
                     devices=jax.devices()[:2]) as eng:
        k = eng.set_knobs(lane=1, max_batch_delay=0.01)
        assert k["lane_delays"] == {1: 0.01}
        assert eng.lanes[1].delay == 0.01
        assert eng.lanes[0].delay == 0.002  # untouched
        assert eng.max_batch_delay == 0.002
        with pytest.raises(ValueError, match="out of range"):
            eng.set_knobs(lane=7, max_batch_delay=0.01)
        with pytest.raises(ValueError, match="exactly one knob"):
            eng.set_knobs(lane=0, max_batch_delay=0.01, max_pending=64)
        with pytest.raises(ValueError, match="exactly one knob"):
            eng.set_knobs(lane=0)


def test_controller_tunes_lane_delay_independently():
    from conflux_tpu.control import AdaptiveController

    serve.clear_plans()
    eng = ServeEngine(max_batch_delay=0.001, devices=jax.devices()[:2])
    try:
        ctl = AdaptiveController(interval=60.0).attach(eng)
        d = AdaptiveController.blank_delta()
        # lane 1 under-coalesces with a building queue for two windows
        rows = [
            {"lane": 0, "batches": 10, "coalesced_requests": 40,
             "queue_depth": 0, "delay": 0.001, "dead": False},
            {"lane": 1, "batches": 10, "coalesced_requests": 10,
             "queue_depth": 4, "delay": 0.001, "dead": False},
        ]
        base = eng.counters()

        def counters(rows=rows):
            out = dict(base)
            out["lanes"] = [dict(r) for r in rows]
            return out

        eng.counters = counters  # scripted per-lane telemetry
        ctl._decide_lane_delays(eng, d, d["engine"])  # window 1: baseline
        rows[0]["batches"] = 20
        rows[0]["coalesced_requests"] = 80
        rows[1]["batches"] = 20
        rows[1]["coalesced_requests"] = 20
        ctl._decide_lane_delays(eng, d, d["engine"])  # pressure 1
        rows[0]["batches"] = 30
        rows[0]["coalesced_requests"] = 120
        rows[1]["batches"] = 30
        rows[1]["coalesced_requests"] = 30
        ctl._decide_lane_delays(eng, d, d["engine"])  # pressure 2: widen
        k = eng.knobs()
        assert 1 in k["lane_delays"] and k["lane_delays"][1] > 0.001
        assert 0 not in k["lane_delays"], "lane 0 stays on the default"
    finally:
        del eng.counters
        eng.close(timeout=60)


# --------------------------------------------------------------------- #
# structured mesh rejection: the genuine residue only (DESIGN §32)
# --------------------------------------------------------------------- #


def test_mesh_plan_unsupported_is_residue_only():
    """The factor lane now SERVES mesh plans; `MeshPlanUnsupported` is
    reserved for the genuine residue — migrating sharded state off its
    mesh. A 4-device mesh leaves devices 4..7 as provable outsiders."""
    serve.clear_plans()
    mesh4 = jax.sharding.Mesh(
        np.asarray(jax.devices()[:4], dtype=object), ("b",))
    mplan = serve.FactorPlan.create((8, N, N), jnp.float32, v=V,
                                    mesh=mesh4)
    A = np.zeros((8, N, N), np.float32) + np.eye(N, dtype=np.float32)
    outside = jax.devices()[7]
    h0 = resilience.health_stats().get("mesh_plan_unsupported", 0)
    with ServeEngine(max_batch_delay=0.0) as eng:
        # the demoted site: submit_factor serves the mesh plan
        s = eng.factor(mplan, A)
        assert s.plan is mplan and s.plan.mesh is not None
        # residue: an explicit pin OUTSIDE the plan's mesh
        with pytest.raises(MeshPlanUnsupported) as ei:
            eng.submit_factor(mplan, A, device=outside)
        assert isinstance(ei.value, ValueError)  # legacy callers OK
        assert ei.value.surface == "factor_lane"
        # an IN-mesh pin is a placement no-op, not an error
        assert eng.factor(mplan, A, device=jax.devices()[0]).plan \
            is mplan
    with pytest.raises(MeshPlanUnsupported) as ei:
        mplan.factor(A, device=outside)
    assert ei.value.surface == "factor"
    with pytest.raises(MeshPlanUnsupported) as ei:
        s.to_device(outside)
    assert ei.value.surface == "to_device"
    assert s.to_device(jax.devices()[1]) is s  # in-mesh: no-op
    h1 = resilience.health_stats()["mesh_plan_unsupported"]
    assert h1 >= h0 + 3
    assert "mesh_plan_unsupported" in profiler.serve_stats()["health"]


# --------------------------------------------------------------------- #
# tier: per-device caps
# --------------------------------------------------------------------- #


def test_tier_per_device_caps_isolate_hot_device():
    from conflux_tpu.tier import ResidentSet

    serve.clear_plans()
    plan = serve.FactorPlan.create((N, N), jnp.float32, v=V)
    d0, d1 = jax.devices()[0], jax.devices()[1]
    cold = [plan.factor(jnp.asarray(_mk(70 + i)), device=d0,
                        sid=f"c{i}") for i in range(2)]
    hot = [plan.factor(jnp.asarray(_mk(80 + i)), device=d1,
                       sid=f"h{i}") for i in range(5)]
    rs = ResidentSet(max_sessions_per_device=2, evict_batch=1)
    rs.adopt(*cold)
    rs.adopt(*hot)
    # the hot device's pressure spilled ITS overflow only
    assert all(s.tier == "device" for s in cold), \
        "cold device residents must not pay for the hot device"
    resident_hot = [s for s in hot if s.tier == "device"]
    assert len(resident_hot) <= 2
    per_dev = rs.stats()["per_device"]
    for _dk, g in per_dev.items():
        assert g["sessions"] <= 2
    # revival on the hot device still bounded, cold side untouched
    spilled = [s for s in hot if s.tier != "device"]
    x = np.asarray(spilled[0].solve(_rhs(90)))  # transparent revival
    assert np.isfinite(x).all()
    assert all(s.tier == "device" for s in cold)
    per_dev = rs.stats()["per_device"]
    for _dk, g in per_dev.items():
        assert g["sessions"] <= 2


def test_checkpoint_restores_sid_for_deterministic_replacement(tmp_path):
    from conflux_tpu import tier

    serve.clear_plans()
    plan = serve.FactorPlan.create((N, N), jnp.float32, v=V)
    s = plan.factor(jnp.asarray(_mk(95)), sid="user-7")
    tier.save_fleet(str(tmp_path / "ck"), [s])
    (r,) = tier.load_fleet(str(tmp_path / "ck"))
    assert r.sid == "user-7"
    devs = jax.devices()
    assert place_session(r.sid, devs) is place_session("user-7", devs)


# --------------------------------------------------------------------- #
# cold-start pool: load balancing + close drains it
# --------------------------------------------------------------------- #


def test_pooled_cold_start_all_resolve_and_close_drains():
    serve.clear_plans()
    plan = serve.FactorPlan.create((N, N), jnp.float32, v=V)
    eng = ServeEngine(max_batch_delay=0.02, lanes="auto",
                      max_factor_batch=4)
    futs = [eng.submit_factor(plan, _mk(600 + i)) for i in range(10)]
    eng.close(timeout=120)  # close answers queued pool work
    sessions = [f.result(timeout=0) for f in futs]
    lane_devs = {str(d) for d in eng.devices}
    for i, s in enumerate(sessions):
        assert str(s.device) in lane_devs
        b = _rhs(700 + i)
        np.testing.assert_array_equal(np.asarray(s.solve(b)),
                                      np.asarray(s.solve(b)))
