"""Test harness config: simulate an 8-device mesh on CPU.

Mirrors the reference's strategy of testing multi-rank logic without a
cluster (its NumPy prototype simulated all ranks in one process,
`python/conflux.py:40`); here XLA's host-platform device-count flag gives us
8 real XLA devices on CPU so the very same `shard_map` code that runs on a
TPU pod runs in CI.

Note: the environment pre-imports jax (sitecustomize) with the TPU platform
selected, so plain env vars are too late — we must override via jax.config
before any backend initializes.
"""

import os

os.environ["XLA_FLAGS"] = (
    "--xla_force_host_platform_device_count=8 " + os.environ.get("XLA_FLAGS", "")
)

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)
