"""Direct coverage for the persistent-XLA-cache switch (conflux_tpu/cache.py)
and the plan cache's `clear_plans()` — previously exercised only
indirectly through the serve tests (ISSUE 2 satellite).

The module-level `_ENABLED_AT` latch is monkeypatched around each test so
ordering against the serve tests (which enable the real cache) does not
matter, and the live jax config is restored afterwards.
"""

import os

import pytest

import jax
import jax.numpy as jnp

from conflux_tpu import cache, serve


@pytest.fixture
def fresh_cache(monkeypatch, tmp_path):
    """Un-latch the enable switch and restore the jax cache config."""
    monkeypatch.setattr(cache, "_ENABLED_AT", None)
    before = jax.config.jax_compilation_cache_dir
    yield tmp_path
    jax.config.update("jax_compilation_cache_dir", before)


def test_default_cache_dir_env_override(monkeypatch):
    monkeypatch.setenv("CONFLUX_TPU_CACHE_DIR", "/tmp/conflux-cache-test")
    assert cache.default_cache_dir() == "/tmp/conflux-cache-test"
    monkeypatch.delenv("CONFLUX_TPU_CACHE_DIR")
    assert cache.default_cache_dir().endswith(
        os.path.join(".cache", "conflux_tpu", "xla"))


def test_enable_points_jax_at_directory(fresh_cache):
    target = str(fresh_cache / "xla")
    got = cache.enable_persistent_cache(target)
    assert got == target
    assert os.path.isdir(target), "cache dir must be created on demand"
    assert jax.config.jax_compilation_cache_dir == target
    # min-entry-size filter zeroed: admission is time-thresholded only
    assert jax.config.jax_persistent_cache_min_entry_size_bytes == 0
    assert cache.cache_enabled()


def test_enable_is_idempotent_first_call_wins(fresh_cache):
    first = cache.enable_persistent_cache(str(fresh_cache / "a"))
    second = cache.enable_persistent_cache(str(fresh_cache / "b"))
    assert second == first, "a live cache must not be re-pointed"
    assert jax.config.jax_compilation_cache_dir == first


def test_enable_degrades_to_noop_on_failure(fresh_cache, monkeypatch):
    """A backend without persistent-cache support costs compile time,
    never an exception."""
    def boom(*a, **k):
        raise RuntimeError("unsupported")

    # context-scoped: the patch must be gone before fixture teardown
    # restores the real jax config
    with monkeypatch.context() as m:
        m.setattr(jax.config, "update", boom)
        assert cache.enable_persistent_cache(str(fresh_cache / "c")) is None
    assert not cache.cache_enabled()


def test_env_var_resolves_when_no_path_given(fresh_cache, monkeypatch):
    target = str(fresh_cache / "from-env")
    monkeypatch.setenv("CONFLUX_TPU_CACHE_DIR", target)
    assert cache.enable_persistent_cache() == target


def test_clear_plans_drops_cached_plans():
    serve.clear_plans()
    plan = serve.FactorPlan.create((16, 16), jnp.float32, v=16,
                                   persistent_cache=False)
    assert serve.FactorPlan.create((16, 16), jnp.float32, v=16,
                                   persistent_cache=False) is plan
    serve.clear_plans()
    fresh = serve.FactorPlan.create((16, 16), jnp.float32, v=16,
                                    persistent_cache=False)
    assert fresh is not plan, "clear_plans left a stale plan behind"
    serve.clear_plans()
