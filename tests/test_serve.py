"""Serving-layer tests: batched engine + plan cache + solve sessions.

The acceptance contracts of ISSUE 1, asserted rather than trusted:
batched results match the one-shot per-matrix path element-for-element
(residual oracle), `SolveSession` reuse triggers zero refactorizations and
zero recompiles after the first call (the plans' trace-count hook — a
Python side effect in the traced function body fires once per TRACE, not
per call), and the batch shards across the simulated 8-device CPU mesh.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from conflux_tpu import batched, serve
from conflux_tpu.solvers import solve


B, N, V = 8, 32, 16


def _systems(b=B, n=N, seed=0, spd=False):
    rng = np.random.default_rng(seed)
    A = (rng.standard_normal((b, n, n)) / np.sqrt(n)
         + 2.0 * np.eye(n)).astype(np.float32)
    if spd:
        A = (np.einsum("bij,bkj->bik", A, A)
             + np.eye(n, dtype=np.float32)).astype(np.float32)
    rhs = rng.standard_normal((b, n)).astype(np.float32)
    return A, rhs


def _residuals(A, x, b):
    r = np.einsum("bij,bj->bi", A.astype(np.float64),
                  np.asarray(x, np.float64)) - b.astype(np.float64)
    return (np.linalg.norm(r, axis=1)
            / np.linalg.norm(b.astype(np.float64), axis=1))


def _oracle_bars(A, b, **kw):
    """Per-element residuals of the one-shot `solvers.solve` loop — the
    bar every batched/served result is held to."""
    xs = np.stack([
        np.asarray(solve(jnp.asarray(A[i]), jnp.asarray(b[i]), v=V, **kw))
        for i in range(A.shape[0])])
    return _residuals(A, xs, b)


# --------------------------------------------------------------------------- #
# batched engine
# --------------------------------------------------------------------------- #


@pytest.mark.parametrize("use_mesh", [False, True])
def test_batched_lu_matches_loop_oracle(use_mesh):
    A, b = _systems()
    mesh = batched.batch_mesh() if use_mesh else None
    x = batched.solve_batched(A, b, v=V, mesh=mesh)
    bars = _oracle_bars(A, b)
    res = _residuals(A, x, b)
    assert (res <= np.maximum(4 * bars, 1e-6)).all(), (res, bars)


def test_batched_factor_then_solve_roundtrip():
    A, b = _systems(seed=3)
    LU, perm = batched.lu_factor_batched(A, v=V)
    x = batched.lu_solve_batched(LU, perm, b)
    res = _residuals(A, x, b)
    assert (res < 1e-5).all(), res
    # multi-RHS form
    k = 3
    bk = np.stack([b] * k, axis=2)
    xk = batched.lu_solve_batched(LU, perm, bk)
    assert xk.shape == (B, N, k)
    np.testing.assert_allclose(np.asarray(xk[:, :, 0]), np.asarray(x),
                               rtol=1e-5, atol=1e-6)


def test_batched_cholesky_matches_loop_oracle():
    A, b = _systems(spd=True, seed=5)
    L = batched.cholesky_factor_batched(A, v=V)
    x = batched.cholesky_solve_batched(L, b)
    bars = _oracle_bars(A, b, spd=True)
    res = _residuals(A, x, b)
    assert (res <= np.maximum(4 * bars, 1e-6)).all(), (res, bars)


def test_batched_ragged_batch_pads_and_slices():
    # 5 systems on an 8-device mesh: padded internally, results exact
    A, b = _systems(b=5, seed=7)
    mesh = batched.batch_mesh()
    x = batched.solve_batched(A, b, v=V, mesh=mesh)
    assert x.shape == (5, N)
    assert (_residuals(A, x, b) < 1e-5).all()


def test_batched_rejects_bad_shapes():
    A, b = _systems()
    with pytest.raises(ValueError, match="batch of square"):
        batched.lu_factor_batched(A[0], v=V)
    with pytest.raises(ValueError, match="multiple of tile size"):
        batched.lu_factor_batched(A, v=V + 1)
    with pytest.raises(ValueError, match="rhs"):
        batched.solve_batched(A, b[:, :-1], v=V)


def test_batch_sharding_on_cpu_mesh():
    """The batch axis really shards over the simulated 8-device mesh."""
    assert jax.device_count() == 8, "conftest sets 8 simulated devices"
    A, b = _systems()
    mesh = batched.batch_mesh()
    LU, perm = batched.lu_factor_batched(A, v=V, mesh=mesh)
    assert len(LU.sharding.device_set) == 8
    shard_batches = sorted(s.data.shape[0] for s in LU.addressable_shards)
    assert shard_batches == [1] * 8  # B=8 split one system per device
    # and the sharded result matches the unsharded one bitwise (same
    # program, partitioned only over the independent batch axis)
    LU0, perm0 = batched.lu_factor_batched(A, v=V)
    np.testing.assert_array_equal(np.asarray(LU), np.asarray(LU0))
    np.testing.assert_array_equal(np.asarray(perm), np.asarray(perm0))


# --------------------------------------------------------------------------- #
# plan cache + sessions
# --------------------------------------------------------------------------- #


def test_plan_cache_hits_and_zero_recompiles():
    serve.clear_plans()
    A, b = _systems(seed=11)
    mesh = batched.batch_mesh()
    plan = serve.FactorPlan.create((B, N, N), jnp.float32, v=V, mesh=mesh)
    assert serve.FactorPlan.create((B, N, N), jnp.float32, v=V,
                                   mesh=mesh) is plan, "plan cache missed"
    session = plan.factor(jnp.asarray(A))
    session.solve(jnp.asarray(b))
    assert plan.trace_counts == {"factor": 1, "solve": 1}
    # the serving hot path: more factors, more RHS batches — no retrace
    rng = np.random.default_rng(0)
    for i in range(3):
        session = plan.factor(jnp.asarray(A))
        for _ in range(2):
            session.solve(jnp.asarray(
                rng.standard_normal((B, N)).astype(np.float32)))
    assert plan.trace_counts == {"factor": 1, "solve": 1}, \
        "repeat traffic recompiled"
    # a second identical create still compiles nothing
    plan2 = serve.FactorPlan.create((B, N, N), jnp.float32, v=V, mesh=mesh)
    plan2.factor(jnp.asarray(A)).solve(jnp.asarray(b))
    assert plan2.trace_counts == {"factor": 1, "solve": 1}
    # different knobs -> different plan
    assert serve.FactorPlan.create((B, N, N), jnp.float32, v=V, mesh=mesh,
                                   refine=1) is not plan


def test_session_zero_refactorizations():
    serve.clear_plans()
    A, b = _systems(seed=13)
    plan = serve.FactorPlan.create((B, N, N), jnp.float32, v=V)
    session = plan.factor(jnp.asarray(A))
    rng = np.random.default_rng(1)
    for _ in range(5):
        session.solve(jnp.asarray(
            rng.standard_normal((B, N)).astype(np.float32)))
    assert session.factorizations == 1
    assert session.solves == 5
    assert plan.trace_counts["factor"] == 1


@pytest.mark.parametrize("use_mesh", [False, True])
def test_session_solutions_match_oracle(use_mesh):
    serve.clear_plans()
    A, b = _systems(seed=17)
    mesh = batched.batch_mesh() if use_mesh else None
    plan = serve.FactorPlan.create((B, N, N), jnp.float32, v=V, mesh=mesh)
    session = plan.factor(jnp.asarray(A))
    bars = _oracle_bars(A, b)
    res = _residuals(A, np.asarray(session.solve(jnp.asarray(b))), b)
    assert (res <= np.maximum(4 * bars, 1e-6)).all(), (res, bars)
    # a second RHS batch through the SAME resident factors stays correct
    b2 = np.asarray(b[::-1])
    res2 = _residuals(A, np.asarray(session.solve(jnp.asarray(b2))), b2)
    assert (res2 <= np.maximum(4 * _oracle_bars(A, b2), 1e-6)).all()


def test_session_bf16_ir_path():
    """The HPL-MxP serving mode: bf16 factors + fused IR sweeps reach the
    one-shot bf16+IR path's bars, and reuse still never refactors."""
    serve.clear_plans()
    A, b = _systems(seed=19)
    plan = serve.FactorPlan.create((B, N, N), jnp.float32, v=V,
                                   factor_dtype=jnp.bfloat16, refine=3)
    session = plan.factor(jnp.asarray(A))
    x = session.solve(jnp.asarray(b))
    bars = _oracle_bars(A, b, factor_dtype=jnp.bfloat16, refine=3)
    res = _residuals(A, np.asarray(x), b)
    assert (res <= np.maximum(4 * bars, 1e-6)).all(), (res, bars)
    session.solve(jnp.asarray(b))
    assert session.factorizations == 1
    assert plan.trace_counts == {"factor": 1, "solve": 1}


def test_session_spd_and_trsm_substitution():
    serve.clear_plans()
    A, b = _systems(spd=True, seed=23)
    for substitution in ("inv", "trsm"):
        plan = serve.FactorPlan.create((B, N, N), jnp.float32, v=V,
                                       spd=True, substitution=substitution)
        x = plan.factor(jnp.asarray(A)).solve(jnp.asarray(b))
        res = _residuals(A, np.asarray(x), b)
        bars = _oracle_bars(A, b, spd=True)
        assert (res <= np.maximum(4 * bars, 1e-6)).all(), (substitution, res)


def test_single_system_plan_multi_rhs():
    serve.clear_plans()
    A, b = _systems(seed=29)
    plan = serve.FactorPlan.create((N, N), jnp.float32, v=V)
    session = plan.factor(jnp.asarray(A[0]))
    x1 = session.solve(jnp.asarray(b[0]))
    assert x1.shape == (N,)
    xk = session.solve(jnp.asarray(
        np.stack([b[0]] * 2, axis=1)))
    assert xk.shape == (N, 2)
    np.testing.assert_allclose(np.asarray(xk[:, 0]), np.asarray(x1),
                               rtol=1e-5, atol=1e-6)
    r = _residuals(A[:1], np.asarray(x1)[None], b[:1])
    assert (r < 1e-5).all()


def test_solve_rhs_bucketing_bounds_recompiles():
    """A traffic mix of RHS widths compiles O(log) solve programs: widths
    round up to power-of-two buckets (pad + slice), and the bucket
    contract is enforced at the program-cache boundary."""
    serve.clear_plans()
    A, b = _systems(seed=31)
    plan = serve.FactorPlan.create((N, N), jnp.float32, v=V)
    session = plan.factor(jnp.asarray(A[0]))
    rng = np.random.default_rng(31)
    widths = [1, 2, 3, 5, 8, 7, 4, 6, 1, 3]
    for w in widths:
        bw = rng.standard_normal((N, w)).astype(np.float32)
        x = session.solve(jnp.asarray(bw))
        assert x.shape == (N, w), "bucket padding leaked into the result"
        r = _residuals(np.repeat(A[:1], w, 0), np.asarray(x).T, bw.T)
        assert (r < 1e-5).all()
    buckets = {1, 2, 4, 8}
    assert plan.trace_counts["solve"] == len(buckets), \
        f"width mix {sorted(set(widths))} should compile {len(buckets)} " \
        f"bucketed programs, traced {plan.trace_counts['solve']}"
    assert set(plan._solve_cache) == buckets
    # padded-bucket answers are bitwise the unpadded ones (columns are
    # independent through substitution, GEMM, and IR alike)
    b3 = rng.standard_normal((N, 3)).astype(np.float32)
    x3 = np.asarray(session.solve(jnp.asarray(b3)))
    x4 = np.asarray(session.solve(jnp.asarray(
        np.pad(b3, ((0, 0), (0, 1))))))
    np.testing.assert_array_equal(x3, x4[:, :3])
    # the contract is enforced, not just followed
    with pytest.raises(AssertionError, match="power-of-two"):
        # conflint: disable=CFX-RECOMPILE asserting the bucket contract rejects 3
        plan._solve_fn(3)


def test_serve_phase_counters():
    """profiler.serve_stats() sees factor/solve/update/refactor counts
    and wall time without the caller instrumenting anything."""
    from conflux_tpu import profiler
    from conflux_tpu.update import DriftPolicy

    serve.clear_plans()
    profiler.clear()
    A, b = _systems(seed=37)
    rng = np.random.default_rng(37)
    U = (rng.standard_normal((N, 2)) / np.sqrt(N)).astype(np.float32)
    plan = serve.FactorPlan.create((N, N), jnp.float32, v=V)
    session = plan.factor(jnp.asarray(A[0]))
    for _ in range(4):
        session.solve(jnp.asarray(b[0]))
    session.update(jnp.asarray(U), jnp.asarray(U))
    session.solve(jnp.asarray(b[0]))
    stats = profiler.serve_stats()
    assert stats["factor"]["count"] == 1
    assert stats["solve"]["count"] == 5
    assert stats["update"]["count"] == 1
    assert stats["refactor"]["count"] == 0
    assert stats["solves_per_factor"] == 5.0
    assert all(stats[ph]["wall_s"] >= 0.0 for ph in profiler.SERVE_PHASES)
    assert stats["factor"]["wall_s"] > 0.0
    # a policy-triggered refactor lands in its own phase
    session2 = plan.factor(jnp.asarray(A[0]),
                           policy=DriftPolicy(cond_limit=0.5))
    session2.update(jnp.asarray(U), jnp.asarray(U))
    stats = profiler.serve_stats()
    assert stats["refactor"]["count"] == 1
    # both update() calls count (including the one that triggered)
    assert stats["updates_per_refactor"] == 2.0
    profiler.clear()
    assert profiler.serve_stats()["factor"]["count"] == 0


def test_concurrent_callers_compile_each_bucket_once():
    """ISSUE 3 satellite: the per-plan memoized program caches are safe
    under concurrent engine workers — a thread pool hammering one plan's
    width mix compiles each bucket exactly once (one cached wrapper, one
    trace), instead of double-compiling and corrupting the trace
    counters."""
    import threading

    serve.clear_plans()
    A, _ = _systems(seed=41)
    plan = serve.FactorPlan.create((N, N), jnp.float32, v=V)
    session = plan.factor(jnp.asarray(A[0]))
    rng = np.random.default_rng(41)
    rhs = {w: jnp.asarray(rng.standard_normal((N, w)).astype(np.float32))
           for w in (1, 2, 3, 5, 7, 8)}
    results: dict = {}
    errors: list = []
    barrier = threading.Barrier(6)

    def worker(tid):
        try:
            barrier.wait()
            for w, b in rhs.items():
                results[(tid, w)] = np.asarray(session.solve(b))
            # the builder itself is also hammered directly: every thread
            # must get the SAME cached wrapper back
            results[(tid, "fn")] = plan._solve_fn(8)
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    threads = [threading.Thread(target=worker, args=(t,)) for t in range(6)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=300)
    assert not errors, errors
    buckets = {1, 2, 4, 8}
    assert set(plan._solve_cache) == buckets
    assert plan.trace_counts["solve"] == len(buckets), \
        f"concurrent callers traced {plan.trace_counts['solve']} solve " \
        f"programs for {len(buckets)} buckets"
    fns = {results[(t, 'fn')] for t in range(6)}
    assert len(fns) == 1, "threads built distinct wrappers for one bucket"
    # and every thread got the same (correct) answers
    for w in rhs:
        ref = results[(0, w)]
        assert (_residuals(np.repeat(A[:1], w, 0), ref.T,
                           np.asarray(rhs[w]).T) < 1e-5).all()
        for t in range(1, 6):
            np.testing.assert_array_equal(results[(t, w)], ref)


def test_plan_rejects_mismatched_inputs():
    serve.clear_plans()
    A, _ = _systems()
    plan = serve.FactorPlan.create((B, N, N), jnp.float32, v=V)
    with pytest.raises(ValueError, match="does not match the plan"):
        plan.factor(jnp.asarray(A[:4]))
    with pytest.raises(ValueError, match="does not match the plan"):
        plan.factor(jnp.asarray(A, jnp.float64))
    session = plan.factor(jnp.asarray(A))
    with pytest.raises(ValueError, match="session needs"):
        session.solve(jnp.zeros((B, N + 1), jnp.float32))
    with pytest.raises(ValueError, match="mesh only applies"):
        serve.FactorPlan.create((N, N), jnp.float32, v=V,
                                mesh=batched.batch_mesh())
