"""Worker for the two-process multihost TSQR test.

Two OS processes x 4 virtual CPU devices run `tsqr_distributed` over an
8-wide x axis spanning the process boundary — the (n, n) R all_gather
crosses the inter-process transport. Validation never materializes the
global matrix: each process checks ||Q_loc R - A_loc|| on its OWN
addressable shards, and orthogonality comes from the one-collective
Gram check G = psum_x(Q_loc^T Q_loc) == I.
"""

import sys

sys.path.insert(0, __import__("os").path.dirname(__file__))
import mh_common  # noqa: F401  (must precede jax backend init)

pid, nproc, port = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402
from jax.sharding import PartitionSpec as P  # noqa: E402

from conflux_tpu.geometry import Grid3  # noqa: E402
from conflux_tpu.parallel.mesh import (  # noqa: E402
    AXIS_X,
    distribute_shards,
    initialize_multihost,
    make_mesh,
)
from conflux_tpu.qr.distributed import tsqr_distributed  # noqa: E402

initialize_multihost(f"localhost:{port}", nproc, pid)
assert len(jax.devices()) == 8, jax.devices()

Px, Ml, n = 8, 32, 12
grid = Grid3(Px, 1, 1)
mesh = make_mesh(grid, devices=jax.devices()[:Px])


def local_rows(px, _py=None):
    # deterministic tall block from global row indices (no process ever
    # holds the (M, n) matrix)
    gi = px * Ml + np.arange(Ml)
    j = np.arange(n)
    blk = np.cos(0.23 * gi[:, None] + 0.71 * j[None, :]).astype(np.float32)
    blk[:, :] += (gi[:, None] == j[None, :])
    return blk


shards = distribute_shards(
    lambda px, py=None: local_rows(px), mesh, shape=(Px, Ml, n),
    dtype=np.float32, spec=P(AXIS_X, None, None))
Qs, R = tsqr_distributed(shards, mesh)

# per-process local reconstruction on addressable shards only
max_rec = 0.0
Rh = np.asarray(R)
for sh in Qs.addressable_shards:
    px = sh.index[0].start if sh.index[0].start is not None else 0
    q_loc = np.asarray(sh.data)[0]
    a_loc = local_rows(px)
    max_rec = max(max_rec, float(np.abs(q_loc @ Rh - a_loc).max()))

# gather-free orthogonality: one (n, n) psum over 'x'
gram = jax.jit(
    jax.shard_map(
        lambda q: jax.lax.psum(
            jnp.matmul(q[0].T, q[0],
                       precision=jax.lax.Precision.HIGHEST), AXIS_X),
        mesh=mesh, in_specs=P(AXIS_X, None, None), out_specs=P()),
)(Qs)
orth = float(np.abs(np.asarray(gram) - np.eye(n)).max())

print(f"proc {pid}: qr rec={max_rec:.3e} orth={orth:.3e}", flush=True)
assert max_rec < 1e-5, max_rec
assert orth < 1e-5, orth
