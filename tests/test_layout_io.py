"""Layout (COSTA-role) and IO (CholeskyIO-role) tests."""

import numpy as np
import pytest

from conflux_tpu.geometry import CholeskyGeometry, Grid3, LUGeometry
from conflux_tpu.io import generate_spd_tiles, load_and_scatter, load_matrix, save_matrix
from conflux_tpu.layout import BlockCyclicLayout, gather, scatter, transform
from conflux_tpu import debug


def test_layout_roundtrip():
    lay = BlockCyclicLayout(M=20, N=12, vr=4, vc=4, Prows=2, Pcols=3)
    A = np.arange(240.0).reshape(20, 12)
    back = gather(scatter(A, lay), lay)
    np.testing.assert_array_equal(A, back)


def test_layout_ragged_edges():
    # non-divisible extents exercise the partial-tile paths
    lay = BlockCyclicLayout(M=10, N=7, vr=4, vc=3, Prows=2, Pcols=2)
    A = np.random.default_rng(0).standard_normal((10, 7))
    back = gather(scatter(A, lay), lay)
    np.testing.assert_array_equal(A, back)


def test_layout_transform_between_tile_sizes():
    A = np.random.default_rng(1).standard_normal((24, 24))
    src = BlockCyclicLayout(M=24, N=24, vr=4, vc=4, Prows=2, Pcols=2)
    dst = BlockCyclicLayout(M=24, N=24, vr=8, vc=8, Prows=3, Pcols=1)
    moved = transform(scatter(A, src), src, dst)
    np.testing.assert_array_equal(gather(moved, dst), A)


def test_layout_transform_shape_mismatch():
    src = BlockCyclicLayout(M=8, N=8, vr=4, vc=4, Prows=1, Pcols=1)
    dst = BlockCyclicLayout(M=16, N=8, vr=4, vc=4, Prows=1, Pcols=1)
    with pytest.raises(ValueError):
        transform(scatter(np.zeros((8, 8)), src), src, dst)


def test_owner_map():
    lay = BlockCyclicLayout(M=16, N=16, vr=4, vc=4, Prows=2, Pcols=2)
    om = lay.owner_map()
    assert om.shape == (4, 4, 2)
    assert om[2, 3].tolist() == [0, 1]


def test_spd_tiles_deterministic_and_spd():
    geom = CholeskyGeometry.create(64, 16, Grid3(2, 2, 1))
    A1 = generate_spd_tiles(geom, seed=5)
    A2 = generate_spd_tiles(geom, seed=5)
    np.testing.assert_array_equal(A1, A2)
    np.testing.assert_array_equal(A1, A1.T)
    assert np.linalg.eigvalsh(A1).min() > 0


def test_matrix_file_roundtrip(tmp_path):
    A = np.random.default_rng(2).standard_normal((12, 8)).astype(np.float32)
    p = str(tmp_path / "m.bin")
    save_matrix(p, A)
    np.testing.assert_array_equal(load_matrix(p), A)
    geom = LUGeometry.create(12, 8, 4, Grid3(1, 1, 1))
    shards = load_and_scatter(p, geom)
    assert shards.shape[0] == 1


def test_debug_checks():
    debug.assert_valid(np.ones(4))
    with pytest.raises(FloatingPointError):
        debug.assert_valid(np.array([1.0, np.nan]))
    with pytest.raises(ZeroDivisionError):
        debug.assert_nonzero_pivots(np.diag([1.0, 0.0, 2.0]))
    debug.assert_pivot_conservation(np.array([[0, 1], [2, 3]]), 4)
    with pytest.raises(AssertionError):
        debug.assert_pivot_conservation(np.array([0, 0, 1]), 4)


def test_layout_grid_larger_than_tile_grid():
    """A grid coordinate owning zero tiles must produce empty shards, not crash."""
    lay = BlockCyclicLayout(M=4, N=4, vr=2, vc=4, Prows=1, Pcols=2)
    A = np.arange(16.0).reshape(4, 4)
    shards = scatter(A, lay)
    assert shards[0][1].size == 0
    np.testing.assert_array_equal(gather(shards, lay), A)


def test_read_header_rejects_corrupt_file(tmp_path):
    import numpy as np
    import pytest

    from conflux_tpu.io import load_matrix

    bad = tmp_path / "bad.bin"
    np.array([8, 8, -1], dtype=np.int64).tofile(str(bad))
    with pytest.raises(ValueError):
        load_matrix(str(bad))
    short = tmp_path / "short.bin"
    short.write_bytes(b"\x01\x02")
    with pytest.raises(ValueError):
        load_matrix(str(short))


def test_read_header_rejects_headerless_raw_dump(tmp_path):
    # reference cholesky_helper format: raw dim*dim doubles, no header —
    # rejected by the header/file-size consistency check
    import numpy as np
    import pytest

    from conflux_tpu.io import load_matrix

    raw = tmp_path / "input_8.bin"
    np.random.default_rng(0).standard_normal((8, 8)).tofile(str(raw))
    with pytest.raises(ValueError, match="not a conflux_tpu matrix file"):
        load_matrix(str(raw))


def test_save_matrix_rejects_bfloat16(tmp_path):
    import jax.numpy as jnp
    import numpy as np
    import pytest

    from conflux_tpu.io import save_matrix

    A = np.asarray(jnp.zeros((4, 4), jnp.bfloat16))
    with pytest.raises(ValueError, match="float32"):
        save_matrix(str(tmp_path / "m.bin"), A)


def test_generate_spd_file_streaming(tmp_path):
    """Streamed SPD file: loadable, SPD, and factorizable; never holds the
    matrix in RAM during generation."""
    import numpy as np
    import scipy.linalg

    from conflux_tpu.io import generate_spd_file, load_matrix

    path = str(tmp_path / "spd.bin")
    generate_spd_file(path, 64, v=16, seed=3)
    A = load_matrix(path)
    assert A.shape == (64, 64)
    np.testing.assert_allclose(A, A.T)
    scipy.linalg.cholesky(A, lower=True)  # SPD or raises


def test_generate_spd_file_rejects_bad_tile(tmp_path):
    import pytest

    from conflux_tpu.io import generate_spd_file

    with pytest.raises(ValueError):
        generate_spd_file(str(tmp_path / "x.bin"), 100, v=16)


def test_layout_transform_ragged_cross_tiles():
    # ragged extents + unaligned tile sizes: every intersection path runs
    A = np.random.default_rng(3).standard_normal((22, 17))
    src = BlockCyclicLayout(M=22, N=17, vr=5, vc=4, Prows=2, Pcols=3)
    dst = BlockCyclicLayout(M=22, N=17, vr=3, vc=7, Prows=3, Pcols=2)
    moved = transform(scatter(A, src), src, dst)
    np.testing.assert_array_equal(gather(moved, dst), A)
    # and back again
    back = transform(moved, dst, src)
    np.testing.assert_array_equal(gather(back, src), A)


def test_spd_shards_match_independent_construction():
    from conflux_tpu.io import _spd_base_tile, generate_spd_shards

    geom = CholeskyGeometry.create(48, 8, Grid3(2, 2, 1))
    shards = generate_spd_shards(geom, seed=9)
    # independent oracle: tile the base block over the FULL matrix and
    # boost the diagonal, without going through the shard builder
    sym = _spd_base_tile(geom, 9, np.float64)
    full = np.tile(sym, (geom.N // geom.v, geom.N // geom.v))
    full[np.arange(geom.N), np.arange(geom.N)] += geom.N
    np.testing.assert_array_equal(shards, geom.scatter(full))
    np.testing.assert_array_equal(generate_spd_tiles(geom, seed=9), full)
    assert np.linalg.eigvalsh(full).min() > 0


def test_choose_cholesky_tile_properties():
    from conflux_tpu.geometry import choose_cholesky_tile

    # memory-ratio heuristic: small problems get small tiles, big single-
    # device problems saturate at the VMEM-safe cap, huge device counts
    # keep at least two tile columns per axis
    assert choose_cholesky_tile(256, 1) <= 128
    assert choose_cholesky_tile(32768, 1) == 1024
    assert choose_cholesky_tile(4096, 64) <= 1024
    v = choose_cholesky_tile(2048, 16)
    assert 2048 // (v * 4) >= 2  # >= 2 tile cols per x-axis device


def test_numroc_matches_local_shape():
    from conflux_tpu.layout import numroc

    # the scattered shard extents and ScaLAPACK's numroc formula must agree
    # exactly on every coordinate, including ragged trailing tiles
    for (M, N, vr, vc, Pr, Pc) in [(20, 12, 4, 4, 2, 3), (10, 7, 4, 3, 2, 2),
                                   (17, 33, 5, 8, 3, 2), (8, 8, 8, 8, 2, 2)]:
        lay = BlockCyclicLayout(M=M, N=N, vr=vr, vc=vc, Prows=Pr, Pcols=Pc)
        for p in range(Pr):
            for q in range(Pc):
                rows = numroc(M, vr, p, 0, Pr)
                cols = numroc(N, vc, q, 0, Pc)
                shard = scatter(np.ones((M, N)), lay)[p][q]
                assert shard.size == rows * cols


def test_scalapack_desc():
    from conflux_tpu.layout import numroc, scalapack_desc

    lay = BlockCyclicLayout(M=100, N=60, vr=8, vc=16, Prows=3, Pcols=2)
    d = scalapack_desc(lay, p=1, ctxt=5)
    assert d.tolist() == [1, 5, 100, 60, 8, 16, 0, 0,
                          numroc(100, 8, 1, 0, 3)]


def test_to_scalapack_placement_matches_indx_formulas():
    """The exported local buffers must place every element exactly where
    ScaLAPACK's own index maps (INDXG2P/INDXG2L) say its owner stores it —
    the contract an external p?getrf/p?gemm consumer relies on."""
    from conflux_tpu.layout import from_scalapack, indxg2l, indxg2p, to_scalapack

    for (M, N, vr, vc, Pr, Pc) in [(20, 12, 4, 4, 2, 3), (17, 33, 5, 8, 3, 2),
                                   (8, 8, 8, 8, 2, 2)]:
        lay = BlockCyclicLayout(M=M, N=N, vr=vr, vc=vc, Prows=Pr, Pcols=Pc)
        A = np.arange(M * N, dtype=np.float64).reshape(M, N)
        locals_, descs = to_scalapack(A, lay)
        for i in range(M):
            for j in range(N):
                p, q = indxg2p(i, vr, 0, Pr), indxg2p(j, vc, 0, Pc)
                buf = locals_[p][q]
                assert buf.flags.f_contiguous or buf.size <= 1
                assert buf[indxg2l(i, vr, Pr), indxg2l(j, vc, Pc)] == A[i, j]
        for p in range(Pr):
            for q in range(Pc):
                # LLD_ (desc[8]) is the column stride of the local buffer
                assert descs[p][q][8] == max(1, locals_[p][q].shape[0])
        np.testing.assert_array_equal(from_scalapack(locals_, lay), A)


def test_scalapack_export_of_computed_factors():
    """End-to-end interop exercise: factors computed by the distributed LU
    exported into ScaLAPACK locals reassemble to the same packed LU (the
    role the reference's COSTA transforms play before pdgemm validation,
    `examples/conflux_miniapp.cpp:349-353`)."""
    from conflux_tpu.geometry import Grid3
    from conflux_tpu.layout import from_scalapack, to_scalapack
    from conflux_tpu.lu.distributed import lu_distributed_host
    from conflux_tpu.validation import make_test_matrix

    N, v = 32, 8
    grid = Grid3(2, 2, 1)
    A = make_test_matrix(N, N, seed=12)
    LU, perm, geom = lu_distributed_host(A, grid, v)
    lay = BlockCyclicLayout.for_grid(N, N, v, grid)
    locals_, descs = to_scalapack(LU, lay)
    assert all(d[4] == v and d[5] == v for row in descs for d in row)
    np.testing.assert_array_equal(from_scalapack(locals_, lay), LU)


def test_matrix_file_int32_roundtrip(tmp_path):
    # int32 is a first-class format code: integer state (the LU row-origin
    # checkpoint) must round-trip exactly at any scale
    from conflux_tpu.io import load_matrix, save_matrix

    big = np.array([[2**24 + 1, -5], [7, 2**30]], np.int32)
    p = str(tmp_path / "ints.bin")
    save_matrix(p, big)
    back = load_matrix(p)
    assert back.dtype == np.int32
    np.testing.assert_array_equal(back, big)


def test_custom_layout_scatter_gather_roundtrip():
    """CustomLayout (the costa::custom_layout role): arbitrary per-tile
    owners, tile stores round-trip exactly."""
    from conflux_tpu.layout import CustomLayout

    rng = np.random.default_rng(11)
    M, N, vr, vc = 50, 38, 8, 16
    Mt, Nt = -(-M // vr), -(-N // vc)
    owners = np.stack([rng.integers(0, 3, (Mt, Nt)),
                       rng.integers(0, 2, (Mt, Nt))], axis=-1)
    lay = CustomLayout.from_owner_map(M, N, vr, vc, owners)
    A = rng.standard_normal((M, N)).astype(np.float32)
    store = lay.scatter(A)
    # every tile landed on its mapped owner
    for ti in range(Mt):
        for tj in range(Nt):
            assert (ti, tj) in store[lay.owner(ti, tj)]
    np.testing.assert_array_equal(lay.gather(store), A)


def test_transform_block_cyclic_to_custom_and_back():
    """costa::transform between the two layout kinds, both directions,
    with different tile sizes — the last sliver of the COSTA adapter
    (VERDICT r2 item 10)."""
    from conflux_tpu.layout import BlockCyclicLayout, CustomLayout, scatter, transform

    rng = np.random.default_rng(12)
    M, N = 64, 48
    bc = BlockCyclicLayout(M=M, N=N, vr=8, vc=8, Prows=2, Pcols=2)
    Mt, Nt = -(-M // 16), -(-N // 12)
    owners = np.stack([rng.integers(0, 2, (Mt, Nt)),
                       rng.integers(0, 3, (Mt, Nt))], axis=-1)
    cl = CustomLayout.from_owner_map(M, N, 16, 12, owners)

    A = rng.standard_normal((M, N)).astype(np.float32)
    shards = scatter(A, bc)
    store = transform(shards, bc, cl)
    np.testing.assert_array_equal(cl.gather(store), A)

    # and back, onto a DIFFERENT block-cyclic layout
    bc2 = BlockCyclicLayout(M=M, N=N, vr=4, vc=16, Prows=3, Pcols=1)
    shards2 = transform(store, cl, bc2)
    from conflux_tpu.layout import gather
    np.testing.assert_array_equal(gather(shards2, bc2), A)


def test_custom_layout_matches_cyclic_owner_map():
    """A CustomLayout built from a BlockCyclicLayout's owner_map is the
    same distribution: transform re-buckets into tiles that match the
    scattered originals tile-for-tile."""
    from conflux_tpu.layout import BlockCyclicLayout, CustomLayout, scatter, transform

    rng = np.random.default_rng(13)
    M, N, v = 40, 40, 8
    bc = BlockCyclicLayout(M=M, N=N, vr=v, vc=v, Prows=2, Pcols=2)
    cl = CustomLayout.from_owner_map(M, N, v, v, bc.owner_map())
    A = rng.standard_normal((M, N)).astype(np.float32)
    store = transform(scatter(A, bc), bc, cl)
    for ti in range(bc.tile_counts()[0]):
        for tj in range(bc.tile_counts()[1]):
            assert cl.owner(ti, tj) == bc.owner(ti, tj)
            np.testing.assert_array_equal(
                store[cl.owner(ti, tj)][(ti, tj)],
                A[ti * v : (ti + 1) * v, tj * v : (tj + 1) * v])


def test_custom_layout_rejects_bad_owner_map():
    import pytest

    from conflux_tpu.layout import CustomLayout

    with pytest.raises(ValueError, match="shape"):
        CustomLayout.from_owner_map(32, 32, 8, 8, np.zeros((3, 4, 2)))
    bad = np.zeros((4, 4, 2), np.int64)
    bad[0, 0, 0] = -1
    with pytest.raises(ValueError, match="non-negative"):
        CustomLayout.from_owner_map(32, 32, 8, 8, bad)
