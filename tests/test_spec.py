"""Executable-spec tests: the NumPy simulation must (a) produce correct
factorizations under every pivoting strategy and (b) agree with the
shard_map implementation — the cross-validation role the reference's
prototype played for its C++ (`python/compare_res.py`)."""

import numpy as np
import pytest

from conflux_tpu.geometry import Grid3
from conflux_tpu.lu.distributed import full_permutation, lu_distributed_host
from conflux_tpu.spec.numpy_lu import simulate_lu
from conflux_tpu.validation import lu_residual, make_test_matrix, residual_bound


@pytest.mark.parametrize("pivoting", ["tournament", "partial"])
@pytest.mark.parametrize("grid", [Grid3(1, 1, 1), Grid3(2, 2, 1), Grid3(2, 2, 2)], ids=str)
def test_spec_residual(grid, pivoting):
    N, v = 32, 8
    A = make_test_matrix(N, N, seed=grid.P + len(pivoting))
    LU, pivots = simulate_lu(A, grid, v, pivoting=pivoting)
    perm = full_permutation(pivots, N)
    res = lu_residual(A, LU[perm], perm)
    assert res < residual_bound(N, np.float64), (grid, pivoting, res)


def test_spec_nopivot_diag_dominant():
    N, v = 16, 4
    A = make_test_matrix(N, N, seed=1)
    A += N * np.eye(N)  # diagonally dominant: row order is pivot order
    LU, pivots = simulate_lu(A, Grid3(2, 1, 1), v, pivoting="none")
    assert pivots.reshape(-1).tolist() == list(range(N))
    perm = full_permutation(pivots, N)
    assert lu_residual(A, LU[perm], perm) < residual_bound(N, np.float64)


def _assert_no_vmem_override():
    """Default-chunk spec-vs-impl agreement holds only when the impl's
    scoped-VMEM budget equals the spec's pinned default: the spec pins
    `_SCOPED_VMEM_DEFAULT` for host-independence while the impl honors
    env/device overrides, so under an override the two would chunk (and
    can pivot) differently. Guard rather than silently diverge."""
    from conflux_tpu.ops import blas

    assert blas.scoped_vmem_bytes() == blas._SCOPED_VMEM_DEFAULT, (
        "scoped-VMEM override active; default-chunk spec-vs-impl "
        "cross-validation needs an explicit shared panel_chunk")


@pytest.mark.parametrize("grid", [Grid3(2, 2, 1), Grid3(2, 1, 2)], ids=str)
def test_spec_matches_shard_map_implementation(grid):
    """Same algorithm, two implementations: pivot choices must be identical
    and factors must agree to fp tolerance."""
    _assert_no_vmem_override()
    N, v = 32, 8
    A = make_test_matrix(N, N, seed=99)
    LU_spec, piv_spec = simulate_lu(A, grid, v, pivoting="tournament")
    LU_impl, perm_impl, _ = lu_distributed_host(A, grid, v)
    piv_impl = perm_impl[: piv_spec.size].reshape(piv_spec.shape)
    np.testing.assert_array_equal(piv_spec, piv_impl)
    np.testing.assert_allclose(LU_spec, LU_impl, atol=1e-10)


def test_spec_matches_implementation_chunked():
    """Cross-validation must hold in the *chunked* election regime too
    (Ml > chunk locally, Px*v > chunk in the election) — the production
    regime of BASELINE.md's grids."""
    N, v, chunk = 64, 8, 16
    A = make_test_matrix(N, N, seed=101)
    for grid in (Grid3(2, 1, 1), Grid3(2, 2, 1)):
        LU_spec, piv_spec = simulate_lu(A, grid, v, pivoting="tournament",
                                        panel_chunk=chunk)
        LU_impl, perm_impl, _ = lu_distributed_host(A, grid, v,
                                                    panel_chunk=chunk)
        piv_impl = perm_impl[: piv_spec.size].reshape(piv_spec.shape)
        np.testing.assert_array_equal(piv_spec, piv_impl)
        np.testing.assert_allclose(LU_spec, LU_impl, atol=1e-10)
