"""Elastic fabric (ISSUE 19): runtime membership, autoscaling,
hot-host rebalancing and K-replica instant fail-over.

- `add_host` / `remove_host` change the live set at runtime; HRW
  remaps ONLY the affected host's sessions (no reshuffle). Removal
  drains through the §28 migrate barrier; a host that cannot finish
  draining returns to service instead of half-leaving.
- Retired ids never resurrect: a host that died or was removed is
  permanently refused by `add_host` under the same id.
- `add_host` reserves the id in its first critical section, so two
  concurrent joins with one id race on the reservation — exactly one
  `start()` runs (the old check-then-insert TOCTOU leaked a started
  handle).
- Migration, the drain storm and the rebalancer share ONE target
  picker that refuses wire-congested hosts (shm ring ≥ 90% full).
- K=2 replica placement: checkpointed records land on the
  rendezvous-RANKED standby; fail-over re-points (local adopt, no
  cross-host snapshot read) with the generation-coherence gate, and
  snapshot restore survives as the fallback when every live standby
  is gone or stale.
- `FabricAutoscaler`: fleet-mean two-axis utilization, hysteresis
  (sustain), cooldown, and a scale-in pre-check — one Poisson clump
  never resizes the fleet.

Everything runs the single-process LocalHost fabric; the real
3-process replicated kill lives in scripts/fabric_drill.py phase 6.
"""

import os
import threading
import time

import numpy as np
import pytest

from conflux_tpu import fabric, resilience
from conflux_tpu.control import AutoscalePolicy, FabricAutoscaler
from conflux_tpu.engine import rendezvous, rendezvous_ranked
from conflux_tpu.fabric import FabricPolicy, LocalHost
from conflux_tpu.resilience import FleetDegraded, HostUnavailable
from conflux_tpu.serve import FactorPlan

N, V = 24, 8


def _mk(seed, n=N):
    rng = np.random.default_rng(seed)
    return (rng.standard_normal((n, n)) / np.sqrt(n)
            + 2.0 * np.eye(n)).astype(np.float32)


def _rhs(seed, w=1):
    b = np.random.default_rng(1000 + seed).standard_normal(
        (N, w) if w > 1 else (N,))
    return b.astype(np.float32)


def _plan():
    return FactorPlan.create((N, N), "float32", v=V)


def _fab(tmp_path, n=3, fault_plan=None, **pol):
    kw = dict(heartbeat_interval=0.05, heartbeat_timeout=1.0,
              suspect_after=2, dead_after=4)
    kw.update(pol)
    return fabric.local_fabric(
        n, str(tmp_path), policy=FabricPolicy(**kw),
        fault_plan=fault_plan,
        engine_kwargs={"max_batch_delay": 0.0})


def _wait_dead(fab, hid, timeout=20.0):
    t0 = time.perf_counter()
    while time.perf_counter() - t0 < timeout:
        if fab.host_state(hid) == "dead":
            return time.perf_counter() - t0
        time.sleep(0.02)
    raise AssertionError(f"host {hid} never declared dead")


def _counter(key):
    return resilience.health_stats().get(key, 0)


def _wait_recovery(fab, hid, timeout=20.0):
    """The recovery record lands after the dead flip; poll for it."""
    t0 = time.perf_counter()
    while time.perf_counter() - t0 < timeout:
        for rec in reversed(fab.stats()["recoveries"]):
            if rec["host"] == hid:
                return rec
        time.sleep(0.02)
    raise AssertionError(f"no recovery record for {hid}")


def _local(hid, root, **kw):
    return LocalHost(hid, os.path.join(str(root), hid),
                     engine_kwargs={"max_batch_delay": 0.0}, **kw)


# --------------------------------------------------------------------------- #
# ranked rendezvous
# --------------------------------------------------------------------------- #


def test_rendezvous_ranked_properties():
    """ranked[0] is the classic owner; removing the winner promotes
    EXACTLY the next-ranked survivor, and only the removed node's
    sids remap (the §34 no-reshuffle extension down the list)."""
    nodes = [f"h{i}" for i in range(5)]
    for sid in range(64):
        order = rendezvous_ranked(sid, nodes)
        assert order[0] == rendezvous(sid, nodes)
        assert sorted(order) == sorted(nodes)
        # drop the winner: the survivors' relative order is unchanged
        survivors = [n for n in nodes if n != order[0]]
        assert rendezvous_ranked(sid, survivors) == order[1:]
        assert rendezvous(sid, survivors) == order[1]
        # k truncates without changing the prefix
        assert rendezvous_ranked(sid, nodes, k=2) == order[:2]
    # dropping ONE node remaps only its own sids
    moved = sum(1 for sid in range(200)
                if rendezvous(sid, nodes) != rendezvous(sid, nodes[:-1])
                and rendezvous(sid, nodes) != nodes[-1])
    assert moved == 0


# --------------------------------------------------------------------------- #
# runtime membership: join
# --------------------------------------------------------------------------- #


class _SlowStart(LocalHost):
    """LocalHost whose start() is slow and counted — the TOCTOU
    window probe: under the old check-then-insert add_host, two
    racing joins with one id BOTH reached start()."""

    started = 0
    _count_lock = threading.Lock()

    def start(self):
        time.sleep(0.15)
        with _SlowStart._count_lock:
            _SlowStart.started += 1
        return super().start()


def test_add_host_toctou_reservation(tmp_path):
    """Two concurrent add_host calls with the same id: exactly one
    wins the reservation and starts a worker; the loser fails before
    owning any resource."""
    fab = _fab(tmp_path, n=2)
    fab.start()
    try:
        _SlowStart.started = 0
        errs = []

        def join(sub):
            try:
                fab.add_host(_SlowStart(
                    "hx", os.path.join(str(tmp_path), sub),
                    engine_kwargs={"max_batch_delay": 0.0}))
            except ValueError as e:
                errs.append(e)

        t1 = threading.Thread(target=join, args=("hx-a",))
        t2 = threading.Thread(target=join, args=("hx-b",))
        t1.start(); t2.start(); t1.join(); t2.join()
        assert len(errs) == 1 and "already present" in str(errs[0])
        assert _SlowStart.started == 1
        assert fab.host_state("hx") == "alive"
        # the winner serves: place a session on the enlarged set
        fab.open("sx", _plan(), _mk(0))
        np.asarray(fab.solve("sx", _rhs(0)))
    finally:
        fab.close()


def test_add_host_failed_start_releases_reservation(tmp_path):
    """A handle whose start() raises must not burn the id: the
    reservation is released (not retired) and a later join with the
    same id succeeds."""
    fab = _fab(tmp_path, n=2)
    fab.start()
    try:
        class _Boom(LocalHost):
            def start(self):
                raise RuntimeError("provision failed")

        with pytest.raises(RuntimeError):
            fab.add_host(_Boom("hy", os.path.join(str(tmp_path), "y")))
        assert "hy" not in fab.taken_ids()
        fab.add_host(_local("hy", tmp_path))
        assert fab.host_state("hy") == "alive"
    finally:
        fab.close()


def test_add_host_adopt_on_arrival_no_reshuffle(tmp_path):
    """Scale-out does not move existing owners; new sessions HRW over
    the enlarged set."""
    fab = _fab(tmp_path, n=2)
    fab.start()
    try:
        sids = [f"s{i}" for i in range(6)]
        for i, s in enumerate(sids):
            fab.open(s, _plan(), _mk(i))
        before = {s: fab.owner_of(s) for s in sids}
        added = _counter("fabric_hosts_added")
        fab.add_host(_local("h9", tmp_path))
        assert _counter("fabric_hosts_added") == added + 1
        assert {s: fab.owner_of(s) for s in sids} == before
        for i, s in enumerate(sids):
            assert np.isfinite(np.asarray(fab.solve(s, _rhs(i)))).all()
    finally:
        fab.close()


# --------------------------------------------------------------------------- #
# runtime membership: leave
# --------------------------------------------------------------------------- #


def test_remove_host_drain_bitwise_and_counted(tmp_path):
    """Scale-in drains every owned session over the migrate barrier;
    drained sessions solve BITWISE identically, the id is retired,
    and the storm is counted."""
    fab = _fab(tmp_path, n=3)
    fab.start()
    try:
        sids = [f"s{i}" for i in range(8)]
        for i, s in enumerate(sids):
            fab.open(s, _plan(), _mk(i))
        before = {s: np.asarray(fab.solve(s, _rhs(i)))
                  for i, s in enumerate(sids)}
        census = fab.owner_census()
        victim = max(census, key=lambda h: (census[h], h))
        owned = sorted((s for s in sids if fab.owner_of(s) == victim),
                       key=str)
        d0 = _counter("fabric_drain_migrations")
        r0 = _counter("fabric_hosts_removed")
        moved = fab.remove_host(victim)
        assert sorted(moved, key=str) == owned
        assert _counter("fabric_drain_migrations") == d0 + len(owned)
        assert _counter("fabric_hosts_removed") == r0 + 1
        assert victim not in fab.owner_census()
        with pytest.raises(KeyError):
            fab.host_state(victim)
        for i, s in enumerate(sids):
            assert np.array_equal(before[s],
                                  np.asarray(fab.solve(s, _rhs(i))))
        st = fab.stats()
        assert st["retired_hosts"] == 1
        assert st["lost_sessions"] == 0
    finally:
        fab.close()


def test_remove_host_refusals(tmp_path):
    """Unknown id -> KeyError; below min_live -> FleetDegraded (the
    fleet is never drained under its own floor)."""
    fab = _fab(tmp_path, n=2, min_live=2)
    fab.start()
    try:
        with pytest.raises(KeyError):
            fab.remove_host("nope")
        with pytest.raises(FleetDegraded):
            fab.remove_host("h0")
        assert fab.host_state("h0") == "alive"
    finally:
        fab.close()


def test_remove_dead_host_is_bookkeeping_and_id_never_resurrects(tmp_path):
    """Removing an already-dead host waits out fail-over and retires
    the entry; add_host under the dead id is refused FOREVER."""
    fab = _fab(tmp_path, n=3)
    fab.start()
    try:
        sids = [f"s{i}" for i in range(6)]
        for i, s in enumerate(sids):
            fab.open(s, _plan(), _mk(i))
        census = fab.owner_census()
        victim = max(census, key=lambda h: (census[h], h))
        fab._hosts[victim].kill()
        _wait_dead(fab, victim)
        # remove during / right after the in-flight fail-over: pure
        # bookkeeping, no drain storm
        assert fab.remove_host(victim) == []
        with pytest.raises(KeyError):
            fab.host_state(victim)
        with pytest.raises(ValueError, match="never resurrect"):
            fab.add_host(LocalHost(
                victim, os.path.join(str(tmp_path), victim + "-again"),
                engine_kwargs={"max_batch_delay": 0.0}))
        assert fab.stats()["lost_sessions"] == 0
        for i, s in enumerate(sids):
            assert np.isfinite(np.asarray(fab.solve(s, _rhs(i)))).all()
    finally:
        fab.close()


def test_remove_while_suspect_abandons_not_half_applies(tmp_path):
    """remove_host on a host that is (secretly dead and) suspect:
    the drain storm cannot move anything, so scale-in is ABANDONED —
    either the host returns to service (HostUnavailable with a retry
    hint) or the concurrent death detection takes over. Never a
    half-applied membership change; zero lost either way."""
    fab = _fab(tmp_path, n=3)
    fab.start()
    try:
        sids = [f"s{i}" for i in range(6)]
        for i, s in enumerate(sids):
            fab.open(s, _plan(), _mk(i))
        census = fab.owner_census()
        victim = max(census, key=lambda h: (census[h], h))
        fab._hosts[victim].kill()
        t0 = time.perf_counter()
        while (fab.host_state(victim) == "alive"
               and time.perf_counter() - t0 < 20.0):
            time.sleep(0.01)
        try:
            fab.remove_host(victim)
        except HostUnavailable as e:
            # undrained sessions stayed on the (still-listed) source
            assert e.retry_after > 0
            _wait_dead(fab, victim)
            assert fab.remove_host(victim) == []
        with pytest.raises(KeyError):
            fab.host_state(victim)
        # heartbeat fail-over re-homed everything; nothing lost
        t0 = time.perf_counter()
        while (fab.stats()["sessions"] < len(sids)
               and time.perf_counter() - t0 < 20.0):
            time.sleep(0.02)
        assert fab.stats()["lost_sessions"] == 0
        for i, s in enumerate(sids):
            assert np.isfinite(np.asarray(fab.solve(s, _rhs(i)))).all()
    finally:
        fab.close()


def test_close_session_census_conservation(tmp_path):
    """close_session is the load-recede half of elasticity: admitted
    == open + failed-over-lost + closed, and closed sids are really
    gone."""
    fab = _fab(tmp_path, n=2)
    fab.start()
    try:
        for i in range(6):
            fab.open(f"s{i}", _plan(), _mk(i))
        c0 = _counter("fabric_sessions_closed")
        for i in range(4):
            assert fab.close_session(f"s{i}") is True
        assert _counter("fabric_sessions_closed") == c0 + 4
        st = fab.stats()
        assert st["closed_sessions"] == 4
        assert st["admitted_sessions"] == 6
        assert (st["admitted_sessions"]
                == st["sessions"] + st["lost_sessions"]
                + st["closed_sessions"])
        with pytest.raises(KeyError):
            fab.solve("s0", _rhs(0))
        assert np.isfinite(np.asarray(fab.solve("s5", _rhs(5)))).all()
    finally:
        fab.close()


# --------------------------------------------------------------------------- #
# the shared target picker (wire congestion)
# --------------------------------------------------------------------------- #


def test_pick_target_and_migrate_avoid_full_wire(tmp_path):
    """migrate and the rebalancer share one picker: a host whose shm
    ring is >= 90% full is never chosen while a clear host exists,
    and the rebalancer refuses OUTRIGHT when nothing has headroom."""
    fab = _fab(tmp_path, n=3)
    fab.start()
    try:
        fab.open("s0", _plan(), _mk(0))
        src = fab.owner_of("s0")
        others = sorted(h for h in fab.stats()["hosts"] if h != src)
        full, clear = others
        fab.load.feed(full, {"seconds": 1.0, "solves": 0,
                             "pending": 0, "wire_used_frac": 0.95})
        assert fab._pick_target(exclude={src}) == clear
        assert fab._pick_target(
            exclude={src}, require_wire_headroom=True) == clear
        tgt = fab.migrate("s0")
        assert tgt == clear
        # every candidate congested: soft mode degrades, the
        # rebalancer's hard mode refuses
        fab.load.feed(clear, {"seconds": 1.0, "solves": 0,
                              "pending": 0, "wire_used_frac": 0.92})
        fab.load.feed(src, {"seconds": 1.0, "solves": 0,
                            "pending": 0, "wire_used_frac": 0.92})
        assert fab._pick_target(exclude={tgt}) is not None
        assert fab._pick_target(
            exclude={tgt}, require_wire_headroom=True) is None
        assert fab.rebalance(max_moves=2, ratio=0.1, floor=1) == []
    finally:
        fab.close()


def test_rebalance_bounded_and_no_reshuffle(tmp_path):
    """The skew detector moves at most max_moves sids off ONE hot
    host per pass; untouched sessions keep their owners, moved ones
    solve bitwise, and a skew-free fleet is left alone."""
    fab = _fab(tmp_path, n=1, min_live=1)
    fab.start()
    try:
        sids = [f"s{i}" for i in range(6)]
        for i, s in enumerate(sids):
            fab.open(s, _plan(), _mk(i))
        before = {s: np.asarray(fab.solve(s, _rhs(i)))
                  for i, s in enumerate(sids)}
        fab.add_host(_local("hb", tmp_path))
        assert fab.owner_census() == {"h0": 6}  # adopt-on-arrival
        b0 = _counter("fabric_rebalance_migrations")
        moved = fab.rebalance(max_moves=2, ratio=1.5, floor=4)
        assert len(moved) == 2
        assert _counter("fabric_rebalance_migrations") == b0 + 2
        for s in moved:
            assert fab.owner_of(s) == "hb"
        for s in (set(sids) - set(moved)):
            assert fab.owner_of(s) == "h0"
        # bounded convergence, then stable: no further skew -> no moves
        while fab.rebalance(max_moves=2, ratio=1.2, floor=2):
            pass
        census = fab.owner_census()
        assert max(census.values()) - min(census.values()) <= 2
        assert fab.rebalance(max_moves=2, ratio=2.0, floor=4) == []
        for i, s in enumerate(sids):
            assert np.array_equal(before[s],
                                  np.asarray(fab.solve(s, _rhs(i))))
    finally:
        fab.close()


# --------------------------------------------------------------------------- #
# K-replica placement + instant fail-over
# --------------------------------------------------------------------------- #


def test_replica_repoint_failover_bitwise(tmp_path):
    """K=2: kill a host and its sessions re-point to standbys that
    adopt from LOCAL replica records — zero snapshot restores, zero
    lost, bitwise answers."""
    fab = _fab(tmp_path, n=3, replicas=2)
    fab.start()
    try:
        sids = [f"s{i}" for i in range(8)]
        for i, s in enumerate(sids):
            fab.open(s, _plan(), _mk(i))
        assert fab.stats()["replicated_sessions"] == len(sids)
        before = {s: np.asarray(fab.solve(s, _rhs(i)))
                  for i, s in enumerate(sids)}
        census = fab.owner_census()
        victim = max(census, key=lambda h: (census[h], h))
        owned = census[victim]
        s0 = _counter("fabric_snapshot_restores")
        p0 = _counter("fabric_replica_repoints")
        fab._hosts[victim].kill()
        _wait_dead(fab, victim)
        rec = _wait_recovery(fab, victim)
        assert rec["lost"] == 0
        assert rec["adopted"] == rec["repointed"] == owned
        assert _counter("fabric_snapshot_restores") == s0
        assert _counter("fabric_replica_repoints") == p0 + owned
        for i, s in enumerate(sids):
            assert np.array_equal(before[s],
                                  np.asarray(fab.solve(s, _rhs(i))))
    finally:
        fab.close()


def test_replica_survives_double_death(tmp_path):
    """The post-fail-over durability pass: adopters re-checkpoint and
    re-push, so a SECOND death immediately after re-point still loses
    nothing."""
    fab = _fab(tmp_path, n=3, replicas=2)
    fab.start()
    try:
        sids = [f"s{i}" for i in range(6)]
        for i, s in enumerate(sids):
            fab.open(s, _plan(), _mk(i))
        before = {s: np.asarray(fab.solve(s, _rhs(i)))
                  for i, s in enumerate(sids)}
        census = fab.owner_census()
        first = max(census, key=lambda h: (census[h], h))
        fab._hosts[first].kill()
        _wait_dead(fab, first)
        _wait_recovery(fab, first)
        census = fab.owner_census()
        second = max(census, key=lambda h: (census[h], h))
        fab._hosts[second].kill()
        _wait_dead(fab, second)
        _wait_recovery(fab, second)
        assert fab.stats()["lost_sessions"] == 0
        for i, s in enumerate(sids):
            assert np.array_equal(before[s],
                                  np.asarray(fab.solve(s, _rhs(i))))
    finally:
        fab.close()


def test_both_top2_dead_falls_back_to_snapshot(tmp_path):
    """Kill the STANDBY first (its death moves nothing), then the
    primary: at fail-over no live standby holds the record, so the
    counted snapshot-restore fallback recovers the session — still
    zero lost."""
    fab = _fab(tmp_path, n=3, replicas=2)
    fab.start()
    try:
        fab.open("s0", _plan(), _mk(0))
        before = np.asarray(fab.solve("s0", _rhs(0)))
        primary = fab.owner_of("s0")
        with fab._lock:
            standbys = sorted(fab._replicas["s0"])
        assert len(standbys) == 1 and primary not in standbys
        standby = standbys[0]
        s0 = _counter("fabric_snapshot_restores")
        fab._hosts[standby].kill()
        _wait_dead(fab, standby)
        assert fab.owner_of("s0") == primary
        fab._hosts[primary].kill()
        _wait_dead(fab, primary)
        rec = _wait_recovery(fab, primary)
        assert rec["lost"] == 0
        assert rec["repointed"] == 0 and rec["adopted"] == 1
        assert _counter("fabric_snapshot_restores") == s0 + 1
        assert np.array_equal(before,
                              np.asarray(fab.solve("s0", _rhs(0))))
    finally:
        fab.close()


def test_replica_push_failure_is_counted_not_fatal(tmp_path):
    """An injected fault on the replicate site leaves the standby a
    generation stale (counted); the session itself stays healthy."""
    from conflux_tpu.resilience import FaultPlan, FaultSpec

    plan = FaultPlan([FaultSpec(site="replicate", kind="crash",
                                count=1)])
    fab = _fab(tmp_path, n=3, replicas=2, fault_plan=plan)
    fab.start()
    try:
        f0 = _counter("fabric_replica_push_failures")
        fab.open("s0", _plan(), _mk(0))
        assert _counter("fabric_replica_push_failures") == f0 + 1
        assert np.isfinite(np.asarray(fab.solve("s0", _rhs(0)))).all()
        # the next checkpoint round heals the standby
        fab.checkpoint_all()
        assert fab.stats()["replicated_sessions"] == 1
    finally:
        fab.close()


# --------------------------------------------------------------------------- #
# autoscaler
# --------------------------------------------------------------------------- #


def _auto(fab, root, made, **kw):
    def provider(hid):
        made.append(hid)
        return _local(hid, root)

    base = dict(min_hosts=2, max_hosts=4, low_water=0.25,
                high_water=0.6, sustain=2, cooldown=10.0,
                bytes_per_session=525e3, host_bytes=4 * 525e3)
    base.update(kw)
    apol = AutoscalePolicy(**base)
    return FabricAutoscaler(fab, provider, policy=apol)


def test_autoscaler_scale_out_hysteresis_and_cooldown(tmp_path):
    """Sustained overload grows the fleet by ONE host; the very next
    tick is inside the cooldown and only rebalances."""
    fab = _fab(tmp_path, n=2)
    fab.start()
    try:
        made = []
        auto = _auto(fab, tmp_path, made)
        for i in range(8):          # 8 sessions / 2 hosts: mean 1.0
            fab.open(f"s{i}", _plan(), _mk(i))
        a0 = _counter("fabric_autoscale_out")
        assert auto.step(now=0.0)["action"] == "none"      # streak 1
        out = auto.step(now=1.0)                           # streak 2
        assert out["action"] == "scale_out"
        assert made == [out["detail"]]
        assert fab.host_state(out["detail"]) == "alive"
        assert _counter("fabric_autoscale_out") == a0 + 1
        assert auto.step(now=2.0)["action"] == "cooldown"
        assert len(made) == 1
        st = auto.stats()
        assert st["scale_out"] == 1 and st["errors"] == 0
    finally:
        fab.close()


def test_autoscaler_poisson_clump_never_resizes(tmp_path):
    """Hysteresis by construction: a clump shorter than `sustain`
    resets the streak on the next mid-band tick — the host set is
    untouched."""
    fab = _fab(tmp_path, n=2)
    fab.start()
    try:
        made = []
        auto = _auto(fab, tmp_path, made, sustain=3)
        for i in range(8):
            fab.open(f"s{i}", _plan(), _mk(i))      # mean 1.0: hot
        assert auto.step(now=0.0)["action"] == "none"
        assert auto.step(now=1.0)["action"] == "none"
        for i in range(5):                          # clump recedes
            fab.close_session(f"s{i}")              # mean 0.375: mid
        assert auto.step(now=2.0)["action"] == "none"
        for i in range(8, 13):                      # clump again
            fab.open(f"s{i}", _plan(), _mk(i))
        assert auto.step(now=3.0)["action"] == "none"
        assert auto.step(now=4.0)["action"] == "none"
        assert made == []
        assert sorted(fab.stats()["hosts"]) == ["h0", "h1"]
        st = auto.stats()
        assert st["scale_out"] == st["scale_in"] == 0
    finally:
        fab.close()


def test_autoscaler_scale_in_drains_least_loaded(tmp_path):
    """Sustained idleness drains ONE host (the least loaded) through
    remove_host; surviving sessions solve bitwise and the retired id
    is never reused by the id allocator."""
    fab = _fab(tmp_path, n=3)
    fab.start()
    try:
        made = []
        auto = _auto(fab, tmp_path, made)
        for i in range(3):
            fab.open(f"s{i}", _plan(), _mk(i))   # mean 0.25 @ n=3
        before = {f"s{i}": np.asarray(fab.solve(f"s{i}", _rhs(i)))
                  for i in range(3)}
        fab.close_session("s2")                  # mean 2/12 < 0.25
        i0 = _counter("fabric_autoscale_in")
        assert auto.step(now=0.0)["action"] == "none"
        out = auto.step(now=1.0)
        assert out["action"] == "scale_in"
        victim = out["detail"]
        assert victim not in fab.stats()["hosts"]
        assert victim in fab.taken_ids()          # retired, not free
        assert _counter("fabric_autoscale_in") == i0 + 1
        assert fab.stats()["lost_sessions"] == 0
        for i in range(2):
            assert np.array_equal(
                before[f"s{i}"],
                np.asarray(fab.solve(f"s{i}", _rhs(i))))
        assert auto.step(now=2.0)["action"] == "cooldown"
        # min_hosts floor: once at 2 hosts, shrink is refused
        fab.close_session("s0"); fab.close_session("s1")
        assert auto.step(now=20.0)["action"] == "none"
        out = auto.step(now=21.0)
        assert out["action"] == "refused" and "min_hosts" in out["detail"]
    finally:
        fab.close()


def test_autoscaler_full_wave_round_trip(tmp_path):
    """A load wave out and back: grow under pressure, shrink when it
    recedes, sessions bitwise across BOTH membership changes."""
    fab = _fab(tmp_path, n=2)
    fab.start()
    try:
        made = []
        auto = _auto(fab, tmp_path, made)
        for i in range(8):
            fab.open(f"s{i}", _plan(), _mk(i))
        before = np.asarray(fab.solve("s7", _rhs(7)))
        auto.step(now=0.0)
        assert auto.step(now=1.0)["action"] == "scale_out"
        for i in range(7):
            fab.close_session(f"s{i}")
        auto.step(now=20.0)
        out = auto.step(now=21.0)
        assert out["action"] == "scale_in"
        assert np.array_equal(before,
                              np.asarray(fab.solve("s7", _rhs(7))))
        log = auto.stats()["decisions_log"]
        assert [e["action"] for e in log] == ["scale_out", "scale_in"]
    finally:
        fab.close()
