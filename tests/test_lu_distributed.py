"""Distributed LU on a simulated multi-device CPU mesh.

Covers the reference's multi-rank correctness strategy (SURVEY.md §4): the
residual oracle ||PA - LU||_F on small deterministic matrices, across the
grid shapes the algorithm must handle (1D, 2D, 2.5D with z replication).
"""

import numpy as np
import pytest

from conflux_tpu.geometry import Grid3
from conflux_tpu.lu.distributed import lu_distributed_host
from conflux_tpu.validation import lu_residual, make_test_matrix, residual_bound


GRIDS = [
    Grid3(1, 1, 1),
    Grid3(2, 1, 1),
    Grid3(1, 2, 1),
    Grid3(2, 2, 1),
    Grid3(1, 1, 2),
    Grid3(2, 2, 2),
    Grid3(4, 2, 1),
    Grid3(2, 2, 1),
]


@pytest.mark.parametrize("grid", GRIDS, ids=str)
def test_lu_distributed_residual(grid):
    N, v = 64, 8
    A = make_test_matrix(N, N, seed=grid.P + grid.Px)
    LU, perm, geom = lu_distributed_host(A, grid, v)
    assert geom.M == N
    res = lu_residual(A, LU[perm], perm)
    assert res < residual_bound(N, np.float64), (grid, res)


def test_lu_distributed_matches_single_device():
    """Same matrix, different grids -> same residual-level factorization."""
    N, v = 32, 8
    A = make_test_matrix(N, N, seed=77)
    LU1, perm1, _ = lu_distributed_host(A, Grid3(1, 1, 1), v)
    LU2, perm2, _ = lu_distributed_host(A, Grid3(2, 2, 2), v)
    # pivot choices can differ only by value ties; residuals must both be tiny
    assert lu_residual(A, LU1[perm1], perm1) < residual_bound(N, np.float64)
    assert lu_residual(A, LU2[perm2], perm2) < residual_bound(N, np.float64)


def test_lu_distributed_padding():
    """Non-divisible N exercises the identity-padded corner."""
    N, v = 50, 8
    A = make_test_matrix(N, N, seed=5)
    LU, perm, geom = lu_distributed_host(A, Grid3(2, 2, 1), v)
    assert geom.M == 64
    Ap = np.eye(geom.M, dtype=A.dtype)
    Ap[:N, :N] = A
    res = lu_residual(Ap, LU[perm], perm)
    assert res < residual_bound(geom.M, np.float64)


def test_lu_distributed_chunked_election():
    """Ml larger than the panel chunk: the local nomination must run the
    chunked tournament (multiple chunks + reduction tree), and the cross-x
    election tree must handle Px·v taller than one chunk — the scaling
    regime the production grids in BASELINE.md hit (Ml = N/Px >> chunk)."""
    N, v = 128, 8
    A = make_test_matrix(N, N, seed=31)
    for grid in (Grid3(2, 2, 1), Grid3(4, 2, 1)):
        # Ml = N/Px = 64 or 32; chunk=16 forces 4+/2+ chunks locally and a
        # (Px*v=32 or 16, v) election through the same chunked tree
        LU, perm, _ = lu_distributed_host(A, grid, v, panel_chunk=16)
        res = lu_residual(A, LU[perm], perm)
        assert res < residual_bound(N, np.float64), (grid, res)
        assert sorted(perm.tolist()) == list(range(N))


def test_lu_distributed_bench_ratios():
    """Structural pin of the headline bench config (bench.py: N=32768,
    v=1024, chunk=8192 on 1x1x1) at 1/128 scale: the same N/v = 32
    supersteps and Ml/chunk = 4 nomination chunks, through the same
    single-device mesh program. Small-N grid tests can't see bugs that
    need many supersteps of live/dead segment transitions or a
    multi-chunk nomination on one device; this shape does."""
    N, v = 256, 8
    A = make_test_matrix(N, N, seed=2, dtype=np.float32)
    LU, perm, _ = lu_distributed_host(A, Grid3(1, 1, 1), v, panel_chunk=64)
    assert sorted(perm.tolist()) == list(range(N))
    res = lu_residual(A, LU[perm], perm)
    assert res < residual_bound(N, np.float32), res


def test_lu_distributed_election_height_bound():
    """Structural guarantee: NO lu primitive in the traced distributed
    program is taller than max(panel_chunk, 2v) — the scoped-VMEM safety
    contract of the TPU LU custom call (ops/blas.py). This is what the
    reference's log-depth butterfly provides (`conflux_opt.hpp:220-336`:
    every factorization is at most 2v rows)."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    from conflux_tpu.geometry import LUGeometry
    from conflux_tpu.lu.distributed import _build
    from conflux_tpu.parallel.mesh import make_mesh, mesh_cache_key

    grid = Grid3(4, 2, 1)
    v, chunk = 8, 16
    geom = LUGeometry.create(256, 256, v, grid)
    mesh = make_mesh(grid, devices=jax.devices()[: grid.P])

    def walk(jx, heights):
        for eqn in jx.eqns:
            if eqn.primitive.name == "lu":
                heights.append(eqn.invars[0].aval.shape[-2])
            for p in eqn.params.values():
                for q in (p if isinstance(p, (list, tuple)) else [p]):
                    if hasattr(q, "eqns"):
                        walk(q, heights)
                    elif hasattr(q, "jaxpr"):
                        walk(q.jaxpr, heights)

    for election in ("gather", "butterfly"):
        fn = _build(geom, mesh_cache_key(mesh), lax.Precision.HIGHEST,
                    "xla", chunk, election=election)
        jaxpr = jax.make_jaxpr(fn)(jnp.zeros((4, 2, geom.Ml, geom.Nl)))
        heights = []
        walk(jaxpr.jaxpr, heights)
        assert heights, "expected lu primitives in the traced program"
        assert max(heights) <= max(chunk, 2 * v), (election, heights)


def test_lu_distributed_chunked_matches_unchunked():
    """Chunk size changes pivot *order* only within tournament ties; the
    factorization must stay residual-correct and a pure permutation."""
    N, v = 64, 8
    A = make_test_matrix(N, N, seed=41)
    for chunk in (8, 16, 4096):
        LU, perm, _ = lu_distributed_host(A, Grid3(2, 1, 1), v,
                                          panel_chunk=chunk)
        res = lu_residual(A, LU[perm], perm)
        assert res < residual_bound(N, np.float64), (chunk, res)


def test_lu_distributed_flat_tree():
    """The flat election tree (one stacked LU instead of the pairwise
    reduction tree — fewer sequential latency-bound custom calls on TPU)
    is a valid CALU election: correct residual, pure permutation, across
    the chunked single-rank path (Px=1, multi-chunk nomination), the
    cross-x gather election, and rectangular shapes."""
    N, v = 128, 8
    A = make_test_matrix(N, N, seed=13)
    for grid in (Grid3(1, 1, 1), Grid3(2, 2, 1), Grid3(4, 2, 1)):
        LU, perm, _ = lu_distributed_host(A, grid, v, panel_chunk=16,
                                          tree="flat")
        res = lu_residual(A, LU[perm], perm)
        assert res < residual_bound(N, np.float64), (grid, res)
        assert sorted(perm.tolist()) == list(range(N))
    # bench-shape ratios (32 supersteps, 4 nomination chunks) as in
    # test_lu_distributed_bench_ratios, now through the flat tree
    N2 = 256
    A2 = make_test_matrix(N2, N2, seed=2, dtype=np.float32)
    LU, perm, _ = lu_distributed_host(A2, Grid3(1, 1, 1), v, panel_chunk=64,
                                      tree="flat")
    assert sorted(perm.tolist()) == list(range(N2))
    assert lu_residual(A2, LU[perm], perm) < residual_bound(N2, np.float32)


def test_lu_flat_tree_vmem_guard():
    """tree='flat' must refuse configurations whose nominee stack exceeds
    the single-call VMEM-safe height instead of failing at compile time
    on the chip."""
    import jax

    from conflux_tpu.geometry import LUGeometry
    from conflux_tpu.lu.distributed import build_program
    from conflux_tpu.parallel.mesh import make_mesh

    grid = Grid3(1, 1, 1)
    geom = LUGeometry.create(32768, 32768, 1024, grid)
    mesh = make_mesh(grid, devices=jax.devices()[:1])
    with pytest.raises(ValueError, match="flat"):
        build_program(geom, mesh, panel_chunk=2048, tree="flat")
    with pytest.raises(ValueError, match="tree"):
        build_program(geom, mesh, tree="bogus")


def test_lu_flat_tree_vmem_guard_dtype_aware():
    """The flat-tree guard must evaluate with the COMPUTE dtype's chunk
    ceilings: an f64 run's single-call-safe height is half f32's, so a
    config that passes for f32 can be unbuildable for f64 (ADVICE r3).
    panel_chunk=4096 at Ml=32768/v=1024 stacks 8 nominees = 8192 rows:
    exactly the f32 ceiling (passes), double the f64 one (must raise)."""
    import jax

    from conflux_tpu.geometry import LUGeometry
    from conflux_tpu.lu.distributed import build_program
    from conflux_tpu.ops import blas
    from conflux_tpu.parallel.mesh import make_mesh

    if blas.scoped_vmem_bytes() != blas._SCOPED_VMEM_DEFAULT:
        pytest.skip("scoped-VMEM override active; pinned heights differ")
    grid = Grid3(1, 1, 1)
    geom = LUGeometry.create(32768, 32768, 1024, grid)
    mesh = make_mesh(grid, devices=jax.devices()[:1])
    # passes with f32 compute (stack == the 8192-row f32 ceiling) ...
    build_program(geom, mesh, panel_chunk=4096, tree="flat",
                  dtype=np.float32)
    # ... and must refuse the same stack for f64 compute, naming the dtype
    with pytest.raises(ValueError, match="float64"):
        build_program(geom, mesh, panel_chunk=4096, tree="flat",
                      dtype=np.float64)


def test_lu_build_program_dtype_resolves_default_chunk():
    """build_program(dtype=...) must resolve the same default panel_chunk
    as lu_factor_distributed does from its shards, so a --profile build
    returns the SAME cached program the timed run used (ADVICE r3: the
    dtype-blind default built and profiled a different f64 program)."""
    import jax

    from conflux_tpu.geometry import LUGeometry
    from conflux_tpu.lu.distributed import build_program
    from conflux_tpu.ops import blas
    from conflux_tpu.parallel.mesh import make_mesh

    grid = Grid3(1, 1, 1)
    geom = LUGeometry.create(256, 256, 64, grid)
    mesh = make_mesh(grid, devices=jax.devices()[:1])
    for dt in (np.float32, np.float64):
        explicit = build_program(
            geom, mesh,
            panel_chunk=blas.single_call_rows(64, blas.compute_dtype(dt)))
        assert build_program(geom, mesh, dtype=dt) is explicit


def test_lu_distributed_segs_invariant():
    """Trailing-update segmentation partitions the same per-element math:
    any (row, col) segment counts — coarse, odd/ragged, tile-granular —
    must produce the same permutation and a correct factorization."""
    N, v = 64, 8
    A = make_test_matrix(N, N, seed=9)
    base = None
    for segs in [(4, 8), (1, 1), (3, 5), (16, 16)]:
        LU, perm, _ = lu_distributed_host(A, Grid3(2, 2, 2), v, segs=segs)
        res = lu_residual(A, LU[perm], perm)
        assert res < residual_bound(N, np.float64), (segs, res)
        if base is None:
            base = perm
        else:
            np.testing.assert_array_equal(base, perm)


@pytest.mark.parametrize("grid", [Grid3(2, 2, 1), Grid3(4, 2, 1)], ids=str)
@pytest.mark.parametrize("shape", [(64, 32), (32, 64)], ids=["tall", "wide"])
def test_lu_distributed_rectangular(shape, grid):
    """M = 2N and N = 2M (reference `lu_params.hpp:21-47` supports ratio-
    driven rectangular problems; round 1 never tested them distributed)."""
    M, N = shape
    A = make_test_matrix(M, N, seed=M + grid.Px)
    LU, perm, geom = lu_distributed_host(A, grid, 8)
    assert (geom.M, geom.N) == (M, N)
    assert sorted(perm.tolist()) == list(range(M))
    res = lu_residual(A, LU[perm], perm)
    assert res < residual_bound(max(M, N), np.float64), (shape, grid, res)


def test_choose_grid_ratio():
    """Grid auto-pick follows the reference's semantics
    (`lu_params.hpp:21-47`): the 2D plane is stretched toward the matrix
    aspect ratio max(M,N)/min(M,N), orientation-agnostic, Px >= Py >= Pz."""
    from conflux_tpu.geometry import choose_grid

    g = choose_grid(8, 2048, 1024)  # ratio 2
    assert (g.Px, g.Py) == (4, 2), g
    assert choose_grid(8, 1024, 2048) == g  # max/min, like the reference
    g16 = choose_grid(16, 4096, 1024)  # ratio 4
    assert (g16.Px, g16.Py) == (8, 2), g16
    sq = choose_grid(16, 4096, 4096)
    assert sq.Px == sq.Py, sq
    for g in (choose_grid(P, 4096, 1024) for P in (2, 4, 8, 12, 24)):
        assert g.Px >= g.Py >= g.Pz, g


def test_lu_distributed_pivots_are_permutation():
    N, v = 64, 8
    A = make_test_matrix(N, N, seed=9)
    _, perm, _ = lu_distributed_host(A, Grid3(2, 2, 1), v)
    assert sorted(perm.tolist()) == list(range(N))


def test_lu_distributed_needs_pivoting():
    """Zero leading diagonal forces cross-rank pivot movement."""
    N, v = 32, 8
    A = make_test_matrix(N, N, seed=13)
    A[0, 0] = 0.0
    A[9, 9] = 0.0  # row owned by x-rank 1 under 2x2
    LU, perm, _ = lu_distributed_host(A, Grid3(2, 2, 1), v)
    assert np.isfinite(LU).all()
    assert lu_residual(A, LU[perm], perm) < residual_bound(N, np.float64)


def test_lu_distributed_f32():
    N, v = 64, 16
    A = make_test_matrix(N, N, seed=21, dtype=np.float32)
    LU, perm, _ = lu_distributed_host(A, Grid3(2, 2, 1), v)
    assert LU.dtype == np.float32
    assert lu_residual(A, LU[perm], perm) < residual_bound(N, np.float32)


def test_lu_distributed_bf16():
    """bf16 storage with f32 panel math: residual at bf16-eps scale."""
    import jax.numpy as jnp
    from conflux_tpu.geometry import LUGeometry
    from conflux_tpu.lu.distributed import lu_factor_distributed
    from conflux_tpu.parallel.mesh import make_mesh
    import jax

    N, v = 64, 16
    grid = Grid3(2, 2, 1)
    A = make_test_matrix(N, N, seed=3, dtype=np.float32)
    geom = LUGeometry.create(N, N, v, grid)
    mesh = make_mesh(grid, devices=jax.devices()[: grid.P])
    shards = jnp.asarray(geom.scatter(A)).astype(jnp.bfloat16)
    out, perm = lu_factor_distributed(shards, geom, mesh)
    assert out.dtype == jnp.bfloat16
    LUp = geom.gather(np.asarray(out, dtype=np.float64))
    perm = np.asarray(perm)
    res = lu_residual(A, LUp, perm)
    # bf16 eps is ~7.8e-3: accept c*eps*sqrt(N) with modest pivot-growth
    # headroom, reject the f32 regime from below
    eps = 2.0 ** -7
    assert res < 0.5 * eps * np.sqrt(N), res
    assert res > 1e-6  # and it genuinely ran in bf16, not f32


def test_distribute_shards_multihost_entry():
    """`distribute_shards` (the multi-host array-construction entry point)
    must produce shards the factorization consumes identically to a plain
    device_put — single-host semantics of jax.make_array_from_callback."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from conflux_tpu.geometry import Grid3, LUGeometry
    from conflux_tpu.lu.distributed import lu_factor_distributed
    from conflux_tpu.parallel.mesh import distribute_shards, make_mesh
    from conflux_tpu.validation import make_test_matrix

    grid = Grid3(2, 2, 2)
    geom = LUGeometry.create(32, 32, 8, grid)
    mesh = make_mesh(grid, devices=jax.devices()[:8])
    A = make_test_matrix(32, 32, seed=3)
    shards = geom.scatter(A)

    arr = distribute_shards(shards, mesh)
    assert isinstance(arr, jax.Array)
    out_a, piv_a = lu_factor_distributed(arr, geom, mesh)
    out_b, piv_b = lu_factor_distributed(jnp.asarray(shards), geom, mesh)
    np.testing.assert_array_equal(np.asarray(piv_a), np.asarray(piv_b))
    np.testing.assert_allclose(np.asarray(out_a), np.asarray(out_b), rtol=0, atol=0)


def test_distribute_shards_callable_form():
    """Callable form: only per-shard data is requested (the multi-host
    per-rank fill); result must equal the full-array form."""
    import jax
    import numpy as np

    from conflux_tpu.geometry import Grid3, LUGeometry
    from conflux_tpu.parallel.mesh import distribute_shards, make_mesh
    from conflux_tpu.validation import make_test_matrix

    grid = Grid3(2, 2, 2)
    geom = LUGeometry.create(32, 32, 8, grid)
    mesh = make_mesh(grid, devices=jax.devices()[:8])
    A = make_test_matrix(32, 32, seed=4)
    shards = geom.scatter(A)

    calls = []

    def fill(px, py):
        calls.append((px, py))
        return shards[px, py]

    arr = distribute_shards(fill, mesh, shape=shards.shape, dtype=shards.dtype)
    np.testing.assert_array_equal(np.asarray(arr), shards)
    assert set(calls) <= {(px, py) for px in range(2) for py in range(2)}


def test_lu_residual_distributed_matches_host():
    """The on-mesh residual oracle must agree with the host oracle."""
    import jax
    import jax.numpy as jnp

    from conflux_tpu.geometry import LUGeometry
    from conflux_tpu.lu.distributed import lu_factor_distributed
    from conflux_tpu.parallel.mesh import make_mesh
    from conflux_tpu.validation import lu_residual_distributed

    N, v = 64, 8
    for grid in (Grid3(2, 2, 1), Grid3(2, 2, 2), Grid3(4, 2, 1)):
        geom = LUGeometry.create(N, N, v, grid)
        mesh = make_mesh(grid, devices=__import__("jax").devices()[: grid.P])
        A = make_test_matrix(N, N, seed=grid.P)
        A_shards = jnp.asarray(geom.scatter(A))
        out, perm = lu_factor_distributed(A_shards, geom, mesh)
        res_mesh = lu_residual_distributed(A_shards, out, perm, geom, mesh)
        LUp = geom.gather(np.asarray(out))
        res_host = lu_residual(A, LUp, np.asarray(perm))
        assert abs(res_mesh - res_host) < 1e-12 + 0.05 * res_host, (
            grid, res_mesh, res_host)
        assert res_mesh < residual_bound(N, np.float64)


def test_lu_residual_distributed_detects_corruption():
    """The oracle must actually look at the factors: corrupting one tile
    must blow the residual up."""
    import jax.numpy as jnp

    from conflux_tpu.geometry import LUGeometry
    from conflux_tpu.lu.distributed import lu_factor_distributed
    from conflux_tpu.parallel.mesh import make_mesh
    from conflux_tpu.validation import lu_residual_distributed

    N, v = 32, 8
    grid = Grid3(2, 2, 1)
    geom = LUGeometry.create(N, N, v, grid)
    mesh = make_mesh(grid, devices=__import__("jax").devices()[: grid.P])
    A = make_test_matrix(N, N, seed=5)
    A_shards = jnp.asarray(geom.scatter(A))
    out, perm = lu_factor_distributed(A_shards, geom, mesh)
    bad = np.array(out)  # writable copy
    bad[0, 0, :4, :4] += 7.0
    res = lu_residual_distributed(A_shards, jnp.asarray(bad), perm, geom, mesh)
    assert res > 1e-2


@pytest.mark.skipif(
    not __import__("os").environ.get("CONFLUX_SLOW_TESTS"),
    reason="~4 min at-scale run; set CONFLUX_SLOW_TESTS=1 to enable",
)
def test_lu_residual_distributed_at_scale():
    """VERDICT round-1 item 6 'done' bar: validation at N=16384 on the
    8-device CPU mesh without materializing (M, N) on the host — every
    host/device array in the flow is a shard or a scalar."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from conflux_tpu.geometry import LUGeometry
    from conflux_tpu.lu.distributed import lu_factor_distributed
    from conflux_tpu.parallel.mesh import AXIS_X, AXIS_Y, make_mesh
    from conflux_tpu.validation import lu_residual_distributed

    N, v = 16384, 256
    grid = Grid3(4, 2, 1)
    geom = LUGeometry.create(N, N, v, grid)
    mesh = make_mesh(grid, devices=jax.devices()[: grid.P])
    sh = NamedSharding(mesh, P(AXIS_X, AXIS_Y, None, None))

    @jax.jit
    def make_shards():
        # deterministic shards generated directly in block-cyclic form
        a = jax.random.normal(jax.random.PRNGKey(0),
                              (N, N), jnp.float32)
        a = a + 2 * jnp.eye(N, dtype=jnp.float32)
        return jnp.asarray(geom.scatter_blocks(a))

    A_shards = jax.device_put(make_shards(), sh)
    out, perm = lu_factor_distributed(A_shards, geom, mesh)
    res = lu_residual_distributed(A_shards, out, perm, geom, mesh)
    assert res < 1e-3, res


def test_lu_distributed_rank_deficient_leading_block_valid():
    """The documented degenerate contract (`lu_factor_distributed`): once a
    superstep's candidates are exactly zero, that block's outputs are
    unspecified — but everything eliminated BEFORE the degeneracy must be
    correct and frozen. A = blockdiag(B, 0) goes degenerate exactly at
    step r/v; the first r positions must still reconstruct A's rows."""
    import jax

    from conflux_tpu.geometry import LUGeometry
    from conflux_tpu.lu.distributed import lu_factor_distributed
    from conflux_tpu.parallel.mesh import make_mesh
    import jax.numpy as jnp

    grid = Grid3(2, 2, 1)
    v, r, N = 8, 16, 32  # B is (r, r); trailing (N-r) block is zero
    rng = np.random.default_rng(11)
    A = np.zeros((N, N), np.float32)
    A[:r, :r] = (rng.standard_normal((r, r)) + 2 * np.eye(r)).astype(np.float32)
    geom = LUGeometry.create(N, N, v, grid)
    mesh = make_mesh(grid, devices=jax.devices()[: grid.P])
    out, perm = lu_factor_distributed(jnp.asarray(geom.scatter(A)), geom, mesh)
    LUp = geom.gather(np.asarray(out))
    p = np.asarray(perm)
    # valid prefix: positions < r hold frozen factor rows of A[p[:r]]
    L = np.tril(LUp, -1) + np.eye(N, dtype=np.float64)
    U = np.triu(LUp).astype(np.float64)
    lead = (L[:r, :r] @ U[:r, :]).astype(np.float64)
    num = np.linalg.norm(A[p[:r]] - lead)
    assert num / np.linalg.norm(A) < 1e-5, num
    # and those perm entries name distinct rows of the nonzero block
    assert sorted(p[:r]) == list(range(r))


@pytest.mark.parametrize("gridspec", [(1, 1, 1), (2, 2, 1), (2, 2, 2),
                                      (4, 2, 1)])
def test_lu_distributed_lookahead_bitwise_equal(gridspec):
    """The software-pipelined (lookahead) loop must be bitwise identical
    to the plain loop: the carried panel is computed from the same
    operands with the same contraction depth as the recomputed one."""
    import jax
    import jax.numpy as jnp

    from conflux_tpu.geometry import LUGeometry
    from conflux_tpu.lu.distributed import lu_factor_distributed
    from conflux_tpu.parallel.mesh import make_mesh

    grid = Grid3(*gridspec)
    v, N = 8, 64
    geom = LUGeometry.create(N, N, v, grid)
    mesh = make_mesh(grid, devices=jax.devices()[: grid.P])
    A = make_test_matrix(N, N, dtype=np.float32)
    shards = jnp.asarray(geom.scatter(A))

    out_a, perm_a = lu_factor_distributed(shards, geom, mesh)
    out_b, perm_b = lu_factor_distributed(shards, geom, mesh,
                                          lookahead=True)
    np.testing.assert_array_equal(np.asarray(perm_a), np.asarray(perm_b))
    np.testing.assert_allclose(np.asarray(out_a), np.asarray(out_b),
                               rtol=0, atol=0)


def test_lu_distributed_butterfly_election():
    """The ppermute hypercube election (reference `conflux_opt.hpp:220-336`
    structure: log2(Px) rounds of (2v, v) reductions) must produce a
    residual-correct factorization with a valid permutation — also under
    lookahead (the miniapp exposes the combination) and on
    non-power-of-two Px, where the overflow ranks fold in/out of the
    subcube (the reference's odd-grid compensating sends,
    `conflux_opt.hpp:266-280`). CALU pivot sets are bracket-dependent,
    so butterfly and gather may elect different, equally valid pivots."""
    import jax
    import jax.numpy as jnp

    from conflux_tpu.geometry import LUGeometry
    from conflux_tpu.lu.distributed import lu_factor_distributed
    from conflux_tpu.parallel.mesh import make_mesh

    N, v = 128, 8
    A = make_test_matrix(N, N, seed=97)
    for gridspec, la in [((2, 2, 1), False), ((4, 2, 1), False),
                         ((2, 1, 2), False), ((4, 2, 1), True),
                         ((3, 1, 1), False), ((3, 2, 1), False),
                         ((5, 1, 1), False), ((3, 2, 1), True)]:
        grid = Grid3(*gridspec)
        geom = LUGeometry.create(N, N, v, grid)
        mesh = make_mesh(grid, devices=jax.devices()[: grid.P])
        host_shards = geom.scatter(A)
        # odd grids pad (e.g. Px=3: M 128 -> 144 with an identity tail);
        # validate the padded problem the kernel actually factors
        Ap = geom.gather(host_shards)
        shards = jnp.asarray(host_shards)
        out, perm = lu_factor_distributed(shards, geom, mesh,
                                          election="butterfly",
                                          lookahead=la)
        perm = np.asarray(perm)
        assert sorted(perm.tolist()) == list(range(geom.M)), (gridspec, la)
        LUp = geom.gather(np.asarray(out))
        res = lu_residual(Ap, LUp, perm)
        assert res < residual_bound(N, np.float64), (gridspec, la, res)
        res_g = None
        if not la:
            out_g, perm_g = lu_factor_distributed(shards, geom, mesh)
            res_g = lu_residual(Ap, geom.gather(np.asarray(out_g)),
                                np.asarray(perm_g))
            assert res_g < residual_bound(N, np.float64), (gridspec, res_g)


@pytest.mark.parametrize("grid", [Grid3(1, 1, 1), Grid3(2, 2, 1),
                                  Grid3(2, 2, 2), Grid3(4, 2, 1)], ids=str)
def test_lu_distributed_block_update(grid):
    """update='block' (one lax.switch live-suffix GEMM per step instead of
    the cond'd segment lattice) partitions the same per-element math:
    same pivots, residual-correct factors, across grids incl. 2.5D and
    many-superstep shapes."""
    import jax
    import jax.numpy as jnp

    from conflux_tpu.geometry import LUGeometry
    from conflux_tpu.lu.distributed import lu_factor_distributed
    from conflux_tpu.parallel.mesh import make_mesh

    N, v = 128, 8
    geom = LUGeometry.create(N, N, v, grid)
    mesh = make_mesh(grid, devices=jax.devices()[: grid.P])
    A = make_test_matrix(N, N, seed=7, dtype=np.float32)
    shards = jnp.asarray(geom.scatter(A))

    out_s, perm_s = lu_factor_distributed(shards, geom, mesh, segs=(4, 4))
    out_b, perm_b = lu_factor_distributed(shards, geom, mesh, segs=(4, 4),
                                          update="block")
    np.testing.assert_array_equal(np.asarray(perm_s), np.asarray(perm_b))
    LUp = geom.gather(np.asarray(out_b))
    p = np.asarray(perm_b)
    res = lu_residual(A, LUp, p)
    assert res < residual_bound(N, np.float32), (grid, res)


def test_lu_distributed_block_update_bench_ratios():
    """The block update at the headline bench's structural ratios (32
    supersteps, multi-chunk nomination, 16x16 boundaries) — the shape
    where bucket transitions and the final fully-dead clamp all occur."""
    N, v = 256, 8
    A = make_test_matrix(N, N, seed=2, dtype=np.float32)
    LU, perm, _ = lu_distributed_host(A, Grid3(1, 1, 1), v, panel_chunk=64,
                                      update="block")
    assert sorted(perm.tolist()) == list(range(N))
    assert lu_residual(A, LU[perm], perm) < residual_bound(N, np.float32)


def test_lu_distributed_block_update_lookahead():
    """update='block' composes with the software-pipelined loop. Unlike
    segments (whose lookahead mirror is bitwise-identical, asserted in
    test_lu_distributed_lookahead_bitwise_equal), the block path's ONE
    wide suffix GEMM may round differently from the mirror's narrow slab
    GEMM (shape-dependent kernel accumulation) — so the contract here is
    value-level: identical pivots, f32-noise-level factors, correct
    residual."""
    import jax
    import jax.numpy as jnp

    from conflux_tpu.geometry import LUGeometry
    from conflux_tpu.lu.distributed import lu_factor_distributed
    from conflux_tpu.parallel.mesh import make_mesh

    grid = Grid3(2, 2, 1)
    N, v = 64, 8
    geom = LUGeometry.create(N, N, v, grid)
    mesh = make_mesh(grid, devices=jax.devices()[: grid.P])
    A = make_test_matrix(N, N, dtype=np.float32)
    shards = jnp.asarray(geom.scatter(A))

    out_a, perm_a = lu_factor_distributed(shards, geom, mesh,
                                          update="block")
    out_b, perm_b = lu_factor_distributed(shards, geom, mesh,
                                          update="block", lookahead=True)
    np.testing.assert_array_equal(np.asarray(perm_a), np.asarray(perm_b))
    np.testing.assert_allclose(np.asarray(out_a), np.asarray(out_b),
                               atol=1e-4)
    LUp = geom.gather(np.asarray(out_b))
    p = np.asarray(perm_b)
    assert lu_residual(A, LUp, p) < residual_bound(N, np.float32)
