"""QR family: blocked single-device, distributed TSQR, CholeskyQR2.

Oracles: A = Q R reconstruction, ||Q^T Q - I|| orthogonality at eps
scale, and agreement with np.linalg.qr under the positive-diagonal
normalization (which makes thin QR of a full-rank matrix unique)."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from conflux_tpu.geometry import Grid3
from conflux_tpu.parallel.mesh import make_mesh
from conflux_tpu.qr import (
    cholesky_qr2_distributed,
    qr_distributed_host,
    qr_factor_blocked,
    tall_qr,
    tsqr_distributed,
)


def _orth_err(Q):
    n = Q.shape[1]
    return np.linalg.norm(Q.T @ Q - np.eye(n)) / np.sqrt(n)


def _check(A, Q, R, eps_mult=50):
    M, n = A.shape
    # eps of the COMPUTE dtype (Q/R), not the oracle copy of A
    eps = np.finfo(np.float32 if np.asarray(Q).dtype == np.float32
                   else np.float64).eps
    assert np.allclose(np.tril(R, -1), 0.0), "R not upper-triangular"
    assert (np.diag(R) >= 0).all(), "R diagonal not normalized positive"
    assert _orth_err(np.asarray(Q, np.float64)) < eps_mult * eps
    rec = np.linalg.norm(np.asarray(Q, np.float64) @ R - A)
    assert rec / np.linalg.norm(A) < eps_mult * eps * np.sqrt(n)


def _pos_diag_ref(A):
    Qr, Rr = np.linalg.qr(A)
    s = np.sign(np.diag(Rr))
    s[s == 0] = 1
    return Qr * s, Rr * s[:, None]


@pytest.mark.parametrize("shape", [(96, 96), (192, 64), (64, 50)])
def test_qr_blocked_single(shape):
    M, N = shape
    rng = np.random.default_rng(3)
    A = rng.standard_normal(shape)
    Q, R = qr_factor_blocked(jnp.asarray(A), v=16)
    _check(A, np.asarray(Q), np.asarray(R))
    Qr, Rr = _pos_diag_ref(A)
    np.testing.assert_allclose(np.asarray(R), Rr, atol=1e-10 * np.abs(Rr).max())


def test_tall_qr_chunked_tree():
    """Chunked tree (several levels) must agree with the unchunked path."""
    rng = np.random.default_rng(5)
    A = rng.standard_normal((640, 24))
    Q1, R1 = tall_qr(jnp.asarray(A), chunk=64)   # 10 chunks, 2 levels
    Q2, R2 = tall_qr(jnp.asarray(A), chunk=4096)  # single call
    _check(A, np.asarray(Q1), np.asarray(R1))
    np.testing.assert_allclose(np.asarray(R1), np.asarray(R2),
                               atol=1e-10 * np.abs(R2).max())


def test_tall_qr_ill_conditioned():
    """The tree path must keep eps-grade orthogonality where plain
    CholeskyQR would have lost it (cond^2 overflows f64 eps^-1 is not
    reachable here; cond 1e8 squares to 1e16 ~ 1/eps_f64, the classic
    breakdown)."""
    rng = np.random.default_rng(7)
    U, _ = np.linalg.qr(rng.standard_normal((256, 24)))
    V, _ = np.linalg.qr(rng.standard_normal((24, 24)))
    s = np.logspace(0, -8, 24)
    A = (U * s) @ V.T
    Q, R = tall_qr(jnp.asarray(A), chunk=64)
    _check(A, np.asarray(Q), np.asarray(R), eps_mult=200)


@pytest.mark.parametrize("Px", [1, 2, 4])
def test_tsqr_distributed(Px):
    rng = np.random.default_rng(11 + Px)
    M, n = 64 * Px, 24
    A = rng.standard_normal((M, n))
    mesh = make_mesh(Grid3(Px, 1, 1), devices=jax.devices()[:Px])
    Qs, R = tsqr_distributed(A.reshape(Px, M // Px, n), mesh)
    Q = np.asarray(Qs).reshape(M, n)
    _check(A, Q, np.asarray(R))


def test_tsqr_matches_across_grids():
    """Same matrix, Px = 1 vs 4: identical R (replicated reduction is
    deterministic) and equally-orthogonal Q."""
    rng = np.random.default_rng(13)
    A = rng.standard_normal((128, 16))
    _, R1 = qr_distributed_host(A, 1)
    _, R4 = qr_distributed_host(A, 4)
    np.testing.assert_allclose(R1, R4, atol=1e-12 * np.abs(R1).max())


def test_cholesky_qr2_distributed():
    rng = np.random.default_rng(17)
    Px, Ml, n = 4, 32, 16
    A = rng.standard_normal((Px * Ml, n))
    mesh = make_mesh(Grid3(Px, 1, 1), devices=jax.devices()[:Px])
    Qs, R = cholesky_qr2_distributed(A.reshape(Px, Ml, n), mesh)
    _check(A, np.asarray(Qs).reshape(-1, n), np.asarray(R))


def test_qr_distributed_host_padding():
    """M not divisible by Px: zero-pad rows, drop them from Q."""
    rng = np.random.default_rng(19)
    A = rng.standard_normal((50, 8))
    Q, R = qr_distributed_host(A, 4)
    assert Q.shape == (50, 8)
    _check(A, Q, R)


def test_qr_f32():
    rng = np.random.default_rng(23)
    A = rng.standard_normal((128, 32)).astype(np.float32)
    Q, R = qr_factor_blocked(jnp.asarray(A), v=16)
    assert Q.dtype == np.float32 and R.dtype == np.float32
    _check(A.astype(np.float64), np.asarray(Q), np.asarray(R),
           eps_mult=100)


def test_qr_rejects_wide():
    with pytest.raises(ValueError):
        qr_factor_blocked(jnp.zeros((8, 16)))
    with pytest.raises(ValueError):
        tall_qr(jnp.zeros((8, 16)))


@pytest.mark.parametrize("gridspec", [(1, 1, 1), (2, 2, 1), (2, 2, 2),
                                      (4, 2, 1)])
def test_qr_factor_distributed(gridspec):
    """Full block-cyclic distributed QR on the 2.5D mesh: A = Q R,
    eps-grade orthogonality, R matches the single-device factorization
    under the positive-diagonal normalization."""
    from conflux_tpu.qr.distributed import qr_blocked_distributed_host

    grid = Grid3(*gridspec)
    N, v = 64, 8
    rng = np.random.default_rng(29 + grid.P)
    A = rng.standard_normal((N, N))
    Q, R, geom = qr_blocked_distributed_host(A, grid, v)
    _check(A, Q, R)
    Qr, Rr = _pos_diag_ref(A)
    np.testing.assert_allclose(R, Rr, atol=1e-9 * np.abs(Rr).max())


def test_qr_factor_distributed_rectangular():
    from conflux_tpu.qr.distributed import qr_blocked_distributed_host

    grid = Grid3(2, 2, 1)
    M, N, v = 128, 48, 8
    rng = np.random.default_rng(41)
    A = rng.standard_normal((M, N))
    Q, R, _ = qr_blocked_distributed_host(A, grid, v)
    assert Q.shape == (M, N) and R.shape == (N, N)
    _check(A, Q, R)


def test_qr_factor_distributed_matches_tall_qr():
    """The general loop on a 1x1x1 mesh agrees with tall_qr on the same
    matrix (both two-pass TSQR with positive-diag normalization)."""
    from conflux_tpu.qr.distributed import qr_blocked_distributed_host

    rng = np.random.default_rng(43)
    A = rng.standard_normal((96, 16))
    Q1, R1 = tall_qr(jnp.asarray(A), chunk=64)
    Q2, R2, _ = qr_blocked_distributed_host(A, Grid3(1, 1, 1), 16)
    np.testing.assert_allclose(np.asarray(R1), R2,
                               atol=1e-10 * np.abs(R2).max())


def test_qr_factor_distributed_ragged_r_rows():
    """Nt not a multiple of Px: R's block-cyclic row padding must be
    sliced off so the (N, N) contract holds (regression: a (4, 2, 1)
    grid with 6 column tiles used to return R as (64, 48))."""
    from conflux_tpu.qr.distributed import qr_blocked_distributed_host

    rng = np.random.default_rng(47)
    A = rng.standard_normal((96, 48))
    Q, R, _ = qr_blocked_distributed_host(A, Grid3(4, 2, 1), 8)
    assert Q.shape == (96, 48) and R.shape == (48, 48)
    _check(A, Q, R)


@pytest.mark.parametrize("shape", [(50, 20), (40, 40), (70, 33)])
def test_qr_factor_distributed_ragged_sizes(shape):
    """Non-grid-multiple sizes go through the block-diagonal identity
    extension (QR(blockdiag(A, I)) = blockdiag(Q, I) blockdiag(R, I)),
    returning exact original-shape factors."""
    from conflux_tpu.qr.distributed import qr_blocked_distributed_host

    M, N = shape
    rng = np.random.default_rng(M + N)
    A = rng.standard_normal(shape)
    Q, R, _ = qr_blocked_distributed_host(A, Grid3(2, 2, 1), 8)
    assert Q.shape == (M, N) and R.shape == (N, N)
    _check(A, Q, R)
    Qr, Rr = _pos_diag_ref(A)
    np.testing.assert_allclose(R, Rr, atol=1e-9 * np.abs(Rr).max())


def test_qr_factor_distributed_bf16():
    """bf16 storage with f32 panel/TSQR math: the trailing GEMMs ride the
    storage dtype (the LU loop's bf16 fast-path contract)."""
    from conflux_tpu.geometry import LUGeometry
    from conflux_tpu.qr.distributed import qr_factor_distributed, r_geometry
    from conflux_tpu.parallel.mesh import make_mesh

    N, v = 64, 8
    grid = Grid3(2, 2, 1)
    rng = np.random.default_rng(89)
    A = rng.standard_normal((N, N)).astype(np.float32)
    geom = LUGeometry.create(N, N, v, grid)
    mesh = make_mesh(grid, devices=jax.devices()[: grid.P])
    shards = jnp.asarray(geom.scatter(A)).astype(jnp.bfloat16)
    Qs, Rs = qr_factor_distributed(shards, geom, mesh)
    assert Qs.dtype == jnp.bfloat16 and Rs.dtype == jnp.bfloat16
    Q = geom.gather(np.asarray(Qs, np.float64))
    R = np.triu(r_geometry(geom).gather(np.asarray(Rs, np.float64))[:N])
    eps = 2.0 ** -7  # bf16
    rec = np.linalg.norm(Q @ R - A) / np.linalg.norm(A)
    assert rec < 0.5 * eps * np.sqrt(N), rec
    assert rec > 1e-6  # genuinely ran in bf16
    orth = np.linalg.norm(Q.T @ Q - np.eye(N)) / np.sqrt(N)
    assert orth < 0.5 * eps * np.sqrt(N), orth


def test_qr_residual_distributed_matches_host():
    """The on-mesh QR oracle must agree with host oracles and detect
    corruption."""
    from conflux_tpu.geometry import LUGeometry
    from conflux_tpu.qr.distributed import qr_factor_distributed, r_geometry
    from conflux_tpu.validation import qr_residual_distributed

    N, v = 64, 8
    for gridspec in [(2, 2, 1), (2, 2, 2), (4, 2, 1)]:
        grid = Grid3(*gridspec)
        geom = LUGeometry.create(N, N, v, grid)
        mesh = make_mesh(grid, devices=jax.devices()[: grid.P])
        rng = np.random.default_rng(grid.P)
        A = rng.standard_normal((N, N)).astype(np.float64)
        A_shards = jnp.asarray(geom.scatter(A))
        Qs, Rs = qr_factor_distributed(A_shards, geom, mesh)
        res, orth = qr_residual_distributed(A_shards, Qs, Rs, geom, mesh)
        # host oracles
        Q = geom.gather(np.asarray(Qs))
        R = np.triu(r_geometry(geom).gather(np.asarray(Rs))[:N])
        res_h = np.linalg.norm(Q @ R - A) / np.linalg.norm(A)
        orth_h = np.linalg.norm(Q.T @ Q - np.eye(N)) / np.sqrt(N)
        assert abs(res - res_h) < 1e-12 + 0.05 * res_h, (gridspec, res, res_h)
        assert abs(orth - orth_h) < 1e-12 + 0.05 * orth_h, (gridspec, orth, orth_h)
        assert res < 1e-13 and orth < 1e-13

    # corruption must blow both up
    bad = np.array(Qs)
    bad[0, 0, :4, :4] += 5.0
    res, orth = qr_residual_distributed(A_shards, jnp.asarray(bad), Rs,
                                        geom, mesh)
    assert res > 1e-2 and orth > 1e-2


def test_tsqr_butterfly_tree():
    """The ppermute hypercube TSQR reduction must agree with the gather
    tree bitwise (QR tree reductions are bracket-dependent in general,
    but the butterfly's pair order over 4 ranks reduces (0,1),(2,3) then
    pairs of pairs — same shape as the gather path's chunked reduction
    of the 4-stack, and the positive-diag normalization makes R unique
    regardless); non-power-of-two Px folds its overflow ranks through
    the subcube (different bracket, so compare by QR validity, not
    bitwise)."""
    rng = np.random.default_rng(101)
    Px, Ml, n = 4, 48, 16
    A = rng.standard_normal((Px * Ml, n))
    mesh = make_mesh(Grid3(Px, 1, 1), devices=jax.devices()[:Px])
    Qb, Rb = tsqr_distributed(A.reshape(Px, Ml, n), mesh, tree="butterfly")
    _check(A, np.asarray(Qb).reshape(-1, n), np.asarray(Rb))
    _, Rg = tsqr_distributed(A.reshape(Px, Ml, n), mesh)
    np.testing.assert_allclose(np.asarray(Rb), np.asarray(Rg),
                               atol=1e-10 * np.abs(np.asarray(Rg)).max())

    for Px3 in (3, 5, 6):
        mesh3 = make_mesh(Grid3(Px3, 1, 1), devices=jax.devices()[:Px3])
        A3 = rng.standard_normal((Px3 * 32, 8))
        Q3, R3 = tsqr_distributed(A3.reshape(Px3, 32, 8), mesh3,
                                  tree="butterfly")
        _check(A3, np.asarray(Q3).reshape(-1, 8), np.asarray(R3))


@pytest.mark.parametrize("gridspec", [(1, 1, 1), (2, 2, 1), (2, 2, 2),
                                      (4, 2, 1)])
def test_qr_factor_distributed_lookahead_bitwise_equal(gridspec):
    """The software-pipelined (lookahead) QR loop must be bitwise
    identical to the plain loop: the carried panel mirrors the segment
    update operand-for-operand, and the re-projection source (A_q) holds
    exactly the post-step values at every done column."""
    import jax

    from conflux_tpu.geometry import LUGeometry
    from conflux_tpu.parallel.mesh import make_mesh
    from conflux_tpu.qr.distributed import qr_factor_distributed

    grid = Grid3(*gridspec)
    N, v = 64, 8
    geom = LUGeometry.create(N, N, v, grid)
    mesh = make_mesh(grid, devices=jax.devices()[: grid.P])
    rng = np.random.default_rng(41 + grid.P)
    A = rng.standard_normal((N, N)).astype(np.float32)
    shards = jnp.asarray(geom.scatter(A))

    Qa, Ra = qr_factor_distributed(shards, geom, mesh)
    Qb, Rb = qr_factor_distributed(shards, geom, mesh, lookahead=True)
    np.testing.assert_allclose(np.asarray(Qa), np.asarray(Qb),
                               rtol=0, atol=0)
    np.testing.assert_allclose(np.asarray(Ra), np.asarray(Rb),
                               rtol=0, atol=0)


def test_qr_build_program_dtype_resolves_default_chunk():
    """build_program(dtype=...) must resolve the same default TSQR chunk
    as qr_factor_distributed does from its shards, so the qr_miniapp
    --profile build returns the SAME cached program the timed run used
    (ADVICE r3: the dtype-blind default profiled a different f64
    program)."""
    from conflux_tpu.geometry import LUGeometry
    from conflux_tpu.ops import blas
    from conflux_tpu.qr.distributed import build_program

    grid = Grid3(1, 1, 1)
    geom = LUGeometry.create(256, 256, 64, grid)
    mesh = make_mesh(grid, devices=jax.devices()[:1])
    for dt in (np.float32, np.float64):
        explicit = build_program(
            geom, mesh,
            chunk=blas.batched_call_rows(64, blas.compute_dtype(dt)))
        assert build_program(geom, mesh, dtype=dt) is explicit
