"""Batched Pallas factor kernels: the ISSUE 14 contracts (DESIGN §29).

- `ops.pallas_factor.pallas_lu_factor_batched` elects the SAME pivot
  permutation as `lax.linalg.lu` and reconstructs A[perm] = L @ U across
  dtypes (f32/f64, f64 interpret-only) and shapes (N in {8, 48, 64,
  256} x B in {1, 4, 32}) — N=48 exercises the power-of-two identity
  tail; the Cholesky kernel reconstructs L @ L^T = A on SPD batches.
- Identity slots factor to EXACT bits (LU == I, perm == arange,
  L == I) — what makes identity pad slots free.
- Per-slot kernel outputs are bitwise invariant to the kernel batch
  size and to the pad contents (grid slots never interact), and the
  fused probe row (`probe_w=`) is bit-neutral to the factors.
- The `ops.blas` registry entries resolve `backend=` (XLA vmapped
  `lax.linalg.lu` / `lax.linalg.cholesky` default, kernel on 'pallas')
  and `batched.lu_factor_batched` / `cholesky_factor_batched` route
  eligible calls (mesh-less, f32/f64) to the kernel.
- Serve wiring: a `backend='pallas'` plan's stacked factor programs
  keep the §21 bucket/pad bitwise-invariance contract, `plan.factor`
  matches the CHECKED coalesced program bitwise, the fused Dinv blocks
  equal a second `diag_block_inverses` pass over the kernel's LU, the
  in-kernel Freivalds verdict agrees with the XLA-backend
  `_factor_health_fn` (healthy AND forced-unhealthy slots), a poisoned
  slot trips alone with its neighbors' factors bitwise untouched, and
  steady-state bucket calls re-trace NOTHING. Ineligible keys
  (factor_dtype != dtype) fall back to the vmapped XLA body.
- Engine end-to-end: coalesced cold starts on a pallas plan solve
  bitwise identically to `plan.factor` sessions.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax import lax

from conflux_tpu import serve
from conflux_tpu.batched import cholesky_factor_batched, lu_factor_batched
from conflux_tpu.engine import ServeEngine
from conflux_tpu.ops import blas
from conflux_tpu.ops import pallas_factor as pf
from conflux_tpu.ops.batched_trsm import diag_block_inverses
from conflux_tpu.resilience import HealthPolicy


def _gen(rng, b, n, dtype):
    return (rng.standard_normal((b, n, n)) / np.sqrt(n)
            + 2.0 * np.eye(n)).astype(dtype)


def _spd(rng, b, n, dtype):
    G = rng.standard_normal((b, n, n))
    return (G @ np.swapaxes(G, -1, -2) / n
            + 2.0 * np.eye(n)).astype(dtype)


def _unpack(LU):
    n = LU.shape[-1]
    L = np.tril(LU, -1) + np.eye(n, dtype=LU.dtype)
    return L, np.triu(LU)


# --------------------------------------------------------------------- #
# the kernels vs the LAPACK oracles
# --------------------------------------------------------------------- #

_GRID = [
    (np.float32, 8, 1), (np.float32, 8, 4), (np.float32, 8, 32),
    (np.float32, 48, 1), (np.float32, 48, 4), (np.float32, 48, 32),
    (np.float32, 64, 1), (np.float32, 64, 4), (np.float32, 64, 32),
    (np.float32, 256, 1),
    (np.float64, 8, 4), (np.float64, 48, 1), (np.float64, 64, 32),
]
# N=256 interpret-mode cells run ~13 s each — slow lane
_GRID_SLOW = [(np.float32, 256, 4), (np.float32, 256, 32),
              (np.float64, 256, 1)]


def _check_lu_cell(dtype, n, b):
    rng = np.random.default_rng(7 * n + b)
    A = _gen(rng, b, n, dtype)
    LU, perm = pf.pallas_lu_factor_batched(A)
    assert LU.dtype == jnp.dtype(dtype) and perm.shape == (b, n)
    # same pivot elections as the oracle (no ties on gaussian data)
    _lu, _piv, operm = jax.vmap(lax.linalg.lu)(jnp.asarray(A))
    np.testing.assert_array_equal(np.asarray(perm), np.asarray(operm))
    # reconstruction: A[perm] = L @ U per slot (accumulated in f64)
    tol = 5e-4 if dtype == np.float32 else 1e-10
    LUn = np.asarray(LU, np.float64)
    pn = np.asarray(perm)
    for i in range(b):
        L, U = _unpack(LUn[i])
        np.testing.assert_allclose(L @ U, A[i][pn[i]].astype(np.float64),
                                   atol=tol, err_msg=f"slot {i}")


def _check_chol_cell(dtype, n, b):
    rng = np.random.default_rng(11 * n + b)
    A = _spd(rng, b, n, dtype)
    L = pf.pallas_cholesky_factor_batched(A)
    Ln = np.asarray(L, np.float64)
    # strictly-upper parts are literal zeros (the contract downstream
    # blocked substitution relies on)
    assert (np.triu(Ln, 1) == 0.0).all()
    tol = 5e-4 if dtype == np.float32 else 1e-10
    np.testing.assert_allclose(Ln @ np.swapaxes(Ln, -1, -2),
                               A.astype(np.float64), atol=tol)
    ref = lax.linalg.cholesky(jnp.asarray(A), symmetrize_input=False)
    np.testing.assert_allclose(Ln, np.asarray(ref, np.float64), atol=tol)


@pytest.mark.parametrize("dtype,n,b", _GRID)
def test_lu_kernel_matches_oracle(dtype, n, b):
    _check_lu_cell(dtype, n, b)


@pytest.mark.slow
@pytest.mark.parametrize("dtype,n,b", _GRID_SLOW)
def test_lu_kernel_matches_oracle_slow(dtype, n, b):
    _check_lu_cell(dtype, n, b)


@pytest.mark.parametrize("dtype,n,b", [
    (np.float32, 8, 4), (np.float32, 48, 4), (np.float32, 64, 32),
    (np.float32, 256, 1), (np.float64, 64, 4)])
def test_cholesky_kernel_matches_oracle(dtype, n, b):
    _check_chol_cell(dtype, n, b)


@pytest.mark.slow
@pytest.mark.parametrize("dtype,n,b", [(np.float32, 256, 32)])
def test_cholesky_kernel_matches_oracle_slow(dtype, n, b):
    _check_chol_cell(dtype, n, b)


def test_identity_slots_factor_to_exact_bits():
    """Identity matrices factor with NO rounding: LU == I and
    perm == arange bitwise (likewise L == I for Cholesky) — the
    property that makes identity pad slots free in the factor lane."""
    rng = np.random.default_rng(3)
    eye = np.eye(64, dtype=np.float32)
    A = np.stack([_gen(rng, 1, 64, np.float32)[0], eye])
    LU, perm = pf.pallas_lu_factor_batched(A)
    np.testing.assert_array_equal(np.asarray(LU)[1], eye)
    np.testing.assert_array_equal(np.asarray(perm)[1], np.arange(64))
    L = pf.pallas_cholesky_factor_batched(A[1:])
    np.testing.assert_array_equal(np.asarray(L)[0], eye)


def test_kernel_bucket_and_pad_bitwise_invariance():
    """Slot i's outputs are bitwise invariant to the kernel batch size
    (B=1 rides the batch-floor pad) and to the other slots' contents —
    grid slots never interact."""
    rng = np.random.default_rng(29)
    A = _gen(rng, 4, 48, np.float32)
    junk = 1e3 * rng.standard_normal((3, 48, 48)).astype(np.float32)
    LU1, p1 = pf.pallas_lu_factor_batched(A[:1])
    LU4, p4 = pf.pallas_lu_factor_batched(A)
    LUj, pj = pf.pallas_lu_factor_batched(
        np.concatenate([A[:1], junk]))
    np.testing.assert_array_equal(np.asarray(LU1)[0], np.asarray(LU4)[0])
    np.testing.assert_array_equal(np.asarray(p1)[0], np.asarray(p4)[0])
    np.testing.assert_array_equal(np.asarray(LU1)[0], np.asarray(LUj)[0])
    np.testing.assert_array_equal(np.asarray(p1)[0], np.asarray(pj)[0])
    S = _spd(rng, 4, 48, np.float32)
    L1 = pf.pallas_cholesky_factor_batched(S[:1])
    L4 = pf.pallas_cholesky_factor_batched(S)
    np.testing.assert_array_equal(np.asarray(L1)[0], np.asarray(L4)[0])


def test_probe_row_is_bit_neutral_and_correct():
    """`probe_w=` adds the step-0 wA dot WITHOUT touching the
    elimination: factors/pivots keep their exact bits, and wA equals
    w^T A to accumulator precision."""
    rng = np.random.default_rng(31)
    A = _gen(rng, 4, 48, np.float32)
    w = np.sign(rng.standard_normal(48)).astype(np.float32)
    LU0, p0 = pf.pallas_lu_factor_batched(A)
    LU1, p1, wa = pf.pallas_lu_factor_batched(A, probe_w=w)
    np.testing.assert_array_equal(np.asarray(LU0), np.asarray(LU1))
    np.testing.assert_array_equal(np.asarray(p0), np.asarray(p1))
    np.testing.assert_allclose(np.asarray(wa, np.float64),
                               w.astype(np.float64) @ A.astype(np.float64),
                               rtol=1e-4, atol=1e-4)
    S = _spd(rng, 2, 48, np.float32)
    L0 = pf.pallas_cholesky_factor_batched(S)
    L1, wa = pf.pallas_cholesky_factor_batched(S, probe_w=w)
    np.testing.assert_array_equal(np.asarray(L0), np.asarray(L1))
    np.testing.assert_allclose(np.asarray(wa, np.float64),
                               w.astype(np.float64) @ S.astype(np.float64),
                               rtol=1e-4, atol=1e-4)


def test_kernel_rejects_bad_shapes():
    with pytest.raises(ValueError, match="batched factor"):
        pf.pallas_lu_factor_batched(np.eye(8, dtype=np.float32))
    with pytest.raises(ValueError, match="batched factor"):
        pf.pallas_cholesky_factor_batched(
            np.zeros((2, 8, 4), np.float32))


# --------------------------------------------------------------------- #
# registry + batched entry-point routing
# --------------------------------------------------------------------- #


def test_blas_registry_resolves_backend():
    """`blas.batched_lu_factor` / `batched_cholesky_factor` honor
    `backend=`: the XLA default is the vmapped LAPACK oracle verbatim,
    and 'pallas' lands on the kernel with the same pivots."""
    rng = np.random.default_rng(37)
    A = _gen(rng, 4, 64, np.float32)
    LUx, px = blas.batched_lu_factor(A)  # module backend (xla)
    olu, _p, op = jax.vmap(lax.linalg.lu)(jnp.asarray(A))
    np.testing.assert_array_equal(np.asarray(LUx), np.asarray(olu))
    np.testing.assert_array_equal(np.asarray(px), np.asarray(op))
    LUp, pp = blas.batched_lu_factor(A, backend="pallas")
    np.testing.assert_array_equal(np.asarray(pp), np.asarray(px))
    np.testing.assert_allclose(np.asarray(LUp), np.asarray(LUx),
                               rtol=1e-4, atol=1e-5)
    # probe rows ride both backends
    w = np.sign(rng.standard_normal(64)).astype(np.float32)
    *_x, wax = blas.batched_lu_factor(A, probe_w=w)
    *_p, wap = blas.batched_lu_factor(A, probe_w=w, backend="pallas")
    np.testing.assert_allclose(np.asarray(wax), np.asarray(wap),
                               rtol=1e-4, atol=1e-4)
    S = _spd(rng, 2, 64, np.float32)
    Lx = blas.batched_cholesky_factor(S)
    Lp = blas.batched_cholesky_factor(S, backend="pallas")
    np.testing.assert_allclose(np.asarray(Lp), np.asarray(Lx),
                               rtol=1e-4, atol=1e-5)


def test_ops_exports_registry_entries():
    import conflux_tpu.ops as ops

    assert ops.batched_lu_factor is blas.batched_lu_factor
    assert ops.batched_cholesky_factor is blas.batched_cholesky_factor


def test_batched_entry_points_route_to_kernel():
    """`lu_factor_batched(..., backend='pallas')` (mesh-less, f32) is
    the kernel bitwise; the XLA route still answers and the tile-size
    guard still fires."""
    rng = np.random.default_rng(41)
    A = _gen(rng, 3, 64, np.float32)
    LU, perm = lu_factor_batched(A, 16, backend="pallas")
    kLU, kperm = pf.pallas_lu_factor_batched(A)
    np.testing.assert_array_equal(np.asarray(LU), np.asarray(kLU))
    np.testing.assert_array_equal(np.asarray(perm), np.asarray(kperm))
    LUx, permx = lu_factor_batched(A, 16)
    np.testing.assert_allclose(np.asarray(LU), np.asarray(LUx),
                               rtol=1e-4, atol=1e-5)
    S = _spd(rng, 2, 64, np.float32)
    L = cholesky_factor_batched(S, 16, backend="pallas")
    np.testing.assert_array_equal(
        np.asarray(L), np.asarray(pf.pallas_cholesky_factor_batched(S)))
    with pytest.raises(ValueError, match="tile size"):
        lu_factor_batched(A, 48, backend="pallas")


# --------------------------------------------------------------------- #
# serve wiring: the pallas factor lane
# --------------------------------------------------------------------- #

N, V = 64, 16


def _plans(spd=False):
    serve.clear_plans()
    pall = serve.FactorPlan.create((N, N), jnp.float32, v=V, spd=spd,
                                   backend="pallas")
    xla = serve.FactorPlan.create((N, N), jnp.float32, v=V, spd=spd)
    assert pall._pallas_factor and not xla._pallas_factor
    return pall, xla


def test_pallas_plan_bucket_and_pad_bitwise_invariance():
    """The §21 lane contract on a pallas plan: slot i's factor pytree is
    bitwise identical across stack buckets and pad contents (the kernel
    dispatches standalone — never fused into a bucket-shaped jit — so
    the interpret-mode graph can't re-fuse per bucket)."""
    pall, _ = _plans()
    rng = np.random.default_rng(43)
    A = _gen(rng, 4, N, np.float32)
    F1 = pall._stacked_factor_fn(1)(jnp.asarray(A[:1]))
    F4 = pall._stacked_factor_fn(4)(jnp.asarray(A))
    for l1, l4 in zip(F1, F4):
        np.testing.assert_array_equal(np.asarray(l1)[0], np.asarray(l4)[0])
    Apad = np.stack([A[0], np.eye(N, dtype=np.float32)])
    F2 = pall._stacked_factor_fn(2)(jnp.asarray(Apad))
    for l1, l2 in zip(F1, F2):
        np.testing.assert_array_equal(np.asarray(l1)[0], np.asarray(l2)[0])
    with pytest.raises(AssertionError, match="power-of-two"):
        # conflint: disable=CFX-RECOMPILE asserting the bucket contract rejects 3
        pall._stacked_factor_fn(3)


@pytest.mark.parametrize("spd", [False, True], ids=["lu", "chol"])
def test_plan_factor_matches_checked_coalesced_bitwise(spd):
    """`plan.factor` (bucket 1) and the CHECKED coalesced program emit
    the same factor bits — the fused verdict changes the program, not
    the factors — and the verdict reads healthy."""
    pall, _ = _plans(spd=spd)
    rng = np.random.default_rng(47)
    A = (_spd if spd else _gen)(rng, 4, N, np.float32)
    s = pall.factor(jnp.asarray(A[0]))
    F, wA, verdict = pall._factor_health_fn(4)(jnp.asarray(A))
    for got, ref in zip(F, s._factors):
        np.testing.assert_array_equal(np.asarray(got)[0], np.asarray(ref))
    v = np.asarray(verdict)
    assert v.shape == (2, 4)
    assert (v[0] == 1.0).all() and (v[1] < 1e-3).all()
    # the in-kernel probe rows are the sessions' probe rows
    np.testing.assert_allclose(
        np.asarray(wA)[0], np.asarray(s._probe_row()),
        rtol=1e-4, atol=1e-4)
    # and the sessions solve to residual
    b = rng.standard_normal((N, 2)).astype(np.float32)
    x = np.asarray(s.solve(jnp.asarray(b)))
    assert np.abs(A[0] @ x - b).max() < 1e-3


def test_fused_dinv_matches_second_pass():
    """The epilogue-fused `substitution='blocked'` diagonal-block
    inverses equal a separate `diag_block_inverses` pass over the SAME
    kernel LU — fusion moved the op, not the math."""
    pall, _ = _plans()
    assert pall.key.substitution == "blocked"
    rng = np.random.default_rng(53)
    A = _gen(rng, 2, N, np.float32)
    LU, Dl, Du, _perm = pall._stacked_factor_fn(2)(jnp.asarray(A))
    rDl = jax.vmap(lambda t: diag_block_inverses(
        t, lower=True, unit_diagonal=True))(LU)
    rDu = jax.vmap(lambda t: diag_block_inverses(t, lower=False))(LU)
    np.testing.assert_allclose(np.asarray(Dl), np.asarray(rDl),
                               rtol=1e-6, atol=1e-7)
    np.testing.assert_allclose(np.asarray(Du), np.asarray(rDu),
                               rtol=1e-6, atol=1e-7)


def test_fused_verdict_agrees_with_xla_health_path():
    """The in-kernel Freivalds verdict and the XLA-backend
    `_factor_health_fn` agree on the same traffic: all-healthy on clean
    systems, and a forced-unhealthy (singular) slot trips BOTH paths in
    the same slot while its neighbors stay healthy."""
    pall, xla = _plans()
    rng = np.random.default_rng(59)
    A = _gen(rng, 4, N, np.float32)
    limit = HealthPolicy().resolved_residual_limit(np.float32, N)
    vp = np.asarray(pall._factor_health_fn(4)(jnp.asarray(A))[2])
    vx = np.asarray(xla._factor_health_fn(4)(jnp.asarray(A))[2])
    np.testing.assert_array_equal(vp[0], vx[0])
    assert (vp[1] < limit).all() and (vx[1] < limit).all()
    # forced-unhealthy: a zero column makes slot 2 exactly singular
    bad = A.copy()
    bad[2, :, 5] = 0.0
    vp = np.asarray(pall._factor_health_fn(4)(jnp.asarray(bad))[2])
    vx = np.asarray(xla._factor_health_fn(4)(jnp.asarray(bad))[2])
    for v in (vp, vx):
        healthy = (v[0] >= 0.5) & (v[1] <= limit)
        assert not healthy[2]
        assert healthy[[0, 1, 3]].all()


def test_poisoned_slot_trips_alone_neighbors_bitwise():
    """A NaN-poisoned slot fails its OWN verdict; co-batched slots keep
    their exact clean-run factor bits (grid-level blast isolation)."""
    pall, _ = _plans()
    rng = np.random.default_rng(61)
    A = _gen(rng, 4, N, np.float32)
    Fc, _wc, vc = pall._factor_health_fn(4)(jnp.asarray(A))
    bad = A.copy()
    bad[1] = np.nan
    Fp, _wp, vp = pall._factor_health_fn(4)(jnp.asarray(bad))
    vc, vp = np.asarray(vc), np.asarray(vp)
    assert vp[0, 1] == 0.0
    assert (vp[0, [0, 2, 3]] == 1.0).all()
    assert (vc[0] == 1.0).all()
    for lc, lp in zip(Fc, Fp):
        np.testing.assert_array_equal(np.asarray(lc)[[0, 2, 3]],
                                      np.asarray(lp)[[0, 2, 3]])


def test_batched_pallas_plan_folds_stack_into_kernel_batch():
    """A batched (B, N, N) pallas plan folds (bb, B) into one kernel
    batch and unflattens back: sessions solve to residual and the
    checked program's per-slot verdict max-reduces over the plan's own
    batch axis."""
    serve.clear_plans()
    Bp = 4
    plan = serve.FactorPlan.create((Bp, N, N), jnp.float32, v=V,
                                   backend="pallas")
    assert plan._pallas_factor and plan.batched
    rng = np.random.default_rng(67)
    A = _gen(rng, Bp, N, np.float32)
    s = plan.factor(jnp.asarray(A))
    b = rng.standard_normal((Bp, N)).astype(np.float32)
    x = np.asarray(s.solve(jnp.asarray(b)))
    assert np.abs(np.einsum("bij,bj->bi", A, x) - b).max() < 1e-3
    Ast = np.stack([A, _gen(rng, Bp, N, np.float32)])
    F, wA, verdict = plan._factor_health_fn(2)(jnp.asarray(Ast))
    assert np.asarray(wA).shape == (2, Bp, N)
    v = np.asarray(verdict)
    assert v.shape == (2, 2)
    assert (v[0] == 1.0).all() and (v[1] < 1e-3).all()
    # slot 0 of the stack is the plan.factor session, bitwise
    for got, ref in zip(F, s._factors):
        np.testing.assert_array_equal(np.asarray(got)[0], np.asarray(ref))


def test_pallas_bucket_programs_trace_once():
    """Steady-state bucket calls on a pallas plan re-trace nothing: the
    eager kernel dispatch + jitted epilogue pair is memoized per bucket
    like every other program family."""
    pall, _ = _plans()
    rng = np.random.default_rng(71)
    A = _gen(rng, 2, N, np.float32)
    pall._stacked_factor_fn(2)(jnp.asarray(A))
    pall._factor_health_fn(2)(jnp.asarray(A))
    snapshot = dict(pall.trace_counts)
    for _ in range(3):
        pall._stacked_factor_fn(2)(jnp.asarray(A))
        pall._factor_health_fn(2)(jnp.asarray(A))
    assert dict(pall.trace_counts) == snapshot, \
        "steady-state pallas factor buckets traced a program"


def test_ineligible_keys_fall_back_to_xla_body():
    """backend='pallas' with factor_dtype != dtype is OUTSIDE the
    kernel's eligibility gate (the in-kernel probe row must read the
    same operand `probe_row` would): the plan factors through the
    vmapped XLA body and still serves."""
    serve.clear_plans()
    plan = serve.FactorPlan.create((N, N), jnp.float32, v=V,
                                   backend="pallas",
                                   factor_dtype=jnp.float64)
    assert not plan._pallas_factor
    rng = np.random.default_rng(73)
    A = _gen(rng, 1, N, np.float32)
    s = plan.factor(jnp.asarray(A[0]))
    b = rng.standard_normal((N, 1)).astype(np.float32)
    x = np.asarray(s.solve(jnp.asarray(b)))
    assert np.abs(A[0] @ x - b).max() < 1e-3


def test_engine_factor_lane_on_pallas_plan_bitwise():
    """Coalesced cold starts on a pallas plan (checked lane) open
    sessions that solve bitwise identically to `plan.factor` — §29
    rides the §21 lane unchanged."""
    serve.clear_plans()
    plan = serve.FactorPlan.create((N, N), jnp.float32, v=V,
                                   backend="pallas")
    rng = np.random.default_rng(79)
    A = _gen(rng, 3, N, np.float32)
    b = rng.standard_normal((N, 2)).astype(np.float32)
    with ServeEngine(max_batch_delay=0.05, max_factor_batch=4,
                     health=HealthPolicy()) as eng:
        futs = [eng.submit_factor(plan, A[i]) for i in range(3)]
        sessions = [f.result(timeout=120) for f in futs]
        for i, s in enumerate(sessions):
            ref = plan.factor(jnp.asarray(A[i]))
            np.testing.assert_array_equal(np.asarray(s.solve(b)),
                                          np.asarray(ref.solve(b)),
                                          err_msg=f"session {i}")
        assert sessions[0]._probe is not None
        stats = eng.stats()
    assert stats["factor_requests"] == 3
    assert stats["factor_batches"] == 1
