"""Tile-op layer tests: backend registry and Pallas kernels (interpret mode
on the CPU test platform)."""

import jax.numpy as jnp
import numpy as np
import pytest

from conflux_tpu.ops import blas
from conflux_tpu.ops import pallas_kernels


def test_backend_registry():
    assert blas.get_backend() == "xla"
    with pytest.raises(ValueError):
        blas.set_backend("cuda")
    blas.set_backend("pallas")
    assert blas.get_backend() == "pallas"
    blas.set_backend("xla")


def test_gemm_alpha_beta():
    rng = np.random.default_rng(0)
    a = jnp.asarray(rng.standard_normal((8, 4)))
    b = jnp.asarray(rng.standard_normal((4, 8)))
    c = jnp.asarray(rng.standard_normal((8, 8)))
    out = blas.gemm(a, b, c=c, alpha=-1.0, beta=1.0)
    np.testing.assert_allclose(np.asarray(out), c - a @ b, rtol=1e-12)


@pytest.mark.parametrize("shape", [(128, 128, 128), (256, 128, 384), (100, 60, 130)])
def test_pallas_gemm_matches_xla(shape):
    M, N, K = shape
    rng = np.random.default_rng(1)
    a = jnp.asarray(rng.standard_normal((M, K)).astype(np.float32))
    b = jnp.asarray(rng.standard_normal((K, N)).astype(np.float32))
    out = pallas_kernels.gemm(a, b)
    np.testing.assert_allclose(np.asarray(out), np.asarray(a) @ np.asarray(b), atol=1e-4)


def test_gemm_backend_dispatch():
    rng = np.random.default_rng(2)
    a = jnp.asarray(rng.standard_normal((128, 128)).astype(np.float32))
    b = jnp.asarray(rng.standard_normal((128, 128)).astype(np.float32))
    out = blas.gemm(a, b, backend="pallas")
    np.testing.assert_allclose(np.asarray(out), np.asarray(a) @ np.asarray(b), atol=1e-4)


def test_trsm_left_lower_unit():
    rng = np.random.default_rng(3)
    L = np.tril(rng.standard_normal((16, 16)), -1) + np.eye(16)
    B = rng.standard_normal((16, 32))
    X = blas.trsm_left_lower_unit(jnp.asarray(L), jnp.asarray(B))
    np.testing.assert_allclose(L @ np.asarray(X), B, atol=1e-10)


def test_trsm_right_upper():
    rng = np.random.default_rng(4)
    U = np.triu(rng.standard_normal((16, 16))) + 4 * np.eye(16)
    B = rng.standard_normal((32, 16))
    X = blas.trsm_right_upper(jnp.asarray(U), jnp.asarray(B))
    np.testing.assert_allclose(np.asarray(X) @ U, B, atol=1e-10)


def test_potrf():
    from conflux_tpu.validation import make_spd_matrix

    A = make_spd_matrix(32)
    L = blas.potrf(jnp.asarray(A))
    np.testing.assert_allclose(np.tril(L) @ np.tril(L).T, A, atol=1e-9)


def test_unit_lower():
    rng = np.random.default_rng(5)
    lu00 = jnp.asarray(rng.standard_normal((8, 8)))
    L = blas.unit_lower(lu00)
    assert np.allclose(np.diag(np.asarray(L)), 1.0)
    assert np.allclose(np.triu(np.asarray(L), 1), 0.0)


def test_vmem_derived_ceilings_pin_v5e():
    """The chunk ceilings derive from the scoped-VMEM budget (element-
    count model); the measured v5e values are pinned here so a budget or
    model change that silently shifts the tuned defaults fails loudly."""
    import pytest

    from conflux_tpu.ops import blas

    # default budget (32 MiB — the measured v5e figure) at the bench tile
    assert blas.single_call_rows(1024) == 8192
    assert blas.batched_call_rows(1024) == 4096
    # element model: heights scale as 1/v and 1/itemsize
    assert blas.single_call_rows(2048) == 4096
    assert blas.batched_call_rows(2048) == 2048
    assert blas.single_call_rows(1024, jnp.bfloat16) == 16384
    # never shorter than one tile
    assert blas.single_call_rows(8192) == 8192
    # chunk_layout's default chunk is the derived batched bound
    c, nch = blas.chunk_layout(32768, 1024)
    assert (c, nch) == (4096, 8)
    # override for unmeasured generations
    blas.set_scoped_vmem_bytes(16 << 20)
    try:
        assert blas.single_call_rows(1024) == 4096
        assert blas.batched_call_rows(1024) == 2048
    finally:
        blas.set_scoped_vmem_bytes(None)
    assert blas.single_call_rows(1024) == 8192
    with pytest.raises(ValueError, match="implausible"):
        blas.set_scoped_vmem_bytes(1000)


@pytest.mark.parametrize("Px", [3, 5, 7])
def test_butterfly_zero_fill_contract_real_reducers(Px):
    """The odd-Px fold/unfold path makes EVERY rank reduce ppermute's
    zero fill on off-subcube lanes; correctness rests on the reducers
    being total on all-zero inputs with the garbage discarded by the
    coordinate selects (zero-fill contract, `butterfly_allreduce`).
    Pin it with the REAL hot-loop reducers — the CALU tournament
    (lu/distributed.py) and the TSQR R-tree (qr/distributed.py) — at
    odd Px: results must be NaN/Inf-free and bitwise-replicated across
    the axis, and elected ids must come from real rows, never from the
    zero fill."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from conflux_tpu.geometry import Grid3
    from conflux_tpu.ops import blas
    from conflux_tpu.parallel.mesh import (butterfly_allreduce, make_mesh,
                                        shard_map)
    from conflux_tpu.qr.single import _tree_r

    v = 4
    mesh = make_mesh(Grid3(Px, 1, 1), devices=jax.devices()[:Px])
    rng = np.random.default_rng(100 + Px)
    data = rng.standard_normal((Px, v, v)).astype(np.float32)
    ids = np.arange(Px * v, dtype=np.int32).reshape(Px, v)

    def calu_pair(top, bot):
        stack = jnp.concatenate([top[0], bot[0]], axis=0)
        sid = jnp.concatenate([top[1], bot[1]])
        lu00, wid = blas.tournament_winners(stack, chunk=2 * v)
        return (jnp.take(stack, wid, axis=0, mode="fill", fill_value=0),
                jnp.take(sid, wid, mode="fill",
                         fill_value=np.iinfo(np.int32).max),
                lu00)

    def fn(blk, bid):
        nom, nid, lu00 = butterfly_allreduce(
            (blk[0], bid[0], jnp.zeros((v, v), jnp.float32)),
            Px, "x", calu_pair)
        (r,) = butterfly_allreduce(
            (_tree_r(blk[0], 2 * v),), Px, "x",
            lambda top, bot: (_tree_r(
                jnp.concatenate([top[0], bot[0]], axis=0), 2 * v),))
        return nom[None], nid[None], lu00[None], r[None]

    # conflint: disable=CFX-RECOMPILE one-shot test trace; nothing to reuse
    nom, nid, lu00, r = jax.jit(shard_map(
        fn, mesh=mesh, in_specs=(P("x", None, None), P("x", None)),
        out_specs=(P("x", None, None), P("x", None),
                   P("x", None, None), P("x", None, None))))(data, ids)
    nom, nid, lu00, r = map(np.asarray, (nom, nid, lu00, r))
    for out in (nom, nid, lu00, r):
        assert np.all(np.isfinite(out)), "zero-fill garbage leaked NaN/Inf"
        for px in range(1, Px):  # bitwise replication across the axis
            np.testing.assert_array_equal(out[px], out[0])
    # every elected id is a real row, never the fold's zero-fill ids
    assert set(nid[0].tolist()) <= set(range(Px * v))
    flat = data.reshape(Px * v, v)
    np.testing.assert_array_equal(nom[0], flat[nid[0]])


@pytest.mark.parametrize("Px", [1, 2, 3, 4, 5, 6, 7, 8])
def test_butterfly_allreduce_any_px(Px):
    """The hypercube all-reduce must deliver every rank's contribution to
    every rank — including non-power-of-two axes, where overflow ranks
    fold in/out of the subcube (the reference's odd-grid compensating
    sends, `conflux_opt.hpp:266-280`) — and must honor the
    lower-coordinate pair ordering (an order-sensitive keep-top reducer
    converges to rank 0's value everywhere)."""
    import jax
    from jax.sharding import PartitionSpec as P

    from conflux_tpu.geometry import Grid3
    from conflux_tpu.parallel.mesh import (butterfly_allreduce, make_mesh,
                                        shard_map)

    mesh = make_mesh(Grid3(Px, 1, 1), devices=jax.devices()[:Px])
    rng = np.random.default_rng(Px)
    data = rng.integers(1, 1 << 20, size=(Px, 4)).astype(np.int32)

    def fn(blk):
        (s,) = butterfly_allreduce(
            (blk[0],), Px, "x", lambda top, bot: (top[0] + bot[0],))
        (w,) = butterfly_allreduce(
            (blk[0],), Px, "x", lambda top, bot: (top[0],))
        return s[None], w[None]

    # conflint: disable=CFX-RECOMPILE one-shot test trace; nothing to reuse
    ssum, wtop = jax.jit(shard_map(
        fn, mesh=mesh, in_specs=P("x", None),
        out_specs=(P("x", None), P("x", None))))(data)
    for px in range(Px):
        # exact integer sum: replication + completeness on every rank
        np.testing.assert_array_equal(np.asarray(ssum)[px], data.sum(axis=0))
        # keep-top reducer: the lower coordinate's value wins every pair
        np.testing.assert_array_equal(np.asarray(wtop)[px], data[0])
