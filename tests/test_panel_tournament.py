"""Tournament (CALU) panel factorization: correctness of the single-device
tournament against exact partial pivoting — same contract, height-bounded LU
calls (role of the reference's `tournament_rounds`, `conflux_opt.hpp:220-336`,
collapsed onto one device)."""

import jax.numpy as jnp
import numpy as np
import pytest

from conflux_tpu.ops import blas
from conflux_tpu.validation import lu_residual, make_test_matrix, residual_bound


def _panel_residual(panel, lu_packed, perm):
    """|| panel[perm] - L @ U ||_F / || panel ||_F for an (m, v) panel."""
    m, v = panel.shape
    L = np.tril(np.asarray(lu_packed), -1)
    L[:v] += np.eye(v)
    U = np.triu(np.asarray(lu_packed)[:v])
    return np.linalg.norm(panel[np.asarray(perm)] - L @ U) / np.linalg.norm(panel)


@pytest.mark.parametrize("m,v,chunk", [(32, 8, 8), (64, 8, 16), (96, 16, 32), (80, 16, 32)])
def test_tournament_panel_residual(m, v, chunk):
    panel = make_test_matrix(m, v, seed=m + v)
    lu_packed, perm = blas.panel_lu_tournament(jnp.asarray(panel), chunk=chunk)
    assert sorted(np.asarray(perm).tolist()) == list(range(m))
    assert _panel_residual(panel, lu_packed, perm) < residual_bound(m, np.float64)


def test_tournament_single_chunk_matches_partial_pivots():
    # one chunk: the tournament must elect the same pivot rows (in the same
    # order) as exact partial pivoting; only the ordering of the *non*-pivot
    # tail may differ
    import jax

    panel = make_test_matrix(32, 8, seed=1)
    lu_t, perm_t = blas.panel_lu_tournament(jnp.asarray(panel), chunk=64)
    _, _, perm_p = jax.lax.linalg.lu(jnp.asarray(panel))
    np.testing.assert_array_equal(np.asarray(perm_t)[:8], np.asarray(perm_p)[:8])
    assert _panel_residual(panel, lu_t, perm_t) < residual_bound(32, np.float64)


def test_tournament_rejects_short_panel():
    import pytest

    from conflux_tpu.ops import blas

    with pytest.raises(ValueError, match="m >= v"):
        blas.tournament_winners(jnp.eye(8, 16, dtype=jnp.float32))


def test_tournament_picks_large_pivots():
    # a panel whose top chunk is tiny: winners must come from the bottom
    rng = np.random.default_rng(7)
    panel = rng.standard_normal((64, 8)) * 1e-8
    panel[48:] = rng.standard_normal((16, 8)) + 3 * np.sign(rng.standard_normal((16, 8)))
    lu_packed, perm = blas.panel_lu_tournament(jnp.asarray(panel), chunk=16)
    winners = set(np.asarray(perm)[:8].tolist())
    assert winners <= set(range(48, 64)), winners
    assert _panel_residual(panel, lu_packed, perm) < residual_bound(64, np.float64)


def test_tournament_nonpow2_chunks():
    # m/chunk = 3 chunks exercises the pad-to-power-of-two path
    panel = make_test_matrix(96, 8, seed=9)
    lu_packed, perm = blas.panel_lu_tournament(jnp.asarray(panel), chunk=32)
    assert _panel_residual(panel, lu_packed, perm) < residual_bound(96, np.float64)


def test_blocked_lu_with_forced_tournament():
    # full blocked LU with every panel going through the tournament
    from conflux_tpu.lu.single import lu_factor_blocked

    blas.set_panel_algo("tournament")
    try:
        N, v = 128, 16
        A = make_test_matrix(N, N, seed=2)
        LU, perm = lu_factor_blocked(jnp.asarray(A), v=v)
        assert lu_residual(A, LU, perm) < residual_bound(N, np.float64)
    finally:
        blas.set_panel_algo("auto")


def test_tournament_f32():
    panel = make_test_matrix(64, 16, dtype=np.float32, seed=4)
    lu_packed, perm = blas.panel_lu_tournament(jnp.asarray(panel), chunk=32)
    assert lu_packed.dtype == jnp.float32
    assert _panel_residual(panel, lu_packed, perm) < residual_bound(64, np.float32)


# ---------------- Pallas blocked panel LU (interpret mode on CPU) ---------- #


def test_lu_block_kernel_matches_elimination():
    """One 128-wide block: kernel output must reproduce exact partial-pivot
    elimination (same pivots as LAPACK up to tie-breaks, valid factors)."""
    import jax.numpy as jnp
    import numpy as np

    from conflux_tpu.ops import pallas_kernels

    m, w = 192, 128  # kernel width is fixed at 128; m > w leaves live rows
    rng = np.random.default_rng(0)
    panel = rng.standard_normal((m, w)).astype(np.float32)
    alive = np.ones((m, 1), np.int32)
    out, alive_out, piv = pallas_kernels.lu_block(
        jnp.asarray(panel), jnp.asarray(alive)
    )
    out, piv = np.asarray(out), np.asarray(piv)[0]
    assert len(set(piv.tolist())) == w  # distinct pivots
    # reconstruct: pivot rows in order give the packed (w, w) LU00; the
    # remaining live rows hold L10 multipliers
    order = np.concatenate([piv, np.setdiff1d(np.arange(m), piv)])
    L = np.tril(out[order], -1) + np.eye(m, w, dtype=np.float32)
    U = np.triu(out[piv])
    np.testing.assert_allclose(panel[order], L @ U, rtol=0, atol=5e-4)
    assert int(np.asarray(alive_out).sum()) == m - w  # w rows were chosen


def test_panel_lu_pallas_contract():
    import jax.numpy as jnp
    import numpy as np

    m, v = 96, 128
    panel = make_test_matrix(m, v, seed=8, dtype=np.float64).astype(np.float32)
    # pad rows so m >= v (contract requires m >= v for full election)
    panel = np.vstack([panel, make_test_matrix(64, v, seed=9).astype(np.float32)])
    lu_packed, perm = blas.panel_lu_pallas(jnp.asarray(panel))
    assert sorted(np.asarray(perm).tolist()) == list(range(panel.shape[0]))
    assert _panel_residual(panel, lu_packed, perm) < residual_bound(
        panel.shape[0], np.float32
    )


def test_panel_lu_pallas_multiblock():
    # v = 256: two 128-wide blocks exercises the inter-block TRSM/GEMM path
    import jax.numpy as jnp
    import numpy as np

    m, v = 384, 256
    panel = make_test_matrix(m, v, seed=11).astype(np.float32)
    lu_packed, perm = blas.panel_lu_pallas(jnp.asarray(panel))
    assert _panel_residual(panel, lu_packed, perm) < residual_bound(m, np.float32)


def test_blocked_lu_with_forced_pallas():
    import jax.numpy as jnp
    import numpy as np

    from conflux_tpu.lu.single import lu_factor_blocked

    blas.set_panel_algo("pallas")
    try:
        N, v = 256, 128
        A = make_test_matrix(N, N, seed=13).astype(np.float32)
        LU, perm = lu_factor_blocked(jnp.asarray(A), v=v)
        assert lu_residual(A, LU, perm) < residual_bound(N, np.float32)
    finally:
        blas.set_panel_algo("auto")


def test_panel_lu_pallas_tall_routes_through_tournament():
    # taller than the VMEM ceiling: panel_lu(algo='pallas') must chunk
    import jax.numpy as jnp
    import numpy as np

    old = blas._PALLAS_MAX_ROWS
    blas._PALLAS_MAX_ROWS = 64  # shrink the ceiling so the test stays small
    try:
        m, v = 256, 128
        panel = make_test_matrix(m, v, seed=17).astype(np.float32)
        lu_packed, perm = blas.panel_lu(jnp.asarray(panel), algo="pallas")
        assert _panel_residual(panel, lu_packed, perm) < residual_bound(m, np.float32)
    finally:
        blas._PALLAS_MAX_ROWS = old


def test_panel_lu_pallas_rejects_bad_dtype():
    import jax.numpy as jnp
    import numpy as np
    import pytest

    panel = make_test_matrix(128, 128, seed=1)  # float64
    with pytest.raises(ValueError):
        blas.panel_lu(jnp.asarray(panel), algo="pallas")
