"""Multi-host serve fabric (ISSUE 13): federated engines with
checkpoint-backed fail-over and live session migration.

- Routing is DETERMINISTIC rendezvous hashing: the owner map is a pure
  function of (sid, live host set), and a host-set change remaps ONLY
  the removed host's sessions.
- The kill-one-host drill: a dead host is detected by heartbeat, its
  fleet revives on the survivors from the last checkpoint, revived
  sessions solve BITWISE identically, and recovery time is measured
  and bounded.
- In-flight / routed requests against a dead host fail with a
  STRUCTURED HostUnavailable (retry_after riding the measured drain
  rate) — never a hang.
- Live migration hands a session across hosts at a drain barrier;
  migrated sessions (drift updates included) solve bitwise.
- Degraded-mode admission: below min_live live hosts, `open` refuses
  with FleetDegraded while existing sessions keep solving.
- Heartbeat hysteresis: misses walk alive -> suspect -> dead with the
  configured thresholds, and a recovered probe walks suspect back to
  alive.

All tests run the single-process LocalHost fabric (deterministic,
lockcheck-able); the two-process ProcessHost path is exercised by
scripts/fabric_drill.py (CI job) and `bench_engine.py --fabric`.
"""

import os
import time

import numpy as np
import pytest

from conflux_tpu import fabric, profiler, resilience
from conflux_tpu.engine import rendezvous
from conflux_tpu.fabric import FabricPolicy, LocalHost, ServeFabric
from conflux_tpu.resilience import (
    FaultPlan,
    FaultSpec,
    FleetDegraded,
    HostUnavailable,
    InjectedFault,
)
from conflux_tpu.serve import FactorPlan

N, V = 24, 8


def _mk(seed, n=N):
    rng = np.random.default_rng(seed)
    return (rng.standard_normal((n, n)) / np.sqrt(n)
            + 2.0 * np.eye(n)).astype(np.float32)


def _rhs(seed, w=1):
    b = np.random.default_rng(1000 + seed).standard_normal(
        (N, w) if w > 1 else (N,))
    return b.astype(np.float32)


def _plan():
    return FactorPlan.create((N, N), "float32", v=V)


def _fab(tmp_path, n=3, fault_plan=None, **pol):
    kw = dict(heartbeat_interval=0.05, heartbeat_timeout=1.0,
              suspect_after=2, dead_after=4)
    kw.update(pol)
    return fabric.local_fabric(
        n, str(tmp_path), policy=FabricPolicy(**kw),
        fault_plan=fault_plan,
        engine_kwargs={"max_batch_delay": 0.0})


def _wait_dead(fab, hid, timeout=20.0):
    t0 = time.perf_counter()
    while time.perf_counter() - t0 < timeout:
        if fab.host_state(hid) == "dead":
            return time.perf_counter() - t0
        time.sleep(0.02)
    raise AssertionError(f"host {hid} never declared dead")


# --------------------------------------------------------------------------- #
# routing
# --------------------------------------------------------------------------- #


def test_router_determinism(tmp_path):
    """Placement is a pure function of (sid, live host ids): the owner
    map matches bare rendezvous() and reproduces across fabrics."""
    sids = [f"user-{i}" for i in range(12)]
    with _fab(tmp_path / "a") as fa:
        hosts = sorted(fa._hosts)
        for s in sids:
            fa.open(s, _plan(), _mk(1))
        owners_a = {s: fa.owner_of(s) for s in sids}
    assert owners_a == {s: rendezvous(s, hosts) for s in sids}
    with _fab(tmp_path / "b") as fb:
        for s in sids:
            fb.open(s, _plan(), _mk(1))
        assert {s: fb.owner_of(s) for s in sids} == owners_a


def test_rendezvous_remap_only_removed_host():
    """The HRW property the fail-over story rides: dropping one host
    moves ONLY that host's sids; every other mapping is unchanged."""
    hosts = ["h0", "h1", "h2", "h3"]
    sids = [f"s{i}" for i in range(200)]
    before = {s: rendezvous(s, hosts) for s in sids}
    survivors = [h for h in hosts if h != "h2"]
    after = {s: rendezvous(s, survivors) for s in sids}
    for s in sids:
        if before[s] == "h2":
            assert after[s] in survivors
        else:
            assert after[s] == before[s]
    # and the dead host owned a nontrivial share (the hash spreads)
    assert sum(1 for s in sids if before[s] == "h2") > 10


def test_open_duplicate_sid_refused(tmp_path):
    with _fab(tmp_path, n=2) as fab:
        fab.open("dup", _plan(), _mk(2))
        with pytest.raises(ValueError, match="already open"):
            fab.open("dup", _plan(), _mk(2))
        with pytest.raises(KeyError, match="unknown sid"):
            fab.solve("never-opened", _rhs(0))


# --------------------------------------------------------------------------- #
# the kill-one-host drill
# --------------------------------------------------------------------------- #


def test_kill_one_host_failover_bitwise_and_bounded(tmp_path):
    """The tentpole drill: kill the host owning sessions; detection +
    fail-over re-home its fleet on survivors from the last checkpoint;
    every session (including revived ones) solves BITWISE as before;
    recovery time is measured and bounded."""
    with _fab(tmp_path) as fab:
        ref, rhs = {}, {}
        for i in range(9):
            sid = f"drill-{i}"
            fab.open(sid, _plan(), _mk(10 + i))
            rhs[sid] = _rhs(i, w=2)
            ref[sid] = np.asarray(fab.solve(sid, rhs[sid]))
        victim = fab.owner_of("drill-0")
        moved = [s for s in ref if fab.owner_of(s) == victim]
        stay = {s: fab.owner_of(s) for s in ref
                if fab.owner_of(s) != victim}
        assert moved, "victim owned nothing — hash degenerated"
        fab._hosts[victim].kill()
        _wait_dead(fab, victim)
        # bounded recovery: the fail-over measured itself
        rec = fab.stats()["recoveries"]
        assert rec and rec[-1]["host"] == victim
        assert rec[-1]["adopted"] == len(moved)
        assert rec[-1]["lost"] == 0
        assert rec[-1]["seconds"] < 30.0
        # every session answers bitwise; survivors never moved
        for sid in ref:
            assert np.array_equal(
                np.asarray(fab.solve(sid, rhs[sid])), ref[sid]), sid
        for sid, h in stay.items():
            assert fab.owner_of(sid) == h
        for sid in moved:
            assert fab.owner_of(sid) != victim
            assert fab.host_state(fab.owner_of(sid)) == "alive"


def test_dead_host_requests_fail_structured_not_hang(tmp_path):
    """With detection disabled (huge heartbeat interval), a request
    routed at a killed host surfaces HostUnavailable immediately —
    the transport tear maps to a structured error, never a hang."""
    with _fab(tmp_path, n=2, heartbeat_interval=60.0) as fab:
        fab.open("s", _plan(), _mk(3))
        hid = fab.owner_of("s")
        fab._hosts[hid].kill()
        t0 = time.perf_counter()
        with pytest.raises(HostUnavailable) as ei:
            fab.solve("s", _rhs(3))
        assert time.perf_counter() - t0 < 10.0
        assert ei.value.retry_after >= 0.0
        assert ei.value.host == hid
        assert resilience.health_stats()["host_unavailable"] >= 1


def test_never_checkpointed_session_reported_lost(tmp_path):
    """durable_open off + no background checkpointing: a killed host's
    sessions are unrecoverable — the fabric says so (structured, with
    the reason), conserves the count in stats, and lets the sid be
    reopened."""
    with _fab(tmp_path, n=2, durable_open=False) as fab:
        fab.open("gone", _plan(), _mk(4))
        victim = fab.owner_of("gone")
        fab._hosts[victim].kill()
        _wait_dead(fab, victim)
        assert fab.stats()["lost_sessions"] == 1
        with pytest.raises(HostUnavailable, match="lost"):
            fab.solve("gone", _rhs(4))
        # a lost sid may be reopened (fresh state, back in service)
        fab.open("gone", _plan(), _mk(4))
        fab.solve("gone", _rhs(4))
        assert fab.stats()["lost_sessions"] == 0


def test_failover_bounded_staleness_of_updates(tmp_path):
    """Background checkpointing bounds fail-over staleness: drift
    updates checkpointed before the kill survive it (the revived
    session solves bitwise WITH the update applied)."""
    with _fab(tmp_path, n=2, checkpoint_interval=0.1) as fab:
        fab.open("drift", _plan(), _mk(5))
        rng = np.random.default_rng(5)
        U = rng.standard_normal((N, 2)).astype(np.float32) * 0.1
        Vm = rng.standard_normal((N, 2)).astype(np.float32) * 0.1
        fab.update("drift", U, Vm)
        want = np.asarray(fab.solve("drift", _rhs(5)))
        victim = fab.owner_of("drift")
        # wait for two FULL background rounds started after the update
        base = fab.stats()["checkpoint_rounds"]
        deadline = time.perf_counter() + 20.0
        while time.perf_counter() < deadline:
            if fab.stats()["checkpoint_rounds"] >= base + 2:
                break
            time.sleep(0.05)
        assert fab.stats()["checkpoint_rounds"] >= base + 2
        assert fabric.latest_checkpoint(
            fab._hosts[victim].ckpt_dir) is not None
        fab._hosts[victim].kill()
        _wait_dead(fab, victim)
        # 'dead' flips before the synchronous fail-over lands: ride the
        # structured in-flight window on its own retry hints (the
        # fabric_drill pattern), bounded — a genuinely lost session
        # still surfaces as the final HostUnavailable
        deadline = time.perf_counter() + 20.0
        while True:
            try:
                got = np.asarray(fab.solve("drift", _rhs(5)))
                break
            except HostUnavailable as e:
                if time.perf_counter() > deadline:
                    raise
                time.sleep(min(max(e.retry_after, 0.01), 0.25))
        assert np.array_equal(got, want)


# --------------------------------------------------------------------------- #
# live migration
# --------------------------------------------------------------------------- #


def test_migration_bitwise_with_drift(tmp_path):
    """A migrated session — drift updates and all — answers bitwise on
    its new host; ownership flips; the source forgets it."""
    with _fab(tmp_path) as fab:
        fab.open("mig", _plan(), _mk(6))
        rng = np.random.default_rng(6)
        U = rng.standard_normal((N, 2)).astype(np.float32) * 0.1
        Vm = rng.standard_normal((N, 2)).astype(np.float32) * 0.1
        fab.update("mig", U, Vm)
        b = _rhs(6, w=3)
        want = np.asarray(fab.solve("mig", b))
        src = fab.owner_of("mig")
        tgt = fab.migrate("mig")
        assert tgt != src and fab.owner_of("mig") == tgt
        assert np.array_equal(np.asarray(fab.solve("mig", b)), want)
        # the source host no longer has the session
        with pytest.raises(KeyError):
            fab._hosts[src].solve("mig", b)
        assert resilience.health_stats()["sessions_migrated"] >= 1


def test_migration_crash_leaves_source_intact(tmp_path):
    """An injected crash at the hand-off barrier (record written, not
    yet adopted) aborts the migration with the session still owned by
    — and solving bitwise on — the source."""
    plan = FaultPlan([FaultSpec(site="migrate", kind="crash", count=1)])
    with _fab(tmp_path, fault_plan=plan) as fab:
        fab.open("crash", _plan(), _mk(7))
        b = _rhs(7)
        want = np.asarray(fab.solve("crash", b))
        src = fab.owner_of("crash")
        with pytest.raises(InjectedFault):
            fab.migrate("crash")
        assert fab.owner_of("crash") == src
        assert np.array_equal(np.asarray(fab.solve("crash", b)), want)
        # fault budget consumed: the retry goes through
        tgt = fab.migrate("crash")
        assert fab.owner_of("crash") == tgt != src
        assert np.array_equal(np.asarray(fab.solve("crash", b)), want)


def test_migrate_picks_least_loaded_target(tmp_path):
    # heartbeats off (huge interval) so the manual load feeds below
    # aren't overwritten by real probe deltas mid-test
    with _fab(tmp_path, heartbeat_interval=60.0) as fab:
        fab.open("ll", _plan(), _mk(8))
        src = fab.owner_of("ll")
        others = [h for h in sorted(fab._hosts) if h != src]
        # seed the load estimator: others[0] busy, others[1] idle
        fab.load.feed(others[0], {"solves": 0, "seconds": 1.0,
                                  "pending": 50})
        fab.load.feed(others[1], {"solves": 100, "seconds": 1.0,
                                  "pending": 0})
        assert fab.migrate("ll") == others[1]


# --------------------------------------------------------------------------- #
# degraded admission + retry hints
# --------------------------------------------------------------------------- #


def test_degraded_admission_below_min_live(tmp_path):
    """Below min_live, `open` refuses with FleetDegraded (structured,
    counted) while existing sessions keep answering on survivors."""
    with _fab(tmp_path, n=2, min_live=2) as fab:
        fab.open("pre", _plan(), _mk(9))
        victim = [h for h in sorted(fab._hosts)
                  if h != fab.owner_of("pre")][0]
        fab._hosts[victim].kill()
        _wait_dead(fab, victim)
        with pytest.raises(FleetDegraded) as ei:
            fab.open("post", _plan(), _mk(9))
        assert ei.value.live == 1 and ei.value.total == 2
        assert ei.value.retry_after >= 0.0
        fab.solve("pre", _rhs(9))  # survivors still serve
        assert resilience.health_stats()["fleet_degraded"] >= 1


def test_retry_after_rides_measured_drain_rate(tmp_path):
    """The HostUnavailable retry hint comes from the load estimator's
    smoothed drain rates (clamped to the policy band)."""
    with _fab(tmp_path, n=2, heartbeat_interval=60.0) as fab:
        # seed measured rates: the fleet drains 20 solves/s
        for hid in sorted(fab._hosts):
            fab.load.feed(hid, {"solves": 10, "seconds": 1.0,
                                "pending": 0})
        hint = fab._retry_hint(backlog=10)
        assert hint == pytest.approx(10 / 20.0, rel=0.01)
        pol = fab.policy
        assert pol.retry_floor <= hint <= pol.retry_ceil


def test_route_fault_maps_to_host_unavailable(tmp_path):
    with _fab(tmp_path, n=2) as fab:
        fab.open("r", _plan(), _mk(11))
        # arm the fault AFTER open (open routes too and would eat it)
        fab._faults = FaultPlan(
            [FaultSpec(site="route", kind="crash", count=1)])
        with pytest.raises(HostUnavailable):
            fab.solve("r", _rhs(11))
        fab.solve("r", _rhs(11))  # budget consumed; traffic resumes


# --------------------------------------------------------------------------- #
# heartbeat hysteresis
# --------------------------------------------------------------------------- #


def test_heartbeat_hysteresis_suspect_then_recover(tmp_path):
    """Two injected probe failures walk the host alive -> suspect
    (below dead_after it is NOT declared dead and loses nothing); the
    next healthy probe walks it back to alive with misses reset."""
    base = resilience.health_stats()
    plan = FaultPlan([FaultSpec(site="heartbeat", kind="crash",
                                count=2)])
    with _fab(tmp_path, n=1, fault_plan=plan, suspect_after=2,
              dead_after=6) as fab:
        fab.open("hys", _plan(), _mk(12))
        # the suspect transition bumps a monotone counter — poll that
        # (the suspect WINDOW itself is one heartbeat wide and a state
        # poll could miss it)
        deadline = time.perf_counter() + 20.0
        while time.perf_counter() < deadline:
            h = resilience.health_stats()
            if h["hosts_suspected"] > base["hosts_suspected"]:
                break
            time.sleep(0.02)
        h = resilience.health_stats()
        assert h["hosts_suspected"] > base["hosts_suspected"], \
            "host never reached suspect"
        assert h["heartbeat_misses"] >= base["heartbeat_misses"] + 2
        # fault budget spent: the next healthy probe walks it back
        deadline = time.perf_counter() + 20.0
        while time.perf_counter() < deadline:
            if fab.host_state("h0") == "alive":
                break
            time.sleep(0.02)
        assert fab.host_state("h0") == "alive"
        # suspect never escalated: no fail-over ran, sessions in place
        assert fab.stats()["recoveries"] == []
        fab.solve("hys", _rhs(12))


def test_host_kill_fault_site_drives_failover(tmp_path):
    """The seeded host_kill fault kills a whole host from inside the
    heartbeat loop; detection + fail-over then run end-to-end."""
    plan = FaultPlan([FaultSpec(site="host_kill", kind="kill",
                                count=1)])
    with _fab(tmp_path, fault_plan=plan, durable_open=True) as fab:
        ref, rhs = {}, {}
        for i in range(4):
            sid = f"hk-{i}"
            fab.open(sid, _plan(), _mk(20 + i))
            rhs[sid] = _rhs(20 + i)
            ref[sid] = np.asarray(fab.solve(sid, rhs[sid]))
        deadline = time.perf_counter() + 20.0
        while time.perf_counter() < deadline:
            if any(fab.host_state(h) == "dead"
                   for h in sorted(fab._hosts)):
                break
            time.sleep(0.02)
        dead = [h for h in sorted(fab._hosts)
                if fab.host_state(h) == "dead"]
        assert len(dead) == 1
        for sid in ref:
            assert np.array_equal(
                np.asarray(fab.solve(sid, rhs[sid])), ref[sid]), sid
        assert resilience.health_stats()["host_failovers"] >= 1


# --------------------------------------------------------------------------- #
# telemetry surfaces
# --------------------------------------------------------------------------- #


def test_fabric_stats_merge_into_serve_stats(tmp_path):
    with _fab(tmp_path, n=2) as fab:
        fab.open("tel", _plan(), _mk(13))
        ss = profiler.serve_stats()
        fs = ss["fabric"]
        assert fs["fabrics"] >= 1
        assert fs["hosts"] >= 2
        assert fs["sessions"] >= 1
        for k in ("host_unavailable", "fleet_degraded",
                  "heartbeat_misses", "hosts_died", "host_failovers",
                  "sessions_failed_over", "sessions_migrated"):
            assert k in ss["health"]
    # closed fabrics drop out of the aggregate census
    assert fab._closed
    assert fab not in [f for f in list(fabric._FABRICS)
                       if not f._closed]


def test_host_load_estimator_window_plumbing(tmp_path):
    """Heartbeats feed CounterWindow deltas into the estimator: after
    traffic, the owning host reports a positive drain rate."""
    with _fab(tmp_path, n=2) as fab:
        fab.open("load", _plan(), _mk(14))
        hid = fab.owner_of("load")
        for i in range(10):
            fab.solve("load", _rhs(30 + i))
        deadline = time.perf_counter() + 10.0
        while time.perf_counter() < deadline:
            rates = fab.load.stats()
            if rates.get(hid, {}).get("drain_per_s", 0.0) > 0.0:
                break
            time.sleep(0.05)
        assert fab.load.stats()[hid]["drain_per_s"] > 0.0


def test_checkpoint_generations_pruned(tmp_path):
    # compact_every=1: every generation self-contained, so the prune
    # bound is exactly checkpoint_keep (the pre-§35 contract; delta
    # chains are covered in tests/test_scale.py)
    with _fab(tmp_path, n=1, checkpoint_keep=2,
              checkpoint_compact_every=1) as fab:
        fab.open("gen", _plan(), _mk(15))
        for _ in range(4):
            fab.checkpoint_all()
        ckpt_dir = fab._hosts["h0"].ckpt_dir
        gens = [d for d in os.listdir(ckpt_dir)
                if d.startswith("fleet-")]
        assert len(gens) <= 2
        snap = fabric.latest_checkpoint(ckpt_dir)
        assert snap is not None
        assert fabric.checkpoint_sids(snap) == {
            "gen": fabric.record_name("gen")}


# --------------------------------------------------------------------------- #
# ProcessHost request plumbing (ISSUE 16): timeout composition + wire
# --------------------------------------------------------------------------- #


class _NeverReplies:
    """A Connection stand-in that accepts sends and never answers —
    the shape of a wedged (not dead) worker."""

    def __init__(self):
        self.sent = []

    def send(self, msg):
        self.sent.append(msg)

    def close(self):
        pass


def test_processhost_per_op_timeout_is_transport_shaped(tmp_path):
    """Regression (ISSUE 16): on Python 3.10
    `concurrent.futures.TimeoutError` is NOT the builtin TimeoutError
    (and not an OSError), so ProcessHost._call's old `except
    TimeoutError` never caught it — the pending entry leaked and the
    raw futures timeout escaped `_TRANSPORT_ERRORS`, reaching callers
    unstructured. Now a slow op under a tight per-op timeout raises
    the BUILTIN TimeoutError (OSError-shaped, so the front maps it to
    HostUnavailable), pops its pending entry, and the per-op timeout
    beats call_timeout."""
    h = fabric.ProcessHost("hx", str(tmp_path / "hx"),
                           call_timeout=30.0, wire="pickle")
    h._conn = _NeverReplies()
    t0 = time.perf_counter()
    with pytest.raises(TimeoutError) as ei:
        h._call("stats", timeout=0.15)
    dt = time.perf_counter() - t0
    assert isinstance(ei.value, fabric._TRANSPORT_ERRORS)
    assert 0.1 < dt < 5.0          # the per-op timeout won, not 30s
    assert h._pending == {}        # no pending-entry leak
    assert h._conn.sent            # the op really went out


def test_processhost_call_timeout_fallback(tmp_path):
    """timeout=None composes predictably: the handle's call_timeout
    applies, through the same single `_deadline` rule as per-op
    timeouts."""
    h = fabric.ProcessHost("hy", str(tmp_path / "hy"),
                           call_timeout=0.15, wire="pickle")
    h._conn = _NeverReplies()
    with pytest.raises(TimeoutError):
        h._call("stats")
    assert h._pending == {}


def test_processhost_rejects_unknown_wire():
    with pytest.raises(ValueError, match="wire"):
        fabric.ProcessHost("hz", "/tmp/unused-hz", wire="carrier-pigeon")


class _AlwaysFullWire:
    """A WireClient stand-in whose request ring never drains — the
    shape of a torn pipe with replies that will never land. Optionally
    fails the owning host mid-pacing, like the recv thread observing
    the pipe EOF while echo_many retries."""

    def __init__(self, host=None, fail_on_call=None):
        self._host = host
        self._fail_on_call = fail_on_call
        self.calls = 0
        self.failed_with = None

    def payload_fits(self, nbytes):
        return True

    def submit_many(self, entries):
        self.calls += 1
        if self.calls == self._fail_on_call:
            self._host._fail(ConnectionError("pipe torn mid-pacing"))
        raise fabric.wire_mod.RingFull("ring full", retry_after=1e-3)

    def fail(self, exc):
        self.failed_with = exc


def test_processhost_fail_also_fails_wire_client(tmp_path):
    """Regression (ISSUE 16 review): `_fail` (a torn pipe) must also
    fail the shm wire client — otherwise the ring-backpressure retry
    loops keep pacing against a ring no reply will ever drain."""
    h = fabric.ProcessHost("hw", str(tmp_path / "hw"))
    w = _AlwaysFullWire()
    h._wire = w
    h._fail(ConnectionError("torn"))
    assert isinstance(w.failed_with, ConnectionError)


def test_echo_many_ring_full_pacing_observes_death(tmp_path):
    """Regression (ISSUE 16 review): echo_many's RingFull pacing loop
    re-checks host death each lap — a pipe torn mid-burst raises a
    structured ConnectionError instead of spinning forever, and the
    unsent tail's pending entries are reclaimed."""
    h = fabric.ProcessHost("hv", str(tmp_path / "hv"))
    h._wire = _AlwaysFullWire(host=h, fail_on_call=2)
    with pytest.raises(ConnectionError, match="pacing|torn"):
        h.echo_many([np.ones(8, np.float32)] * 3)
    assert h._pending == {}


def test_echo_many_ring_full_pacing_bounded_by_op_timeout(tmp_path):
    """A ring that stays full with the host still alive cannot pace
    past the op timeout: echo_many raises the builtin TimeoutError
    (transport-shaped) and reclaims the unsent pending entries."""
    h = fabric.ProcessHost("hu", str(tmp_path / "hu"),
                           call_timeout=0.15)
    h._wire = _AlwaysFullWire()
    t0 = time.perf_counter()
    with pytest.raises(TimeoutError, match="stayed full"):
        h.echo_many([np.ones(8, np.float32)] * 3)
    assert time.perf_counter() - t0 < 5.0
    assert h._pending == {}


def test_wire_corrupt_codec_rehydrates_kind_and_host():
    """Regression (ISSUE 16 review): a worker-side WireCorrupt (corrupt
    REQUEST record) crosses the pickle control plane intact — the front
    re-raises the ConnectionError-shaped type with kind/host, not a
    generic RuntimeError."""
    e = resilience.WireCorrupt("request record torn",
                               kind="stale_generation", host="h9")
    with pytest.raises(resilience.WireCorrupt) as ei:
        fabric._raise_wire(fabric._encode_exc(e))
    assert ei.value.kind == "stale_generation"
    assert ei.value.host == "h9"
    assert isinstance(ei.value, ConnectionError)
