"""Native layout engine: agreement with the NumPy reference path."""

import numpy as np
import pytest

from conflux_tpu import native
from conflux_tpu.geometry import Grid3, LUGeometry


needs_native = pytest.mark.skipif(
    not native.available(), reason="native library not built"
)


@needs_native
@pytest.mark.parametrize("dtype", [np.float32, np.float64])
@pytest.mark.parametrize("grid", [Grid3(1, 1, 1), Grid3(2, 2, 1), Grid3(4, 2, 1)], ids=str)
def test_native_matches_numpy(grid, dtype):
    v = 8
    geom = LUGeometry.create(v * grid.Px * 3, v * grid.Py * 3, v, grid)
    rng = np.random.default_rng(grid.P)
    A = rng.standard_normal((geom.M, geom.N)).astype(dtype)

    fast = native.scatter(A, v, grid.Px, grid.Py)
    assert fast is not None
    # pure-numpy path, forced
    T = A.reshape(geom.Mtl, grid.Px, v, geom.Ntl, grid.Py, v)
    slow = np.ascontiguousarray(
        np.transpose(T, (1, 4, 0, 2, 3, 5)).reshape(grid.Px, grid.Py, geom.Ml, geom.Nl)
    )
    np.testing.assert_array_equal(fast, slow)

    back = native.gather(fast, v, grid.Px, grid.Py)
    np.testing.assert_array_equal(back, A)


@needs_native
def test_native_rejects_unsupported():
    A = np.zeros((8, 8), dtype=np.int32)
    assert native.scatter(A, 4, 1, 1) is None  # dtype unsupported -> fallback
    assert native.scatter(np.zeros((10, 8)), 4, 1, 1) is None  # bad extent


def test_geometry_uses_native_transparently():
    """Scatter/gather must round-trip whether or not the native lib exists."""
    geom = LUGeometry.create(64, 64, 8, Grid3(2, 2, 1))
    A = np.random.default_rng(0).standard_normal((64, 64))
    np.testing.assert_array_equal(geom.gather(geom.scatter(A)), A)
