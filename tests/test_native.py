"""Native layout engine: agreement with the NumPy reference path."""

import numpy as np
import pytest

from conflux_tpu import native
from conflux_tpu.geometry import Grid3, LUGeometry


needs_native = pytest.mark.skipif(
    not native.available(), reason="native library not built"
)


@needs_native
@pytest.mark.parametrize("dtype", [np.float32, np.float64])
@pytest.mark.parametrize("grid", [Grid3(1, 1, 1), Grid3(2, 2, 1), Grid3(4, 2, 1)], ids=str)
def test_native_matches_numpy(grid, dtype):
    v = 8
    geom = LUGeometry.create(v * grid.Px * 3, v * grid.Py * 3, v, grid)
    rng = np.random.default_rng(grid.P)
    A = rng.standard_normal((geom.M, geom.N)).astype(dtype)

    fast = native.scatter(A, v, grid.Px, grid.Py)
    assert fast is not None
    # pure-numpy path, forced
    T = A.reshape(geom.Mtl, grid.Px, v, geom.Ntl, grid.Py, v)
    slow = np.ascontiguousarray(
        np.transpose(T, (1, 4, 0, 2, 3, 5)).reshape(grid.Px, grid.Py, geom.Ml, geom.Nl)
    )
    np.testing.assert_array_equal(fast, slow)

    back = native.gather(fast, v, grid.Px, grid.Py)
    np.testing.assert_array_equal(back, A)


@needs_native
def test_native_rejects_unsupported():
    A = np.zeros((8, 8), dtype=np.int32)
    assert native.scatter(A, 4, 1, 1) is None  # dtype unsupported -> fallback
    assert native.scatter(np.zeros((10, 8)), 4, 1, 1) is None  # bad extent


def test_geometry_uses_native_transparently():
    """Scatter/gather must round-trip whether or not the native lib exists."""
    geom = LUGeometry.create(64, 64, 8, Grid3(2, 2, 1))
    A = np.random.default_rng(0).standard_normal((64, 64))
    np.testing.assert_array_equal(geom.gather(geom.scatter(A)), A)


def test_file_scatter_gather_roundtrip(tmp_path):
    """Streaming file <-> shards IO (native mmap engine with memmap fallback):
    file -> shards must equal in-memory scatter; shards -> file must restore
    the original matrix bytes."""
    import numpy as np

    from conflux_tpu.geometry import Grid3, LUGeometry
    from conflux_tpu.io import load_scattered, save_matrix, save_scattered

    geom = LUGeometry.create(64, 64, 8, Grid3(2, 2, 1))
    rng = np.random.default_rng(0)
    A = rng.standard_normal((64, 64))
    path = str(tmp_path / "m.bin")
    save_matrix(path, A)

    shards = load_scattered(path, geom)
    np.testing.assert_array_equal(shards, geom.scatter(A))

    out = str(tmp_path / "out.bin")
    save_scattered(out, shards, geom)
    from conflux_tpu.io import load_matrix

    np.testing.assert_array_equal(load_matrix(out), A)


def test_file_scatter_shape_mismatch(tmp_path):
    import numpy as np
    import pytest

    from conflux_tpu.geometry import Grid3, LUGeometry
    from conflux_tpu.io import load_scattered, save_matrix

    path = str(tmp_path / "m.bin")
    save_matrix(path, np.zeros((32, 32)))
    geom = LUGeometry.create(64, 64, 8, Grid3(2, 2, 1))
    with pytest.raises(ValueError):
        load_scattered(path, geom)


def test_file_io_memmap_fallback(tmp_path, monkeypatch):
    """The np.memmap strip-at-a-time fallback must produce exactly what the
    native mmap engine produces."""
    import numpy as np

    from conflux_tpu import native
    from conflux_tpu.geometry import Grid3, LUGeometry
    from conflux_tpu.io import load_matrix, load_scattered, save_matrix, save_scattered

    monkeypatch.setattr(native, "_LIB", None)
    monkeypatch.setattr(native, "_TRIED", True)
    monkeypatch.setattr(native, "_FILE_OK", False)

    geom = LUGeometry.create(48, 96, 8, Grid3(3, 2, 1))
    rng = np.random.default_rng(1)
    A = rng.standard_normal((48, 96)).astype(np.float32)
    path = str(tmp_path / "m.bin")
    save_matrix(path, A)

    shards = load_scattered(path, geom)
    np.testing.assert_array_equal(shards, geom.scatter(A))

    out = str(tmp_path / "o.bin")
    save_scattered(out, shards, geom)
    np.testing.assert_array_equal(load_matrix(out), A)


def test_save_scattered_rejects_wrong_shape(tmp_path):
    import numpy as np
    import pytest

    from conflux_tpu.geometry import Grid3, LUGeometry
    from conflux_tpu.io import save_scattered

    geom = LUGeometry.create(64, 64, 8, Grid3(4, 2, 1))
    bad = np.zeros((2, 2, 32, 32))
    with pytest.raises(ValueError):
        save_scattered(str(tmp_path / "x.bin"), bad, geom)


@needs_native
def test_native_tile_pack_roundtrip_and_transform():
    """bc_to_tiles/tiles_to_bc match the Python layout walk, and the
    transform() fast paths (bc <-> CustomLayout at one tile size)
    produce exactly what the region-walk fallback produces."""
    from conflux_tpu.layout import (
        BlockCyclicLayout,
        CustomLayout,
        _native_bc_to_custom,
        _native_custom_to_bc,
        gather,
        scatter,
    )

    rng = np.random.default_rng(21)
    M, N, v = 64, 48, 8
    bc = BlockCyclicLayout(M=M, N=N, vr=v, vc=v, Prows=2, Pcols=2)
    Mt, Nt = bc.tile_counts()
    owners = np.stack([rng.integers(0, 3, (Mt, Nt)),
                       rng.integers(0, 2, (Mt, Nt))], axis=-1)
    cl = CustomLayout.from_owner_map(M, N, v, v, owners)
    A = rng.standard_normal((M, N)).astype(np.float32)
    shards = scatter(A, bc)

    store_fast = _native_bc_to_custom(shards, bc, cl)
    assert store_fast is not None, "native fast path did not engage"
    np.testing.assert_array_equal(cl.gather(store_fast), A)

    back_fast = _native_custom_to_bc(store_fast, cl, bc)
    assert back_fast is not None
    np.testing.assert_array_equal(gather(back_fast, bc), A)

    # raw kernels round-trip directly too
    stacked = np.stack([np.stack(row) for row in shards])
    tiles = native.bc_to_tiles(stacked, v, bc.Prows, bc.Pcols)
    assert tiles is not None and tiles.shape == (Mt * Nt, v, v)
    np.testing.assert_array_equal(tiles[1], A[0:v, v:2 * v])
    back = native.tiles_to_bc(tiles, M, N, v, bc.Prows, bc.Pcols)
    np.testing.assert_array_equal(back[0, 0], shards[0][0])
