"""The §33 precision ladder: per-request tier routing on the
kind-generic PlanKey.

Contracts asserted here (ISSUE 18):

- `precision=None` stays BITWISE-identical to the pre-§33 path — the
  default route never even looks at the tier machinery.
- Per-request tiers select distinct compiled program families under ONE
  plan (`("tier", tier, wb)` keyspace in `_solve_cache`), warmed and
  retired through the same `bucket_ready`/`release_buckets` lifecycle
  as the native buckets, with ZERO compiles after `prewarm(...,
  precisions=)`.
- `"auto"` starts on bf16+IR and the fused §20 Freivalds verdict climbs
  the ladder (`resilience.escalate_precision`) — sticky per session,
  counted, and falling through to the native escalation rungs at the
  top.
- The fleet codec speaks `kind`, decodes pre-§33 `"spd"` checkpoints,
  and refuses non-representable precision payloads with the offending
  value named (encode AND decode).
- Tier-opened sessions ride spill/revive and checkpoint/restore
  bitwise, serving their tier after every round trip.
"""

import json
import os

import numpy as np
import pytest

from conflux_tpu import resilience, serve, tier
from conflux_tpu.engine import ServeEngine

N, V = 256, 256


def _system(n=N, seed=0, scale=None):
    rng = np.random.default_rng(seed)
    A = (rng.standard_normal((n, n))
         + (n if scale is None else scale) * np.eye(n)).astype(np.float32)
    b = rng.standard_normal(n).astype(np.float32)
    return A, b


def _ill_conditioned(n=N, seed=3, cond=1e6):
    rng = np.random.default_rng(seed)
    U, _ = np.linalg.qr(rng.standard_normal((n, n)))
    sv = np.logspace(0, -np.log10(cond), n)
    return ((U * sv) @ U.T).astype(np.float32)


def _resid(A, x, b):
    x = np.asarray(x, np.float64)
    return (np.linalg.norm(A.astype(np.float64) @ x - b)
            / np.linalg.norm(b))


# --------------------------------------------------------------------------- #
# the kind-generic key + request validation
# --------------------------------------------------------------------------- #


def test_plan_key_kind_replaces_spd():
    lu = serve.FactorPlan.create((N, N), np.float32, kind="lu")
    ch = serve.FactorPlan.create((N, N), np.float32, kind="chol")
    legacy = serve.FactorPlan.create((N, N), np.float32, spd=True)
    assert lu.key.kind == "lu" and not lu.key.spd
    assert ch.key.kind == "chol" and ch.key.spd
    # spd=True is the pre-§33 spelling of kind='chol': same key, same
    # cached plan object
    assert legacy is ch
    with pytest.raises(ValueError, match="kind"):
        serve.FactorPlan.create((N, N), np.float32, kind="qz")


def test_check_precision_request_names_offender():
    for ok in (None, "auto") + serve.PRECISION_TIERS:
        assert serve.check_precision_request(ok) == ok
    with pytest.raises(ValueError, match="fp8"):
        serve.check_precision_request("fp8")
    with pytest.raises(ValueError):
        serve.check_precision_request(16)


# --------------------------------------------------------------------------- #
# codec hardening: _encode_precision / _decode_precision
# --------------------------------------------------------------------------- #


def test_encode_precision_rejects_non_enum_objects():
    from jax import lax

    assert serve._encode_precision(None) is None
    assert serve._encode_precision("highest") == "highest"
    assert serve._encode_precision(lax.Precision.HIGHEST) == \
        ["precision", "HIGHEST"]
    # a non-enum object must be refused while the checkpoint is still
    # writable — with the offending value in the message
    with pytest.raises(ValueError, match=r"\('highest', 'highest'\)"):
        serve._encode_precision(("highest", "highest"))
    with pytest.raises(ValueError, match="float32"):
        serve._encode_precision(np.float32)


def test_decode_precision_rejects_malformed_payloads():
    from jax import lax

    assert serve._decode_precision(None) is None
    assert serve._decode_precision("highest") == "highest"
    assert serve._decode_precision(["precision", "HIGHEST"]) == \
        lax.Precision.HIGHEST
    # the decode-rejection cases: payloads no encoder produced must
    # raise with the value named, never flow into a mismatched PlanKey
    with pytest.raises(ValueError, match="NOPE"):
        serve._decode_precision(["precision", "NOPE"])
    with pytest.raises(ValueError, match="3"):
        serve._decode_precision(["precision", "HIGHEST", 3])
    with pytest.raises(ValueError, match="17"):
        serve._decode_precision(17)
    with pytest.raises(ValueError, match="dict"):
        serve._decode_precision({"precision": "HIGHEST"})


def test_plan_spec_roundtrip_and_spd_migration_shim():
    plan = serve.FactorPlan.create((N, N), np.float32, kind="chol")
    spec = serve.plan_spec(plan)
    assert spec["kind"] == "chol" and "spd" not in spec
    assert serve.plan_from_spec(json.loads(json.dumps(spec))) is plan
    # the §33 migration shim: a pre-refactor spec spelling the family
    # as a bare boolean decodes to the same plan
    old = {k: v for k, v in spec.items() if k != "kind"}
    old["spd"] = True
    assert serve.plan_from_spec(old) is plan
    old["spd"] = False
    assert serve.plan_from_spec(old) is \
        serve.FactorPlan.create((N, N), np.float32, kind="lu")
    bad = dict(spec)
    bad["kind"] = "qz"
    with pytest.raises(ValueError, match="qz"):
        serve.plan_from_spec(bad)


def test_pre_refactor_fleet_checkpoint_restores(tmp_path):
    """Round-trip against a pre-§33 fleet.json fixture: the snapshot is
    rewritten to the old on-disk dialect ('spd' boolean in the plan
    spec, none of the new meta keys in the record manifests) and must
    restore bitwise through the migration shim."""
    A, b = _system(seed=11)
    spd = (A @ A.T + N * np.eye(N)).astype(np.float32)
    lu = serve.FactorPlan.create((N, N), np.float32, kind="lu").factor(A)
    ch = serve.FactorPlan.create((N, N), np.float32,
                                 kind="chol").factor(spd)
    x_lu = np.asarray(lu.solve(b))
    x_ch = np.asarray(ch.solve(b))
    path = os.path.join(tmp_path, "fleet")
    tier.save_fleet(path, [lu, ch], names=["lu", "ch"])
    # rewrite to the pre-refactor dialect
    fj = os.path.join(path, "fleet.json")
    with open(fj) as f:
        fleet = json.load(f)
    for e in fleet["sessions"]:
        e["plan"]["spd"] = e["plan"].pop("kind") == "chol"
    with open(fj, "w") as f:
        json.dump(fleet, f)
    for name in ("lu", "ch"):
        mp = os.path.join(path, name, "manifest.json")
        with open(mp) as f:
            man = json.load(f)
        for k in ("precision", "auto_rung", "probe_parts"):
            man["meta"].pop(k, None)
        with open(mp, "w") as f:
            json.dump(man, f)
    r_lu, r_ch = tier.load_fleet(path)
    assert r_lu.plan.key.kind == "lu" and r_ch.plan.key.kind == "chol"
    assert r_lu.served_tier is None and r_ch.served_tier is None
    assert np.array_equal(x_lu, np.asarray(r_lu.solve(b)))
    assert np.array_equal(x_ch, np.asarray(r_ch.solve(b)))


# --------------------------------------------------------------------------- #
# per-request tier routing (session surface)
# --------------------------------------------------------------------------- #


def test_default_precision_bitwise_and_tier_routing():
    A, b = _system(seed=1)
    plan = serve.FactorPlan.create((N, N), np.float32, kind="lu",
                                   refine=1)
    s = plan.factor(A)
    x0 = np.asarray(s.solve(b))
    # default None is the pre-§33 program, bitwise
    assert np.array_equal(x0, np.asarray(s.solve(b, precision=None)))
    # the f32 tier of an f32-native plan computes the same factors at
    # the same dtype/sweeps — same answer
    xf = np.asarray(s.solve(b, precision="f32"))
    assert _resid(A, xf, b) < 1e-5
    xb = np.asarray(s.solve(b, precision="bf16_ir"))
    assert _resid(A, xb, b) < 1e-2  # bf16 factors + 1 IR sweep
    with pytest.raises(ValueError, match="fp8"):
        s.solve(b, precision="fp8")


def test_factor_at_tier_opens_smaller_session():
    A, b = _system(seed=2)
    plan = serve.FactorPlan.create((N, N), np.float32, kind="lu",
                                   refine=1)
    native = plan.factor(A)
    tiered = plan.factor(A, precision="bf16_ir")
    assert native.served_tier is None
    assert tiered.served_tier == "bf16_ir"
    # the capacity mechanism: bf16 factors are ~half the resident bytes
    assert tiered.nbytes < 0.85 * native.nbytes
    assert _resid(A, np.asarray(tiered.solve(b)), b) < 1e-2
    # an explicit native-tier request on a tiered session re-routes
    # through the derived cross-tier cache and matches the native bits
    xf = np.asarray(tiered.solve(b, precision="f32"))
    assert np.array_equal(xf, np.asarray(native.solve(b,
                                                      precision="f32")))


def test_drifted_session_cross_tier_falls_back_counted():
    A, b = _system(seed=4)
    rng = np.random.default_rng(4)
    plan = serve.FactorPlan.create((N, N), np.float32, kind="lu",
                                   refine=1)
    s = plan.factor(A)
    u = (rng.standard_normal((N, 1)) * 0.01).astype(np.float32)
    v = (rng.standard_normal((N, 1)) * 0.01).astype(np.float32)
    s.update(u, v)
    A1 = A + u @ v.T
    # a drifted session serving a cross-tier request answers against
    # the DRIFTED system on its resident path — counted, not an error
    x = np.asarray(s.solve(b, precision="bf16_ir"))
    assert _resid(A1, x, b) < 1e-4
    assert s.precision_fallbacks == 1


def test_bucket_lifecycle_tier_families():
    A, _b = _system(seed=5)
    plan = serve.FactorPlan.create((N, N), np.float32, kind="lu",
                                   refine=1)
    s = plan.factor(A)
    eng = ServeEngine(max_batch_delay=0.001)
    try:
        assert not plan.bucket_ready(width=2, precision="bf16_ir")
        eng.prewarm(s, widths=(2,), factor_batches=(1, 2),
                    precisions=("bf16_ir",))
        assert plan.bucket_ready(width=2, precision="bf16_ir")
        assert plan.bucket_ready(factor_batch=2, precision="bf16_ir")
        with pytest.raises(ValueError, match="auto"):
            plan.bucket_ready(width=2, precision="auto")
        with pytest.raises(ValueError, match="gang"):
            plan.bucket_ready(stack=(2, 2), precision="bf16_ir")
        # retirement drops the tier families with their buckets
        assert plan.release_buckets(widths=(2,),
                                    factor_batches=(2,)) > 0
        assert not plan.bucket_ready(width=2, precision="bf16_ir")
        assert not plan.bucket_ready(factor_batch=2,
                                     precision="bf16_ir")
    finally:
        eng.close()


# --------------------------------------------------------------------------- #
# engine routing + the auto ladder
# --------------------------------------------------------------------------- #


def test_engine_precision_routing_and_zero_compiles():
    A, b = _system(seed=6)
    plan = serve.FactorPlan.create((N, N), np.float32, kind="lu",
                                   refine=1)
    s = plan.factor(A)
    eng = ServeEngine(max_batch_delay=0.001)
    try:
        eng.prewarm(s, widths=(1, 2), factor_batches=(1, 2),
                    precisions=("auto",))
        t0 = dict(plan.trace_counts)
        x0 = eng.submit(s, b).result(timeout=60)
        xa = eng.submit(s, b, precision="auto").result(timeout=60)
        xb = eng.submit(s, b, precision="bf16_ir").result(timeout=60)
        s2 = eng.submit_factor(plan, A, precision="auto") \
                .result(timeout=60)
        x2 = eng.submit(s2, b, precision="auto").result(timeout=60)
        assert {k: v - t0.get(k, 0) for k, v in plan.trace_counts.items()
                if v - t0.get(k, 0)} == {}, "steady state recompiled"
        assert np.array_equal(np.asarray(x0), np.asarray(s.solve(b)))
        assert np.array_equal(np.asarray(xa), np.asarray(xb))
        assert s2.served_tier == "bf16_ir"
        assert _resid(A, x2, b) < 1e-2
        with pytest.raises(ValueError, match="fp8"):
            eng.submit(s, b, precision="fp8")
    finally:
        eng.close()


def test_auto_ladder_escalates_and_sticks():
    Abad = _ill_conditioned()
    b = np.random.default_rng(7).standard_normal(N).astype(np.float32)
    plan = serve.FactorPlan.create((N, N), np.float32, kind="lu",
                                   refine=1)
    s = plan.factor(Abad)
    eng = ServeEngine(max_batch_delay=0.001)
    try:
        x = eng.submit(s, b, precision="auto").result(timeout=120)
        # the bf16 rung's verdict trips on a cond~1e6 system; the
        # ladder climbs to f32 and answers there
        assert _resid(Abad, x, b) < 1e-2
        assert s.precision_escalations >= 1
        assert s.auto_rung >= 1
        rung = s.auto_rung
        # sticky: the next auto request starts AT the learned rung
        # (no repeated bf16 failures)
        esc0 = s.precision_escalations
        x2 = eng.submit(s, b, precision="auto").result(timeout=120)
        assert _resid(Abad, x2, b) < 1e-2
        assert s.auto_rung == rung
        assert s.precision_escalations == esc0
        st = eng.stats()
        assert st["precision_escalations"] >= 1
    finally:
        eng.close()


def test_mesh_plans_reject_precision():
    from conflux_tpu import batched
    from conflux_tpu.resilience import MeshPlanUnsupported

    mesh = batched.batch_mesh()
    plan = serve.FactorPlan.create((8, 64, 64), np.float32, v=32,
                                   kind="lu", mesh=mesh)
    rng = np.random.default_rng(8)
    A = (rng.standard_normal((8, 64, 64)) / 8
         + 2 * np.eye(64)).astype(np.float32)
    # the plan surface refuses before any factor work (serve layer
    # speaks ValueError; the engine surfaces MeshPlanUnsupported)
    with pytest.raises(ValueError, match="native precision"):
        plan.factor(A, precision="bf16_ir")
    eng = ServeEngine(max_batch_delay=0.001)
    try:
        s = plan.factor(A)
        b = rng.standard_normal((8, 64)).astype(np.float32)
        with pytest.raises(MeshPlanUnsupported):
            eng.submit(s, b, precision="auto")
        with pytest.raises(MeshPlanUnsupported):
            eng.submit_factor(plan, A, precision="bf16_ir")
    finally:
        eng.close()


# --------------------------------------------------------------------------- #
# tiering: spill/revive + checkpoint keep the served tier
# --------------------------------------------------------------------------- #


def test_tier_session_spill_revive_checkpoint_bitwise(tmp_path):
    A, b = _system(seed=9)
    plan = serve.FactorPlan.create((N, N), np.float32, kind="lu",
                                   refine=1)
    s = plan.factor(A, precision="bf16_ir")
    x0 = np.asarray(s.solve(b))
    rs = tier.ResidentSet(max_sessions=4).adopt(s)
    rs.spill(s)
    assert np.array_equal(x0, np.asarray(s.solve(b)))
    assert s.served_tier == "bf16_ir"
    path = os.path.join(tmp_path, "fleet")
    tier.save_fleet(path, [s])
    (r,) = tier.load_fleet(path)
    assert r.served_tier == "bf16_ir"
    assert np.array_equal(x0, np.asarray(r.solve(b)))


def test_escalate_precision_ladder_direct():
    """The resilience rung sequence without an engine: bf16 verdict
    evidence -> escalate_precision climbs to f32, evidence chain
    carries the tier rung."""
    Abad = _ill_conditioned(seed=10)
    b = np.random.default_rng(10).standard_normal(N).astype(np.float32)
    plan = serve.FactorPlan.create((N, N), np.float32, kind="lu",
                                   refine=1)
    s = plan.factor(Abad)
    x, verdict = s.solve_checked(b, precision="auto")
    finite, res = (float(np.asarray(verdict)[0]),
                   float(np.asarray(verdict)[1]))
    pol = resilience.HealthPolicy()
    limit = pol.resolved_residual_limit(np.dtype(np.float32), N)
    assert res > limit  # the bf16 rung really is unhealthy here
    b2 = b[:, None]
    out = resilience.escalate_precision(
        s, b2, "auto", pol, limit,
        evidence0={"rung": "bf16_ir", "finite": finite,
                   "residual": res})
    assert _resid(Abad, out[..., 0], b) < 1e-2
    assert s.auto_rung >= 1 and s.precision_escalations >= 1
