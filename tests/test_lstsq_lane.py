"""QR-backed least-squares sessions through the serve path (§33).

`FactorPlan.create(kind='qr')` opens tall-skinny min||Ax-b|| sessions
whose (Q, R) factor pytree rides the pytree-generic machinery: engine
coalescing (solve + factor lanes), tier spill/revive, checkpoint/
restore — all BITWISE against the direct session path — and gang
exclusion accounting (a QR plan that cannot gang is a COUNTED
exclusion, never an error). Residue counters stay zero on healthy
traces.
"""

import os

import numpy as np
import pytest

from conflux_tpu import qos, serve, tier
from conflux_tpu.engine import ServeEngine

M, N = 512, 256


def _lstsq_system(m=M, n=N, seed=0):
    rng = np.random.default_rng(seed)
    A = rng.standard_normal((m, n)).astype(np.float32)
    b = rng.standard_normal(m).astype(np.float32)
    return A, b


def _lstsq_oracle(A, b):
    return np.linalg.lstsq(A.astype(np.float64), b.astype(np.float64),
                           rcond=None)[0]


# --------------------------------------------------------------------------- #
# the session surface
# --------------------------------------------------------------------------- #


def test_qr_session_solves_least_squares():
    A, b = _lstsq_system()
    plan = serve.FactorPlan.create((M, N), np.float32, kind="qr")
    assert plan.key.kind == "qr" and plan.M == M and plan.N == N
    s = plan.factor(A)
    x = np.asarray(s.solve(b))
    assert x.shape == (N,)
    assert np.abs(x.astype(np.float64) - _lstsq_oracle(A, b)).max() < 1e-4
    # multi-rhs
    B2 = np.random.default_rng(1).standard_normal((M, 3)) \
        .astype(np.float32)
    X = np.asarray(s.solve(B2))
    assert X.shape == (N, 3)
    for j in range(3):
        assert np.abs(X[:, j].astype(np.float64)
                      - _lstsq_oracle(A, B2[:, j])).max() < 1e-4
    # an N-row rhs is the wrong surface for an (M, N) plan
    with pytest.raises(ValueError, match=str(M)):
        s.solve(b[:N])


def test_qr_checked_verdict_trips_on_corruption():
    A, b = _lstsq_system(seed=2)
    plan = serve.FactorPlan.create((M, N), np.float32, kind="qr")
    s = plan.factor(A)
    x, verdict = s.solve_checked(b)
    v = np.asarray(verdict)
    assert v[0] == 1.0 and v[1] < 1e-4  # finite, tiny projected residual
    # the §20-analog guard: u in range(A) is orthogonal to the
    # least-squares residual, so u.b - (u^T A) x vanishes at the
    # optimum — poisoned factors must trip it
    with s._lock:
        s._factors = tuple(f * np.nan for f in s._factors)
    _x, bad = s.solve_checked(b)
    assert np.asarray(bad)[0] == 0.0


def test_qr_sessions_reject_woodbury_updates():
    A, _b = _lstsq_system(seed=3)
    plan = serve.FactorPlan.create((M, N), np.float32, kind="qr")
    s = plan.factor(A)
    u = np.zeros((M, 1), np.float32)
    v = np.zeros((N, 1), np.float32)
    with pytest.raises(ValueError, match="qr"):
        s.update(u, v)


def test_qr_rejects_batched_and_square_validation():
    with pytest.raises(ValueError):
        serve.FactorPlan.create((4, M, N), np.float32, kind="qr")
    with pytest.raises(ValueError):
        serve.FactorPlan.create((N, M), np.float32, kind="qr")  # M < N


def test_request_cost_prices_by_rows():
    sq = qos.request_cost((N, N), width=1)
    tall = qos.request_cost((M, N), width=1)
    assert tall >= sq  # O(M N w) vs O(N^2 w), M = 2N here
    # factor pricing: O(M N^2) reduces exactly to N^3 when square
    assert qos.request_cost((N, N), factor=True) == \
        max(1.0, float(N) ** 3 / qos.REF_FACTOR_UNITS)
    assert qos.request_cost((M, N), factor=True) == \
        max(1.0, M * float(N) ** 2 / qos.REF_FACTOR_UNITS)


# --------------------------------------------------------------------------- #
# engine lanes: coalescing bitwise, exclusions counted, residue zero
# --------------------------------------------------------------------------- #


def test_lstsq_rides_engine_coalescing_bitwise():
    plan = serve.FactorPlan.create((M, N), np.float32, kind="qr")
    systems = [_lstsq_system(seed=10 + i) for i in range(4)]
    eng = ServeEngine(max_batch_delay=0.02)
    try:
        # factor lane: coalesced cold starts open QR sessions
        futs = [eng.submit_factor(plan, A) for A, _ in systems]
        sessions = [f.result(timeout=120) for f in futs]
        # solve lane: coalesced requests answer bitwise what the
        # direct session path answers
        direct = [np.asarray(s.solve(b))
                  for s, (_, b) in zip(sessions, systems)]
        futs = [eng.submit(s, b)
                for s, (_, b) in zip(sessions, systems)]
        served = [np.asarray(f.result(timeout=120)) for f in futs]
        for d, v, (A, b) in zip(direct, served, systems):
            assert np.array_equal(d, v)
            assert np.abs(v.astype(np.float64)
                          - _lstsq_oracle(A, b)).max() < 1e-4
        st = eng.stats()
        assert st["failed"] == 0
        assert st["factor_coalesced_requests"] == 4
    finally:
        eng.close()


def test_qr_gang_exclusion_counted_not_error():
    plan = serve.FactorPlan.create((M, N), np.float32, kind="qr")
    systems = [_lstsq_system(seed=20 + i) for i in range(3)]
    eng = ServeEngine(max_batch_delay=0.02, stack_sessions=True)
    try:
        sessions = [plan.factor(A) for A, _ in systems]
        futs = [eng.submit(s, b)
                for s, (_, b) in zip(sessions, systems)]
        for f, s, (A, b) in zip(futs, sessions, systems):
            x = np.asarray(f.result(timeout=120))
            assert np.array_equal(x, np.asarray(s.solve(b)))
        st = eng.stats()
        # the (M, N) factor shapes cannot gang-stack: a counted
        # exclusion per session, never a failure
        assert st["stack_exclusions"]["kind"] >= 3
        assert st["failed"] == 0
        assert st["gang_batches"] == 0
    finally:
        eng.close()


# --------------------------------------------------------------------------- #
# tiering + checkpoint: bitwise round trips
# --------------------------------------------------------------------------- #


def test_lstsq_spill_revive_bitwise():
    A, b = _lstsq_system(seed=30)
    plan = serve.FactorPlan.create((M, N), np.float32, kind="qr")
    s = plan.factor(A)
    x0, v0 = s.solve_checked(b)
    x0, v0 = np.asarray(x0), np.asarray(v0)
    rs = tier.ResidentSet(max_sessions=4).adopt(s)
    assert rs.spill(s) == 1
    x1, v1 = s.solve_checked(b)
    assert np.array_equal(x0, np.asarray(x1))
    assert np.array_equal(v0, np.asarray(v1))  # (u, uA) probe survived
    # coalesced revival: same-plan QR records stack through ONE h2d
    others = [plan.factor(_lstsq_system(seed=31 + i)[0])
              for i in range(2)]
    base = [np.asarray(o.solve(b)) for o in others]
    rs.adopt(*others)
    rs.spill(*others)
    assert rs.revive_many(others) == 2
    for o, x in zip(others, base):
        assert np.array_equal(x, np.asarray(o.solve(b)))


def test_lstsq_checkpoint_restore_bitwise(tmp_path):
    A, b = _lstsq_system(seed=40)
    plan = serve.FactorPlan.create((M, N), np.float32, kind="qr")
    s = plan.factor(A)
    x0, v0 = s.solve_checked(b)
    x0, v0 = np.asarray(x0), np.asarray(v0)
    path = os.path.join(tmp_path, "fleet")
    tier.save_fleet(path, [s], names=["lstsq"])
    (r,) = tier.load_fleet(path)
    assert r.plan is plan  # exact key -> same cached plan
    x1, v1 = r.solve_checked(b)
    assert np.array_equal(x0, np.asarray(x1))
    assert np.array_equal(v0, np.asarray(v1))
