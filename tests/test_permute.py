"""Permutation-op tests — direct analog of the reference's gtest suite
(`tests/unit/test_utils.cpp`: push_pivots_up with hand-computed expected
output, permute_rows over shape cases, inverse round-trip property)."""

import jax.numpy as jnp
import numpy as np
import pytest

from conflux_tpu.ops.permute import (
    inverse_permute_rows,
    invert_permutation,
    permute_rows,
    prepend_column,
    push_pivots_up,
)


def test_push_pivots_up_hand_checked():
    # mirrors the hand-computed style of test_utils.cpp:8-84
    A = jnp.asarray(np.arange(20.0).reshape(5, 4))
    mask = jnp.asarray([False, True, False, False, True])
    out, perm = push_pivots_up(A, mask)
    expected_order = [1, 4, 0, 2, 3]  # pivots first, stable within groups
    assert perm.tolist() == expected_order
    np.testing.assert_array_equal(np.asarray(out), np.asarray(A)[expected_order])


def test_push_pivots_up_no_pivots():
    A = jnp.asarray(np.random.default_rng(0).standard_normal((6, 3)))
    out, perm = push_pivots_up(A, jnp.zeros(6, bool))
    np.testing.assert_array_equal(np.asarray(out), np.asarray(A))
    assert perm.tolist() == list(range(6))


def test_push_pivots_up_all_pivots():
    A = jnp.asarray(np.random.default_rng(1).standard_normal((4, 4)))
    out, perm = push_pivots_up(A, jnp.ones(4, bool))
    np.testing.assert_array_equal(np.asarray(out), np.asarray(A))


@pytest.mark.parametrize("shape", [(1, 1), (4, 4), (7, 3), (16, 5)])
def test_permute_rows_shapes(shape):
    rng = np.random.default_rng(shape[0])
    A = jnp.asarray(rng.standard_normal(shape))
    perm = jnp.asarray(rng.permutation(shape[0]))
    out = permute_rows(A, perm)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(A)[np.asarray(perm)])


def test_inverse_permute_roundtrip():
    # the round-trip property test (test_utils.cpp:426-768)
    rng = np.random.default_rng(3)
    A = jnp.asarray(rng.standard_normal((12, 7)))
    perm = jnp.asarray(rng.permutation(12))
    back = inverse_permute_rows(permute_rows(A, perm), perm)
    np.testing.assert_array_equal(np.asarray(back), np.asarray(A))


def test_invert_permutation():
    perm = jnp.asarray([2, 0, 3, 1])
    inv = invert_permutation(perm)
    assert np.asarray(inv)[np.asarray(perm)].tolist() == [0, 1, 2, 3]


def test_prepend_column():
    A = jnp.ones((3, 2))
    col = jnp.asarray([5, 6, 7])
    out = prepend_column(A, col)
    assert out.shape == (3, 3)
    assert out[:, 0].tolist() == [5.0, 6.0, 7.0]


def test_swap_minimal_perm_basic():
    import jax.numpy as jnp
    import numpy as np

    from conflux_tpu.ops.permute import swap_minimal_perm

    # winners 5, 1 of m=8: slot0<-5, slot1<-1 (already there), row0 drops
    # into the slot row 5 vacated; everything else stays put
    sperm = np.asarray(swap_minimal_perm(jnp.array([5, 1]), 8))
    assert sorted(sperm.tolist()) == list(range(8))
    assert sperm[0] == 5 and sperm[1] == 1
    assert (sperm != np.arange(8)).sum() <= 4


def test_swap_minimal_perm_random_is_permutation():
    import jax.numpy as jnp
    import numpy as np

    from conflux_tpu.ops.permute import swap_minimal_perm

    rng = np.random.default_rng(0)
    for m, v in [(16, 4), (64, 8), (256, 32)]:
        for _ in range(20):
            gpiv = rng.choice(m, size=v, replace=False)
            sperm = np.asarray(swap_minimal_perm(jnp.asarray(gpiv), m))
            assert sorted(sperm.tolist()) == list(range(m)), (m, v, gpiv)
            np.testing.assert_array_equal(sperm[:v], gpiv)
            assert (sperm != np.arange(m)).sum() <= 2 * v


def test_swap_minimal_perm_sanitizes_out_of_range():
    import jax.numpy as jnp
    import numpy as np

    from conflux_tpu.ops.permute import swap_minimal_perm

    # pad ids >= m (rank-deficient tournament) must still yield a permutation
    sperm = np.asarray(swap_minimal_perm(jnp.array([10, 3]), 8))
    assert sorted(sperm.tolist()) == list(range(8))
    assert sperm[1] == 3
