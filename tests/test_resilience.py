"""Serve-path resilience tests: the ISSUE 4 acceptance contracts.

- Blast-radius isolation: a NaN injected into one request at STAGING
  fails that request's future alone — every co-batched request in the
  same coalescing window still returns a BITWISE-correct answer.
- Escalation ladder: an injected drift-solve health failure triggers
  exactly ONE forced-refactor escalation (riding the plan's cached
  factor program) and then succeeds; a full-ladder loss raises a
  structured `SolveUnhealthy` with per-rung evidence.
- Deadlines: lazy eviction fires mid-window, fails the future with
  `DeadlineExceeded`, and RELEASES the pending slot (un-wedging an
  `on_full='block'` submitter).
- Quarantine: the circuit breaker opens after K consecutive ladder
  failures (fast `SessionQuarantined`), half-open probes after the
  cooldown, and closes again on a healthy answer.
- Fault recovery: an injected drain crash re-dispatches the innocent
  survivors solo instead of failing the batch; a killed worker thread
  trips the watchdog, which fails pending work instead of queueing
  forever; a wedged `close(timeout)` names the stuck thread and fails
  still-pending futures.
- All outcomes surface through `profiler.serve_stats()['health']`.
"""

import dataclasses
import threading
import time

import numpy as np
import pytest

import jax.numpy as jnp

from conflux_tpu import profiler, resilience, serve
from conflux_tpu.engine import (
    EngineClosed,
    EngineSaturated,
    ServeEngine,
)
from conflux_tpu.resilience import (
    CircuitBreaker,
    DeadlineExceeded,
    FaultPlan,
    FaultSpec,
    HealthPolicy,
    RhsNonFinite,
    SessionQuarantined,
    SolveUnhealthy,
)

N, V = 32, 16


def _system(seed=0, n=N):
    rng = np.random.default_rng(seed)
    return (rng.standard_normal((n, n)) / np.sqrt(n)
            + 2.0 * np.eye(n)).astype(np.float32)


def _session(seed=0):
    serve.clear_plans()
    plan = serve.FactorPlan.create((N, N), jnp.float32, v=V)
    return plan.factor(jnp.asarray(_system(seed)))


def _rhs(seed=1, w=2):
    rng = np.random.default_rng(seed)
    shape = (N, w) if w > 1 else (N,)
    return rng.standard_normal(shape).astype(np.float32)


def _delta(before, after):
    return {k: after[k] - before.get(k, 0) for k in after
            if after[k] != before.get(k, 0)}


# --------------------------------------------------------------------- #
# admission guards + blast-radius isolation
# --------------------------------------------------------------------- #


def test_submit_guard_rejects_nonfinite_rhs():
    session = _session(11)
    bad = _rhs(11)
    bad[3, 0] = np.inf
    h0 = resilience.health_stats()
    with ServeEngine(max_batch_delay=0.0, health=HealthPolicy()) as eng:
        with pytest.raises(RhsNonFinite, match="admission"):
            eng.submit(session, bad)
        # the reject consumed no pending slot
        assert eng.stats()["pending"] == 0
        good = _rhs(12)
        np.testing.assert_allclose(
            eng.solve(session, good, timeout=60),
            np.asarray(session.solve(good)), rtol=1e-5, atol=1e-6)
    assert resilience.health_stats()["rhs_rejects"] \
        - h0["rhs_rejects"] == 1


def test_staging_nan_isolates_to_one_future_bitwise_survivors():
    """The acceptance contract: a request poisoned AFTER admission (the
    seeded staging fault) fails its own future; the co-batched requests
    in the SAME window get bitwise the answers they would have gotten
    alone."""
    session = _session(13)
    bs = [_rhs(20 + i, w) for i, w in enumerate((2, 2, 1))]
    direct = [np.asarray(session.solve(b)) for b in bs]
    faults = FaultPlan([FaultSpec("staging", "nan", count=1)])
    h0 = resilience.health_stats()
    eng = ServeEngine(max_batch_delay=60.0, health=HealthPolicy(),
                      fault_plan=faults)
    futs = [eng.submit(session, b) for b in bs]  # one window, one batch
    assert eng.close(timeout=120) == []
    with pytest.raises(RhsNonFinite, match="staging"):
        futs[0].result(0)  # the poisoned request fails ALONE
    for f, d in zip(futs[1:], direct[1:]):  # survivors: bitwise
        np.testing.assert_array_equal(np.asarray(f.result(0)), d)
    dh = _delta(h0, resilience.health_stats())
    assert dh["staging_isolations"] == 1
    assert dh["faults_injected"] == 1
    assert faults.injected[("staging", "nan")] == 1


def test_drain_crash_redispatches_survivors():
    """Satellite: a batch-attributable drain failure routes through solo
    survivor re-dispatch — every innocent request still gets its answer,
    and the worker thread survives to serve later traffic."""
    session = _session(17)
    bs = [_rhs(30 + i, 2) for i in range(3)]
    direct = [np.asarray(session.solve(b)) for b in bs]
    faults = FaultPlan([FaultSpec("drain", "crash", count=1)])
    h0 = resilience.health_stats()
    eng = ServeEngine(max_batch_delay=60.0, fault_plan=faults)
    futs = [eng.submit(session, b) for b in bs]
    # close() dispatches the window; the crash hits at drain; all three
    # requests recover through solo re-dispatch
    assert eng.close(timeout=120) == []
    for f, d in zip(futs, direct):
        np.testing.assert_array_equal(np.asarray(f.result(0)), d)
    assert _delta(h0, resilience.health_stats())[
        "survivor_redispatches"] == 3


# --------------------------------------------------------------------- #
# output health + the escalation ladder
# --------------------------------------------------------------------- #


def test_drift_health_failure_one_refactor_escalation_then_succeeds():
    """The acceptance contract: an injected health failure on a DRIFTED
    solve climbs exactly one rung — a forced refactor through the
    plan's cached factor program — and the retried answer is healthy
    and correct against the drifted oracle."""
    session = _session(19)
    A = _system(19)
    rng = np.random.default_rng(91)
    U = (0.01 * rng.standard_normal((N, 2))).astype(np.float32)
    Vm = (0.01 * rng.standard_normal((N, 2))).astype(np.float32)
    session.update(U, Vm)
    assert session.update_rank == 2 and session.refactors == 0
    b = _rhs(92, 2)
    faults = FaultPlan([FaultSpec("solve", "unhealthy", count=1)])
    h0 = resilience.health_stats()
    trace0 = dict(session.plan.trace_counts)
    with ServeEngine(max_batch_delay=0.0, health=HealthPolicy(),
                     fault_plan=faults) as eng:
        x = eng.solve(session, b, timeout=120)
    dh = _delta(h0, resilience.health_stats())
    assert dh["output_failures"] == 1
    assert dh["refactor_escalations"] == 1
    assert "refine_escalations" not in dh and "unhealthy" not in dh
    assert session.refactors == 1 and session.update_rank == 0
    # the escalation rode the CACHED factor program — no new factor trace
    assert session.plan.trace_counts["factor"] == trace0["factor"]
    oracle = np.linalg.solve(A + U @ Vm.T, b)
    np.testing.assert_allclose(np.asarray(x), oracle, rtol=2e-3,
                               atol=1e-4)


def test_ladder_exhaustion_raises_structured_solve_unhealthy():
    session = _session(23)
    b = _rhs(94, 1)
    # initial verdict + refactor rung + refine rung all forced unhealthy
    faults = FaultPlan([FaultSpec("solve", "unhealthy", count=3)])
    h0 = resilience.health_stats()
    with ServeEngine(max_batch_delay=0.0, health=HealthPolicy(),
                     fault_plan=faults) as eng:
        fut = eng.submit(session, b)
        with pytest.raises(SolveUnhealthy) as ei:
            fut.result(120)
    ev = ei.value.evidence
    assert [r["rung"] for r in ev["rungs"]] == \
        ["dispatch", "refactor", "refine"]
    assert ev["residual_limit"] > 0 and ev["update_rank"] == 0
    dh = _delta(h0, resilience.health_stats())
    assert dh["unhealthy"] == 1
    assert dh["refactor_escalations"] == 1
    assert dh["refine_escalations"] == 1


def test_unhealthy_batch_isolates_then_survivors_answer():
    """A forced-unhealthy verdict on a MULTI-request batch first
    isolates (solo re-dispatch); the re-checks pass, so every request
    answers — no collateral failures from one bad verdict."""
    session = _session(27)
    bs = [_rhs(40 + i, 1) for i in range(3)]
    direct = [np.asarray(session.solve(b)) for b in bs]
    faults = FaultPlan([FaultSpec("solve", "unhealthy", count=1)])
    h0 = resilience.health_stats()
    eng = ServeEngine(max_batch_delay=60.0, health=HealthPolicy(),
                      fault_plan=faults)
    futs = [eng.submit(session, b) for b in bs]
    assert eng.close(timeout=120) == []
    for f, d in zip(futs, direct):
        np.testing.assert_array_equal(np.asarray(f.result(0)), d)
    dh = _delta(h0, resilience.health_stats())
    assert dh["output_failures"] == 1
    assert dh["survivor_redispatches"] == 3


# --------------------------------------------------------------------- #
# deadlines + backpressure hints
# --------------------------------------------------------------------- #


def test_deadline_evicts_mid_window():
    session = _session(29)
    h0 = resilience.health_stats()
    eng = ServeEngine(max_batch_delay=60.0)
    t0 = time.perf_counter()
    fut = eng.submit(session, _rhs(95), deadline=0.1)
    with pytest.raises(DeadlineExceeded, match="slot released"):
        fut.result(30)
    # evicted when the deadline passed, not when the 60s window closed
    assert time.perf_counter() - t0 < 30
    assert eng.stats()["pending"] == 0
    assert eng.close(timeout=60) == []
    assert _delta(h0, resilience.health_stats())["evictions"] == 1


def test_deadline_eviction_frees_slots_under_block():
    """The acceptance contract: expired requests release their pending
    slots, so a blocked submitter gets through instead of deadlocking
    behind abandoned work."""
    session = _session(31)
    b = _rhs(96)
    eng = ServeEngine(max_batch_delay=60.0, max_pending=2,
                      on_full="block")
    f1 = eng.submit(session, b, deadline=0.0)   # already expired:
    f2 = eng.submit(session, b, deadline=0.0)   # lazy eviction fodder
    got = []
    t = threading.Thread(target=lambda: got.append(eng.submit(session, b)))
    t.start()
    t.join(timeout=60)
    assert not t.is_alive(), "eviction did not release the blocked slot"
    for f in (f1, f2):
        with pytest.raises(DeadlineExceeded):
            f.result(30)
    assert eng.close(timeout=120) == []
    np.testing.assert_array_equal(np.asarray(got[0].result(0)),
                                  np.asarray(session.solve(b)))


def test_saturated_carries_growing_backoff_hint():
    session = _session(37)
    b = _rhs(97)
    eng = ServeEngine(max_batch_delay=60.0, max_pending=1)
    eng.submit(session, b)
    hints = []
    for _ in range(3):
        with pytest.raises(EngineSaturated) as ei:
            eng.submit(session, b)
        assert "retry in" in str(ei.value)
        hints.append(ei.value.retry_after)
    assert hints[0] > 0 and hints[1] == 2 * hints[0] \
        and hints[2] == 2 * hints[1]
    assert eng.close(timeout=60) == []


# --------------------------------------------------------------------- #
# quarantine circuit breaker
# --------------------------------------------------------------------- #


def test_quarantine_opens_and_half_open_recovers():
    """The breaker opens after `quarantine_after` consecutive ladder
    failures (fast SessionQuarantined, no device work), admits one probe
    after the cooldown, and a healthy probe closes the circuit."""
    session = _session(41)
    b = _rhs(98, 1)
    # one full ladder loss: initial + refactor + refine verdicts forced
    faults = FaultPlan([FaultSpec("solve", "unhealthy", count=3)])
    policy = HealthPolicy(quarantine_after=1, quarantine_cooldown=30.0)
    h0 = resilience.health_stats()
    eng = ServeEngine(max_batch_delay=0.0, health=policy,
                      fault_plan=faults)
    with pytest.raises(SolveUnhealthy):
        eng.submit(session, b).result(120)
    assert session._breaker.state == "open"
    with pytest.raises(SessionQuarantined) as ei:
        eng.submit(session, b)
    assert ei.value.retry_after > 0
    # cooldown elapses (deterministically — no sleep): half-open probe
    session._breaker.cooldown = 0.0
    x = eng.solve(session, b, timeout=120)   # the probe, now healthy
    assert session._breaker.state == "closed"
    np.testing.assert_array_equal(np.asarray(x),
                                  np.asarray(session.solve(b)))
    assert eng.close(timeout=60) == []
    dh = _delta(h0, resilience.health_stats())
    assert dh["quarantine_opened"] == 1
    assert dh["quarantine_probes"] >= 1
    assert dh["quarantine_recoveries"] == 1


def test_breaker_sick_probe_reopens():
    clock = [0.0]
    br = CircuitBreaker(threshold=2, cooldown=10.0,
                        clock=lambda: clock[0])
    assert br.allow() == (True, 0.0)
    br.record_failure()
    br.record_failure()
    assert br.state == "open"
    ok, retry = br.allow()
    assert not ok and retry == pytest.approx(10.0)
    clock[0] = 11.0
    assert br.allow()[0]            # the half-open probe
    assert not br.allow()[0]        # only ONE probe per window
    br.record_failure()             # sick probe: straight back open
    assert br.state == "open"
    clock[0] = 22.0
    assert br.allow()[0]
    br.record_success()
    assert br.state == "closed"


# --------------------------------------------------------------------- #
# watchdog + close(timeout)
# --------------------------------------------------------------------- #


def test_watchdog_fails_pending_when_worker_dies():
    session = _session(43)
    faults = FaultPlan([FaultSpec("drain", "kill", count=1)])
    h0 = resilience.health_stats()
    eng = ServeEngine(max_batch_delay=0.0, fault_plan=faults,
                      watchdog_interval=0.05)
    fut = eng.submit(session, _rhs(99))
    with pytest.raises(EngineClosed, match="died"):
        fut.result(60)
    with pytest.raises(EngineClosed):
        eng.submit(session, _rhs(99))
    assert _delta(h0, resilience.health_stats())["watchdog_trips"] >= 1
    eng.close(timeout=10)


def test_close_timeout_reports_wedged_thread_and_fails_pending():
    """Satellite: a wedged close() names the stuck worker and fails the
    still-pending futures with EngineClosed instead of leaving them
    hanging forever — and the wedged thread waking up later cannot
    double-resolve them (resolution ownership)."""
    session = _session(47)
    faults = FaultPlan([FaultSpec("dispatch", "delay", delay_s=1.5,
                                  count=1)])
    eng = ServeEngine(max_batch_delay=0.0, fault_plan=faults)
    fut = eng.submit(session, _rhs(100))
    time.sleep(0.05)  # let the dispatcher enter the injected sleep
    wedged = eng.close(timeout=0.2)
    assert "serve-engine-dispatch" in wedged
    with pytest.raises(EngineClosed, match="wedged"):
        fut.result(10)
    time.sleep(1.6)  # wedged worker wakes; must not double-resolve
    with pytest.raises(EngineClosed):
        fut.result(0)


# --------------------------------------------------------------------- #
# clean path + thread hammer + observability
# --------------------------------------------------------------------- #


def test_guarded_clean_path_zero_compiles_after_prewarm():
    session = _session(53)
    plan = session.plan
    with ServeEngine(max_batch_delay=0.02, max_coalesce_width=4,
                     health=HealthPolicy()) as eng:
        eng.prewarm(session, widths=(1, 2, 4))
        snapshot = dict(plan.trace_counts)
        futs = [eng.submit(session, _rhs(50 + i, 1 + i % 2))
                for i in range(12)]
        for f in futs:
            f.result(timeout=60)
        assert plan.trace_counts == snapshot, \
            "guarded steady-state traffic compiled after prewarm"


def test_thread_hammer_every_future_resolves():
    """Chaos hammer: concurrent submitters, mixed clean / poisoned /
    zero-deadline traffic, staging faults injected — every future
    resolves (an answer or a structured resilience error), no request
    leaks a slot, clean answers match direct solves."""
    sessions = [_session(59), ]
    plan = sessions[0].plan
    sessions.append(plan.factor(jnp.asarray(_system(61))))
    faults = FaultPlan([FaultSpec("staging", "nan", prob=0.2, count=4),
                        FaultSpec("drain", "crash", count=1)], seed=7)
    eng = ServeEngine(max_batch_delay=0.001, health=HealthPolicy(),
                      fault_plan=faults)
    results: list = []
    lock = threading.Lock()
    errs: list = []

    def pump(tid):
        rng = np.random.default_rng(tid)
        for i in range(10):
            s = sessions[(tid + i) % 2]
            kind = i % 5
            try:
                if kind == 3:  # poisoned at the source
                    bad = rng.standard_normal((N, 1)).astype(np.float32)
                    bad[0, 0] = np.nan
                    fut, b = eng.submit(s, bad), None
                elif kind == 4:  # born expired
                    b = rng.standard_normal(N).astype(np.float32)
                    fut = eng.submit(s, b, deadline=0.0)
                else:
                    b = rng.standard_normal(
                        (N, 1 + i % 2)).astype(np.float32)
                    fut = eng.submit(s, b)
            except (RhsNonFinite, SessionQuarantined):
                continue
            with lock:
                results.append((s, b, kind, fut))

    threads = [threading.Thread(target=pump, args=(t,)) for t in range(6)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
        assert not t.is_alive(), "hammer submitter wedged"
    assert eng.close(timeout=300) == []
    ok_kinds = (RhsNonFinite, DeadlineExceeded, SolveUnhealthy,
                SessionQuarantined)
    for s, b, kind, fut in results:
        assert fut.done(), "close() left a future unresolved"
        try:
            x = fut.result(0)
        except ok_kinds:
            continue
        except Exception as e:  # noqa: BLE001
            errs.append(e)
            continue
        np.testing.assert_allclose(
            np.asarray(x), np.asarray(s.solve(b)), rtol=1e-4, atol=1e-5,
            err_msg=f"kind={kind}")
    assert not errs, f"unstructured failures leaked: {errs[:3]}"
    stats = eng.stats()
    assert stats["pending"] == 0
    assert stats["completed"] + stats["failed"] == stats["requests"]


def test_health_counters_in_serve_stats():
    session = _session(67)
    bad = _rhs(101)
    bad[0, 0] = np.nan
    with ServeEngine(max_batch_delay=0.0, health=HealthPolicy()) as eng:
        with pytest.raises(RhsNonFinite):
            eng.submit(session, bad)
        eng.solve(session, _rhs(102), timeout=60)
    stats = profiler.serve_stats()
    assert set(resilience._HEALTH_KEYS) <= set(stats["health"])
    assert stats["health"]["rhs_rejects"] >= 1
    # the health counters are global like the region tables: clear()
    # resets them (engine counters, living on engines, survive)
    profiler.clear()
    stats = profiler.serve_stats()
    assert stats["health"]["rhs_rejects"] == 0
    assert stats["engine"]["requests"] >= 1


def test_cond_guard_trip_counts_into_health():
    session = _session(71)
    rng = np.random.default_rng(103)
    U = rng.standard_normal((N, 2)).astype(np.float32)
    h0 = resilience.health_stats()
    session.policy = dataclasses.replace(session.policy,
                                         cond_limit=1.0 + 1e-9)
    session.update(U, U)  # cond(C) > 1 for any real drift: guard trips
    assert session.refactors == 1
    assert _delta(h0, resilience.health_stats())["cond_refactors"] == 1


def test_fault_spec_validation_and_determinism():
    with pytest.raises(ValueError, match="unknown fault site"):
        FaultSpec("nowhere", "nan")
    with pytest.raises(ValueError, match="unknown fault kind"):
        FaultSpec("staging", "meteor")
    a = FaultPlan([FaultSpec("dispatch", "delay", prob=0.5,
                             delay_s=0.0)], seed=3)
    b = FaultPlan([FaultSpec("dispatch", "delay", prob=0.5,
                             delay_s=0.0)], seed=3)
    fires = [(a.fire("dispatch") is not None,
              b.fire("dispatch") is not None) for _ in range(64)]
    assert all(x == y for x, y in fires), "seeded streams diverged"
    assert any(x for x, _ in fires) and not all(x for x, _ in fires)
