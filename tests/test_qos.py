"""Multi-tenant QoS tests: the ISSUE 15 acceptance contracts.

- `qos=None` engines are byte-identical to the pre-QoS engine (no
  'qos' key anywhere, bitwise answers, no ledger allocated).
- `QosClass` validates its fields and round-trips the fabric wire
  encoding; `collect_delay` resolves override > tier override > tier
  default.
- `FairShareLedger` is work-conserving below contention, sheds an
  over-share tenant while contended, and the deficit-round-robin
  credit readmits priority-0 traffic at the weighted drain fraction.
- Engine throttling raises `TenantThrottled` with the structured
  attrs (`retry_after`/`tenant`/`qos_class`) and counts per class in
  the health ledger; `EngineSaturated` carries the same attrs.
- Per-class counters/percentiles surface in `counters()['qos']` /
  `stats()['qos']` / `serve_stats()['qos']`, and per-class
  `StatsWindow(engine, qos_class=...)` deltas sum to the cumulative
  per-class counts under concurrent writers (the §24 hammer, extended
  to N coexisting windows).
- The persistent operating point (`control.save_operating_point` /
  `load_operating_point`) round-trips, rejects malformed rows, and
  re-seeds a `persist=True` controller at attach.
- The fabric carries `qos=` to the owning host and returns
  `TenantThrottled` with attrs intact across the wire encoding.
"""

import json
import os
import threading

import numpy as np
import pytest

import jax.numpy as jnp

from conflux_tpu import control, profiler, qos, resilience, serve
from conflux_tpu.engine import EngineSaturated, ServeEngine
from conflux_tpu.qos import (
    FairShareLedger,
    QosClass,
    class_from_wire,
    collect_delay,
)
from conflux_tpu.resilience import TenantThrottled

N, V = 32, 16


def _session(seed=0, v=V):
    rng = np.random.default_rng(seed)
    A = (rng.standard_normal((N, N)) / np.sqrt(N)
         + 2.0 * np.eye(N)).astype(np.float32)
    plan = serve.FactorPlan.create((N, N), jnp.float32, v=v)
    return plan, plan.factor(jnp.asarray(A))


# --------------------------------------------------------------------------- #
# QosClass: validation, wire encoding, collect-delay resolution
# --------------------------------------------------------------------------- #


def test_qos_class_validation():
    c = QosClass(tenant="gold", tier="latency", slo=0.025, weight=3.0)
    assert c.key == "gold/latency"
    with pytest.raises(ValueError, match="tier"):
        QosClass(tier="interactive")
    with pytest.raises(ValueError, match="tenant"):
        QosClass(tenant="")
    with pytest.raises(ValueError, match="'/'"):
        QosClass(tenant="a/b")
    with pytest.raises(ValueError, match="slo"):
        QosClass(slo=0.0)
    with pytest.raises(ValueError, match="weight"):
        QosClass(weight=0.0)
    with pytest.raises(ValueError, match="collect_delay"):
        QosClass(collect_delay=-1e-3)


def test_qos_class_wire_round_trip():
    c = QosClass(tenant="gold", tier="latency", priority=-1,
                 slo=0.025, weight=2.5, collect_delay=0.001)
    assert class_from_wire(c.to_wire()) == c
    assert class_from_wire(None) is None
    assert class_from_wire(c) is c  # already-built classes pass through
    # wire dicts with missing keys fall back to the defaults
    assert class_from_wire({"tenant": "t"}) == QosClass(tenant="t")


def test_collect_delay_resolution():
    eng_delay = 0.002
    # tier defaults: latency dispatches now, throughput rides the
    # engine window, batch stretches it (clamped at the ceiling)
    assert collect_delay(None, eng_delay, {}) == eng_delay
    assert collect_delay(QosClass(tier="latency"), eng_delay, {}) == 0.0
    assert collect_delay(QosClass(tier="throughput"),
                         eng_delay, {}) == eng_delay
    assert collect_delay(QosClass(tier="batch"), eng_delay, {}) == \
        pytest.approx(eng_delay * qos.BATCH_STRETCH)
    assert collect_delay(QosClass(tier="batch"), 1.0, {}) == \
        qos.MAX_TIER_DELAY
    # the controller's per-tier override trumps the default...
    assert collect_delay(QosClass(tier="batch"), eng_delay,
                         {"batch": 0.016}) == 0.016
    # ...and the request's own override trumps everything (clamped)
    c = QosClass(tier="batch", collect_delay=0.001)
    assert collect_delay(c, eng_delay, {"batch": 0.016}) == 0.001
    assert collect_delay(QosClass(collect_delay=1.0), eng_delay,
                         {}) == qos.MAX_TIER_DELAY


# --------------------------------------------------------------------------- #
# FairShareLedger math (pure, no engine)
# --------------------------------------------------------------------------- #


def test_ledger_work_conserving_below_contention():
    led = FairShareLedger(contention=0.5)
    bulk = QosClass(tenant="bulk", tier="batch")
    # an idle engine admits everything, share or no share
    for pend in range(7):
        assert led.try_admit(bulk, pend, 16) is None


def test_ledger_sheds_over_share_when_contended():
    led = FairShareLedger(contention=0.5)
    gold = QosClass(tenant="gold", weight=1.0)
    bulk = QosClass(tenant="bulk", weight=1.0, priority=1)
    led.note(gold)
    led.note(bulk)
    # equal weights, max_pending=8: share is 4 each
    assert led.share("bulk", 8) == 4.0
    assert led.frac("bulk") == 0.5
    for _ in range(4):  # fill bulk to its share (engine uncontended)
        assert led.try_admit(bulk, 0, 8) is None
    # contended + at share + background priority: shed, with the
    # over-share backlog as the hint basis
    over = led.try_admit(bulk, 4, 8)
    assert over == pytest.approx(1.0)
    # the under-share tenant still admits while contended
    assert led.try_admit(gold, 4, 8) is None
    st = led.stats(8)
    assert st["bulk"]["throttled"] == 1 and st["bulk"]["pending"] == 4
    assert st["gold"]["admitted"] == 1


def test_ledger_deficit_readmits_priority_zero():
    led = FairShareLedger(contention=0.25)
    gold = QosClass(tenant="gold", weight=1.0)
    bulk = QosClass(tenant="bulk", weight=1.0, priority=1)
    bulk0 = QosClass(tenant="bulk", weight=1.0, priority=0)
    for _ in range(4):
        assert led.try_admit(bulk, 0, 8) is None
    assert led.try_admit(gold, 4, 8) is None
    # at the share line while contended: background bulk sheds
    assert led.try_admit(bulk, 5, 8) is not None
    # releases distribute credit by weight; after enough quanta the
    # tenant's PRIORITY-0 traffic readmits while still over share
    for _ in range(4):
        led.release(bulk)
        led.try_admit(bulk, 5, 8)  # pending returns to the share line
    assert led.try_admit(bulk0, 8, 8) is None
    # ...but only by spending credit: the next one sheds again
    led._deficit["bulk"] = 0.0
    assert led.try_admit(bulk0, 8, 8) is not None


def test_ledger_release_never_goes_negative():
    led = FairShareLedger()
    c = QosClass(tenant="t")
    led.note(c)
    led.release(c)
    assert led.stats(8)["t"]["pending"] == 0


# --------------------------------------------------------------------------- #
# qos=None stays byte-identical
# --------------------------------------------------------------------------- #


def test_qos_none_engine_bitwise_identical():
    serve.clear_plans()
    _, s = _session(seed=11)
    b = jnp.asarray(np.ones(N, np.float32))
    with ServeEngine(max_batch_delay=0.0) as eng:
        plain = np.asarray(eng.solve(s, b))
        # no classified traffic ever: no state, no dict keys
        assert eng._qos is None
        assert "qos" not in eng.counters()
        assert "qos" not in eng.stats()
        assert "qos_contention" not in eng.knobs()
    with ServeEngine(max_batch_delay=0.0) as eng:
        tagged = np.asarray(eng.solve(
            s, b, qos=QosClass(tenant="gold", tier="throughput")))
        assert eng._qos is not None
        assert "qos" in eng.counters()
    np.testing.assert_array_equal(plain, tagged)


def test_qos_type_validation_on_submit():
    serve.clear_plans()
    _, s = _session(seed=12)
    b = jnp.asarray(np.ones(N, np.float32))
    with ServeEngine(max_batch_delay=0.0) as eng:
        with pytest.raises(TypeError, match="QosClass"):
            eng.submit(s, b, qos={"tenant": "gold"})


# --------------------------------------------------------------------------- #
# engine throttling: structured errors, counters, health ledger
# --------------------------------------------------------------------------- #


def test_engine_throttles_over_share_tenant():
    serve.clear_plans()
    resilience.clear_health()
    _, s = _session(seed=13)
    b = jnp.asarray(np.ones(N, np.float32))
    gold = QosClass(tenant="gold", tier="throughput", weight=1.0)
    bulk = QosClass(tenant="bulk", tier="throughput", weight=1.0,
                    priority=1)
    # a huge window parks the dispatcher, so pending grows
    # deterministically; shares are 2 each at max_pending=4
    eng = ServeEngine(max_batch_delay=60.0, max_pending=4)
    futs = [eng.submit(s, b, qos=gold),
            eng.submit(s, b, qos=bulk),
            eng.submit(s, b, qos=bulk)]
    with pytest.raises(TenantThrottled) as ei:
        eng.submit(s, b, qos=bulk)
    assert ei.value.tenant == "bulk"
    assert ei.value.qos_class == "bulk/throughput"
    assert ei.value.retry_after > 0.0
    # the under-share tenant still admits past the contention line
    futs.append(eng.submit(s, b, qos=gold))
    # the GLOBAL bound still backstops everything, attrs included
    with pytest.raises(EngineSaturated) as ei2:
        eng.submit(s, b, qos=gold)
    assert ei2.value.tenant == "gold"
    assert ei2.value.qos_class == "gold/throughput"
    c = eng.counters()["qos"]
    assert c["classes"]["bulk/throughput"]["throttled"] == 1
    assert c["classes"]["gold/throughput"]["requests"] == 2
    assert c["tenants"]["bulk"]["pending"] == 2
    h = resilience.health_stats()
    assert h["tenant_throttled"] == 1
    assert h["tenant_throttled[bulk/throughput]"] == 1
    eng.close(timeout=60)  # releases the parked batch
    for f in futs:
        f.result(timeout=60)
    # every ledger slot came back when its request settled
    assert all(r["pending"] == 0
               for r in eng.counters()["qos"]["tenants"].values())
    resilience.clear_health()


def test_latency_class_pulls_in_the_window():
    """A latency-class arrival resolves a ~0 collect delay, so it
    drains promptly even under a parked-dispatcher window."""
    serve.clear_plans()
    _, s = _session(seed=14)
    b = jnp.asarray(np.ones(N, np.float32))
    with ServeEngine(max_batch_delay=60.0) as eng:
        f = eng.submit(s, b, qos=QosClass(tenant="gold",
                                          tier="latency", slo=1.0))
        f.result(timeout=60)  # would park for 60s without the tier cut
        row = eng.stats()["qos"]["classes"]["gold/latency"]
        assert row["completed"] == 1
        assert row["latency_samples"] == 1
        assert row["slo_attainment_pct"] == 100.0


def test_qos_knobs_round_trip():
    serve.clear_plans()
    _, s = _session(seed=15)
    b = jnp.asarray(np.ones(N, np.float32))
    with ServeEngine(max_batch_delay=0.0) as eng:
        with pytest.raises(ValueError, match="qos_contention"):
            eng.set_knobs(qos_contention=0.0)
        with pytest.raises(ValueError, match="qos_tier_delay"):
            eng.set_knobs(qos_tier_delay={"interactive": 0.001})
        eng.set_knobs(qos_contention=0.25,
                      qos_tier_delay={"batch": 0.008})
        k = eng.knobs()
        assert k["qos_contention"] == 0.25
        assert k["qos_tier_delay"] == {"batch": 0.008}
        eng.set_knobs(qos_tier_delay={"batch": None})  # None clears
        assert eng.knobs()["qos_tier_delay"] == {}
        # the knobs still drive a live ledger
        np.asarray(eng.solve(s, b, qos=QosClass(tenant="t")))
        assert eng.counters()["qos"]["contention"] == 0.25


# --------------------------------------------------------------------------- #
# per-class windows: the §24 hammer extended to N coexisting windows
# --------------------------------------------------------------------------- #


def test_per_class_stats_windows_coexist_under_hammer():
    """N per-class StatsWindows + the engine-wide window taken WHILE
    concurrent per-class writers drive traffic: every window's deltas
    sum to exactly its class's cumulative counts, and the engine-wide
    window is untouched by the per-class ones."""
    serve.clear_plans()
    _, s = _session(seed=16)
    b = jnp.asarray(np.ones(N, np.float32))
    classes = [QosClass(tenant=f"t{i}", tier="throughput")
               for i in range(3)]
    PER = 40
    with ServeEngine(max_batch_delay=0.0) as eng:
        wall = profiler.StatsWindow(eng)
        per = {c.key: profiler.StatsWindow(eng, qos_class=c.key)
               for c in classes}
        sums = {c.key: 0 for c in classes}
        lats = {c.key: 0 for c in classes}
        stop = threading.Event()

        def writer(c):
            for _ in range(PER):
                eng.solve(s, b, qos=c)

        def taker():
            while not stop.is_set():
                for k, w in per.items():
                    d = w.delta()["engine"]
                    sums[k] += d["qos_completed"]
                    lats[k] += d["latency_samples"]

        ts = [threading.Thread(target=writer, args=(c,))
              for c in classes]
        tk = threading.Thread(target=taker)
        tk.start()
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=120)
            assert not t.is_alive(), "qos writer wedged"
        stop.set()
        tk.join(timeout=120)
        for k, w in per.items():  # the tail windows
            d = w.delta()["engine"]
            sums[k] += d["qos_completed"]
            lats[k] += d["latency_samples"]
        assert sums == {c.key: PER for c in classes}
        assert lats == {c.key: PER for c in classes}
        # the engine-wide window saw every request exactly once
        d = wall.delta()["engine"]
        assert d["completed"] == PER * len(classes)
        assert d["latency_samples"] == PER * len(classes)
        # cumulative consumers unchanged by any of the windowing
        rows = eng.counters()["qos"]["classes"]
        assert all(rows[c.key]["completed"] == PER for c in classes)


def test_qos_latency_window_unknown_key_is_empty():
    serve.clear_plans()
    with ServeEngine(max_batch_delay=0.0) as eng:
        # windows may open ahead of traffic: unknown keys read empty
        assert eng.qos_latency_window("nobody/latency") == (0, [])
        assert eng.qos_latency_samples() == {}
        w = profiler.StatsWindow(eng, qos_class="nobody/latency")
        d = w.delta()["engine"]
        assert d["qos_requests"] == 0 and d["latency_samples"] == 0


def test_serve_stats_merges_qos_across_engines():
    serve.clear_plans()
    _, s = _session(seed=17)
    b = jnp.asarray(np.ones(N, np.float32))
    with ServeEngine(max_batch_delay=0.0) as eng:
        eng.solve(s, b, qos=QosClass(tenant="gold", tier="latency",
                                     slo=1.0))
        agg = profiler.serve_stats()["qos"]
        assert agg["engines"] >= 1
        row = agg["classes"]["gold/latency"]
        assert row["completed"] >= 1
        assert row["slo_attainment_pct"] == 100.0
        assert agg["tenants"]["gold"]["admitted"] >= 1


# --------------------------------------------------------------------------- #
# persistent operating point
# --------------------------------------------------------------------------- #


def test_operating_point_round_trip(tmp_path, monkeypatch):
    path = str(tmp_path / "op.json")
    monkeypatch.setenv("CONFLUX_TPU_OPERATING_POINT", path)
    assert control.operating_point_path() == path
    assert control.load_operating_point("r1") == {}
    control.save_operating_point("r1", {
        "max_batch_delay": 0.004, "max_pending": 256,
        "qos_contention": 0.3, "qos_tier_delay": {"batch": 0.01},
        "drain_rate": 120.0, "max_coalesce_width": 64})
    row = control.load_operating_point("r1")
    # only the compile-safe seed knobs persist — never bucket caps
    assert row == {"max_batch_delay": 0.004, "max_pending": 256,
                   "qos_contention": 0.3,
                   "qos_tier_delay": {"batch": 0.01}}
    # a second regime coexists; re-saving r1 replaces only r1
    control.save_operating_point("r2", {"max_pending": 64})
    control.save_operating_point("r1", {"max_pending": 128})
    assert control.load_operating_point("r1") == {"max_pending": 128}
    assert control.load_operating_point("r2") == {"max_pending": 64}
    doc = json.loads(open(path).read())
    assert doc["version"] == control._OP_VERSION
    assert len(doc["rows"]) == 2


def test_operating_point_rejects_malformed(tmp_path, monkeypatch):
    path = str(tmp_path / "op.json")
    monkeypatch.setenv("CONFLUX_TPU_OPERATING_POINT", path)
    # corrupt file: load is {} and save starts fresh
    with open(path, "w") as f:
        f.write("{not json")
    assert control.load_operating_point("r") == {}
    control.save_operating_point("r", {"max_pending": 64})
    assert control.load_operating_point("r") == {"max_pending": 64}
    # hand-edited rows with unknown knobs or bad shapes are dropped
    doc = json.loads(open(path).read())
    doc["rows"].append({"regime": "bad", "knobs": {"max_stack": 8},
                        "updated": "now"})
    doc["rows"].append({"regime": "worse",
                        "knobs": {"qos_tier_delay": {"oops": 1.0}},
                        "updated": "now"})
    with open(path, "w") as f:
        json.dump(doc, f)
    assert control.load_operating_point("bad") == {}
    assert control.load_operating_point("worse") == {}
    assert control.load_operating_point("r") == {"max_pending": 64}


def test_controller_reseeds_and_persists(tmp_path, monkeypatch):
    path = str(tmp_path / "op.json")
    monkeypatch.setenv("CONFLUX_TPU_OPERATING_POINT", path)
    serve.clear_plans()
    control.save_operating_point("slo25-l1", {
        "max_batch_delay": 0.004, "max_pending": 128,
        "qos_contention": 0.3})
    ctl = control.AdaptiveController(persist=True, interval=60.0)
    eng = ServeEngine(max_batch_delay=0.0, controller=ctl)
    try:
        assert ctl._regime == "slo25-l1"
        k = eng.knobs()
        assert k["max_batch_delay"] == 0.004
        assert k["max_pending"] == 128
        assert k["qos_contention"] == 0.3
        st = ctl.stats()
        assert st["persist"]["enabled"]
        assert st["persist"]["reseeded"]["max_pending"] == 128
        eng.set_knobs(max_pending=96)
    finally:
        eng.close()
    # close() dumped the final vector back to the same regime row
    assert control.load_operating_point("slo25-l1")["max_pending"] == 96


def test_controller_default_regime_never_persists_without_optin(
        tmp_path, monkeypatch):
    path = str(tmp_path / "op.json")
    monkeypatch.setenv("CONFLUX_TPU_OPERATING_POINT", path)
    serve.clear_plans()
    ctl = control.AdaptiveController(interval=60.0)  # persist=False
    eng = ServeEngine(max_batch_delay=0.0, controller=ctl)
    try:
        assert ctl.stats()["persist"] == {"enabled": False}
    finally:
        eng.close()
    assert not os.path.exists(path)


def test_controller_steers_qos_contention_down_under_slo_pressure():
    """Two scripted hot windows (a latency class p99 inside headroom
    of its SLO) halve qos_contention; the decision is recorded."""
    serve.clear_plans()
    _, s = _session(seed=18)
    b = jnp.asarray(np.ones(N, np.float32))
    ctl = control.AdaptiveController(interval=60.0,
                                     min_window_samples=1)
    eng = ServeEngine(max_batch_delay=0.0, controller=ctl)
    try:
        slow = QosClass(tenant="gold", tier="latency", slo=1e-9)
        for _ in range(3):  # every sample blows a 1ns SLO
            eng.solve(s, b, qos=slow)
        before = eng.knobs()["qos_contention"]
        for _ in range(3):
            eng.solve(s, b, qos=slow)
            ctl.step()
        after = eng.knobs()["qos_contention"]
        assert after < before
        assert any(d["knob"] == "qos_contention"
                   for d in ctl.stats()["decisions_log"])
    finally:
        eng.close()


# --------------------------------------------------------------------------- #
# fabric passthrough
# --------------------------------------------------------------------------- #


def test_fabric_wire_round_trip_tenant_throttled():
    from conflux_tpu.fabric import _encode_exc, _raise_wire

    e = TenantThrottled("over", retry_after=0.07, tenant="bulk",
                        qos_class="bulk/batch")
    enc = _encode_exc(e)
    assert enc["etype"] == "TenantThrottled"
    with pytest.raises(TenantThrottled) as ei:
        _raise_wire(enc)
    assert ei.value.retry_after == 0.07
    assert ei.value.tenant == "bulk"
    assert ei.value.qos_class == "bulk/batch"
    e2 = EngineSaturated("full", retry_after=0.1, tenant="t",
                         qos_class="t/latency")
    with pytest.raises(EngineSaturated) as ei2:
        _raise_wire(_encode_exc(e2))
    assert ei2.value.tenant == "t"
    assert ei2.value.qos_class == "t/latency"


def test_fabric_local_host_carries_qos(tmp_path):
    from conflux_tpu.fabric import LocalHost, ServeFabric

    serve.clear_plans()
    rng = np.random.default_rng(19)
    A = (rng.standard_normal((N, N)) / np.sqrt(N)
         + 2.0 * np.eye(N)).astype(np.float32)
    plan = serve.FactorPlan.create((N, N), jnp.float32, v=V)
    hosts = [LocalHost("h0", str(tmp_path / "h0"),
                       engine_kwargs=dict(max_batch_delay=0.0))]
    fab = ServeFabric(hosts)
    try:
        fab.start()
        fab.open("s0", plan.spec(), A)
        gold = QosClass(tenant="gold", tier="latency", slo=1.0)
        b = np.ones((N,), np.float32)
        plain = np.asarray(fab.solve("s0", b))
        tagged = np.asarray(fab.solve("s0", b, qos=gold))
        np.testing.assert_array_equal(plain, tagged)
        with pytest.raises(TypeError, match="QosClass"):
            fab.solve("s0", b, qos={"tenant": "gold"})
        # the heartbeat payload grows flat per-tier drain counters
        ping = hosts[0].ping()
        assert ping["counters"]["qos_latency_solves"] == 1
        core = hosts[0].core
        row = core.eng.counters()["qos"]["classes"]["gold/latency"]
        assert row["completed"] == 1
    finally:
        fab.close()


def test_host_load_estimator_folds_tier_rates():
    est = control.HostLoadEstimator()
    est.feed("h0", {"seconds": 2.0, "solves": 10, "pending": 1,
                    "qos_latency_solves": 4, "qos_batch_solves": 6})
    st = est.stats()["h0"]
    assert st["qos_drain_per_s"] == {"batch": 3.0, "latency": 2.0}
    est.forget("h0")
    assert "h0" not in est.stats()
