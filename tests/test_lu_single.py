"""Single-device blocked LU: residual tests against the direct construction
(the role of the reference's CONFLUX_WITH_VALIDATION residual oracle, §3.3)."""

import jax.numpy as jnp
import numpy as np
import pytest

from conflux_tpu.lu.single import lu_factor_blocked, unpack_lu
from conflux_tpu.validation import lu_residual, make_test_matrix, residual_bound


@pytest.mark.parametrize("N,v", [(16, 4), (64, 16), (128, 32), (96, 32)])
def test_lu_residual_f64(N, v):
    A = make_test_matrix(N, N, seed=N + v)
    LU, perm = lu_factor_blocked(jnp.asarray(A), v=v)
    res = lu_residual(A, LU, perm)
    assert res < residual_bound(N, np.float64), res


def test_lu_tall_matrix():
    A = make_test_matrix(96, 32, seed=3)
    LU, perm = lu_factor_blocked(jnp.asarray(A), v=16)
    res = lu_residual(A, LU, perm)
    assert res < residual_bound(96, np.float64), res


def test_lu_perm_is_permutation():
    A = make_test_matrix(64, 64)
    _, perm = lu_factor_blocked(jnp.asarray(A), v=16)
    assert sorted(np.asarray(perm).tolist()) == list(range(64))


def test_lu_pivoting_actually_pivots():
    # a matrix whose naive (unpivoted) LU would divide by ~0
    A = make_test_matrix(32, 32, seed=11)
    A[0, 0] = 1e-300
    LU, perm = lu_factor_blocked(jnp.asarray(A), v=8)
    assert np.isfinite(np.asarray(LU)).all()
    assert lu_residual(A, LU, perm) < residual_bound(32, np.float64)


def test_lu_matches_numpy_solve():
    # solve A x = b through the factors
    N = 64
    A = make_test_matrix(N, N, seed=5)
    b = np.linspace(-1, 1, N)
    LU, perm = lu_factor_blocked(jnp.asarray(A), v=16)
    L, U = unpack_lu(LU)
    from scipy.linalg import solve_triangular

    y = solve_triangular(np.asarray(L), b[np.asarray(perm)], lower=True, unit_diagonal=True)
    x = solve_triangular(np.asarray(U), y, lower=False)
    np.testing.assert_allclose(A @ x, b, atol=1e-10)


def test_lu_f32():
    N = 64
    A = make_test_matrix(N, N, dtype=np.float32)
    LU, perm = lu_factor_blocked(jnp.asarray(A), v=16)
    assert LU.dtype == jnp.float32
    assert lu_residual(A, LU, perm) < residual_bound(N, np.float32)


def test_lu_full_gather_path_matches():
    """The large-M full-gather branch must agree with the swap-minimal one
    (thresholds shrunk so both run at test size)."""
    from conflux_tpu.lu import single as lu_single

    N, v = 128, 16
    A = make_test_matrix(N, N, seed=21)
    LU_small, perm_small = lu_factor_blocked(jnp.asarray(A), v=v)
    old = lu_single._SWAP_SCATTER_MAX
    lu_single._SWAP_SCATTER_MAX = 0  # force the full-gather branch
    try:
        lu_single._lu_factor_blocked.clear_cache()
        LU_big, perm_big = lu_factor_blocked(jnp.asarray(A), v=v)
    finally:
        lu_single._SWAP_SCATTER_MAX = old
        lu_single._lu_factor_blocked.clear_cache()
    assert lu_residual(A, LU_big, perm_big) < residual_bound(N, np.float64)
    # same pivots elected, same factors (row order of ties may differ)
    np.testing.assert_allclose(
        np.asarray(LU_small)[np.argsort(np.asarray(perm_small))],
        np.asarray(LU_big)[np.argsort(np.asarray(perm_big))],
        atol=1e-12,
    )
