"""Incremental low-rank refresh tests (ISSUE 2): Woodbury correctness,
drift-policy refactor triggers, and the compiled-once-per-bucket contract.

The acceptance contracts, asserted rather than trusted: an updated
session solves the DRIFTED system (held to the full-refactor oracle's
residual bars), accumulation composes (two rank-1 updates == one rank-2
update bitwise), `update()` + corrected solves perform zero recompiles
after the first call per (rank bucket, RHS bucket) via the plan's
trace-count hook, and the drift policy pays exactly one true
refactorization when rank/conditioning stops paying.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from conflux_tpu import batched, serve, solvers
from conflux_tpu.update import DriftPolicy, apply_update, rank_bucket

B, N, V, K = 8, 32, 16, 3


def _systems(b=B, n=N, seed=0, spd=False):
    rng = np.random.default_rng(seed)
    lead = () if b is None else (b,)
    A = (rng.standard_normal(lead + (n, n)) / np.sqrt(n)
         + 2.0 * np.eye(n)).astype(np.float32)
    if spd:
        A = (A @ np.swapaxes(A, -1, -2)
             + np.eye(n, dtype=np.float32)).astype(np.float32)
    U = (rng.standard_normal(lead + (n, K)) / np.sqrt(n)).astype(np.float32)
    Vm = (rng.standard_normal(lead + (n, K)) / np.sqrt(n)).astype(np.float32)
    rhs = rng.standard_normal(lead + (n,)).astype(np.float32)
    return A, U, Vm, rhs


def _res(A1, x, b):
    """Relative residuals against the DRIFTED matrix, per element."""
    A64 = np.asarray(A1, np.float64)
    x64, b64 = np.asarray(x, np.float64), np.asarray(b, np.float64)
    if A64.ndim == 2:
        return np.linalg.norm(A64 @ x64 - b64) / np.linalg.norm(b64)
    r = np.einsum("bij,bj->bi", A64, x64) - b64
    return np.linalg.norm(r, axis=1) / np.linalg.norm(b64, axis=1)


def _refactor_bars(A1, b, **kw):
    """The full-refactor oracle: factor the drifted matrix directly."""
    if np.asarray(A1).ndim == 2:
        x = solvers.solve(jnp.asarray(A1), jnp.asarray(b), v=V, **kw)
        return _res(A1, x, b)
    xs = np.stack([
        np.asarray(solvers.solve(jnp.asarray(A1[i]), jnp.asarray(b[i]),
                                 v=V, **kw))
        for i in range(A1.shape[0])])
    return _res(A1, xs, b)


def _bars(A1, b, **kw):
    return np.maximum(10.0 * _refactor_bars(A1, b, **kw), 1e-6)


# --------------------------------------------------------------------------- #
# rank buckets
# --------------------------------------------------------------------------- #


def test_rank_bucket_contract():
    assert [rank_bucket(k) for k in (1, 2, 3, 4, 5, 8, 9)] \
        == [1, 2, 4, 4, 8, 8, 16]
    with pytest.raises(ValueError, match="positive"):
        rank_bucket(0)


# --------------------------------------------------------------------------- #
# session update correctness
# --------------------------------------------------------------------------- #


def test_session_update_solves_drifted_system():
    serve.clear_plans()
    A, U, Vm, b = _systems(b=None, seed=1)
    plan = serve.FactorPlan.create((N, N), jnp.float32, v=V)
    session = plan.factor(jnp.asarray(A))
    session.update(jnp.asarray(U), jnp.asarray(Vm))
    assert session.update_rank == K and session.updates == 1
    x = session.solve(jnp.asarray(b))
    A1 = A + U @ Vm.T
    assert _res(A1, x, b) <= _bars(A1, b), "updated solve missed the bar"
    assert session.factorizations == 1, "update refactored"
    # the un-drifted base is NOT what we solve anymore
    assert _res(A, x, b) > 1e-4


def test_session_update_batched_matches_oracle():
    serve.clear_plans()
    A, U, Vm, b = _systems(seed=2)
    plan = serve.FactorPlan.create((B, N, N), jnp.float32, v=V)
    session = plan.factor(jnp.asarray(A))
    x = session.update(jnp.asarray(U), jnp.asarray(Vm)).solve(jnp.asarray(b))
    A1 = np.asarray(apply_update(jnp.asarray(A), jnp.asarray(U),
                                 jnp.asarray(Vm)))
    assert (_res(A1, x, b) <= _bars(A1, b)).all()
    assert session.factorizations == 1


def test_session_update_mesh_sharded():
    serve.clear_plans()
    A, U, Vm, b = _systems(seed=3)
    mesh = batched.batch_mesh()
    plan = serve.FactorPlan.create((B, N, N), jnp.float32, v=V, mesh=mesh)
    session = plan.factor(jnp.asarray(A))
    session.update(jnp.asarray(U), jnp.asarray(Vm))
    x = session.solve(jnp.asarray(b))
    assert len(x.sharding.device_set) == 8
    A1 = A + np.einsum("bik,bjk->bij", U, Vm)
    assert (_res(A1, x, b) <= _bars(A1, b)).all()


def test_session_update_spd_base():
    """Cholesky base factors; the drift need not preserve symmetry."""
    serve.clear_plans()
    A, U, Vm, b = _systems(b=None, seed=4, spd=True)
    plan = serve.FactorPlan.create((N, N), jnp.float32, v=V, spd=True)
    session = plan.factor(jnp.asarray(A))
    x = session.update(jnp.asarray(U), jnp.asarray(Vm)).solve(jnp.asarray(b))
    A1 = A + U @ Vm.T
    assert _res(A1, x, b) <= _bars(A1, b)


def test_session_update_accumulates_and_replaces():
    serve.clear_plans()
    A, U, Vm, b = _systems(b=None, seed=5)
    plan = serve.FactorPlan.create((N, N), jnp.float32, v=V)
    # two stacked updates == one combined update, bitwise (same padded
    # capacitance program, same accumulated factors)
    s1 = plan.factor(jnp.asarray(A))
    s1.update(jnp.asarray(U[:, :1]), jnp.asarray(Vm[:, :1]))
    s1.update(jnp.asarray(U[:, 1:]), jnp.asarray(Vm[:, 1:]))
    assert s1.update_rank == K
    s2 = plan.factor(jnp.asarray(A))
    s2.update(jnp.asarray(U), jnp.asarray(Vm))
    np.testing.assert_array_equal(np.asarray(s1.solve(jnp.asarray(b))),
                                  np.asarray(s2.solve(jnp.asarray(b))))
    # replace=True measures the drift from the base again
    s1.update(jnp.asarray(U), jnp.asarray(Vm), replace=True)
    assert s1.update_rank == K
    np.testing.assert_array_equal(np.asarray(s1.solve(jnp.asarray(b))),
                                  np.asarray(s2.solve(jnp.asarray(b))))


def test_session_update_refine_backstop():
    """The IR backstop computes residuals against the DRIFTED matrix and
    tightens the refreshed solution."""
    serve.clear_plans()
    A, U, Vm, b = _systems(b=None, seed=6)
    A1 = A + U @ Vm.T
    plan = serve.FactorPlan.create((N, N), jnp.float32, v=V)
    plain = plan.factor(jnp.asarray(A)) \
        .update(jnp.asarray(U), jnp.asarray(Vm)).solve(jnp.asarray(b))
    refined = plan.factor(jnp.asarray(A), policy=DriftPolicy(refine=2)) \
        .update(jnp.asarray(U), jnp.asarray(Vm)).solve(jnp.asarray(b))
    assert _res(A1, refined, b) <= max(float(_res(A1, plain, b)), 1e-7)


def test_session_update_rejects_bad_shapes():
    serve.clear_plans()
    A, U, Vm, _ = _systems(seed=7)
    plan = serve.FactorPlan.create((B, N, N), jnp.float32, v=V)
    session = plan.factor(jnp.asarray(A))
    with pytest.raises(ValueError, match="must agree"):
        session.update(jnp.asarray(U), jnp.asarray(Vm[:, :, :1]))
    with pytest.raises(ValueError, match="rank axis"):
        session.update(jnp.asarray(U[0]), jnp.asarray(Vm[0]))
    with pytest.raises(ValueError, match="rank axis"):
        session.update(jnp.asarray(U[:4]), jnp.asarray(Vm[:4]))


# --------------------------------------------------------------------------- #
# compile-count contract (the ISSUE 2 acceptance test)
# --------------------------------------------------------------------------- #


def test_update_zero_recompiles_per_bucket():
    """`update()` + corrected solves compile once per (rank bucket,
    RHS bucket) — repeat drift traffic (ranks/widths within the same
    buckets) traces nothing new."""
    serve.clear_plans()
    A, U, Vm, b = _systems(b=None, seed=8)
    rng = np.random.default_rng(80)
    plan = serve.FactorPlan.create((N, N), jnp.float32, v=V)
    session = plan.factor(jnp.asarray(A))
    session.update(jnp.asarray(U), jnp.asarray(Vm))  # k=3 -> bucket 4
    session.solve(jnp.asarray(b))                    # nrhs=1 -> bucket 1
    t = dict(plan.trace_counts)
    assert t["update"] == 1 and t["update_solve"] == 1
    for k in (3, 4, 3):  # same rank bucket (4), fresh drifts
        Un = (rng.standard_normal((N, k)) / np.sqrt(N)).astype(np.float32)
        Vn = (rng.standard_normal((N, k)) / np.sqrt(N)).astype(np.float32)
        session.update(jnp.asarray(Un), jnp.asarray(Vn), replace=True)
        session.solve(jnp.asarray(
            rng.standard_normal(N).astype(np.float32)))
    assert plan.trace_counts == t, "same-bucket drift traffic recompiled"
    # a second session on the same plan shares every compiled program
    s2 = plan.factor(jnp.asarray(A))
    s2.update(jnp.asarray(U), jnp.asarray(Vm)).solve(jnp.asarray(b))
    assert plan.trace_counts == t, "second session recompiled"
    # a new rank bucket traces exactly one more update + solve pair
    U8 = (rng.standard_normal((N, 8)) / np.sqrt(N)).astype(np.float32)
    session.update(jnp.asarray(U8), jnp.asarray(U8), replace=True)
    session.solve(jnp.asarray(b))
    assert plan.trace_counts["update"] == t["update"] + 1
    assert plan.trace_counts["update_solve"] == t["update_solve"] + 1


# --------------------------------------------------------------------------- #
# drift policy
# --------------------------------------------------------------------------- #


def test_drift_policy_rank_trigger_refactors_once():
    serve.clear_plans()
    A, U, Vm, b = _systems(b=None, seed=9)
    plan = serve.FactorPlan.create((N, N), jnp.float32, v=V)
    session = plan.factor(jnp.asarray(A),
                          policy=DriftPolicy(max_rank=2 * K - 1))
    session.update(jnp.asarray(U), jnp.asarray(Vm))
    assert session.refactors == 0 and session.update_rank == K
    session.update(jnp.asarray(U), jnp.asarray(Vm))  # 2K > max_rank
    assert session.refactors == 1 and session.factorizations == 2
    assert session.update_rank == 0, "correction must reset after refactor"
    # the refactored base IS the twice-drifted matrix
    A2 = A + 2.0 * (U @ Vm.T)
    x = session.solve(jnp.asarray(b))
    assert _res(A2, x, b) <= _bars(A2, b)
    # and the plan's factor program was reused, not re-traced
    assert plan.trace_counts["factor"] == 1


def test_drift_policy_cond_trigger():
    """cond1(C) >= 1 by construction, so a sub-1 limit must always
    refactor — the ill-conditioned-capacitance escape hatch."""
    serve.clear_plans()
    A, U, Vm, b = _systems(b=None, seed=10)
    plan = serve.FactorPlan.create((N, N), jnp.float32, v=V)
    session = plan.factor(jnp.asarray(A),
                          policy=DriftPolicy(cond_limit=0.5))
    session.update(jnp.asarray(U), jnp.asarray(Vm))
    assert session.refactors == 1 and session.update_rank == 0
    A1 = A + U @ Vm.T
    x = session.solve(jnp.asarray(b))
    assert _res(A1, x, b) <= _bars(A1, b)


def test_drift_policy_default_max_rank():
    assert DriftPolicy().resolved_max_rank(1024) == 128
    assert DriftPolicy().resolved_max_rank(32) == 8
    assert DriftPolicy(max_rank=5).resolved_max_rank(1024) == 5


# --------------------------------------------------------------------------- #
# one-shot entry points
# --------------------------------------------------------------------------- #


def test_refactor_buffer_donation_and_live_array_parity():
    """ISSUE 3 satellite: a long-lived drifting session holds ONE
    resident base+factor set. The refresh program donates the superseded
    base once the session owns it (never the caller's array), old factor
    and Woodbury references drop before the replacement dispatch, and the
    live-buffer count stays flat across repeated refactors."""
    import gc

    import jax

    serve.clear_plans()
    A, U, Vm, b = _systems(b=None, seed=21)
    plan = serve.FactorPlan.create((N, N), jnp.float32, v=V)
    # max_rank=1 < K forces a true refactor on every update
    session = plan.factor(jnp.asarray(A), policy=DriftPolicy(max_rank=1))
    caller_A = session._A0
    session.update(jnp.asarray(U), jnp.asarray(Vm))
    assert session.refactors == 1
    # first refactor: the base was the CALLER's array — never donated
    assert not caller_A.is_deleted()
    owned_A = session._A0
    assert session._owns_base
    session.update(jnp.asarray(U), jnp.asarray(Vm))
    assert session.refactors == 2
    # later refactors: the session-owned base is donated to its successor
    assert owned_A.is_deleted(), \
        "superseded owned base survived the refresh dispatch"
    # the session still answers correctly after donation churn
    x = session.solve(jnp.asarray(b))
    A1 = np.asarray(apply_update(jnp.asarray(A), jnp.asarray(U),
                                 jnp.asarray(Vm)))
    A2 = np.asarray(apply_update(jnp.asarray(A1), jnp.asarray(U),
                                 jnp.asarray(Vm)))
    assert _res(A2, x, b) <= float(_bars(A2, b))
    # live-array parity: more refactors may not grow resident state
    x.block_until_ready()
    gc.collect()
    n0 = len(jax.live_arrays())
    for _ in range(4):
        session.update(jnp.asarray(U), jnp.asarray(Vm))
    session.solve(jnp.asarray(b)).block_until_ready()
    gc.collect()
    n1 = len(jax.live_arrays())
    assert n1 <= n0, \
        f"live buffers grew across refactors: {n0} -> {n1}"


def test_solve_updated_matches_refactor_oracle():
    A, U, Vm, b = _systems(b=None, seed=11)
    x = solvers.solve_updated(jnp.asarray(A), jnp.asarray(U),
                              jnp.asarray(Vm), jnp.asarray(b), v=V)
    A1 = A + U @ Vm.T
    assert _res(A1, x, b) <= _bars(A1, b)
    # multi-RHS + refine
    bk = np.stack([b, 2 * b], axis=1)
    xk = solvers.solve_updated(jnp.asarray(A), jnp.asarray(U),
                               jnp.asarray(Vm), jnp.asarray(bk), v=V,
                               refine=1)
    assert xk.shape == (N, 2)
    np.testing.assert_allclose(np.asarray(xk[:, 1]), 2 * np.asarray(xk[:, 0]),
                               rtol=1e-5, atol=1e-6)


def test_solve_updated_pads_non_tile_sizes():
    A, U, Vm, b = _systems(b=None, n=N - 2, seed=12)
    x = solvers.solve_updated(jnp.asarray(A), jnp.asarray(U),
                              jnp.asarray(Vm), jnp.asarray(b), v=V)
    assert x.shape == (N - 2,)
    A1 = A + U @ Vm.T
    assert _res(A1, x, b) < 1e-5


def test_solve_updated_batched_matches_oracle():
    A, U, Vm, b = _systems(seed=13)
    x = batched.solve_updated_batched(jnp.asarray(A), jnp.asarray(U),
                                      jnp.asarray(Vm), jnp.asarray(b), v=V)
    A1 = A + np.einsum("bik,bjk->bij", U, Vm)
    assert (_res(A1, x, b) <= _bars(A1, b)).all()
    with pytest.raises(ValueError, match="update factors"):
        batched.solve_updated_batched(jnp.asarray(A), jnp.asarray(U[0]),
                                      jnp.asarray(Vm[0]), jnp.asarray(b),
                                      v=V)


def test_solve_updated_batched_ragged_mesh():
    A, U, Vm, b = _systems(b=5, seed=14)
    mesh = batched.batch_mesh()
    x = batched.solve_updated_batched(jnp.asarray(A), jnp.asarray(U),
                                      jnp.asarray(Vm), jnp.asarray(b),
                                      v=V, mesh=mesh)
    assert x.shape == (5, N)
    A1 = A + np.einsum("bik,bjk->bij", U, Vm)
    assert (_res(A1, x, b) < 1e-5).all()
