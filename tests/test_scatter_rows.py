"""scatter_rows contract tests (XLA fallback path; the DMA path is
experimental and exercised only by the TPU bring-up test below)."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from conflux_tpu.ops.pallas_kernels import scatter_rows


def _case(M, N, v, n_sentinel, dtype=np.float32, seed=0):
    rng = np.random.default_rng(seed)
    A = rng.standard_normal((M, N)).astype(dtype)
    rows = rng.standard_normal((v, N)).astype(dtype)
    idx = rng.choice(M, size=v - n_sentinel, replace=False).astype(np.int32)
    idx = np.concatenate([idx, np.full(n_sentinel, M + 3, np.int32)])
    ref = A.copy()
    ref[idx[: v - n_sentinel]] = rows[: v - n_sentinel]
    return A, rows, idx, ref


def test_scatter_rows_fallback_matches_reference():
    A, rows, idx, ref = _case(96, 256, 16, 4)
    out = np.asarray(scatter_rows(jnp.asarray(A), jnp.asarray(rows),
                                  jnp.asarray(idx)))
    np.testing.assert_array_equal(out, ref)


def test_scatter_rows_all_sentinel_identity():
    A, rows, _, _ = _case(64, 128, 8, 0)
    idx = np.full(8, 64, np.int32)
    out = np.asarray(scatter_rows(jnp.asarray(A), jnp.asarray(rows),
                                  jnp.asarray(idx)))
    np.testing.assert_array_equal(out, A)


@pytest.mark.skipif(jax.default_backend() != "tpu",
                    reason="DMA path is TPU-only")
def test_scatter_rows_tpu():
    # bring-up test for the experimental DMA path; row length 1024 f32
    # satisfies the 4 KB slice-alignment requirement
    A, rows, idx, ref = _case(512, 1024, 64, 8)
    out = np.asarray(scatter_rows(jnp.asarray(A), jnp.asarray(rows),
                                  jnp.asarray(idx), use_dma=True))
    np.testing.assert_array_equal(out, ref)
