"""The guided example must keep running end to end (it asserts every
capability's residual internally)."""

import os
import subprocess
import sys

import pytest


@pytest.mark.slow
def test_examples_tour_runs():
    tour = os.path.join(os.path.dirname(__file__), "..", "examples",
                        "tour.py")
    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    out = subprocess.run(
        [sys.executable, tour], capture_output=True, text=True, env=env,
        timeout=480, cwd=os.path.dirname(os.path.dirname(tour)),
    )
    assert out.returncode == 0, out.stdout[-2000:] + out.stderr[-2000:]
    assert "Tour complete." in out.stdout


def test_top_level_lazy_api():
    import conflux_tpu

    # every advertised name must resolve (lazy imports included)
    for name in conflux_tpu.__all__:
        assert getattr(conflux_tpu, name) is not None
    with pytest.raises(AttributeError):
        conflux_tpu.not_a_real_api
