"""Independent-library oracle leg (VERDICT r4 item 6).

The repo's usual validation path (`conflux_tpu.validation`) is
self-built; the reference instead validates against a DIFFERENT
library's code path — ScaLAPACK `pdgemm_` via COSTA transforms
(`examples/conflux_miniapp.cpp:404-500`). This module is that leg for
the TPU framework: the full distributed pipeline (scatter → factor →
gather) at the largest CPU-feasible sizes, judged ONLY with
numpy/scipy primitives computed in this file —

  * factors are unpacked with plain numpy (no `validation.py` import),
  * residuals are formed with plain numpy matmuls in float64,
  * the quality bar is RELATIVE to scipy/LAPACK's own same-precision
    factorization of the same matrix (ours must be within 10x of
    scipy's residual — the independent library sets the bar, exactly
    the spirit of the reference's pdgemm_ oracle),
  * unique factors (Cholesky L; QR's positive-diagonal R) are compared
    ELEMENTWISE against scipy's.
"""

import numpy as np
import pytest
import scipy.linalg
import jax
import jax.numpy as jnp

from conflux_tpu.geometry import CholeskyGeometry, Grid3, LUGeometry
from conflux_tpu.parallel.mesh import make_mesh

GRID = Grid3(4, 2, 1)


def _fro(x):
    return float(np.linalg.norm(np.asarray(x, dtype=np.float64)))


@pytest.mark.slow
def test_lu_pipeline_vs_scipy_at_4096():
    """scatter → lu_factor_distributed → gather at N=4096 f32 on an
    8-device mesh, judged against scipy.linalg.lu_factor of the SAME
    f32 matrix: our ||A[perm] - L U||_F (unpacked and multiplied here
    with numpy, in f64) must be within 10x of scipy's."""
    from conflux_tpu.lu.distributed import lu_factor_distributed

    N, v = 4096, 256
    rng = np.random.default_rng(4096)
    A = rng.standard_normal((N, N)).astype(np.float32)
    A += 2 * np.eye(N, dtype=np.float32)

    geom = LUGeometry.create(N, N, v, GRID)
    mesh = make_mesh(GRID, devices=jax.devices()[: GRID.P])
    out, perm = lu_factor_distributed(jnp.asarray(geom.scatter(A)),
                                      geom, mesh)
    LU = geom.gather(np.asarray(out))
    perm = np.asarray(perm)

    # unpack + residual with numpy only (f64)
    L = np.tril(LU, -1).astype(np.float64) + np.eye(N)
    U = np.triu(LU).astype(np.float64)
    ours = _fro(A.astype(np.float64)[perm] - L @ U) / _fro(A)

    # scipy's own f32 factorization of the same matrix, same metric
    slu, piv = scipy.linalg.lu_factor(A)
    sperm = np.arange(N)
    for i, p in enumerate(piv):
        sperm[i], sperm[p] = sperm[p], sperm[i]
    Ls = np.tril(slu, -1).astype(np.float64) + np.eye(N)
    Us = np.triu(slu).astype(np.float64)
    theirs = _fro(A.astype(np.float64)[sperm] - Ls @ Us) / _fro(A)

    assert np.isfinite(ours)
    assert ours <= 10 * theirs, (ours, theirs)


@pytest.mark.slow
def test_cholesky_pipeline_vs_scipy_at_4096():
    """Cholesky's factor is UNIQUE (SPD, positive diagonal), so beyond
    the 10x-residual bar the gathered L is compared elementwise against
    scipy.linalg.cholesky of the same matrix in f64."""
    from conflux_tpu.cholesky.distributed import cholesky_factor_distributed

    N, v = 4096, 256
    # the repo's SPD recipe reproduced inline (diagonally dominant —
    # reconstructions are well-conditioned, so comparisons stay tight)
    rng = np.random.default_rng(7)
    B = rng.uniform(-1.0, 1.0, size=(N, N)).astype(np.float32)
    S = (B + B.T) / 2
    S[np.arange(N), np.arange(N)] += N

    geom = CholeskyGeometry.create(N, v, GRID)
    mesh = make_mesh(GRID, devices=jax.devices()[: GRID.P])
    out = cholesky_factor_distributed(jnp.asarray(geom.scatter(S)),
                                      geom, mesh)
    L = np.tril(geom.gather(np.asarray(out))).astype(np.float64)
    S64 = S.astype(np.float64)

    ours = _fro(S64 - L @ L.T) / _fro(S64)
    Ls = scipy.linalg.cholesky(S, lower=True).astype(np.float64)
    theirs = _fro(S64 - Ls @ Ls.T) / _fro(S64)
    assert np.isfinite(ours)
    assert ours <= 10 * theirs, (ours, theirs)

    # unique-factor elementwise check vs scipy's f64 factorization
    Lref = scipy.linalg.cholesky(S64, lower=True)
    rel = _fro(L - Lref) / _fro(Lref)
    assert rel <= 1e-5, rel


@pytest.mark.slow
def test_qr_pipeline_vs_scipy_at_2048():
    """Full block-cyclic QR at N=2048 f32: reconstruction within 10x of
    scipy's same-precision QR, orthogonality judged with plain numpy,
    and the positive-diagonal R (unique for full-rank A) compared
    normwise against scipy's sign-normalized R."""
    from conflux_tpu.qr.distributed import qr_factor_distributed, r_geometry

    N, v = 2048, 256
    rng = np.random.default_rng(2048)
    A = rng.standard_normal((N, N)).astype(np.float32)

    grid = Grid3(2, 2, 1)
    geom = LUGeometry.create(N, N, v, grid)
    mesh = make_mesh(grid, devices=jax.devices()[: grid.P])
    Qd, Rd = qr_factor_distributed(jnp.asarray(geom.scatter(A)),
                                   geom, mesh)
    Q = geom.gather(np.asarray(Qd)).astype(np.float64)
    R = np.triu(r_geometry(geom).gather(np.asarray(Rd))).astype(np.float64)
    A64 = A.astype(np.float64)

    ours = _fro(A64 - Q @ R) / _fro(A64)
    Qs, Rs = scipy.linalg.qr(A)
    theirs = _fro(A64 - Qs.astype(np.float64) @ Rs.astype(np.float64)) \
        / _fro(A64)
    assert np.isfinite(ours)
    assert ours <= 10 * theirs, (ours, theirs)

    orth = _fro(Q.T @ Q - np.eye(N)) / np.sqrt(N)
    assert orth <= 1e-5, orth

    s = np.sign(np.diag(Rs)).astype(np.float64)
    s[s == 0] = 1.0
    rel = _fro(R - Rs.astype(np.float64) * s[:, None]) / _fro(Rs)
    # R's columnwise sensitivity scales with cond(A) (~1e3 for square
    # gaussian at this size), so the factor bar is looser than the
    # backward-error bars above
    assert rel <= 5e-3, rel
