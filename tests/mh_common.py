"""Shared preamble + fixtures for the multihost worker scripts.

Import this FIRST in a worker: it forces the 4-virtual-device CPU
platform before any jax backend initializes (the conftest pattern — env
vars alone are too late in this image) and puts the repo on sys.path.
"""

import os
import sys

os.environ["XLA_FLAGS"] = ("--xla_force_host_platform_device_count=4 "
                           + os.environ.get("XLA_FLAGS", ""))
sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402


def pos_fill(geom, grid, px, py):
    """Deterministic (Ml, Nl) shard straight from global indices — the
    tile-local position-formula fill every multihost worker uses (the
    reference's per-rank `InitMatrix` role, `lu_params.hpp:141-376`).
    The single definition keeps phase-2 validation and phase-1 input
    generation on the same matrix by construction."""
    v = geom.v
    li = np.arange(geom.Ml)
    lj = np.arange(geom.Nl)
    gi = ((li // v) * grid.Px + px) * v + li % v
    gj = ((lj // v) * grid.Py + py) * v + lj % v
    G = np.sin(0.37 * gi[:, None] + 1.31 * gj[None, :]).astype(np.float32)
    return G + geom.M * (gi[:, None] == gj[None, :])


def my_shard_coords(mesh):
    """Distinct (x, y) shard coordinates among THIS process's devices
    (z-replication can place one shard on several local devices)."""
    return sorted({
        (ix, iy)
        for (ix, iy, iz), d in np.ndenumerate(mesh.devices)
        if d.process_index == jax.process_index()
    })
