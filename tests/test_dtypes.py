"""Element-type coverage: the reference instantiates its LU and layout for
float/double/complex<float>/complex<double> (`layout.cpp:138-191`,
`LU_rep<T>`); the TPU rebuild must factor the same set. bfloat16 is the
TPU-native addition (storage dtype with f32 panel math)."""

import jax.numpy as jnp
import numpy as np
import pytest

from conflux_tpu.geometry import Grid3
from conflux_tpu.lu.distributed import lu_distributed_host
from conflux_tpu.lu.single import lu_factor_blocked
from conflux_tpu.validation import lu_residual, make_test_matrix, residual_bound


def make_complex_matrix(N: int, seed: int = 42, dtype=np.complex128) -> np.ndarray:
    rng = np.random.default_rng(seed)
    A = (rng.uniform(-1, 1, (N, N)) + 1j * rng.uniform(-1, 1, (N, N))).astype(dtype)
    A[np.arange(N), np.arange(N)] += 2.0 + 2.0j
    return A


@pytest.mark.parametrize("dtype", [np.complex64, np.complex128])
def test_lu_single_complex(dtype):
    N = 64
    A = make_complex_matrix(N, dtype=dtype)
    LU, perm = lu_factor_blocked(jnp.asarray(A), v=16)
    assert LU.dtype == jnp.dtype(dtype)
    real = np.float32 if dtype == np.complex64 else np.float64
    assert lu_residual(A, LU, perm) < residual_bound(N, real)


def test_lu_single_complex_tournament():
    from conflux_tpu.ops import blas

    N = 64
    A = make_complex_matrix(N, seed=3)
    blas.set_panel_algo("tournament")
    try:
        LU, perm = lu_factor_blocked(jnp.asarray(A), v=16)
    finally:
        blas.set_panel_algo("auto")
    assert lu_residual(A, LU, perm) < residual_bound(N, np.float64)


def test_lu_distributed_complex():
    N, v = 64, 8
    A = make_complex_matrix(N, seed=5)
    LU, perm, geom = lu_distributed_host(A, Grid3(2, 2, 1), v)
    assert lu_residual(A, LU[perm], perm) < residual_bound(N, np.float64)


def test_lu_single_bfloat16_storage():
    # bf16 storage, f32 panel math: residual at bf16 scale, not garbage
    N = 64
    A = make_test_matrix(N, N, dtype=np.float32)
    LU, perm = lu_factor_blocked(jnp.asarray(A, jnp.bfloat16), v=16)
    assert LU.dtype == jnp.bfloat16
    res = lu_residual(A, np.asarray(LU, np.float32), perm)
    assert res < 100 * np.sqrt(N) * 2**-8, res  # bf16 eps = 2^-8
