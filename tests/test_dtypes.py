"""Element-type coverage: the reference instantiates its LU and layout for
float/double/complex<float>/complex<double> (`layout.cpp:138-191`,
`LU_rep<T>`); the TPU rebuild must factor the same set. bfloat16 is the
TPU-native addition (storage dtype with f32 panel math)."""

import jax.numpy as jnp
import numpy as np
import pytest

from conflux_tpu.geometry import Grid3
from conflux_tpu.lu.distributed import lu_distributed_host
from conflux_tpu.lu.single import lu_factor_blocked
from conflux_tpu.validation import lu_residual, make_test_matrix, residual_bound


def make_complex_matrix(N: int, seed: int = 42, dtype=np.complex128) -> np.ndarray:
    rng = np.random.default_rng(seed)
    A = (rng.uniform(-1, 1, (N, N)) + 1j * rng.uniform(-1, 1, (N, N))).astype(dtype)
    A[np.arange(N), np.arange(N)] += 2.0 + 2.0j
    return A


@pytest.mark.parametrize("dtype", [np.complex64, np.complex128])
def test_lu_single_complex(dtype):
    N = 64
    A = make_complex_matrix(N, dtype=dtype)
    LU, perm = lu_factor_blocked(jnp.asarray(A), v=16)
    assert LU.dtype == jnp.dtype(dtype)
    real = np.float32 if dtype == np.complex64 else np.float64
    assert lu_residual(A, LU, perm) < residual_bound(N, real)


def test_lu_single_complex_tournament():
    from conflux_tpu.ops import blas

    N = 64
    A = make_complex_matrix(N, seed=3)
    blas.set_panel_algo("tournament")
    try:
        LU, perm = lu_factor_blocked(jnp.asarray(A), v=16)
    finally:
        blas.set_panel_algo("auto")
    assert lu_residual(A, LU, perm) < residual_bound(N, np.float64)


def test_lu_distributed_complex():
    N, v = 64, 8
    A = make_complex_matrix(N, seed=5)
    LU, perm, geom = lu_distributed_host(A, Grid3(2, 2, 1), v)
    assert lu_residual(A, LU[perm], perm) < residual_bound(N, np.float64)


@pytest.mark.parametrize("dtype", [np.complex64, np.complex128])
def test_cholesky_single_complex(dtype):
    """Hermitian positive-definite factorization, A = L L^H (the complex
    instantiation the reference's Cholesky core lacks — its potrf path is
    double-only, `Cholesky.cpp:188`)."""
    from conflux_tpu.cholesky.single import cholesky_blocked
    from conflux_tpu.validation import cholesky_residual, make_hpd_matrix

    N = 64
    A = make_hpd_matrix(N, seed=11, dtype=dtype)
    L = cholesky_blocked(jnp.asarray(A), v=16)
    real = np.float32 if dtype == np.complex64 else np.float64
    assert cholesky_residual(A, np.asarray(L)) < residual_bound(N, real)
    assert np.allclose(np.triu(np.asarray(L), 1), 0.0)
    # the diagonal of a Cholesky factor is real-positive
    assert np.all(np.asarray(L).diagonal().real > 0)
    assert np.allclose(np.asarray(L).diagonal().imag, 0.0, atol=1e-6)


def test_cholesky_distributed_complex():
    from conflux_tpu.cholesky.distributed import cholesky_distributed_host
    from conflux_tpu.validation import cholesky_residual, make_hpd_matrix

    N, v = 64, 8
    A = make_hpd_matrix(N, seed=13)
    L, geom = cholesky_distributed_host(A, Grid3(2, 2, 2), v)
    assert cholesky_residual(A, L) < residual_bound(N, np.float64)
    np.testing.assert_allclose(L, np.linalg.cholesky(A), atol=1e-8)


def test_lu_solve_distributed_complex():
    """Complex through the whole distributed LU chain: factor, on-mesh
    residual oracle (conj-product norms), and the mesh triangular solve
    (complex-safe replicated output)."""
    from conflux_tpu.geometry import LUGeometry
    from conflux_tpu.lu.distributed import lu_factor_distributed
    from conflux_tpu.parallel.mesh import make_mesh
    from conflux_tpu.solvers import lu_solve_distributed
    from conflux_tpu.validation import lu_residual_distributed

    N, v = 64, 8
    grid = Grid3(2, 2, 1)
    geom = LUGeometry.create(N, N, v, grid)
    mesh = make_mesh(grid)
    A = make_complex_matrix(N, seed=23)
    sh = jnp.asarray(geom.scatter(A))
    out, perm = lu_factor_distributed(sh, geom, mesh)
    res = float(lu_residual_distributed(sh, out, perm, geom, mesh))
    assert res < residual_bound(N, np.float64), res
    rng = np.random.default_rng(1)
    b = rng.standard_normal(N) + 1j * rng.standard_normal(N)
    x = lu_solve_distributed(out, perm, geom, mesh, jnp.asarray(b))
    assert np.linalg.norm(A @ np.asarray(x) - b) / np.linalg.norm(b) < 1e-10


def test_cholesky_solve_distributed_complex():
    from conflux_tpu.cholesky.distributed import cholesky_factor_distributed
    from conflux_tpu.geometry import CholeskyGeometry
    from conflux_tpu.parallel.mesh import make_mesh
    from conflux_tpu.solvers import cholesky_solve_distributed
    from conflux_tpu.validation import (
        cholesky_residual_distributed, make_hpd_matrix,
    )

    N, v = 64, 8
    grid = Grid3(2, 2, 1)
    geom = CholeskyGeometry.create(N, v, grid)
    mesh = make_mesh(grid)
    A = make_hpd_matrix(N, seed=17)
    sh = jnp.asarray(geom.scatter(A))
    out = cholesky_factor_distributed(sh, geom, mesh)
    # gather-free on-mesh residual handles the Hermitian product
    res = float(cholesky_residual_distributed(sh, out, geom, mesh))
    assert res < residual_bound(N, np.float64), res
    rng = np.random.default_rng(0)
    b = (rng.standard_normal(N) + 1j * rng.standard_normal(N))
    x = cholesky_solve_distributed(out, geom, mesh, jnp.asarray(b))
    assert np.linalg.norm(A @ np.asarray(x) - b) / np.linalg.norm(b) < 1e-10


def test_lu_single_bfloat16_storage():
    # bf16 storage, f32 panel math: residual at bf16 scale, not garbage
    N = 64
    A = make_test_matrix(N, N, dtype=np.float32)
    LU, perm = lu_factor_blocked(jnp.asarray(A, jnp.bfloat16), v=16)
    assert LU.dtype == jnp.bfloat16
    res = lu_residual(A, np.asarray(LU, np.float32), perm)
    assert res < 100 * np.sqrt(N) * 2**-8, res  # bf16 eps = 2^-8


@pytest.mark.parametrize("dtype", [np.complex64, np.complex128])
def test_qr_complex(dtype):
    """QR joins the complex instantiation set (`layout.cpp:138-191`):
    tall tree + blocked path with unitary phase normalization."""
    from conflux_tpu.qr import qr_factor_blocked, tall_qr

    rng = np.random.default_rng(61)
    A = (rng.standard_normal((96, 24))
         + 1j * rng.standard_normal((96, 24))).astype(dtype)
    Q, R = tall_qr(jnp.asarray(A), chunk=32)
    Q, R = np.asarray(Q), np.asarray(R)
    real = np.float32 if dtype == np.complex64 else np.float64
    eps = np.finfo(real).eps
    d = np.diag(R)
    assert np.abs(d.imag).max() < 100 * eps * np.abs(d).max()  # real diag
    assert (d.real >= -100 * eps).all()
    assert np.linalg.norm(Q.conj().T @ Q - np.eye(24)) < 200 * eps
    assert np.linalg.norm(Q @ R - A) / np.linalg.norm(A) < 200 * eps

    Qb, Rb = qr_factor_blocked(jnp.asarray(A), v=8)
    Qb, Rb = np.asarray(Qb), np.asarray(Rb)
    assert np.linalg.norm(Qb @ Rb - A) / np.linalg.norm(A) < 500 * eps
    assert np.linalg.norm(Qb.conj().T @ Qb - np.eye(24)) < 500 * eps


def test_qr_distributed_complex():
    from conflux_tpu.geometry import Grid3
    from conflux_tpu.qr.distributed import qr_blocked_distributed_host

    rng = np.random.default_rng(67)
    A = (rng.standard_normal((64, 32))
         + 1j * rng.standard_normal((64, 32))).astype(np.complex128)
    Q, R, _ = qr_blocked_distributed_host(A, Grid3(2, 2, 1), 8)
    assert np.linalg.norm(Q @ R - A) / np.linalg.norm(A) < 1e-13
    assert np.linalg.norm(Q.conj().T @ Q - np.eye(32)) < 1e-12


def test_cholesky_qr2_complex():
    """The Gram election's upper factor must be L^H, not L^T: a plain
    transpose keeps Q R = A (residual checks pass!) while Q loses
    orthogonality by O(1) on complex inputs."""
    import jax
    from conflux_tpu.geometry import Grid3
    from conflux_tpu.parallel.mesh import make_mesh
    from conflux_tpu.qr.distributed import cholesky_qr2_distributed

    rng = np.random.default_rng(71)
    Px, Ml, n = 4, 32, 12
    A = (rng.standard_normal((Px * Ml, n))
         + 1j * rng.standard_normal((Px * Ml, n))).astype(np.complex128)
    mesh = make_mesh(Grid3(Px, 1, 1), devices=jax.devices()[:Px])
    Qs, R = cholesky_qr2_distributed(A.reshape(Px, Ml, n), mesh)
    Q = np.asarray(Qs).reshape(-1, n)
    assert np.linalg.norm(Q.conj().T @ Q - np.eye(n)) < 1e-12
    assert np.linalg.norm(Q @ np.asarray(R) - A) / np.linalg.norm(A) < 1e-13


def test_lu_distributed_f64_flat_tree():
    """float64 end to end through the flat election tree: the compute
    dtype halves the VMEM-safe call heights, so the dtype-resolved chunk
    default (ADVICE r3) must produce a consistent, correct program —
    chunked nomination, flat nominee stack, f64-grade residual."""
    import jax
    import jax.numpy as jnp

    from conflux_tpu.geometry import Grid3, LUGeometry
    from conflux_tpu.lu.distributed import lu_factor_distributed
    from conflux_tpu.parallel.mesh import make_mesh
    from conflux_tpu.validation import (
        lu_residual,
        make_test_matrix,
        residual_bound,
    )

    N, v = 128, 8
    grid = Grid3(2, 2, 1)
    geom = LUGeometry.create(N, N, v, grid)
    mesh = make_mesh(grid, devices=jax.devices()[: grid.P])
    A = make_test_matrix(N, N, seed=5, dtype=np.float64)
    shards = jnp.asarray(geom.scatter(A))
    out, perm = lu_factor_distributed(shards, geom, mesh,
                                      panel_chunk=2 * v, tree="flat")
    perm = np.asarray(perm)
    assert sorted(perm.tolist()) == list(range(N))
    res = lu_residual(A, geom.gather(np.asarray(out)), perm)
    assert res < residual_bound(N, np.float64), res
