"""Tiered session residency tests: the ISSUE 7 contracts (DESIGN §23).

- The leaf codec round-trips every state dtype BITWISE through the
  io.py disk format (views/casts are lossless by construction).
- Spill -> transparent revive is BITWISE on the plain and checked solve
  paths, with and without Woodbury drift state, from the host AND disk
  tiers; `stack_host_trees` batched restores match per-leaf ones.
- The device tier stays bounded: LRU eviction under count and byte
  caps, high-water never above the cap, spilled sessions report
  `nbytes == 0` while their records account host/disk bytes.
- Stale-drift revival re-factorizes — coalescing through the engine's
  factor lane when client threads storm — and absorbs the drift like a
  DriftPolicy refactor.
- checkpoint()/restore() round-trips a mixed fleet bitwise (counters,
  drift state, probe rows included) at a drain barrier, lazily through
  a residency or eagerly without one.
- Fault sites spill/revive/disk_write/disk_read fail ONLY the owning
  session with structured errors (`SessionSpilled`, `RestoreCorrupt`,
  `InjectedFault`); a spill crash leaves the session resident, a
  revive crash leaves it fully spilled, a corrupt record pins its
  error.
- deadline= x revival: a request expiring while its session faults in
  releases its admission slot and never leaves the session
  half-resident.
- Counters/gauges surface through `profiler.serve_stats()['tier']` and
  `engine.stats()['tier']`.
"""

import os
import threading
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from conflux_tpu import profiler, serve, tier
from conflux_tpu.engine import EngineSaturated, ServeEngine
from conflux_tpu.resilience import (
    DeadlineExceeded,
    FaultPlan,
    FaultSpec,
    HealthPolicy,
    InjectedFault,
    RestoreCorrupt,
    SessionSpilled,
)
from conflux_tpu.tier import ResidentSet, _decode_leaf, _encode_leaf

N, V = 32, 16


def _plan(**kw):
    return serve.FactorPlan.create((N, N), jnp.float32, v=V, **kw)


def _mk(rng, n=N):
    return (rng.standard_normal((n, n)) / np.sqrt(n)
            + 2.0 * np.eye(n)).astype(np.float32)


def _fleet(plan, count, seed=0, drift_rank=0):
    rng = np.random.default_rng(seed)
    out = []
    for _ in range(count):
        A = _mk(rng)
        s = plan.factor(jnp.asarray(A))
        A64 = A.astype(np.float64)
        if drift_rank:
            U = (0.01 * rng.standard_normal((N, drift_rank))
                 ).astype(np.float32)
            Vm = (0.01 * rng.standard_normal((N, drift_rank))
                  ).astype(np.float32)
            s.update(U, Vm)
            A64 = A64 + U.astype(np.float64) @ Vm.astype(np.float64).T
        out.append((s, A64))
    return out


# --------------------------------------------------------------------- #
# the codec
# --------------------------------------------------------------------- #


def test_leaf_codec_bitwise_all_dtypes():
    rng = np.random.default_rng(0)
    leaves = [
        rng.standard_normal((3, 5)).astype(np.float32),
        rng.standard_normal((2, 3, 4)),  # float64
        rng.integers(-(2 ** 30), 2 ** 30, size=(7,)).astype(np.int32),
        rng.integers(-(2 ** 60), 2 ** 60, size=(4, 2)),  # int64
        (rng.standard_normal((3, 3))
         + 1j * rng.standard_normal((3, 3))).astype(np.complex64),
        jnp.asarray(rng.standard_normal((4, 4)),
                    jnp.bfloat16).__array__(),
    ]
    for a in leaves:
        enc, meta = _encode_leaf(a)
        dec = _decode_leaf(enc, meta)
        assert dec.dtype == a.dtype and dec.shape == a.shape
        assert np.array_equal(
            dec.view(np.uint8) if dec.dtype.kind not in "fiu"
            else dec, a.view(np.uint8) if a.dtype.kind not in "fiu"
            else a), a.dtype


def test_disk_record_roundtrip_and_crc(tmp_path):
    rng = np.random.default_rng(1)
    leaves = {"f0": rng.standard_normal((4, 4)).astype(np.float32),
              "A0": rng.standard_normal((4, 4)).astype(np.float32)}
    meta = {"n_factors": 1, "keep_A": False, "has_probe": False,
            "upd": None, "owns_base": False, "last_cond": None,
            "counters": {"factorizations": 1, "solves": 0,
                         "updates": 0, "refactors": 0}}
    d = str(tmp_path / "rec")
    tier._write_record(d, leaves, meta)
    back, meta2 = tier._read_record(d)
    assert meta2 == meta
    for k in leaves:
        assert np.array_equal(back[k], leaves[k])
    # flip a payload byte: the CRC must catch it, with evidence
    with open(str(tmp_path / "rec" / "f0.bin"), "r+b") as f:
        f.seek(30)
        f.write(b"\xff")
    with pytest.raises(RestoreCorrupt) as ei:
        tier._read_record(d)
    assert ei.value.evidence["leaf"] == "f0"
    assert "expected_crc" in ei.value.evidence


# --------------------------------------------------------------------- #
# nbytes accounting
# --------------------------------------------------------------------- #


def test_nbytes_accounting():
    plan = _plan()
    rng = np.random.default_rng(2)
    s, _ = _fleet(plan, 1, seed=2)[0]
    base = s.nbytes
    # trsm single plan: (LU, perm) + A0 (A aliases A0 only when
    # refine > 0, and refine=0 here keeps _A None)
    itemsize = 4
    assert base >= 2 * N * N * itemsize
    U = rng.standard_normal((N, 2)).astype(np.float32)
    s.update(U, U)
    grown = s.nbytes
    assert grown > base  # Up/Vp/Y/Cinv joined the footprint
    rs = ResidentSet()
    rs.adopt(s)
    rs.spill(s)
    assert s.nbytes == 0
    assert s._spill.nbytes > 0
    assert rs.stats()["host_bytes"] == s._spill.nbytes


def test_nbytes_in_engine_stats():
    plan = _plan()
    s, _ = _fleet(plan, 1, seed=3)[0]
    rs = ResidentSet(max_sessions=4)
    rs.adopt(s)
    eng = ServeEngine(max_batch_delay=0.0, residency=rs)
    try:
        st = eng.stats()["tier"]
        assert st["resident_sessions"] == 1
        assert st["device_bytes"] == s.nbytes
    finally:
        eng.close()


# --------------------------------------------------------------------- #
# spill / revive: bitwise transparency
# --------------------------------------------------------------------- #


def test_spill_revive_bitwise_plain_and_checked():
    plan = _plan()
    rng = np.random.default_rng(4)
    s, _ = _fleet(plan, 1, seed=4)[0]
    b = rng.standard_normal((N, 3)).astype(np.float32)
    x0 = np.asarray(s.solve(b))
    xc0, v0 = s.solve_checked(b)
    xc0 = np.asarray(xc0)
    rs = ResidentSet()
    rs.adopt(s)
    assert rs.spill(s) == 1
    assert s.tier == "host" and s._factors is None
    x1 = np.asarray(s.solve(b))  # transparent fault-in
    assert s.tier == "device"
    assert np.array_equal(x0, x1)
    rs.spill(s)
    xc1, v1 = s.solve_checked(b)
    assert np.array_equal(xc0, np.asarray(xc1))
    assert np.array_equal(np.asarray(v0), np.asarray(v1))


def test_spill_revive_bitwise_with_drift():
    plan = _plan()
    rng = np.random.default_rng(5)
    s, _ = _fleet(plan, 1, seed=5, drift_rank=2)[0]
    b = rng.standard_normal((N, 2)).astype(np.float32)
    x0 = np.asarray(s.solve(b))
    rs = ResidentSet()
    rs.adopt(s)
    rs.spill(s)
    assert np.array_equal(x0, np.asarray(s.solve(b)))
    assert s.update_rank == 2  # the Woodbury state came back whole


def test_disk_tier_revive_bitwise(tmp_path):
    plan = _plan()
    rng = np.random.default_rng(6)
    s, _ = _fleet(plan, 1, seed=6, drift_rank=1)[0]
    b = rng.standard_normal((N, 1)).astype(np.float32)
    x0 = np.asarray(s.solve(b))
    h0 = tier.tier_stats()
    rs = ResidentSet(disk_dir=str(tmp_path))
    rs.adopt(s)
    rs.spill(s)
    assert rs.demote(s) == 1
    assert s.tier == "disk"
    assert rs.stats()["disk_bytes"] > 0
    assert np.array_equal(x0, np.asarray(s.solve(b)))
    h1 = tier.tier_stats()
    assert h1["spills_disk"] - h0.get("spills_disk", 0) == 1
    assert h1["revives_disk"] - h0.get("revives_disk", 0) == 1
    assert h1["disk_bytes_written"] > h0.get("disk_bytes_written", 0)
    assert h1["disk_bytes_read"] > h0.get("disk_bytes_read", 0)


def test_update_and_refactor_on_spilled_session():
    """update()/refactor() fault a spilled session in first — every
    state-touching entry revives, not just solve."""
    plan = _plan()
    rng = np.random.default_rng(7)
    s, _ = _fleet(plan, 1, seed=7)[0]
    rs = ResidentSet()
    rs.adopt(s)
    rs.spill(s)
    U = (0.01 * rng.standard_normal((N, 1))).astype(np.float32)
    s.update(U, U)
    assert s.tier == "device" and s.update_rank == 1
    rs.spill(s)
    s.refactor()
    assert s.tier == "device" and s.refactors >= 1


def test_revive_many_stacked_bitwise():
    plan = _plan()
    fleet = _fleet(plan, 4, seed=8)
    rng = np.random.default_rng(8)
    b = rng.standard_normal((N, 2)).astype(np.float32)
    want = [np.asarray(s.solve(b)) for s, _ in fleet]
    rs = ResidentSet()
    rs.adopt(*[s for s, _ in fleet])
    rs.spill(*[s for s, _ in fleet])
    assert rs.revive_many([s for s, _ in fleet]) == 4
    for (s, _), w in zip(fleet, want):
        assert s.tier == "device"
        assert np.array_equal(w, np.asarray(s.solve(b)))


# --------------------------------------------------------------------- #
# capacity: LRU under count/byte caps, bounded high-water
# --------------------------------------------------------------------- #


def test_lru_eviction_count_cap():
    plan = _plan()
    rs = ResidentSet(max_sessions=2, evict_batch=1)
    fleet = _fleet(plan, 5, seed=9)
    for s, _ in fleet:
        rs.adopt(s)
    st = rs.stats()
    assert st["resident_sessions"] <= 2
    assert st["resident_high_water"] <= 2
    assert st["managed_sessions"] == 5
    # the two most recently adopted survive; the LRU spilled
    assert fleet[0][0].tier == "host"
    assert fleet[-1][0].tier == "device"
    # touching a spilled one revicts the now-coldest resident
    rng = np.random.default_rng(9)
    b = rng.standard_normal((N,)).astype(np.float32)
    fleet[0][0].solve(b)
    assert fleet[0][0].tier == "device"
    assert rs.stats()["resident_sessions"] <= 2


def test_byte_cap_bounds_high_water():
    plan = _plan()
    fleet = _fleet(plan, 4, seed=10)
    per = fleet[0][0].nbytes
    cap = 2 * per
    rs = ResidentSet(max_bytes=cap, evict_batch=1)
    for s, _ in fleet:
        rs.adopt(s)
    rng = np.random.default_rng(10)
    b = rng.standard_normal((N,)).astype(np.float32)
    for s, _ in fleet * 2:  # churn through the fleet twice
        s.solve(b)
    st = rs.stats()
    assert st["device_bytes"] <= cap
    assert st["device_bytes_high_water"] <= cap, st
    h = tier.tier_stats()
    assert h["spills_host"] > 0 and h["revives_h2d"] > 0


def test_host_cap_demotes_to_disk(tmp_path):
    plan = _plan()
    fleet = _fleet(plan, 5, seed=11)
    rs = ResidentSet(max_sessions=1, host_max_sessions=2,
                     disk_dir=str(tmp_path), evict_batch=1)
    for s, _ in fleet:
        rs.adopt(s)
    st = rs.stats()
    assert st["resident_sessions"] <= 1
    assert st["host_sessions"] <= 2
    assert st["disk_sessions"] >= 2
    total = (st["resident_sessions"] + st["host_sessions"]
             + st["disk_sessions"] + st["corrupt_sessions"])
    assert total == st["managed_sessions"] == 5  # conservation


# --------------------------------------------------------------------- #
# stale-drift revival through the factor lane
# --------------------------------------------------------------------- #


def test_revive_refactor_direct():
    plan = _plan()
    fleet = _fleet(plan, 1, seed=12, drift_rank=2)
    s, A64 = fleet[0]
    rs = ResidentSet(revive_refactor_rank=2)
    rs.adopt(s)
    rs.spill(s)
    rng = np.random.default_rng(12)
    b = rng.standard_normal((N, 1)).astype(np.float32)
    h0 = tier.tier_stats()
    x = np.asarray(s.solve(b))
    h1 = tier.tier_stats()
    assert h1["revives_refactor"] - h0.get("revives_refactor", 0) == 1
    assert s.update_rank == 0 and s.refactors == 1  # drift absorbed
    want = np.linalg.solve(A64, b.astype(np.float64))
    assert (np.linalg.norm(x - want) / np.linalg.norm(want)) < 1e-4


def test_revive_refactor_coalesces_through_factor_lane():
    plan = _plan()
    fleet = _fleet(plan, 3, seed=13, drift_rank=1)
    rs = ResidentSet(revive_refactor_rank=1)
    eng = ServeEngine(max_batch_delay=0.05, residency=rs)
    rng = np.random.default_rng(13)
    b = rng.standard_normal((N, 1)).astype(np.float32)
    try:
        rs.adopt(*[s for s, _ in fleet])
        rs.spill(*[s for s, _ in fleet])
        errs = []

        def touch(s):
            try:
                s.solve(b)
            except Exception as e:  # noqa: BLE001 — recorded, asserted
                errs.append(e)

        ts = [threading.Thread(target=touch, args=(s,))
              for s, _ in fleet]
        for t in ts:
            t.start()
        for t in ts:
            t.join(60)
        assert not errs, errs
        st = eng.stats()
        # the storm coalesced: fewer factor dispatches than sessions
        assert st["factor_batches"] < 3
        assert st["factor_coalesced_requests"] == 3
        for s, A64 in fleet:
            assert s.update_rank == 0 and s.refactors == 1
            x = np.asarray(s.solve(b))
            want = np.linalg.solve(A64, b.astype(np.float64))
            assert (np.linalg.norm(x - want)
                    / np.linalg.norm(want)) < 1e-4
    finally:
        eng.close()


# --------------------------------------------------------------------- #
# checkpoint / restore
# --------------------------------------------------------------------- #


def test_checkpoint_restore_bitwise(tmp_path):
    plan = _plan(refine=1)
    fleet = _fleet(plan, 2, seed=14) + _fleet(plan, 1, seed=15,
                                              drift_rank=2)
    sessions = [s for s, _ in fleet]
    rng = np.random.default_rng(14)
    b = rng.standard_normal((N, 2)).astype(np.float32)
    want_plain = [np.asarray(s.solve(b)) for s in sessions]
    want_checked = [tuple(np.asarray(a) for a in s.solve_checked(b))
                    for s in sessions]
    counters = [(s.factorizations, s.solves, s.updates, s.refactors)
                for s in sessions]
    tier.save_fleet(str(tmp_path / "ck"), sessions)
    # simulate the process dying: drop every cached plan/program
    serve.clear_plans()
    restored = tier.load_fleet(str(tmp_path / "ck"))
    for i, r in enumerate(restored):
        assert (r.factorizations, r.solves, r.updates,
                r.refactors) == counters[i]
        assert np.array_equal(want_plain[i], np.asarray(r.solve(b)))
        xc, v = r.solve_checked(b)
        assert np.array_equal(want_checked[i][0], np.asarray(xc))
        assert np.array_equal(want_checked[i][1], np.asarray(v))
    assert restored[2].update_rank == 2  # drift state survived


def test_engine_checkpoint_drain_barrier_and_lazy_restore(tmp_path):
    plan = _plan()
    fleet = _fleet(plan, 3, seed=16)
    rs = ResidentSet(max_sessions=2)
    rs.adopt(*[s for s, _ in fleet])
    eng = ServeEngine(max_batch_delay=0.002, residency=rs)
    rng = np.random.default_rng(16)
    b = rng.standard_normal((N, 1)).astype(np.float32)
    want = [np.asarray(s.solve(b)) for s, _ in fleet]
    try:
        # checkpoint races live traffic: the barrier drains first
        futs = [eng.submit(fleet[i % 3][0], b) for i in range(9)]
        eng.checkpoint(str(tmp_path / "ck"))
        for f in futs:
            f.result(60)
        # restore through a residency: sessions come back HOST-tier
        # (lazy) and fault in on first touch
        rs2 = ResidentSet(max_sessions=2)
        eng2 = ServeEngine(max_batch_delay=0.0, residency=rs2)
        try:
            restored = eng2.restore(str(tmp_path / "ck"))
            assert all(r.tier == "host" for r in restored)
            for i, r in enumerate(restored):
                x = eng2.solve(r, b, timeout=60)
                assert np.array_equal(want[i], x)
            assert rs2.stats()["resident_sessions"] <= 2
        finally:
            eng2.close()
    finally:
        eng.close()


def test_mesh_plan_key_roundtrips_fleet_codec(tmp_path):
    """ISSUE 17 satellite: a mesh plan's identity — device ids, axis
    names, device-array shape — rides the fleet.json codec, and the
    restored plan rebuilds its mesh (hence its out_shardings, which key
    on `mesh_cache_key`) EXACTLY. The restored session answers bitwise
    and its factors land sharded back across the mesh."""
    from conflux_tpu.batched import batch_mesh

    serve.clear_plans()
    mesh = batch_mesh()
    plan = serve.FactorPlan.create((8, N, N), jnp.float32, v=V,
                                   mesh=mesh)
    key0 = plan.key
    rng = np.random.default_rng(17)
    A = np.stack([_mk(rng) for _ in range(8)])
    s = plan.factor(jnp.asarray(A))
    b = rng.standard_normal((8, N)).astype(np.float32)
    x0 = np.asarray(s.solve(jnp.asarray(b)))
    tier.save_fleet(str(tmp_path / "ck"), [s], names=["m"])
    serve.clear_plans()  # a cold process: the codec must carry it all
    (back,) = tier.load_fleet(str(tmp_path / "ck"))
    assert back.plan.key == key0
    assert back.plan.key.mesh_key == key0.mesh_key
    m2 = back.plan.mesh
    assert [d.id for d in m2.devices.flat] \
        == [d.id for d in mesh.devices.flat]
    assert m2.axis_names == mesh.axis_names
    assert m2.devices.shape == mesh.devices.shape
    np.testing.assert_array_equal(
        x0, np.asarray(back.solve(jnp.asarray(b))))
    f0 = jax.tree_util.tree_leaves(back._factors)[0]
    assert len(f0.sharding.device_set) == 8
    # the registry aliases: an equal key resolves to the live plan
    assert serve.FactorPlan.from_key(back.plan.key) is back.plan


# --------------------------------------------------------------------- #
# fault injection: blast radius is one session
# --------------------------------------------------------------------- #


def test_spill_fault_leaves_session_resident():
    plan = _plan()
    s, _ = _fleet(plan, 1, seed=18)[0]
    rs = ResidentSet(fault_plan=FaultPlan(
        [FaultSpec("spill", "crash", count=1)]))
    rs.adopt(s)
    h0 = tier.tier_stats()
    assert rs.spill(s) == 0  # the crash aborted the spill
    assert s.tier == "device"  # fail-safe: still resident
    assert (tier.tier_stats()["spill_faults"]
            - h0.get("spill_faults", 0)) == 1
    rng = np.random.default_rng(18)
    s.solve(rng.standard_normal((N,)).astype(np.float32))
    assert rs.spill(s) == 1  # budget spent: the next spill works


def test_revive_fault_structured_and_record_intact():
    plan = _plan()
    s, _ = _fleet(plan, 1, seed=19)[0]
    rng = np.random.default_rng(19)
    b = rng.standard_normal((N, 1)).astype(np.float32)
    x0 = np.asarray(s.solve(b))
    rs = ResidentSet(fault_plan=FaultPlan(
        [FaultSpec("revive", "crash", count=1)]))
    rs.adopt(s)
    rs.spill(s)
    with pytest.raises(InjectedFault):
        s.solve(b)
    assert s.tier == "host"  # fully spilled, record intact
    assert np.array_equal(x0, np.asarray(s.solve(b)))  # retry revives


def test_revive_fault_fails_only_owner_in_engine():
    """A revive crash on one session's dispatch fails only that
    session's request; co-submitted requests against healthy sessions
    answer normally (blast-radius isolation through the engine)."""
    plan = _plan()
    (sick, _), (ok, _) = _fleet(plan, 2, seed=20)
    rng = np.random.default_rng(20)
    b = rng.standard_normal((N, 1)).astype(np.float32)
    x_ok = np.asarray(ok.solve(b))
    faults = FaultPlan([FaultSpec("revive", "crash", count=2)])
    rs = ResidentSet(fault_plan=faults)
    eng = ServeEngine(max_batch_delay=0.01, residency=rs)
    try:
        rs.adopt(sick, ok)
        rs.spill(sick)
        f_sick = eng.submit(sick, b)
        f_ok = eng.submit(ok, b)
        assert np.array_equal(x_ok, f_ok.result(60))
        with pytest.raises(InjectedFault):
            f_sick.result(60)
        assert sick.tier == "host"
    finally:
        eng.close()


def test_disk_corruption_restorecorrupt_only_owner(tmp_path):
    plan = _plan()
    (bad, _), (good, _) = _fleet(plan, 2, seed=21)
    rng = np.random.default_rng(21)
    b = rng.standard_normal((N, 1)).astype(np.float32)
    x_good = np.asarray(good.solve(b))
    faults = FaultPlan([FaultSpec("disk_write", "nan", count=1)])
    rs = ResidentSet(disk_dir=str(tmp_path), fault_plan=faults)
    rs.adopt(bad, good)
    rs.spill(bad, good)
    rs.demote(bad)   # this write corrupts (the injected 'nan')
    rs.demote(good)  # budget spent: a clean record
    with pytest.raises(RestoreCorrupt) as ei:
        bad.solve(b)
    assert "expected_crc" in ei.value.evidence
    assert bad.tier == "corrupt"
    h = tier.tier_stats()
    assert h["restore_corrupt"] >= 1
    # the error is pinned: every later touch re-raises it
    with pytest.raises(RestoreCorrupt):
        bad.solve(b)
    # the sibling is untouched, bitwise
    assert np.array_equal(x_good, np.asarray(good.solve(b)))
    st = rs.stats()
    assert st["corrupt_sessions"] == 1
    assert (st["resident_sessions"] + st["host_sessions"]
            + st["disk_sessions"] + st["corrupt_sessions"]) == 2


def test_disk_read_fault_then_recovers(tmp_path):
    plan = _plan()
    s, _ = _fleet(plan, 1, seed=22)[0]
    rng = np.random.default_rng(22)
    b = rng.standard_normal((N, 1)).astype(np.float32)
    x0 = np.asarray(s.solve(b))
    faults = FaultPlan([FaultSpec("disk_read", "crash", count=1)])
    rs = ResidentSet(disk_dir=str(tmp_path), fault_plan=faults)
    rs.adopt(s)
    rs.spill(s)
    rs.demote(s)
    with pytest.raises(InjectedFault):
        s.solve(b)
    assert s.tier == "disk"  # record intact on disk
    assert np.array_equal(x0, np.asarray(s.solve(b)))


# --------------------------------------------------------------------- #
# deadline x revival + backpressure
# --------------------------------------------------------------------- #


def test_deadline_expiring_during_fault_in_releases_slot():
    plan = _plan()
    s, _ = _fleet(plan, 1, seed=23)[0]
    rng = np.random.default_rng(23)
    b = rng.standard_normal((N, 1)).astype(np.float32)
    x0 = np.asarray(s.solve(b))
    rs = ResidentSet(max_concurrent_revives=1)
    eng = ServeEngine(max_batch_delay=0.0, residency=rs)
    try:
        rs.adopt(s)
        rs.spill(s)
        assert rs._revive_sem.acquire(timeout=1)  # saturate the lane
        try:
            fut = eng.submit(s, b, deadline=0.1)
            with pytest.raises((SessionSpilled, DeadlineExceeded)):
                fut.result(30)
            # the admission slot is released and the session is FULLY
            # spilled — never half-resident
            assert eng.stats()["pending"] == 0
            assert s.tier == "host" and s._factors is None
            assert tier.tier_stats()["revive_rejects"] >= 1
        finally:
            rs._revive_sem.release()
        # the lane freed: the same session revives and answers bitwise
        assert np.array_equal(x0, eng.solve(s, b, timeout=60))
    finally:
        eng.close()


def test_direct_fault_in_timeout_structured():
    plan = _plan()
    s, _ = _fleet(plan, 1, seed=24)[0]
    rs = ResidentSet(max_concurrent_revives=1)
    rs.adopt(s)
    rs.spill(s)
    assert rs._revive_sem.acquire(timeout=1)
    try:
        with pytest.raises(SessionSpilled):
            rs.fault_in(s, timeout=0.05)
        assert s.tier == "host"
    finally:
        rs._revive_sem.release()
    rs.fault_in(s)
    assert s.tier == "device"


# --------------------------------------------------------------------- #
# review regressions: barrier x revival, concurrent checkpoints/adopts,
# corrupt-record accounting, revive_many partial progress
# --------------------------------------------------------------------- #


def test_factor_lane_sheds_at_drain_barrier():
    """A factor submission during a checkpoint drain SHEDS instead of
    waiting: a stale-drift revival holds its session RLock while
    submitting, and save_fleet needs that lock — waiting would wedge
    the engine forever (review-caught deadlock)."""
    plan = _plan()
    rng = np.random.default_rng(32)
    A = _mk(rng)
    eng = ServeEngine(max_batch_delay=0.0)
    try:
        with eng._lock:
            eng._draining = True
        try:
            t0 = time.perf_counter()
            with pytest.raises(EngineSaturated):
                eng.submit_factor(plan, A)
            assert time.perf_counter() - t0 < 5.0  # shed, not waited
        finally:
            with eng._lock:
                eng._draining = False
                eng._not_full.notify_all()
        # the barrier cleared: the factor lane flows again
        s = eng.factor(plan, A, timeout=60)
        assert np.asarray(
            s.solve(np.zeros(N, np.float32))).shape == (N,)
    finally:
        eng.close()


def test_checkpoint_vs_stale_revival_no_deadlock(tmp_path, monkeypatch):
    """checkpoint() racing a client-thread stale-drift revival: the
    client holds the session RLock and submits to the factor lane while
    the drain barrier is up; the submission sheds, the revival falls
    back to the direct factor path, and save_fleet then gets the lock —
    both sides complete."""
    plan = _plan()
    fleet = _fleet(plan, 2, seed=33, drift_rank=1)
    rs = ResidentSet(revive_refactor_rank=1)
    eng = ServeEngine(max_batch_delay=0.0, residency=rs)
    rng = np.random.default_rng(33)
    b = rng.standard_normal((N, 1)).astype(np.float32)
    in_barrier = threading.Event()
    client_done = threading.Event()
    real_save = tier.save_fleet

    def slow_save(path, sessions, names=None, **kw):
        in_barrier.set()
        client_done.wait(30)  # hold the barrier across the revival
        return real_save(path, sessions, names, **kw)

    monkeypatch.setattr(tier, "save_fleet", slow_save)
    try:
        rs.adopt(*[s for s, _ in fleet])
        rs.spill(*[s for s, _ in fleet])
        errs, xs = [], []

        def ckpt():
            try:
                eng.checkpoint(str(tmp_path / "ck"))
            except Exception as e:  # noqa: BLE001 — recorded, asserted
                errs.append(e)

        def touch():
            try:
                xs.append(np.asarray(fleet[0][0].solve(b)))
            except Exception as e:  # noqa: BLE001 — recorded, asserted
                errs.append(e)

        ct = threading.Thread(target=ckpt, daemon=True)
        ct.start()
        assert in_barrier.wait(30)
        tt = threading.Thread(target=touch, daemon=True)
        tt.start()
        tt.join(30)
        revived = not tt.is_alive()
        client_done.set()
        ct.join(60)
        assert revived, "revival deadlocked against the drain barrier"
        assert not ct.is_alive(), "checkpoint deadlocked"
        assert not errs, errs
        want = np.linalg.solve(fleet[0][1], b.astype(np.float64))
        assert (np.linalg.norm(xs[0] - want)
                / np.linalg.norm(want)) < 1e-4
        assert fleet[0][0].refactors == 1  # the direct fallback ran
    finally:
        client_done.set()
        eng.close()


def test_concurrent_checkpoints_serialize(tmp_path, monkeypatch):
    """Two concurrent checkpoint() calls take their own complete drain
    barriers (the snapshots never overlap), both land restorable
    records, and admission reopens afterwards."""
    plan = _plan()
    fleet = _fleet(plan, 2, seed=34)
    rs = ResidentSet()
    eng = ServeEngine(max_batch_delay=0.0, residency=rs)
    rng = np.random.default_rng(34)
    b = rng.standard_normal((N, 1)).astype(np.float32)
    real_save = tier.save_fleet
    alock = threading.Lock()
    active, peak = [0], [0]

    def counted_save(path, sessions, names=None, **kw):
        with alock:
            active[0] += 1
            peak[0] = max(peak[0], active[0])
        try:
            time.sleep(0.05)
            return real_save(path, sessions, names, **kw)
        finally:
            with alock:
                active[0] -= 1

    monkeypatch.setattr(tier, "save_fleet", counted_save)
    try:
        rs.adopt(*[s for s, _ in fleet])
        want = [np.asarray(s.solve(b)) for s, _ in fleet]
        errs = []

        def ck(d):
            try:
                eng.checkpoint(str(d))
            except Exception as e:  # noqa: BLE001 — recorded, asserted
                errs.append(e)

        ts = [threading.Thread(target=ck, args=(tmp_path / f"ck{i}",),
                               daemon=True) for i in range(2)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(60)
        assert not any(t.is_alive() for t in ts)
        assert not errs, errs
        assert peak[0] == 1, "snapshots overlapped under one barrier"
        with eng._lock:
            assert not eng._draining  # the barrier fully cleared
        assert np.array_equal(want[0],
                              eng.solve(fleet[0][0], b, timeout=60))
        for i in range(2):
            restored = tier.load_fleet(str(tmp_path / f"ck{i}"))
            for j, r in enumerate(restored):
                assert np.array_equal(want[j], np.asarray(r.solve(b)))
    finally:
        eng.close()


def test_concurrent_adopt_touch_churn_consistent():
    """Concurrent re-adopts and touches under count pressure: adopt()
    used to size its eviction wave while HOLDING the adoptee's session
    lock, letting two adopts pick each other's adoptee as a victim
    (lock cycle) or a re-adoption spill its own adoptee mid-adopt.
    The hammer asserts liveness and resident<->record consistency."""
    plan = _plan()
    fleet = _fleet(plan, 3, seed=35)
    rs = ResidentSet(max_sessions=1, evict_batch=1)
    rs.adopt(*[s for s, _ in fleet])
    rng = np.random.default_rng(35)
    b = rng.standard_normal((N,)).astype(np.float32)
    stop = time.perf_counter() + 2.0
    errs = []

    def churn(s):
        try:
            while time.perf_counter() < stop:
                rs.adopt(s)
                s.solve(b)
        except Exception as e:  # noqa: BLE001 — recorded, asserted
            errs.append(e)

    ts = [threading.Thread(target=churn, args=(s,), daemon=True)
          for s, _ in fleet]
    for t in ts:
        t.start()
    for t in ts:
        t.join(30)
    assert not any(t.is_alive() for t in ts), "adopt churn deadlocked"
    assert not errs, errs
    st = rs.stats()
    assert (st["resident_sessions"] + st["host_sessions"]
            + st["disk_sessions"] + st["corrupt_sessions"]) == 3
    with rs._lock:
        states = {id(s): rs._state.get(id(s)) for s, _ in fleet}
    for s, _ in fleet:
        if states[id(s)] == "resident":
            assert s._spill is None  # never resident WITH a record
        elif states[id(s)] in ("host", "disk"):
            assert s._spill is not None
    # the fleet still answers correctly after the storm
    for s, A64 in fleet:
        x = np.asarray(s.solve(b))
        want = np.linalg.solve(A64, b.astype(np.float64))
        assert (np.linalg.norm(x - want) / np.linalg.norm(want)) < 1e-4


def test_corrupt_record_retires_gauges_and_disk_space(tmp_path):
    plan = _plan()
    s, _ = _fleet(plan, 1, seed=36)[0]
    rng = np.random.default_rng(36)
    b = rng.standard_normal((N, 1)).astype(np.float32)
    faults = FaultPlan([FaultSpec("disk_write", "nan", count=1)])
    rs = ResidentSet(disk_dir=str(tmp_path), fault_plan=faults)
    rs.adopt(s)
    rs.spill(s)
    rs.demote(s)
    rec_path = s._spill.path
    assert rs.stats()["disk_bytes"] > 0
    with pytest.raises(RestoreCorrupt) as e1:
        s.solve(b)
    # the dead record stops counting against the disk tier and its
    # directory is reclaimed (a CRC failure is permanent)
    assert rs.stats()["disk_bytes"] == 0
    assert not os.path.exists(rec_path)
    # later touches raise a FRESH copy of the pinned error — the one
    # instance is never re-raised (and traceback-mutated) across
    # threads — chained to the original with the same evidence
    with pytest.raises(RestoreCorrupt) as e2:
        s.solve(b)
    assert e2.value is not e1.value
    assert e2.value.__cause__ is e1.value
    assert e2.value.evidence == e1.value.evidence


def test_fault_in_reports_noop_and_revive_many_counts_real_work():
    plan = _plan()
    fleet = _fleet(plan, 3, seed=37)
    rs = ResidentSet()
    rs.adopt(*[s for s, _ in fleet])
    assert rs.fault_in(fleet[0][0]) is False  # resident: a no-op
    rs.spill(*[s for s, _ in fleet])
    assert rs.fault_in(fleet[0][0]) is True
    assert rs.fault_in(fleet[0][0]) is False  # already back
    # only the two still-spilled sessions count as revived
    assert rs.revive_many([s for s, _ in fleet]) == 2
    assert all(s.tier == "device" for s, _ in fleet)


def test_revive_many_respects_device_caps():
    """The stacked group path lands a whole chunk in one h2d — an
    uncapped group overshot max_sessions with nothing left to evict
    (caught driving the warm-restart surface). Groups now chunk to the
    caps: later chunks LRU-evict earlier ones, the high-water stays
    bounded, and every revived answer is still bitwise."""
    plan = _plan()
    fleet = _fleet(plan, 6, seed=39)
    rng = np.random.default_rng(39)
    b = rng.standard_normal((N, 1)).astype(np.float32)
    want = [np.asarray(s.solve(b)) for s, _ in fleet]
    rs = ResidentSet(max_sessions=3)
    rs.adopt(*[s for s, _ in fleet])
    rs.spill(*[s for s, _ in fleet])
    assert rs.revive_many([s for s, _ in fleet]) == 6
    st = rs.stats()
    assert st["resident_sessions"] <= 3, st
    assert st["resident_high_water"] <= 3, st
    for (s, _), w in zip(fleet, want):
        assert np.array_equal(w, np.asarray(s.solve(b)))
    assert rs.stats()["resident_high_water"] <= 3


def test_revive_many_partial_progress_under_backpressure():
    """A saturated revive lane skips sessions (records intact,
    `revive_rejects` bumped) instead of aborting the whole batch with
    the first SessionSpilled; the count reports what actually landed."""
    plan = _plan()
    fleet = _fleet(plan, 2, seed=38, drift_rank=1)  # drifted: rest path
    rs = ResidentSet(max_concurrent_revives=1)
    rs.adopt(*[s for s, _ in fleet])
    rs.spill(*[s for s, _ in fleet])
    h0 = tier.tier_stats()
    assert rs._revive_sem.acquire(timeout=1)  # saturate the lane
    try:
        assert rs.revive_many([s for s, _ in fleet], timeout=0.05) == 0
        assert all(s.tier == "host" for s, _ in fleet)
        assert (tier.tier_stats()["revive_rejects"]
                - h0.get("revive_rejects", 0)) >= 2
    finally:
        rs._revive_sem.release()
    # the lane freed: the same call revives everyone
    assert rs.revive_many([s for s, _ in fleet]) == 2
    assert all(s.tier == "device" for s, _ in fleet)


# --------------------------------------------------------------------- #
# observability
# --------------------------------------------------------------------- #


def test_tier_counters_in_serve_stats(tmp_path):
    plan = _plan()
    s, _ = _fleet(plan, 1, seed=25)[0]
    rng = np.random.default_rng(25)
    b = rng.standard_normal((N, 1)).astype(np.float32)
    rs = ResidentSet(disk_dir=str(tmp_path))
    rs.adopt(s)
    rs.spill(s)
    s.solve(b)
    st = profiler.serve_stats()["tier"]
    assert st["spills_host"] >= 1
    assert st["revives_h2d"] >= 1
    assert st["fault_in_p50_ms"] > 0
    assert st["managed_sessions"] >= 1
    assert st["device_bytes_high_water"] > 0
    # clear() resets the counters; the manager's gauges survive
    profiler.clear()
    st2 = profiler.serve_stats()["tier"]
    assert st2["spills_host"] == 0 and st2["revives_h2d"] == 0
    assert st2["managed_sessions"] >= 1
