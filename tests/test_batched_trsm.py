"""Blocked batched trsm: the ISSUE 11 contracts (DESIGN §27).

- `ops.batched_trsm.blocked_trsm` agrees with `lax.linalg.triangular_solve`
  across dtypes (f32/f64), shapes (N in {8, 64, 256}, B in {1, 4, 32}),
  sides (lower/upper) and unit/non-unit diagonals — including N not a
  multiple of the block size (identity-extended tail block).
- The Pallas kernel (interpret mode on CPU) matches the pure-XLA path.
- The fused Freivalds probe epilogue leaves x untouched and its in-loop
  accumulators equal the post-hoc reductions.
- `substitution="auto"` resolves to 'blocked' for every servable plan
  (batched AND single-system — the gang/factor-lane-served shapes);
  'inv'/'trsm' stay explicit opt-ins; the blocked engine's answers hold
  the other engines' residual bars, drift/refactor included.
- The fused-probe checked programs live in the dedicated `_trsm_cache`
  (never polluting `_solve_cache`, whose key set tests pin), and ride
  `bucket_ready`/`release_buckets` like every other family.
- The vmapped blocked programs keep the bucket/pad bitwise-invariance
  contract (slot i identical across stack buckets and pad contents).
- Gang end-to-end: a `substitution="blocked"` (auto) plan serves
  stacked — clean, drifted (Woodbury) and checked (fused per-slot
  verdict) legs — with the exclusion counters at literal zero: the
  "gang plans must open with inv" rule is retired.
- `PlanKey.substitution` round-trips through the tier layer's
  fleet.json save/restore codec.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax import lax

from conflux_tpu import serve
from conflux_tpu.batched import solve_batched, stack_trees
from conflux_tpu.engine import ServeEngine
from conflux_tpu.ops import batched_trsm as bt
from conflux_tpu.ops import blas
from conflux_tpu.resilience import HealthPolicy


def _tri(rng, B, N, dtype, lower):
    A = (rng.standard_normal((B, N, N)) / np.sqrt(N)
         + 2.0 * np.eye(N)).astype(dtype)
    return np.tril(A) if lower else np.triu(A), A


# --------------------------------------------------------------------- #
# the kernel engine vs lax.linalg.triangular_solve
# --------------------------------------------------------------------- #


@pytest.mark.parametrize("dtype,rtol", [(np.float32, 3e-4),
                                        (np.float64, 1e-10)])
@pytest.mark.parametrize("B,N", [(32, 8), (4, 64), (1, 256), (32, 256)])
@pytest.mark.parametrize("lower,unit", [(True, False), (True, True),
                                        (False, False)])
def test_blocked_trsm_matches_lax(dtype, rtol, B, N, lower, unit):
    rng = np.random.default_rng(N * B + lower + 2 * unit)
    T, A = _tri(rng, B, N, dtype, lower)
    # unit solves read the packed form: pass the FULL matrix (garbage
    # on/above the diagonal from the other factor) like packed LU does
    operand = A if unit else T
    b = rng.standard_normal((B, N, 2)).astype(dtype)
    x = bt.blocked_trsm(operand, b, lower=lower, unit_diagonal=unit)
    ref = lax.linalg.triangular_solve(
        jnp.asarray(T), jnp.asarray(b), left_side=True, lower=lower,
        unit_diagonal=unit)
    np.testing.assert_allclose(np.asarray(x), np.asarray(ref),
                               rtol=rtol, atol=30 * rtol)


def test_blocked_trsm_ragged_tail_block():
    """N=48 is not a multiple of the default 32-wide block: the tail
    block identity-extends, and padded answers slice back exactly."""
    rng = np.random.default_rng(48)
    T, _ = _tri(rng, 3, 48, np.float32, True)
    b = rng.standard_normal((3, 48, 1)).astype(np.float32)
    x = bt.blocked_trsm(T, b, lower=True)
    ref = lax.linalg.triangular_solve(
        jnp.asarray(T), jnp.asarray(b), left_side=True, lower=True)
    np.testing.assert_allclose(np.asarray(x), np.asarray(ref),
                               rtol=3e-4, atol=1e-5)


def test_blocked_trsm_vector_rhs_and_shape_checks():
    rng = np.random.default_rng(5)
    T, _ = _tri(rng, 2, 64, np.float32, True)
    b = rng.standard_normal((2, 64)).astype(np.float32)
    x = bt.blocked_trsm(T, b)
    assert x.shape == (2, 64)
    with pytest.raises(ValueError, match="rhs"):
        bt.blocked_trsm(T, b[:, :32])
    with pytest.raises(ValueError, match="T must be"):
        bt.blocked_trsm(T[:, :32, :], b)


def test_pallas_kernel_matches_xla_path():
    """The Pallas batched kernel (interpret mode off-TPU) is bitwise-
    grade close to the pure-XLA block loop, lower and upper, ragged
    included — the §7 interpret-mode correctness discipline."""
    rng = np.random.default_rng(9)
    for N, k, lower in [(128, 1, True), (128, 4, False), (48, 2, True)]:
        T, _ = _tri(rng, 4, N, np.float32, lower)
        b = rng.standard_normal((4, N, k)).astype(np.float32)
        xp = bt.blocked_trsm(T, b, lower=lower, backend="pallas")
        xx = bt.blocked_trsm(T, b, lower=lower, backend="xla")
        np.testing.assert_allclose(np.asarray(xp), np.asarray(xx),
                                   rtol=1e-5, atol=1e-6)


def test_blas_registry_entry_resolves_backend():
    rng = np.random.default_rng(13)
    T, _ = _tri(rng, 2, 64, np.float32, True)
    b = rng.standard_normal((2, 64, 1)).astype(np.float32)
    x0 = blas.blocked_trsm(T, b)  # module backend (xla)
    x1 = blas.blocked_trsm(T, b, backend="pallas")
    np.testing.assert_allclose(np.asarray(x0), np.asarray(x1),
                               rtol=1e-5, atol=1e-6)


def test_probe_epilogue_accumulates_in_loop():
    """The fused epilogue's accumulators equal the post-hoc reductions
    and leave x exactly the unfused solve's bits."""
    rng = np.random.default_rng(21)
    T, _ = _tri(rng, 1, 64, np.float32, False)
    T = T[0]
    dinv = bt.diag_block_inverses(jnp.asarray(T), lower=False)
    b = rng.standard_normal((64, 2)).astype(np.float32)
    wA = rng.standard_normal(64).astype(np.float32)
    x, xsum, wAx = bt.blocked_solve_probe(
        jnp.asarray(T), dinv, jnp.asarray(b), jnp.asarray(wA),
        lower=False, stats_dtype=jnp.float32)
    x0 = bt.blocked_solve(jnp.asarray(T), dinv, jnp.asarray(b),
                          lower=False)
    np.testing.assert_array_equal(np.asarray(x), np.asarray(x0))
    assert np.isclose(float(xsum), float(np.sum(np.asarray(x))),
                      rtol=1e-4)
    assert np.isclose(float(wAx),
                      float(np.dot(wA, np.asarray(x)[:, 0])),
                      rtol=1e-3, atol=1e-4)


# --------------------------------------------------------------------- #
# plan wiring: auto resolution, residual bars, cache isolation
# --------------------------------------------------------------------- #

N, V = 64, 16


def _mk(rng, n=1):
    A = (rng.standard_normal((n, N, N)) / np.sqrt(N)
         + 2.0 * np.eye(N)).astype(np.float32)
    return A


def test_auto_resolves_to_blocked_everywhere():
    serve.clear_plans()
    single = serve.FactorPlan.create((N, N), jnp.float32, v=V)
    batched = serve.FactorPlan.create((4, N, N), jnp.float32, v=V)
    assert single.key.substitution == "blocked"
    assert batched.key.substitution == "blocked"
    # explicit opt-ins still resolve verbatim
    for sub in ("inv", "trsm", "blocked"):
        p = serve.FactorPlan.create((N, N), jnp.float32, v=V,
                                    substitution=sub)
        assert p.key.substitution == sub
    with pytest.raises(ValueError, match="substitution"):
        serve.FactorPlan.create((N, N), jnp.float32, v=V,
                                substitution="nope")


@pytest.mark.parametrize("spd", [False, True])
def test_blocked_plan_holds_residual_bars(spd):
    serve.clear_plans()
    rng = np.random.default_rng(31)
    A = _mk(rng)[0]
    if spd:
        A = (A @ A.T / N + 2.0 * np.eye(N)).astype(np.float32)
    plan = serve.FactorPlan.create((N, N), jnp.float32, v=V, spd=spd)
    assert plan.key.substitution == "blocked"
    s = plan.factor(jnp.asarray(A))
    b = rng.standard_normal((N, 3)).astype(np.float32)
    x = np.asarray(s.solve(jnp.asarray(b)))
    assert np.abs(A @ x - b).max() < 1e-4
    # drift + refactor ride the blocked corr too (spd plans need an
    # SPD-preserving drift — a refactor re-runs Cholesky on the
    # drifted base)
    U = (0.01 * rng.standard_normal((N, 2))).astype(np.float32)
    Vv = U if spd else (0.01 * rng.standard_normal((N, 2))
                        ).astype(np.float32)
    s.update(U, Vv)
    xd = np.asarray(s.solve(jnp.asarray(b)))
    assert np.abs((A + U @ Vv.T) @ xd - b).max() < 1e-4
    s.refactor()
    xr = np.asarray(s.solve(jnp.asarray(b)))
    assert np.abs((A + U @ Vv.T) @ xr - b).max() < 1e-4


def test_solve_batched_blocked_substitution():
    rng = np.random.default_rng(37)
    A = _mk(rng, 6)
    b = rng.standard_normal((6, N)).astype(np.float32)
    xt = np.asarray(solve_batched(A, b, v=V))
    xb = np.asarray(solve_batched(A, b, v=V, substitution="blocked"))
    np.testing.assert_allclose(xb, xt, rtol=2e-4, atol=1e-5)
    with pytest.raises(ValueError, match="substitution"):
        solve_batched(A, b, v=V, substitution="inv")


def test_fused_checked_programs_live_in_trsm_cache():
    """The blocked engine's checked programs are their own program
    family: dedicated memo dict (test_serve pins _solve_cache's key
    set), bucket_ready sees their warmth, release_buckets retires
    them with the width bucket."""
    serve.clear_plans()
    rng = np.random.default_rng(41)
    plan = serve.FactorPlan.create((N, N), jnp.float32, v=V)
    s = plan.factor(jnp.asarray(_mk(rng)[0]))
    b = rng.standard_normal((N, 2)).astype(np.float32)
    x, verdict = s.solve_checked(jnp.asarray(b))
    v = np.asarray(verdict)
    assert v[0] == 1.0 and v[1] < 1e-4
    assert ("health", 2) in plan._trsm_cache
    assert ("health", 2) not in plan._solve_cache
    assert plan.bucket_ready(width=2, checked=True)
    # stacked checked program: same family, same dict
    F = stack_trees([s._factors, s._factors])
    wA = jnp.stack([s._probe_row(), s._probe_row()])
    buf = np.stack([b, b]).astype(np.float32)
    xs, vs = plan._stacked_solve_health_fn(2, 2)(F, None, wA,
                                                 jnp.asarray(buf))
    vs = np.asarray(vs)
    assert vs.shape == (2, 2)
    assert vs[0].all() and (vs[1] < 1e-4).all()
    assert ("gstack_health", 2, 2) in plan._trsm_cache
    assert plan.bucket_ready(stack=(2, 2), checked=True)
    # the checked answer equals the plain blocked solve's columns
    np.testing.assert_allclose(np.asarray(x),
                               np.asarray(s.solve(jnp.asarray(b))),
                               rtol=2e-5, atol=1e-6)
    # retirement drops the family with the width bucket
    dropped = plan.release_buckets(widths=(2,))
    assert ("health", 2) not in plan._trsm_cache
    assert ("gstack_health", 2, 2) not in plan._trsm_cache
    assert dropped >= 2
    assert not plan.bucket_ready(width=2, checked=True)
    # a re-touch re-traces and answers (released, not forbidden)
    x2, v2 = s.solve_checked(jnp.asarray(b))
    assert np.asarray(v2)[0] == 1.0


def test_fused_verdict_trips_on_poison():
    """A non-finite RHS trips the fused finite accumulator — the
    epilogue is a real verdict, not a vestige."""
    serve.clear_plans()
    rng = np.random.default_rng(43)
    plan = serve.FactorPlan.create((N, N), jnp.float32, v=V)
    s = plan.factor(jnp.asarray(_mk(rng)[0]))
    b = np.ones((N, 1), np.float32)
    b[3] = np.nan
    _x, verdict = s.solve_checked(jnp.asarray(b))
    assert np.asarray(verdict)[0] == 0.0


def test_stacked_blocked_bucket_pad_invariance():
    """The vmapped blocked programs keep the §21/§26 contract: slot i
    is BITWISE invariant to the stack bucket size and pad contents."""
    serve.clear_plans()
    rng = np.random.default_rng(47)
    A = _mk(rng, 2)
    plan = serve.FactorPlan.create((N, N), jnp.float32, v=V)
    s0, s1 = plan.factor(jnp.asarray(A[0])), plan.factor(jnp.asarray(A[1]))
    b = rng.standard_normal((N, 1)).astype(np.float32)
    F2 = stack_trees([s0._factors, s1._factors])
    F4 = stack_trees([s0._factors, s1._factors,
                      s0._factors, s0._factors])
    buf2 = np.zeros((2, N, 1), np.float32)
    buf2[0] = b
    buf4 = rng.standard_normal((4, N, 1)).astype(np.float32)
    buf4[0] = b
    x2 = np.asarray(plan._stacked_solve_fn(2, 1)(F2, None, buf2))[0]
    x4 = np.asarray(plan._stacked_solve_fn(4, 1)(F4, None, buf4))[0]
    np.testing.assert_array_equal(x2, x4)
    # the checked (fused-probe) stacked program holds it too
    wA2 = jnp.stack([s0._probe_row(), s1._probe_row()])
    wA4 = jnp.stack([s0._probe_row(), s1._probe_row(),
                     s0._probe_row(), s0._probe_row()])
    h2 = np.asarray(plan._stacked_solve_health_fn(2, 1)(
        F2, None, wA2, jnp.asarray(buf2))[0])[0]
    h4 = np.asarray(plan._stacked_solve_health_fn(4, 1)(
        F4, None, wA4, jnp.asarray(buf4))[0])[0]
    np.testing.assert_array_equal(h2, h4)


# --------------------------------------------------------------------- #
# gang end-to-end: the retired inv rule
# --------------------------------------------------------------------- #


def test_gang_serves_blocked_plan_clean_drifted_checked():
    """A substitution='auto' (blocked) plan gangs at full function:
    clean, drifted (stacked Woodbury) and checked (fused per-slot
    verdict) windows all ride the stacked path with exclusion counters
    at zero — no inv opt-in anywhere."""
    serve.clear_plans()
    rng = np.random.default_rng(53)
    A = _mk(rng, 4)
    plan = serve.FactorPlan.create((N, N), jnp.float32, v=V)
    assert plan.key.substitution == "blocked"
    fleet = [plan.factor(jnp.asarray(A[i]), sid=f"u{i}")
             for i in range(4)]
    bs = [rng.standard_normal((N, 1)).astype(np.float32)
          for _ in range(4)]
    direct = [np.asarray(s.solve(b)) for s, b in zip(fleet, bs)]

    # clean window
    eng = ServeEngine(max_batch_delay=60.0, stack_sessions=True,
                      max_stack=8)
    futs = [eng.submit(s, b) for s, b in zip(fleet, bs)]
    eng.close(timeout=120)
    res = [np.asarray(f.result(60)) for f in futs]
    for r, d in zip(res, direct):
        np.testing.assert_allclose(r, d, rtol=2e-5, atol=1e-6)
    st = eng.stats()
    assert st["gang_batches"] == 1
    for reason in ("upd_pending", "checked", "mesh"):
        assert st["stack_exclusions"][reason] == 0

    # drifted + checked window
    from conflux_tpu.resilience import health_stats

    esc0 = health_stats().get("escalations", 0)
    U = (0.01 * rng.standard_normal((N, 2))).astype(np.float32)
    Vv = (0.01 * rng.standard_normal((N, 2))).astype(np.float32)
    fleet[0].update(U, Vv)
    fleet[2].update(U, Vv)
    drifted_direct = [np.asarray(s.solve(b))
                      for s, b in zip(fleet, bs)]
    engH = ServeEngine(max_batch_delay=60.0, stack_sessions=True,
                       max_stack=8, health=HealthPolicy())
    futs = [engH.submit(s, b) for s, b in zip(fleet, bs)]
    engH.close(timeout=120)
    res = [np.asarray(f.result(60)) for f in futs]
    for r, d in zip(res, drifted_direct):
        np.testing.assert_allclose(r, d, rtol=2e-5, atol=1e-6)
    stH = engH.stats()
    assert stH["gang_batches"] >= 1
    for reason in ("upd_pending", "checked", "mesh"):
        assert stH["stack_exclusions"][reason] == 0
    # the fused verdicts passed clean: no escalation ladder ran
    assert health_stats().get("escalations", 0) == esc0


def test_gang_blocked_zero_compiles_after_prewarm():
    """Steady-state stacked windows on a blocked plan trace nothing
    after prewarm — the §26 zero-compile contract carries over."""
    serve.clear_plans()
    rng = np.random.default_rng(59)
    A = _mk(rng, 4)
    plan = serve.FactorPlan.create((N, N), jnp.float32, v=V)
    fleet = [plan.factor(jnp.asarray(A[i])) for i in range(4)]
    eng = ServeEngine(max_batch_delay=0.05, stack_sessions=True,
                      max_stack=4)
    eng.prewarm(fleet[0], widths=(1,), stacks=(4,))
    bs = [rng.standard_normal((N, 1)).astype(np.float32)
          for _ in range(4)]
    futs = [eng.submit(s, b) for s, b in zip(fleet, bs)]
    for f in futs:
        f.result(60)
    snapshot = dict(plan.trace_counts)
    for _ in range(3):
        futs = [eng.submit(s, b) for s, b in zip(fleet, bs)]
        for f in futs:
            f.result(60)
    assert plan.trace_counts == snapshot, \
        "steady-state blocked gang windows traced a program"
    eng.close(timeout=120)


# --------------------------------------------------------------------- #
# checkpoint codec round-trip
# --------------------------------------------------------------------- #


def test_plankey_substitution_roundtrips_fleet_codec():
    """tier.py's fleet.json plan codec reconstructs the EXACT PlanKey
    — substitution='blocked' included — and lands on the same cached
    plan object (`FactorPlan.from_key`)."""
    from conflux_tpu.tier import _plan_fields, _plan_from_fields

    serve.clear_plans()
    for sub in ("blocked", "inv", "trsm"):
        plan = serve.FactorPlan.create((N, N), jnp.float32, v=V,
                                       substitution=sub)
        d = _plan_fields(plan)
        assert d["substitution"] == sub
        import json

        restored = _plan_from_fields(json.loads(json.dumps(d)))
        assert restored is plan
        assert restored.key.substitution == sub
