"""Zero-copy fabric wire (ISSUE 16 / DESIGN §31): shared-memory
payload rings + batched control plane.

- A :class:`~conflux_tpu.wire.Ring` record round-trips BITWISE through
  the shared segment, wraps cleanly past the capacity, and reclaims
  out-of-order frees via the contiguous-prefix floor.
- Integrity is structural: a stale descriptor (recycled slot), a torn
  footer (writer SIGKILLed mid-copy) and an out-of-bounds descriptor
  each raise a typed :class:`~conflux_tpu.resilience.WireCorrupt`
  (kind-tagged) — never a silent wrong answer, never a hang.
- Backpressure is a structured refusal: a full ring raises
  :class:`~conflux_tpu.wire.RingFull` with a measured-drain
  retry_after; the worker's reply side falls back to an inline value
  (never blocks) when the reply ring stays full.
- The in-process loopback (:class:`~conflux_tpu.wire.InProcWire`)
  drives the REAL client/server endpoints over real segments: echo
  parity, engine parity (bitwise vs direct submit), fault-site
  injection (ring_full / torn_segment / stale_generation), and
  instant-structural-death of every pending future on corruption.
- Segments never leak: close() unlinks, and names are audited under
  /dev/shm.
- The batched control plane holds its contracts: `submit_many` stages
  a burst under one lock (short count on a mid-burst RingFull, raise
  only when NOTHING fit), frames never exceed `max_frame_items` (the
  anti-lockstep slicing), and `ProcessHost.echo_many` preserves order
  with zero pending-entry leaks.

The cross-process path (ProcessHost + worker) is exercised by
scripts/fabric_drill.py and ``bench_engine.py --wire`` (CI jobs);
the ProcessHost timeout-composition regression lives in
tests/test_fabric.py.
"""

import os
import time
from concurrent.futures import Future

import numpy as np
import pytest

from conflux_tpu import wire as wire_mod
from conflux_tpu.resilience import FaultPlan, FaultSpec, WireCorrupt
from conflux_tpu.wire import InProcWire, Ring, RingFull, WireConfig


def _ring(capacity=1 << 16, reclaim="local"):
    name, _ = wire_mod.segment_names("t")
    return Ring.create(name, capacity, reclaim=reclaim)


def _shm_names():
    try:
        return {f for f in os.listdir("/dev/shm")
                if f.startswith("cfxw-")}
    except FileNotFoundError:  # non-Linux: rely on close() not raising
        return set()


def _echo_submit_many(batch):
    futs = []
    for _sid, b, _q in batch:
        f = Future()
        f.set_result(np.asarray(b).copy())
        futs.append(f)
    return futs


# --------------------------------------------------------------------------- #
# ring protocol
# --------------------------------------------------------------------------- #


def test_segment_names_fit_posix_name_limit():
    """macOS caps POSIX shm names at 31 bytes (PSHMNAMLEN) INCLUDING
    the leading '/' the stdlib prepends — a long host id must trim,
    not make Ring.create fail, and the random token keeps two starts
    of the same host distinct."""
    rq, rp = wire_mod.segment_names("host-" + "x" * 60)
    assert max(len(rq), len(rp)) <= 30
    assert rq != rp
    assert wire_mod.segment_names("h")[0] != \
        wire_mod.segment_names("h")[0]


def test_ring_roundtrip_bitwise():
    """stage -> read is bitwise for every dtype/shape the fabric
    ships, both as a copy and as a zero-copy view."""
    r = _ring()
    try:
        for arr in [np.arange(24, dtype=np.float32),
                    np.random.default_rng(0).standard_normal(
                        (32, 256, 1)).astype(np.float32),
                    np.arange(6, dtype=np.float64).reshape(2, 3),
                    np.array([], dtype=np.float32),
                    np.arange(7, dtype=np.int32)]:
            d = r.stage(arr)
            got = r.read(d, copy=True)
            assert got.dtype == arr.dtype and got.shape == arr.shape
            assert np.array_equal(got, arr)
            view = r.read(d, copy=False)
            assert np.array_equal(view, arr)
            del view
            r.free(d)
    finally:
        r.close()


def test_ring_wrap_and_reclaim():
    """Thousands of stage/free cycles through a small ring: the
    monotonic cursors wrap past capacity many times and every read
    stays bitwise — the skip-tail wrap never aliases a live record."""
    r = _ring(capacity=4096)
    try:
        live = []
        rng = np.random.default_rng(1)
        for i in range(2000):
            arr = rng.standard_normal(
                rng.integers(1, 80)).astype(np.float32)
            d = r.stage(arr)
            live.append((d, arr))
            if len(live) > 3:
                d0, a0 = live.pop(0)
                assert np.array_equal(r.read(d0, copy=True), a0)
                r.free(d0)
        assert r._w > 10 * r.capacity  # really wrapped
    finally:
        r.close()


def test_ring_out_of_order_free():
    """The floor only advances over the contiguous freed prefix, so
    freeing out of order never reclaims bytes a live record holds."""
    r = _ring(capacity=4096)
    try:
        a = [np.full(64, i, np.float32) for i in range(3)]
        d = [r.stage(x) for x in a]
        r.free(d[1])             # hole: floor must NOT move
        assert r.used_bytes() == r._w
        r.free(d[0])             # prefix closes: floor jumps over both
        assert r.used_bytes() == r._w - d[2]["c"]
        assert np.array_equal(r.read(d[2], copy=True), a[2])
        r.free(d[2])
        assert r.used_bytes() == 0
    finally:
        r.close()


def test_ring_full_is_structured():
    """An allocation past capacity raises RingFull (needed/capacity
    attached) and the ring stays usable after frees."""
    r = _ring(capacity=4096)
    try:
        big = np.zeros(700, np.float32)  # ~2.8KB + overhead
        d0 = r.stage(big)
        with pytest.raises(RingFull) as ei:
            r.stage(big)
        assert ei.value.needed > 0 and ei.value.capacity == 4096
        r.free(d0)
        r.free(r.stage(big))     # reclaimed space admits again
    finally:
        r.close()


def test_ring_stale_generation_detected():
    """A recycled slot under a live descriptor (the post-SIGKILL /
    wrapped-writer hazard) fails the header generation check."""
    r = _ring()
    try:
        d = r.stage(np.arange(8, dtype=np.float32))
        stale = dict(d, g=d["g"] + 7)
        with pytest.raises(WireCorrupt) as ei:
            r.read(stale, copy=True)
        assert ei.value.kind == "stale_generation"
    finally:
        r.close()


def test_ring_torn_footer_detected():
    """A record whose footer never landed (writer died mid-copy) is a
    torn segment — typed, instant, never a garbage payload."""
    r = _ring()
    try:
        d = r.stage(np.arange(8, dtype=np.float32))
        # scribble over the footer exactly as an unfinished write would
        import struct
        struct.pack_into("<II", r._shm.buf,
                         64 + d["o"] + 24 + d["n"], 0, 0)
        with pytest.raises(WireCorrupt) as ei:
            r.read(d, copy=True)
        assert ei.value.kind == "torn_segment"
    finally:
        r.close()


def test_ring_overrun_descriptor_detected():
    """A descriptor naming bytes outside the segment is refused
    before any memory is touched."""
    r = _ring(capacity=4096)
    try:
        d = r.stage(np.arange(8, dtype=np.float32))
        with pytest.raises(WireCorrupt) as ei:
            r.read(dict(d, o=4096 - 8), copy=True)
        assert ei.value.kind == "overrun"
        with pytest.raises(WireCorrupt):
            r.read(dict(d, n=1 << 30), copy=True)
    finally:
        r.close()


def test_ring_close_unlinks_segment():
    """close() removes the /dev/shm name (leak audit), and a creator
    close beats any number of attacher closes."""
    before = _shm_names()
    r = _ring()
    made = _shm_names() - before
    att = Ring.attach(r.name) if made else None
    if att is not None:
        att.close()          # attacher: detach only, name survives
        assert made <= _shm_names()
    r.close()
    assert not (_shm_names() & made)
    r.close()                # idempotent


def test_wire_config_validates():
    with pytest.raises(ValueError):
        WireConfig(ring_bytes=16)
    with pytest.raises(ValueError):
        WireConfig(max_payload_frac=0.0)
    cfg = WireConfig(ring_bytes=1 << 20, batch_window_s=0.002)
    assert WireConfig.from_json(cfg.to_json()) == cfg


# --------------------------------------------------------------------------- #
# loopback endpoints (real segments, in-process control plane)
# --------------------------------------------------------------------------- #


def test_loopback_echo_parity_and_batching():
    """A burst of echoes round-trips bitwise through the rings, and
    the opportunistic pump coalesces them into fewer control frames
    than requests."""
    w = InProcWire(_echo_submit_many)
    try:
        rng = np.random.default_rng(2)
        payloads = [rng.standard_normal((32, 256, 1)).astype(np.float32)
                    for _ in range(40)]
        futs = [w.solve(None, p, op="echo") for p in payloads]
        for f, p in zip(futs, payloads):
            assert np.array_equal(f.result(timeout=30), p)
        st = w.stats()
        assert st["staged"] == 40 and st["replies"] == 40
        assert st["frames"] <= 40  # batching never inflates the frame count
        assert st["req_used"] == 0 and st["rep_used"] == 0  # all reclaimed
    finally:
        w.close()


def test_loopback_engine_parity_bitwise():
    """Solves routed through the shm wire into a REAL ServeEngine are
    BITWISE identical to direct submits — zero-copy staging does not
    perturb a single bit."""
    import jax.numpy as jnp

    from conflux_tpu import serve
    from conflux_tpu.engine import ServeEngine

    serve.clear_plans()
    n, v = 24, 8
    rng = np.random.default_rng(3)
    A = (rng.standard_normal((n, n)) / np.sqrt(n)
         + 2.0 * np.eye(n)).astype(np.float32)
    plan = serve.FactorPlan.create((n, n), jnp.float32, v=v)
    s = plan.factor(jnp.asarray(A))
    with ServeEngine(max_batch_delay=0.0) as eng:
        w = InProcWire(lambda batch: eng.submit_many(
            [(s, b, q) for _sid, b, q in batch]))
        try:
            for width in (1, 3, 1):
                b = rng.standard_normal((n, width)).astype(np.float32)
                ref = np.asarray(eng.submit(s, b).result(timeout=30))
                got = w.solve("sid", b).result(timeout=30)
                assert np.array_equal(got, ref)
        finally:
            w.close()


def test_loopback_large_payload_inline_fallback():
    """A reply too large for its configured ring share rides the
    control frame inline (pickle fallback) — still bitwise, counted."""
    cfg = WireConfig(ring_bytes=1 << 20, max_payload_frac=0.01)
    w = InProcWire(_echo_submit_many, config=cfg)
    try:
        big = np.random.default_rng(4).standard_normal(
            (64, 256)).astype(np.float32)  # 64KB > 1% of 1MB
        assert np.array_equal(w.solve(None, big, op="echo")
                              .result(timeout=30), big)
    finally:
        w.close()


def test_reply_ring_full_falls_back_inline():
    """The worker's reply pump never blocks on ring space: past the
    bounded wait it ships the value inline and counts the fallback."""
    cfg = WireConfig(ring_bytes=4096, reply_wait_s=0.02)
    frames = []
    rq, rp = _ring(capacity=4096), _ring(capacity=4096,
                                         reclaim="shared")
    srv = wire_mod.WireServer(rq, rp, frames.append, config=cfg)
    try:
        # stuff the reply ring with minimum-size records until even
        # the smallest allocation refuses, with no reader draining
        while True:
            try:
                rp.stage(np.zeros(1, np.float32))
            except RingFull:
                break
        srv.reply(7, value=np.arange(16, dtype=np.float32))
        t0 = time.perf_counter()
        while not frames and time.perf_counter() - t0 < 10:
            time.sleep(0.005)
        (item,) = frames[0]["items"]
        assert item["id"] == 7 and "d" not in item
        assert np.array_equal(item["v"],
                              np.arange(16, dtype=np.float32))
        assert srv.stats()["fallbacks"] == 1
    finally:
        srv.close()
        rq.close()
        rp.close()


# --------------------------------------------------------------------------- #
# fault sites + structural death
# --------------------------------------------------------------------------- #


def test_fault_site_ring_full_backpressure():
    """The ring_full fault site forces the structured refusal path:
    submit raises RingFull with a retry hint; traffic then resumes."""
    plan = FaultPlan([FaultSpec(site="ring_full", kind="crash",
                                count=1)])
    w = InProcWire(_echo_submit_many, fault_plan=plan)
    try:
        b = np.arange(8, dtype=np.float32)
        with pytest.raises(RingFull) as ei:
            w.solve(None, b, op="echo")
        assert ei.value.retry_after > 0.0
        assert np.array_equal(w.solve(None, b, op="echo")
                              .result(timeout=30), b)
        assert plan.injected.get(("ring_full", "crash")) == 1
    finally:
        w.close()


@pytest.mark.parametrize("site,kind", [
    ("torn_segment", "torn_segment"),
    ("stale_generation", "stale_generation"),
])
def test_fault_site_corruption_is_instant_structural_death(site, kind):
    """torn_segment / stale_generation fire on the CLIENT's decode of
    a reply record: every pending future fails with WireCorrupt NOW
    (kind-tagged), the wire refuses new traffic — never a hang, never
    a wrong answer."""
    plan = FaultPlan([FaultSpec(site=site, kind="crash", count=1)])
    w = InProcWire(_echo_submit_many, fault_plan=plan)
    try:
        fut = w.solve(None, np.arange(8, dtype=np.float32), op="echo")
        with pytest.raises(WireCorrupt) as ei:
            fut.result(timeout=30)
        assert ei.value.kind == kind
        with pytest.raises(ConnectionError):
            w.solve(None, np.arange(8, dtype=np.float32), op="echo")
    finally:
        w.close()


def test_server_side_corrupt_request_fails_per_item():
    """A corrupt REQUEST record fails its own item with a structured
    error reply; frame-mates still answer bitwise."""
    # server reads requests with the installed plan absent; inject by
    # corrupting the staged record directly instead
    w = InProcWire(_echo_submit_many)
    try:
        good = np.arange(8, dtype=np.float32)
        # craft a frame by hand: one good item, one stale descriptor
        d_ok = w.client._req.stage(good)
        d_bad = dict(w.client._req.stage(good), g=999999)
        fut_ok: Future = Future()
        fut_bad: Future = Future()
        with w._lock:
            w._pending[101] = fut_ok
            w._pending[102] = fut_bad
            w.client._by_mid[101] = d_ok
            w.client._by_mid[102] = d_bad
        w.server.handle(
            {"op": "solve_many",
             "items": [{"id": 101, "sid": None, "d": d_ok,
                        "op": "echo"},
                       {"id": 102, "sid": None, "d": d_bad,
                        "op": "echo"}]},
            _echo_submit_many)
        assert np.array_equal(fut_ok.result(timeout=30), good)
        with pytest.raises(RuntimeError, match="WireCorrupt"):
            fut_bad.result(timeout=30)
    finally:
        w.close()


def test_loopback_no_shm_leaks_after_close():
    before = _shm_names()
    w = InProcWire(_echo_submit_many)
    w.solve(None, np.arange(4, dtype=np.float32),
            op="echo").result(timeout=30)
    assert len(_shm_names() - before) == 2
    w.close()
    assert not (_shm_names() - before)

# --------------------------------------------------------------------------- #
# batched submission (submit_many / max_frame_items — ISSUE 16 satellites)
# --------------------------------------------------------------------------- #


def test_submit_many_one_lock_burst_bitwise():
    """A whole burst staged through `submit_many` round-trips bitwise
    and counts as staged; the control plane needs far fewer frames
    than requests."""
    w = InProcWire(_echo_submit_many)
    try:
        rng = np.random.default_rng(11)
        payloads = [rng.standard_normal((8, 32)).astype(np.float32)
                    for _ in range(24)]
        futs, entries = [], []
        with w._lock:
            for p in payloads:
                mid = w._next
                w._next += 1
                f: Future = Future()
                w._pending[mid] = f
                futs.append(f)
                entries.append((mid, None, p, None, "echo"))
        assert w.client.submit_many(entries) == len(entries)
        for f, p in zip(futs, payloads):
            assert np.array_equal(f.result(timeout=30), p)
        st = w.stats()
        assert st["staged"] == 24 and st["replies"] == 24
        assert st["frames"] < 24
    finally:
        w.close()


def test_submit_many_short_count_on_ring_full():
    """A burst bigger than the ring stages a PREFIX and returns the
    short count — RingFull raises only when nothing fit, with the
    measured-drain retry hint attached."""
    frames: list = []
    rq = _ring(capacity=4096)
    rp = _ring(capacity=4096, reclaim="shared")
    c = wire_mod.WireClient(rq, rp, frames.append, host_id="t")
    try:
        arr = np.zeros(256, np.float32)  # 1112B record span
        entries = [(i, None, arr, None, "solve") for i in range(6)]
        n = c.submit_many(entries)
        assert 0 < n < 6               # the ring filled mid-burst
        with pytest.raises(RingFull) as ei:
            c.submit_many(entries[n:])  # nothing can fit now
        assert ei.value.retry_after > 0.0
        assert c.stats()["staged"] == n
    finally:
        c.close()
        rq.close()
        rp.close()


def test_max_frame_items_slices_bursts():
    """Frames never exceed `max_frame_items`: a one-lock burst is
    sliced into consecutive frames so the peer starts draining the
    first slice while the rest is still queued (the anti-lockstep
    contract)."""
    frames: list = []
    rq = _ring(capacity=1 << 20)
    rp = _ring(capacity=1 << 20, reclaim="shared")
    c = wire_mod.WireClient(rq, rp, frames.append, host_id="t",
                            config=WireConfig(max_frame_items=4))
    try:
        arr = np.zeros(16, np.float32)
        assert c.submit_many(
            [(i, None, arr, None, "solve") for i in range(18)]) == 18
        deadline = time.time() + 10.0
        while (sum(len(f["items"]) for f in frames) < 18
               and time.time() < deadline):
            time.sleep(0.005)
        sizes = [len(f["items"]) for f in frames]
        assert sum(sizes) == 18
        assert max(sizes) <= 4         # the cap held on every frame
        assert len(frames) >= 5        # 18 items can't fit in 4 frames
    finally:
        c.close()
        rq.close()
        rp.close()


def test_processhost_echo_many_pickle_path_order_and_cleanup():
    """`echo_many` on the pickle wire sends the whole burst under one
    lock, preserves order, and leaves no pending entries behind."""
    from conflux_tpu import fabric

    class _EchoNow:
        """A Connection stand-in that answers echoes synchronously
        (send is called under _send_lock, so resolve inline)."""

        def __init__(self, host):
            self.host = host

        def send(self, msg):
            fut = self.host._pending.pop(msg["id"])
            fut.set_result({"id": msg["id"], "ok": True,
                            "value": msg["b"] * 2.0})

        def close(self):
            pass

    h = fabric.ProcessHost("he", "/tmp/unused-he", wire="pickle")
    h._conn = _EchoNow(h)
    payloads = [np.full((4,), float(i), np.float32) for i in range(7)]
    out = h.echo_many(payloads, timeout=5.0)
    assert len(out) == 7
    for i, x in enumerate(out):
        assert np.array_equal(x, payloads[i] * 2.0)
    assert h._pending == {}
